"""Native chunked block store (mdanalysis_mpi_tpu/io/store — docs/STORE.md).

Round-trip parity (ingest → read vs the source reader, every
quantization tier), the raw-slice staging fast path, read-time
fingerprint verification (corrupt chunks, swapped chunks, corrupt
manifests all rejected TYPED and counted), exact-slice chunk fetch
accounting, chunk-aligned shard routing, the executor boundary
(jax backend + DeviceBlockCache off a store), and the CLI surfaces.
"""

import json
import os

import numpy as np
import pytest

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.io.store import (
    LocalDirBackend, StoreReader, ingest, is_store, store_meta,
)
from mdanalysis_mpi_tpu.obs import METRICS
from mdanalysis_mpi_tpu.utils.integrity import (
    IntegrityError, StoreCorruptError, StoreUnavailableError,
)

pytestmark = pytest.mark.store


def _rejects() -> int:
    # reason-labeled (corrupt|unavailable): sum across every reason
    return sum(METRICS.snapshot().get(
        "mdtpu_store_chunk_crc_rejects_total",
        {"values": {}})["values"].values())


def _topology(n_atoms: int) -> Topology:
    names = np.tile(np.array(["CA", "HA"]), n_atoms // 2 + 1)[:n_atoms]
    return Topology(names=names, resnames=np.full(n_atoms, "ALA"),
                    resids=np.arange(n_atoms) // 2 + 1)


def _source(n_frames=40, n_atoms=60, seed=0, scale=12.0):
    rng = np.random.default_rng(seed)
    base = rng.normal(scale=scale, size=(n_atoms, 3)).astype(np.float32)
    frames = base[None] + rng.normal(
        scale=0.4, size=(n_frames, n_atoms, 3)).astype(np.float32)
    dims = np.tile(np.array([40.0, 40, 40, 90, 90, 90],
                            dtype=np.float32), (n_frames, 1))
    times = np.arange(n_frames, dtype=np.float64) * 2.0
    return MemoryReader(frames, dimensions=dims, times=times), frames


class TestRoundTrip:
    def test_int16_parity_and_metadata(self, tmp_path):
        src, frames = _source()
        out = str(tmp_path / "s16")
        summary = ingest(src, out, chunk_frames=16, quant="int16")
        assert summary["n_chunks"] == 3          # 16+16+8
        assert summary["n_frames"] == 40
        sr = StoreReader(out)
        assert sr.n_frames == 40 and sr.n_atoms == 60
        assert sr.quant == "int16" and sr.chunk_frames == 16
        got, boxes = sr.read_block(0, 40)
        # one int16 round trip: resolution = max|x| * margin / 32000
        tol = float(np.abs(frames).max()) * 1.05 / 32000.0
        assert float(np.abs(got - frames).max()) <= tol + 1e-6
        assert boxes.shape == (40, 6)
        np.testing.assert_allclose(boxes[0], [40, 40, 40, 90, 90, 90])
        # per-frame cursor reads carry time + dims
        ts = sr[17]
        assert ts.frame == 17 and ts.time == pytest.approx(34.0)
        assert ts.dimensions is not None
        # frame_times serves without decoding coordinates
        np.testing.assert_allclose(
            sr.frame_times(range(5, 9)), [10.0, 12.0, 14.0, 16.0])
        # strided + selected block reads
        sel = np.arange(0, 60, 3)
        got_s, _ = sr.read_block(4, 36, sel=sel, step=4)
        assert got_s.shape == (8, 20, 3)
        assert float(np.abs(got_s - frames[4:36:4][:, sel]).max()) \
            <= tol + 1e-6

    def test_f32_tier_is_bit_exact(self, tmp_path):
        src, frames = _source(n_frames=10)
        out = str(tmp_path / "sf32")
        ingest(src, out, chunk_frames=4, quant="f32")
        sr = StoreReader(out)
        got, _ = sr.read_block(0, 10)
        np.testing.assert_array_equal(got, frames)
        # f32 staging requests pass straight through
        block, _boxes, inv = sr.stage_block(0, 8)
        assert inv is None and block.dtype == np.float32
        np.testing.assert_array_equal(block, frames[:8])

    def test_int8_tier_coarse_round_trip(self, tmp_path):
        src, frames = _source(n_frames=12)
        out = str(tmp_path / "s8")
        ingest(src, out, chunk_frames=6, quant="int8")
        got, _ = StoreReader(out).read_block(0, 12)
        tol = float(np.abs(frames).max()) * 1.05 / 120.0
        assert float(np.abs(got - frames).max()) <= tol + 1e-6


class TestStagingFastPath:
    def test_serves_raw_quantized_slices(self, tmp_path):
        src, frames = _source()
        out = str(tmp_path / "s")
        ingest(src, out, chunk_frames=16, quant="int16")
        sr = StoreReader(out)
        sel = np.arange(10, 50)
        q, boxes, inv = sr.stage_block(16, 32, sel=sel, quantize="int16")
        assert q.dtype == np.int16 and q.shape == (16, 40, 3)
        assert isinstance(inv, np.float32)
        assert boxes.shape == (16, 6)
        # dequantized staged bytes match the source inside one step
        deq = q.astype(np.float32) * inv
        assert float(np.abs(deq - frames[16:32][:, sel]).max()) \
            <= float(inv) / 2 + 1e-6
        # chunk-spanning request under the store-wide uniform scale
        q2, _b2, inv2 = sr.stage_block(8, 40, sel=None, quantize=True)
        assert q2.dtype == np.int16 and q2.shape == (32, 60, 3)
        assert float(inv2) == pytest.approx(float(inv))
        deq2 = q2.astype(np.float32) * inv2
        assert float(np.abs(deq2 - frames[8:40]).max()) \
            <= float(inv2) / 2 + 1e-6

    def test_mixed_scale_chunks_requantize_not_misdequantize(
            self, tmp_path):
        # chunk 1's range outgrows the store-wide margin -> it gets an
        # exact per-chunk scale, and a request spanning both chunks
        # must requantize through f32 rather than serve mixed-scale
        # bytes under one inv_scale
        frames = np.zeros((16, 20, 3), dtype=np.float32)
        rng = np.random.default_rng(1)
        frames[:8] = rng.normal(scale=5.0, size=(8, 20, 3))
        frames[8:] = rng.normal(scale=500.0, size=(8, 20, 3))
        out = str(tmp_path / "mixed")
        summary = ingest(MemoryReader(frames), out, chunk_frames=8,
                         quant="int16")
        # the degraded fast path is DISCLOSED, not silent
        assert summary["scale_overflow_chunks"] == 1
        man = store_meta(out)
        assert man["scale_overflow_chunks"] == 1
        scales = [c["inv_scale"] for c in man["chunks"]]
        assert scales[0] != scales[1]
        sr = StoreReader(out)
        q, _boxes, inv = sr.stage_block(0, 16, quantize="int16")
        assert q.dtype == np.int16
        deq = np.asarray(q, np.float32) * np.asarray(inv, np.float32)
        # exact-per-block requantize resolution (quantize_block policy)
        tol = float(np.abs(frames).max()) / 32000.0
        assert float(np.abs(deq - frames).max()) <= tol + 1e-6

    def test_exact_slice_fetches(self, tmp_path):
        # a shard child reading its window must fetch ONLY the chunks
        # covering it — the fleet's fetch-exactly-your-slice contract
        src, _frames = _source(n_frames=64)
        out = str(tmp_path / "slices")
        ingest(src, out, chunk_frames=8, quant="int16")

        class CountingBackend(LocalDirBackend):
            def __init__(self, root):
                super().__init__(root)
                self.fetched = []

            def get_bytes(self, name):
                self.fetched.append(name)
                return super().get_bytes(name)

        be = CountingBackend(out)
        sr = StoreReader(out, backend=be)
        be.fetched.clear()                       # drop the manifest get
        sr.stage_block(24, 40, quantize="int16")
        assert be.fetched == ["chunk-00000003.mdtc",
                              "chunk-00000004.mdtc"]


class TestVerifiedReads:
    def test_corrupt_payload_rejected_typed_and_counted(self, tmp_path):
        src, _ = _source()
        out = str(tmp_path / "c")
        ingest(src, out, chunk_frames=16, quant="int16")
        path = os.path.join(out, "chunk-00000001.mdtc")
        blob = bytearray(open(path, "rb").read())
        blob[-5] ^= 0x10
        with open(path, "wb") as f:
            f.write(bytes(blob))
        sr = StoreReader(out)
        before = _rejects()
        with pytest.raises(StoreCorruptError):
            sr.read_block(16, 32)
        assert _rejects() == before + 1
        # frames outside the corrupt chunk still serve
        assert sr.read_block(0, 16)[0].shape == (16, 60, 3)

    def test_corrupt_header_and_truncation_rejected(self, tmp_path):
        src, _ = _source(n_frames=8)
        out = str(tmp_path / "h")
        ingest(src, out, chunk_frames=8, quant="int16")
        path = os.path.join(out, "chunk-00000000.mdtc")
        orig = open(path, "rb").read()
        blob = bytearray(orig)
        blob[20] ^= 0x01                         # inside the header JSON
        with open(path, "wb") as f:
            f.write(bytes(blob))
        with pytest.raises(IntegrityError):
            StoreReader(out).read_block(0, 8)
        with open(path, "wb") as f:
            f.write(orig[:-100])                 # truncated payload
        with pytest.raises(StoreCorruptError):
            StoreReader(out).read_block(0, 8)

    def test_swapped_self_valid_chunks_rejected(self, tmp_path):
        # two chunks, each internally consistent, swapped on disk:
        # self-CRCs pass, the manifest fingerprint comparison must not
        src, _ = _source(n_frames=32)
        out = str(tmp_path / "swap")
        ingest(src, out, chunk_frames=16, quant="int16")
        a = os.path.join(out, "chunk-00000000.mdtc")
        b = os.path.join(out, "chunk-00000001.mdtc")
        da, db = open(a, "rb").read(), open(b, "rb").read()
        with open(a, "wb") as f:
            f.write(db)
        with open(b, "wb") as f:
            f.write(da)
        with pytest.raises(StoreCorruptError):
            StoreReader(out).read_block(0, 16)

    def test_missing_chunk_rejected_typed_and_counted(self, tmp_path):
        # a chunk the manifest promises but the backend cannot produce
        # is the RETRYABLE half of the taxonomy (docs/STORE.md): typed
        # StoreUnavailableError (the bytes were never seen, so nothing
        # is known corrupt), counted under reason="unavailable" —
        # never a raw FileNotFoundError
        src, _ = _source(n_frames=32)
        out = str(tmp_path / "gone")
        ingest(src, out, chunk_frames=16, quant="int16")
        os.remove(os.path.join(out, "chunk-00000001.mdtc"))
        before = _rejects()
        with pytest.raises(StoreUnavailableError):
            StoreReader(out).read_block(16, 32)
        assert _rejects() == before + 1
        snap = METRICS.snapshot()["mdtpu_store_chunk_crc_rejects_total"]
        assert any("unavailable" in k for k in snap["values"])

    def test_reingest_kills_manifest_first(self, tmp_path):
        # a crashed re-ingest must leave "not a store", never a valid
        # old manifest over half-replaced chunks: the manifest dies
        # before the first chunk write
        src, _ = _source(n_frames=16)
        out = str(tmp_path / "reingest")
        ingest(src, out, chunk_frames=8, quant="int16")
        assert is_store(out)

        class CrashingReader(MemoryReader):
            def read_block(self, start, stop, sel=None, step=1):
                if start > 0:
                    raise RuntimeError("simulated ingest crash")
                return MemoryReader.read_block(self, start, stop,
                                               sel=sel, step=step)

        src2, _ = _source(n_frames=16, seed=3)
        crasher = CrashingReader(src2._coords)
        with pytest.raises(RuntimeError):
            ingest(crasher, out, chunk_frames=8, quant="int16")
        assert not is_store(out)             # no manifest, no store
        # a fresh ingest over the wreckage recovers cleanly
        ingest(src, out, chunk_frames=8, quant="int16")
        assert StoreReader(out).read_block(0, 16)[0].shape == (16, 60, 3)

    def test_reingest_sweeps_orphan_chunks(self, tmp_path):
        # re-chunking to a coarser geometry must not strand the old
        # geometry's files as unreferenced disk
        src, _ = _source(n_frames=16)
        out = str(tmp_path / "rechunk")
        ingest(src, out, chunk_frames=4, quant="int16")    # 4 chunks
        ingest(src, out, chunk_frames=8, quant="int16")    # 2 chunks
        names = sorted(f for f in os.listdir(out)
                       if f.startswith("chunk-"))
        assert names == ["chunk-00000000.mdtc", "chunk-00000001.mdtc"]
        assert StoreReader(out).read_block(0, 16)[0].shape == (16, 60, 3)

    def test_corrupt_manifest_rejected(self, tmp_path):
        src, _ = _source(n_frames=8)
        out = str(tmp_path / "m")
        ingest(src, out, chunk_frames=8, quant="int16")
        mpath = os.path.join(out, "manifest.json")
        man = json.loads(open(mpath).read())
        man["n_frames"] = 9999                   # tampered, CRC stale
        with open(mpath, "w") as f:
            f.write(json.dumps(man))
        with pytest.raises(StoreCorruptError):
            StoreReader(out)
        # and is_store still sniffs it (format field intact) while
        # store_meta refuses it typed
        assert is_store(out)
        with pytest.raises(StoreCorruptError):
            store_meta(out)


class TestShardRouting:
    def test_shard_windows_chunk_aligned(self):
        from mdanalysis_mpi_tpu.parallel.partition import shard_windows

        wins = shard_windows(100, None, None, None, 3, chunk_frames=16)
        # boundaries land on chunk multiples; union covers [0, 100)
        # exactly, in order
        assert wins == [(0, 48, 1), (48, 80, 1), (80, 100, 1)]
        covered = []
        for s, e, st in wins:
            assert s % 16 == 0 or s == 0
            assert e % 16 == 0 or e == 100
            covered.extend(range(s, e, st))
        assert covered == list(range(100))
        # windows that start mid-chunk keep their exact bounds
        wins = shard_windows(100, 10, 90, 1, 2, chunk_frames=32)
        assert wins == [(10, 64, 1), (64, 90, 1)]
        assert [f for w in wins for f in range(*w)] == list(range(10, 90))
        # non-unit steps align to VISITED chunks (r17 regression:
        # this silently skipped alignment before): same exact-union
        # contract, and no chunk is fetched by two shards
        wins = shard_windows(100, 0, 100, 3, 2, chunk_frames=16)
        assert [f for w in wins for f in range(*w)] \
            == list(range(0, 100, 3))
        sets = [{f // 16 for f in range(*w)} for w in wins]
        assert sets[0].isdisjoint(sets[1])
        # unchanged default path
        assert shard_windows(10, None, None, None, 2) \
            == [(0, 5, 1), (5, 10, 1)]

    def test_fleet_store_meta_routes_chunk_geometry(self, tmp_path):
        from mdanalysis_mpi_tpu.service.fleet import _store_meta

        src, _ = _source(n_frames=48)
        out = str(tmp_path / "fleet_store")
        ingest(src, out, chunk_frames=12, quant="int16")
        meta = _store_meta({"trajectory": out, "topology": "x.gro"})
        assert meta["chunk_frames"] == 12 and meta["n_frames"] == 48
        assert _store_meta({"trajectory": str(tmp_path)}) is None
        assert _store_meta({"fixture": {"n_frames": 8}}) is None


class TestExecutorBoundary:
    def test_jax_int16_parity_and_device_cache(self, tmp_path):
        jax = pytest.importorskip("jax")  # noqa: F841
        from mdanalysis_mpi_tpu.analysis import AlignedRMSF
        from mdanalysis_mpi_tpu.parallel.executors import (
            DeviceBlockCache, reader_fingerprint,
        )

        src, frames = _source(n_frames=32, n_atoms=40)
        out = str(tmp_path / "exec")
        ingest(src, out, chunk_frames=8, quant="int16")
        topo = _topology(40)
        u_mem = Universe(topo, src)
        u_store = Universe(topo, StoreReader(out))
        # the store dir IS the reader's cache-key namespace
        assert reader_fingerprint(u_store.trajectory) == out
        s = AlignedRMSF(u_mem, select="heavy").run(backend="serial")
        cache = DeviceBlockCache()
        a1 = AlignedRMSF(u_store, select="heavy").run(
            backend="jax", batch_size=8, transfer_dtype="int16",
            block_cache=cache)
        err = float(np.abs(np.asarray(a1.results.rmsf)
                           - s.results.rmsf).max())
        assert err < 1e-3, err
        # pass 2 of a second run rides HBM-resident superblocks — the
        # stage_cached boundary is unchanged by construction
        m0 = cache.misses
        a2 = AlignedRMSF(u_store, select="heavy").run(
            backend="jax", batch_size=8, transfer_dtype="int16",
            block_cache=cache)
        assert cache.misses == m0 and cache.hits > 0
        err2 = float(np.abs(np.asarray(a2.results.rmsf)
                            - s.results.rmsf).max())
        assert err2 < 1e-3, err2


class TestCLI:
    def test_universe_opens_store_dir(self, tmp_path):
        src, _ = _source(n_frames=8, n_atoms=20)
        out = str(tmp_path / "u")
        ingest(src, out, chunk_frames=4, quant="int16")
        u = Universe(_topology(20), out)
        assert isinstance(u.trajectory, StoreReader)
        assert u.trajectory.n_frames == 8
        with pytest.raises(ValueError, match="not an ingested block"):
            Universe(_topology(20), str(tmp_path))

    def test_ingest_cli_roundtrip_and_idempotence(self, tmp_path,
                                                  capsys):
        from mdanalysis_mpi_tpu.io.store.cli import ingest_main
        from mdanalysis_mpi_tpu.io.xtc import write_xtc

        rng = np.random.default_rng(5)
        frames = rng.normal(scale=9.0, size=(12, 30, 3)).astype(
            np.float32)
        xtc = str(tmp_path / "t.xtc")
        write_xtc(xtc, frames)
        out = str(tmp_path / "t.store")
        assert ingest_main([xtc, "--out", out, "--chunk-frames", "4",
                            "--quant", "int16"]) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["n_chunks"] == 3 and rec["store_ingest_fps"] > 0
        # ingest-once: a second invocation is an answer, not a re-run
        assert ingest_main([xtc, "--out", out]) == 0
        rec2 = json.loads(capsys.readouterr().out.strip())
        assert rec2["already_ingested"] is True
        assert rec2["n_chunks"] == 3

    def test_ingest_smoke_gate(self, capsys):
        from mdanalysis_mpi_tpu.io.store.cli import ingest_main

        assert ingest_main(["--smoke"]) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["ok"] is True
        assert rec["corrupt_chunk_rejected"] == "StoreCorruptError"

    def test_batch_prefers_store(self, tmp_path, capsys):
        # the job file's trajectory path is DELETED after ingest, so
        # the batch run can only succeed by reading the store — the
        # strongest possible "--store was actually used" proof
        from mdanalysis_mpi_tpu.io.gro import write_gro
        from mdanalysis_mpi_tpu.io.xtc import write_xtc
        from mdanalysis_mpi_tpu.service.cli import batch_main

        topo = _topology(20)
        rng = np.random.default_rng(9)
        frames = rng.normal(scale=8.0, size=(12, 20, 3)).astype(
            np.float32)
        gro = str(tmp_path / "top.gro")
        write_gro(gro, topo, frames[0])
        xtc = str(tmp_path / "t.xtc")
        write_xtc(xtc, frames)
        store = str(tmp_path / "t.store")
        ingest(xtc, store, chunk_frames=4, quant="int16")
        os.remove(xtc)
        jobs = str(tmp_path / "jobs.json")
        with open(jobs, "w") as f:
            json.dump({"topology": gro, "trajectory": xtc,
                       "jobs": [{"analysis": "rmsf",
                                 "select": "heavy",
                                 "backend": "serial"}]}, f)
        assert batch_main([jobs, "--store", store]) == 0
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["jobs"][0]["state"] == "done"
