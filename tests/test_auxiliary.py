"""Auxiliary time-series subsystem (upstream ``MDAnalysis.auxiliary``):
XVG parsing, nearest-time alignment with cutoff, and the trajectory
``ts.aux`` surface."""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.auxiliary import ArrayAuxReader, XVGReader
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader

XVG = """\
# GROMACS pull force
@    title "Pull force"
@    xaxis  label "Time (ps)"
@ s0 legend "force"
0.0   1.5   10.0
0.5   2.5   20.0
1.0   3.5   30.0
2.0   4.5   40.0
"""


def _universe(times):
    n = len(times)
    pos = np.zeros((n, 2, 3), np.float32)
    top = Topology(names=np.array(["CA", "CB"]),
                   resnames=np.array(["ALA", "ALA"]),
                   resids=np.array([1, 1]))
    return Universe(top, MemoryReader(pos, times=np.asarray(times,
                                                            np.float32)))


def test_xvg_parsing(tmp_path):
    p = tmp_path / "force.xvg"
    p.write_text(XVG)
    aux = XVGReader(str(p))
    assert aux.n_steps == 4
    np.testing.assert_allclose(aux.times, [0.0, 0.5, 1.0, 2.0])
    np.testing.assert_allclose(aux.data[:, 1], [1.5, 2.5, 3.5, 4.5])
    # grace dataset separator ends the series
    p2 = tmp_path / "two.xvg"
    p2.write_text("0 1\n1 2\n&\n0 99\n")
    assert XVGReader(str(p2)).n_steps == 2
    with pytest.raises(ValueError, match="non-numeric"):
        bad = tmp_path / "bad.xvg"
        bad.write_text("0.0 not_a_number\n")
        XVGReader(str(bad))
    with pytest.raises(ValueError, match="no data"):
        empty = tmp_path / "empty.xvg"
        empty.write_text("# only comments\n")
        XVGReader(str(empty))
    with pytest.raises(ValueError, match="ragged"):
        ragged = tmp_path / "ragged.xvg"
        ragged.write_text("0 1 2\n1 2\n")
        XVGReader(str(ragged))


def test_closest_step_and_cutoff():
    aux = ArrayAuxReader([0.0, 1.0, 3.0], [[0.0, 10], [1.0, 20],
                                           [3.0, 30]])
    assert aux.closest_step(-5.0) == 0
    assert aux.closest_step(0.4) == 0
    assert aux.closest_step(0.6) == 1
    assert aux.closest_step(2.1) == 2
    assert aux.closest_step(99.0) == 2
    np.testing.assert_allclose(aux.value_at(0.9), [1.0, 20])
    # cutoff: a frame farther than cutoff from every step reads NaN
    v = aux.value_at(2.0, cutoff=0.5)
    assert np.isnan(v).all()
    np.testing.assert_allclose(aux.value_at(2.9, cutoff=0.5), [3.0, 30])
    with pytest.raises(ValueError, match="non-decreasing"):
        ArrayAuxReader([1.0, 0.0], [[1], [2]])
    with pytest.raises(ValueError, match="empty"):
        ArrayAuxReader([], np.empty((0, 1)))


def test_trajectory_aux_surface(tmp_path):
    p = tmp_path / "force.xvg"
    p.write_text(XVG)
    u = _universe(times=[0.0, 1.0, 2.0])
    u.trajectory.add_auxiliary("force", XVGReader(str(p)))
    seen = [float(ts.aux.force[1]) for ts in u.trajectory]
    assert seen == [1.5, 3.5, 4.5]
    # two auxiliaries coexist; frames carry both
    u.trajectory.add_auxiliary(
        "cv", ArrayAuxReader([0.0, 2.0], [[0.0, 7], [2.0, 9]]))
    ts = u.trajectory[2]
    assert float(ts.aux.force[1]) == 4.5
    assert float(ts.aux.cv[1]) == 9.0
    with pytest.raises(AttributeError, match="attached"):
        ts.aux.nope
    with pytest.raises(ValueError, match="already attached"):
        u.trajectory.add_auxiliary("force", XVGReader(str(p)))
    u.trajectory.remove_auxiliary("cv")
    with pytest.raises(ValueError, match="no auxiliary"):
        u.trajectory.remove_auxiliary("cv")
    with pytest.raises(TypeError, match="value_at"):
        u.trajectory.add_auxiliary("bad", object())


def test_aux_cutoff_on_trajectory():
    u = _universe(times=[0.0, 5.0])
    u.trajectory.add_auxiliary(
        "e", ArrayAuxReader([0.0], [[0.0, 42.0]]), cutoff=1.0)
    assert float(u.trajectory[0].aux.e[1]) == 42.0
    assert np.isnan(u.trajectory[1].aux.e).all()


def test_plain_frames_have_no_aux():
    u = _universe(times=[0.0, 1.0])
    assert u.trajectory[0].aux is None


def test_scalar_series_and_bad_shapes():
    aux = ArrayAuxReader([0.0, 1.0, 2.0], [5.0, 6.0, 7.0])   # 1-D data
    assert aux.n_steps == 3
    np.testing.assert_allclose(aux.value_at(1.2), [6.0])
    with pytest.raises(ValueError, match="data"):
        ArrayAuxReader([0.0], np.zeros((1, 2, 2)))


def test_dict_method_names_rejected():
    u = _universe(times=[0.0])
    aux = ArrayAuxReader([0.0], [1.0])
    for bad in ("values", "items", "copy", "not an identifier"):
        with pytest.raises(ValueError, match="identifier"):
            u.trajectory.add_auxiliary(bad, aux)


def test_remove_auxiliary_refreshes_cursor():
    u = _universe(times=[0.0])
    u.trajectory.add_auxiliary("e", ArrayAuxReader([0.0], [42.0]))
    assert float(u.trajectory.ts.aux.e[0]) == 42.0
    u.trajectory.remove_auxiliary("e")
    assert u.trajectory.ts.aux is None          # no stale aux view


def test_timestep_copy_keeps_aux():
    u = _universe(times=[0.0])
    u.trajectory.add_auxiliary("e", ArrayAuxReader([0.0], [42.0]))
    snap = u.trajectory.ts.copy()
    assert float(snap.aux.e[0]) == 42.0


def test_chain_refuses_child_auxiliaries(tmp_path):
    from mdanalysis_mpi_tpu.io.chain import ChainReader
    from mdanalysis_mpi_tpu.io.xtc import write_xtc, XTCReader

    rng = np.random.default_rng(3)
    frames = rng.normal(size=(3, 5, 3)).astype(np.float32)
    p1, p2 = str(tmp_path / "a.xtc"), str(tmp_path / "b.xtc")
    write_xtc(p1, frames, times=np.array([0.0, 1.0, 2.0], np.float32))
    write_xtc(p2, frames, times=np.array([3.0, 4.0, 5.0], np.float32))
    child = XTCReader(p1)
    child.add_auxiliary("e", ArrayAuxReader([0.0], [1.0]))
    chain = ChainReader([child, XTCReader(p2)])
    with pytest.raises(ValueError, match="auxiliaries"):
        chain[0]
    # attached to the CHAIN itself (continuous times) it aligns
    # GLOBALLY: frame 4's time is 4.0, in the second segment
    chain2 = ChainReader([XTCReader(p1), XTCReader(p2)])
    chain2.add_auxiliary(
        "e", ArrayAuxReader(np.arange(6.0), np.arange(6.0) * 10))
    assert float(chain2[4].aux.e[0]) == 40.0
    # segment clocks that RESTART are refused: alignment by time would
    # silently hand segment-2 frames the aux records of segment 1
    p3 = str(tmp_path / "c.xtc")
    write_xtc(p3, frames, times=np.array([0.0, 1.0, 2.0], np.float32))
    chain3 = ChainReader([XTCReader(p1), XTCReader(p3)])
    with pytest.raises(ValueError, match="non-decreasing"):
        chain3.add_auxiliary("e", ArrayAuxReader([0.0], [1.0]))


def test_aux_values_are_copies():
    """Mutating ts.aux.<name> must not corrupt the series."""
    aux = ArrayAuxReader([0.0], [[0.0, 42.0]])
    u = _universe(times=[0.0])
    u.trajectory.add_auxiliary("e", aux)
    ts = u.trajectory[0]
    ts.aux.e[1] = -1.0
    assert float(u.trajectory[0].aux.e[1]) == 42.0
    assert aux.data[0, 1] == 42.0


def test_attach_preserves_current_frame():
    """add/remove_auxiliary must not silently rewind the cursor."""
    u = _universe(times=[0.0, 1.0, 2.0])
    u.trajectory[2]
    u.trajectory.add_auxiliary("e", ArrayAuxReader([0.0, 2.0],
                                                   [1.0, 9.0]))
    assert u.trajectory.ts.frame == 2
    assert float(u.trajectory.ts.aux.e[0]) == 9.0
    u.trajectory.remove_auxiliary("e")
    assert u.trajectory.ts.frame == 2
    assert u.trajectory.ts.aux is None


def test_edr_closed_with_guidance(tmp_path):
    """EDR is a documented conversion path, not a parser: the error
    carries the gmx recipe (the TPR/H5MD closure pattern)."""
    from mdanalysis_mpi_tpu.auxiliary import EDRReader

    p = tmp_path / "ener.edr"
    p.write_bytes(b"\x00" * 16)
    with pytest.raises(ValueError, match="gmx energy"):
        EDRReader(str(p))
