"""TopologyGroup surface (u.bonds/angles/dihedrals/impropers,
AtomGroup intersection filtering, vectorized values() against analytic
geometry), PSF NTHETA/NPHI/NIMPHI round trips, subset/concatenate
tuple remapping, and the bond-graph guessers."""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.core.topology import Topology, concatenate
from mdanalysis_mpi_tpu.core.topologyobjects import (
    guess_angles, guess_dihedrals, guess_improper_dihedrals)
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.io.psf import parse_psf, write_psf


def _butane_like():
    """4-atom chain C0-C1-C2-C3 with exact analytic geometry: 90-degree
    angle at C1, right-handed 90-degree dihedral."""
    top = Topology(
        names=np.array(["C0", "C1", "C2", "C3"]),
        resnames=np.full(4, "BUT"), resids=np.ones(4, np.int64),
        segids=np.full(4, "S"), elements=np.array(["C"] * 4),
        masses=np.full(4, 12.0), charges=np.zeros(4),
        bonds=np.array([[0, 1], [1, 2], [2, 3]]),
        angles=np.array([[0, 1, 2], [1, 2, 3]]),
        dihedrals=np.array([[0, 1, 2, 3]]),
        impropers=np.array([[1, 0, 2, 3]]),
    )
    coords = np.array([[1.5, 0, 0],       # C0
                       [0, 0, 0],         # C1
                       [0, 1.5, 0],       # C2
                       [0, 1.5, 1.5]],    # C3: dihedral 90 deg
                      np.float32)
    return Universe(top, MemoryReader(coords[None]))


def test_universe_groups_and_values():
    u = _butane_like()
    assert len(u.bonds) == 3
    np.testing.assert_allclose(u.bonds.values(), [1.5, 1.5, 1.5],
                               atol=1e-6)
    assert len(u.angles) == 2
    np.testing.assert_allclose(u.angles.values(), [90.0, 90.0],
                               atol=1e-5)
    assert len(u.dihedrals) == 1
    assert abs(abs(u.dihedrals.values()[0]) - 90.0) < 1e-4
    assert len(u.impropers) == 1
    assert np.isfinite(u.impropers.values()).all()


def test_atomgroup_strict_intersection():
    u = _butane_like()
    ag = u.atoms[[0, 1, 2]]
    assert len(ag.bonds) == 2                 # 0-1, 1-2; 2-3 dropped
    assert len(ag.angles) == 1                # only (0,1,2)
    assert len(ag.dihedrals) == 0
    # single-member indexing works like upstream's per-object value
    b0 = u.bonds[0]
    assert len(b0) == 1
    np.testing.assert_allclose(b0.values(), [1.5], atol=1e-6)


def test_missing_connectivity_raises():
    top = Topology(names=np.array(["A"]), resnames=np.array(["R"]),
                   resids=np.array([1]))
    u = Universe(top, MemoryReader(np.zeros((1, 1, 3), np.float32)))
    with pytest.raises(ValueError, match="no angles"):
        u.angles
    with pytest.raises(ValueError, match="no bonds"):
        u.bonds


def test_psf_round_trip_with_all_sections(tmp_path):
    u = _butane_like()
    p = str(tmp_path / "but.psf")
    write_psf(p, u.topology)
    top = parse_psf(p)
    np.testing.assert_array_equal(top.bonds, u.topology.bonds)
    np.testing.assert_array_equal(top.angles, u.topology.angles)
    np.testing.assert_array_equal(top.dihedrals, u.topology.dihedrals)
    np.testing.assert_array_equal(top.impropers, u.topology.impropers)


def test_subset_remaps_tuples():
    u = _butane_like()
    sub = u.topology.subset(np.array([1, 2, 3]))
    np.testing.assert_array_equal(sub.bonds, [[0, 1], [1, 2]])
    np.testing.assert_array_equal(sub.angles, [[0, 1, 2]])
    # atom 0 left the selection: the tuples are KNOWN and zero survive
    # — an empty array, NOT None (None means 'no connectivity info')
    assert sub.dihedrals is not None and len(sub.dihedrals) == 0
    assert sub.impropers is not None and len(sub.impropers) == 0


def test_concatenate_offsets_tuples():
    u = _butane_like()
    t = u.topology
    both = concatenate([t, t])
    assert len(both.angles) == 4
    np.testing.assert_array_equal(both.angles[2:], t.angles + 4)
    np.testing.assert_array_equal(both.dihedrals[1], t.dihedrals[0] + 4)


def test_guessers_on_chain():
    bonds = np.array([[0, 1], [1, 2], [2, 3]])
    angles = guess_angles(bonds, 4)
    np.testing.assert_array_equal(angles, [[0, 1, 2], [1, 2, 3]])
    dih = guess_dihedrals(angles, bonds, 4)
    np.testing.assert_array_equal(dih, [[0, 1, 2, 3]])
    # branched center: improper at the apex
    star = np.array([[0, 1], [0, 2], [0, 3]])
    a = guess_angles(star, 4)
    assert len(a) == 3
    imp = guess_improper_dihedrals(a, star, 4)
    assert len(imp) == 3
    assert all(t[0] == 0 for t in imp)        # apex first


def test_minimum_image_bond_values():
    """A bond crossing the periodic boundary measures the wrapped
    distance."""
    top = Topology(names=np.array(["A", "B"]),
                   resnames=np.full(2, "R"), resids=np.ones(2, np.int64),
                   bonds=np.array([[0, 1]]))
    coords = np.array([[[0.5, 5, 5], [9.5, 5, 5]]], np.float32)
    dims = np.array([10, 10, 10, 90, 90, 90], np.float32)
    u = Universe(top, MemoryReader(coords, dimensions=dims))
    np.testing.assert_allclose(u.bonds.values(), [1.0], atol=1e-5)


def test_dihedral_analysis_accepts_topologygroup():
    """Dihedral(u.dihedrals) runs the batched kernel over every proper
    dihedral and matches the TopologyGroup's own per-frame values."""
    from mdanalysis_mpi_tpu.analysis import Dihedral

    u = _butane_like()
    a = Dihedral(u.dihedrals).run()
    assert np.asarray(a.results.angles).shape == (1, 1)
    tg_val = u.dihedrals.values()[0]
    np.testing.assert_allclose(abs(a.results.angles[0, 0]),
                               abs(tg_val), atol=1e-4)
    with pytest.raises(ValueError, match="4-atom"):
        Dihedral(u.angles)
