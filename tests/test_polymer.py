"""PersistenceLength (upstream ``analysis.polymer``): bond-vector
autocorrelation + exponential decay fit.  Analytic fixtures: a rigid
rod (C(n)=1, lp=inf-like) and a freely-jointed chain (C(n>=1)~0);
backend parity on random chains."""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.analysis import PersistenceLength
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader


def _chain_universe(frames):
    """frames: (T, N, 3) — one chain of N atoms in file order."""
    n = frames.shape[1]
    top = Topology(names=np.full(n, "C"), resnames=np.full(n, "POL"),
                   resids=np.arange(1, n + 1))
    return Universe(top, MemoryReader(frames.astype(np.float32)))


def test_rigid_rod():
    """A straight rod: every bond vector identical -> C(n) = 1."""
    n = 12
    pos = np.zeros((3, n, 3))
    pos[:, :, 0] = np.arange(n) * 1.5
    u = _chain_universe(pos)
    r = PersistenceLength([u.atoms]).run(backend="serial")
    np.testing.assert_allclose(r.results.bond_autocorrelation, 1.0,
                               atol=1e-12)
    assert r.results.lb == pytest.approx(1.5)
    assert r.results.lp == np.inf or r.results.lp > 1e6


def test_right_angle_chain():
    """A zigzag with 90-degree turns: C(1) = 0 exactly; C(2) = 1
    (every second bond parallel)."""
    # bonds alternate +x, +y, +x, +y, ...
    steps = np.array([[1.0, 0, 0], [0, 1.0, 0]] * 5)
    pos = np.concatenate([np.zeros((1, 3)), np.cumsum(steps, axis=0)])
    u = _chain_universe(pos[None])
    r = PersistenceLength([u.atoms]).run(backend="serial")
    c = r.results.bond_autocorrelation
    assert c[0] == pytest.approx(1.0)
    assert c[1] == pytest.approx(0.0, abs=1e-12)
    assert c[2] == pytest.approx(1.0)


def test_backend_parity_multi_chain():
    rng = np.random.default_rng(41)
    t, nchains, length = 10, 4, 9
    pos = np.cumsum(rng.normal(scale=1.0, size=(t, nchains * length, 3)),
                    axis=1)
    u = _chain_universe(pos)
    chains = [u.atoms[i * length:(i + 1) * length] for i in range(nchains)]
    s = PersistenceLength(chains).run(backend="serial")
    j = PersistenceLength(chains).run(backend="jax", batch_size=4)
    np.testing.assert_allclose(j.results.bond_autocorrelation,
                               s.results.bond_autocorrelation, atol=1e-5)
    assert j.results.lb == pytest.approx(s.results.lb, rel=1e-5)
    m = PersistenceLength(chains).run(backend="mesh", batch_size=2)
    np.testing.assert_allclose(m.results.bond_autocorrelation,
                               s.results.bond_autocorrelation, atol=1e-5)


def test_known_decay_recovers_lp():
    """A worm-like chain sampled with per-bond angular diffusion: the
    fitted lp should be near the construction value lb/(1-<cos>)."""
    rng = np.random.default_rng(42)
    t, length, lb = 60, 40, 1.0
    kappa = 0.3                      # per-step angular noise
    pos = np.zeros((t, length, 3))
    for f in range(t):
        d = np.array([1.0, 0.0, 0.0])
        pts = [np.zeros(3)]
        for _ in range(length - 1):
            d = d + rng.normal(scale=kappa, size=3)
            d /= np.linalg.norm(d)
            pts.append(pts[-1] + lb * d)
        pos[f] = pts
    u = _chain_universe(pos)
    r = PersistenceLength([u.atoms]).run(backend="serial")
    c = r.results.bond_autocorrelation
    # C decays roughly geometrically; fitted lp within a factor ~2 of
    # the discrete estimate -lb/ln(C(1))
    lp_expected = -lb / np.log(c[1])
    assert 0.5 * lp_expected < r.results.lp < 2.0 * lp_expected


def test_validation():
    u = _chain_universe(np.zeros((2, 6, 3)))
    with pytest.raises(ValueError, match="at least one"):
        PersistenceLength([])
    with pytest.raises(ValueError, match="lengths"):
        PersistenceLength([u.atoms[:4], u.atoms[:5]])
    with pytest.raises(ValueError, match="3 atoms"):
        PersistenceLength([u.atoms[:2]])
    uag = u.select_atoms("name C", updating=True)
    with pytest.raises(TypeError, match="UpdatingAtomGroup"):
        PersistenceLength([uag])


def test_minimum_image_bonds():
    """A chain crossing the periodic boundary (atom-wrapped) must give
    the same lb/C(n) as its unwrapped image."""
    box = 10.0
    dims = np.array([box, box, box, 90.0, 90.0, 90.0], np.float32)
    n = 8
    un = np.zeros((2, n, 3))
    un[:, :, 0] = 7.0 + np.arange(n) * 1.5          # crosses x boundary
    wrapped = un % box
    top = Topology(names=np.full(n, "C"), resnames=np.full(n, "POL"),
                   resids=np.arange(1, n + 1))
    uu = Universe(top, MemoryReader(un.astype(np.float32),
                                    dimensions=dims))
    uw = Universe(top, MemoryReader(wrapped.astype(np.float32),
                                    dimensions=dims))
    for backend in ("serial", "jax"):
        ru = PersistenceLength([uu.atoms]).run(backend=backend,
                                               batch_size=2)
        rw = PersistenceLength([uw.atoms]).run(backend=backend,
                                               batch_size=2)
        assert rw.results.lb == pytest.approx(ru.results.lb, rel=1e-5)
        assert rw.results.lb == pytest.approx(1.5, rel=1e-5)
        np.testing.assert_allclose(rw.results.bond_autocorrelation,
                                   ru.results.bond_autocorrelation,
                                   atol=1e-5)


def test_no_exponential_regime_refuses_fit():
    """C(1) = 0 (right-angle zigzag): the autocorrelation is readable,
    the FIT raises instead of reporting lp=inf."""
    steps = np.array([[1.0, 0, 0], [0, 1.0, 0]] * 5)
    pos = np.concatenate([np.zeros((1, 3)), np.cumsum(steps, axis=0)])
    u = _chain_universe(pos[None])
    r = PersistenceLength([u.atoms]).run(backend="serial")
    assert r.results.bond_autocorrelation[1] == pytest.approx(0.0,
                                                              abs=1e-12)
    with pytest.raises(ValueError, match="not positive at lag 1"):
        _ = r.results.lp
