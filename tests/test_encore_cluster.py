"""encore.ces / encore.dres (clustering- and dimensionality-
reduction-based ensemble similarity): constructed two-state ensembles
with known cluster structure, the ln 2 saturation bound for disjoint
ensembles, near-zero divergence for identical ensembles, clusterer
unit behavior (affinity propagation + k-means on separable data), SPE
geometry preservation, and KDE correctness against the closed-form
Gaussian density."""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.analysis.encore import (
    LN2, AffinityPropagationNative, GaussianKDE, KMeansNative,
    StochasticProximityEmbeddingNative, ces,
    conformational_distance_matrix, dres)


def _blob(state, t=30, n=5, scale=0.05, seed=0):
    """(T, n, 3) tight conformational cluster: a seeded base structure
    per ``state`` plus thermal noise.  Distinct states differ in
    INTERNAL geometry (different random bases) — superposed RMSD is
    rigid-motion-invariant, so translated copies of one base would be
    the SAME conformation."""
    base = np.random.default_rng(99 + state).normal(scale=3.0,
                                                    size=(n, 3))
    rng = np.random.default_rng(seed)
    return base + rng.normal(scale=scale, size=(t, n, 3))


# --- clusterers -------------------------------------------------------

def test_affinity_propagation_two_clusters():
    a = _blob(0, seed=1)
    b = _blob(1, seed=2)
    joint = np.concatenate([a, b])
    d = conformational_distance_matrix(joint)
    labels = AffinityPropagationNative(preference=-10.0)(-d)
    assert len(labels) == len(joint)
    # the two blobs land in (at least) two clusters and no cluster
    # straddles them
    la, lb = set(labels[: len(a)].tolist()), set(labels[len(a):].tolist())
    assert la.isdisjoint(lb)


def test_affinity_propagation_rejects_bad_shapes():
    with pytest.raises(ValueError, match="square"):
        AffinityPropagationNative()(np.zeros((3, 4)))
    with pytest.raises(ValueError, match="damping"):
        AffinityPropagationNative(damping=0.2)


def test_kmeans_separable():
    x = np.concatenate([np.random.default_rng(0).normal(0, 0.1, (20, 2)),
                        np.random.default_rng(1).normal(9, 0.1, (25, 2))])
    labels = KMeansNative(n_clusters=2)(x)
    assert len(set(labels[:20].tolist())) == 1
    assert len(set(labels[20:].tolist())) == 1
    assert labels[0] != labels[-1]


def test_kmeans_validates():
    with pytest.raises(ValueError, match="n_clusters"):
        KMeansNative(n_clusters=0)


# --- ces --------------------------------------------------------------

def test_ces_identical_ensembles_zero():
    a = _blob(0, seed=3)
    d, details = ces([a, a.copy()])
    assert d.shape == (2, 2)
    assert d[0, 1] == pytest.approx(0.0, abs=1e-12)
    assert d[0, 0] == 0.0
    assert details["n_clusters"] >= 1


def test_ces_disjoint_ensembles_saturate():
    """Ensembles that never share a cluster sit at the JS bound ln 2."""
    a = _blob(0, seed=4)
    b = _blob(1, seed=5)
    d, details = ces([a, b])
    assert d[0, 1] == pytest.approx(LN2, abs=1e-9)
    assert details["populations"].shape[0] == 2


def test_ces_mixed_ensembles_intermediate():
    """An ensemble that splits its frames between both states lands
    strictly between 0 and ln 2 against a pure ensemble."""
    pure = _blob(0, seed=6)
    mixed = np.concatenate([_blob(0, t=15, seed=7),
                            _blob(1, t=15, seed=8)])
    d, _ = ces([pure, mixed])
    assert 0.05 < d[0, 1] < LN2 - 0.05


def test_ces_three_ensembles_symmetric():
    a = _blob(0, seed=9)
    b = _blob(1, seed=10)
    c = _blob(2, seed=11)
    d, _ = ces([a, b, c])
    assert d.shape == (3, 3)
    assert np.allclose(d, d.T)
    assert np.allclose(np.diag(d), 0.0)


def test_ces_kmeans_method_and_precomputed_matrix():
    a = _blob(0, seed=12)
    b = _blob(1, seed=13)
    d_km, _ = ces([a, b], clustering_method=KMeansNative(n_clusters=2))
    assert d_km[0, 1] == pytest.approx(LN2, abs=1e-9)
    # precomputed distance matrix short-circuits the device kernel
    joint = np.concatenate([a, b])
    dm = conformational_distance_matrix(joint)
    d_pre, details = ces([a, b], distance_matrix=dm)
    assert d_pre[0, 1] == pytest.approx(LN2, abs=1e-9)
    assert details["distance_matrix"] is not None
    with pytest.raises(ValueError, match="does not match"):
        ces([a, b], distance_matrix=dm[:-1, :-1])
    # a coordinate-space clusterer cannot consume a distance matrix —
    # loud error instead of silently discarding the expensive input
    with pytest.raises(ValueError, match="clusters coordinates"):
        ces([a, b], clustering_method=KMeansNative(n_clusters=2),
            distance_matrix=dm)


def test_ces_rejects_single_ensemble():
    with pytest.raises(ValueError, match="two ensembles"):
        ces([_blob(0)])


# --- SPE + KDE --------------------------------------------------------

def test_spe_preserves_separation():
    """Two far-apart blobs stay far apart relative to their internal
    spread after embedding to 2D."""
    a = _blob(0, seed=14)
    b = _blob(1, seed=15)
    d = conformational_distance_matrix(np.concatenate([a, b]))
    emb = StochasticProximityEmbeddingNative(
        dimension=2, distance_cutoff=5.0, ncycle=60, nstep=4000)(d)
    assert emb.shape == (60, 2)
    ca, cb = emb[:30].mean(0), emb[30:].mean(0)
    # spread about each centroid (NOT .std() of raw coordinates, which
    # is dominated by the centroid's position itself)
    spread = max(np.linalg.norm(emb[:30] - ca, axis=1).mean(),
                 np.linalg.norm(emb[30:] - cb, axis=1).mean())
    assert np.linalg.norm(ca - cb) > 5 * spread


def test_spe_deterministic():
    d = conformational_distance_matrix(_blob(0, seed=16))
    m = StochasticProximityEmbeddingNative(ncycle=5, nstep=500, seed=3)
    assert np.array_equal(m(d), m(d))


def test_gaussian_kde_normalizes():
    """The KDE is a density: exp(logpdf) integrates to ~1."""
    kde = GaussianKDE(np.random.default_rng(0).normal(0, 1.0, (500, 2)))
    g = np.linspace(-6, 6, 61)
    xx, yy = np.meshgrid(g, g)
    grid = np.stack([xx.ravel(), yy.ravel()], axis=1)
    mass = np.exp(kde.logpdf(grid)).sum() * (g[1] - g[0]) ** 2
    assert mass == pytest.approx(1.0, rel=0.02)


def test_gaussian_kde_closed_form_gaussian_kernel():
    """Against the analytic mixture: for KNOWN centers and bandwidth H,
    logpdf(x) must equal log( mean_i N(x; c_i, H) ) exactly — computed
    here independently from the same H the KDE built (Scott's rule)."""
    rng = np.random.default_rng(3)
    centers = rng.normal(0.0, 2.0, (6, 2))
    kde = GaussianKDE(centers)
    h = kde.h                             # (2, 2) bandwidth matrix
    hinv = np.linalg.inv(h)
    norm = 1.0 / (2 * np.pi * np.sqrt(np.linalg.det(h)))
    x = np.array([[0.3, -1.2], [4.0, 4.0], [centers[0, 0],
                                            centers[0, 1]]])
    expected = np.log(np.array([
        np.mean([norm * np.exp(-0.5 * (xi - c) @ hinv @ (xi - c))
                 for c in centers]) for xi in x]))
    np.testing.assert_allclose(kde.logpdf(x), expected, rtol=1e-10)


def test_gaussian_kde_degenerate_spread_jitter():
    """Near-identical centers exercise the jitter branch: the KDE must
    stay finite and normalized instead of failing Cholesky."""
    pts = np.zeros((4, 2))
    pts[1:] = np.random.default_rng(0).normal(0, 1e-9, (3, 2))
    kde = GaussianKDE(pts)
    assert np.isfinite(kde.logpdf(np.array([[0.0, 0.0]]))).all()


def test_gaussian_kde_sampling_follows_density():
    rng = np.random.default_rng(1)
    kde = GaussianKDE(rng.normal(5.0, 2.0, (800, 1)))
    s = kde.sample(4000, np.random.default_rng(2))
    assert s.mean() == pytest.approx(5.0, abs=0.2)
    assert s.std() == pytest.approx(2.0, rel=0.15)


# --- dres -------------------------------------------------------------

def test_dres_identical_low_disjoint_high():
    a = _blob(0, seed=17)
    b = _blob(1, seed=18)
    d_same, _ = dres([a, a.copy()], nsamples=400)
    d_diff, details = dres([a, b], nsamples=400)
    assert d_same[0, 1] < 0.05
    assert d_diff[0, 1] > 0.5          # near the ln 2 bound
    assert d_diff[0, 1] <= LN2
    assert details["embedded"].shape == (60, 3)


def test_dres_deterministic_and_symmetric():
    a = _blob(0, seed=19)
    b = _blob(1, seed=20)
    d1, _ = dres([a, b], nsamples=200, seed=5)
    d2, _ = dres([a, b], nsamples=200, seed=5)
    assert np.array_equal(d1, d2)
    assert np.allclose(d1, d1.T)
