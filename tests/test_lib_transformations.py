"""lib.transformations — 4×4 homogeneous-matrix helpers (upstream
conventions: column vectors, radians, scalar-first quaternions,
axes-string Euler conventions)."""

import itertools
import math

import numpy as np
import pytest

from mdanalysis_mpi_tpu.lib import transformations as tf


def _rand_rot(rng):
    a = rng.uniform(0, 2 * np.pi)
    d = rng.normal(size=3)
    return tf.rotation_matrix(a, d)


def test_identity_translation_scale():
    np.testing.assert_array_equal(tf.identity_matrix(), np.eye(4))
    t = tf.translation_matrix([1.0, -2.0, 3.0])
    np.testing.assert_allclose(t @ [0, 0, 0, 1], [1, -2, 3, 1])
    np.testing.assert_allclose(tf.translation_from_matrix(t), [1, -2, 3])
    s = tf.scale_matrix(2.0, origin=[1.0, 1.0, 1.0])
    # the origin is the fixed point of the scaling
    np.testing.assert_allclose(s @ [1, 1, 1, 1], [1, 1, 1, 1])
    np.testing.assert_allclose(s @ [2, 1, 1, 1], [3, 1, 1, 1])


def test_rotation_matrix_basics():
    r = tf.rotation_matrix(np.pi / 2, [0, 0, 1])
    np.testing.assert_allclose(r @ [1, 0, 0, 1], [0, 1, 0, 1], atol=1e-12)
    # about a point: that point is fixed
    rp = tf.rotation_matrix(1.1, [1, 2, 3], point=[4, 5, 6])
    np.testing.assert_allclose(rp @ [4, 5, 6, 1], [4, 5, 6, 1],
                               atol=1e-12)
    with pytest.raises(ValueError, match="nonzero"):
        tf.rotation_matrix(1.0, [0, 0, 0])


def test_rotation_from_matrix_round_trip():
    rng = np.random.default_rng(1)
    for _ in range(10):
        angle = rng.uniform(-np.pi + 0.05, np.pi - 0.05)
        direction = rng.normal(size=3)
        point = rng.normal(size=3)
        m = tf.rotation_matrix(angle, direction, point)
        a2, d2, p2 = tf.rotation_from_matrix(m)
        np.testing.assert_allclose(tf.rotation_matrix(a2, d2, p2), m,
                                   atol=1e-8)


def test_concatenate_is_right_to_left():
    t = tf.translation_matrix([1, 0, 0])
    r = tf.rotation_matrix(np.pi / 2, [0, 0, 1])
    # rotate first, then translate
    m = tf.concatenate_matrices(t, r)
    np.testing.assert_allclose(m @ [1, 0, 0, 1], [1, 1, 0, 1], atol=1e-12)


@pytest.mark.parametrize("axes", [
    "s" + "".join(p) for p in itertools.product("xyz", repeat=3)
    if p[0] != p[1] and p[1] != p[2]
] + ["r" + "".join(p) for p in itertools.product("xyz", repeat=3)
     if p[0] != p[1] and p[1] != p[2]])
def test_euler_round_trip_all_24_conventions(axes):
    # stable per-convention seed (hash() is salted per process and
    # would make a failing angle triple unreproducible)
    seed = sum(ord(c) * 7 ** i for i, c in enumerate(axes))
    rng = np.random.default_rng(seed)
    ai, aj, ak = rng.uniform(-1.2, 1.2, size=3)   # away from gimbal lock
    m = tf.euler_matrix(ai, aj, ak, axes)
    # a proper rotation
    np.testing.assert_allclose(m[:3, :3] @ m[:3, :3].T, np.eye(3),
                               atol=1e-12)
    got = tf.euler_from_matrix(m, axes)
    np.testing.assert_allclose(tf.euler_matrix(*got, axes), m, atol=1e-10)


def test_euler_static_xyz_convention_pinned():
    # sxyz: R = Rz(ak) @ Ry(aj) @ Rx(ai) (static axes compose left)
    ai, aj, ak = 0.3, -0.4, 0.9
    m = tf.euler_matrix(ai, aj, ak, "sxyz")
    expect = (tf.rotation_matrix(ak, [0, 0, 1])
              @ tf.rotation_matrix(aj, [0, 1, 0])
              @ tf.rotation_matrix(ai, [1, 0, 0]))
    np.testing.assert_allclose(m, expect, atol=1e-12)
    with pytest.raises(ValueError, match="axes"):
        tf.euler_matrix(0, 0, 0, "sxxz")


def test_quaternion_round_trip():
    rng = np.random.default_rng(7)
    for _ in range(12):
        m = _rand_rot(rng)
        q = tf.quaternion_from_matrix(m)
        assert q[0] >= 0.0 and abs(float(q @ q) - 1.0) < 1e-12
        np.testing.assert_allclose(tf.quaternion_matrix(q), m, atol=1e-10)
    # identity quaternion
    np.testing.assert_allclose(tf.quaternion_matrix([1, 0, 0, 0]),
                               np.eye(4), atol=1e-15)


def test_matches_rotateby_transformation():
    """The trajectory-level rotateby and the matrix helper must agree."""
    from mdanalysis_mpi_tpu import transformations as trf
    from mdanalysis_mpi_tpu.core.timestep import Timestep

    pos = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)
    ts = Timestep(positions=pos.copy(), frame=0)
    trf.rotateby(37.0, [1, 1, 0], point=[1, 0, 0])(ts)
    m = tf.rotation_matrix(math.radians(37.0), [1, 1, 0], point=[1, 0, 0])
    hom = np.concatenate([pos.astype(np.float64),
                          np.ones((2, 1))], axis=1)
    np.testing.assert_allclose(ts.positions, (hom @ m.T)[:, :3],
                               atol=1e-5)
