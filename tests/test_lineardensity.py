"""LinearDensity — per-axis slab mass/charge density profiles
(upstream ``analysis.lineardensity.LinearDensity`` semantics: fixed
bins from the first frame's box, g/cm³ mass units, per-frame stddev).
"""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.analysis import LinearDensity
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.testing import make_water_universe

_AMU = 1.66053906660


def _slab_universe(n_frames=4, jitter=0.0, seed=0):
    """Six atoms in two z-slabs of a 10 Å box, known masses/charges."""
    rng = np.random.default_rng(seed)
    base = np.array([
        [1.0, 1.0, 1.0], [5.0, 5.0, 1.2], [9.0, 9.0, 1.4],   # z ~ 1
        [1.0, 5.0, 8.0], [5.0, 9.0, 8.2], [9.0, 1.0, 8.4],   # z ~ 8
    ], np.float32)
    frames = np.repeat(base[None], n_frames, axis=0)
    if jitter:
        frames = frames + rng.normal(
            scale=jitter, size=frames.shape).astype(np.float32)
    top = Topology(
        names=np.array(["OW", "OW", "OW", "HW", "HW", "HW"]),
        resnames=np.array(["SOL"] * 6),
        resids=np.array([1, 1, 1, 2, 2, 2]),
        masses=np.array([16.0, 16.0, 16.0, 1.0, 1.0, 1.0]),
        charges=np.array([-0.8, -0.8, -0.8, 0.4, 0.4, 0.4]))
    dims = np.array([10.0, 10, 10, 90, 90, 90], np.float32)
    return Universe(top, MemoryReader(frames, dimensions=dims))


def test_mass_profile_integrates_to_total_mass():
    u = _slab_universe()
    ld = LinearDensity(u.atoms, binsize=1.0).run(backend="serial")
    for axis in ("x", "y", "z"):
        sub = ld.results[axis]
        dens = sub.mass_density            # g/cm3
        slab_vol = sub.slab_volume
        total_amu = float((dens / _AMU * slab_vol).sum())
        np.testing.assert_allclose(total_amu, 48.0 + 3.0, rtol=1e-10)
    # the z profile puts the heavy slab in bin 1, the light one in bin 8
    z = ld.results.z.mass_density / _AMU * ld.results.z.slab_volume
    np.testing.assert_allclose(z[1], 48.0)
    np.testing.assert_allclose(z[8], 3.0)
    # static trajectory: zero per-frame stddev
    assert float(ld.results.z.mass_density_stddev.max()) == 0.0
    # charge bookkeeping: OW slab carries 3 x -0.8, box total is -1.2
    q = ld.results.z.charge_density * ld.results.z.slab_volume
    np.testing.assert_allclose(q[1], -2.4, atol=1e-12)
    np.testing.assert_allclose(q.sum(), -1.2, atol=1e-12)


@pytest.mark.parametrize("grouping", ["atoms", "residues", "segments"])
@pytest.mark.parametrize("backend", ["jax", "mesh"])
def test_backend_parity(grouping, backend):
    u = _slab_universe(n_frames=16, jitter=0.3, seed=3)
    s = LinearDensity(u.atoms, grouping=grouping,
                      binsize=1.0).run(backend="serial")
    j = LinearDensity(u.atoms, grouping=grouping,
                      binsize=1.0).run(backend=backend, batch_size=2)
    for axis in ("x", "y", "z"):
        for key in ("mass_density", "mass_density_stddev",
                    "charge_density", "charge_density_stddev"):
            np.testing.assert_allclose(
                np.asarray(getattr(getattr(j.results, axis), key)),
                np.asarray(getattr(getattr(s.results, axis), key)),
                atol=1e-4, err_msg=f"{grouping}/{backend}/{axis}/{key}")


def test_residue_grouping_bins_com():
    u = _slab_universe()
    ld = LinearDensity(u.atoms, grouping="residues",
                       binsize=1.0).run(backend="serial")
    z = ld.results.z.mass_density / _AMU * ld.results.z.slab_volume
    # residue 1 (the three OW, com z = 1.2) lands whole in bin 1;
    # residue 2 (the three HW, com z = 8.2) in bin 8
    np.testing.assert_allclose(z[1], 48.0)
    np.testing.assert_allclose(z[8], 3.0)


def test_jitter_gives_positive_stddev_and_water_fixture_runs():
    u = _slab_universe(n_frames=12, jitter=0.4, seed=5)
    ld = LinearDensity(u.atoms, binsize=1.0).run(backend="serial")
    assert float(ld.results.z.mass_density_stddev.max()) > 0.0
    uw = make_water_universe(n_waters=30, n_frames=4, box=12.0)
    uw.topology.charges = np.zeros(uw.topology.n_atoms)
    lw = LinearDensity(uw.select_atoms("name OW"),
                       binsize=0.5).run(backend="jax", batch_size=2)
    assert lw.results.x.mass_density.shape == (lw.results.nbins,)


def test_noncubic_box_upstream_layout():
    """Upstream quirk, pinned: every axis histograms over [0, max(dims))
    with nbins = max per-axis bin count; slab_volume stays per-axis."""
    base = np.array([[1.0, 1.0, 1.0], [15.0, 8.0, 8.0]], np.float32)
    top = Topology(names=np.array(["CA", "CA"]),
                   resnames=np.array(["ALA"] * 2),
                   resids=np.array([1, 2]),
                   masses=np.array([10.0, 20.0]),
                   charges=np.array([0.5, -0.5]))
    dims = np.array([20.0, 10, 10, 90, 90, 90], np.float32)
    u = Universe(top, MemoryReader(base[None], dimensions=dims))
    ld = LinearDensity(u.atoms, binsize=1.0).run(backend="serial")
    assert ld.results.nbins == 20
    for axis, slab_vol in (("x", 100.0), ("y", 200.0), ("z", 200.0)):
        sub = getattr(ld.results, axis)
        assert sub.slab_volume == slab_vol
        np.testing.assert_allclose(sub.hist_bin_edges,
                                   np.linspace(0, 20, 21))
        total = float((sub.mass_density / _AMU * slab_vol).sum())
        np.testing.assert_allclose(total, 30.0, rtol=1e-12)
    # y positions 1 and 8 land in bins 1 and 8 of the shared 20-bin
    # range; the tail bins past the y extent stay empty
    y = ld.results.y.mass_density / _AMU * 200.0
    np.testing.assert_allclose(y[1], 10.0)
    np.testing.assert_allclose(y[8], 20.0)
    assert float(np.abs(y[10:]).max()) == 0.0


def test_validation():
    u = _slab_universe()
    with pytest.raises(ValueError, match="grouping"):
        LinearDensity(u.atoms, grouping="molecules")
    with pytest.raises(ValueError, match="binsize"):
        LinearDensity(u.atoms, binsize=0.0)
    with pytest.raises(ValueError, match="no atoms"):
        LinearDensity(u.select_atoms("name XX")).run(backend="serial")
    boxless = Universe(u.topology,
                       MemoryReader(np.zeros((2, 6, 3), np.float32)))
    with pytest.raises(ValueError, match="box"):
        LinearDensity(boxless.atoms).run(backend="serial")
    uw = make_water_universe(n_waters=4, n_frames=1)   # no charges
    with pytest.raises(ValueError, match="charges"):
        LinearDensity(uw.atoms).run(backend="serial")


def test_right_edge_and_materialize():
    """np.histogram's last bin is right-closed (an atom exactly at the
    box edge counts), and materialize() recurses into the nested
    per-axis Results."""
    pos = np.array([[[10.0, 5.0, 5.0], [5.0, 5.0, 5.0]]], np.float32)
    top = Topology(names=np.array(["CA", "CA"]),
                   resnames=np.array(["ALA"] * 2),
                   resids=np.array([1, 2]),
                   masses=np.array([7.0, 3.0]),
                   charges=np.zeros(2))
    dims = np.array([10.0, 10, 10, 90, 90, 90], np.float32)
    u = Universe(top, MemoryReader(pos, dimensions=dims))
    ld = LinearDensity(u.atoms, binsize=1.0).run(backend="jax",
                                                 batch_size=1)
    x = ld.results.x.mass_density / _AMU * ld.results.x.slab_volume
    np.testing.assert_allclose(x[9], 7.0)          # edge atom kept
    np.testing.assert_allclose(x.sum(), 10.0)
    ld2 = LinearDensity(u.atoms, binsize=1.0).run(backend="serial")
    m = ld2.results.materialize()
    assert isinstance(m["x"]["mass_density"], np.ndarray)
    np.testing.assert_allclose(
        m["x"]["mass_density"], np.asarray(ld.results.x.mass_density),
        atol=1e-5)
