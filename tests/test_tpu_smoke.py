"""On-chip smoke test (`pytest -m tpu`) — VERDICT r1 missing #2.

SURVEY.md §4 names "single-chip end-to-end runs as the hardware
integration test".  The normal suite pins JAX to a virtual CPU platform
(tests/conftest.py), so the real accelerator is exercised in a
subprocess with the default environment: AlignedRMSF (both transfer
dtypes) and the COMPILED Pallas RDF kernel (interpret mode is
auto-disabled on TPU backends, ops/pallas_distances.py) are differenced
against the serial f64 oracle on the chip.

Selection: runs under ``pytest -m tpu`` or ``MDTPU_TPU_TESTS=1``;
otherwise skipped (keeps the default suite hardware-independent and
immune to accelerator-link weather).  Skips cleanly when no TPU is
attached.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import sys
sys.path.insert(0, sys.argv[1])
import jax

if jax.default_backend() not in ("tpu", "axon") and not any(
        d.platform in ("tpu", "axon") for d in jax.devices()):
    print("NO_TPU")
    sys.exit(42)

import numpy as np
from mdanalysis_mpi_tpu.testing import make_solvated_universe, make_water_universe
from mdanalysis_mpi_tpu.analysis import AlignedRMSF, InterRDF

# --- AlignedRMSF on-chip vs serial oracle, both staging dtypes ---
u = make_solvated_universe(n_frames=24)
s = AlignedRMSF(u, select="protein and name CA").run(backend="serial")
for tdtype, tol in (("float32", 1e-4), ("int16", 1e-3)):
    a = AlignedRMSF(u, select="protein and name CA").run(
        backend="jax", batch_size=8, transfer_dtype=tdtype)
    err = float(np.abs(a.results.rmsf - s.results.rmsf).max())
    assert err < tol, f"AlignedRMSF[{tdtype}] diverged on chip: {err:.2e}"
    print(f"aligned_rmsf {tdtype} err {err:.2e}")

# --- compiled Pallas RDF (interpret auto-off on TPU) vs serial ---
uw = make_water_universe(n_waters=300, n_frames=4, seed=9)
ow = uw.select_atoms("name OW")
rp = InterRDF(ow, ow, nbins=50, range=(0.0, 8.0), engine="pallas").run(
    backend="jax", batch_size=4)
rs = InterRDF(ow, ow, nbins=50, range=(0.0, 8.0)).run(backend="serial")
err = float(np.abs(rp.results.rdf - rs.results.rdf).max())
assert err < 0.05, f"pallas RDF diverged on chip: {err:.2e}"
print(f"pallas_rdf err {err:.2e}")

# --- round-3 kernel families on chip: covariance matmul + on-device
# eigh (PCA), FFT lag algebra (MSD), int32 scatter grid (density) ---
from mdanalysis_mpi_tpu.analysis import PCA, EinsteinMSD, DensityAnalysis

p = PCA(u, select="protein and name CA", n_components=3).run(
    backend="jax", batch_size=8)
ps = PCA(u, select="protein and name CA", n_components=3).run(
    backend="serial")
perr = float(np.abs(np.asarray(p.results.variance)
                    - ps.results.variance).max())
assert perr < 1e-2 * max(float(ps.results.variance[0]), 1e-9), \
    f"PCA diverged on chip: {perr:.2e}"
print(f"pca err {perr:.2e}")

m = EinsteinMSD(uw, select="name OW").run(backend="jax", batch_size=4)
ms = EinsteinMSD(uw, select="name OW").run(backend="serial")
merr = float(np.abs(m.results.timeseries - ms.results.timeseries).max())
assert merr < 1e-2 * max(float(ms.results.timeseries.max()), 1e-9), \
    f"MSD diverged on chip: {merr:.2e}"
print(f"msd err {merr:.2e}")

d = DensityAnalysis(ow, delta=2.0).run(backend="jax", batch_size=4)
ds = DensityAnalysis(ow, delta=2.0).run(backend="serial")
# a sample sitting numerically ON a voxel boundary can floor() to
# different voxels under the kernel's f32 vs the oracle's f64 —
# platform-dependent single-sample flips, not divergence.  Recover the
# integer per-voxel counts (catching any sub-integer normalization
# drift), include the trapdoor so off-grid leakage is visible, and
# bound the number of moved samples.
nfr = uw.trajectory.n_frames
cj = np.concatenate([(np.asarray(d.results.grid) * nfr).reshape(-1),
                     [float(d.results.n_outside) * nfr]])
cs = np.concatenate([(ds.results.grid * nfr).reshape(-1),
                     [float(ds.results.n_outside) * nfr]])
resid = max(float(np.abs(cj - cj.round()).max()),
            float(np.abs(cs - cs.round()).max()))
assert resid < 1e-3, f"density counts drifted off-integer: {resid:.2e}"
moved = int(np.abs(cj.round() - cs.round()).sum())
assert moved % 2 == 0 and moved <= 8, \
    f"density diverged on chip: {moved} count deltas"
print(f"density boundary flips {moved // 2}")

# --- round-4 analysis families on chip: LinearDensity (scatter +
# Chan-moment stddev) and GNM (batched Kirchhoff eigh) ---
from mdanalysis_mpi_tpu.analysis import GNMAnalysis, LinearDensity

uw.topology.charges = np.zeros(uw.topology.n_atoms)
lds = LinearDensity(ow, binsize=1.0).run(backend="serial")
ldj = LinearDensity(ow, binsize=1.0).run(backend="jax", batch_size=4)
# all selected atoms are OW (one mass), so integer per-slab sample
# counts are exactly recoverable — same technique as the density grid:
# on-integer residual catches normalization drift, moved-count bounds
# the boundary flips without loosening sensitivity
ow_mass = float(uw.topology.masses[ow.indices[0]])
lmoved = 0
for ax in ("x", "y", "z"):
    sj, ss = getattr(ldj.results, ax), getattr(lds.results, ax)
    cj = (np.asarray(sj.mass_density) * sj.slab_volume / 1.66053906660
          / ow_mass * nfr)
    cs = (ss.mass_density * ss.slab_volume / 1.66053906660
          / ow_mass * nfr)
    resid = max(float(np.abs(cj - cj.round()).max()),
                float(np.abs(cs - cs.round()).max()))
    assert resid < 1e-2, \
        f"LinearDensity {ax} counts drifted off-integer: {resid:.2e}"
    lmoved += int(np.abs(cj.round() - cs.round()).sum())
assert lmoved <= 8, f"LinearDensity diverged on chip: {lmoved} deltas"
print(f"lineardensity boundary deltas {lmoved}")

gs = GNMAnalysis(u, select="protein and name CA").run(backend="serial")
gj = GNMAnalysis(u, select="protein and name CA").run(
    backend="jax", batch_size=8)
gdiff = np.abs(np.asarray(gj.results.eigenvalues)
               - gs.results.eigenvalues)
# the f32 contact test (d2 < cutoff2) can flip one spring on a frame
# whose pair distance sits at the cutoff — a discrete eigenvalue jump
# that is boundary noise, not divergence.  Allow at most ONE such
# frame; every other frame must agree tightly.
bad = int((gdiff > 1e-3).sum())
assert bad <= 1, f"GNM diverged on chip: {bad} frames off (max {gdiff.max():.2e})"
# one spring flip perturbs the Laplacian by a rank-2 matrix of 2-norm
# <= 2 — a boundary frame may move that far, corruption moves further
assert float(gdiff.max()) < 4.0, \
    f"GNM frame corrupted on chip: {gdiff.max():.2e}"
print(f"gnm err median {np.median(gdiff):.2e}, boundary frames {bad}")

# --- flagship cold-path mechanisms on chip (VERDICT r3 next-round #5):
# a real XTC decoded through the C++ codec, fused int16 staging via the
# decode-then-wire prestage path, and DeviceBlockCache reuse across two
# runs — all differenced against the serial f64 oracle ---
import os as _os
import tempfile

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.xtc import XTCReader, write_xtc
from mdanalysis_mpi_tpu.parallel.executors import DeviceBlockCache

rng = np.random.default_rng(17)
base = rng.normal(scale=12.0, size=(600, 3)).astype(np.float32)
frames = base[None] + rng.normal(
    scale=0.4, size=(64, 600, 3)).astype(np.float32)
path = _os.path.join(tempfile.mkdtemp(), "smoke.xtc")
write_xtc(path, frames,
          dimensions=np.array([40.0, 40, 40, 90, 90, 90]))
topo = Topology(names=np.tile(np.array(["CA", "HA"]), 300),
                resnames=np.full(600, "ALA"),
                resids=np.arange(600) // 2 + 1)
uf = Universe(topo, XTCReader(path))
sf = AlignedRMSF(uf, select="heavy").run(backend="serial")
cachef = DeviceBlockCache()
a1 = AlignedRMSF(uf, select="heavy").run(
    backend="jax", batch_size=16, transfer_dtype="int16",
    block_cache=cachef, prestage=True)
e1 = float(np.abs(a1.results.rmsf - sf.results.rmsf).max())
assert e1 < 1e-3, f"file-backed int16 cold run diverged: {e1:.2e}"
m0 = cachef.misses
a2 = AlignedRMSF(uf, select="heavy").run(
    backend="jax", batch_size=16, transfer_dtype="int16",
    block_cache=cachef)
assert cachef.misses == m0, "second run re-staged HBM-resident blocks"
assert cachef.hits > 0, "DeviceBlockCache never hit on the second run"
e2 = float(np.abs(a2.results.rmsf - sf.results.rmsf).max())
assert e2 < 1e-3, f"cache-served second run diverged: {e2:.2e}"
print(f"file_backed int16 prestage+cache err {e1:.2e}/{e2:.2e} "
      f"hits {cachef.hits}")

# --- chunked prestage + windowed wire on chip: force multiple chunks
# (4 blocks / chunk 2) with a 2-deep put window — the bounded-residency
# schedule must reproduce the one-chunk run's staged bytes exactly ---
_os.environ["MDTPU_PRESTAGE_CHUNK"] = "2"
_os.environ["MDTPU_WIRE_WINDOW"] = "2"
try:
    # fresh hint state: a1 warmed the adaptive quantizer's scale hints,
    # and a warm-hint re-stage quantizes at a DIFFERENT (coarser-range)
    # scale — bit-equality needs the same cold-hint evolution
    uf.trajectory.__dict__.pop("_quant_max_hints", None)
    uf.trajectory.__dict__.pop("_host_stage_cache", None)
    a3 = AlignedRMSF(uf, select="heavy").run(
        backend="jax", batch_size=16, transfer_dtype="int16",
        block_cache=DeviceBlockCache(), prestage=True)
    e3 = float(np.abs(np.asarray(a3.results.rmsf)
                      - np.asarray(a1.results.rmsf)).max())
    assert e3 == 0.0, f"chunked schedule changed staged bytes: {e3:.2e}"
    print("chunked prestage (chunk=2, window=2) bit-equal: ok")
finally:
    _os.environ.pop("MDTPU_PRESTAGE_CHUNK", None)
    _os.environ.pop("MDTPU_WIRE_WINDOW", None)

# --- round-5 delta wire format on chip: correlated trajectory (the
# format's stated envelope), keyframe+residual reconstruction on
# device, differenced against the serial f64 oracle ---
from mdanalysis_mpi_tpu.testing import make_md_universe

um = make_md_universe(n_residues=150, n_frames=64, step=0.05, seed=18)
sm = AlignedRMSF(um, select="heavy").run(backend="serial")
dm = AlignedRMSF(um, select="heavy").run(
    backend="jax", batch_size=16, transfer_dtype="delta")
ed = float(np.abs(dm.results.rmsf - sm.results.rmsf).max())
assert ed < 1e-3, f"delta staging diverged on chip: {ed:.2e}"
print(f"delta wire format on-chip err {ed:.2e}")

# --- round-5 additions: the fused quantized-native kernels (real
# Mosaic codegen, not interpret mode) and an AnalysisCollection over
# one staged pass — both against the serial f64 oracle ---
import os as _os2

# engine choice is read per run (default_engine) and kernels cache
# per engine string, so flipping the env var is enough
_os2.environ["MDTPU_RMSF_PALLAS"] = "1"     # exercise the Pallas sweeps
ff = AlignedRMSF(uf, select="heavy", engine="fused").run(
    backend="jax", batch_size=16, transfer_dtype="int16")
ef = float(np.abs(ff.results.rmsf - sf.results.rmsf).max())
assert ef < 1e-3, f"fused Pallas path diverged on chip: {ef:.2e}"
_os2.environ.pop("MDTPU_RMSF_PALLAS")
fx = AlignedRMSF(uf, select="heavy", engine="fused").run(
    backend="jax", batch_size=16, transfer_dtype="int16")
ex = float(np.abs(fx.results.rmsf - sf.results.rmsf).max())
assert ex < 1e-3, f"fused XLA path diverged on chip: {ex:.2e}"
print(f"fused engine on-chip err pallas {ef:.2e} / xla {ex:.2e}")

from mdanalysis_mpi_tpu.analysis import (AnalysisCollection,
                                         AverageStructure, RMSF)

coll = AnalysisCollection(
    RMSF(uf.select_atoms("heavy")),
    AverageStructure(uf, select="name CA", select_only=True))
coll.run(backend="jax", batch_size=16, transfer_dtype="int16")
srf = RMSF(uf.select_atoms("heavy")).run(backend="serial")
ec = float(np.abs(np.asarray(coll.analyses[0].results.rmsf)
                  - srf.results.rmsf).max())
assert ec < 1e-3, f"collection diverged on chip: {ec:.2e}"
print(f"collection on-chip err {ec:.2e}")
print("TPU_SMOKE_OK")
"""


def _tpu_selected(config) -> bool:
    if os.environ.get("MDTPU_TPU_TESTS") == "1":
        return True
    m = config.getoption("-m") or ""
    return "tpu" in m and "not tpu" not in m


@pytest.mark.tpu
def test_on_chip_smoke(request, tmp_path):
    if not _tpu_selected(request.config):
        pytest.skip("on-chip smoke runs under 'pytest -m tpu' or "
                    "MDTPU_TPU_TESTS=1")
    script = tmp_path / "tpu_child.py"
    script.write_text(CHILD)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable, str(script), REPO], env=env,
        capture_output=True, text=True, timeout=540)
    out = proc.stdout + proc.stderr
    if proc.returncode == 42:
        pytest.skip("no TPU attached")
    assert proc.returncode == 0, f"on-chip smoke failed:\n{out[-4000:]}"
    assert "TPU_SMOKE_OK" in proc.stdout
