"""UpdatingAtomGroup (VERDICT r4 #6): ``select_atoms(..., updating=True)``.

Membership re-evaluates whenever the universe's current frame changes —
the general form of the reference's in-loop ``select_atoms``
(RMSF.py:126).  Pinned: per-frame membership of geometric selections
(table-driven), scoped (subgroup) updating selections, the
AnalysisFromFunction dynamic route, and the loud static-snapshot
refusal from ordinary analyses on every backend.
"""

import numpy as np
import pytest

from mdanalysis_mpi_tpu import UpdatingAtomGroup
from mdanalysis_mpi_tpu.analysis import AnalysisFromFunction, RMSD
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader

IN, OUT = 2.0, 9.0          # inside / outside a 3 Å shell of the CA atom


def _universe(frames):
    """One fixed CA atom at the origin + three waters whose per-frame x
    positions are scripted, so shell membership is known exactly."""
    n = len(frames)
    pos = np.zeros((n, 4, 3), np.float32)
    for f, xs in enumerate(frames):
        for j, x in enumerate(xs):
            pos[f, j + 1] = [x, 0.0, 0.0]
    top = Topology(names=np.array(["CA", "OW", "OW", "OW"]),
                   resnames=np.array(["GLY", "SOL", "SOL", "SOL"]),
                   resids=np.array([1, 2, 3, 4]))
    return Universe(top, MemoryReader(pos))


# (frame layouts, expected member water indices per frame) — the
# table-driven contract: an `around`-based group changes membership
# across frames
CASES = [
    ([(IN, IN, OUT), (IN, OUT, OUT), (OUT, OUT, OUT), (IN, IN, IN)],
     [[1, 2], [1], [], [1, 2, 3]]),
    ([(OUT, OUT, OUT), (OUT, IN, OUT)],
     [[], [2]]),
]


@pytest.mark.parametrize("frames,expected", CASES)
def test_membership_tracks_frames(frames, expected):
    u = _universe(frames)
    shell = u.select_atoms("name OW and around 3.0 name CA", updating=True)
    assert isinstance(shell, UpdatingAtomGroup)
    seen = []
    for _ts in u.trajectory:
        seen.append(shell.indices.tolist())
        # positions re-gather through the same freshness check
        assert shell.positions.shape == (len(seen[-1]), 3)
        assert len(shell) == len(seen[-1])
    assert seen == expected
    # iterating again re-evaluates again (no stale terminal state)
    u.trajectory[0]
    assert shell.indices.tolist() == expected[0]


def test_static_group_stays_static():
    u = _universe([(IN, IN, OUT), (OUT, OUT, OUT)])
    static = u.select_atoms("name OW and around 3.0 name CA")
    assert static.indices.tolist() == [1, 2]
    u.trajectory[1]
    assert static.indices.tolist() == [1, 2]       # frozen, by contract


def test_scoped_updating_group():
    """updating selection within a subgroup: only that group's atoms are
    candidates (upstream scope semantics)."""
    u = _universe([(IN, IN, IN), (IN, OUT, IN)])
    two = u.atoms[[0, 1, 2]]                        # CA + first two waters
    shell = two.select_atoms("name OW and around 3.0 name CA",
                             updating=True)
    assert shell.indices.tolist() == [1, 2]
    u.trajectory[1]
    assert shell.indices.tolist() == [1]            # water 3 never eligible


def test_same_frame_single_evaluation():
    u = _universe([(IN, IN, OUT), (IN, OUT, OUT)])
    shell = u.select_atoms("name OW and around 3.0 name CA", updating=True)
    _ = shell.indices
    first = shell.indices
    # same frame: the SAME array object comes back (one evaluation)
    assert shell.indices is first
    u.trajectory[1]
    assert shell.indices is not first


def test_analysis_from_function_sees_updates():
    """The supported dynamic-membership analysis route: the user
    function reads the group per frame."""
    u = _universe([(IN, IN, OUT), (IN, OUT, OUT), (OUT, OUT, OUT)])
    shell = u.select_atoms("name OW and around 3.0 name CA", updating=True)
    r = AnalysisFromFunction(lambda ag: ag.n_atoms, shell).run(
        backend="serial")
    assert [int(v) for v in r.results.timeseries] == [2, 1, 0]


def test_snapshot_analyses_refuse_loudly():
    u = _universe([(IN, IN, OUT), (IN, OUT, OUT)])
    shell = u.select_atoms("name OW and around 3.0 name CA", updating=True)
    for backend in ("serial", "jax"):
        with pytest.raises(TypeError, match="UpdatingAtomGroup"):
            RMSD(shell).run(backend=backend)


def test_validates_eagerly():
    u = _universe([(IN, IN, OUT)])
    with pytest.raises(Exception):
        u.select_atoms("nmae OW", updating=True)


def test_nested_updating_group_tracks_base():
    """An updating selection whose BASE is itself updating must see the
    base's per-frame membership, not its creation-frame snapshot."""
    u = _universe([(IN, IN, OUT), (OUT, IN, IN)])
    shell = u.select_atoms("around 3.0 name CA", updating=True)
    nested = shell.select_atoms("name OW", updating=True)
    assert shell.indices.tolist() == [1, 2]
    assert nested.indices.tolist() == [1, 2]
    u.trajectory[1]
    assert shell.indices.tolist() == [2, 3]
    assert nested.indices.tolist() == [2, 3]      # tracks, not frozen


def test_duplicate_length_group_is_not_whole_universe():
    """A base group whose LENGTH happens to equal n_atoms (duplicates)
    must still scope the updating selection to its members."""
    u = _universe([(IN, IN, IN)])
    grp = u.atoms[[0, 0, 1, 2]]                   # len 4 == n_atoms; no 3
    shell = grp.select_atoms("name OW", updating=True)
    assert shell.indices.tolist() == [1, 2]       # atom 3 never eligible


def test_constructor_snapshot_analyses_refuse():
    """Dihedral/Contacts snapshot groups in __init__ and drop them —
    they must refuse an UpdatingAtomGroup there, since the run()-time
    scan cannot see a dropped group."""
    from mdanalysis_mpi_tpu.analysis import Contacts, Dihedral

    u = _universe([(IN, IN, OUT), (IN, OUT, OUT)])
    uag = u.select_atoms("around 3.0 name CA", updating=True)
    with pytest.raises(TypeError, match="UpdatingAtomGroup"):
        Dihedral([uag])
    with pytest.raises(TypeError, match="UpdatingAtomGroup"):
        Contacts(u, select=("name OW", "name OW"), refgroup=(uag, uag))
