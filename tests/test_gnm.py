"""GNMAnalysis — Kirchhoff-matrix slowest-mode time series (upstream
``analysis.gnm.GNMAnalysis`` semantics: contact springs within cutoff,
per-frame eigenvalue[1] + eigenvector)."""

import numpy as np
import pytest

from mdanalysis_mpi_tpu.analysis import GNMAnalysis
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.testing import make_protein_universe


def _chain_universe(n_frames=1):
    """4 nodes on a line, 5 Å apart: with cutoff 7 only neighbors bond
    → path-graph Laplacian with known spectrum 2-2cos(kπ/4)."""
    pos = np.array([[0.0, 0, 0], [5.0, 0, 0], [10.0, 0, 0],
                    [15.0, 0, 0]], np.float32)
    frames = np.repeat(pos[None], n_frames, axis=0)
    top = Topology(names=np.array(["CA"] * 4),
                   resnames=np.array(["GLY"] * 4),
                   resids=np.arange(4) + 1)
    return Universe(top, MemoryReader(frames))


def test_path_graph_spectrum():
    u = _chain_universe(n_frames=3)
    r = GNMAnalysis(u, select="name CA", cutoff=7.0).run(backend="serial")
    # path graph P4: eigenvalues 2 - 2cos(k pi / 4), k=0..3; mode 1:
    lam1 = 2.0 - 2.0 * np.cos(np.pi / 4)
    np.testing.assert_allclose(r.results.eigenvalues,
                               np.full(3, lam1), atol=1e-10)
    assert r.results.eigenvectors.shape == (3, 4)
    # Fiedler vector of a path is monotone across the chain
    v = r.results.eigenvectors[0]
    assert (np.diff(v) > 0).all() or (np.diff(v) < 0).all()


def test_backend_parity_and_sign_convention():
    u = make_protein_universe(n_residues=12, n_frames=16, noise=0.4,
                              seed=3)
    s = GNMAnalysis(u, select="name CA").run(backend="serial")
    j = GNMAnalysis(u, select="name CA").run(backend="jax", batch_size=4)
    np.testing.assert_allclose(np.asarray(j.results.eigenvalues),
                               s.results.eigenvalues, atol=1e-3)
    # eigenVECTORS are only comparable across f32/f64 when the mode is
    # spectrally isolated — near-degenerate lambda_1 ~ lambda_2 frames
    # legitimately rotate the eigenbasis; compare where the serial
    # eigengap is clear (the sign convention makes them directly equal)
    def _gaps():
        out = []
        for i in s._frame_indices:
            x = u.trajectory[i].positions[s._idx].astype(np.float64)
            d2 = ((x[:, None] - x[None]) ** 2).sum(-1)
            a = (d2 < 49.0).astype(float)
            np.fill_diagonal(a, 0)
            lam = np.linalg.eigvalsh(np.diag(a.sum(1)) - a)
            out.append(lam[2] - lam[1])
        return np.asarray(out)

    clear = _gaps() > 0.1
    assert clear.sum() >= 8, "fixture too degenerate to test vectors"
    jv = np.asarray(j.results.eigenvectors)[clear]
    sv = s.results.eigenvectors[clear]
    # the sign convention breaks ties by |component| argmax, which can
    # land differently across f32/f64 — align residual sign per frame
    # (the eigenvector contract is up-to-sign there)
    sign = np.sign((jv * sv).sum(axis=1, keepdims=True))
    np.testing.assert_allclose(jv * sign, sv, atol=5e-3)
    m = GNMAnalysis(u, select="name CA").run(backend="mesh", batch_size=2)
    np.testing.assert_allclose(np.asarray(m.results.eigenvalues),
                               s.results.eigenvalues, atol=1e-3)


def test_validation():
    u = _chain_universe()
    with pytest.raises(ValueError, match="cutoff"):
        GNMAnalysis(u, cutoff=0.0)
    with pytest.raises(ValueError, match="at least 3"):
        GNMAnalysis(u, select="resid 1:2").run(backend="serial")
    n = 8_100
    big_top = Topology(names=np.array(["CA"] * n),
                       resnames=np.array(["GLY"] * n),
                       resids=np.arange(n) + 1)
    big = Universe(big_top,
                   MemoryReader(np.zeros((1, n, 3), np.float32)))
    with pytest.raises(ValueError, match="Kirchhoff"):
        GNMAnalysis(big, select="all").run(backend="serial")
