"""Pluggable execution backends.

The dispatch point BASELINE.json's north_star prescribes: "only the inner
``_single_frame`` compute crosses the backend boundary".  An analysis
(:class:`~mdanalysis_mpi_tpu.analysis.base.AnalysisBase`) exposes

- ``_single_frame(ts)`` + ``_serial_summary()``   (host oracle path), and
- ``_make_batch_kernel() -> fn(batch, mask) -> partials`` with
  ``_combine(a, b)`` / optional ``_device_combine(partials, axis)``
  (device batch path),

and the executors below schedule those over the trajectory:

- :class:`SerialExecutor` — per-frame NumPy loop in float64; the
  reference's single-rank behavior and the differential-test oracle.
- :class:`JaxExecutor` — single-device: frame blocks staged host→HBM,
  one jitted batch kernel per block, cross-block merge folded ON DEVICE
  in float32 (``_device_fold_fn``, e.g. the Chan moment merge).
- :class:`MeshExecutor` — multi-device: batches sharded over the mesh
  data axis via ``shard_map``; cross-chip merge by the analysis'
  ``_device_combine`` (``jax.lax.psum``-based — the TPU-native
  replacement for ``comm.Allreduce``/``comm.reduce``,
  RMSF.py:110,143).

Precision policy (SURVEY.md §7 Q4): within-batch math runs in full f32
(see ``_f32_precision``); the cross-batch fold also runs in f32 *on
device* — per-batch device→host readback for a host f64 merge costs
seconds on tunneled TPU targets and was removed.  Error analysis for
the f32 Chan fold: with T ≤ 1e6 frames and coordinate scale ~1e2, the
dominant term ``T·μ`` stays ≤ 1e8 where f32 carries ~7 significant
digits, giving ≲1e-5 relative drift in mean/M2 — inside the framework's
stated f32 tolerance (differential tests pin it).  The serial backend
remains the exact f64 oracle.
"""

from __future__ import annotations

import time as _time

import numpy as np

from mdanalysis_mpi_tpu.obs import prof as _prof
from mdanalysis_mpi_tpu.obs import spans as _spans
from mdanalysis_mpi_tpu.obs import usage as _usage
from mdanalysis_mpi_tpu.parallel.partition import iter_batches, pad_batch
from mdanalysis_mpi_tpu.reliability import faults as _faults
from mdanalysis_mpi_tpu.utils import compile_cache as _cc
from mdanalysis_mpi_tpu.utils import integrity as _integrity
from mdanalysis_mpi_tpu.utils.timers import TIMERS


def _shard_map():
    """``jax.shard_map`` across the supported jax range: top-level when
    present, else the experimental module — with the checking flag
    picked by SIGNATURE (``check_vma=`` post-rename, ``check_rep=``
    before it; some releases have a public jax.shard_map that still
    takes check_rep, so attribute presence alone is not enough).
    Returned as a uniform ``fn(f, mesh, in_specs, out_specs)`` with
    replication/vma checking off."""
    import functools
    import inspect

    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):      # C-accelerated / wrapped
        params = {"check_vma": None}
    if "check_vma" in params:
        return functools.partial(sm, check_vma=False)
    if "check_rep" in params:
        return functools.partial(sm, check_rep=False)
    return sm


def _f32_precision(fn):
    """Trace ``fn`` under full-float32 matmul precision.

    TPU matmuls (including jnp.linalg internals the explicit
    ``precision=`` pins in ops/ can't reach) default to bfloat16 passes —
    a ~1e-2 relative error that breaks superposition geometry.  The
    kernels are bandwidth-bound with tiny contraction dims, so full f32
    is effectively free (precision policy, SURVEY.md §7 Q4).
    """
    import functools

    import jax

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("float32"):
            return fn(*args, **kwargs)

    return wrapped


# Module-level jit/mesh caches.  Analyses hand executors *module-level*
# kernel functions plus a params pytree (instead of per-run closures), so
# the compile cache survives across run() calls — a fresh closure per run
# would force XLA recompilation every time (~tens of seconds for the
# superposition kernels, observed on TPU).
_JIT_CACHE: dict = {}
_MESH_CACHE: dict = {}


def _jit_kernel(f):
    fn = _JIT_CACHE.get(f)
    if fn is None:
        # every jit here routes through the compile cache so the
        # persistent on-disk cache is active for ANY entry point
        # (docs/COLDSTART.md)
        fn = _cc.jit(_f32_precision(f))
        _JIT_CACHE[f] = fn
    return fn


_FUSED_STEP_CACHE: dict = {}


def _fused_step(kernel, fold):
    """One jitted ``total, params, *staged -> fold(total, kernel(...))``
    per (kernel, fold) identity pair.

    The steady-state flagship is DISPATCH-bound (~0.1 ms of fixed
    per-dispatch latency on tunneled targets × ~4 dispatches per batch
    across two passes — PERF.md §6); folding the cross-batch merge into
    the kernel's own dispatch halves the per-batch dispatch count on
    the single-device path.  Cache keyed on module-level function
    identities (same contract as ``_jit_kernel``), so compiles survive
    across ``run()`` calls.

    Cost accepted deliberately: a fresh process compiles the kernel
    TWICE (standalone for batch 1, fused for batch 2+).  The win is the
    steady-state/repeat-run regime the flagship headline measures —
    there the compile cache is warm and every batch saves one fixed
    ~0.1 ms dispatch round-trip; one-shot users pay one extra compile,
    bounded by the first run's existing compile wall."""
    key = (kernel, fold)
    fn = _FUSED_STEP_CACHE.get(key)
    if fn is None:
        def step(total, params, *staged):
            return fold(total, kernel(params, *staged))

        fn = _cc.jit(_f32_precision(step))
        _FUSED_STEP_CACHE[key] = fn
    return fn


#: Resolved ``scan_k`` of the most recent batch run (1 = per-block
#: schedule).  Telemetry only — bench artifacts disclose it next to
#: ``dispatch_count`` so the dispatch-batching claim is attributable
#: from the JSON alone; never part of the results contract.
LAST_SCAN_K = 1


class _ScanCalls:
    """The three single-dispatch scan programs a backend hands
    ``_run_batches`` when the scan-folded schedule is active:
    ``init(*stacked)`` (first group, no running total),
    ``fused(total, *stacked)`` (later groups, fold into the total),
    ``series(*stacked)`` (no fold: per-step partials emitted stacked and
    flattened to frame order).  Reduction analyses set init/fused;
    time-series analyses set series."""

    __slots__ = ("init", "fused", "series")

    def __init__(self, init=None, fused=None, series=None):
        self.init = init
        self.fused = fused
        self.series = series


def _scan_accum(kernel, fold, params, stacked):
    """The ONE carry+step reduction body every scan program traces —
    single-device (_scan_fns) and per-shard inside shard_map
    (MeshExecutor._build_scan) alike, so the two schedules cannot
    diverge.  The carry seeds from block 0's kernel output (no identity
    element required of ``fold``); a K=1 group degenerates to a
    zero-length scan."""
    import jax

    first = kernel(params, *(x[0] for x in stacked))

    def step(carry, xs):
        return fold(carry, kernel(params, *xs)), None

    acc, _ = jax.lax.scan(step, first, tuple(x[1:] for x in stacked))
    return acc


def _scan_emit(kernel, params, stacked):
    """Series twin of :func:`_scan_accum`: per-step partials emitted
    stacked (K, B, ...) — flatten with :func:`_flatten_block_axis`."""
    import jax

    def step(carry, xs):
        return carry, kernel(params, *xs)

    _, ys = jax.lax.scan(step, 0, stacked)
    return ys


def _flatten_block_axis(ys):
    """(K, B, ...) scan outputs → (K·B, ...): exactly the per-block
    schedule's concatenation order."""
    import jax

    return jax.tree.map(
        lambda y: y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:]),
        ys)


def _make_scan_calls(fns, params) -> "_ScanCalls":
    """Bind a ``(init, fused, series)`` jitted-program triple to this
    run's params pytree — the one params-binding contract both
    executors hand ``_run_batches``."""
    s_init, s_fused, s_series = fns
    return _ScanCalls(
        init=(None if s_init is None
              else lambda *st: s_init(params, *st)),
        fused=(None if s_fused is None
               else lambda tot, *st: s_fused(tot, params, *st)),
        series=(None if s_series is None
                else lambda *st: s_series(params, *st)))


_SCAN_FN_CACHE: dict = {}


def _scan_fns(kernel, fold):
    """Jitted scan programs over a STACKED K-block group (leading block
    axis): the dispatch-amortization lever VERDICT r5 #7 named.

    The steady-state flagship is dispatch-bound: ~0.1 ms of fixed
    per-dispatch latency × one dispatch per block (PERF.md §8d/§9e —
    batch 512 won the sweep precisely because it meant FEWER
    dispatches).  When the staged blocks are HBM-resident
    (DeviceBlockCache), the per-block Python loop is pure overhead:
    these programs run the SAME per-block kernel inside one
    ``lax.scan`` over the group's stacked blocks, folding partials in
    the scan carry, so K blocks cost one host dispatch instead of K.

    Reduction form: the carry seeds from block 0's kernel output (no
    identity-element requirement on ``fold``) and scans blocks 1..K-1;
    a K=1 group degenerates to a zero-length scan.  Series form: the
    carry is unused and per-step partials come back stacked (K, B, ...)
    then reshape to (K·B, ...) — exactly the concatenation order of the
    per-block schedule.  Compile cost is O(1) in K (scan is a loop
    primitive; XLA retraces per distinct K shape, i.e. once plus once
    for an uneven tail group).  Cache keyed on module-level function
    identities, same contract as ``_jit_kernel``."""
    key = (kernel, fold)
    fns = _SCAN_FN_CACHE.get(key)
    if fns is None:
        if fold is not None:
            def init(params, *stacked):
                return _scan_accum(kernel, fold, params, stacked)

            def fused(total, params, *stacked):
                return fold(total,
                            _scan_accum(kernel, fold, params, stacked))

            fns = (_cc.jit(_f32_precision(init)),
                   _cc.jit(_f32_precision(fused)), None)
        else:
            def series(params, *stacked):
                return _flatten_block_axis(
                    _scan_emit(kernel, params, stacked))

            fns = (None, None, _cc.jit(_f32_precision(series)))
        _SCAN_FN_CACHE[key] = fns
    return fns


_STACK_CACHE: dict = {}


def _stack_staged(blocks: list[tuple]):
    """K per-block staged tuples → one stacked tuple with a leading
    block axis — the HBM superblock the scan programs consume.

    Device leaves stack ON DEVICE in one jitted dispatch (one HBM copy,
    paid once on the populating pass; re-staging through the host would
    cost wire bytes instead).  Host-side leaves (quantize scales that
    ride the dispatch) stack with NumPy.  On the mesh path the inputs
    carry their NamedShardings and GSPMD propagates the frame-axis
    sharding to the stacked output (leading block axis unsharded)."""
    import jax
    import jax.numpy as jnp

    n = len(blocks[0])
    k = len(blocks)
    dev_pos = tuple(i for i in range(n)
                    if isinstance(blocks[0][i], jax.Array))
    key = (n, dev_pos, k)
    fn = _STACK_CACHE.get(key)
    if fn is None:
        m = len(dev_pos)

        def stack(*flat):
            return tuple(jnp.stack(flat[j * k:(j + 1) * k])
                         for j in range(m))

        fn = _cc.jit(stack)
        _STACK_CACHE[key] = fn
    flat = [blocks[b][i] for i in dev_pos for b in range(k)]
    stacked_dev = iter(fn(*flat))
    return tuple(
        next(stacked_dev) if i in dev_pos
        else np.stack([np.asarray(blocks[b][i]) for b in range(k)])
        for i in range(n))


def _delete_staged(staged) -> None:
    """Release a staged tuple's device buffers NOW (``Array.delete()``),
    not when the GC gets around to it: on tunneled targets the client
    pins ~1 host byte per device byte (PERF.md §9d), so silently
    dropped buffers leak host RSS into the fast-page window §9b
    diagnosed.  Safe on buffers with in-flight consumers — the runtime
    holds its own reference until enqueued executions complete."""
    import jax

    for leaf in jax.tree.leaves(staged):
        if hasattr(leaf, "delete"):
            try:
                leaf.delete()
            except Exception:       # already deleted / donated
                pass


def _block_nbytes(bs: int, sel_idx, n_atoms: int,
                  transfer_dtype: str) -> int:
    """Estimated staged bytes of one (bs, S, 3) block — the auto scan_k
    policy's unit (boxes/mask/keyframes are noise at these shapes)."""
    s = n_atoms if sel_idx is None else len(sel_idx)
    per = {"float32": 4, "int16": 2, "int8": 1, "delta": 1}[transfer_dtype]
    return bs * s * 3 * per


def _staged_avals(bs: int, n_stage: int, quantize,
                  delta_anchors: int = 1, inv_per_frame: bool = False,
                  shardings=None, layout: str = "interleaved"):
    """`jax.ShapeDtypeStruct`s of the staged tuple `_host_stage`
    produces for this geometry — the shape contract the AOT warmup
    surface lowers against (docs/COLDSTART.md).  MUST mirror
    `_host_stage`/`_put_staged` exactly: an AOT executable registered
    under these avals is later called with the real staged tuple, and
    a drift here turns every warmup into a dead registry entry (the
    executors fall back to the jit path on key mismatch, so drift is a
    perf regression, not a crash).  ``shardings``: optional per-element
    NamedShardings (mesh path), applied positionally like
    ``_put_staged`` targets.  ``layout='planar'`` (the fused Pallas
    kernel's staging form, ops/pallas_fused.py) swaps the quantized
    block aval to ``(3, bs, n_stage)`` component planes."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    if quantize == "delta":
        a = delta_anchors
        avals = (S((bs, n_stage, 3), jnp.int8),
                 S((a, n_stage, 3), jnp.int16),
                 S((a, 1, 1), jnp.float32),
                 S((bs, 1, 1), jnp.float32),
                 S((bs, 6), jnp.float32),
                 S((bs,), jnp.float32))
    elif quantize:
        inv = (S((bs, 1, 1), jnp.float32) if inv_per_frame
               else S((), jnp.float32))
        q_shape = ((3, bs, n_stage) if layout == "planar"
                   else (bs, n_stage, 3))
        avals = (S(q_shape, jnp.dtype(quantize)), inv,
                 S((bs, 6), jnp.float32),
                 S((bs,), jnp.float32))
    else:
        avals = (S((bs, n_stage, 3), jnp.float32),
                 S((bs, 6), jnp.float32),
                 S((bs,), jnp.float32))
    if shardings is not None:
        avals = tuple(
            S(a.shape, a.dtype, sharding=s) if s is not None else a
            for a, s in zip(avals, shardings))
    return avals


def _stacked_avals(avals, k: int):
    """Leading scan-group axis ``k`` prepended to every staged aval —
    the stacked-superblock shapes the scan programs consume."""
    import jax

    return tuple(jax.ShapeDtypeStruct((k,) + tuple(a.shape), a.dtype)
                 for a in avals)


def _op_label(base_fn, transfer_dtype: str, backend: str,
              role: str) -> str:
    """Stable-across-processes AOT op label: the un-wrapped kernel's
    module.qualname.name + staging dtype + executor backend + program
    role (kernel / fused / scan_init / scan_fused / scan_series).
    ``__name__`` rides along because factory-built kernels share a
    qualname but stamp a distinctive name (the collection kernel names
    its children) — without it two different collections with
    identical staged shapes would collide on one executable."""
    return (f"{base_fn.__module__}.{base_fn.__qualname__}"
            f".{base_fn.__name__}|{transfer_dtype}|{backend}|{role}")


def _resolve_scan_k(setting, cache, n_blocks: int,
                    block_nbytes: int) -> int:
    """Effective scan group size for this run.

    ``setting``: the executor's ``scan_k`` (None defers to env
    ``MDTPU_SCAN_K``, default ``"auto"``).  Auto folds ALL of the run's
    blocks into one scan group up to an HBM budget; the budget defaults
    to the cache's own byte cap (the stacked groups ARE the cached
    entries) and can be pinned via MDTPU_SCAN_HBM_BUDGET.  An explicit
    integer is clamped to the block count AND the same byte budget —
    an over-budget group would materialize a stacked superblock the
    cache then rejects (one transient HBM spike, zero entries cached,
    every later run re-staging: strictly worse than a smaller K).
    Either way a DeviceBlockCache is REQUIRED: the scan dispatches only
    over cached superblocks, so a cacheless run would pay the group
    bookkeeping forever without a single scan ever firing — and worse,
    report a scan_k in the telemetry that never describes a real
    dispatch.  docs/DISPATCH.md discusses when scan_k=1 is right."""
    if cache is None:
        return 1
    if setting is None:
        setting = _os.environ.get("MDTPU_SCAN_K", "auto")
    budget = int(_os.environ.get("MDTPU_SCAN_HBM_BUDGET", "0")
                 or 0) or cache.max_bytes
    budget_blocks = max(1, budget // max(block_nbytes, 1))
    if isinstance(setting, str):
        s = setting.strip().lower()
        if s in ("auto", ""):
            return max(1, min(n_blocks, budget_blocks))
        setting = int(s)
    return max(1, min(int(setting), max(n_blocks, 1), budget_blocks))


def _uniform_stride(frames) -> int | None:
    """The constant positive stride of ``frames``, or None.  Strided
    windows (``run(step=N)``) then ride the readers' bulk ``read_block``
    instead of per-frame reads."""
    if len(frames) < 2:
        return 1
    step = frames[1] - frames[0]
    if step < 1:
        return None
    if frames[-1] - frames[0] == step * (len(frames) - 1):
        it = iter(frames)
        prev = next(it)
        for f in it:
            if f - prev != step:
                return None
            prev = f
        return step
    return None


def _stage(reader, frames: list[int], sel_idx):
    """Read ``frames`` → (float32 (b, S, 3), boxes (b, 6) or None) with
    the selection gather pushed into the reader (one copy; slashes host
    work and host→HBM traffic when S << N)."""
    if len(frames) == 0:
        n = reader.n_atoms if sel_idx is None else len(sel_idx)
        return np.empty((0, n, 3), dtype=np.float32), None
    stride = _uniform_stride(frames)
    if stride is not None:
        return reader.read_block(frames[0], frames[-1] + 1, sel=sel_idx,
                                 step=stride)
    tss = [reader[i] for i in frames]
    block = np.stack([ts.positions for ts in tss])
    # per-frame optional boxes: zeros for boxless frames, None only when
    # no frame carries one (matches the contiguous read_block contract)
    boxes = None
    for j, ts in enumerate(tss):
        if ts.dimensions is not None:
            if boxes is None:
                boxes = np.zeros((len(tss), 6), dtype=np.float32)
            boxes[j] = ts.dimensions
    if sel_idx is not None:
        block = block[:, sel_idx]
    return block, boxes


_DEQUANT_WRAPPERS: dict = {}


def _dequant_wrapper(fn):
    """Wrap kernel ``fn(params, batch_f32, boxes, mask)`` as
    ``g((sel, params), batch_i16, inv_scale, boxes, mask)``: dequantize
    on device and, when ``sel`` is not None, gather the selection on
    device too (full-frame staging skips the host-side fancy-index
    gather — cheaper for wide selections on a single staging core).
    Cached per fn so the jit cache stays stable."""
    g = _DEQUANT_WRAPPERS.get(fn)
    if g is None:
        import jax.numpy as jnp

        def g(wrapped_params, q, inv_scale, boxes, mask):
            sel, params = wrapped_params
            x = q.astype(jnp.float32) * inv_scale
            if sel is not None:
                x = x[:, sel]
            return fn(params, x, boxes, mask)

        _DEQUANT_WRAPPERS[fn] = g
    return g


def _quantized_native(analysis, transfer_dtype: str):
    """Analysis-provided quantized-native kernel, or None.

    An analysis may implement ``_quantized_batch(transfer_dtype)``
    returning ``(fn, params, sel_idx)`` where ``fn(params, q,
    inv_scale, boxes, mask)`` consumes the staged quantized block
    DIRECTLY (same calling convention the dequant wrapper produces) —
    e.g. the fused Pallas RMSF path (ops/pallas_rmsf.py), which reads
    the int16 block twice and materializes no dequantized copy.
    Returning None keeps the generic dequant-wrapper path."""
    get = getattr(analysis, "_quantized_batch", None)
    if get is None:
        return None
    return get(transfer_dtype)


def _mesh_quantized_native(analysis, transfer_dtype: str):
    """Mesh-path variant of :func:`_quantized_native`: planar-staged
    kernels (``staging_layout='planar'``, the fused Pallas path) are
    single-device programs today — their ``(3, B, S)`` staging would
    need a component-replicated/frame-sharded in_spec the mesh builder
    doesn't carry — so the mesh keeps the interleaved-staging program
    for them (the generic dequant path, or the interleaved XLA fused
    form when the Pallas engine is off).  The demotion is counted, not
    silent."""
    qn = _quantized_native(analysis, transfer_dtype)
    if (qn is not None and getattr(qn[0], "staging_layout",
                                   "interleaved") != "interleaved"):
        from mdanalysis_mpi_tpu import obs

        obs.METRICS.inc("mdtpu_fused_fallbacks_total")
        return None
    return qn


def _validate_transfer_dtype(transfer_dtype: str) -> None:
    if transfer_dtype not in ("float32", "int16", "int8", "delta"):
        raise ValueError(
            f"transfer_dtype must be 'float32', 'int16', 'int8' or "
            f"'delta', got {transfer_dtype!r}")


def _quant_mode(transfer_dtype: str):
    """Executor transfer dtype → stage_block/stage_cached quantize arg
    (None for the float32 path)."""
    return None if transfer_dtype == "float32" else transfer_dtype


def _wrap_for_transfer(params, sel_idx, n_atoms: int, transfer_dtype: str):
    """Shared quantized-staging setup for Jax/Mesh executors: wrap
    params as ``(device_gather_sel, params)`` for the dequant wrapper,
    moving the selection gather onto the device for wide selections
    (see ``_DEVICE_GATHER_FRACTION``).  Returns (params, sel_idx)."""
    if transfer_dtype == "float32":
        return params, sel_idx
    if transfer_dtype == "delta":
        # delta staging always gathers the selection on host — the wire
        # saving IS the point, and a full-frame residual stream would
        # give it back
        return (None, params), sel_idx
    if (sel_idx is not None
            and len(sel_idx) > _DEVICE_GATHER_FRACTION * n_atoms):
        import jax.numpy as jnp

        return (jnp.asarray(sel_idx), params), None
    return (None, params), sel_idx


# Selections wider than this fraction of the system are gathered on
# device (full-frame staging) instead of on the host staging core.
# Worth enabling (~0.25) when the host link is fast (PCIe-attached TPU)
# and the single staging core is the bottleneck; disabled by default
# because on tunneled targets (axon) wire bytes dominate and host
# gather halves them (measured: 130 → 46 fps when staging full frames).
# Override via MDTPU_DEVICE_GATHER_FRACTION.
import os as _os

_DEVICE_GATHER_FRACTION = float(
    _os.environ.get("MDTPU_DEVICE_GATHER_FRACTION", "1.1"))

# Host-side stage-time fingerprints for cached blocks (the SDC-scrub
# reference copy, docs/RELIABILITY.md §5): per-array zlib CRCs over
# the staged host bytes, recorded into the DeviceBlockCache beside the
# entry.  ~GB/s on the host, a fraction of a ms per flagship block —
# MDTPU_INTEGRITY_FINGERPRINTS=0 opts out for hosts where even that
# matters.
_INTEGRITY_FINGERPRINTS = _os.environ.get(
    "MDTPU_INTEGRITY_FINGERPRINTS", "1") not in ("0", "false", "no")


def quantize_block(block: np.ndarray, dtype: str = "int16",
                   layout: str = "interleaved"):
    """Quantize an (B, S, 3) float32 block to ``dtype`` + inverse scale.

    One symmetric scale per block.  ``int16``: resolution = max|x| /
    32000 (e.g. 0.002 Å for a 60 Å system) — far below thermal
    fluctuation scales, bounded relative error ~6e-5 of the coordinate
    range; halves host→device wire bytes, the dominant cost when
    staging 100k-atom frames through a slow link (SURVEY.md §7).
    ``int8``: resolution = max|x| / 120 — halves the bytes AGAIN but is
    COARSE (0.5 Å on a 60 Å system): fit for wire-bound reductions on
    small-range systems (a water box: ~0.1 Å resolution, quantization
    σ ≈ 0.03 Å) and gated by the same divergence checks as every other
    staging dtype; unfit for Å-precision observables on wide systems —
    the bench's divergence gate fails loudly rather than score it.

    ``layout='planar'`` additionally repacks the quantized block to
    ``(3, B, S)`` component planes (the fused Pallas kernel's staging
    form) — the one host copy the planar path pays, on int16/int8
    bytes rather than f32, behind the staging boundary.
    """
    from mdanalysis_mpi_tpu.io.base import QUANT_TARGETS, planar_repack

    target = QUANT_TARGETS[dtype]
    m = float(np.abs(block).max()) if block.size else 1.0
    scale = target / max(m, 1e-30)
    q = np.round(block * scale).astype(dtype)
    if layout == "planar":
        q = planar_repack(q)
    return q, np.float32(1.0 / scale)


def quantize_block_delta(block: np.ndarray, n_anchors: int = 1,
                         n_valid: int | None = None):
    """Closed-loop frame-delta (DPCM) wire format (VERDICT r4 #5).

    MD frames are temporally correlated: frame t differs from frame
    t−1 by thermal displacements, a tiny fraction of the coordinate
    range.  Residuals against the RECONSTRUCTED previous frame
    therefore fit int8 *at int16-like resolution* (the shrunk range is
    the precision win), and the wire carries:

    - ``key``  (n_anchors, S, 3) int16 — one absolute keyframe per
      device shard (block-range scale, same resolution as the int16
      format);
    - ``res``  (B, S, 3) int8 — per-frame residuals, each frame with
      its OWN scale (``inv_res`` (B, 1, 1)), so one large step only
      coarsens its own frame;
    - scales ``inv_abs`` (n_anchors, 1, 1) — anchor-axis-sharded with
      the keyframes — and per-frame ``inv_res``.

    Closed-loop: residual t is computed against the receiver's
    reconstruction x̂_{t−1}, so quantization errors do NOT random-walk —
    every frame's error is bounded by its own residual step plus the
    keyframe step.  Reconstruction is one cumulative sum anchored at
    the shard's keyframe (see ``_delta_wrapper``), which is why
    ``n_anchors`` equals the device count on the mesh path: each shard
    reconstructs from its own anchor, no cross-shard dependency.

    Wire bytes/frame ≈ 3·S·(1 + 2/seg) vs int16's 6·S — a 0.5 + 1/seg
    ratio, ≤ 0.6× for anchor segments of ≥ 10 frames (the shipped
    batch geometries stage 64 frames per shard → 0.52×).  The precision envelope is the SAME contract
    as every staging dtype: the bench divergence gate and per-analysis
    parity tests decide; a decorrelated trajectory (consecutive frames
    independent — e.g. the synthetic bench fixture's per-frame
    tumbling) blows up the residual range and fails the gate LOUDLY
    rather than scoring (PERF.md §7f).  Pad rows (``n_valid`` onward)
    carry zero residuals — masked downstream, they must not widen any
    frame's scale.  The analog cost being attacked is the reference's
    3·n_atoms f64 Allreduce per block (RMSF.py:110).
    """
    b, _s, _ = block.shape
    if n_anchors < 1 or b % n_anchors or b < n_anchors:
        raise ValueError(
            f"batch of {b} frames does not split into {n_anchors} "
            "anchor segments")
    seg = b // n_anchors
    if n_valid is None:
        n_valid = b
    m = float(np.abs(block).max()) if block.size else 1.0
    scale_abs = 32000.0 / max(m, 1e-30)
    # per-anchor (A, 1, 1) rather than one scalar: sharded along the
    # anchor axis with the keyframes, each device (and, at N
    # controllers, each PROCESS) dequants its anchor with its own
    # locally-computed scale — no cross-process scale agreement needed
    inv_abs = np.full((n_anchors, 1, 1), np.float32(1.0 / scale_abs),
                      dtype=np.float32)
    key = np.round(block[::seg] * scale_abs).astype(np.int16)
    res = np.zeros(block.shape, dtype=np.int8)
    inv_res = np.ones((b, 1, 1), dtype=np.float32)
    for a in range(n_anchors):
        lo = a * seg
        if lo >= n_valid:
            break                        # whole segment is padding
        xhat = key[a].astype(np.float32) * inv_abs[a, 0, 0]
        for t in range(lo + 1, min(lo + seg, n_valid)):
            r = block[t] - xhat
            mr = float(np.abs(r).max())
            s = 120.0 / max(mr, 1e-30)
            q = np.round(r * s).astype(np.int8)
            inv = np.float32(1.0 / s)
            res[t] = q
            inv_res[t] = inv
            xhat = xhat + q.astype(np.float32) * inv
    return res, key, inv_abs, inv_res


_DELTA_WRAPPERS: dict = {}


def _delta_wrapper(fn):
    """Wrap kernel ``fn(params, batch_f32, boxes, mask)`` as
    ``g((sel, params), res_i8, key_i16, inv_abs, inv_res, boxes, mask)``:
    reconstruct the block on device — keyframe dequant + one cumulative
    sum of the scaled residuals.  Inside ``shard_map`` each device sees
    its (1, S, 3) key and (B_local, S, 3) residual shard, so the same
    expression serves single-device and mesh (anchor-per-shard) layouts.
    Cached per fn so the jit cache stays stable."""
    g = _DELTA_WRAPPERS.get(fn)
    if g is None:
        import jax.numpy as jnp

        def g(wrapped_params, res, key, inv_abs, inv_res, boxes, mask):
            sel, params = wrapped_params
            x = (key.astype(jnp.float32) * inv_abs
                 + jnp.cumsum(res.astype(jnp.float32) * inv_res, axis=0))
            if sel is not None:          # pragma: no cover - delta path
                x = x[:, sel]            # never device-gathers today
            return fn(params, x, boxes, mask)

        _DELTA_WRAPPERS[fn] = g
    return g


from mdanalysis_mpi_tpu.io.base import BlockCache  # noqa: E402


def reader_fingerprint(reader):
    """The leading element of every staged-block cache key
    (``_run_batches._key`` / ``_group_key``) — a reader's identity in
    the shared cache's key namespace.  The serving layer pins hot
    tenants' entries by this value (``BlockCache.pin``), so it must
    stay THE one definition both sides use."""
    return getattr(reader, "_path", None) or id(reader)


class DeviceBlockCache(BlockCache):
    """HBM-resident staged-block cache shared across trajectory passes.

    The reference re-reads (re-decodes) every frame in pass 2
    (RMSF.py:124); on TPU the analogous waste is re-staging the same
    (B, S, 3) blocks host→device.  Multi-pass analyses (AlignedRMSF)
    share one cache so pass 2 reads HBM-resident blocks.  Bounded by
    ``max_bytes`` (default 4 GiB ≈ a quarter of a v5e chip's HBM);
    blocks beyond the cap are simply re-staged.
    """

    def __init__(self, max_bytes: int = 4 << 30):
        super().__init__(max_bytes)
        # rotating scrub cursor: bounded scrub passes (max_entries)
        # resume where the last one stopped instead of re-verifying
        # the same head entries forever
        self._scrub_cursor = 0

    def put(self, key, value, nbytes: int) -> bool:
        """Insert, explicitly ``Array.delete()``-ing any entry this
        overwrites: a silently dropped device buffer keeps its ~1:1
        host-side client mirror pinned (PERF.md §9d), leaking host RSS
        into the fast-page window §9b diagnosed.  (The base policy
        never evicts, so overwrite — same key restaged, e.g. after a
        resilient run salvages different bytes — is the only way an
        entry leaves the store outside :meth:`drop` /
        :meth:`evict_unpinned`.)  The read-old/insert pair runs under
        the cache lock: with scheduler workers sharing one cache, two
        racing same-key puts would otherwise both snapshot the SAME
        ``old`` — one replaced buffer double-``delete()``d, the other
        silently leaked with its host mirror pinned."""
        with self._lock:
            old = self._store.get(key)
            stored = super().put(key, value, nbytes)
        if stored and old is not None:
            _delete_staged(old)
        return stored

    def drop(self) -> None:
        """Release every cached device buffer NOW (``Array.delete()``),
        not when the GC gets around to it.  On tunneled targets the
        client keeps a host-side mirror of every device buffer
        (measured ~1 host byte per device byte), so a replaced
        flagship-scale cache that lingers costs gigabytes of host RSS —
        and past the hypervisor's fast-page window (~3 GB here) every
        fresh allocation in the NEXT run pays 15-35× page-supply
        penalties.  Benchmarks re-running cold legs must drop the
        previous attempt's cache first."""
        with self._lock:
            staged_all = list(self._store.values())
            self.clear()
        for staged in staged_all:
            _delete_staged(staged)

    def evict_unpinned(self) -> list:
        """Admission-driven eviction (service layer): drop entries
        outside the pinned tenant namespaces AND release their device
        buffers + host mirrors — freed budget a queued job can then
        reserve without touching a hot tenant's superblocks."""
        evicted = super().evict_unpinned()
        for staged in evicted:
            _delete_staged(staged)
        return evicted

    def quarantine(self, key, expect) -> bool:
        """Scrub-path removal (base semantics) + device-buffer
        release: a corrupt superblock must leave HBM NOW so the
        re-stage has budget to land in."""
        removed = super().quarantine(key, expect)
        if removed:
            _delete_staged(expect)
        return removed

    def scrub(self, max_entries: int | None = None) -> dict:
        """One SDC-scrub pass (docs/RELIABILITY.md §5): re-fetch every
        fingerprinted resident entry device→host, recompute its
        per-array CRCs, and compare against the host-side fingerprint
        recorded at stage time.  A mismatch — a bit flipped on the
        host→device wire, in HBM, or in the stacked superblock — is
        QUARANTINED: the entry (and its device buffers) are dropped,
        so the next pass over those frames re-stages clean bytes from
        the source trajectory instead of serving corrupt ones forever.

        Deliberately fetch-heavy: run it on idle cycles (the
        scheduler's ``scrub=`` thread only scrubs while no worker is
        mid-run).  Fingerprints are recorded over the PROCESS-LOCAL
        staged bytes, so on a multi-host mesh the comparison re-fetches
        only this process's shard of each global array
        (:func:`~mdanalysis_mpi_tpu.parallel.distributed.
        local_host_copy`) — never another host's bytes, which a local
        fingerprint could never match.  Returns ``{"checked",
        "corrupt", "bytes"}``; outcomes land in ``mdtpu_scrub_*``
        metrics and a ``scrub_corrupt`` trace instant per quarantined
        entry.
        """
        from mdanalysis_mpi_tpu import obs
        from mdanalysis_mpi_tpu.parallel.distributed import (
            local_host_copy,
        )

        items = self.scrub_items()
        if max_entries is not None and items:
            # rotate: a bounded pass picks up where the previous one
            # stopped, so every resident entry is eventually verified
            take = min(max_entries, len(items))
            with self._lock:
                start = self._scrub_cursor % len(items)
                self._scrub_cursor = start + take
            items = (items + items)[start:start + take]
        checked = corrupt = nbytes = fetch_errors = 0
        for key, value, fp in items:
            try:
                actual = _integrity.staged_fingerprint(
                    [local_host_copy(x) for x in value])
            except Exception as exc:
                with self._lock:
                    still_stored = self._store.get(key) is value
                if not still_stored:
                    # entry overwritten/deleted mid-fetch: nothing to
                    # say about bytes that no longer exist
                    continue
                # the entry is still resident and the device refused
                # the re-fetch (device loss, collapsed link): the
                # scrubber is BLIND, and a blind protection layer
                # must say so — never report a clean pass
                fetch_errors += 1
                obs.METRICS.inc("mdtpu_scrub_fetch_errors_total")
                from mdanalysis_mpi_tpu.utils.log import get_logger

                get_logger("mdtpu").warning(
                    "scrub could not re-fetch resident block %r "
                    "(%s: %s) — SDC verification is NOT running for "
                    "this entry", self._key_ns(key),
                    type(exc).__name__, exc)
                continue
            checked += 1
            nbytes += sum(getattr(x, "nbytes", 0) for x in value)
            if tuple(actual) == tuple(fp):
                continue
            corrupt += 1
            quarantined = self.quarantine(key, value)
            obs.METRICS.inc("mdtpu_scrub_corrupt_total")
            obs.span_event("scrub_corrupt", ns=str(self._key_ns(key)),
                           quarantined=quarantined)
            from mdanalysis_mpi_tpu.utils.log import get_logger

            get_logger("mdtpu").error(
                "SDC detected: cached block %r fails its stage-time "
                "fingerprint%s — the next pass re-stages it",
                self._key_ns(key),
                "" if quarantined else " (already replaced)")
        obs.METRICS.inc("mdtpu_scrub_passes_total")
        if checked:
            obs.METRICS.inc("mdtpu_scrub_blocks_total", checked)
        return {"checked": checked, "corrupt": corrupt,
                "bytes": nbytes, "fetch_errors": fetch_errors}


class _InlinePool:
    """Degenerate 'pool' that runs submissions inline on the caller.

    On a single-core host (the measurement environment: 1 CPU feeding a
    tunneled TPU) a real prefetch thread only adds GIL/scheduler
    contention and run-to-run variance — staging, the tunnel client,
    and dispatch all want the same core.  Multi-core hosts keep the
    genuine double-buffering thread.  Override via MDTPU_PREFETCH=0/1.
    """

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def submit(self, fn, *args):
        result = fn(*args)

        class _Done:
            def result(self):
                return result

        return _Done()


def _put_staged(staged, targets):
    """Place a staged tuple on device: batch/boxes/mask go to their
    ``targets`` (devices or shardings, in staged order); the small
    host-side scale arrays stay put (they ride the dispatch — an
    explicit put would pay a tunnel round-trip for a few hundred
    bytes).  The one definition of the staged-tuple layouts shared by
    every single-controller executor."""
    import jax

    if len(staged) == 6:     # delta: (res, key, inv_abs, inv_res, boxes, mask)
        res, key, inv_abs, inv_res, boxes, mask = staged
        return (jax.device_put(res, targets[0]),
                jax.device_put(key, targets[1]), inv_abs, inv_res,
                jax.device_put(boxes, targets[2]),
                jax.device_put(mask, targets[3]))
    if len(staged) == 4:               # (q, inv_scale, boxes, mask)
        q, inv, boxes, mask = staged
        return (jax.device_put(q, targets[0]), inv,
                jax.device_put(boxes, targets[1]),
                jax.device_put(mask, targets[2]))
    return tuple(jax.device_put(x, t) for x, t in zip(staged, targets))


def _staging_pool():
    from concurrent.futures import ThreadPoolExecutor

    pref = _os.environ.get("MDTPU_PREFETCH")
    if pref is not None:
        use_thread = pref not in ("0", "false", "no")
    else:
        use_thread = (_os.cpu_count() or 1) > 1
    return ThreadPoolExecutor(max_workers=1) if use_thread else _InlinePool()


def _cold_pipeline_enabled() -> bool:
    """Whether the prestage (cold) schedule double-buffers decode
    against wire on a dedicated thread (docs/COLDSTART.md).  Same
    default policy as ``_staging_pool``: on for multi-core hosts, off
    on 1-core hosts where the transfer client and the decoder compete
    for the only core (VERDICT r3 #2 measured decode dropping ~4× —
    there the chunked decode-then-wire phase separation stays right).
    Override via MDTPU_COLD_PIPELINE=0/1."""
    pipe = _os.environ.get("MDTPU_COLD_PIPELINE")
    if pipe is not None:
        return pipe not in ("0", "false", "no")
    return (_os.cpu_count() or 1) > 1


def _run_batches(analysis, reader, frames, bs, call, sel_idx,
                 device_put_fn=None, cache: "DeviceBlockCache | None" = None,
                 quantize: bool = False, local_divisor: int = 1,
                 local_index: int = 0, inv_per_frame: bool = False,
                 prestage: bool = False, fused_call=None,
                 delta_anchors: int = 1, reliability=None,
                 scan_k: int = 1, scan_calls: "_ScanCalls | None" = None,
                 stage_only: bool = False, layout: str = "interleaved",
                 engine: str = "generic"):
    """Shared batch loop: stage → kernel → DEVICE-side accumulation.

    ``scan_k > 1`` (with ``scan_calls``) activates the SCAN-FOLDED
    schedule: blocks are grouped into runs of ``scan_k``, a group whose
    stacked superblock is HBM-resident (DeviceBlockCache hit) costs ONE
    jitted ``lax.scan`` dispatch instead of K per-block dispatches
    (partials fold on-device in the scan carry — the dispatch-overhead
    amortization of VERDICT r5 #7), and a miss group runs the per-block
    schedule then stacks its blocks on device into the cache entry the
    next run's scan consumes.  ``scan_k=1`` is byte-for-byte today's
    per-block schedule — same staging calls, same cache keys, same
    jitted programs.

    ``prestage=True`` switches the schedule from interleaved
    (stage batch i+1 while the device consumes batch i) to CHUNKED
    DECODE-THEN-WIRE (VERDICT r3 next-round #2): a chunk of batches is
    host-staged through the fused native decode→gather→quantize path
    with zero device contact, then wired with a windowed put pipeline
    and drained, then the next chunk.  On tunneled targets the
    transfer client and the decoder compete for the same host core, so
    interleaving runs the decode at a fraction of its quiet-host rate
    (measured ~4×); phase separation restores it.  Chunking bounds
    peak host residency to ``MDTPU_PRESTAGE_CHUNK`` blocks (~1 GB at
    flagship shape) — whole-trajectory prestaging drove RSS past the
    hypervisor's fast-page window and degraded its own tail (see the
    schedule comments).

    Partials never leave the device per batch: results are either folded
    on-device with the analysis' module-level ``_device_fold_fn`` (one
    jitted merge per batch, e.g. the Chan moment merge) or collected and
    concatenated on-device at the end (time-series analyses).  The
    single final pytree is what ``_conclude`` sees — it fetches what it
    needs once.  Rationale: on tunneled TPU targets (axon) every
    device→host fetch pays ~100-200 ms of fixed round-trip latency
    (measured; size-independent below ~1 MB), so per-batch fetches
    dominated the wall clock; device-side folding removes them entirely.

    Multi-host (``local_divisor`` = process count > 1): batch bounds
    stay GLOBAL — every process walks the same batch schedule (the jit
    dispatches must agree across controllers) — but each process stages
    only its own ``1/local_divisor`` slice of every batch (the
    ``local_index``-th sub-block, matching its addressable devices'
    position in the mesh), padded to the local batch size; the
    executor's ``device_put_fn`` assembles the slices into one global
    sharded array (``distributed.global_batch_from_local``).  This is
    the reference's N-independent-reader-handles pattern (RMSF.py:56)
    one level up: hosts instead of ranks, no cross-host staging traffic
    (SURVEY.md §5.8).
    """
    fold = analysis._device_fold_fn
    fold_j = _jit_kernel(fold) if fold is not None else None
    total = None
    parts_list = []
    bounds = list(iter_batches(0, len(frames), bs))
    global LAST_SCAN_K
    # stage_only (the scheduler-prefetch path, docs/COLDSTART.md):
    # walk the exact staging schedule — same cache keys, same scan
    # superblock grouping — but dispatch nothing; `call` may be None.
    scan_active = (scan_k > 1 and (scan_calls is not None or stage_only)
                   and len(bounds) > 1)
    if not stage_only:
        LAST_SCAN_K = scan_k if scan_active else 1
    # reliability runtime (reliability/policy.ReliabilityRuntime), duck-
    # called so this module never imports the policy layer: rt.op wraps
    # failure-prone ops in retry/backoff/deadline, rt.salvage_block
    # implements corrupt-frame retry → skip-with-count → abort
    rt = reliability
    validate = rt is not None and rt.policy.validate_frames

    # Cache-key namespace: a shared DeviceBlockCache must never serve
    # blocks staged for a different selection (exact content hash), a
    # different trajectory (reader path or identity), stride, batch
    # size, or transfer dtype.
    from mdanalysis_mpi_tpu.io.base import sel_fingerprint

    sel_fp = sel_fingerprint(sel_idx)
    reader_fp = reader_fingerprint(reader)
    # a reader with transformations attached stages DIFFERENT bytes for
    # the same frames; the transformation tuple (set-once) namespaces
    # the cached entries
    xform_fp = getattr(reader, "transformations", ())
    # planar staging (the fused Pallas path) caches transposed bytes a
    # generic run must never be served; the suffix exists ONLY for
    # planar so every interleaved key — scan_k=1 included — stays
    # byte-identical to the pre-planar schedule
    planar = layout == "planar"
    key_suffix = ("planar",) if planar else ()

    def _key(ab):
        a, b = ab
        # `validate` namespaces resilient-mode entries: their blocks may
        # have salvage-dropped rows (and exact per-block quantize scales)
        # a non-resilient run sharing the cache must not be served
        return (reader_fp, tuple(frames[a:b]), bs, quantize, sel_fp,
                xform_fp, delta_anchors, validate) + key_suffix

    def _host_stage(batch_frames):
        """Pure host side of one batch: read+gather (+quantize) + pad.
        No jax call anywhere on this path — the prestage schedule
        depends on that.  Returns (staged_host_tuple, resident_nbytes).
        """
        pad_to = bs
        if local_divisor > 1:
            # stage only this process's slice of the global batch
            pad_to = bs // local_divisor
            batch_frames = batch_frames[local_index * pad_to:
                                        (local_index + 1) * pad_to]
        contiguous = (len(batch_frames) > 0
                      and batch_frames[-1] - batch_frames[0] + 1
                      == len(batch_frames))
        # With a DEVICE block cache, repeat passes hit HBM — the host
        # stage cache would only duplicate every staged block in host
        # RAM, and holding gigabytes of staged blocks drives this
        # host's allocator past the hypervisor's fast-page window
        # (measured: fresh 157 MB allocations jump 65 ms → 1-3 s once
        # ~3 GB is resident).  Host-cache only when the device cache
        # cannot serve the repeats: absent, or already at its byte cap
        # (BlockCache inserts until the cap and never evicts, so blocks
        # staged once it is full would otherwise be fully re-decoded on
        # every later pass).
        if cache is None or cache.full:
            stage = getattr(reader, "stage_cached", None)
        else:
            stage = getattr(reader, "stage_block", None)
        # delta reads float32 through the fused native path and runs
        # the closed-loop DPCM quantizer here — the sequential
        # reconstruction dependency doesn't fit the codec's one-shot
        # per-block quantize
        q_inline = None if quantize == "delta" else quantize
        # corrupt-frame validation needs pre-quantize coordinates, so
        # the resilient path stages float32 and quantizes after the
        # check (the fused decode→gather fast path is kept; only its
        # in-C quantize leg is deferred)
        q_fused = None if validate else q_inline
        # planar staging rides the SAME fused read leg — the reader
        # repacks its already-quantized bytes to (3, b, S) planes
        # behind the staging boundary (io/base.planar_repack); the
        # f32 legs below stay interleaved (salvage/fault sites see the
        # layout they always did) and quantize_block repacks instead
        staged_planar = False
        if contiguous and stage is not None:
            # fused native gather(+quantize); see stage selection above
            with _spans.span("read", n_frames=len(batch_frames)):
                if planar and q_fused:
                    block, boxes, inv_scale = stage(
                        batch_frames[0], batch_frames[-1] + 1, sel_idx,
                        q_fused, layout="planar")
                    staged_planar = True
                else:
                    block, boxes, inv_scale = stage(
                        batch_frames[0], batch_frames[-1] + 1, sel_idx,
                        q_fused)
        else:
            with _spans.span("read", n_frames=len(batch_frames)):
                block, boxes = _stage(reader, batch_frames, sel_idx)
            inv_scale = None
        if _faults.plans():
            block = _faults.fire("stage", frames=batch_frames, array=block)
        n_dropped = 0
        if validate:
            block, boxes, n_dropped = rt.salvage_block(
                reader, sel_idx, batch_frames, block, boxes,
                series=fold is None)
        if q_inline and inv_scale is None:
            block, inv_scale = quantize_block(block, q_inline,
                                              layout=layout)
            staged_planar = planar
        n_frames_staged = block.shape[1 if staged_planar else 0]
        if boxes is None:
            boxes = np.zeros((n_frames_staged, 6), dtype=np.float32)
        padded, mask = pad_batch(block, pad_to,
                                 axis=1 if staged_planar else 0)
        boxes_p, _ = pad_batch(np.ascontiguousarray(boxes, np.float32),
                               pad_to)
        if quantize == "delta":
            res, dkey, inv_abs, inv_res = quantize_block_delta(
                padded, delta_anchors, n_valid=block.shape[0])
            return ((res, dkey, inv_abs, inv_res, boxes_p, mask),
                    -1 if n_dropped else res.nbytes + dkey.nbytes)
        if quantize and inv_per_frame:
            # multi-host int16: every process quantizes its own slice
            # with its own adaptive scale, so the scale travels WITH the
            # frames — a (B, 1, 1) array sharded like the batch instead
            # of one replicated scalar (the "globally-agreed scale"
            # VERDICT r2 missing #1 asked for, realized per-shard)
            inv_scale = np.full((pad_to, 1, 1), np.float32(inv_scale),
                                dtype=np.float32)
        staged = ((padded, inv_scale, boxes_p, mask) if quantize
                  else (padded, boxes_p, mask))
        # nbytes=-1 marks a salvage-shortened block UNCACHEABLE: a
        # cache hit in a later run would skip salvage and leave that
        # run's reliability report blind to the dropped frames
        return staged, -1 if n_dropped else padded.nbytes

    # stage-time integrity fingerprints (docs/RELIABILITY.md §5):
    # per-array host CRCs recorded beside each cache entry so the SDC
    # scrubber can re-fetch and compare.  On multi-host the staged
    # tuple IS this process's shard of the global batch, and the
    # scrubber compares against a local-shard re-fetch
    # (``distributed.local_host_copy``) — fleet hosts get the same
    # scrub coverage as single-host caches (the PR-9 gap).
    fingerprinting = _INTEGRITY_FINGERPRINTS and cache is not None
    # usage metering (obs/usage.py): cache residency is charged as
    # byte-seconds over the insert→pass-end window — the interval this
    # pass is responsible for; later passes that HIT the entry charge
    # nothing (a hit stages no new bytes).  Appends are GIL-atomic, so
    # prefetch/wire threads share the list without a lock.
    cache_inserts: list = []

    def _charge_cache_residency():
        if cache_inserts:
            now = _time.monotonic()
            _usage.charge_current(cache_byte_seconds=sum(
                nb * max(0.0, now - t) for nb, t in cache_inserts))
            cache_inserts.clear()

    # scan-group accumulator: gi -> (blocks_chained, per-array crcs).
    # _stack_staged stacks each leaf along a new leading axis in block
    # order, so chaining the per-block CRCs at stage time equals the
    # fetched superblock's fingerprint — no device fetch needed here.
    group_fp_acc: dict = {}

    def _place(staged, key, nbytes, bi=None):
        """Device side: transfer a host-staged tuple and cache it
        (``key=None`` — the scan-folded schedule's per-block transfers
        — skips the cache: the group's STACKED superblock is the entry,
        written by _note_block_done when the group completes).  Also
        the integrity boundary: the host-side fingerprint is computed
        HERE, before the transfer, and the ``bitflip`` SDC fault site
        fires between the two — a corrupted device copy under a clean
        fingerprint, which is exactly what the scrubber must catch."""
        fp = None
        if fingerprinting and nbytes >= 0 and not cache.full:
            # a full cache will refuse the insert (scan groups check
            # the same flag in _note_block_done): don't pay the CRC
            # for a fingerprint nothing would store
            if key is not None:
                fp = _integrity.staged_fingerprint(staged)
            elif scan_active and bi is not None:
                gi = block_group[bi]
                n, crcs = group_fp_acc.get(gi, (0, None))
                group_fp_acc[gi] = (
                    n + 1, _integrity.staged_fingerprint(staged, crcs))
        if _faults.plans():
            first = _faults.fire("bitflip", array=staged[0])
            if first is not staged[0]:
                staged = (first,) + tuple(staged[1:])

        def _put():
            if _faults.plans():
                _faults.fire("put")
            return (device_put_fn(staged) if device_put_fn is not None
                    else staged)

        staged = _put() if rt is None else rt.op("put", _put)
        if cache is not None and key is not None and nbytes >= 0:
            # charge this process's resident share of the cached entry:
            # the host block nbytes IS the per-host charge (on
            # multi-host the staged slice is already 1/local_divisor of
            # the global batch, and a global sharded array keeps exactly
            # those bytes resident per host)
            if cache.put(key, staged, nbytes):
                cache_inserts.append((nbytes, _time.monotonic()))
                if fp is not None:
                    cache.note_fingerprint(key, fp, expect=staged)
        return staged

    # trace-context hand-off: `prepare` runs on the prefetch thread,
    # and the span context (job/tenant attribution the scheduler set)
    # is thread-local — capture it on the submitting thread so staging
    # spans carry the same job ids as the dispatch spans they overlap
    trace_ctx = _spans.current_context()

    def prepare(bi):
        """Host side of one batch: read+gather (+quantize) and enqueue
        the device transfer.  Runs on the prefetch thread so the next
        batch stages while the device consumes the current one (the
        double-buffering from SURVEY.md §7 layer 5; NumPy releases the
        GIL for the big copies).  Returns (staged, nbytes); nbytes is 0
        for a cache hit (nothing new resident)."""
        ab = bounds[bi]
        a, b = ab
        key = None if scan_active else _key(ab)
        if key is not None and cache is not None:
            staged = cache.get(key)
            if staged is not None:
                return staged, 0
        with _spans.saved_context(trace_ctx), \
                TIMERS.phase("stage", lo=a, hi=b):
            staged, nbytes = _stage_op(frames[a:b])
        return _place(staged, key, nbytes, bi), nbytes

    def _stage_op(batch_frames):
        """_host_stage under the reliability retry/deadline envelope."""
        staged, nbytes = (_host_stage(batch_frames) if rt is None
                          else rt.op("stage",
                                     lambda: _host_stage(batch_frames)))
        if nbytes > 0:
            # usage charge site: freshly staged bytes (cache hits never
            # reach here; salvage-shortened blocks carry nbytes == -1)
            _usage.charge_current(staged_bytes=nbytes)
        return staged, nbytes

    def _note_fused_blocks(n: int):
        if engine == "fused":
            from mdanalysis_mpi_tpu import obs

            obs.METRICS.inc("mdtpu_fused_blocks_total", n)

    def consume(staged):
        nonlocal total
        # continuous-profiler dispatch latency (obs/prof.py): one
        # perf_counter pair per dispatch, only while sampling is on
        _pt0 = _time.perf_counter() if _prof.enabled() else None
        with TIMERS.phase("dispatch", scan_k=1, engine=engine):

            def _dispatch():
                if _faults.plans():
                    _faults.fire("kernel")
                if fold_j is None or total is None:
                    return call(*staged)
                if fused_call is not None:
                    # merge folded into the kernel dispatch (_fused_step)
                    return fused_call(total, *staged)
                return fold_j(total, call(*staged))

            out = _dispatch() if rt is None else rt.op("kernel", _dispatch)
            if fold_j is None:
                parts_list.append(out)
            else:
                total = out
        _note_fused_blocks(1)
        if _pt0 is not None:
            _prof.note_dispatch((_time.perf_counter() - _pt0) * 1e3,
                                geometry=f"bs{bs}_scan1", engine=engine)

    # ---- scan-folded dispatch bookkeeping (scan_active only) ----
    #
    # Blocks are grouped into runs of `scan_k` consecutive bounds (the
    # last group is the K ∤ n_blocks uneven tail, its own smaller scan
    # shape).  Groups are consumed strictly in schedule order — series
    # partials must concatenate in frame order and the fold order stays
    # deterministic — by flushing pending HIT groups before each miss
    # block and once more after the loop.
    if scan_active:
        groups = [list(range(lo, min(lo + scan_k, len(bounds))))
                  for lo in range(0, len(bounds), scan_k)]

        def _group_key(g):
            a, b = bounds[g[0]][0], bounds[g[-1]][1]
            # same namespace fields as the per-block key, plus the
            # group length: a scan superblock must never be served to a
            # differently-grouped schedule (or to the per-block one)
            return (reader_fp, tuple(frames[a:b]), bs, quantize, sel_fp,
                    xform_fp, delta_anchors, validate, "scan",
                    len(g)) + key_suffix

        group_keys = [_group_key(g) for g in groups]
        group_hits = [cache.get(k) if cache is not None else None
                      for k in group_keys]
        block_group = {bi: gi for gi, g in enumerate(groups) for bi in g}
        miss_blocks = [bi for gi, g in enumerate(groups)
                       if group_hits[gi] is None for bi in g]
        pending: dict[int, list] = {}
        next_group = 0

        def consume_scan(stacked):
            """ONE dispatch for a whole HBM-resident K-block group."""
            nonlocal total
            n_blocks = int(stacked[0].shape[0])
            _pt0 = _time.perf_counter() if _prof.enabled() else None
            # span tag: this single dispatch covers a K-block scan
            # group (the dispatch-count shrink docs/DISPATCH.md claims)
            with TIMERS.phase("dispatch", scan_k=scan_k,
                              blocks=n_blocks, engine=engine):

                def _dispatch():
                    if _faults.plans():
                        _faults.fire("kernel")
                    if fold_j is None:
                        return scan_calls.series(*stacked)
                    if total is None:
                        return scan_calls.init(*stacked)
                    return scan_calls.fused(total, *stacked)

                out = (_dispatch() if rt is None
                       else rt.op("kernel", _dispatch))
                if fold_j is None:
                    parts_list.append(out)
                else:
                    total = out
            _note_fused_blocks(n_blocks)
            if _pt0 is not None:
                # program geometry = batch size × scan group length
                # (the jitted scan shape — the uneven tail group is
                # its own program and labels itself)
                _prof.note_dispatch(
                    (_time.perf_counter() - _pt0) * 1e3,
                    geometry=f"bs{bs}_scan{n_blocks}", engine=engine)

        def _flush_hits_before(gi_limit):
            """Consume, in order, every not-yet-consumed HIT group that
            precedes ``gi_limit`` (miss groups advance the cursor in
            _note_block_done, so everything walked here is a hit)."""
            nonlocal next_group
            while next_group < gi_limit:
                consume_scan(group_hits[next_group])
                next_group += 1

        def _note_block_done(bi, staged, nbytes):
            """Per-block-consumed miss bookkeeping: when the group
            completes, stack its blocks on device into the cache
            superblock (what the next run's scan dispatches over) and
            explicitly release the per-block device buffers — silently
            dropped buffers keep their host-side client mirrors pinned
            (PERF.md §9d)."""
            nonlocal next_group
            gi = block_group[bi]
            pending.setdefault(gi, []).append((staged, nbytes))
            if bi != groups[gi][-1]:
                return
            blocks = pending.pop(gi)
            next_group = gi + 1
            # the chained stage-time fingerprint (see _place): only
            # trustworthy when every block of the group contributed
            fp_n, fp = group_fp_acc.pop(gi, (0, None))
            # nbytes < 0 marks a salvage-shortened block: uncacheable,
            # same rule as the per-block schedule
            if (cache is not None and not cache.full
                    and all(nb >= 0 for _, nb in blocks)):
                stacked = _stack_staged([s for s, _ in blocks])
                group_nb = sum(nb for _, nb in blocks)
                if not cache.put(group_keys[gi], stacked, group_nb):
                    _delete_staged(stacked)   # rejected: don't leak HBM
                else:
                    cache_inserts.append((group_nb, _time.monotonic()))
                    if fp is not None and fp_n == len(blocks):
                        cache.note_fingerprint(group_keys[gi], fp,
                                               expect=stacked)
            for s, _ in blocks:
                _delete_staged(s)

    if stage_only:
        staged_blocks = 0
        seq = miss_blocks if scan_active else list(range(len(bounds)))
        for bi in seq:
            staged, nbytes = prepare(bi)
            if nbytes:
                staged_blocks += 1
            if scan_active:
                _note_block_done(bi, staged, nbytes)
        _charge_cache_residency()
        return staged_blocks

    if prestage and _cold_pipeline_enabled():
        # DOUBLE-BUFFERED decode→wire (PERF.md §12): §8d measured the
        # WIRE leg, not decode, as the cold wall once the link slowed
        # (3 GB staged ≈ 46 s of a ~70 s cold wall at 0.8 GB/s), so
        # strict decode-then-wire phase separation leaves the link
        # idle while the decoder runs.  Here the wire of block i runs
        # on a dedicated "mdtpu-wire" thread while block i+1 decodes
        # on this one — decode hides entirely under the wire wall, and
        # the overlap is VISIBLE as stage-vs-wire spans on distinct
        # threads in the span trace (docs/COLDSTART.md shows the
        # timeline).  Host residency stays bounded: at most
        # MDTPU_WIRE_WINDOW blocks are decoded-but-unconsumed, the
        # same constraint the chunk bound enforced.  1-core hosts keep
        # the chunked schedule below (see _cold_pipeline_enabled).
        from concurrent.futures import ThreadPoolExecutor

        window = max(1, int(_os.environ.get("MDTPU_WIRE_WINDOW", "4")))
        seq = miss_blocks if scan_active else list(range(len(bounds)))
        wire_ctx = _spans.current_context()

        def _wire(staged_host, key, nbytes, bi):
            # span context handed to the wire thread so wire spans
            # carry the same job attribution as the stage spans they
            # overlap (the PR-5 prefetch-thread contract)
            with _spans.saved_context(wire_ctx), TIMERS.phase("wire"):
                return _place(staged_host, key, nbytes, bi)

        with ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="mdtpu-wire") as wpool:
            futs: dict[int, tuple] = {}
            nxt = 0
            for i in range(len(seq)):
                while nxt < len(seq) and nxt - i < window:
                    ab = bounds[seq[nxt]]
                    key = None if scan_active else _key(ab)
                    hit = (cache.get(key)
                           if key is not None and cache is not None
                           else None)
                    if hit is not None:
                        futs[nxt] = (None, hit, 0)
                    else:
                        a, b = ab
                        with TIMERS.phase("stage", lo=a, hi=b):
                            sh, nb = _stage_op(frames[a:b])
                        futs[nxt] = (wpool.submit(_wire, sh, key, nb,
                                                  seq[nxt]),
                                     None, nb)
                    nxt += 1
                fut, hit, nbytes = futs.pop(i)
                staged = hit if fut is None else fut.result()
                bi = seq[i]
                if scan_active:
                    _flush_hits_before(block_group[bi])
                consume(staged)
                if scan_active:
                    _note_block_done(bi, staged, nbytes)
        if scan_active:
            _flush_hits_before(len(groups))
    elif prestage:
        # CHUNKED decode-then-wire (two measured constraints):
        #
        # 1. Phase separation (VERDICT r3 #2): while the native decoder
        #    runs, the transfer client must be idle — on 1-core hosts
        #    they compete for the same core and decode drops ~4x.
        # 2. Bounded residency: the hypervisor supplies fresh pages
        #    fast only up to a working-set threshold (measured ~3 GB on
        #    this target: 157 MB allocations jump 65 ms → 1-3 s past
        #    it), so staging the WHOLE trajectory before any wire —
        #    round 4's schedule — degrades its own tail blocks.
        #
        # So: stage a CHUNK of batches with zero device contact, then
        # wire that chunk with a windowed put pipeline (several
        # transfers in flight — measured 1.35-4x over strict
        # put→dispatch alternation), drain, drop the chunk's host
        # blocks, repeat.  Peak host residency ≈ chunk × block bytes.
        window = max(1, int(_os.environ.get("MDTPU_WIRE_WINDOW", "4")))
        chunk = max(window,
                    int(_os.environ.get("MDTPU_PRESTAGE_CHUNK", "6")))
        # scan-folded runs stage only the blocks of MISS groups (hit
        # groups are whole HBM-resident superblocks: nothing to decode
        # or wire, one scan dispatch each, interleaved in order below)
        seq = miss_blocks if scan_active else list(range(len(bounds)))
        for clo in range(0, len(seq), chunk):
            items: list = []
            for bi in seq[clo:clo + chunk]:
                ab = bounds[bi]
                key = None if scan_active else _key(ab)
                hit = (cache.get(key)
                       if key is not None and cache is not None else None)
                if hit is not None:
                    items.append((None, hit, key, 0, bi))
                    continue
                a, b = ab
                with TIMERS.phase("stage", lo=a, hi=b):
                    staged_host, nbytes = _stage_op(frames[a:b])
                items.append((staged_host, None, key, nbytes, bi))
            placed: dict[int, tuple] = {}
            nxt = 0
            last_placed = None
            for i in range(len(items)):
                while nxt < len(items) and nxt - i < window:
                    staged_host, staged, key, nbytes, bi = items[nxt]
                    if staged is None:
                        with TIMERS.phase("wire"):
                            staged = _place(staged_host, key, nbytes,
                                            bi)
                        last_placed = staged
                    placed[nxt] = (staged, nbytes)
                    items[nxt] = None
                    nxt += 1
                staged, nbytes = placed.pop(i)
                bi = seq[clo + i]
                if scan_active:
                    _flush_hits_before(block_group[bi])
                consume(staged)
                if scan_active:
                    _note_block_done(bi, staged, nbytes)
            if last_placed is not None and clo + chunk < len(seq):
                # chunk barrier: drain in-flight transfers before the
                # next chunk's decode starts (constraint 1) and let the
                # chunk's host blocks free (constraint 2)
                import jax

                with TIMERS.phase("wire"):
                    if scan_active:
                        # the chunk's per-block buffers may already be
                        # Array.delete()d (a scan group completed on
                        # the chunk's last block) — block on the latest
                        # consume output instead, which transitively
                        # drains every transfer that fed it
                        tgt = (total if fold_j is not None
                               else (parts_list[-1] if parts_list
                                     else None))
                        if tgt is not None:
                            jax.block_until_ready(tgt)
                    else:
                        jax.block_until_ready(last_placed)
        if scan_active:
            _flush_hits_before(len(groups))
    else:
        seq = miss_blocks if scan_active else list(range(len(bounds)))
        with _staging_pool() as pool:
            fut = pool.submit(prepare, seq[0]) if seq else None
            for j, bi in enumerate(seq):
                staged, nbytes = fut.result()
                if j + 1 < len(seq):
                    fut = pool.submit(prepare, seq[j + 1])
                if scan_active:
                    _flush_hits_before(block_group[bi])
                consume(staged)
                if scan_active:
                    _note_block_done(bi, staged, nbytes)
        if scan_active:
            _flush_hits_before(len(groups))
    _charge_cache_residency()
    if fold is not None:
        if fold_j is not None and total is not None:
            import jax

            # block here so "execute" cleanly separates device time from
            # the _conclude fetch in the phase report
            with TIMERS.phase("device_wait"):
                jax.block_until_ready(total)
        return total if total is not None else analysis._identity_partials()
    if not parts_list:
        return analysis._identity_partials()
    if len(parts_list) == 1:
        return parts_list[0]
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts_list)


class SerialExecutor:
    """Frame-at-a-time host loop (the reference's per-rank body,
    RMSF.py:91-103/123-138, minus MPI)."""

    name = "serial"
    # execute() accumulates inside the analysis and returns the full
    # summary — NOT per-call partials (utils/checkpoint.py refuses it)
    per_call_partials = False
    reliability = None

    def __init__(self, reliability=None):
        if reliability is not None:
            self.reliability = reliability

    def execute(self, analysis, reader, frames, batch_size=None):
        rt = self.reliability
        if rt is None:
            for i in frames:
                analysis._single_frame(reader[i])
        else:
            processed = []
            for i in frames:
                # validated read: retry transient/corrupt reads, then
                # skip-with-count or abort per policy (None = skipped)
                ts = rt.read_frame(reader, i)
                if ts is not None:
                    analysis._single_frame(ts)
                    processed.append(i)
            if len(processed) != len(frames):
                # shrink the resolved frame list to what actually ran:
                # _conclude builds per-frame columns (results.frames,
                # time axes) from _frame_indices, and a dropped frame
                # must not leave a full-length column misaligned
                # against the shortened per-frame outputs
                analysis._frame_indices = processed
                analysis.n_frames = len(processed)
        return analysis._serial_summary()


class JaxExecutor:
    """Single-device batch pipeline: stage block → jitted kernel →
    ON-DEVICE f32 fold across blocks (fused into the kernel dispatch
    when the analysis declares a ``_device_fold_fn``; see the module
    precision-policy docstring)."""

    name = "jax"
    # execute() returns one partials pytree per call — checkpointable
    per_call_partials = True
    reliability = None

    def __init__(self, batch_size: int = 128, device=None,
                 block_cache: DeviceBlockCache | None = None,
                 transfer_dtype: str = "float32",
                 prestage: bool = False, reliability=None,
                 scan_k: "int | str | None" = None,
                 use_quantized_native: bool = True):
        _validate_transfer_dtype(transfer_dtype)
        self.batch_size = batch_size
        self.device = device
        self.block_cache = block_cache
        self.transfer_dtype = transfer_dtype
        # decode-then-wire cold schedule (see _run_batches); holds the
        # staged trajectory in host RAM for the length of the run
        self.prestage = prestage
        # scan-folded dispatch group size: int, "auto" or None (None
        # defers to env MDTPU_SCAN_K, default auto — docs/DISPATCH.md)
        self.scan_k = scan_k
        # False pins the generic dequant+align schedule even when the
        # analysis registers a fused quantized-native program — the
        # degradation chain's fused → generic rung (reliability/
        # policy.py degradation_chain)
        self.use_quantized_native = use_quantized_native
        if reliability is not None:
            self.reliability = reliability

    def _setup(self, analysis, reader):
        """Kernel/params/selection resolution shared by ``execute``,
        ``warmup`` and ``stage`` — one site, so the three paths cannot
        disagree about what gets staged or dispatched.  The trailing
        (layout, engine) pair carries the quantized-native kernel's
        staging form ('planar' for the fused Pallas path — the kernel
        declares it via ``staging_layout``) and the dispatch engine
        label ('fused' whenever a quantized-native program replaces
        the generic dequant wrapper)."""
        quantize = _quant_mode(self.transfer_dtype)
        qn = (_quantized_native(analysis, self.transfer_dtype)
              if self.use_quantized_native else None)
        if qn is not None:
            wrapped, params, sel_idx = qn
            base_fn = wrapped
            layout = getattr(wrapped, "staging_layout", "interleaved")
            engine = "fused"
        else:
            base_fn = analysis._batch_fn()
            if self.transfer_dtype == "delta":
                wrapped = _delta_wrapper(base_fn)
            elif quantize:
                wrapped = _dequant_wrapper(base_fn)
            else:
                wrapped = base_fn
            params, sel_idx = _wrap_for_transfer(
                analysis._batch_params(), analysis._batch_select(),
                reader.n_atoms, self.transfer_dtype)
            layout, engine = "interleaved", "generic"
        return wrapped, base_fn, params, sel_idx, quantize, layout, engine

    def _scan_group_sizes(self, scan_k: int, n_blocks: int):
        """(init_sizes, fused_sizes): the distinct stacked-group shapes
        ``_run_batches``'s scan schedule will actually dispatch —
        exactly what warmup must compile, nothing more."""
        if scan_k <= 1 or n_blocks <= 1:
            return set(), set()
        n_groups = -(-n_blocks // scan_k)
        tail = n_blocks % scan_k
        init_sizes = {scan_k}          # group 0 is always a full group
        fused_sizes = set()
        if n_groups > 2 or (n_groups == 2 and not tail):
            fused_sizes.add(scan_k)
        if tail and n_groups > 1:
            fused_sizes.add(tail)
        return init_sizes, fused_sizes

    def warmup(self, analysis, reader, frames, batch_size=None) -> int:
        """AOT-compile every program ``execute`` would dispatch for
        this exact geometry — ``jit(...).lower().compile()`` keyed by
        (op, shape, dtype, backend, scan_k) — so the first real
        dispatch skips tracing AND compilation (docs/COLDSTART.md).
        The caller must have resolved frames and run
        ``analysis._prepare()`` (AnalysisBase.run's own order).
        Returns the number of executables registered."""
        import jax

        if getattr(analysis, "_mesh_only", False):
            return 0
        bs = batch_size or self.batch_size
        try:
            (wrapped, base_fn, params, sel_idx, quantize, layout,
             _engine) = self._setup(analysis, reader)
        except NotImplementedError:
            return 0          # serial-only analysis: nothing to compile
        kernel = _jit_kernel(wrapped)
        fold = analysis._device_fold_fn
        frames = list(frames)
        n_blocks = -(-len(frames) // bs) if frames else 0
        if n_blocks == 0:
            return 0
        n_stage = reader.n_atoms if sel_idx is None else len(sel_idx)
        avals = _staged_avals(bs, n_stage, quantize, layout=layout)
        td = self.transfer_dtype
        n = 0
        if _cc.aot_compile(_op_label(base_fn, td, "jax", "kernel"),
                           kernel, params, *avals) is not None:
            n += 1
        total_aval = (jax.eval_shape(kernel, params, *avals)
                      if fold is not None else None)
        if fold is not None and n_blocks > 1:
            step = _fused_step(wrapped, fold)
            if _cc.aot_compile(_op_label(base_fn, td, "jax", "fused"),
                               step, total_aval, params,
                               *avals) is not None:
                n += 1
        scan_k = _resolve_scan_k(
            self.scan_k, self.block_cache, n_blocks,
            _block_nbytes(bs, sel_idx, reader.n_atoms, td))
        init_sizes, fused_sizes = self._scan_group_sizes(scan_k, n_blocks)
        s_init, s_fused, s_series = (
            _scan_fns(wrapped, fold) if init_sizes else (None,) * 3)
        for k in sorted(init_sizes | fused_sizes):
            st = _stacked_avals(avals, k)
            if fold is not None:
                if k in init_sizes and _cc.aot_compile(
                        _op_label(base_fn, td, "jax", "scan_init"),
                        s_init, params, *st, scan_k=k) is not None:
                    n += 1
                if k in fused_sizes and _cc.aot_compile(
                        _op_label(base_fn, td, "jax", "scan_fused"),
                        s_fused, total_aval, params, *st,
                        scan_k=k) is not None:
                    n += 1
            elif _cc.aot_compile(
                    _op_label(base_fn, td, "jax", "scan_series"),
                    s_series, params, *st, scan_k=k) is not None:
                n += 1
        return n

    def stage(self, analysis, reader, frames, batch_size=None) -> int:
        """Stage this run's blocks into ``block_cache`` WITHOUT
        dispatching any kernel — the scheduler-prefetch entry point
        (docs/COLDSTART.md).  Walks the exact schedule ``execute``
        would (same cache keys, same scan grouping), so a later run
        over the same window is all cache hits.  Returns the number of
        blocks staged (0 when there is no cache to stage into)."""
        if self.block_cache is None or getattr(analysis, "_mesh_only",
                                               False):
            return 0
        bs = batch_size or self.batch_size
        try:
            (_w, _b, _params, sel_idx, quantize, layout,
             engine) = self._setup(analysis, reader)
        except NotImplementedError:
            return 0          # serial-only analysis: nothing to stage
        frames = list(frames)
        scan_k = _resolve_scan_k(
            self.scan_k, self.block_cache,
            -(-len(frames) // bs) if frames else 0,
            _block_nbytes(bs, sel_idx, reader.n_atoms,
                          self.transfer_dtype))

        def put(staged):
            return _put_staged(staged, (self.device,) * 4)

        return _run_batches(
            analysis, reader, frames, bs, None, sel_idx,
            device_put_fn=put, cache=self.block_cache, quantize=quantize,
            reliability=self.reliability, scan_k=scan_k,
            stage_only=True, layout=layout, engine=engine)

    def execute(self, analysis, reader, frames, batch_size=None):
        import jax

        if getattr(analysis, "_mesh_only", False):
            raise ValueError(
                f"{type(analysis).__name__} uses an atom-sharded ring "
                "kernel (mesh collectives); run it with backend='mesh'")
        bs = batch_size or self.batch_size
        (wrapped, base_fn, params, sel_idx, quantize, layout,
         engine) = self._setup(analysis, reader)
        kernel = _jit_kernel(wrapped)
        fold = analysis._device_fold_fn
        step = _fused_step(wrapped, fold) if fold is not None else None
        frames = list(frames)

        scan_k = _resolve_scan_k(
            self.scan_k, self.block_cache,
            -(-len(frames) // bs) if frames else 0,
            _block_nbytes(bs, sel_idx, reader.n_atoms,
                          self.transfer_dtype))
        scan_calls = (None if scan_k <= 1
                      else _make_scan_calls(_scan_fns(wrapped, fold),
                                            params))

        call = lambda *staged: kernel(params, *staged)  # noqa: E731
        fused_call = (None if step is None else
                      lambda total, *staged: step(total, params, *staged))
        # AOT-executable binding: when warmup registered executables
        # for this exact geometry, dispatch through them — zero
        # tracing, zero compile on first contact.  Keys derive from
        # the same _staged_avals the warmup used, so a bound
        # executable's shapes are correct by construction.  Skipped
        # for an explicit non-default device (the executable's input
        # placement would not match).
        if self.device is None and _cc.aot_active():
            td = self.transfer_dtype
            n_stage = (reader.n_atoms if sel_idx is None
                       else len(sel_idx))
            avals = _staged_avals(bs, n_stage, quantize, layout=layout)
            bound = False
            comp_k = _cc.aot_get(_cc.aot_key(
                _op_label(base_fn, td, "jax", "kernel"),
                (params,) + avals))
            if comp_k is not None:
                call = lambda *staged: comp_k(params, *staged)  # noqa: E731
                bound = True
            if fold is not None:
                total_aval = jax.eval_shape(kernel, params, *avals)
                comp_f = _cc.aot_get(_cc.aot_key(
                    _op_label(base_fn, td, "jax", "fused"),
                    (total_aval, params) + avals))
                if comp_f is not None:
                    fused_call = (lambda total, *staged:
                                  comp_f(total, params, *staged))
                    bound = True
            if scan_calls is not None:
                comp_scan = {}
                init_sizes, fused_sizes = self._scan_group_sizes(
                    scan_k, -(-len(frames) // bs))
                for k in init_sizes | fused_sizes:
                    st = _stacked_avals(avals, k)
                    if fold is not None:
                        comp_scan[("scan_init", k)] = _cc.aot_get(
                            _cc.aot_key(_op_label(base_fn, td, "jax",
                                                  "scan_init"),
                                        (params,) + st, scan_k=k))
                        comp_scan[("scan_fused", k)] = _cc.aot_get(
                            _cc.aot_key(_op_label(base_fn, td, "jax",
                                                  "scan_fused"),
                                        (total_aval, params) + st,
                                        scan_k=k))
                    else:
                        comp_scan[("scan_series", k)] = _cc.aot_get(
                            _cc.aot_key(_op_label(base_fn, td, "jax",
                                                  "scan_series"),
                                        (params,) + st, scan_k=k))
                if any(v is not None for v in comp_scan.values()):
                    scan_calls = self._bind_aot_scan(
                        scan_calls, comp_scan, params, fold)
                    bound = True
            if bound:
                _cc.note_aot_dispatch()

        def put(staged):
            return _put_staged(staged, (self.device,) * 4)

        return _run_batches(
            analysis, reader, frames, bs, call, sel_idx,
            device_put_fn=put, cache=self.block_cache, quantize=quantize,
            prestage=self.prestage, reliability=self.reliability,
            scan_k=scan_k, scan_calls=scan_calls,
            fused_call=fused_call, layout=layout, engine=engine)

    @staticmethod
    def _bind_aot_scan(scan_calls: "_ScanCalls", comp_scan: dict,
                       params, fold) -> "_ScanCalls":
        """Rebind the scan-program triple so each dispatch picks the
        AOT executable matching its stacked group size, falling back
        to the jitted program for unwarmed sizes."""
        jit_init, jit_fused = scan_calls.init, scan_calls.fused
        jit_series = scan_calls.series
        if fold is not None:
            def init(*st):
                c = comp_scan.get(("scan_init", st[0].shape[0]))
                return (c(params, *st) if c is not None
                        else jit_init(*st))

            def fused(total, *st):
                c = comp_scan.get(("scan_fused", st[0].shape[0]))
                return (c(total, params, *st) if c is not None
                        else jit_fused(total, *st))

            return _ScanCalls(init=init, fused=fused)

        def series(*st):
            c = comp_scan.get(("scan_series", st[0].shape[0]))
            return c(params, *st) if c is not None else jit_series(*st)

        return _ScanCalls(series=series)


class MeshExecutor:
    """Data-parallel mesh pipeline.

    Frames are sharded over the ``data`` mesh axis; each device runs the
    batch kernel on its shard and the cross-device merge happens on-chip
    via the analysis' ``_device_combine`` (psum over ICI).  This is the
    TPU-native image of the reference's SPMD ranks + collectives
    (SURVEY.md §2.3 "DP over frames").
    """

    name = "mesh"
    # execute() returns one partials pytree per call — checkpointable
    per_call_partials = True
    reliability = None

    def __init__(self, batch_size: int = 64, devices=None,
                 axis_name: str = "data",
                 block_cache: DeviceBlockCache | None = None,
                 transfer_dtype: str = "float32",
                 prestage: bool = False, reliability=None,
                 scan_k: "int | str | None" = None):
        _validate_transfer_dtype(transfer_dtype)
        self.batch_size = batch_size
        self.devices = devices
        self.axis_name = axis_name
        self.block_cache = block_cache
        self.transfer_dtype = transfer_dtype
        # decode-then-wire cold schedule (see _run_batches)
        self.prestage = prestage
        # scan-folded dispatch group size (docs/DISPATCH.md); the scan
        # wraps INSIDE shard_map so the psum merge runs once per scan
        self.scan_k = scan_k
        if reliability is not None:
            self.reliability = reliability

    def _build(self, analysis, qn_fn=None):
        """``qn_fn``: the quantized-native kernel resolved ONCE by
        ``execute`` (same `custom is None` guard) — _build must not call
        ``_quantized_batch`` again, both to avoid a second discarded
        jitted ``build_params`` dispatch and to keep the kernel/params
        decision in one place."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        shard_map = _shard_map()

        devices = self.devices if self.devices is not None else jax.devices()
        quantize = _quant_mode(self.transfer_dtype) is not None
        delta = self.transfer_dtype == "delta"
        custom = analysis._batch_specs(self.axis_name)
        if custom is not None and quantize:
            raise ValueError(
                "atom-sharded (ring) kernels support transfer_dtype="
                "'float32' only")
        if qn_fn is not None:
            f = qn_fn
        else:
            f = analysis._batch_fn()
            if delta:
                f = _delta_wrapper(f)
            elif quantize:
                f = _dequant_wrapper(f)
        devcombine = analysis._device_combine
        if custom is not None and devcombine is None:
            raise ValueError(
                "atom-sharded kernels need a _device_combine psum merge")
        n_proc = jax.process_count()
        # multi-controller variations (both no-ops single-host):
        # - time-series analyses (no psum merge) all_gather their
        #   per-shard series so the output is replicated — every
        #   controller can fetch it in _conclude (out_specs=P(axis)
        #   would span non-addressable devices)
        # - int16 staging ships a per-frame (B,1,1) inv_scale sharded
        #   with the batch instead of one replicated scalar
        series_gather = devcombine is None and n_proc > 1
        inv_sharded = quantize and n_proc > 1
        fold = analysis._device_fold_fn
        key = (f, devcombine, fold, tuple(devices), self.axis_name,
               series_gather, inv_sharded)
        cached = _MESH_CACHE.get(key)
        if cached is not None:
            return cached

        mesh = Mesh(np.asarray(devices), (self.axis_name,))
        kernel = _f32_precision(f)
        axis = self.axis_name

        def shard_fn(params, *staged):
            partials = kernel(params, *staged)
            if devcombine is not None:
                return devcombine(partials, axis)
            if series_gather:
                return jax.tree.map(
                    lambda x: jax.lax.all_gather(x, axis, axis=0,
                                                 tiled=True), partials)
            return partials

        if custom is not None:
            # atom-sharded: analysis declares every spec; frames are NOT
            # sharded (each device sees the full batch, its atom block)
            params_spec, batch_spec, boxes_spec, mask_spec = custom
            in_specs = (params_spec, batch_spec, boxes_spec, mask_spec)
            out_specs = P()
            put_specs = (batch_spec, boxes_spec, mask_spec)
            frames_per_batch_factor = 1
        else:
            out_specs = (P() if (devcombine is not None or series_gather)
                         else P(axis))
            # staged is (batch, boxes, mask) or (batch_i16, inv_scale,
            # boxes, mask); the inv_scale is a replicated scalar
            # single-host, a (B, 1, 1) frame-sharded array multi-host
            inv_spec = P(axis) if inv_sharded else P()
            if delta:
                # (res, key, inv_abs, inv_res, boxes, mask): residuals
                # and per-frame scales shard with the frames; the
                # keyframe array has one anchor PER DEVICE on axis 0 —
                # and its (A, 1, 1) inv_abs shards WITH it — so each
                # shard reconstructs from its own absolute anchor at
                # its own locally-computed scale (no cross-shard cumsum
                # dependency, no cross-process scale agreement)
                in_specs = (P(), P(axis), P(axis), P(axis), P(axis),
                            P(axis), P(axis))
                put_specs = (P(axis), P(axis), P(axis), P(axis))
            elif quantize:
                in_specs = (P(), P(axis), inv_spec, P(axis), P(axis))
                put_specs = (P(axis), P(axis), P(axis))
            else:
                in_specs = (P(), P(axis), P(axis), P(axis))
                put_specs = (P(axis), P(axis), P(axis))
            frames_per_batch_factor = len(devices)
        # replication/vma checking stays off (bound inside _shard_map):
        # jnp.linalg.svd lowers to an iterative scan on TPU whose bool
        # carry trips the varying-manual-axes check inside shard_map
        # (works on CPU, fails on TPU); the kernel is purely per-shard
        # + explicit psum, so the check adds nothing here.
        gfn = _cc.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs))
        # fused cross-batch fold (same dispatch-halving as the
        # single-device path, _fused_step): the replicated running total
        # rides into the shard_map as a P() input and the fold applies
        # right after the psum merge — one dispatch per batch
        gfn_fused = None
        if custom is None and devcombine is not None and fold is not None:
            def shard_fn_fused(total, params, *staged):
                # reuse shard_fn so the merge semantics cannot diverge
                # between batch 1 (gfn) and batches 2+ (gfn_fused)
                return fold(total, shard_fn(params, *staged))

            gfn_fused = _cc.jit(shard_map(
                shard_fn_fused, mesh=mesh,
                in_specs=(P(),) + in_specs,
                out_specs=P()))
        shardings = tuple(NamedSharding(mesh, s) for s in put_specs)
        result = (frames_per_batch_factor, gfn, shardings,
                  custom[0] if custom is not None else None, gfn_fused)
        _MESH_CACHE[key] = result
        return result

    def _build_scan(self, analysis, qn_fn=None):
        """Scan-group programs for the single-controller mesh path.

        The per-shard ``lax.scan`` accumulates LOCAL partials across
        the group's K blocks (fold is associative/commutative — the
        same algebra that lets the reference merge per-rank summaries
        in any order, RMSF.py:36-41) and the ``_device_combine`` psum
        merge runs ONCE per scan, not once per block: a K-group costs
        K× fewer ICI collectives and K× fewer host dispatches.  Series
        analyses scan with stacked per-step outputs (frame axis stays
        sharded through ``out_specs=P(None, axis)``) and flatten to
        frame order inside the same jit.  Cached in _MESH_CACHE under
        the same module-level-identity contract as ``_build``."""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        shard_map = _shard_map()
        devices = (self.devices if self.devices is not None
                   else jax.devices())
        delta = self.transfer_dtype == "delta"
        quantize = _quant_mode(self.transfer_dtype) is not None
        if qn_fn is not None:
            f = qn_fn
        else:
            f = analysis._batch_fn()
            if delta:
                f = _delta_wrapper(f)
            elif quantize:
                f = _dequant_wrapper(f)
        devcombine = analysis._device_combine
        fold = analysis._device_fold_fn
        key = (f, devcombine, fold, tuple(devices), self.axis_name,
               "scan")
        cached = _MESH_CACHE.get(key)
        if cached is None:
            mesh = Mesh(np.asarray(devices), (self.axis_name,))
            kernel = _f32_precision(f)
            axis = self.axis_name
            if delta:
                # stacked (res, key, inv_abs, inv_res, boxes, mask):
                # every element gains a leading UNSHARDED block axis;
                # frames/anchors stay sharded on their own axis
                staged_specs = (P(None, axis),) * 6
            elif quantize:
                # stacked (q, inv, boxes, mask): the (K,) per-block
                # scale array is replicated (single-controller — the
                # scan path never runs multi-host, see execute)
                staged_specs = (P(None, axis), P(), P(None, axis),
                                P(None, axis))
            else:
                staged_specs = (P(None, axis),) * 3

            if fold is not None:
                # per-shard body is the SHARED _scan_accum — local
                # partials across the group, then ONE devcombine psum
                def shard_init(params, *stacked):
                    return devcombine(
                        _scan_accum(kernel, fold, params, stacked), axis)

                def shard_fused(total, params, *stacked):
                    return fold(total, devcombine(
                        _scan_accum(kernel, fold, params, stacked),
                        axis))

                cached = (
                    _cc.jit(shard_map(shard_init, mesh=mesh,
                                      in_specs=(P(),) + staged_specs,
                                      out_specs=P())),
                    _cc.jit(shard_map(shard_fused, mesh=mesh,
                                      in_specs=(P(), P()) + staged_specs,
                                      out_specs=P())),
                    None)
            else:
                def shard_series(params, *stacked):
                    return _scan_emit(kernel, params, stacked)

                inner = shard_map(shard_series, mesh=mesh,
                                  in_specs=(P(),) + staged_specs,
                                  out_specs=P(None, axis))

                def series_fn(params, *stacked):
                    # (K, B_global, ...) → (K·B_global, ...): the
                    # per-block concatenation order, inside the jit
                    return _flatten_block_axis(inner(params, *stacked))

                cached = (None, None, _cc.jit(series_fn))
            _MESH_CACHE[key] = cached
        s_init, s_fused, s_series = cached
        return s_init, s_fused, s_series

    def warmup(self, analysis, reader, frames, batch_size=None) -> int:
        """Precompile the mesh programs for this geometry: the jitted
        shard_map kernel (and fused fold form) is lowered + compiled
        with the dispatch-time input shardings, populating the
        persistent compile cache so a fresh process's first mesh
        dispatch is a disk deserialization, not an XLA compile
        (docs/COLDSTART.md).  The mesh path keeps jit dispatch (no
        AOT-executable binding — sharded executables are bound at the
        jit layer), and the scan-group programs compile lazily; both
        still hit tier 1 once any process has run them.
        Single-controller, frame-sharded kernels only."""
        import jax

        if jax.process_count() > 1:
            return 0
        bs = batch_size or self.batch_size
        try:
            qn = (_mesh_quantized_native(analysis, self.transfer_dtype)
                  if analysis._batch_specs(self.axis_name) is None
                  else None)
            bs_factor, gfn, shardings, params_specs, gfn_fused = \
                self._build(analysis,
                            qn_fn=qn[0] if qn is not None else None)
        except NotImplementedError:
            return 0          # serial-only analysis: nothing to compile
        if params_specs is not None:
            return 0          # ring path: per-process staging shapes
        global_bs = bs * bs_factor
        if qn is not None:
            params, sel_idx = qn[1], qn[2]
        else:
            params, sel_idx = _wrap_for_transfer(
                analysis._batch_params(), analysis._batch_select(),
                reader.n_atoms, self.transfer_dtype)
        frames = list(frames)
        n_blocks = -(-len(frames) // global_bs) if frames else 0
        if n_blocks == 0:
            return 0
        quantize = _quant_mode(self.transfer_dtype)
        n_stage = reader.n_atoms if sel_idx is None else len(sel_idx)
        # positional shardings matching _put_staged targets: device-put
        # elements carry their NamedSharding, host-side scale arrays
        # ride the dispatch unsharded
        if quantize == "delta":
            sh = (shardings[0], shardings[1], None, None,
                  shardings[2], shardings[3])
            anchors = bs_factor
        elif quantize:
            sh = (shardings[0], None, shardings[1], shardings[2])
            anchors = 1
        else:
            sh = shardings
            anchors = 1
        avals = _staged_avals(global_bs, n_stage, quantize,
                              delta_anchors=anchors, shardings=sh)
        td = self.transfer_dtype
        base_fn = qn[0] if qn is not None else analysis._batch_fn()
        n = 0
        if _cc.aot_compile(_op_label(base_fn, td, "mesh", "kernel"),
                           gfn, params, *avals) is not None:
            n += 1
        if gfn_fused is not None and n_blocks > 1:
            total_aval = jax.eval_shape(gfn, params, *avals)
            if _cc.aot_compile(_op_label(base_fn, td, "mesh", "fused"),
                               gfn_fused, total_aval, params,
                               *avals) is not None:
                n += 1
        return n

    def stage(self, analysis, reader, frames, batch_size=None) -> int:
        """Scheduler-prefetch staging for the mesh path: populate
        ``block_cache`` with this run's (sharded) staged blocks without
        dispatching — same keys and scan grouping as ``execute``.
        Single-controller, frame-sharded kernels only."""
        import jax

        if self.block_cache is None or jax.process_count() > 1:
            return 0
        bs = batch_size or self.batch_size
        try:
            qn = (_mesh_quantized_native(analysis, self.transfer_dtype)
                  if analysis._batch_specs(self.axis_name) is None
                  else None)
            bs_factor, _gfn, shardings, params_specs, _gf = self._build(
                analysis, qn_fn=qn[0] if qn is not None else None)
        except NotImplementedError:
            return 0          # serial-only analysis: nothing to stage
        if params_specs is not None:
            return 0
        global_bs = bs * bs_factor
        if qn is not None:
            sel_idx = qn[2]
        else:
            _, sel_idx = _wrap_for_transfer(
                analysis._batch_params(), analysis._batch_select(),
                reader.n_atoms, self.transfer_dtype)
        frames = list(frames)
        fold = analysis._device_fold_fn
        devcombine = analysis._device_combine
        scan_k = 1
        if (fold is None) == (devcombine is None):
            scan_k = _resolve_scan_k(
                self.scan_k, self.block_cache,
                -(-len(frames) // global_bs) if frames else 0,
                _block_nbytes(global_bs, sel_idx, reader.n_atoms,
                              self.transfer_dtype))

        def put(staged):
            return _put_staged(staged, shardings)

        return _run_batches(
            analysis, reader, frames, global_bs, None, sel_idx,
            device_put_fn=put, cache=self.block_cache,
            quantize=_quant_mode(self.transfer_dtype),
            reliability=self.reliability, scan_k=scan_k,
            stage_only=True,
            delta_anchors=(bs_factor if self.transfer_dtype == "delta"
                           else 1))

    def execute(self, analysis, reader, frames, batch_size=None):
        import jax

        bs = batch_size or self.batch_size
        qn = (_mesh_quantized_native(analysis, self.transfer_dtype)
              if analysis._batch_specs(self.axis_name) is None else None)
        bs_factor, gfn, shardings, params_specs, gfn_fused = self._build(
            analysis, qn_fn=qn[0] if qn is not None else None)
        global_bs = bs * bs_factor
        if qn is not None:
            params, sel_idx = qn[1], qn[2]
        else:
            params, sel_idx = _wrap_for_transfer(
                analysis._batch_params(), analysis._batch_select(),
                reader.n_atoms, self.transfer_dtype)
        frames = list(frames)
        fused_call = (None if gfn_fused is None else
                      lambda total, *staged: gfn_fused(total, params,
                                                       *staged))

        n_proc = jax.process_count()
        if n_proc > 1:
            # Multi-controller (DCN) path: every process runs this same
            # execute() over the same global frame schedule; frame-
            # sharded analyses stage only their own slice of each batch
            # (see _run_batches) and the slices assemble into one global
            # mesh-sharded array; atom-sharded (ring) analyses replicate
            # frames and slice the ATOM axis per process instead.  The
            # kernel + psum merge are IDENTICAL to the single-host path;
            # time-series outputs are all_gathered to replicated and
            # int16 scales travel per-frame (see _build) — every
            # analysis family the reference could run at N ranks
            # (RMSF.py:59-61) runs at N controllers.
            if params_specs is not None:
                return self._execute_ring_multihost(
                    analysis, reader, frames, bs, gfn, shardings,
                    params_specs, params, sel_idx, n_proc)
            from mdanalysis_mpi_tpu.parallel.distributed import (
                global_batch_from_local)

            mesh = shardings[0].mesh
            axis = self.axis_name

            def put(staged):
                # every element is frame-sharded, including the int16
                # per-frame inv array when present
                return tuple(global_batch_from_local(x, mesh, axis)
                             for x in staged)

            return _run_batches(
                analysis, reader, frames, global_bs,
                lambda *staged: gfn(params, *staged), sel_idx,
                device_put_fn=put, cache=self.block_cache,
                quantize=_quant_mode(self.transfer_dtype),
                local_divisor=n_proc, local_index=jax.process_index(),
                inv_per_frame=True, prestage=self.prestage,
                fused_call=fused_call, reliability=self.reliability,
                engine="fused" if qn is not None else "generic",
                # delta at N controllers: each process quantizes its
                # OWN slice with one anchor per LOCAL device; the
                # (A, 1, 1) inv_abs shards with the keyframes, so no
                # scale agreement ever crosses DCN
                delta_anchors=(jax.local_device_count()
                               if self.transfer_dtype == "delta"
                               else 1))

        def put(staged):
            return _put_staged(staged, shardings)

        # scan-folded dispatch (single-controller only: the multi-host
        # path above assembles global arrays per block and keeps the
        # per-block schedule).  Eligible when frames are mesh-sharded
        # (no ring/custom specs) and the analysis is EITHER a reduction
        # with both fold and psum merge (the scan carries local
        # partials, one psum per group) OR a pure series (stacked
        # per-step outputs); mixed declarations keep scan_k=1.
        fold = analysis._device_fold_fn
        devcombine = analysis._device_combine
        scan_k = 1
        scan_calls = None
        if params_specs is None and (fold is None) == (devcombine is None):
            scan_k = _resolve_scan_k(
                self.scan_k, self.block_cache,
                -(-len(frames) // global_bs) if frames else 0,
                _block_nbytes(global_bs, sel_idx, reader.n_atoms,
                              self.transfer_dtype))
        if scan_k > 1:
            scan_calls = _make_scan_calls(
                self._build_scan(analysis,
                                 qn_fn=qn[0] if qn is not None else None),
                params)

        # With _device_combine, gfn outputs replicated merged partials;
        # without, out_specs=P(axis) concatenates per-device outputs along
        # axis 0 in device (= frame) order — either way one partials
        # pytree per global batch, accumulated on device by _run_batches.
        return _run_batches(
            analysis, reader, frames, global_bs,
            lambda *staged: gfn(params, *staged), sel_idx,
            device_put_fn=put, cache=self.block_cache,
            quantize=_quant_mode(self.transfer_dtype),
            prestage=self.prestage, fused_call=fused_call,
            reliability=self.reliability,
            scan_k=scan_k, scan_calls=scan_calls,
            engine="fused" if qn is not None else "generic",
            # delta: one absolute anchor per device shard (see _build)
            delta_anchors=(bs_factor if self.transfer_dtype == "delta"
                           else 1))

    def _execute_ring_multihost(self, analysis, reader, frames, bs, gfn,
                                shardings, params_specs, params, sel_idx,
                                n_proc):
        """Atom-sharded (ring) kernels at N controllers: frames are
        replicated (every process stages the same frame batches), the
        union ATOM axis is process-sliced — each process stages and
        holds only its devices' contiguous atom block — and atom-sharded
        params (the ring weight vectors) assemble the same way.  The
        ppermute ring then rotates blocks across process boundaries over
        DCN exactly as it does over ICI single-host (SURVEY.md §5.7)."""
        import jax

        from mdanalysis_mpi_tpu.parallel.distributed import global_from_local

        mesh = shardings[0].mesh
        axis = self.axis_name
        pid = jax.process_index()

        def globalize(x, spec):
            """Per-process slice of full ``x`` along the axis ``spec``
            shards (if any) → one global array on the multi-host mesh
            (assembly itself is the shared distributed.global_from_local
            invariant)."""
            x = np.asarray(x)
            local = x
            for dim, s in enumerate(spec):
                if s == axis:
                    if x.shape[dim] % n_proc:
                        raise ValueError(
                            f"axis {dim} of shape {x.shape} does not "
                            f"divide across {n_proc} processes")
                    per = x.shape[dim] // n_proc
                    sl = [slice(None)] * x.ndim
                    sl[dim] = slice(pid * per, (pid + 1) * per)
                    local = x[tuple(sl)]
                    break
            return global_from_local(mesh=mesh, spec=spec,
                                     local=np.ascontiguousarray(local),
                                     global_shape=x.shape)

        # the union atom axis must split evenly over processes (device
        # divisibility is already guaranteed by the analysis' ring
        # padding) — checked before any globalization so the failure
        # names the actual remedy
        n_union = len(sel_idx)
        if n_union % n_proc:
            raise ValueError(
                f"ring union of {n_union} atoms does not divide across "
                f"{n_proc} processes; run with a process count that "
                f"divides it (the union is padded to multiples of the "
                f"ring pad)")
        # ring params are a flat tuple zipped against their specs
        # (PartitionSpec is itself a tuple — tree.map would recurse
        # into it).  Globalizing fetches device-held params to host
        # once; memoized per (mesh, processes) on the analysis so
        # repeat run() calls skip the round trip.
        pkey = (id(mesh), n_proc, axis)
        cached = getattr(analysis, "_ring_global_params", None)
        if cached is not None and cached[0] == pkey:
            params = cached[1]
        else:
            params = tuple(globalize(x, spec)
                           for x, spec in zip(params, params_specs))
            analysis._ring_global_params = (pkey, params)
        # stage only this process's contiguous atom block of the union
        per = n_union // n_proc
        local_sel = np.asarray(sel_idx)[pid * per:(pid + 1) * per]
        batch_spec, boxes_spec, mask_spec = (s.spec for s in shardings)

        def put(staged):
            block, boxes, mask = staged
            return (globalize_block(block),
                    globalize(boxes, boxes_spec),
                    globalize(mask, mask_spec))

        def globalize_block(block):
            # local (B, per, 3) → global (B, n_union, 3) atom-sharded
            return global_from_local(
                np.ascontiguousarray(block), mesh, batch_spec,
                global_shape=(block.shape[0], n_union) + block.shape[2:])

        return _run_batches(
            analysis, reader, frames, bs,
            lambda *staged: gfn(params, *staged), local_sel,
            device_put_fn=put, cache=self.block_cache, quantize=False,
            prestage=self.prestage, reliability=self.reliability)


from mdanalysis_mpi_tpu.parallel.mpi import MPIExecutor  # noqa: E402
# (module import is cheap and mpi4py itself stays lazy — it loads only
# when MPIExecutor() is built without an explicit communicator)

_EXECUTORS = {
    "serial": SerialExecutor,
    "jax": JaxExecutor,
    "mesh": MeshExecutor,
    "mpi": MPIExecutor,
}


def warmup_analysis(analysis, executor, start=None, stop=None,
                    step=None, frames=None, batch_size=None) -> int:
    """AOT-warm every kernel ``analysis.run(backend=executor, ...)``
    would compile over this window (docs/COLDSTART.md): resolves the
    frame window and runs ``_prepare`` exactly as ``run()`` would,
    then hands each of the analysis' ``_warmup_analyses()`` to the
    executor's ``warmup``.  Returns executables registered; 0 for
    executors/analyses with nothing to precompile."""
    if not hasattr(executor, "warmup"):
        return 0
    n = 0
    for a in analysis._warmup_analyses():
        fl = list(a._frames(start, stop, step, frames))
        a.n_frames = len(fl)
        a._frame_indices = fl
        a._prepare()
        n += executor.warmup(a, a._universe.trajectory, fl,
                             batch_size=batch_size)
    return n


def stage_analysis(analysis, executor, start=None, stop=None,
                   step=None, frames=None, batch_size=None) -> int:
    """Prefetch-stage the blocks ``analysis.run(backend=executor,
    ...)`` would stage, into the executor's ``block_cache``, without
    dispatching any kernel (the scheduler-prefetch entry point —
    docs/COLDSTART.md).  Returns blocks newly staged."""
    if not hasattr(executor, "stage"):
        return 0
    n = 0
    for a in analysis._warmup_analyses():
        fl = list(a._frames(start, stop, step, frames))
        a.n_frames = len(fl)
        a._frame_indices = fl
        a._prepare()
        n += executor.stage(a, a._universe.trajectory, fl,
                            batch_size=batch_size)
    return n


def get_executor(backend, **kwargs):
    """Resolve a backend name or instance → executor instance."""
    if hasattr(backend, "execute"):
        return backend
    try:
        cls = _EXECUTORS[backend]
    except KeyError:
        raise ValueError(
            f"unknown backend {backend!r}; available: {sorted(_EXECUTORS)}"
        ) from None
    return cls(**kwargs)
