"""Pluggable execution backends.

The dispatch point BASELINE.json's north_star prescribes: "only the inner
``_single_frame`` compute crosses the backend boundary".  An analysis
(:class:`~mdanalysis_mpi_tpu.analysis.base.AnalysisBase`) exposes

- ``_single_frame(ts)`` + ``_serial_summary()``   (host oracle path), and
- ``_make_batch_kernel() -> fn(batch, mask) -> partials`` with
  ``_combine(a, b)`` / optional ``_device_combine(partials, axis)``
  (device batch path),

and the executors below schedule those over the trajectory:

- :class:`SerialExecutor` — per-frame NumPy loop; the reference's
  single-rank behavior and the differential-test oracle.
- :class:`JaxExecutor` — single-device: frame blocks staged host→HBM,
  one jitted batch kernel per block, Chan-merge across blocks on host
  in float64 (precision policy, SURVEY.md §7 hard parts).
- :class:`MeshExecutor` — multi-device: batches sharded over the mesh
  data axis via ``shard_map``; cross-chip merge by the analysis'
  ``_device_combine`` (``jax.lax.psum``-based — the TPU-native
  replacement for ``comm.Allreduce``/``comm.reduce``,
  RMSF.py:110,143).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.parallel.partition import iter_batches, pad_batch


def _f32_precision(fn):
    """Trace ``fn`` under full-float32 matmul precision.

    TPU matmuls (including jnp.linalg internals the explicit
    ``precision=`` pins in ops/ can't reach) default to bfloat16 passes —
    a ~1e-2 relative error that breaks superposition geometry.  The
    kernels are bandwidth-bound with tiny contraction dims, so full f32
    is effectively free (precision policy, SURVEY.md §7 Q4).
    """
    import functools

    import jax

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("float32"):
            return fn(*args, **kwargs)

    return wrapped


def _stage(reader, frames: list[int], sel_idx) -> np.ndarray:
    """Read ``frames`` → float32 (b, S, 3) with optional host-side
    selection gather (gathering before device_put slashes host→HBM
    traffic when S << N)."""
    if len(frames) == 0:
        n = reader.n_atoms if sel_idx is None else len(sel_idx)
        return np.empty((0, n, 3), dtype=np.float32)
    contiguous = frames[-1] - frames[0] + 1 == len(frames)
    if contiguous:
        block, _ = reader.read_block(frames[0], frames[-1] + 1)
    else:
        block = np.stack([reader[i].positions for i in frames])
    return block if sel_idx is None else block[:, sel_idx]


class SerialExecutor:
    """Frame-at-a-time host loop (the reference's per-rank body,
    RMSF.py:91-103/123-138, minus MPI)."""

    name = "serial"

    def execute(self, analysis, reader, frames, batch_size=None):
        for i in frames:
            analysis._single_frame(reader[i])
        return analysis._serial_summary()


class JaxExecutor:
    """Single-device batch pipeline: stage block → jitted kernel →
    host float64 Chan merge across blocks."""

    name = "jax"

    def __init__(self, batch_size: int = 128, device=None):
        self.batch_size = batch_size
        self.device = device

    def execute(self, analysis, reader, frames, batch_size=None):
        import jax

        bs = batch_size or self.batch_size
        kernel = jax.jit(_f32_precision(analysis._make_batch_kernel()))
        sel_idx = analysis._batch_select()
        frames = list(frames)
        total = None
        for a, b in iter_batches(0, len(frames), bs):
            block = _stage(reader, frames[a:b], sel_idx)
            padded, mask = pad_batch(block, bs)
            partials = kernel(padded, mask)
            partials = jax.tree.map(lambda x: np.asarray(x, np.float64),
                                    partials)
            total = partials if total is None else analysis._combine(total, partials)
        if total is None:
            total = analysis._identity_partials()
        return total


class MeshExecutor:
    """Data-parallel mesh pipeline.

    Frames are sharded over the ``data`` mesh axis; each device runs the
    batch kernel on its shard and the cross-device merge happens on-chip
    via the analysis' ``_device_combine`` (psum over ICI).  This is the
    TPU-native image of the reference's SPMD ranks + collectives
    (SURVEY.md §2.3 "DP over frames").
    """

    name = "mesh"

    def __init__(self, batch_size: int = 64, devices=None,
                 axis_name: str = "data"):
        self.batch_size = batch_size
        self.devices = devices
        self.axis_name = axis_name

    def _build(self, analysis):
        import jax
        from jax import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = self.devices if self.devices is not None else jax.devices()
        mesh = Mesh(np.asarray(devices), (self.axis_name,))
        kernel = _f32_precision(analysis._make_batch_kernel())
        devcombine = analysis._device_combine

        def shard_fn(batch, mask):
            partials = kernel(batch, mask)
            if devcombine is not None:
                return devcombine(partials, self.axis_name)
            return partials

        out_specs = P() if devcombine is not None else P(self.axis_name)
        # check_vma=False: jnp.linalg.svd lowers to an iterative scan on
        # TPU whose bool carry trips the varying-manual-axes check inside
        # shard_map (works on CPU, fails on TPU); the kernel is purely
        # per-shard + explicit psum, so the check adds nothing here.
        gfn = jax.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(self.axis_name), P(self.axis_name)),
            out_specs=out_specs, check_vma=False))
        sharding = NamedSharding(mesh, P(self.axis_name))
        return len(devices), gfn, sharding

    def execute(self, analysis, reader, frames, batch_size=None):
        import jax

        bs = batch_size or self.batch_size
        n_dev, gfn, sharding = self._build(analysis)
        global_bs = bs * n_dev
        sel_idx = analysis._batch_select()
        frames = list(frames)
        total = None
        for a, b in iter_batches(0, len(frames), global_bs):
            block = _stage(reader, frames[a:b], sel_idx)
            padded, mask = pad_batch(block, global_bs)
            padded = jax.device_put(padded, sharding)
            mask = jax.device_put(mask, sharding)
            partials = gfn(padded, mask)
            # With _device_combine, outputs are replicated merged partials;
            # without, out_specs=P(axis) concatenates per-device outputs
            # along axis 0 in device (= frame) order — either way one
            # partials pytree per global batch.
            part = jax.tree.map(lambda x: np.asarray(x, np.float64), partials)
            total = part if total is None else analysis._combine(total, part)
        if total is None:
            total = analysis._identity_partials()
        return total


_EXECUTORS = {
    "serial": SerialExecutor,
    "jax": JaxExecutor,
    "mesh": MeshExecutor,
}


def get_executor(backend, **kwargs):
    """Resolve a backend name or instance → executor instance."""
    if hasattr(backend, "execute"):
        return backend
    try:
        cls = _EXECUTORS[backend]
    except KeyError:
        raise ValueError(
            f"unknown backend {backend!r}; available: {sorted(_EXECUTORS)}"
        ) from None
    return cls(**kwargs)
