"""MPI host executor: the reference's SPMD pattern behind AnalysisBase.

BASELINE.json's north_star keeps "the existing mpi4py+NumPy path" as one
executor next to the JAX/TPU ones.  This module is that path, rebuilt on
the framework's dispatch boundary: each rank runs the serial per-frame
loop over its static frame block (the reference's per-rank body,
RMSF.py:91-103/123-138), and per-rank partials merge through the
communicator — the image of the reference's two collectives:

- additive partials (``tree_add`` folds, e.g. AverageStructure sums)
  correspond to ``comm.Allreduce(MPI.SUM)`` (RMSF.py:109-110);
- algebraic merges (the Chan moment merge, RMSF.py:36-41) correspond to
  ``comm.reduce(S, op=second_order_moments)`` (RMSF.py:143) — here an
  ``allreduce`` with the analysis' fold function so every rank finishes
  with the full result (the reference leaves ranks != 0 with garbage
  and a dead ``MPI.Op`` handle, quirk Q1; we use the fold directly).

Failure semantics (SURVEY.md §5.3): like the reference, a rank that
dies before its collective leaves the others blocked in
``allreduce``/``allgather`` — MPI offers no cheap liveness detection,
so this path fails fast only on *raising* ranks (the exception
propagates before the collective).  For crash-durable long runs use the
single-controller backends with
:func:`~mdanalysis_mpi_tpu.utils.checkpoint.run_checkpointed`, whose
mergeable partials make block-level recovery free.

mpi4py is an *optional* dependency: :class:`MPIExecutor` accepts any
object with the tiny communicator surface it uses (``Get_rank``,
``Get_size``, ``allreduce(obj, op)``, ``allgather(obj)``), so tests
drive it with an in-process communicator and no MPI runtime; under
``mpirun`` it defaults to ``mpi4py.MPI.COMM_WORLD``.

Why object-path collectives only: per-rank partials are small (a moment
summary is ``(1 + 2·S·3)`` doubles), so the pickle path costs nothing
next to the per-frame compute — the reference's zero-copy buffer
``Allreduce`` mattered only because it reduced 3·n_atoms doubles every
run (RMSF.py:110); our wide-average path ships the same bytes once.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.parallel.partition import static_blocks


def _world_comm():
    try:
        from mpi4py import MPI
    except ImportError:
        raise RuntimeError(
            "MPIExecutor needs mpi4py (not installed) unless an explicit "
            "communicator object is passed; run under mpirun with mpi4py "
            "available, or pass comm=<object with Get_rank/Get_size/"
            "allreduce/allgather>") from None
    return MPI.COMM_WORLD


class MPIExecutor:
    """Frame-parallel host execution over an MPI-style communicator.

    ``MPIExecutor()`` under ``mpirun -np N`` reproduces the reference
    program's topology: N single-threaded NumPy ranks, static block
    decomposition (balanced variant of RMSF.py:65-69 — block sizes
    differ by at most 1 instead of rank N-1 absorbing the whole
    remainder), collective merge, every rank left holding the final
    partials.  Empty blocks (size > n_frames, quirk Q2) contribute the
    analysis' identity partials instead of crashing.
    """

    name = "mpi"

    def __init__(self, comm=None):
        self.comm = comm if comm is not None else _world_comm()

    def execute(self, analysis, reader, frames, batch_size=None):
        del batch_size  # host path is per-frame, like the reference
        comm = self.comm
        rank, size = comm.Get_rank(), comm.Get_size()
        frames = list(frames)
        block = static_blocks(len(frames), size)[rank]
        for i in block:
            analysis._single_frame(reader[frames[i]])
        partial = (analysis._serial_summary() if len(block)
                   else analysis._identity_partials())
        fold = analysis._device_fold_fn
        if fold is not None:
            # allreduce with the analysis' merge (host-safe on NumPy:
            # merge_moments / tree_add dispatch on array type) — the
            # RMSF.py:143 reduction without the discarded-Op quirk
            return comm.allreduce(partial, op=fold)
        # time-series analyses: concatenate per-rank partials in rank
        # (= frame) order
        parts = [p for p in comm.allgather(partial) if _n_rows(p)]
        if not parts:
            return analysis._identity_partials()
        if len(parts) == 1:
            return parts[0]
        import jax

        return jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
            *parts)


def _n_rows(partial):
    import jax

    leaves = jax.tree.leaves(partial)
    return leaves[0].shape[0] if leaves else 0


class ThreadComm:
    """In-process communicator: N threads, barrier-synchronized
    collectives over shared memory.

    The subset MPIExecutor needs (``Get_rank``/``Get_size``/
    ``allreduce``/``allgather``), implemented with a
    ``threading.Barrier`` double-handshake.  Exists so the MPI code
    path is exercised (tests, single-host smoke runs) without an MPI
    runtime — build one per rank via :meth:`make`.
    """

    def __init__(self, rank: int, size: int, shared: dict):
        self._rank = rank
        self._size = size
        self._shared = shared

    @classmethod
    def make(cls, size: int):
        """Return ``size`` communicators sharing one collective state."""
        import threading

        shared = {"slots": [None] * size,
                  "barrier": threading.Barrier(size)}
        return [cls(r, size, shared) for r in range(size)]

    def Get_rank(self) -> int:
        return self._rank

    def Get_size(self) -> int:
        return self._size

    def allgather(self, obj):
        slots, barrier = self._shared["slots"], self._shared["barrier"]
        slots[self._rank] = obj
        barrier.wait()          # all contributions visible
        out = list(slots)
        barrier.wait()          # all reads done before slots are reused
        return out

    def allreduce(self, obj, op):
        parts = self.allgather(obj)
        acc = parts[0]
        for p in parts[1:]:
            acc = op(acc, p)    # every rank folds identically, in order
        return acc
