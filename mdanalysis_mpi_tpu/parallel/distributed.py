"""Multi-host (DCN) bring-up for the mesh executor.

The reference scales across nodes with ``mpirun`` + MPI collectives
(RMSF.py:59-61,110,143 — SURVEY.md §5.8).  The TPU-native image is
multi-controller JAX: one Python process per host, each seeing only its
local chips, joined into one global mesh by ``jax.distributed`` over
DCN; in-program reductions stay ``psum`` over ICI/DCN exactly as on a
single host (the MeshExecutor kernel is unchanged — only array
placement differs).

Division of labor on multi-host (SURVEY.md §5.8 "host-side staging uses
no collectives"):

- every process calls :func:`initialize` once, before any other JAX
  call;
- every process opens the SAME trajectory files (the reference's
  pattern: N ranks, N independent reader handles, RMSF.py:56);
- :func:`process_frame_shard` tells each process which contiguous
  frame block its local chips are responsible for — frames are
  sharded host-first so each host's block is contiguous on disk
  (sequential decode, SURVEY.md §7 "Host I/O vs TPU throughput");
- per-host staged blocks become one global array via
  :func:`jax.make_array_from_process_local_data`.

Only chip-count-preserving facts are encoded here; the mesh/psum logic
lives in :class:`~mdanalysis_mpi_tpu.parallel.executors.MeshExecutor`.
This environment exposes one process with one chip, so multi-host wiring
is validated structurally (unit tests over the shard math + the
single-process degenerate path); the mesh collectives themselves are
exercised on the 8-device virtual CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

from mdanalysis_mpi_tpu.parallel.partition import static_blocks


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or single-process-degenerate) the multi-host JAX runtime.

    Call once per process before any other JAX API.  With no arguments
    on a single host this is a no-op; on TPU pods the three values are
    normally auto-detected from the TPU environment, and on other
    fabrics they come from the launcher (one process per host).
    """
    if num_processes is not None and num_processes == 1:
        return
    import jax

    # CPU fleets (and the 2-controller CPU parity test) need a real
    # cross-process collectives backend: without one, XLA:CPU raises
    # "Multiprocess computations aren't implemented on the CPU
    # backend" at the first psum.  jaxlib ships gloo for exactly this;
    # opt it in ONLY when the platform is explicitly pinned to CPU
    # (JAX_PLATFORMS / jax_platforms config).  Unset means
    # autodetection — likely an accelerator fleet, where flipping the
    # secondary CPU client's collectives is an unintended global
    # config change.  Guarded: older jaxlibs without the knob keep
    # today's behavior (mesh tests there run single-process).
    try:
        if "cpu" in str(jax.config.jax_platforms or "").lower():
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    except Exception:
        pass

    if (coordinator_address is None and num_processes is None
            and process_id is None):
        try:
            jax.distributed.initialize()
        except ValueError:
            # no cluster environment to auto-detect: single process
            return
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)


def process_frame_shard(n_frames: int, process_id: int | None = None,
                        num_processes: int | None = None) -> range:
    """The contiguous frame block this process stages for its chips.

    Balanced static decomposition (the reference's RMSF.py:65-69 block
    partition, one level up: hosts instead of ranks).  Defaults to the
    live ``jax.process_index()/process_count()``.
    """
    import jax

    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    return static_blocks(n_frames, n)[pid]


def global_from_local(local, mesh, spec, global_shape=None):
    """Assemble per-process local blocks into one mesh-sharded global
    array — the single definition of the "process ``pid`` owns the
    ``pid``-th contiguous block of every ``spec``-sharded axis"
    invariant (shared by the frame-sharded and atom-sharded multi-host
    paths so they cannot drift).

    ``local``: this process's block — for each axis ``spec`` shards,
    1/process_count of the global extent, in process order; replicated
    axes (and fully replicated ``spec=P()`` arrays) carry the full,
    process-identical data.  ``global_shape`` is inferred by scaling
    the sharded axes by process_count when omitted.  Single-process
    meshes take the fast path (plain ``device_put``).
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    n_proc = jax.process_count()
    if n_proc == 1:
        return jax.device_put(local, sharding)
    if global_shape is None:
        global_shape = tuple(
            d * n_proc if dim < len(spec) and spec[dim] is not None else d
            for dim, d in enumerate(local.shape))
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape)


def local_host_copy(x):
    """Host copy of THIS process's slice of an array — the inverse of
    :func:`global_from_local`, and the fetch half of multi-host SDC
    scrubbing (docs/RELIABILITY.md §5): stage-time fingerprints cover
    the process-local staged bytes, so the scrub comparison must fetch
    exactly those bytes back, never another host's shard (which
    ``np.asarray`` on a multi-process global array cannot fetch at
    all).

    Single-process / fully-addressable arrays (and plain numpy) take
    the exact ``np.asarray`` path.  For a multi-process global array,
    the unique addressable shards are reassembled in index order along
    the sharded axis — by the :func:`global_from_local` invariant they
    are this process's contiguous block of every sharded axis, so the
    result equals the locally staged array bit for bit; a replicated
    array has one unique (full) shard.
    """
    import numpy as np

    shards = getattr(x, "addressable_shards", None)
    if shards is None or getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    uniq: dict = {}
    for shard in shards:
        key = tuple((s.start, s.stop, s.step) for s in shard.index)
        if key not in uniq:
            uniq[key] = np.asarray(shard.data)
    if len(uniq) == 1:
        return next(iter(uniq.values()))
    # the varying dimension is the sharded axis; shards are disjoint
    # contiguous slices there (process-contiguous by construction)
    keys = sorted(uniq)
    axis = next(d for d in range(len(keys[0]))
                if len({k[d] for k in keys}) > 1)
    ordered = sorted(uniq.items(),
                     key=lambda kv: (kv[0][axis][0] or 0))
    return np.concatenate([v for _, v in ordered], axis=axis)


def global_batch_from_local(local_batch, mesh, axis_name: str = "data"):
    """Frame-axis convenience wrapper over :func:`global_from_local`:
    this process's (B_local, ...) staged frames — B_local = B_global /
    process_count, matching :func:`process_frame_shard` order so global
    frame order is preserved."""
    from jax.sharding import PartitionSpec as P

    return global_from_local(local_batch, mesh, P(axis_name))
