"""Parallel scheduling + execution (reference layers L4/L5, SURVEY.md §1).

- :mod:`partition` — frame partitioner generalizing the reference's
  static block decomposition (RMSF.py:65-72) with padding + masks for
  the short-trajectory edge cases (quirk Q2).
- :mod:`executors` — the pluggable backend layer the reference lacks
  (BASELINE.json north_star): serial NumPy oracle, JAX single-device,
  and JAX mesh (shard_map + psum over the data axis, replacing
  ``comm.Allreduce``/``comm.reduce``, RMSF.py:110,143).
- :mod:`mpi` — the mpi4py+NumPy host path the north_star keeps as a
  peer executor (optional dependency; communicator injectable).
- :mod:`distributed` — multi-host (DCN) bring-up helpers around
  ``jax.distributed`` + per-process frame sharding.
"""

from mdanalysis_mpi_tpu.parallel.partition import static_blocks, iter_batches
from mdanalysis_mpi_tpu.parallel.executors import (
    SerialExecutor, JaxExecutor, MeshExecutor, get_executor,
)
from mdanalysis_mpi_tpu.parallel.mpi import MPIExecutor, ThreadComm

__all__ = [
    "static_blocks", "iter_batches",
    "SerialExecutor", "JaxExecutor", "MeshExecutor", "MPIExecutor",
    "ThreadComm", "get_executor",
]
