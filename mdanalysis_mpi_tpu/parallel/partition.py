"""Frame partitioning.

Generalizes the reference's static block decomposition (RMSF.py:65-72:
``n_frames // size`` per rank, last rank absorbs the remainder) into a
balanced partition (block sizes differ by at most 1 — avoids the
reference's pathological last block) and explicit handling of the
``size > n_frames`` / ``n_frames == 0`` failure modes (quirk Q2, which
crashes the reference with ZeroDivisionError).
"""

from __future__ import annotations

import numpy as np


def static_blocks(n_frames: int, n_blocks: int) -> list[range]:
    """Partition ``range(n_frames)`` into ``n_blocks`` contiguous blocks.

    Balanced: each block gets ``n_frames // n_blocks`` frames and the
    first ``n_frames % n_blocks`` blocks get one extra.  Blocks may be
    empty when ``n_blocks > n_frames`` — callers handle empties via
    masks, not crashes (Q2).
    """
    if n_blocks < 1:
        raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
    if n_frames < 0:
        raise ValueError(f"n_frames must be >= 0, got {n_frames}")
    base = n_frames // n_blocks
    extra = n_frames % n_blocks
    blocks = []
    start = 0
    for i in range(n_blocks):
        size = base + (1 if i < extra else 0)
        blocks.append(range(start, start + size))
        start += size
    return blocks


def shard_windows(n_frames: int | None, start: int | None,
                  stop: int | None, step: int | None,
                  n_shards: int, chunk_frames: int | None = None
                  ) -> list:
    """Split one job's frame window into ``n_shards`` contiguous
    sub-windows — the fleet tier's trajectory sharding
    (docs/RELIABILITY.md §6): each shard is an independent
    ``(start, stop, step)`` job on some host, and the controller
    concatenates the per-frame result series back in shard order (the
    task-parallel map-reduce decomposition of PAPERS.md 1801.07630).

    Shards partition the window's frame INDEX SEQUENCE
    (``range(start, stop, step)``), so non-unit steps shard exactly:
    the union of the sub-windows visits the same frames in the same
    order.  Returns one ``(start, stop, step)`` per shard, ``None``
    for shards left empty (``n_shards > n_window_frames``).
    ``n_frames`` bounds an open window (``stop=None``); with neither
    a ``stop`` nor ``n_frames`` the window is unbounded and unsplittable.

    ``chunk_frames`` (a block store's chunk geometry, docs/STORE.md)
    aligns shard boundaries to chunk multiples, so each shard child's
    reads cover whole chunks and no chunk is fetched by two hosts:
    shards get balanced CHUNK counts (edge chunks may be partial where
    the window itself starts/ends mid-chunk).  The union/order
    contract is unchanged.  Non-unit steps align too: the VISITED
    chunks (``f // chunk_frames`` over the strided index sequence)
    are what get balanced, and each shard's window regenerates
    exactly its run of the visited sequence — a stride wider than a
    chunk simply skips chunks no shard ever fetches.
    """
    step = 1 if step is None else int(step)
    if step < 1:
        raise ValueError(f"step must be >= 1, got {step}")
    lo = 0 if start is None else int(start)
    hi = stop if stop is not None else n_frames
    if hi is None:
        raise ValueError(
            "shard_windows needs a bounded window: pass stop= or "
            "n_frames=")
    if n_frames is not None:
        hi = min(int(hi), int(n_frames))
    if chunk_frames and hi > lo:
        cf = int(chunk_frames)
        idx = range(lo, hi, step)
        # distinct chunks the strided window VISITS, in order — for
        # step == 1 this is every chunk overlapping [lo, hi); for a
        # stride wider than a chunk it skips the untouched ones.
        # f // cf is nondecreasing over idx, so each shard's frames
        # are one contiguous run of the visited sequence and a single
        # (first, last + step, step) window regenerates it exactly.
        chunk_first: list[int] = []        # first visited frame per chunk
        chunk_last: list[int] = []         # last visited frame per chunk
        for ci in range(lo // cf, (hi - 1) // cf + 1):
            # first/last multiple of `step` (offset from lo) landing
            # in chunk ci's span, clipped to the window
            c_lo, c_hi = max(ci * cf, lo), min((ci + 1) * cf, hi)
            first = lo + -(-(c_lo - lo) // step) * step
            if first >= c_hi:
                continue                   # stride skipped this chunk
            chunk_first.append(first)
            chunk_last.append(lo + ((c_hi - 1 - lo) // step) * step)
        out = []
        for block in static_blocks(len(chunk_first), n_shards):
            if len(block) == 0:
                out.append(None)
                continue
            out.append((chunk_first[block.start],
                        chunk_last[block.stop - 1] + step, step))
        return out
    idx = range(lo, hi, step)
    out = []
    for block in static_blocks(len(idx), n_shards):
        if len(block) == 0:
            out.append(None)
            continue
        out.append((idx[block.start], idx[block.stop - 1] + step,
                    step))
    return out


def iter_batches(start: int, stop: int, batch_size: int):
    """Yield (a, b) batch bounds covering [start, stop) in chunks of at
    most ``batch_size`` frames."""
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    a = start
    while a < stop:
        b = min(a + batch_size, stop)
        yield a, b
        a = b


def pad_batch(batch: np.ndarray, batch_size: int, axis: int = 0):
    """Pad a frame batch to ``batch_size`` along the frame ``axis`` and
    return (padded, mask) where mask is float32 (batch_size,) with 1.0
    for real frames.  Static shapes for XLA (SURVEY.md §7 hard parts);
    padding rows repeat the last frame (any finite values — the mask
    zeroes their contribution).  ``axis=1`` serves the planar staged
    layout (3, b, S), whose frame axis sits behind the component
    planes."""
    b = batch.shape[axis]
    if b > batch_size:
        raise ValueError(f"batch of {b} frames exceeds batch_size {batch_size}")
    mask = np.zeros(batch_size, dtype=np.float32)
    mask[:b] = 1.0
    if b == batch_size:
        return batch, mask
    if b == 0:
        shape = list(batch.shape)
        shape[axis] = batch_size
        return np.zeros(tuple(shape), dtype=batch.dtype), mask
    last = np.take(batch, [-1], axis=axis)
    pad = np.concatenate(
        [batch, np.repeat(last, batch_size - b, axis=axis)], axis=axis)
    return pad, mask
