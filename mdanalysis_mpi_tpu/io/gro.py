"""GRO (GROMACS) topology/coordinate file parser + writer.

The reference's topology source (``mda.Universe(GRO, XTC)``, RMSF.py:56;
GRO fixture imported at RMSF.py:34).  Fixed-column format::

    title
    natoms
    %5d%-5s%5s%5d%8.3f%8.3f%8.3f[%8.4f%8.4f%8.4f]   (resid resname name
                                                     atomid x y z [v])
    box: lx ly lz [v1y v1z v2x v2z v3x v3y]          (free format, nm)

Coordinates are nm on disk; the framework uses Å.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.box import vectors_to_box, box_to_vectors
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files

_NM_TO_A = 10.0


def parse_gro(path: str) -> Topology:
    with open(path) as fh:
        lines = fh.read().splitlines()
    if len(lines) < 3:
        raise ValueError(f"GRO file {path!r} too short")
    try:
        n = int(lines[1].strip())
    except ValueError as e:
        raise ValueError(f"GRO file {path!r}: bad atom count line") from e
    if len(lines) < n + 3:
        raise ValueError(
            f"GRO file {path!r}: expected {n} atom lines, found {len(lines) - 3}")
    resids = np.empty(n, dtype=np.int64)
    resnames = np.empty(n, dtype="U5")
    names = np.empty(n, dtype="U5")
    coords = np.empty((n, 3), dtype=np.float32)
    # optional velocity columns (chars 44-68, nm/ps): present on every
    # atom line or treated as absent (the format is all-or-nothing)
    vels = np.zeros((n, 3), dtype=np.float32)
    have_vels = True
    for i in range(n):
        ln = lines[i + 2]
        resids[i] = int(ln[0:5])
        resnames[i] = ln[5:10].strip()
        names[i] = ln[10:15].strip()
        coords[i, 0] = float(ln[20:28])
        coords[i, 1] = float(ln[28:36])
        coords[i, 2] = float(ln[36:44])
        if have_vels and len(ln.rstrip()) >= 68:
            vels[i, 0] = float(ln[44:52])
            vels[i, 1] = float(ln[52:60])
            vels[i, 2] = float(ln[60:68])
        else:
            have_vels = False
    coords *= _NM_TO_A

    box_fields = [float(x) for x in lines[n + 2].split()]
    dims = None
    if box_fields and any(box_fields):
        m = np.zeros((3, 3))
        m[0, 0], m[1, 1], m[2, 2] = box_fields[:3]
        if len(box_fields) >= 9:
            (m[0, 1], m[0, 2], m[1, 0],
             m[1, 2], m[2, 0], m[2, 1]) = box_fields[3:9]
        dims = vectors_to_box(m * _NM_TO_A)

    top = Topology(names=names, resnames=resnames, resids=resids)
    top._coordinates = coords[None]       # single-frame fallback trajectory
    top._dimensions = dims
    # Å/ps, the upstream Timestep convention (nm/ps in the file)
    top._velocities = vels[None] * _NM_TO_A if have_vels else None
    return top


def write_gro(path: str, topology: Topology, coordinates: np.ndarray,
              dimensions: np.ndarray | None = None,
              velocities: np.ndarray | None = None,
              title: str = "written by mdanalysis_mpi_tpu") -> None:
    """Write one frame of Å coordinates (and optional Å/ps velocities)
    as a GRO file (fixture writer)."""
    coords = np.asarray(coordinates, dtype=np.float64) / _NM_TO_A
    if coords.ndim == 3:
        coords = coords[0]
    n = topology.n_atoms
    if coords.shape != (n, 3):
        raise ValueError(f"coordinates must be ({n}, 3), got {coords.shape}")
    if velocities is not None:
        velocities = np.asarray(velocities, dtype=np.float64) / _NM_TO_A
        if velocities.ndim == 3:
            velocities = velocities[0]
        if velocities.shape != (n, 3):
            raise ValueError(
                f"velocities must be ({n}, 3), got {velocities.shape}")
    with open(path, "w") as fh:
        fh.write(title + "\n")
        fh.write(f"{n:5d}\n")
        for i in range(n):
            line = "%5d%-5s%5s%5d%8.3f%8.3f%8.3f" % (
                topology.resids[i] % 100000, topology.resnames[i][:5],
                topology.names[i][:5], (i + 1) % 100000,
                coords[i, 0], coords[i, 1], coords[i, 2])
            if velocities is not None:
                line += "%8.4f%8.4f%8.4f" % tuple(velocities[i])
            fh.write(line + "\n")
        if dimensions is None:
            fh.write("   0.00000   0.00000   0.00000\n")
        else:
            m = box_to_vectors(np.asarray(dimensions)) / _NM_TO_A
            off = (m[0, 1], m[0, 2], m[1, 0], m[1, 2], m[2, 0], m[2, 1])
            if any(abs(x) > 1e-9 for x in off):
                fh.write(("%10.5f" * 9 + "\n") % (
                    m[0, 0], m[1, 1], m[2, 2], *off))
            else:
                fh.write("%10.5f%10.5f%10.5f\n" % (m[0, 0], m[1, 1], m[2, 2]))


topology_files.register("gro", parse_gro)
