"""ChainReader: several trajectory files presented as one.

Upstream-API mirror (``MDAnalysis.coordinates.chain.ChainReader``,
reached as ``Universe(top, [part1.xtc, part2.xtc])``): simulation
output commonly arrives in restart segments; the chain concatenates
them virtually — global frame i dispatches to the owning child reader
by cumulative offset.  ``read_block`` splits a window at child
boundaries and concatenates, so every piece rides its child's fused
native fast path (e.g. the XTC decode→gather f32 kernel).  For int16
staging, windows contained in ONE child pass straight through to that
child's fused decode→quantize kernel (the common case: batch windows
rarely straddle a segment boundary); boundary-straddling windows fall
back to read-then-quantize, since a single block carries a single
scale.  Transformations attach to the CHAIN, not to its parts (one
semantics for per-frame and block reads alike; enforced)."""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.io.base import ReaderBase
from mdanalysis_mpi_tpu.core.timestep import Timestep


class ChainReader(ReaderBase):
    def __init__(self, sources, n_atoms: int | None = None):
        from mdanalysis_mpi_tpu.io import trajectory_files

        readers = []
        for src in sources:
            if isinstance(src, ReaderBase):
                readers.append(src)
            elif isinstance(src, np.ndarray):
                from mdanalysis_mpi_tpu.io.memory import MemoryReader

                readers.append(MemoryReader(src))
            else:
                readers.append(trajectory_files.open(src, n_atoms=n_atoms))
        if not readers:
            raise ValueError("ChainReader needs at least one trajectory")
        na = readers[0].n_atoms
        for j, r in enumerate(readers[1:], 1):
            if r.n_atoms != na:
                raise ValueError(
                    f"chained trajectory {j} has {r.n_atoms} atoms, "
                    f"the first has {na}")
        self._check_children(readers)
        self._readers = readers
        self._starts = np.concatenate(
            [[0], np.cumsum([r.n_frames for r in readers])])

    @property
    def n_frames(self) -> int:
        return int(self._starts[-1])

    @property
    def n_atoms(self) -> int:
        return self._readers[0].n_atoms

    @property
    def filename(self):
        return None          # many files; parts expose their own

    @property
    def filenames(self) -> list:
        return [r.filename for r in self._readers]

    def reopen(self) -> "ChainReader":
        return ChainReader([r.reopen() for r in self._readers])

    @staticmethod
    def _check_children(readers) -> None:
        """No child may carry transformations — per-frame reads go
        through the raw child ``_read_frame`` while block reads go
        through the child's ``read_block`` (which applies them), so a
        transformed child would make the two paths disagree.  Checked at
        construction AND on every dispatch: ``add_transformations`` on a
        child AFTER chaining must fail loudly, not skew results."""
        for j, r in enumerate(readers):
            if r.transformations:
                raise ValueError(
                    f"chained trajectory {j} has transformations attached; "
                    "add them to the ChainReader itself so per-frame and "
                    "block reads agree")

    def _locate(self, i: int) -> tuple[int, int]:
        k = int(np.searchsorted(self._starts, i, side="right")) - 1
        return k, i - int(self._starts[k])

    def _read_frame(self, i: int) -> Timestep:
        k, local = self._locate(i)
        # per-frame hot path: only the serving child can skew this read
        if self._readers[k].transformations:
            raise ValueError(
                f"chained trajectory {k} has transformations attached; "
                "add them to the ChainReader itself so per-frame and "
                "block reads agree")
        if self._readers[k].auxiliaries:
            # same loud contract as transformations: the chain bypasses
            # child cursor paths, so a child-attached auxiliary would
            # silently vanish from every chained frame
            raise ValueError(
                f"chained trajectory {k} has auxiliaries attached; "
                "attach them to the ChainReader itself "
                "(add_auxiliary on the chain)")
        ts = self._readers[k]._read_frame(local)
        ts.frame = i                     # global numbering
        return ts

    def _split(self, start: int, stop: int, step: int):
        """Yield (reader, local_start, local_stop, local_step) pieces
        covering [start:stop:step] in order."""
        i = start
        while i < stop:
            k, local = self._locate(i)
            child_end = int(self._starts[k + 1])
            seg_stop = min(stop, child_end)
            n = -(-(seg_stop - i) // step)          # frames in this piece
            yield (self._readers[k], local,
                   local + (n - 1) * step + 1, step)
            i += n * step

    def read_block(self, start: int, stop: int, sel=None, step: int = 1):
        self._check_children(self._readers)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        if self.transformations:
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        blocks, boxes_list, any_box = [], [], False
        for r, a, b, st in self._split(start, stop, step):
            blk, boxes = r.read_block(a, b, sel=sel, step=st)
            blocks.append(blk)
            boxes_list.append(boxes)
            any_box = any_box or boxes is not None
        if not blocks:
            n = self.n_atoms if sel is None else len(sel)
            return np.empty((0, n, 3), np.float32), None
        out = np.concatenate(blocks)
        if not any_box:
            return out, None
        full = np.zeros((len(out), 6), dtype=np.float32)
        lo = 0
        for blk, boxes in zip(blocks, boxes_list):
            if boxes is not None:
                full[lo:lo + len(blk)] = boxes
            lo += len(blk)
        return out, full

    def stage_block(self, start: int, stop: int, sel=None,
                    quantize: bool = False, layout: str = "interleaved"):
        self._check_children(self._readers)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if not self.transformations and start < stop:
            k0, a = self._locate(start)
            k1, _ = self._locate(stop - 1)
            if k0 == k1:
                # window inside one child: its fused decode(+quantize)
                # fast path applies unchanged
                return self._readers[k0].stage_block(
                    a, a + (stop - start), sel=sel, quantize=quantize,
                    layout=layout)
        return ReaderBase.stage_block(self, start, stop, sel=sel,
                                      quantize=quantize, layout=layout)

    def add_auxiliary(self, name, aux, cutoff=None):
        """Auxiliaries align by ``ts.time``, and a chain's child files
        commonly RESTART their embedded clocks — which would silently
        hand segment-2 frames the aux records of segment 1's times.
        Attach only when the chained time axis is globally
        non-decreasing; otherwise fail with the remedy."""
        t = self.frame_times(range(self.n_frames))
        if t is None or np.any(np.diff(t) < 0):
            raise ValueError(
                "chained trajectory times are not globally "
                "non-decreasing (segment clocks restart, or no time "
                "metadata); time-aligned auxiliaries would silently "
                "misalign — write the segment files with continuous "
                "times, or transfer_to_memory() with explicit times")
        super().add_auxiliary(name, aux, cutoff)

    def frame_times(self, frames):
        idx = np.asarray(list(frames), dtype=np.int64)
        times = np.empty(len(idx), dtype=np.float64)
        owners = np.searchsorted(self._starts, idx, side="right") - 1
        # one child call (one file open) per owning segment
        for k in np.unique(owners):
            where = owners == k
            t = self._readers[int(k)].frame_times(
                (idx[where] - int(self._starts[int(k)])).tolist())
            if t is None:
                return None
            times[where] = t
        return times

    def close(self):
        for r in self._readers:
            r.close()
