"""In-memory ndarray-backed trajectory.

The reference builds one of these implicitly at RMSF.py:113:
``mda.Universe(GRO, positions.reshape((1, -1, 3)))`` wraps a raw ndarray
as a single-frame trajectory.  Here it is also the staging format for
TPU frame blocks and the backbone of synthetic test fixtures.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io.base import ReaderBase


class MemoryReader(ReaderBase):
    """Trajectory over a coordinate array of shape (n_frames, n_atoms, 3).

    Reads *copy* the stored frame so in-place Timestep edits do not
    persist across reads — matching the file-backed semantics the
    reference relies on (pass 2 re-reads pristine frames after pass 1's
    in-place rotations, RMSF.py:124; SURVEY.md §2.1 "Pass 2").
    """

    def __init__(self, coordinates: np.ndarray,
                 dimensions: np.ndarray | None = None,
                 dt: float = 1.0, times: np.ndarray | None = None,
                 velocities: np.ndarray | None = None):
        coords = np.asarray(coordinates, dtype=np.float32)
        if coords.ndim == 2:
            coords = coords[None]
        if coords.ndim != 3 or coords.shape[2] != 3:
            raise ValueError(
                f"coordinates must be (n_frames, n_atoms, 3), got {coords.shape}")
        self._coords = coords
        if velocities is not None:
            velocities = np.asarray(velocities, dtype=np.float32)
            if velocities.ndim == 2:
                velocities = velocities[None]
            if velocities.shape != coords.shape:
                raise ValueError(
                    f"velocities must match coordinates {coords.shape}, "
                    f"got {velocities.shape}")
        self._vels = velocities
        if dimensions is not None:
            dimensions = np.asarray(dimensions, dtype=np.float32)
            if dimensions.ndim == 1:
                dimensions = np.broadcast_to(
                    dimensions, (coords.shape[0], 6)).copy()
            if dimensions.shape != (coords.shape[0], 6):
                raise ValueError(
                    f"dimensions must be (n_frames, 6), got {dimensions.shape}")
        self._dims = dimensions
        self._dt = float(dt)
        if times is not None:
            times = np.asarray(times, dtype=np.float64)
            if times.shape != (coords.shape[0],):
                raise ValueError(
                    f"times must be ({coords.shape[0]},), got {times.shape}")
        self._times = times

    @property
    def n_frames(self) -> int:
        return self._coords.shape[0]

    @property
    def n_atoms(self) -> int:
        return self._coords.shape[1]

    @property
    def coordinates(self) -> np.ndarray:
        """The full backing array (n_frames, n_atoms, 3) — zero-copy."""
        return self._coords

    def _read_frame(self, i: int) -> Timestep:
        t = (i * self._dt) if self._times is None else float(self._times[i])
        return Timestep(
            self._coords[i].copy(), frame=i, time=t,
            dimensions=None if self._dims is None else self._dims[i].copy(),
            velocities=None if self._vels is None else self._vels[i].copy())

    def reopen(self) -> "MemoryReader":
        """Independent cursor over the same backing array (zero-copy),
        supporting ``Universe.copy()`` (RMSF.py:57 semantics)."""
        return MemoryReader(self._coords, self._dims, self._dt,
                            times=self._times, velocities=self._vels)

    def frame_times(self, frames) -> np.ndarray:
        idx = np.asarray(list(frames), dtype=np.int64)
        if self._times is not None:
            return self._times[idx]
        return idx.astype(np.float64) * self._dt

    def read_block(self, start: int, stop: int, sel=None, step: int = 1):
        if self.transformations:
            # transformed reads must go through the generic
            # read-transform-gather loop (ReaderBase)
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        boxes = (None if self._dims is None
                 else self._dims[start:stop:step].copy())
        if sel is None:
            return self._coords[start:stop:step].copy(), boxes
        # slice + advanced index = a single gather copy
        return self._coords[start:stop:step, sel], boxes

    def stage_block(self, start: int, stop: int, sel=None,
                    quantize: bool = False, layout: str = "interleaved"):
        """Gather (+quantize) straight from the backing array in C++ —
        no intermediate ``read_block`` copy.  In-memory trajectories are
        the staging fast path (the reference's RMSF.py:113 in-memory
        universe generalized to the TPU feed), so this one fused pass is
        where the single staging core's cycles go.  Planar requests
        stage through the same fused gather, then one ``planar_repack``
        on the quantized bytes."""
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        from mdanalysis_mpi_tpu.io.base import norm_quantize, planar_repack

        if layout == "planar":
            if norm_quantize(quantize) is None:
                raise ValueError(
                    "layout='planar' requires quantized staging "
                    "(int16/int8); float32 blocks stay interleaved")
            q, boxes, inv_scale = self.stage_block(start, stop, sel=sel,
                                                   quantize=quantize)
            return planar_repack(q), boxes, inv_scale

        qmode = norm_quantize(quantize)
        if self.transformations:
            return ReaderBase.stage_block(self, start, stop, sel=sel,
                                          quantize=quantize)
        boxes = None if self._dims is None else self._dims[start:stop].copy()
        view = self._coords[start:stop]
        if qmode == "int8":
            # no fused native int8 kernel; quantize straight off the
            # backing view (no intermediate read_block copy)
            from mdanalysis_mpi_tpu.parallel.executors import quantize_block

            q, inv_scale = quantize_block(
                view if sel is None else view[:, sel], "int8")
            return q, boxes, inv_scale
        if qmode is not None:
            # adaptive one-pass gather+quantize (ReaderBase helper)
            q, inv_scale = self._quantize_staged(view, sel)
            return q, boxes, inv_scale
        try:
            from mdanalysis_mpi_tpu.io import native

            return native.stage_gather(view, sel), boxes, None
        except Exception:
            block = view[:, sel] if sel is not None else view.copy()
            return block, boxes, None
