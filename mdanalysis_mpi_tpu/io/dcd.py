"""DCD trajectory reader/writer over the native codec.

BASELINE config 1's format (PSF/DCD ADK trajectory).  CHARMM/NAMD
binary: Fortran record markers, fixed-size frames (so random access is
pure arithmetic — no offset index needed), optional per-frame unit cell.
Coordinates are already in Å.  Unit cell on disk is XTLABC order
``[A, gamma, B, beta, alpha, C]`` where angles may be stored as degrees
(NAMD) or cosines (CHARMM ≥22) — normalized on read with the standard
|x| ≤ 1 → cosine heuristic.
"""

from __future__ import annotations

import ctypes

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import native, trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase


def _cell_to_dimensions(cell: np.ndarray) -> np.ndarray:
    a, gamma, b, beta, alpha, c = (float(x) for x in cell)
    angles = []
    for ang in (alpha, beta, gamma):
        if -1.0 <= ang <= 1.0:
            ang = np.degrees(np.arccos(ang))
        angles.append(ang)
    return np.array([a, b, c] + angles, dtype=np.float32)


class DCDReader(ReaderBase):
    """Random-access DCD reader."""

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        lib = native.load()
        natoms = ctypes.c_int(-1)
        has_box = ctypes.c_int(0)
        first = ctypes.c_long(0)
        fbytes = ctypes.c_long(0)
        n = lib.dcd_scan(path.encode(), ctypes.byref(natoms),
                         ctypes.byref(has_box), ctypes.byref(first),
                         ctypes.byref(fbytes))
        if n < 0:
            raise IOError(f"cannot read DCD file {path!r} (error {n})")
        self._n_frames = int(n)
        self._natoms = natoms.value
        self._has_box = bool(has_box.value)
        self._first = first.value
        self._fbytes = fbytes.value
        self._lib = lib
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"DCD {path!r} has {self._natoms} atoms, expected {n_atoms}")

    @property
    def n_frames(self) -> int:
        return self._n_frames

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "DCDReader":
        return DCDReader(self._path)

    def _read_range(self, idx: np.ndarray):
        n = len(idx)
        coords = np.empty((n, self._natoms, 3), dtype=np.float32)
        box = (np.empty((n, 6), dtype=np.float64) if self._has_box else None)
        rc = self._lib.dcd_read_frames(
            self._path.encode(), np.ascontiguousarray(idx, np.int64), n,
            self._natoms, int(self._has_box), self._first, self._fbytes,
            coords,
            box.ctypes.data_as(ctypes.c_void_p) if box is not None else None)
        if rc != 0:
            raise IOError(f"DCD read failed for {self._path!r} (error {rc})")
        return coords, box

    def _read_frame(self, i: int) -> Timestep:
        coords, box = self._read_range(np.array([i]))
        dims = _cell_to_dimensions(box[0]) if box is not None else None
        return Timestep(coords[0], frame=i, time=float(i), dimensions=dims)

    def frame_times(self, frames) -> np.ndarray:
        # matches _read_frame's time=float(i) so transfer_to_memory
        # preserves exactly what direct reads report
        return np.asarray(list(frames), dtype=np.float64)

    def read_block(self, start: int, stop: int, sel=None, step: int = 1):
        if self.transformations:
            # transformed reads must go through the generic
            # read-transform-gather loop (ReaderBase)
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        if start == stop:
            n = self._natoms if sel is None else len(sel)
            return np.empty((0, n, 3), np.float32), None
        coords, box = self._read_range(np.arange(start, stop, step))
        if sel is not None:
            coords = np.ascontiguousarray(coords[:, sel])
        boxes = (np.stack([_cell_to_dimensions(b) for b in box])
                 if box is not None else None)
        return coords, boxes


def write_dcd(path: str, coordinates: np.ndarray,
              dimensions: np.ndarray | None = None, dt: float = 1.0) -> None:
    """Write (n_frames, n_atoms, 3) Å coordinates as DCD (NAMD-style:
    cell angles in degrees)."""
    coords = np.ascontiguousarray(coordinates, dtype=np.float32)
    if coords.ndim != 3 or coords.shape[2] != 3:
        raise ValueError(f"coordinates must be (F, N, 3), got {coords.shape}")
    nframes, natoms = coords.shape[:2]
    boxp = None
    if dimensions is not None:
        dims = np.asarray(dimensions, dtype=np.float64)
        if dims.ndim == 1:
            dims = np.broadcast_to(dims, (nframes, 6))
        cell = np.empty((nframes, 6), dtype=np.float64)
        cell[:, 0] = dims[:, 0]  # A
        cell[:, 1] = dims[:, 5]  # gamma
        cell[:, 2] = dims[:, 1]  # B
        cell[:, 3] = dims[:, 4]  # beta
        cell[:, 4] = dims[:, 3]  # alpha
        cell[:, 5] = dims[:, 2]  # C
        cell = np.ascontiguousarray(cell)
        boxp = cell.ctypes.data_as(ctypes.c_void_p)
    rc = native.load().dcd_write(path.encode(), natoms, nframes, coords,
                                 boxp, ctypes.c_double(dt))
    if rc != 0:
        raise IOError(f"DCD write failed for {path!r} (error {rc})")


trajectory_files.register("dcd", DCDReader)
