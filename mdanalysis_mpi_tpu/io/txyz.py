"""Tinker TXYZ/ARC files (upstream ``TXYZParser`` / ``TXYZReader``).

Layout per frame: a header line ``natom [title]``, optionally a
periodic-box line (6 floats — newer Tinker), then one line per atom:
``index name x y z type [bonded-indices...]`` — the trailing integers
are the 1-based bond list, giving TXYZ the rare property of carrying
BOTH coordinates and connectivity.  ``.arc`` archives repeat the
frame block; atoms/bonds come from the first frame.  Bond pairs are
deduplicated (each bond appears in both atoms' lists).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files, trajectory_files
from mdanalysis_mpi_tpu.io.memory import MemoryReader


def _is_box_line(t: list[str]) -> bool:
    """Box line = 6 floats whose last three are plausible cell angles.
    Disambiguation from an atom line: atom lines have a non-numeric
    name in field 2 and an integer index first — a 6-token line of
    pure numbers with angle-range fields 4-6 can only be a box (an
    atom line's fields 4-6 are x-coordinate/type/bond-index)."""
    if len(t) != 6:
        return False
    try:
        v = [float(x) for x in t]
    except ValueError:
        return False
    return all(0.0 < a < 180.0 for a in v[3:]) and all(
        x > 0 for x in v[:3])


def parse_txyz(path: str):
    """→ (Topology, frames (F, n, 3) f32, boxes (F, 6) or None)."""
    names: list[str] = []
    bonds: set[tuple[int, int]] = set()
    frames: list[np.ndarray] = []
    boxes: list[np.ndarray] = []
    with open(path) as fh:
        while True:
            header = fh.readline()
            if not header.strip():
                break
            t = header.split()
            try:
                natom = int(t[0])
            except ValueError as e:
                raise ValueError(
                    f"{path}: expected 'natom [title]' header, got "
                    f"{header!r}") from e
            first = not frames
            coords = np.empty((natom, 3), np.float32)
            i = 0
            while i < natom:
                ln = fh.readline()
                if not ln:
                    raise ValueError(
                        f"{path}: truncated frame ({i}/{natom} atoms)")
                t = ln.split()
                if i == 0 and _is_box_line(t):
                    # per-frame boxes (NPT archives change cell size)
                    boxes.append(np.asarray([float(x) for x in t],
                                            np.float32))
                    continue
                if len(t) < 5:
                    raise ValueError(
                        f"{path}: TXYZ atom line needs >= 5 fields: "
                        f"{ln!r}")
                coords[i] = (float(t[2]), float(t[3]), float(t[4]))
                if first:
                    names.append(t[1])
                    for b in t[6:]:
                        j = int(b) - 1
                        if j != i:
                            bonds.add((min(i, j), max(i, j)))
                i += 1
            frames.append(coords)
    if not frames:
        raise ValueError(f"{path!r} contains no frames")
    if any(len(f) != len(frames[0]) for f in frames):
        raise ValueError(f"{path!r}: frames differ in atom count")
    if boxes and len(boxes) != len(frames):
        raise ValueError(
            f"{path!r}: {len(boxes)} box lines for {len(frames)} "
            "frames (all frames or none)")
    box = np.stack(boxes) if boxes else None
    top = Topology(
        names=np.array(names),
        resnames=np.full(len(names), "MOL"),
        resids=np.ones(len(names), np.int64),
        bonds=(np.asarray(sorted(bonds), np.int64) if bonds else None))
    return top, np.stack(frames), box


def _parse_topology(path: str) -> Topology:
    top, frames, box = parse_txyz(path)
    top._coordinates = frames
    top._dimensions = box          # per-frame (F, 6) — NPT archives
    return top


def _open_trajectory(path: str, n_atoms: int | None = None) -> MemoryReader:
    _, frames, box = parse_txyz(path)
    if n_atoms is not None and frames.shape[1] != n_atoms:
        raise ValueError(
            f"{path} carries {frames.shape[1]} atoms, topology has "
            f"{n_atoms}")
    return MemoryReader(frames, dimensions=box)


def write_txyz(path: str, universe_or_group, frames=None) -> None:
    """Write frames (default: current) as TXYZ/ARC with bond lists."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    u = ag._universe
    top = u.topology
    idx = np.asarray(ag.indices)
    pos_map = {int(a): j for j, a in enumerate(idx)}
    neigh: dict[int, list[int]] = {j: [] for j in range(len(idx))}
    if top.bonds is not None:
        for a, b in np.asarray(top.bonds):
            if int(a) in pos_map and int(b) in pos_map:
                neigh[pos_map[int(a)]].append(pos_map[int(b)] + 1)
                neigh[pos_map[int(b)]].append(pos_map[int(a)] + 1)
    frame_list = ([u.trajectory.ts.frame] if frames is None
                  else list(frames))
    with open(path, "w") as fh:
        for f in frame_list:
            pos = u.trajectory[f].positions[idx]
            fh.write(f"{len(idx):6d}  mdanalysis_mpi_tpu\n")
            for j, i in enumerate(idx):
                nb = "".join(f"{v:6d}" for v in sorted(neigh[j]))
                fh.write(
                    f"{j + 1:6d}  {top.names[i]:<4s}"
                    f"{pos[j][0]:12.6f}{pos[j][1]:12.6f}"
                    f"{pos[j][2]:12.6f}{1:6d}{nb}\n")


topology_files.register("txyz", _parse_topology)
topology_files.register("arc", _parse_topology)
trajectory_files.register("arc", _open_trajectory)
trajectory_files.register("txyz", _open_trajectory)
