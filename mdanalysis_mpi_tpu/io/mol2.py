"""TRIPOS MOL2 topology/coordinate parser + writer (upstream
``MOL2Parser`` / ``MOL2Reader``/``MOL2Writer``).

Sections handled: ``@<TRIPOS>MOLECULE`` (repeated blocks become an
in-memory trajectory, upstream's multi-frame MOL2 semantics — atom
counts must agree), ``@<TRIPOS>ATOM`` (id name x y z sybyl_type
[subst_id [subst_name [charge]]]) and ``@<TRIPOS>BOND`` (bond graph —
order tokens ``1/2/3/am/ar/du/un/nc`` are accepted and discarded; the
Topology stores connectivity only, like the PSF path).  Elements
derive from the SYBYL type's element part (``C.3`` → C, ``N.ar`` → N);
``subst_name``/``subst_id`` map to resname/resid with the trailing
digits upstream strips (``ALA1`` → ALA) removed when the name embeds
the id.  Charges land on ``Topology.charges`` unless the charge type
is ``NO_CHARGES``.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files

_BOND_ORDERS = {"1", "2", "3", "am", "ar", "du", "un", "nc"}


def _split_sections(path: str):
    """Yield one dict of section-name → line-list per MOLECULE block."""
    current: dict[str, list[str]] | None = None
    section: list[str] | None = None
    with open(path) as fh:
        for raw in fh:
            ln = raw.rstrip("\n")
            if ln.startswith("#"):
                continue
            if ln.upper().startswith("@<TRIPOS>"):
                name = ln[9:].strip().upper()
                if name == "MOLECULE":
                    if current is not None:
                        yield current
                    current = {}
                if current is None:
                    raise ValueError(
                        f"{path}: section @<TRIPOS>{name} before any "
                        "MOLECULE record")
                section = current.setdefault(name, [])
                section_name = name
                continue
            # MOLECULE keeps blank lines: its records are POSITIONAL
            # (name / counts / mol_type / charge_type) and a blank
            # molecule name (common obabel output) must not shift the
            # charge-type line
            if section is not None and (ln.strip()
                                        or section_name == "MOLECULE"):
                section.append(ln)
    if current is not None:
        yield current


def parse_mol2(path: str) -> Topology:
    names, resnames, resids, elements, charges = [], [], [], [], []
    bonds: list[tuple[int, int]] = []
    frames: list[np.ndarray] = []
    no_charges = False
    for imol, mol in enumerate(_split_sections(path)):
        atoms = mol.get("ATOM")
        if not atoms:
            raise ValueError(
                f"{path}: MOLECULE block {imol} has no @<TRIPOS>ATOM")
        header = mol.get("MOLECULE", [])
        if imol == 0:
            # charge semantics come from block 0 (the block whose
            # charges are stored); later blocks' headers are not
            # consulted — a disagreeing charge_type cannot
            # retroactively null or fabricate charges
            no_charges = (len(header) >= 4 and
                          header[3].strip().upper() == "NO_CHARGES")
        coords = np.empty((len(atoms), 3), np.float32)
        for j, ln in enumerate(atoms):
            t = ln.split()
            if len(t) < 6:
                raise ValueError(
                    f"{path}: ATOM line needs >= 6 fields: {ln!r}")
            coords[j] = (float(t[2]), float(t[3]), float(t[4]))
            if imol == 0:
                name = t[1]
                sybyl = t[5]
                subst_id = int(t[6]) if len(t) > 6 else 1
                subst_name = t[7] if len(t) > 7 else "MOL"
                q = float(t[8]) if len(t) > 8 else 0.0
                names.append(name)
                resids.append(subst_id)
                # upstream strips the residue number glued to the
                # substructure name ONLY when it IS the subst_id
                # (ALA1 with subst_id 1 -> ALA; HIS2 with subst_id 1
                # stays HIS2)
                stripped = subst_name.rstrip("0123456789")
                resnames.append(
                    stripped if stripped
                    and stripped + str(subst_id) == subst_name
                    else subst_name)
                elements.append(sybyl.split(".")[0].capitalize())
                charges.append(q)
        if imol == 0:
            for ln in mol.get("BOND", []):
                t = ln.split()
                if len(t) < 4 or (t[3].lower() not in _BOND_ORDERS
                                  and not t[3].isdigit()):
                    raise ValueError(
                        f"{path}: unparseable BOND line {ln!r}")
                bonds.append((int(t[1]) - 1, int(t[2]) - 1))
        elif len(coords) != len(names):
            raise ValueError(
                f"{path}: MOLECULE block {imol} has {len(coords)} atoms, "
                f"first block has {len(names)} (multi-frame MOL2 needs "
                "identical molecules)")
        frames.append(coords)
    if not frames:
        raise ValueError(f"{path!r} contains no MOLECULE records")
    top = Topology(
        names=np.array(names), resnames=np.array(resnames),
        resids=np.array(resids), elements=np.array(elements),
        charges=None if no_charges else np.array(charges),
        bonds=np.asarray(bonds, np.int64) if bonds else None)
    top._coordinates = np.stack(frames)
    top._dimensions = None
    return top


def write_mol2(path: str, universe_or_group, frames=None) -> None:
    """Write one MOLECULE block per frame (current frame by default).

    Bonds internal to the written selection are emitted with order
    ``1`` (connectivity is what the Topology stores); charges default
    to 0 with ``NO_CHARGES`` declared when the topology has none."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    u = ag._universe
    top = u.topology
    idx = np.asarray(ag.indices)
    pos_map = {int(a): j for j, a in enumerate(idx)}
    sub_bonds = []
    if top.bonds is not None:
        for a, b in np.asarray(top.bonds):
            if int(a) in pos_map and int(b) in pos_map:
                sub_bonds.append((pos_map[int(a)], pos_map[int(b)]))
    charge_type = "NO_CHARGES" if top.charges is None else "USER_CHARGES"
    frame_list = ([u.trajectory.ts.frame] if frames is None
                  else list(frames))
    with open(path, "w") as fh:
        for f in frame_list:
            pos = u.trajectory[f].positions[idx]
            fh.write("@<TRIPOS>MOLECULE\n")
            fh.write("mdanalysis_mpi_tpu\n")
            fh.write(f"{len(idx)} {len(sub_bonds)} "
                     f"{len(np.unique(top.resindices[idx]))} 0 0\n")
            fh.write("SMALL\n")
            fh.write(f"{charge_type}\n")
            fh.write("@<TRIPOS>ATOM\n")
            for j, i in enumerate(idx, 1):
                el = str(top.elements[i]) or "Du"
                q = 0.0 if top.charges is None else float(top.charges[i])
                fh.write(
                    f"{j:7d} {top.names[i]:<6s} "
                    f"{pos[j - 1][0]:11.4f} {pos[j - 1][1]:11.4f} "
                    f"{pos[j - 1][2]:11.4f} {el:<5s} "
                    f"{int(top.resids[i]):5d} "
                    f"{top.resnames[i]:<6s} "
                    f"{q:9.4f}\n")
            fh.write("@<TRIPOS>BOND\n")
            for k, (a, b) in enumerate(sub_bonds, 1):
                fh.write(f"{k:6d} {a + 1:6d} {b + 1:6d} 1\n")


topology_files.register("mol2", parse_mol2)
