"""Trajectory file-format registry: path → Reader.

Dispatch point for ``Universe(top, "traj.xtc")`` (RMSF.py:56 analog).
Formats register via :func:`register`; readers live in sibling modules
(``xtc``, ``dcd``).
"""

from __future__ import annotations

import os

_READERS: dict[str, callable] = {}


def register(extension: str, opener) -> None:
    """Register ``opener(path, n_atoms=...) -> ReaderBase``."""
    _READERS[extension.lower().lstrip(".")] = opener


def open(path: str, n_atoms: int | None = None):
    if isinstance(path, str) and \
            path.startswith(("http://", "https://")):
        # a remote chunk-store URL (docs/STORE.md "Remote backend")
        # opens wherever a trajectory path is accepted, exactly like
        # the store-directory branch below — a fleet job spec's
        # trajectory can be "http://host:port/stores/NAME?mirror=..."
        # and every read rides the hardened HTTP boundary
        from mdanalysis_mpi_tpu.io.store.remote import open_remote_store

        return open_remote_store(path, n_atoms=n_atoms)
    if os.path.isdir(path):
        # an ingested block store (docs/STORE.md) opens wherever a
        # trajectory path is accepted — Universe(top, store_dir),
        # batch/fleet job specs — so "prefer the store" is just a
        # path swap for every caller.  Opened directly (no is_store
        # pre-sniff: that would parse the O(chunks) manifest twice);
        # a CORRUPT manifest surfaces as its typed StoreCorruptError.
        from mdanalysis_mpi_tpu.io.store import StoreReader

        try:
            return StoreReader(path, n_atoms=n_atoms)
        except FileNotFoundError as exc:
            raise ValueError(
                f"{path!r} is a directory but not an ingested block "
                f"store (no valid manifest.json); run "
                f"`python -m mdanalysis_mpi_tpu ingest` first") from exc
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    if not ext:
        # extensionless conventions (DL_POLY's HISTORY): the basename
        # IS the format name
        ext = os.path.basename(path).lower()
    _autoload()
    opener = _READERS.get(ext)
    if opener is None:
        known = ", ".join(sorted(_READERS)) or "(none)"
        raise ValueError(
            f"no trajectory reader for {ext!r} ({path}); known formats: {known}")
    return opener(path, n_atoms=n_atoms)


def _unavailable(fmt: str, why: str, recipe: str):
    def opener(path: str, n_atoms=None):
        raise ValueError(
            f"{fmt} files are not supported ({path}): {why}. {recipe}")

    return opener


# H5MD/GSD/TNG are closed by decision (registered with loud guidance
# in _autoload): their container libraries (h5py / gsd / pytng) are
# not in this environment, and a from-scratch binary container parser
# validated only against self-written bytes would be circular — the
# TPR rationale (io/topology_files.py:_tpr).
_autoloaded = False


def _autoload():
    """Guarded by a flag, not ``_READERS`` truthiness: a format module
    imported directly (e.g. ``from ...io.inpcrd import read_inpcrd``)
    self-registers before the first ``open`` call, which must not
    suppress the remaining registrations (same fix as
    topology_files)."""
    global _autoloaded
    if _autoloaded:
        return
    _autoloaded = True
    # pure-NumPy modules: an ImportError from them is always a
    # programming error and must surface, unlike the native-backed
    # xtc/dcd modules
    from mdanalysis_mpi_tpu.io import (  # noqa: F401  (self-register)
        dlpoly, inpcrd, lammps, mdcrd, netcdf, trr, txyz, xyz)
    register("h5md", _unavailable(
        "H5MD", "the HDF5 container needs h5py, which is not installed",
        "convert once with MDAnalysis/mdconvert on a machine with "
        "h5py and open the XTC/DCD/NetCDF here"))
    register("h5", _unavailable(
        "H5MD", "the HDF5 container needs h5py, which is not installed",
        "convert once with MDAnalysis/mdconvert on a machine with "
        "h5py and open the XTC/DCD/NetCDF here"))
    register("gsd", _unavailable(
        "GSD", "the HOOMD container needs the gsd package",
        "convert once via MDAnalysis/gsd elsewhere, or write "
        "LAMMPS-style dumps which read natively here"))
    register("tng", _unavailable(
        "TNG", "GROMACS' TNG container needs pytng",
        "convert once with 'gmx trjconv -f traj.tng -o traj.xtc' and "
        "open the XTC here"))
    register("trz", _unavailable(
        "TRZ", "the IBIsCO/YASP binary layout has no public spec to "
        "validate a from-scratch reader against in this offline "
        "environment (a reader checked only against self-written bytes "
        "would be circular — the TPR rationale)",
        "convert once with MDAnalysis elsewhere ('mda.Writer' to "
        "XTC/DCD) and open the result here"))
    try:
        from mdanalysis_mpi_tpu.io import xtc, dcd  # noqa: F401  (self-register)
    except ImportError:
        pass
