"""Trajectory file-format registry: path → Reader.

Dispatch point for ``Universe(top, "traj.xtc")`` (RMSF.py:56 analog).
Formats register via :func:`register`; readers live in sibling modules
(``xtc``, ``dcd``).
"""

from __future__ import annotations

import os

_READERS: dict[str, callable] = {}


def register(extension: str, opener) -> None:
    """Register ``opener(path, n_atoms=...) -> ReaderBase``."""
    _READERS[extension.lower().lstrip(".")] = opener


def open(path: str, n_atoms: int | None = None):
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    _autoload()
    opener = _READERS.get(ext)
    if opener is None:
        known = ", ".join(sorted(_READERS)) or "(none)"
        raise ValueError(
            f"no trajectory reader for {ext!r} ({path}); known formats: {known}")
    return opener(path, n_atoms=n_atoms)


def _autoload():
    if _READERS:
        return
    # trr/netcdf are pure NumPy: an ImportError from them is always a
    # programming error and must surface, unlike the native-backed
    # xtc/dcd modules
    from mdanalysis_mpi_tpu.io import (  # noqa: F401  (self-register)
        lammps, netcdf, trr, xyz)
    try:
        from mdanalysis_mpi_tpu.io import xtc, dcd  # noqa: F401  (self-register)
    except ImportError:
        pass
