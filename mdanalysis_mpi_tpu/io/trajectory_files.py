"""Trajectory file-format registry: path → Reader.

Dispatch point for ``Universe(top, "traj.xtc")`` (RMSF.py:56 analog).
Formats register via :func:`register`; readers live in sibling modules
(``xtc``, ``dcd``).
"""

from __future__ import annotations

import os

_READERS: dict[str, callable] = {}


def register(extension: str, opener) -> None:
    """Register ``opener(path, n_atoms=...) -> ReaderBase``."""
    _READERS[extension.lower().lstrip(".")] = opener


def open(path: str, n_atoms: int | None = None):
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    _autoload()
    opener = _READERS.get(ext)
    if opener is None:
        known = ", ".join(sorted(_READERS)) or "(none)"
        raise ValueError(
            f"no trajectory reader for {ext!r} ({path}); known formats: {known}")
    return opener(path, n_atoms=n_atoms)


_autoloaded = False


def _autoload():
    """Guarded by a flag, not ``_READERS`` truthiness: a format module
    imported directly (e.g. ``from ...io.inpcrd import read_inpcrd``)
    self-registers before the first ``open`` call, which must not
    suppress the remaining registrations (same fix as
    topology_files)."""
    global _autoloaded
    if _autoloaded:
        return
    _autoloaded = True
    # pure-NumPy modules: an ImportError from them is always a
    # programming error and must surface, unlike the native-backed
    # xtc/dcd modules
    from mdanalysis_mpi_tpu.io import (  # noqa: F401  (self-register)
        inpcrd, lammps, mdcrd, netcdf, trr, txyz, xyz)
    try:
        from mdanalysis_mpi_tpu.io import xtc, dcd  # noqa: F401  (self-register)
    except ImportError:
        pass
