"""LAMMPS dump trajectory format (upstream ``coordinates.LAMMPS``,
``.lammpsdump``/``.dump``).

The text format: per frame, ``ITEM: TIMESTEP`` / ``ITEM: NUMBER OF
ATOMS`` / ``ITEM: BOX BOUNDS`` / ``ITEM: ATOMS <columns>`` blocks.
Dumps are frequently UNORDERED (atom rows in arbitrary order), so rows
are sorted by the ``id`` column before they become positions — the
silent-misorder hazard upstream also guards.

Coordinate variants (the ATOMS header declares which): ``x y z``
(wrapped), ``xu yu zu`` (unwrapped — used as-is), ``xs ys zs`` (scaled
— mapped through the box: ``x = xlo + xs·lx``).  Orthogonal boxes
only; triclinic ``BOX BOUNDS xy xz yz`` dumps refuse loudly rather
than silently mis-converting the tilt.

Random access via the shared mtime-validated offset cache (the text
format has no seek table), like XYZ/TRR.
"""

from __future__ import annotations

import os

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import _offsets, trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase


def _scan(path: str):
    """Byte offset of each ``ITEM: TIMESTEP`` + the atom count."""
    cached = _offsets.load(path)
    if cached is not None:
        return cached
    mtime = os.path.getmtime(path)
    offsets = []
    n_atoms = None
    with open(path, "rb") as f:
        pos = 0
        line = f.readline()
        while line:
            if line.startswith(b"ITEM: TIMESTEP"):
                offsets.append(pos)
            elif line.startswith(b"ITEM: NUMBER OF ATOMS"):
                count = int(f.readline())
                if n_atoms is None:
                    n_atoms = count
                elif count != n_atoms:
                    raise ValueError(
                        f"{path!r}: frame {len(offsets) - 1} has "
                        f"{count} atoms, previous frames {n_atoms}")
            pos = f.tell()
            line = f.readline()
    if not offsets or n_atoms is None:
        raise ValueError(f"{path!r}: no LAMMPS dump frames found")
    offsets = np.asarray(offsets, np.int64)
    _offsets.save(path, offsets, n_atoms, mtime)
    return offsets, n_atoms


class LAMMPSDumpReader(ReaderBase):
    """Random-access LAMMPS dump reader (id-sorted positions, Å
    assumed — LAMMPS units are simulation-defined, upstream caveat)."""

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        self._offsets, self._natoms = _scan(path)
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"LAMMPS dump {path!r} has {self._natoms} atoms, "
                f"expected {n_atoms}")
        self._file = open(path, "rb")

    @property
    def n_frames(self) -> int:
        return len(self._offsets)

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "LAMMPSDumpReader":
        return LAMMPSDumpReader(self._path)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _read_frame(self, i: int) -> Timestep:
        if not 0 <= i < len(self._offsets):
            raise IndexError(
                f"frame {i} out of range [0, {len(self._offsets)})")
        f = self._file
        f.seek(self._offsets[i])
        line = f.readline()
        if not line.startswith(b"ITEM: TIMESTEP"):
            # explicit raise, never assert: the readline is a file-
            # advancing side effect that python -O must not strip
            raise ValueError(
                f"{self._path!r}: frame {i} offset does not start at "
                "ITEM: TIMESTEP (stale index? delete the offset cache)")
        step = int(f.readline())
        line = f.readline()
        if not line.startswith(b"ITEM: NUMBER OF ATOMS"):
            raise ValueError(
                f"{self._path!r}: frame {i} lacks NUMBER OF ATOMS")
        n = int(f.readline())
        line = f.readline()
        if not line.startswith(b"ITEM: BOX BOUNDS"):
            raise ValueError(
                f"{self._path!r}: frame {i} lacks BOX BOUNDS")
        if b"xy" in line or b"xz" in line or b"yz" in line:
            raise ValueError(
                f"{self._path!r}: triclinic BOX BOUNDS (tilt factors) "
                "are not supported — orthogonal dumps only")
        bounds = np.array([[float(v) for v in f.readline().split()[:2]]
                           for _ in range(3)])
        lengths = bounds[:, 1] - bounds[:, 0]
        line = f.readline()
        if not line.startswith(b"ITEM: ATOMS"):
            raise ValueError(
                f"{self._path!r}: frame {i} lacks an ATOMS block")
        cols = line.split()[2:]
        cols = [c.decode() for c in cols]

        if "id" not in cols:
            raise ValueError(
                f"{self._path!r}: ATOMS block has no id column "
                f"(columns: {cols}) — unordered rows cannot be placed")
        id_col = cols.index("id")

        def cols3(names):
            js = [cols.index(c) for c in names if c in cols]
            return js if len(js) == 3 else None

        mode, idx = "plain", cols3(("x", "y", "z"))
        if idx is None:
            mode, idx = "unwrapped", cols3(("xu", "yu", "zu"))
        if idx is None:
            mode, idx = "scaled", cols3(("xs", "ys", "zs"))
        if idx is None:
            raise ValueError(
                f"{self._path!r}: ATOMS columns {cols} carry no "
                "x y z / xu yu zu / xs ys zs coordinates")
        ids = np.empty(n, np.int64)
        xyz = np.empty((n, 3), np.float64)
        for a in range(n):
            parts = f.readline().split()
            if len(parts) < len(cols):
                raise ValueError(
                    f"{self._path!r}: truncated ATOMS row in frame {i}")
            ids[a] = int(parts[id_col])
            xyz[a] = [float(parts[j]) for j in idx]
        order = np.argsort(ids, kind="stable")
        xyz = xyz[order]
        if mode == "scaled":
            xyz = bounds[:, 0] + xyz * lengths
        dims = np.array([lengths[0], lengths[1], lengths[2],
                         90.0, 90.0, 90.0], np.float32)
        return Timestep(xyz.astype(np.float32), frame=i,
                        time=float(step), dimensions=dims)


def write_lammpsdump(path: str, frames: np.ndarray, dimensions=None,
                     steps=None, mode: str = "w") -> None:
    """Write (F, N, 3) coordinates as an orthogonal LAMMPS dump
    (``id type x y z`` rows, ids 1..N)."""
    frames = np.asarray(frames, np.float64)
    if frames.ndim != 3 or frames.shape[2] != 3:
        raise ValueError(f"frames must be (F, N, 3), got {frames.shape}")
    f_count, n, _ = frames.shape
    if dimensions is None:
        lo = frames.min(axis=(0, 1)) - 1.0
        hi = frames.max(axis=(0, 1)) + 1.0
    else:
        dimensions = np.asarray(dimensions, np.float64).reshape(6)
        if not np.all(np.abs(dimensions[3:] - 90.0) < 1e-6):
            raise ValueError(
                "write_lammpsdump supports orthogonal boxes only")
        lo = np.zeros(3)
        hi = dimensions[:3]
    if steps is None:
        steps = np.arange(f_count)
    with open(path, mode) as out:
        for f, frame in enumerate(frames):
            out.write("ITEM: TIMESTEP\n")
            out.write(f"{int(steps[f])}\n")
            out.write("ITEM: NUMBER OF ATOMS\n")
            out.write(f"{n}\n")
            out.write("ITEM: BOX BOUNDS pp pp pp\n")
            for d in range(3):
                out.write(f"{lo[d]:.6f} {hi[d]:.6f}\n")
            out.write("ITEM: ATOMS id type x y z\n")
            for a, (x, y, z) in enumerate(frame, start=1):
                out.write(f"{a} 1 {x:.6f} {y:.6f} {z:.6f}\n")


trajectory_files.register("lammpsdump", LAMMPSDumpReader)
trajectory_files.register("dump", LAMMPSDumpReader)
trajectory_files.register("lammpstrj", LAMMPSDumpReader)
