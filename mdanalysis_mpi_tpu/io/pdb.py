"""PDB topology/coordinate parser + writer (fixed-column ATOM/HETATM
records, CRYST1 box; multi-MODEL files yield an in-memory trajectory).
Convenience format beyond the reference's GRO/XTC surface."""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files


def parse_pdb(path: str) -> Topology:
    names, resnames, segids, elements = [], [], [], []
    resids = []
    frames: list[list[list[float]]] = []
    current: list[list[float]] = []
    dims = None
    first_model_done = False
    with open(path) as fh:
        for ln in fh:
            rec = ln[:6]
            if rec == "CRYST1":
                dims = np.array([float(ln[6:15]), float(ln[15:24]),
                                 float(ln[24:33]), float(ln[33:40]),
                                 float(ln[40:47]), float(ln[47:54])],
                                dtype=np.float32)
            elif rec in ("ATOM  ", "HETATM"):
                current.append([float(ln[30:38]), float(ln[38:46]),
                                float(ln[46:54])])
                if not first_model_done:
                    names.append(ln[12:16].strip())
                    resnames.append(ln[17:21].strip())
                    resids.append(int(ln[22:26]))
                    chain = ln[21].strip()
                    segid = ln[72:76].strip() if len(ln) > 72 else ""
                    segids.append(segid or chain or "SYSTEM")
                    el = ln[76:78].strip() if len(ln) > 76 else ""
                    elements.append(el.upper())
            elif rec.startswith("ENDMDL"):
                if current:
                    frames.append(current)
                    current = []
                    first_model_done = True
    if current:
        frames.append(current)
    if not frames:
        raise ValueError(f"PDB file {path!r} contains no ATOM records")
    n = len(frames[0])
    if any(len(f) != n for f in frames):
        raise ValueError(f"PDB file {path!r}: models differ in atom count")
    top = Topology(
        names=np.array(names), resnames=np.array(resnames),
        resids=np.array(resids), segids=np.array(segids),
        elements=np.array(elements) if any(elements) else None)
    top._coordinates = np.asarray(frames, dtype=np.float32)
    top._dimensions = dims
    return top


def write_pdb(path: str, topology: Topology, coordinates: np.ndarray,
              dimensions: np.ndarray | None = None) -> None:
    """Write Å coordinates ((N,3) or (F,N,3) → MODEL records) as PDB."""
    coords = np.asarray(coordinates, dtype=np.float64)
    if coords.ndim == 2:
        coords = coords[None]
    t = topology
    with open(path, "w") as fh:
        if dimensions is not None:
            d = np.asarray(dimensions)
            fh.write("CRYST1%9.3f%9.3f%9.3f%7.2f%7.2f%7.2f P 1           1\n"
                     % tuple(d[:6]))
        multi = coords.shape[0] > 1
        for f in range(coords.shape[0]):
            if multi:
                fh.write("MODEL     %4d\n" % (f + 1))
            for i in range(t.n_atoms):
                name = t.names[i][:4]
                fh.write(
                    "ATOM  %5d %-4s%1s%-4s%1s%4d%1s   %8.3f%8.3f%8.3f%6.2f%6.2f      %-4s%2s\n"
                    % ((i + 1) % 100000, name, "", t.resnames[i][:4], "",
                       t.resids[i] % 10000, "",
                       coords[f, i, 0], coords[f, i, 1], coords[f, i, 2],
                       1.0, 0.0, t.segids[i][:4], t.elements[i][:2]))
            if multi:
                fh.write("ENDMDL\n")
        fh.write("END\n")


topology_files.register("pdb", parse_pdb)
