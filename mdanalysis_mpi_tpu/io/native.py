"""ctypes loader for the native trajio codec (libtrajio.so).

Builds the shared object on first use (g++ via the adjacent Makefile —
the toolchain is a baked-in dependency of this framework's environment;
SURVEY.md §7 layer 2 calls for C++ where the reference's I/O stack is
native).  pybind11 is unavailable here, hence the plain C ABI + ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SO = os.path.join(_DIR, "libtrajio.so")
_SRC = os.path.join(_DIR, "trajio.cpp")

_lock = threading.Lock()
_lib = None

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_i16p = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build():
    try:
        subprocess.run(
            ["make", "-s", "-C", _DIR],
            check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise RuntimeError(
            f"failed to build native trajio library in {_DIR}: {detail}"
        ) from e


def load() -> ctypes.CDLL:
    """Load (building if needed) the native library; thread-safe."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_SO)

        lib.xtc_scan.restype = ctypes.c_long
        lib.xtc_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.c_void_p, ctypes.c_long]

        lib.xtc_read_frames.restype = ctypes.c_int
        lib.xtc_read_frames.argtypes = [
            ctypes.c_char_p, _i64p, ctypes.c_long, ctypes.c_int,
            _f32p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]

        lib.xtc_stage_i16.restype = ctypes.c_int
        lib.xtc_stage_i16.argtypes = [
            ctypes.c_char_p, _i64p, ctypes.c_long, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_long, ctypes.c_float, _i16p,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]

        lib.xtc_stage_f32.restype = ctypes.c_int
        lib.xtc_stage_f32.argtypes = [
            ctypes.c_char_p, _i64p, ctypes.c_long, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_long, _f32p, ctypes.c_void_p]

        lib.xtc_write.restype = ctypes.c_int
        lib.xtc_write.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_long, _f32p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_float]

        lib.dcd_scan.restype = ctypes.c_long
        lib.dcd_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long)]

        lib.dcd_read_frames.restype = ctypes.c_int
        lib.dcd_read_frames.argtypes = [
            ctypes.c_char_p, _i64p, ctypes.c_long, ctypes.c_int,
            ctypes.c_int, ctypes.c_long, ctypes.c_long, _f32p,
            ctypes.c_void_p]

        lib.dcd_write.restype = ctypes.c_int
        lib.dcd_write.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_long, _f32p,
            ctypes.c_void_p, ctypes.c_double]

        lib.stage_gather_quantize_i16.restype = ctypes.c_int
        lib.stage_gather_quantize_i16.argtypes = [
            _f32p, ctypes.c_long, ctypes.c_long, ctypes.c_void_p,
            ctypes.c_long, _i16p, ctypes.POINTER(ctypes.c_float)]

        lib.stage_gather_quantize_i16_scaled.restype = ctypes.c_int
        lib.stage_gather_quantize_i16_scaled.argtypes = [
            _f32p, ctypes.c_long, ctypes.c_long, ctypes.c_void_p,
            ctypes.c_long, ctypes.c_float, _i16p,
            ctypes.POINTER(ctypes.c_float)]

        lib.stage_gather_f32.restype = ctypes.c_int
        lib.stage_gather_f32.argtypes = [
            _f32p, ctypes.c_long, ctypes.c_long, ctypes.c_void_p,
            ctypes.c_long, _f32p]

        lib.qcp_superpose_apply.restype = ctypes.c_int
        lib.qcp_superpose_apply.argtypes = [
            _f32p, ctypes.c_long, _i64p, ctypes.c_long, _f64p,
            _f64p, _f64p, _f64p, ctypes.c_void_p]

        lib.qcp_superpose_moments.restype = ctypes.c_int
        lib.qcp_superpose_moments.argtypes = [
            _f32p, ctypes.c_long, _i64p, ctypes.c_long, _f64p,
            _f64p, _f64p, ctypes.c_long, _f64p, _f64p]

        _lib = lib
        return _lib


def stage_gather_quantize(src: np.ndarray, sel=None):
    """Fused selection-gather + block int16 quantization in C++.

    ``src`` is (B, N, 3) float32 C-contiguous; ``sel`` an int array into
    the atom axis or None for all atoms.  Returns (q (B, S, 3) int16,
    inv_scale float32) — this exact-scale kernel is bit-identical to
    ``parallel.executors.quantize_block(src[:, sel])`` (the adaptive
    one-pass ``stage_gather_quantize_scaled`` below is not: it trades
    exact per-block scales for half the memory traffic).
    """
    lib = load()
    b, n = src.shape[0], src.shape[1]
    if sel is None:
        s = n
        idx_p = None
    else:
        idx = np.ascontiguousarray(sel, dtype=np.int32)
        s = len(idx)
        idx_p = idx.ctypes.data_as(ctypes.c_void_p)
    out = np.empty((b, s, 3), dtype=np.int16)
    inv = ctypes.c_float(0.0)
    rc = lib.stage_gather_quantize_i16(
        src, b, n, idx_p, s, out, ctypes.byref(inv))
    if rc != 0:
        raise RuntimeError(f"stage_gather_quantize_i16 failed (rc={rc})")
    return out, np.float32(inv.value)


def stage_gather_quantize_scaled(src: np.ndarray, sel, scale: float):
    """One-pass fused gather + int16 quantize with a caller-provided
    ``scale`` (see trajio.cpp: halves the two-pass kernel's memory
    traffic).  Returns ``(q, max_abs, overflowed)``: ``overflowed`` True
    means the scale would have clipped real data — the caller must
    discard ``q`` and re-quantize with a scale from ``max_abs``.
    """
    lib = load()
    b, n = src.shape[0], src.shape[1]
    if sel is None:
        s = n
        idx_p = None
    else:
        idx = np.ascontiguousarray(sel, dtype=np.int32)
        s = len(idx)
        idx_p = idx.ctypes.data_as(ctypes.c_void_p)
    out = np.empty((b, s, 3), dtype=np.int16)
    vmax = ctypes.c_float(0.0)
    rc = lib.stage_gather_quantize_i16_scaled(
        src, b, n, idx_p, s, ctypes.c_float(scale), out, ctypes.byref(vmax))
    if rc < 0:
        raise RuntimeError(f"stage_gather_quantize_i16_scaled failed (rc={rc})")
    return out, float(vmax.value), rc == 1


def xtc_stage_i16(path: str, offsets: np.ndarray, natoms: int, sel,
                  scale: float, want_box: bool = True):
    """Fused XTC decode → selection gather → nm→Å → int16 quantize.

    Never materializes the full-system float32 block (each frame decodes
    into a cache-hot per-worker scratch; only the selection's int16
    bytes reach DRAM) — the cold-path staging kernel.  Returns
    ``(q (F, S, 3) int16, box (F, 9) nm float32 | None, max_abs_Å,
    overflowed)``; on ``overflowed`` the caller re-runs with an exact
    scale from ``max_abs_Å`` (same contract as
    :func:`stage_gather_quantize_scaled`).
    """
    lib = load()
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets)
    if sel is None:
        s = natoms
        idx_p = None
    else:
        idx = np.ascontiguousarray(sel, dtype=np.int32)
        s = len(idx)
        idx_p = idx.ctypes.data_as(ctypes.c_void_p)
    out = np.empty((n, s, 3), dtype=np.int16)
    box = np.empty((n, 9), dtype=np.float32) if want_box else None
    vmax = ctypes.c_float(0.0)
    rc = lib.xtc_stage_i16(
        path.encode(), offsets, n, natoms, idx_p, s,
        ctypes.c_float(scale), out,
        box.ctypes.data_as(ctypes.c_void_p) if want_box else None,
        ctypes.byref(vmax))
    if rc < 0:
        raise IOError(f"xtc_stage_i16 failed for {path!r} (rc={rc})")
    return out, box, float(vmax.value), rc == 1


def xtc_stage_f32(path: str, offsets: np.ndarray, natoms: int, sel,
                  want_box: bool = True):
    """Fused XTC decode → selection gather → nm→Å (float32 staging
    path); see :func:`xtc_stage_i16`.  Returns ``(coords (F, S, 3) Å,
    box (F, 9) nm | None)``."""
    lib = load()
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets)
    if sel is None:
        s = natoms
        idx_p = None
    else:
        idx = np.ascontiguousarray(sel, dtype=np.int32)
        s = len(idx)
        idx_p = idx.ctypes.data_as(ctypes.c_void_p)
    out = np.empty((n, s, 3), dtype=np.float32)
    box = np.empty((n, 9), dtype=np.float32) if want_box else None
    rc = lib.xtc_stage_f32(
        path.encode(), offsets, n, natoms, idx_p, s, out,
        box.ctypes.data_as(ctypes.c_void_p) if want_box else None)
    if rc != 0:
        raise IOError(f"xtc_stage_f32 failed for {path!r} (rc={rc})")
    return out, box


def stage_gather(src: np.ndarray, sel=None) -> np.ndarray:
    """Selection gather ``src[:, sel]`` in C++ (float32 staging path)."""
    lib = load()
    b, n = src.shape[0], src.shape[1]
    if sel is None:
        s = n
        idx_p = None
    else:
        idx = np.ascontiguousarray(sel, dtype=np.int32)
        s = len(idx)
        idx_p = idx.ctypes.data_as(ctypes.c_void_p)
    out = np.empty((b, s, 3), dtype=np.float32)
    rc = lib.stage_gather_f32(src, b, n, idx_p, s, out)
    if rc != 0:
        raise RuntimeError(f"stage_gather_f32 failed (rc={rc})")
    return out


def qcp_superpose_apply(coords: np.ndarray, sel: np.ndarray,
                        weights: np.ndarray, ref_c: np.ndarray,
                        ref_com: np.ndarray, want_rot: bool = False):
    """Native full-frame QCP superposition (see trajio.cpp).

    coords (N,3) f32 C-contiguous; returns aligned (N,3) f64 (and R
    (3,3) f64 when ``want_rot``) with the ops.host conventions.
    """
    import ctypes as _ct

    lib = load()
    n = coords.shape[0]
    sel = np.ascontiguousarray(sel, dtype=np.int64)
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    ref_c = np.ascontiguousarray(ref_c, dtype=np.float64)
    ref_com = np.ascontiguousarray(ref_com, dtype=np.float64)
    out = np.empty((n, 3), dtype=np.float64)
    rot = np.empty((3, 3), dtype=np.float64) if want_rot else None
    rc = lib.qcp_superpose_apply(
        coords, n, sel, len(sel), weights, ref_c, ref_com, out,
        rot.ctypes.data_as(_ct.c_void_p) if want_rot else None)
    if rc != 0:
        raise RuntimeError(f"qcp_superpose_apply failed (rc={rc})")
    return (out, rot) if want_rot else out


def qcp_superpose_moments(coords: np.ndarray, sel: np.ndarray,
                          weights: np.ndarray, ref_c: np.ndarray,
                          ref_com: np.ndarray, k: int,
                          mean: np.ndarray, m2: np.ndarray) -> None:
    """Native fused superpose-selection + streaming Welford update
    (mean/m2 (S,3) f64 updated in place; caller advances the count)."""
    lib = load()
    sel = np.ascontiguousarray(sel, dtype=np.int64)
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    ref_c = np.ascontiguousarray(ref_c, dtype=np.float64)
    ref_com = np.ascontiguousarray(ref_com, dtype=np.float64)
    rc = lib.qcp_superpose_moments(
        coords, coords.shape[0], sel, len(sel), weights, ref_c, ref_com,
        k, mean, m2)
    if rc != 0:
        raise RuntimeError(f"qcp_superpose_moments failed (rc={rc})")
