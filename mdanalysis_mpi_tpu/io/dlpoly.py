"""DL_POLY CONFIG / REVCON / HISTORY formats (upstream
``topology.DLPolyParser`` + ``coordinates.DLPoly``).

Text layouts (DL_POLY classic & 4, positions in Å)::

    CONFIG / REVCON
        title
        levcfg imcon [natoms]
        3 cell-vector lines (Å), when imcon > 0
        per atom, 2 + levcfg lines:
            name [index [atomic-number]]
            x y z
            vx vy vz          (levcfg >= 1, Å/ps)
            fx fy fz          (levcfg >= 2)

    HISTORY
        title
        levcfg imcon natoms [nframes [nrecords]]
        per frame:
            'timestep' nstep natoms levcfg imcon dt [time]
            3 cell-vector lines, when imcon > 0
            per atom: name [index [mass [charge]]], then coordinates
            (+ velocity/force lines per levcfg)

Conventions honored: atoms are re-ordered by their DL_POLY index when
every atom record carries one (the format permits arbitrary file
order); with any index missing, file order is kept.  Triclinic cells
go through the shared ``core.box`` vector↔box math.  Writers emit
CONFIG (levcfg 0) and HISTORY fixtures for the tests — round-trip
validated, SURVEY.md §4's offline-fixture strategy.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.box import box_to_vectors, vectors_to_box
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files, trajectory_files
from mdanalysis_mpi_tpu.io.memory import MemoryReader


def _read_cell(lines, i, path):
    if i + 3 > len(lines):
        raise ValueError(f"{path!r}: truncated cell block (imcon > 0 "
                         "requires 3 cell-vector lines)")
    vecs = np.array([[float(v) for v in lines[i + k].split()[:3]]
                     for k in range(3)], np.float64)
    return vectors_to_box(vecs), i + 3


def _atom_record(tok):
    """(name, index-or-None) from a DL_POLY atom record line."""
    name = tok[0]
    idx = None
    if len(tok) > 1:
        try:
            idx = int(tok[1])
        except ValueError:
            idx = None
    return name, idx


def parse_config(path: str) -> Topology:
    with open(path) as fh:
        lines = fh.read().splitlines()
    if len(lines) < 2:
        raise ValueError(f"DL_POLY CONFIG {path!r} too short")
    head = lines[1].split()
    if len(head) < 2:
        raise ValueError(
            f"DL_POLY CONFIG {path!r}: line 2 needs 'levcfg imcon'")
    levcfg, imcon = int(head[0]), int(head[1])
    if levcfg not in (0, 1, 2):
        raise ValueError(f"{path!r}: levcfg must be 0/1/2, got {levcfg}")
    declared = int(head[2]) if len(head) >= 3 else None
    i = 2
    dims = None
    if imcon > 0:
        dims, i = _read_cell(lines, i, path)
    per_atom = 2 + levcfg
    names, idxs, coords = [], [], []
    while i < len(lines):
        tok = lines[i].split()
        if not tok:
            break
        if i + per_atom > len(lines):
            raise ValueError(
                f"{path!r}: truncated atom record at line {i + 1} "
                f"(levcfg {levcfg} needs {per_atom} lines per atom)")
        name, idx = _atom_record(tok)
        names.append(name)
        idxs.append(idx)
        coords.append([float(v) for v in lines[i + 1].split()[:3]])
        i += per_atom
    n = len(names)
    if n == 0:
        raise ValueError(f"{path!r}: no atoms found")
    if declared is not None and n != declared:
        raise ValueError(
            f"{path!r}: header declares {declared} atoms, found {n} "
            "(truncated or corrupt file)")
    order = np.arange(n)
    if all(x is not None for x in idxs):
        order = np.argsort(np.asarray(idxs, np.int64), kind="stable")
    names_arr = np.asarray(names, "U8")[order]
    top = Topology(names=names_arr,
                   resnames=np.full(n, "SYS"),
                   resids=np.ones(n, np.int64))
    coords_arr = np.asarray(coords, np.float32)[order]
    top._coordinates = coords_arr[None]
    if dims is not None:
        top._dimensions = np.asarray(dims, np.float32)
    return top


class HistoryReader(MemoryReader):
    """DL_POLY HISTORY as an in-memory trajectory (whole-file parse —
    HISTORY is a plain-text archive; the staging stack then serves it
    like any MemoryReader)."""

    def __init__(self, path: str, n_atoms: int | None = None):
        with open(path) as fh:
            lines = fh.read().splitlines()
        if len(lines) < 2:
            raise ValueError(f"DL_POLY HISTORY {path!r} too short")
        head = lines[1].split()
        if len(head) < 3:
            raise ValueError(
                f"{path!r}: line 2 needs 'levcfg imcon natoms'")
        levcfg, imcon, natoms = (int(head[0]), int(head[1]),
                                 int(head[2]))
        if n_atoms is not None and natoms != n_atoms:
            raise ValueError(
                f"{path!r} has {natoms} atoms; topology has {n_atoms}")
        per_atom = 2 + levcfg
        frames, boxes, times = [], [], []
        i = 2
        while i < len(lines):
            tok = lines[i].split()
            if not tok:
                break
            if tok[0].lower() != "timestep":
                raise ValueError(
                    f"{path!r}: expected 'timestep' record at line "
                    f"{i + 1}, got {lines[i]!r}")
            if len(tok) >= 7:
                times.append(float(tok[6]))
            elif len(tok) >= 6:
                # nstep * dt when no explicit time column
                times.append(int(tok[1]) * float(tok[5]))
            i += 1
            dims = None
            if imcon > 0:
                dims, i = _read_cell(lines, i, path)
            coords = np.empty((natoms, 3), np.float32)
            idxs: list = []
            for a in range(natoms):
                if i + 1 >= len(lines):
                    raise ValueError(
                        f"{path!r}: truncated frame {len(frames)} at "
                        f"atom {a}")
                _, idx = _atom_record(lines[i].split())
                idxs.append(idx)
                coords[a] = [float(v) for v in lines[i + 1].split()[:3]]
                i += per_atom
            if all(x is not None for x in idxs):
                order = np.argsort(np.asarray(idxs, np.int64),
                                   kind="stable")
                coords = coords[order]
            frames.append(coords)
            boxes.append(dims)
        if not frames:
            raise ValueError(f"{path!r}: no frames found")
        have_box = boxes[0] is not None
        if any((b is not None) != have_box for b in boxes):
            raise ValueError(f"{path!r}: inconsistent cell records")
        dims_arr = (np.asarray(boxes, np.float32) if have_box else None)
        super().__init__(np.stack(frames), dimensions=dims_arr,
                         times=(np.asarray(times, np.float64)
                                if len(times) == len(frames) else None))
        self._path = path


def write_config(path: str, topology: Topology, coordinates: np.ndarray,
                 dimensions=None, title: str = "mdanalysis_mpi_tpu"
                 ) -> None:
    """CONFIG writer (levcfg 0): fixture generator + export path."""
    coords = np.asarray(coordinates, np.float64)
    n = topology.n_atoms
    if coords.shape != (n, 3):
        raise ValueError(f"coordinates must be ({n}, 3), got "
                         f"{coords.shape}")
    imcon = 0 if dimensions is None else 3
    with open(path, "w") as fh:
        fh.write(f"{title[:72]}\n")
        fh.write(f"{0:10d}{imcon:10d}{n:10d}\n")
        if dimensions is not None:
            for v in box_to_vectors(np.asarray(dimensions, np.float64)):
                fh.write(f"{v[0]:20.10f}{v[1]:20.10f}{v[2]:20.10f}\n")
        for a in range(n):
            fh.write(f"{topology.names[a]:<8s}{a + 1:10d}\n")
            fh.write(f"{coords[a, 0]:20.10f}{coords[a, 1]:20.10f}"
                     f"{coords[a, 2]:20.10f}\n")


def write_history(path: str, topology: Topology, frames: np.ndarray,
                  dimensions=None, dt: float = 1.0,
                  title: str = "mdanalysis_mpi_tpu") -> None:
    """HISTORY writer (levcfg 0) for fixtures/round-trips."""
    frames = np.asarray(frames, np.float64)
    n = topology.n_atoms
    if frames.ndim != 3 or frames.shape[1:] != (n, 3):
        raise ValueError(f"frames must be (F, {n}, 3), got "
                         f"{frames.shape}")
    imcon = 0 if dimensions is None else 3
    with open(path, "w") as fh:
        fh.write(f"{title[:72]}\n")
        fh.write(f"{0:10d}{imcon:10d}{n:10d}{len(frames):10d}\n")
        for f, frame in enumerate(frames):
            fh.write(f"timestep{f + 1:10d}{n:10d}{0:10d}{imcon:10d}"
                     f"{dt:12.6f}{(f + 1) * dt:12.4f}\n")
            if dimensions is not None:
                d = np.asarray(dimensions, np.float64)
                d = d[f] if d.ndim == 2 else d
                for v in box_to_vectors(d):
                    fh.write(f"{v[0]:20.10f}{v[1]:20.10f}"
                             f"{v[2]:20.10f}\n")
            for a in range(n):
                fh.write(f"{topology.names[a]:<8s}{a + 1:10d}\n")
                fh.write(f"{frame[a, 0]:20.10f}{frame[a, 1]:20.10f}"
                         f"{frame[a, 2]:20.10f}\n")


topology_files.register("config", parse_config)
topology_files.register("revcon", parse_config)
trajectory_files.register("history", HistoryReader)
