"""GROMACS ITP / TOP topology parser (upstream ``ITPParser``).

The GROMACS side of what PRMTOP gives AMBER users: GRO coordinate
files carry no masses/charges/bonds — those live in the ``.top`` /
``.itp`` force-field topology.  ``Universe("topol.top", "md.xtc")``
builds the full system: every ``[moleculetype]``'s ``[atoms]`` /
``[bonds]`` / ``[settles]`` / ``[constraints]`` / ``[angles]`` /
``[dihedrals]`` blocks are collected (dihedral function types 2/4
land in ``impropers``, the GROMACS convention),
``#include`` lines are resolved relative to the including file (a
missing include — e.g. a force-field file living in a GROMACS install
this environment doesn't have — fails loudly with the remedy), and
the ``[molecules]`` section replicates each molecule
by its count into one concatenated
:class:`~mdanalysis_mpi_tpu.core.topology.Topology`.

Preprocessor handling is the deliberate subset real topologies use:
``#include``, ``#define NAME`` (flags collected; ``-DPOSRES``-style
values via the ``defines`` argument), ``#ifdef``/``#ifndef``/
``#else``/``#endif`` (nesting supported).  ``settles`` and
``constraints`` become bonds (connectivity is what the Topology
stores, same policy as MOL2).  A bare ``.itp`` without ``[molecules]``
yields one copy of its (single) moleculetype, upstream's behavior.
"""

from __future__ import annotations

import os

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology, concatenate
from mdanalysis_mpi_tpu.io import topology_files


class _Molecule:
    def __init__(self, name):
        self.name = name
        self.names: list[str] = []
        self.resids: list[int] = []
        self.resnames: list[str] = []
        self.charges: list[float] = []
        self.masses: list[float] = []
        self.bonds: list[tuple[int, int]] = []
        self.angles: list[tuple[int, int, int]] = []
        self.dihedrals: list[tuple[int, int, int, int]] = []
        self.impropers: list[tuple[int, int, int, int]] = []


def _iter_lines(path: str, defines: set, stack=()):
    """Yield (path, lineno, line) with includes resolved and
    ifdef/ifndef blocks evaluated against ``defines``."""
    if path in stack:
        raise ValueError(f"#include cycle at {path}")
    cond: list[bool] = []          # active-block stack
    with open(path) as fh:
        lineno = 0
        for lineno, raw in enumerate(fh, 1):
            ln = raw.split(";", 1)[0].rstrip()
            if not ln.strip():
                continue
            t = ln.split()
            if t[0] == "#ifdef":
                cond.append(t[1] in defines)
                continue
            if t[0] == "#ifndef":
                cond.append(t[1] not in defines)
                continue
            if t[0] == "#else":
                if not cond:
                    raise ValueError(f"{path}:{lineno}: #else without #ifdef")
                cond[-1] = not cond[-1]
                continue
            if t[0] == "#endif":
                if not cond:
                    raise ValueError(f"{path}:{lineno}: #endif without #ifdef")
                cond.pop()
                continue
            if not all(cond):
                continue
            if t[0] == "#define":
                defines.add(t[1])
                continue
            if t[0] == "#include":
                inc = t[1].strip('"<>')
                target = os.path.join(os.path.dirname(path), inc)
                if not os.path.exists(target):
                    raise FileNotFoundError(
                        f"{path}:{lineno}: #include {inc!r} not found "
                        f"next to the including file; GROMACS "
                        "force-field includes must be copied alongside "
                        "the topology (no GROMACS share/ directory in "
                        "this environment)")
                yield from _iter_lines(target, defines, stack + (path,))
                continue
            yield path, lineno, ln
    if cond:
        raise ValueError(
            f"{path}: {len(cond)} unterminated #ifdef/#ifndef at end "
            "of file (missing #endif) — refusing a silently truncated "
            "topology")


def parse_itp(path: str, defines=()) -> Topology:
    defines = set(defines)
    molecules: dict[str, _Molecule] = {}
    current: _Molecule | None = None
    section = None
    system_mols: list[tuple[str, int]] = []
    for src, lineno, ln in _iter_lines(path, defines):
        s = ln.strip()
        if s.startswith("["):
            section = s.strip("[] \t").lower()
            continue
        t = s.split()
        if section == "moleculetype":
            if t[0] in molecules:
                raise ValueError(
                    f"{src}:{lineno}: [moleculetype] {t[0]!r} is "
                    "redefined (same .itp included twice, or two "
                    "files defining it) — GROMACS grompp refuses "
                    "this too")
            current = _Molecule(t[0])
            molecules[t[0]] = current
        elif section == "atoms":
            if current is None:
                raise ValueError(
                    f"{src}:{lineno}: [atoms] outside [moleculetype]")
            # nr type resnr residue atom cgnr [charge [mass]]
            if len(t) < 5:
                raise ValueError(
                    f"{src}:{lineno}: [atoms] line needs >= 5 fields: "
                    f"{s!r}")
            current.resids.append(int(t[2]))
            current.resnames.append(t[3])
            current.names.append(t[4])
            current.charges.append(float(t[6]) if len(t) > 6 else 0.0)
            current.masses.append(float(t[7]) if len(t) > 7 else -1.0)
        elif section in ("bonds", "constraints"):
            if current is None:
                raise ValueError(
                    f"{src}:{lineno}: [{section}] outside [moleculetype]")
            current.bonds.append((int(t[0]) - 1, int(t[1]) - 1))
        elif section == "angles":
            if current is None:
                raise ValueError(
                    f"{src}:{lineno}: [angles] outside [moleculetype]")
            current.angles.append(
                (int(t[0]) - 1, int(t[1]) - 1, int(t[2]) - 1))
        elif section == "dihedrals":
            if current is None:
                raise ValueError(
                    f"{src}:{lineno}: [dihedrals] outside "
                    "[moleculetype]")
            quad = (int(t[0]) - 1, int(t[1]) - 1, int(t[2]) - 1,
                    int(t[3]) - 1)
            # GROMACS function type 2/4 are improper conventions;
            # everything else (1, 3, 5, 9...) is a proper dihedral
            func = int(t[4]) if len(t) > 4 else 1
            (current.impropers if func in (2, 4)
             else current.dihedrals).append(quad)
        elif section == "settles":
            # rigid water: OW is atom ai; bonds OW-HW1, OW-HW2
            if current is None:
                raise ValueError(
                    f"{src}:{lineno}: [settles] outside [moleculetype]")
            ow = int(t[0]) - 1
            current.bonds.append((ow, ow + 1))
            current.bonds.append((ow, ow + 2))
        elif section == "molecules":
            system_mols.append((t[0], int(t[1])))
        # every other section (atomtypes, pairs,
        # exclusions, position_restraints, system, defaults...) carries
        # force-field data the Topology does not store
    if not molecules:
        raise ValueError(f"{path!r} declares no [moleculetype]")
    if not system_mols:
        if len(molecules) > 1:
            raise ValueError(
                f"{path!r} declares {len(molecules)} moleculetypes but "
                "no [molecules] section to order them")
        system_mols = [(next(iter(molecules)), 1)]
    parts = []
    for name, count in system_mols:
        mol = molecules.get(name)
        if mol is None:
            known = ", ".join(sorted(molecules))
            raise ValueError(
                f"[molecules] references {name!r} but no such "
                f"[moleculetype] was parsed (known: {known}); the "
                "defining .itp is probably behind an unresolved "
                "#include")
        if not mol.names:
            raise ValueError(f"[moleculetype] {name!r} has no [atoms]")
        masses = np.asarray(mol.masses)
        if (masses >= 0).all():
            pass                            # every mass explicit
        elif (masses < 0).all():
            masses = None                   # none given: element table
        else:
            # mixed explicit/omitted: fill ONLY the gaps from the
            # element table; explicit masses (isotopes!) must survive
            from mdanalysis_mpi_tpu.core import tables

            gaps = masses < 0
            masses = masses.copy()
            masses[gaps] = tables.guess_masses(
                np.array(mol.names)[gaps], np.array(mol.resnames)[gaps])
        # replicate ONCE per [molecules] entry with np.tile — a
        # 30000-copy solvent box must not build a 30000-part list
        nm = len(mol.names)
        names = np.tile(np.array(mol.names), count)
        resnames = np.tile(np.array(mol.resnames), count)
        resids = np.tile(np.array(mol.resids, np.int64), count)
        charges = np.tile(np.array(mol.charges), count)
        m_t = None if masses is None else np.tile(masses, count)
        def _replicate(tuples, width):
            if not tuples:
                return None
            b = np.asarray(tuples, np.int64)
            return (b[None] + (np.arange(count) * nm)[:, None, None]
                    ).reshape(-1, width)

        bonds = _replicate(mol.bonds, 2)
        angles = _replicate(mol.angles, 3)
        dihedrals = _replicate(mol.dihedrals, 4)
        impropers = _replicate(mol.impropers, 4)
        # per-copy residue separation: shift resindices by copy so
        # identical (resid, segid) in adjacent copies stay distinct.
        # The change-point cumsum is derived directly (a throwaway
        # Topology would run per-atom element/mass guessing for
        # nothing); segids are constant within a moleculetype
        rid = np.array(mol.resids, np.int64)
        change = np.ones(nm, dtype=bool)
        if nm > 1:
            change[1:] = rid[1:] != rid[:-1]
        base_ri = np.cumsum(change) - 1
        nres_mol = int(base_ri.max()) + 1 if nm else 0
        resindices = (np.tile(base_ri, count)
                      + np.repeat(np.arange(count), nm) * nres_mol)
        parts.append(Topology(
            names=names, resnames=resnames, resids=resids,
            charges=charges, masses=m_t, bonds=bonds, angles=angles,
            dihedrals=dihedrals, impropers=impropers,
            resindices=resindices))
    return parts[0] if len(parts) == 1 else concatenate(parts)


topology_files.register("itp", parse_itp)
# .top would collide with AMBER PRMTOP (upstream maps .top to its TOP
# parser too); GROMACS tops are sniffed there by content
