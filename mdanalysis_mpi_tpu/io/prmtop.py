"""AMBER PRMTOP topology parser (upstream ``TOPParser``) + a minimal
writer for offline test fixtures.

Completes the AMBER stack next to the NetCDF trajectory reader
(io/netcdf.py) and the INPCRD restart reader (io/inpcrd.py):
``Universe("sys.prmtop", "md.nc")``.

The format is %FLAG-sectioned FORTRAN card data.  Sections are parsed
by the FIELD WIDTH declared in each ``%FORMAT(...)`` line — mandatory
for ``20a4`` name blocks (4-char names pack with NO separators) and
the safe choice for numeric blocks too (I8 fields can touch at large
values).  Consumed flags: POINTERS (NATOM, NRES), ATOM_NAME, CHARGE
(AMBER's internal units, ÷18.2223 → e), MASS, ATOMIC_NUMBER (element
source when present; else nearest-mass lookup), RESIDUE_LABEL,
RESIDUE_POINTER, BONDS_INC_HYDROGEN + BONDS_WITHOUT_HYDROGEN (AMBER's
index*3 convention → (n, 2) atom-index bond list).  Unknown flags are
skipped, so real pmemd/tleap outputs with the full flag roster parse.
"""

from __future__ import annotations

import re

import numpy as np

from mdanalysis_mpi_tpu.core.tables import MASSES
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files

#: AMBER stores charges multiplied by sqrt(332.0522173) ≈ 18.2223
#: (kcal/mol electrostatic prefactor folded into the unit)
AMBER_CHARGE_SCALE = 18.2223

_FMT = re.compile(r"%FORMAT\(\s*(\d+)\s*([aIiEeFf])\s*(\d+)", re.ASCII)

_Z_TO_ELEMENT = {
    1: "H", 2: "HE", 3: "LI", 4: "BE", 5: "B", 6: "C", 7: "N", 8: "O",
    9: "F", 10: "NE", 11: "NA", 12: "MG", 13: "AL", 14: "SI", 15: "P",
    16: "S", 17: "CL", 18: "AR", 19: "K", 20: "CA", 25: "MN", 26: "FE",
    27: "CO", 28: "NI", 29: "CU", 30: "ZN", 35: "BR", 37: "RB",
    38: "SR", 42: "MO", 53: "I", 55: "CS", 56: "BA",
}


def _sections(path: str):
    """{FLAG: (kind, width, [data lines])} — kind 'a'/'I'/'E'."""
    out: dict[str, tuple[str, int, list[str]]] = {}
    flag = None
    with open(path) as fh:
        for ln in fh:
            ln = ln.rstrip("\n")
            if ln.startswith("%VERSION") or ln.startswith("%COMMENT"):
                continue
            if ln.startswith("%FLAG"):
                flag = ln[5:].strip()
                out[flag] = ("?", 0, [])
                continue
            if ln.startswith("%FORMAT"):
                m = _FMT.match(ln)
                if m is None or flag is None:
                    raise ValueError(
                        f"{path}: unparseable FORMAT line {ln!r}")
                kind = m.group(2).upper()
                kind = "E" if kind in ("E", "F") else kind
                out[flag] = (kind, int(m.group(3)), out[flag][2])
                continue
            if flag is not None:
                out[flag][2].append(ln)
    return out


def _values(section, n=None):
    kind, width, lines = section
    vals = []
    for ln in lines:
        for k in range(0, len(ln), width):
            field = ln[k:k + width]
            if not field.strip():
                continue
            if kind == "A":
                vals.append(field.strip())
            elif kind == "I":
                vals.append(int(field))
            else:
                vals.append(float(field))
    if n is not None:
        vals = vals[:n]
        if len(vals) < n:
            raise ValueError(
                f"PRMTOP section carries {len(vals)} values, need {n}")
    return vals


def _elements_from_masses(masses: np.ndarray) -> np.ndarray:
    """Nearest-mass element per atom, computed once per DISTINCT mass
    (older tleap prmtops lack ATOMIC_NUMBER; a million-atom system has
    a handful of distinct masses)."""
    els = np.array(list(MASSES.keys()))
    ems = np.array([MASSES[e] for e in els])
    uniq, inv = np.unique(np.asarray(masses, np.float64),
                          return_inverse=True)
    nearest = els[np.abs(ems[None, :] - uniq[:, None]).argmin(axis=1)]
    return nearest[inv]


def parse_prmtop(path: str) -> Topology:
    sec = _sections(path)
    if "POINTERS" not in sec:
        raise ValueError(f"{path}: no %FLAG POINTERS — not a PRMTOP")
    ptr = _values(sec["POINTERS"])
    natom, nres = int(ptr[0]), int(ptr[11])
    names = np.array(_values(sec["ATOM_NAME"], natom))
    charges = (np.array(_values(sec["CHARGE"], natom))
               / AMBER_CHARGE_SCALE) if "CHARGE" in sec else None
    masses = (np.array(_values(sec["MASS"], natom))
              if "MASS" in sec else None)
    labels = _values(sec["RESIDUE_LABEL"], nres)
    rptr = _values(sec["RESIDUE_POINTER"], nres)      # 1-based firsts
    starts = np.asarray(rptr, np.int64) - 1
    bounds = np.append(starts, natom)
    resnames = np.empty(natom, dtype=np.dtype("U8"))
    resids = np.empty(natom, dtype=np.int64)
    for r in range(nres):
        resnames[bounds[r]:bounds[r + 1]] = labels[r]
        resids[bounds[r]:bounds[r + 1]] = r + 1
    if "ATOMIC_NUMBER" in sec:
        z = _values(sec["ATOMIC_NUMBER"], natom)
        elements = np.array(
            [_Z_TO_ELEMENT.get(int(v), "X") for v in z])
    elif masses is not None:
        elements = _elements_from_masses(masses)
    else:
        elements = None
    bonds = []
    for flagname in ("BONDS_INC_HYDROGEN", "BONDS_WITHOUT_HYDROGEN"):
        if flagname not in sec:
            continue
        trip = _values(sec[flagname])
        if len(trip) % 3:
            raise ValueError(
                f"{path}: {flagname} length {len(trip)} is not a "
                "multiple of 3")
        for k in range(0, len(trip), 3):
            # AMBER stores coordinate-array offsets = atom_index * 3
            bonds.append((int(trip[k]) // 3, int(trip[k + 1]) // 3))
    return Topology(
        names=names, resnames=resnames, resids=resids,
        elements=elements, masses=masses, charges=charges,
        bonds=np.asarray(bonds, np.int64) if bonds else None)


def write_prmtop(path: str, universe_or_group) -> None:
    """Minimal PRMTOP writer (the flags :func:`parse_prmtop` consumes,
    standard card formats) — fixture generation for the offline test
    strategy (SURVEY.md §4) and a functional topology exporter for the
    AMBER toolchain."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    top = ag._universe.topology
    idx = np.asarray(ag.indices)
    sub = top.subset(idx)
    n = len(idx)
    ri = sub.resindices
    nres = int(ri.max()) + 1 if n else 0
    starts = (np.flatnonzero(np.r_[True, ri[1:] != ri[:-1]])
              if n else np.array([], np.int64))
    labels = [str(sub.resnames[s]) for s in starts]

    def cards(vals, per, fmt):
        lines = []
        for k in range(0, len(vals), per):
            lines.append("".join(fmt(v) for v in vals[k:k + per]))
        return lines or [""]

    with open(path, "w") as fh:
        def section(flag, fortran, lines):
            fh.write(f"%FLAG {flag}\n%FORMAT({fortran})\n")
            for ln in lines:
                fh.write(ln + "\n")

        fh.write("%VERSION  VERSION_STAMP = V0001.000  "
                 "(mdanalysis_mpi_tpu)\n")
        pointers = [0] * 32
        pointers[0] = n
        pointers[11] = nres
        section("POINTERS", "10I8",
                cards(pointers, 10, lambda v: f"{v:8d}"))
        section("ATOM_NAME", "20a4",
                cards([str(x)[:4] for x in sub.names], 20,
                      lambda v: f"{v:<4s}"))
        if sub.charges is not None:
            section("CHARGE", "5E16.8",
                    cards(sub.charges * AMBER_CHARGE_SCALE, 5,
                          lambda v: f"{v:16.8E}"))
        section("MASS", "5E16.8",
                cards(sub.masses, 5, lambda v: f"{v:16.8E}"))
        section("RESIDUE_LABEL", "20a4",
                cards([s[:4] for s in labels], 20, lambda v: f"{v:<4s}"))
        section("RESIDUE_POINTER", "10I8",
                cards((starts + 1).tolist(), 10, lambda v: f"{v:8d}"))
        if sub.bonds is not None and len(sub.bonds):
            # emit everything as BONDS_WITHOUT_HYDROGEN; bond-type
            # index 0 (parse ignores it)
            trip = []
            for a, b in np.asarray(sub.bonds):
                trip += [int(a) * 3, int(b) * 3, 0]
            section("BONDS_WITHOUT_HYDROGEN", "10I8",
                    cards(trip, 10, lambda v: f"{v:8d}"))


def _parse_top(path: str) -> Topology:
    """`.top` is claimed by TWO ecosystems: AMBER prmtop (upstream also
    maps .top here) and GROMACS topologies.  Sniff by content — AMBER
    card files open with %VERSION/%FLAG lines, GROMACS tops with
    directives/sections/comments."""
    with open(path) as fh:
        for ln in fh:
            if not ln.strip():
                continue
            if ln.startswith("%"):
                return parse_prmtop(path)
            from mdanalysis_mpi_tpu.io.itp import parse_itp

            return parse_itp(path)
    raise ValueError(f"{path!r} is empty")


topology_files.register("prmtop", parse_prmtop)
topology_files.register("parm7", parse_prmtop)
topology_files.register("top", _parse_top)
