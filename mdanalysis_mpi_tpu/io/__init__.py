"""Trajectory and topology I/O (reference layer L2, SURVEY.md §1).

Readers expose the interface the analysis layer depends on:

- ``n_frames``, ``n_atoms``
- random access ``reader[i] -> Timestep`` (RMSF.py:92,124 semantics:
  every access re-reads from the backing store; in-place edits to the
  previous Timestep do not persist)
- ``read_block(start, stop) -> (positions (B,N,3) float32, boxes)`` —
  the block-staging primitive the TPU executor feeds on (the reference
  has no analog; it reads frame-at-a-time)
- iteration and ``ts`` (current frame)
"""

from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.io.base import ReaderBase
from mdanalysis_mpi_tpu.io.writer import TrajectoryWriter, Writer

__all__ = ["MemoryReader", "ReaderBase", "TrajectoryWriter", "Writer"]
