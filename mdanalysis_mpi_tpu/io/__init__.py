"""Trajectory and topology I/O (reference layer L2, SURVEY.md §1).

Readers expose the interface the analysis layer depends on:

- ``n_frames``, ``n_atoms``
- random access ``reader[i] -> Timestep`` (RMSF.py:92,124 semantics:
  every access re-reads from the backing store; in-place edits to the
  previous Timestep do not persist)
- ``read_block(start, stop) -> (positions (B,N,3) float32, boxes)`` —
  the block-staging primitive the TPU executor feeds on (the reference
  has no analog; it reads frame-at-a-time)
- iteration and ``ts`` (current frame)

Format roster (each module self-registers with the topology_files /
trajectory_files registries; PARITY.md maps every row to its upstream
counterpart and tests):

- topology (+coordinates where the format carries them): GRO, PSF,
  PDB, PQR, MOL2, CRD, PDBQT, TXYZ/ARC, Desmond DMS, AMBER
  PRMTOP/parm7, GROMACS ITP/TOP (`.top` sniffs AMBER vs GROMACS by
  content), DL_POLY CONFIG/REVCON (bare-filename dispatch); TPR is a
  documented conversion path.
- trajectories: XTC + DCD (C++ codec, NumPy fallbacks), TRR, AMBER
  NetCDF (.nc/.ncdf, from-scratch NetCDF-3), AMBER ASCII
  mdcrd/crdbox/trj, AMBER INPCRD/restrt/rst7 restarts, XYZ, LAMMPS
  dump, Tinker ARC, DL_POLY HISTORY, in-memory arrays, and multi-file
  chains (io/chain.py).  H5MD/GSD/TNG/TRZ are documented conversion
  paths (loud per-format guidance in trajectory_files.py).
"""

from mdanalysis_mpi_tpu.io.memory import MemoryReader
from mdanalysis_mpi_tpu.io.base import ReaderBase
from mdanalysis_mpi_tpu.io.writer import TrajectoryWriter, Writer

__all__ = ["MemoryReader", "ReaderBase", "TrajectoryWriter", "Writer"]
