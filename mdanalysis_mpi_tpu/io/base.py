"""Reader protocol shared by all trajectory backends."""

from __future__ import annotations

import os
import threading

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.reliability import faults as _faults


class BlockCache:
    """Byte-capped staged-block cache (shared by the host staging cache
    here and the executors' HBM ``DeviceBlockCache``).

    Policy: insert until ``max_bytes``, then stop (no eviction).  For
    repeated sequential scans — the access pattern of every analysis
    here — keeping the head and re-staging the tail is optimal; FIFO/LRU
    would evict exactly the blocks the next scan needs first.

    Thread safety: every mutation (``put``/``clear``/reservations/
    pinning/eviction) runs under one re-entrant lock.  A single run
    never needed it, but the serving layer
    (:mod:`mdanalysis_mpi_tpu.service`) shares one cache between
    scheduler workers, and unlocked concurrent ``put`` interleavings
    corrupt the byte accounting (two threads both read ``_bytes``
    before either adds) — the thread-safety audit the service PR's
    stress test pins.  ``get`` takes the lock too: the hit/miss
    counters feed serving telemetry and lost updates would skew the
    reported hit rate.

    Multi-tenant hooks (used by the service admission layer; no-ops for
    solo runs):

    - ``reserve(nbytes)`` / ``release(nbytes)`` — admission control:
      a scheduler reserves a job's estimated working set before letting
      it stage into the cache, so concurrently admitted jobs cannot
      jointly overcommit the budget.  Reservations gate *admission
      decisions* only — ``put`` keeps its own byte cap check (an
      estimate is not a contract).
    - ``pin(ns)`` / ``unpin(ns)`` — mark a key namespace (the leading
      key element: the executors' cache keys lead with the reader
      fingerprint) as belonging to a hot tenant.
    - ``evict_unpinned()`` — drop every entry outside the pinned
      namespaces, returning the evicted values so device-backed
      subclasses can release their buffers.  This is the ONLY eviction
      path: the no-eviction policy above still holds for cache-internal
      behavior, and only an explicit admission decision ("make room for
      a queued tenant without touching hot ones") triggers it.
    """

    def __init__(self, max_bytes: int):
        self._store: dict = {}
        self._sizes: dict = {}
        self._bytes = 0
        self._rejected = False
        self._reserved = 0
        self._pinned_ns: set = set()
        self._lock = threading.RLock()
        self.max_bytes = max_bytes
        self.hits = 0
        self.misses = 0
        # host-side stage-time fingerprints (utils/integrity.py
        # staged_fingerprint), keyed like _store — the SDC scrubber's
        # reference copy (docs/RELIABILITY.md §5).  Entries without
        # one (multi-host slices, pre-integrity callers) are never
        # scrubbed.
        self._fps: dict = {}
        #: high-water mark of stored + reserved bytes — the staged
        #: admission pressure gauge (`mdtpu_staged_bytes_peak`) the
        #: memory watchdog reads
        self.bytes_peak = 0

    def get(self, key):
        with self._lock:
            value = self._store.get(key)
            if value is None:
                self.misses += 1
            else:
                self.hits += 1
            return value

    def put(self, key, value, nbytes: int) -> bool:
        """Insert; returns whether the entry was stored (callers that
        must clean up a rejected value — e.g. the scan superblock
        stacker — branch on this instead of probing the store)."""
        # overwriting a key credits the replaced entry's bytes back
        # first — without this, re-staging the same block (e.g. a
        # resilient run salvaging different bytes) double-counts and
        # silently flips `full`, demoting every later run to re-staging
        with self._lock:
            freed = self._sizes.get(key, 0)
            if self._bytes - freed + nbytes <= self.max_bytes:
                self._store[key] = value
                self._sizes[key] = nbytes
                self._bytes += nbytes - freed
                # a replaced entry's fingerprint no longer describes
                # the stored value; the caller re-notes the fresh one
                self._fps.pop(key, None)
                self.bytes_peak = max(self.bytes_peak,
                                      self._bytes + self._reserved)
                return True
            # the cache just refused a block: record it, so `full`
            # flips even when _bytes never lands exactly on the cap
            self._rejected = True
            return False

    @property
    def full(self) -> bool:
        """True once the cache has stopped accepting blocks — the byte
        cap was reached exactly, OR an insert was rejected for not
        fitting (the common over-cap case: _bytes stays below
        max_bytes forever, so the byte comparison alone never flips).
        Consumers route overflow elsewhere, e.g. the executors hand
        staging back to the host stage cache once the device cache
        stops accepting (executors._run_batches stage selection)."""
        return self._rejected or self._bytes >= self.max_bytes

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._sizes.clear()
            self._fps.clear()
            self._bytes = 0
            self._rejected = False
            self._reserved = 0

    # ---- admission / pinning hooks (service layer) ----

    @property
    def available_bytes(self) -> int:
        """Budget not yet held by stored entries OR outstanding
        reservations — what an admission decision compares a job's
        estimated working set against."""
        with self._lock:
            return max(0, self.max_bytes - self._bytes - self._reserved)

    def reserve(self, nbytes: int) -> bool:
        """Atomically claim ``nbytes`` of budget for a job about to
        stage, or refuse (the scheduler then queues the job instead of
        letting it thrash the cache).  Pair with :meth:`release` when
        the job finishes — the bytes it actually cached are then
        accounted as stored entries."""
        with self._lock:
            if nbytes <= self.max_bytes - self._bytes - self._reserved:
                self._reserved += nbytes
                self.bytes_peak = max(self.bytes_peak,
                                      self._bytes + self._reserved)
                return True
            return False

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._reserved = max(0, self._reserved - nbytes)

    def pin(self, ns) -> None:
        """Pin a key namespace (``key[0]`` for tuple keys — the reader
        fingerprint the executors lead their cache keys with): its
        entries survive :meth:`evict_unpinned`."""
        with self._lock:
            self._pinned_ns.add(ns)

    def unpin(self, ns) -> None:
        with self._lock:
            self._pinned_ns.discard(ns)

    def _key_ns(self, key):
        return key[0] if isinstance(key, tuple) and key else key

    def ns_bytes(self, ns) -> int:
        """Stored bytes under one key namespace — how much of the cache
        a tenant already holds.  Admission uses it to let a RESIDENT
        tenant ride its own entries when a fresh reservation would not
        fit (its prior superblocks are the very budget the reservation
        competes with; denying it would force re-staging blocks that
        are already cached)."""
        with self._lock:
            return sum(size for key, size in self._sizes.items()
                       if self._key_ns(key) == ns)

    def unpinned_bytes(self) -> int:
        """Stored bytes :meth:`evict_unpinned` would reclaim — what an
        admission decision checks BEFORE evicting, so idle tenants'
        staged superblocks are never destroyed when the reclaim could
        not make the reservation fit anyway."""
        with self._lock:
            return sum(size for key, size in self._sizes.items()
                       if self._key_ns(key) not in self._pinned_ns)

    def evict_unpinned(self) -> list:
        """Drop every entry whose namespace is not pinned, crediting
        their bytes back (and un-flipping ``full`` — the cache accepts
        inserts again).  Returns the evicted values; device-backed
        subclasses release the buffers."""
        with self._lock:
            evicted = []
            for key in [k for k in self._store
                        if self._key_ns(k) not in self._pinned_ns]:
                evicted.append(self._store.pop(key))
                self._bytes -= self._sizes.pop(key)
                self._fps.pop(key, None)
            if evicted:
                self._rejected = False
            return evicted

    # ---- SDC-scrub fingerprint hooks (docs/RELIABILITY.md §5) ----

    def note_fingerprint(self, key, fp, expect=None) -> None:
        """Record the host-side stage-time fingerprint of a stored
        entry (``utils.integrity.staged_fingerprint`` tuple).  No-op
        for keys not currently stored — the entry may already have
        been evicted/overwritten by the time the stager gets here.
        ``expect`` (identity-compared, like :meth:`quarantine`) pins
        the fingerprint to the VALUE it describes: a racing same-key
        put must not end up paired with the loser's fingerprint — the
        scrubber would falsely quarantine a clean block."""
        with self._lock:
            if key in self._store and (
                    expect is None or self._store[key] is expect):
                self._fps[key] = tuple(fp)

    def fingerprint(self, key):
        with self._lock:
            return self._fps.get(key)

    def scrub_items(self) -> list:
        """Snapshot of ``(key, value, fingerprint)`` for every stored
        entry that carries a fingerprint — what one scrub pass
        verifies."""
        with self._lock:
            return [(k, self._store[k], self._fps[k])
                    for k in list(self._store) if k in self._fps]

    def quarantine(self, key, expect) -> bool:
        """Drop ``key`` iff it still stores ``expect`` (identity): the
        scrubber's remove path, raced safely against concurrent
        overwrites.  Returns whether the entry was removed; subclasses
        release device buffers on True."""
        with self._lock:
            if self._store.get(key) is not expect:
                return False
            self._store.pop(key)
            self._bytes -= self._sizes.pop(key, 0)
            self._fps.pop(key, None)
            # freed budget: the cache accepts inserts again, so the
            # re-staged replacement block has somewhere to land
            self._rejected = False
            return True


#: Host staged-block cache (``ReaderBase.stage_cached``).
#:
#: The staging pipeline's bottleneck is the single host core: every byte
#: it moves (decode, selection gather, quantize, transfer serialization)
#: is additive wall time — there is nothing to overlap with (measured:
#: ``jax.device_put`` on tunneled targets is CPU-bound, ~21 ms CPU per
#: 21 ms wall for a 38 MB block).  Re-running an analysis over the same
#: (trajectory, selection) therefore re-pays the full gather+quantize
#: for bytes that have not changed.  The cache keeps the *post-gather*
#: (and post-quantize) host blocks so repeated passes pay only the wire
#: serialization — the same design move as the upstream oracle's
#: ``in_memory=True`` workflow (RMSF.py:12), applied at the staging
#: layer.  Assumes the backing trajectory is immutable (file readers
#: already validate offset indexes by mtime; in-memory readers document
#: it); a reader's ``_host_stage_cache.clear()`` resets.
HostStageCache = BlockCache


#: THE quantization policy numbers, one copy for every tier that
#: quantizes coordinates — the wire formats (``executors.
#: quantize_block``), the readers' fused int16 staging
#: (``ReaderBase.QUANT_TARGET`` below mirrors the int16 entry), and
#: the block store's ingest (docs/STORE.md).  ``QUANT_TARGETS`` is the
#: symmetric-scale target (resolution = max|x| / target);
#: ``QUANT_INT_MAX`` the representable bound overflow checks compare
#: against.  Retuning a target here retunes every tier together — a
#: store ingested under one policy must never be served raw against a
#: reader expecting another.
QUANT_TARGETS = {"int16": 32000.0, "int8": 120.0}
QUANT_INT_MAX = {"int16": 32767.0, "int8": 127.0}


def planar_repack(q: np.ndarray) -> np.ndarray:
    """Repack an interleaved ``(B, S, 3)`` staged block to the planar
    ``(3, B, S)`` component-plane layout the fused Pallas kernel
    consumes (ops/pallas_fused.py).  This is THE one host copy the
    planar path pays — on quantized (int16/int8) bytes, behind the
    staging boundary, counted in
    ``mdtpu_fused_planar_repacks_total``."""
    from mdanalysis_mpi_tpu import obs

    obs.METRICS.inc("mdtpu_fused_planar_repacks_total")
    return np.ascontiguousarray(np.moveaxis(q, 2, 0))


def norm_quantize(quantize) -> str | None:
    """Normalize a staging-quantization request: ``False``/``None`` →
    None, ``True`` → ``"int16"`` (backward compatible), ``"int16"`` /
    ``"int8"`` pass through.  One normalization shared by every reader
    and cache key, so ``True`` and ``"int16"`` can never produce
    distinct cache entries for identical bytes."""
    if not quantize:
        return None
    if quantize is True:
        return "int16"
    if quantize in ("int16", "int8"):
        return quantize
    raise ValueError(
        f"quantize must be a bool, 'int16' or 'int8', got {quantize!r}")


def sel_fingerprint(sel) -> int | None:
    """Content hash of a selection index array — the cache-key component
    shared by the host stage cache and the executors' device block cache
    (a shared key namespace must never serve blocks gathered for a
    different selection)."""
    if sel is None:
        return None
    return hash(np.ascontiguousarray(sel).tobytes())


def _host_stage_cache_bytes() -> int:
    """Host cache cap in bytes (env ``MDTPU_HOST_STAGE_CACHE_MB``;
    0 disables).  Default 4 GB — sized so the flagship working set
    (BASELINE config 2 at stated scale: 10k frames × 50k selected
    atoms × int16 = ~3 GB) fits whole; whatever exceeds the cap is
    simply re-staged, and any host analyzing that workload holds the
    12 GB raw trajectory anyway, so the cache is not the constraint.
    Read per call so tests/benches can toggle it without reloads."""
    return int(float(os.environ.get("MDTPU_HOST_STAGE_CACHE_MB", "4096"))
               * 1e6)


class ReaderBase:
    """Abstract trajectory reader.

    Subclasses implement ``n_frames``, ``n_atoms`` and ``_read_frame(i)``;
    the base provides indexing, iteration, the ``ts`` cursor (the
    reference's ``trajectory.ts``, RMSF.py:80), and a default
    ``read_block`` built on per-frame reads (native readers override it
    with a bulk decode path).
    """

    _ts: Timestep | None = None

    @property
    def n_frames(self) -> int:  # RMSF.py:65
        raise NotImplementedError

    @property
    def n_atoms(self) -> int:   # RMSF.py:89
        raise NotImplementedError

    def _read_frame(self, i: int) -> Timestep:
        raise NotImplementedError

    # Adaptive int16 staging-scale policy (io/xtc.py's fused path and
    # _quantize_staged below must quantize with bit-identical scales):
    # the module-level QUANT_TARGETS int16 entry, ×1.05 drift margin
    # on the previous max, float64 scale arithmetic.
    QUANT_TARGET = QUANT_TARGETS["int16"]
    QUANT_MARGIN = 1.05

    def _quant_hints(self) -> dict:
        """Per-selection max-|coordinate| hints for the adaptive
        one-pass int16 quantizers (scoped per selection content so one
        wide-coordinate selection cannot coarsen another's
        resolution)."""
        return self.__dict__.setdefault("_quant_max_hints", {})

    @property
    def filename(self) -> str | None:
        """Backing file path, or None for non-file readers (the public
        contract consumers like AlignTraj's default-output naming use;
        file-backed subclasses store it as ``_path``)."""
        return getattr(self, "_path", None)

    # ---- on-the-fly transformations (upstream add_transformations) ----

    @property
    def transformations(self) -> tuple:
        return self.__dict__.get("_transformations", ())

    def add_transformations(self, *transformations) -> None:
        """Attach ``ts -> ts`` callables applied to every frame read
        (upstream one-shot contract: set once).  Readers with
        transformations fall back from fused decode→gather fast paths
        to the generic read-transform-gather loop, because a
        transformation may need atoms outside the staged selection."""
        if self.__dict__.get("_transformations"):
            raise ValueError(
                "transformations are already set (upstream contract: "
                "add_transformations can only be called once)")
        self.__dict__["_transformations"] = tuple(transformations)
        # staged-block caches hold UNtransformed data
        self.__dict__.pop("_host_stage_cache", None)
        self._reset_cursor()       # re-read transformed, same frame
        # the cursor re-read above passed one frame through any NEW
        # stateful transformation; clear that seed so the caller's own
        # first read is window frame 1, not a double-counted duplicate
        for t in transformations:
            if getattr(t, "stateful", False):
                t.reset()

    # ---- auxiliary series (upstream add_auxiliary / ts.aux) ----

    @property
    def auxiliaries(self) -> dict:
        return self.__dict__.get("_auxiliaries", {})

    def add_auxiliary(self, name: str, aux, cutoff: float | None = None
                      ) -> None:
        """Attach an auxiliary time series (``auxiliary.XVGReader`` /
        ``ArrayAuxReader``); every subsequently read frame carries
        ``ts.aux.<name>`` = the aux step nearest the frame's time
        (NaNs past ``cutoff`` — never a silently wrong neighbor)."""
        if not hasattr(aux, "value_at"):
            raise TypeError(
                f"aux must provide value_at(time, cutoff) (an "
                f"auxiliary.ArrayAuxReader/XVGReader), got "
                f"{type(aux).__name__}")
        if not name.isidentifier() or hasattr(dict, name):
            # ts.aux is attribute-accessed; a name shadowing a dict
            # method ('values', 'items', ...) would silently return the
            # bound method instead of the data
            raise ValueError(
                f"auxiliary name {name!r} must be a Python identifier "
                "that does not collide with dict attributes")
        auxs = self.__dict__.setdefault("_auxiliaries", {})
        if name in auxs:
            raise ValueError(f"auxiliary {name!r} already attached")
        auxs[name] = (aux, cutoff)
        self._reset_cursor()       # re-read with aux attached, IN PLACE

    def remove_auxiliary(self, name: str) -> None:
        try:
            del self.__dict__["_auxiliaries"][name]
        except KeyError:
            raise ValueError(
                f"no auxiliary {name!r}; attached: "
                f"{sorted(self.auxiliaries)}") from None
        self._reset_cursor()       # drop the stale aux view, IN PLACE

    def _reset_cursor(self) -> None:
        """Invalidate the ts cursor WITHOUT losing the current frame: a
        bare ``_ts = None`` would silently rewind the next ``ts`` access
        to frame 0 — wrong for a user positioned mid-trajectory."""
        cur = None if self._ts is None else self._ts.frame
        self._ts = None
        if cur is not None:
            self[cur]

    def _emit(self, ts: Timestep) -> Timestep:
        for t in self.transformations:
            ts = t(ts)
        return ts

    def _emit_cursor(self, ts: Timestep) -> Timestep:
        """The per-frame CURSOR path: transformations + the auxiliary
        namespace.  Block reads (read_block/stage_block) go through
        plain ``_emit`` — batch kernels never see aux, so building an
        AuxHolder per staged frame would be pure discarded work."""
        ts = self._emit(ts)
        auxs = self.auxiliaries
        if auxs:
            from mdanalysis_mpi_tpu.auxiliary import AuxHolder

            ts.aux = AuxHolder(
                {name: aux.value_at(ts.time, cutoff)
                 for name, (aux, cutoff) in auxs.items()})
        return ts

    # ---- shared behavior ----

    @property
    def ts(self) -> Timestep:
        if self._ts is None:
            self._ts = self._emit_cursor(self._read_frame(0))
        return self._ts

    def __len__(self) -> int:
        return self.n_frames

    def __getitem__(self, i) -> Timestep:
        if isinstance(i, slice):
            raise TypeError("slice indexing not supported; use read_block")
        i = int(i)
        if i < 0:
            i += self.n_frames
        if not 0 <= i < self.n_frames:
            raise IndexError(f"frame {i} out of range [0, {self.n_frames})")
        ts = self._read_frame(i)
        if _faults.plans():
            # "read" fault site (reliability/faults.py): the per-frame
            # cursor read — the serial oracle path and the policy
            # layer's corrupt-frame salvage re-read both land here
            ts.positions = _faults.fire("read", frame=i,
                                        array=ts.positions)
        self._ts = self._emit_cursor(ts)
        return self._ts

    def __iter__(self):
        for i in range(self.n_frames):
            yield self[i]

    def rewind(self) -> Timestep:
        return self[0]

    def read_block(self, start: int, stop: int,
                   sel: np.ndarray | None = None, step: int = 1
                   ) -> tuple[np.ndarray, np.ndarray | None]:
        """Bulk-read frames [start:stop:step] → (positions (B,S,3) f32,
        boxes).

        ``sel`` (optional int index array) gathers a subset of atoms
        during the read — one copy instead of read-then-gather, which
        matters when staging 100k-atom frames for a small selection.
        ``boxes`` is (B, 6) float32 ([lx,ly,lz,alpha,beta,gamma]) or None
        if the trajectory carries no box.  This is the staging primitive
        for host→HBM block transfer (SURVEY.md §7 layer 2).
        """
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        if any(getattr(t, "stateful", False) for t in self.transformations):
            # block/cache schedules are not the sequential cursor a
            # stateful transformation's numbers depend on — two passes
            # could silently disagree; refuse instead
            raise ValueError(
                "stateful transformations (PositionAverager) are "
                "sequential-cursor only; iterate frames with "
                "backend='serial' (block staging would silently change "
                "their output)")
        frames = range(start, stop, step)
        b = len(frames)
        n = self.n_atoms if sel is None else len(sel)
        out = np.empty((b, n, 3), dtype=np.float32)
        boxes = None
        for j, i in enumerate(frames):
            ts = self._emit(self._read_frame(i))
            out[j] = ts.positions if sel is None else ts.positions[sel]
            if ts.dimensions is not None:
                if boxes is None:
                    # zeros, not empty: frames before the first boxed frame
                    # must not leak uninitialized memory
                    boxes = np.zeros((b, 6), dtype=np.float32)
                boxes[j] = ts.dimensions
        return out, boxes

    def frame_times(self, frames) -> np.ndarray | None:
        """Per-frame times for ``frames`` (an iterable of indices), or
        None when the format carries no time metadata the reader can
        fetch without decoding coordinates.  Used by
        ``Universe.transfer_to_memory`` to preserve times."""
        return None

    def stage_block(self, start: int, stop: int,
                    sel: np.ndarray | None = None, quantize=False,
                    layout: str = "interleaved"):
        """Staging primitive: ``read_block`` plus optional fused
        quantization → (block, boxes, inv_scale).

        ``quantize``: False (float32 staging, ``inv_scale`` None), True
        or ``"int16"`` (the default wire format — native C++ fused path
        when available, NumPy fallback), or ``"int8"`` (half the wire
        bytes again; coarse — see ``quantize_block`` for the accuracy
        envelope; NumPy path only).  The first int16 block per
        selection uses the exact per-block scale (bit-identical to the
        NumPy path); later blocks use the adaptive one-pass scale (see
        ``_quantize_staged``) — same resolution class, different bits.

        ``layout``: ``"interleaved"`` (the default ``(B, S, 3)`` block)
        or ``"planar"`` — the ``(3, B, S)`` component-plane form the
        fused Pallas kernel consumes (ops/pallas_fused.py), produced by
        one ``planar_repack`` on the quantized bytes.  Planar staging
        requires a quantized dtype: the fused path exists to avoid host
        float32 materialization, so float32 planar is a contract error.
        """
        qmode = norm_quantize(quantize)
        if layout == "planar" and qmode is None:
            raise ValueError(
                "layout='planar' requires quantized staging "
                "(int16/int8); float32 blocks stay interleaved")
        block, boxes = self.read_block(start, stop, sel=sel)
        if qmode is None:
            return block, boxes, None
        if qmode == "int8":
            from mdanalysis_mpi_tpu.parallel.executors import quantize_block

            q, inv_scale = quantize_block(block, "int8")
        else:
            q, inv_scale = self._quantize_staged(
                block, None, sel_fp=sel_fingerprint(sel))
        if layout == "planar":
            q = planar_repack(q)
        return q, boxes, inv_scale

    def _quantize_staged(self, src: np.ndarray, sel, sel_fp=None):
        """Fused gather + int16 quantize of ``src[:, sel]`` → (q, inv_scale).

        Adaptive one-pass path: after the first block establishes the
        coordinate range, later blocks quantize in a single streaming
        pass against that range (×1.05 margin) via the native
        ``stage_gather_quantize_i16_scaled`` kernel — the separate
        max-abs read pass is what made int16 staging lose to float32 on
        a clean link (VERDICT r1 weak #2).  A block whose true max
        exceeds the margin is detected by the kernel and re-quantized
        exactly (rare: coordinate ranges drift slowly).  Range hints are
        scoped per selection content (``sel_fp``) so one wide-coordinate
        selection cannot coarsen another's resolution on the same
        reader.  Falls back to the NumPy reference path without the
        native library.
        """
        try:
            from mdanalysis_mpi_tpu.io import native

            hints = self._quant_hints()
            key = sel_fp if sel_fp is not None else sel_fingerprint(sel)
            hint = hints.get(key, 0.0)
            if hint > 0.0:
                scale = self.QUANT_TARGET / (hint * self.QUANT_MARGIN)
                q, vmax, overflowed = native.stage_gather_quantize_scaled(
                    src, sel, scale)
                if vmax > hint:
                    hints[key] = vmax
                if not overflowed:
                    return q, np.float32(1.0 / scale)
            q, inv_scale = native.stage_gather_quantize(src, sel)
            # the exact kernel's scale encodes the block max: seed the hint
            hints[key] = max(hints.get(key, 0.0),
                             float(inv_scale) * self.QUANT_TARGET)
            return q, inv_scale
        except Exception:
            from mdanalysis_mpi_tpu.parallel.executors import quantize_block

            return quantize_block(src if sel is None else src[:, sel])

    def stage_cached(self, start: int, stop: int,
                     sel: np.ndarray | None = None, quantize=False,
                     layout: str = "interleaved"):
        """``stage_block`` through the reader's :class:`HostStageCache`.

        The executors' staging entry point.  Cache key = (frame window,
        selection content, transfer dtype, layout); the stored blocks
        are treated as immutable by all consumers (pad_batch passes
        full batches through untouched and ``device_put`` only reads).
        Planar blocks cache under a distinct key so the two layouts of
        one window never alias (the scrub fingerprints hash whatever
        bytes were staged, so both layouts scrub independently).
        """
        cap = _host_stage_cache_bytes()
        if cap <= 0:
            return self.stage_block(start, stop, sel=sel, quantize=quantize,
                                    layout=layout)
        cache = self.__dict__.get("_host_stage_cache")
        if cache is None or cache.max_bytes != cap:
            cache = HostStageCache(cap)
            self.__dict__["_host_stage_cache"] = cache
        key = (start, stop, sel_fingerprint(sel), norm_quantize(quantize))
        if layout != "interleaved":
            key = key + (layout,)
        staged = cache.get(key)
        if staged is None:
            staged = self.stage_block(start, stop, sel=sel, quantize=quantize,
                                      layout=layout)
            cache.put(key, staged, staged[0].nbytes)
        return staged

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
