"""Reader protocol shared by all trajectory backends."""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep


class ReaderBase:
    """Abstract trajectory reader.

    Subclasses implement ``n_frames``, ``n_atoms`` and ``_read_frame(i)``;
    the base provides indexing, iteration, the ``ts`` cursor (the
    reference's ``trajectory.ts``, RMSF.py:80), and a default
    ``read_block`` built on per-frame reads (native readers override it
    with a bulk decode path).
    """

    _ts: Timestep | None = None

    @property
    def n_frames(self) -> int:  # RMSF.py:65
        raise NotImplementedError

    @property
    def n_atoms(self) -> int:   # RMSF.py:89
        raise NotImplementedError

    def _read_frame(self, i: int) -> Timestep:
        raise NotImplementedError

    # ---- shared behavior ----

    @property
    def ts(self) -> Timestep:
        if self._ts is None:
            self._ts = self._read_frame(0)
        return self._ts

    def __len__(self) -> int:
        return self.n_frames

    def __getitem__(self, i) -> Timestep:
        if isinstance(i, slice):
            raise TypeError("slice indexing not supported; use read_block")
        i = int(i)
        if i < 0:
            i += self.n_frames
        if not 0 <= i < self.n_frames:
            raise IndexError(f"frame {i} out of range [0, {self.n_frames})")
        self._ts = self._read_frame(i)
        return self._ts

    def __iter__(self):
        for i in range(self.n_frames):
            yield self[i]

    def rewind(self) -> Timestep:
        return self[0]

    def read_block(self, start: int, stop: int,
                   sel: np.ndarray | None = None, step: int = 1
                   ) -> tuple[np.ndarray, np.ndarray | None]:
        """Bulk-read frames [start:stop:step] → (positions (B,S,3) f32,
        boxes).

        ``sel`` (optional int index array) gathers a subset of atoms
        during the read — one copy instead of read-then-gather, which
        matters when staging 100k-atom frames for a small selection.
        ``boxes`` is (B, 6) float32 ([lx,ly,lz,alpha,beta,gamma]) or None
        if the trajectory carries no box.  This is the staging primitive
        for host→HBM block transfer (SURVEY.md §7 layer 2).
        """
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        frames = range(start, stop, step)
        b = len(frames)
        n = self.n_atoms if sel is None else len(sel)
        out = np.empty((b, n, 3), dtype=np.float32)
        boxes = None
        for j, i in enumerate(frames):
            ts = self._read_frame(i)
            out[j] = ts.positions if sel is None else ts.positions[sel]
            if ts.dimensions is not None:
                if boxes is None:
                    # zeros, not empty: frames before the first boxed frame
                    # must not leak uninitialized memory
                    boxes = np.zeros((b, 6), dtype=np.float32)
                boxes[j] = ts.dimensions
        return out, boxes

    def frame_times(self, frames) -> np.ndarray | None:
        """Per-frame times for ``frames`` (an iterable of indices), or
        None when the format carries no time metadata the reader can
        fetch without decoding coordinates.  Used by
        ``Universe.transfer_to_memory`` to preserve times."""
        return None

    def stage_block(self, start: int, stop: int,
                    sel: np.ndarray | None = None, quantize: bool = False):
        """Staging primitive: ``read_block`` plus optional fused int16
        quantization → (block, boxes, inv_scale).

        ``inv_scale`` is None on the float32 path.  Quantization runs in
        the native C++ codec when available (single fused max+round pass
        — the host staging core is the throughput bottleneck, SURVEY.md
        §7) and falls back to the NumPy reference implementation
        (``parallel.executors.quantize_block``) otherwise; both produce
        bit-identical outputs.
        """
        block, boxes = self.read_block(start, stop, sel=sel)
        if not quantize:
            return block, boxes, None
        try:
            from mdanalysis_mpi_tpu.io import native

            q, inv_scale = native.stage_gather_quantize(block, None)
        except Exception:
            from mdanalysis_mpi_tpu.parallel.executors import quantize_block

            q, inv_scale = quantize_block(block)
        return q, boxes, inv_scale

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
