"""TRR (GROMACS full-precision) trajectory reader/writer.

Completes the GROMACS trajectory pair next to XTC (SURVEY.md §2.2 "and
TRR if cheap" — it is: TRR is plain big-endian XDR with no bit-packed
compression, so NumPy ``frombuffer`` decodes at memcpy speed and no C++
codec is needed).  Layout per frame (libxdrfile ``t_trnheader``
semantics, reimplemented from the on-disk format):

- magic ``1993`` (i4), version-string length ``13`` (i4), XDR string
  ``"GMX_trn_file"`` (i4 length + 12 bytes),
- 13 i4 fields: ir/e/box/vir/pres/top/sym sizes, x/v/f sizes, natoms,
  step, nre,
- time + lambda in the frame's float width (4 or 8 bytes, inferred
  from box_size/x_size as upstream does),
- payload: box (3×3), virial, pressure, positions, velocities, forces
  — each present iff its size field is nonzero.

Frame byte size is fully determined by the header, so the offset index
is a cheap header-hop scan (cached to disk like the XTC index).
Single-frame reads expose velocities/forces on the Timestep when the
frame carries them (upstream units: Å/ps, kJ/(mol·Å)); the bulk
``read_block`` staging path reads positions+box only (the analysis
kernels consume coordinates).  Coordinates convert nm→Å at the
boundary, matching the rest of the io layer.

Throughput class (measured, 100 frames × 50k atoms, this host):
``read_block`` decodes one contiguous file read via vectorized
``np.frombuffer``/``astype`` — 1004 frames/s vs 356 f/s for the C++
XTC codec (which pays 3dfcoord bit-unpacking) and 2229 f/s for C++
DCD; random single-frame reads 0.5 ms vs XTC's 3 ms.  The NumPy
decode is NOT the slow path of the trio, so no native codec is
warranted; the cost is the big-endian→native byteswap at memory
bandwidth.  TRR files are ~2.1× larger than XTC for the same data.
"""

from __future__ import annotations

import os

import numpy as np

from mdanalysis_mpi_tpu.core.box import box_to_vectors, vectors_to_box
from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase

_NM_TO_A = 10.0
_MAGIC = 1993
_TAG = b"GMX_trn_file"
# magic + slen + (strlen + 12 tag bytes) + 13 i4 header fields
_HEAD_INTS = 13
_HEAD_BYTES = 4 + 4 + (4 + len(_TAG)) + 4 * _HEAD_INTS


class _Header:
    __slots__ = ("sizes", "natoms", "step", "flsize", "payload_start",
                 "frame_bytes", "x_off")

    # order of the 13 i4 fields after the version tag
    _FIELDS = ("ir_size", "e_size", "box_size", "vir_size", "pres_size",
               "top_size", "sym_size", "x_size", "v_size", "f_size",
               "natoms", "step", "nre")


def _parse_header(buf: bytes, offset: int, path: str) -> _Header:
    if len(buf) - offset < _HEAD_BYTES:
        raise IOError(f"truncated TRR header in {path!r} at byte {offset}")
    ints = np.frombuffer(buf, dtype=">i4", count=2, offset=offset)
    if ints[0] != _MAGIC:
        raise IOError(
            f"bad TRR magic {int(ints[0])} in {path!r} at byte {offset}")
    if ints[1] != len(_TAG) + 1:
        raise IOError(f"bad TRR version-string length in {path!r}")
    strlen = int(np.frombuffer(buf, ">i4", 1, offset + 8)[0])
    if strlen != len(_TAG) or buf[offset + 12:offset + 12 + strlen] != _TAG:
        raise IOError(f"bad TRR version tag in {path!r}")
    fields = np.frombuffer(buf, ">i4", _HEAD_INTS,
                           offset + 12 + len(_TAG))
    h = _Header()
    h.sizes = dict(zip(_Header._FIELDS, (int(v) for v in fields)))
    h.natoms = h.sizes["natoms"]
    h.step = h.sizes["step"]
    # float width inferred exactly as upstream nFloatSize()
    if h.sizes["box_size"]:
        h.flsize = h.sizes["box_size"] // 9
    elif h.sizes["x_size"]:
        h.flsize = h.sizes["x_size"] // (3 * h.natoms)
    elif h.sizes["v_size"]:
        h.flsize = h.sizes["v_size"] // (3 * h.natoms)
    elif h.sizes["f_size"]:
        h.flsize = h.sizes["f_size"] // (3 * h.natoms)
    else:
        h.flsize = 4
    if h.flsize not in (4, 8):
        raise IOError(f"unsupported TRR float width {h.flsize} in {path!r}")
    s = h.sizes
    h.payload_start = offset + _HEAD_BYTES + 2 * h.flsize   # after t, lambda
    h.x_off = (h.payload_start + s["box_size"] + s["vir_size"]
               + s["pres_size"])
    h.frame_bytes = (h.x_off - offset + s["x_size"] + s["v_size"]
                     + s["f_size"])
    return h


from mdanalysis_mpi_tpu.io import _offsets

_offset_cache_path = _offsets.cache_path    # shared scheme with XTC


def _scan(path: str):
    """Header-hop offset scan: seek+read ~90 bytes per frame (never the
    payload — a full-precision TRR can be tens of GB), with the shared
    mtime-validated cache (SURVEY.md §2.2 random-access requirement)."""
    cached = _offsets.load(path)
    if cached is not None:
        return cached
    mtime = os.path.getmtime(path)     # BEFORE the scan (_offsets.save)
    size = os.path.getsize(path)
    offsets = []
    natoms = -1
    with open(path, "rb") as f:
        pos = 0
        while pos < size:
            f.seek(pos)
            # header + t/lambda at their widest (2×f8): enough to size
            # the whole frame without touching its payload
            head = f.read(_HEAD_BYTES + 16)
            h = _parse_header(head, 0, path)
            if natoms == -1:
                natoms = h.natoms
            elif h.natoms != natoms:
                raise IOError(
                    f"TRR {path!r}: frame {len(offsets)} has {h.natoms} "
                    f"atoms, expected {natoms}")
            offsets.append(pos)
            pos += h.frame_bytes
    offsets = np.asarray(offsets, dtype=np.int64)
    _offsets.save(path, offsets, natoms, mtime)
    return offsets, natoms


class TRRReader(ReaderBase):
    """Random-access TRR reader (positions in Å, box as dimensions).

    Frames without positions (``x_size == 0`` — TRR interleaves
    energy-only frames in some workflows) raise on access rather than
    returning garbage.
    """

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        self._offsets, self._natoms = _scan(path)
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"TRR {path!r} has {self._natoms} atoms, expected {n_atoms}")
        self._file = open(path, "rb")

    @property
    def n_frames(self) -> int:
        return len(self._offsets)

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "TRRReader":
        return TRRReader(self._path)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _read_frame(self, i: int) -> Timestep:
        off = int(self._offsets[i])
        # read just this frame's bytes (header declares the exact size)
        self._file.seek(off)
        head = self._file.read(_HEAD_BYTES + 16)
        h = _parse_header(head, 0, self._path)
        self._file.seek(off)
        buf = self._file.read(h.frame_bytes)
        if h.sizes["x_size"] == 0:
            raise IOError(
                f"TRR {self._path!r} frame {i} carries no positions")
        fl = ">f4" if h.flsize == 4 else ">f8"
        x = np.frombuffer(buf, fl, 3 * h.natoms, h.x_off)
        coords = np.ascontiguousarray(
            x.astype(np.float32).reshape(h.natoms, 3)) * _NM_TO_A
        dims = None
        if h.sizes["box_size"]:
            vecs = np.frombuffer(buf, fl, 9, h.payload_start)
            dims = vectors_to_box(vecs.astype(np.float64).reshape(3, 3)
                                  * _NM_TO_A)
            if not dims[:3].any():
                dims = None
        # velocities (nm/ps → Å/ps) and forces (kJ/mol/nm → kJ/mol/Å)
        # when the frame carries them — upstream Timestep units
        vel = frc = None
        if h.sizes["v_size"]:
            v = np.frombuffer(buf, fl, 3 * h.natoms,
                              h.x_off + h.sizes["x_size"])
            vel = v.astype(np.float32).reshape(h.natoms, 3) * _NM_TO_A
        if h.sizes["f_size"]:
            fo = np.frombuffer(buf, fl, 3 * h.natoms,
                               h.x_off + h.sizes["x_size"]
                               + h.sizes["v_size"])
            frc = fo.astype(np.float32).reshape(h.natoms, 3) / _NM_TO_A
        t = float(np.frombuffer(buf, fl, 1, _HEAD_BYTES)[0])
        return Timestep(coords, frame=i, time=t, dimensions=dims,
                        velocities=vel, forces=frc)

    def frame_times(self, frames) -> np.ndarray:
        idx = np.asarray(list(frames), dtype=np.int64)
        times = np.empty(len(idx), dtype=np.float64)
        for j, i in enumerate(idx):
            self._file.seek(int(self._offsets[i]))
            head = self._file.read(_HEAD_BYTES + 16)
            h = _parse_header(head, 0, self._path)
            fl = ">f4" if h.flsize == 4 else ">f8"
            times[j] = np.frombuffer(head, fl, 1, _HEAD_BYTES)[0]
        return times

    def read_block(self, start: int, stop: int, sel=None, step: int = 1):
        if self.transformations:
            # transformed reads must go through the generic
            # read-transform-gather loop (ReaderBase)
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        n_out = self._natoms if sel is None else len(sel)
        if start == stop:
            return np.empty((0, n_out, 3), np.float32), None
        # one contiguous file read for the whole block, then per-frame
        # frombuffer views (the bulk staging path, SURVEY.md §7 layer 2)
        first = int(self._offsets[start])
        self._file.seek(first)
        if stop < self.n_frames:
            nbytes = int(self._offsets[stop]) - first
            buf = self._file.read(nbytes)
        else:
            buf = self._file.read()
        frames = range(start, stop, step)
        out = np.empty((len(frames), n_out, 3), dtype=np.float32)
        boxes = None
        for j, i in enumerate(frames):
            base = int(self._offsets[i]) - first
            # header fields parsed at `base` yield offsets relative to buf
            h = _parse_header(buf, base, self._path)
            if h.sizes["x_size"] == 0:
                raise IOError(
                    f"TRR {self._path!r} frame {i} carries no positions")
            fl = ">f4" if h.flsize == 4 else ">f8"
            x = np.frombuffer(buf, fl, 3 * h.natoms, h.x_off)
            frame = x.astype(np.float32).reshape(h.natoms, 3)
            out[j] = (frame if sel is None else frame[sel])
            if h.sizes["box_size"]:
                if boxes is None:
                    boxes = np.zeros((len(frames), 6), dtype=np.float32)
                vecs = np.frombuffer(buf, fl, 9, h.payload_start)
                boxes[j] = vectors_to_box(
                    vecs.astype(np.float64).reshape(3, 3) * _NM_TO_A)
        out *= _NM_TO_A
        return out, boxes


def write_trr(path: str, coordinates: np.ndarray,
              dimensions: np.ndarray | None = None,
              times: np.ndarray | None = None,
              steps: np.ndarray | None = None,
              velocities: np.ndarray | None = None,
              forces: np.ndarray | None = None) -> None:
    """Write (n_frames, n_atoms, 3) Å coordinates as a float32 TRR
    (positions + optional box/velocities/forces) — the fixture writer
    counterpart of :func:`TRRReader` (SURVEY.md §4).  ``velocities`` in
    Å/ps and ``forces`` in kJ/(mol·Å), the upstream Timestep units."""
    coords = np.asarray(coordinates, dtype=np.float32) / _NM_TO_A
    if coords.ndim != 3 or coords.shape[2] != 3:
        raise ValueError(f"coordinates must be (F, N, 3), got {coords.shape}")
    nframes, natoms = coords.shape[:2]
    # validate ALL per-frame metadata up front: a length mismatch
    # surfacing as an IndexError mid-loop would leave a partial file
    if times is not None and len(times) != nframes:
        raise ValueError(
            f"times has {len(times)} entries for {nframes} frames")
    if steps is not None and len(steps) != nframes:
        raise ValueError(
            f"steps has {len(steps)} entries for {nframes} frames")
    def _per_atom(name, arr, scale):
        """Shape-check + unit-convert in one place (the only copy of
        the Å↔nm scale factors)."""
        if arr is None:
            return None
        arr = np.asarray(arr, np.float32)
        if arr.shape != coords.shape:
            raise ValueError(
                f"{name} must be shaped like coordinates {coords.shape}, "
                f"got {arr.shape}")
        return arr * np.float32(scale)

    vel = _per_atom("velocities", velocities, 1.0 / _NM_TO_A)  # Å/ps→nm/ps
    frc = _per_atom("forces", forces, _NM_TO_A)                # /Å → /nm
    if dimensions is not None:
        dimensions = np.asarray(dimensions)
        if dimensions.ndim == 1:
            if dimensions.shape != (6,):
                raise ValueError(
                    f"dimensions must be (6,) or ({nframes}, 6), got "
                    f"{dimensions.shape}")
            dimensions = np.broadcast_to(dimensions, (nframes, 6))
        elif dimensions.shape != (nframes, 6):
            raise ValueError(
                f"dimensions must be (6,) or ({nframes}, 6), got "
                f"{dimensions.shape}")
    with open(path, "wb") as f:
        for i in range(nframes):
            box_size = 36 if dimensions is not None else 0
            x_size = 12 * natoms
            v_size = x_size if vel is not None else 0
            f_size = x_size if frc is not None else 0
            head = np.array([_MAGIC, len(_TAG) + 1], dtype=">i4").tobytes()
            head += np.array([len(_TAG)], dtype=">i4").tobytes() + _TAG
            fields = [0, 0, box_size, 0, 0, 0, 0, x_size, v_size, f_size,
                      natoms, int(steps[i]) if steps is not None else i, 0]
            head += np.asarray(fields, dtype=">i4").tobytes()
            t = float(times[i]) if times is not None else 0.0
            head += np.asarray([t, 0.0], dtype=">f4").tobytes()
            f.write(head)
            if dimensions is not None:
                vecs = box_to_vectors(dimensions[i]) / _NM_TO_A
                f.write(np.asarray(vecs, dtype=">f4").tobytes())
            f.write(np.ascontiguousarray(coords[i], np.float32)
                    .astype(">f4").tobytes())
            if vel is not None:
                f.write(np.ascontiguousarray(vel[i], np.float32)
                        .astype(">f4").tobytes())
            if frc is not None:
                f.write(np.ascontiguousarray(frc[i], np.float32)
                        .astype(">f4").tobytes())


trajectory_files.register("trr", TRRReader)
