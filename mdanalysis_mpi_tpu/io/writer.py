"""Streaming multi-frame trajectory writer (upstream ``mda.Writer``).

The reference's oracle workflow writes aligned trajectories to disk when
``in_memory=False`` (the default of upstream ``align.AlignTraj``, whose
in-memory variant the reference docstring pins at RMSF.py:12).  The
one-shot writers (:func:`~mdanalysis_mpi_tpu.io.xtc.write_xtc` et al.)
need the full ``(F, N, 3)`` array in memory; this class streams frames
in chunks of any size, so a 10k-frame alignment never materializes more
than one batch on the host.

Append strategy, per format:

- **XTC/TRR**: frames are self-delimiting XDR records at arbitrary
  offsets, so chunk files concatenate byte-wise into one valid
  trajectory (the same property the frame-parallel decoder and the
  bench fixture generator exploit).
- **DCD**: the header is a fixed 196-byte prefix (record markers + 84-
  byte icntrl + 92-byte title record + 12-byte natoms record — see
  ``dcd_write`` in ``io/native/trajio.cpp``); frames are fixed-size
  records.  Chunks after the first are appended with the header
  stripped, and the two frame-count fields (icntrl[0] at byte 8,
  icntrl[3] at byte 20, little-endian u32) are patched on ``close()``.
"""

from __future__ import annotations

import os
import struct

import numpy as np

_DCD_HEADER_BYTES = 196
_DCD_NFRAMES_OFFSETS = (8, 20)   # icntrl[0], icntrl[3]
_CHUNK_SUFFIX = ".mdtpu_chunk"


class TrajectoryWriter:
    """Write a trajectory file frame-by-frame or chunk-by-chunk.

    ``format`` defaults to the file extension (xtc | trr | dcd).
    ``write()`` accepts a single ``(N, 3)`` frame, an ``(F, N, 3)``
    chunk, an :class:`~mdanalysis_mpi_tpu.core.groups.AtomGroup`, a
    :class:`~mdanalysis_mpi_tpu.core.universe.Universe` (current frame,
    upstream ``W.write(ag)`` idiom), or a Timestep.  Times/steps default
    to the running frame index.  Context-manager friendly::

        with TrajectoryWriter("out.xtc") as w:
            for block, boxes in blocks:
                w.write(block, dimensions=boxes)
    """

    def __init__(self, path: str, n_atoms: int | None = None,
                 format: str | None = None, precision: float = 1000.0,
                 dt: float = 1.0):
        fmt = (format or os.path.splitext(path)[1].lstrip(".")).lower()
        if fmt == "nc":
            fmt = "ncdf"
        if fmt not in ("xtc", "trr", "dcd", "ncdf", "xyz"):
            raise ValueError(
                f"unsupported trajectory format {fmt!r} for {path!r} "
                "(xtc, trr, dcd, nc/ncdf, xyz)")
        self.path = path
        self.format = fmt
        self.n_atoms = n_atoms
        self.frames_written = 0
        self._precision = precision
        self._dt = dt
        self._box_flag: bool | None = None   # DCD/NetCDF: cells all-or-none
        self._vel_flag: bool | None = None   # NetCDF: velocities likewise
        self._ncdf_strip: int | None = None  # later chunks' header length
        self._file = open(path, "wb")
        # per-instance temp name: two writers targeting the same output
        # path (or a crashed run's leftover) must not clobber each
        # other's in-flight chunk file
        self._chunk_path = (f"{path}{_CHUNK_SUFFIX}"
                            f".{os.getpid()}.{id(self):x}")
        self._reap_orphans(path)
        self._closed = False

    @staticmethod
    def _reap_orphans(path: str) -> None:
        """Best-effort removal of chunk temp files a hard-killed run
        left behind for THIS output path — only when the embedded pid is
        verifiably dead (a live pid may still be mid-write)."""
        import glob

        for p in glob.glob(glob.escape(path + _CHUNK_SUFFIX) + ".*"):
            try:
                pid = int(p[len(path + _CHUNK_SUFFIX) + 1:].split(".")[0])
                os.kill(pid, 0)          # raises if no such process
            except ProcessLookupError:
                try:
                    os.remove(p)
                except OSError:
                    pass
            except (ValueError, OSError, PermissionError):
                pass

    # -- input normalization --

    def _coerce(self, obj):
        """obj → (coords (F,N,3) f32 view, dims (F,6) or None)."""
        from mdanalysis_mpi_tpu.core.groups import AtomGroup
        from mdanalysis_mpi_tpu.core.timestep import Timestep
        from mdanalysis_mpi_tpu.core.universe import Universe

        dims = None
        if isinstance(obj, Universe):
            obj = obj.atoms
        if isinstance(obj, AtomGroup):
            ts = obj.universe.trajectory.ts
            coords = obj.positions
            dims = ts.dimensions
        elif isinstance(obj, Timestep):
            coords = obj.positions
            dims = obj.dimensions
        else:
            coords = np.asarray(obj, dtype=np.float32)
        coords = np.asarray(coords, dtype=np.float32)
        if coords.ndim == 2:
            coords = coords[None]
        if coords.ndim != 3 or coords.shape[2] != 3:
            raise ValueError(
                f"expected (N, 3) or (F, N, 3) coordinates, got "
                f"{coords.shape}")
        if dims is not None:
            dims = np.broadcast_to(np.asarray(dims, np.float32),
                                   (len(coords), 6))
        return coords, dims

    def write(self, obj, dimensions=None, times=None, steps=None,
              velocities=None, forces=None) -> int:
        """Append one frame or a chunk of frames; returns frames written
        so far."""
        if self._closed:
            raise ValueError(f"writer for {self.path!r} is closed")
        coords, auto_dims = self._coerce(obj)
        if dimensions is not None and self.format == "xyz":
            # EXPLICIT dimensions are refused like every unstorable
            # field; a Universe/AtomGroup's auto-dims stay droppable so
            # W.write(u) keeps working for box-carrying sources
            raise ValueError(
                "xyz stores no unit cell; drop dimensions= (the text "
                "format carries coordinates only)")
        if dimensions is None and self.format != "xyz":
            dimensions = auto_dims
        elif np.ndim(dimensions) == 1:
            dimensions = np.broadcast_to(
                np.asarray(dimensions, np.float32), (len(coords), 6))
        nf, na = coords.shape[:2]
        if nf == 0:
            # header-only chunks must not splice (a second chunk would
            # then keep its own header and corrupt dcd/ncdf record data)
            return self.frames_written
        if self.n_atoms is None:
            self.n_atoms = na
        elif na != self.n_atoms:
            raise ValueError(
                f"frame has {na} atoms, writer opened for {self.n_atoms}")
        has_box = dimensions is not None
        # ALL refusals precede any state latching: a rejected write must
        # not leave _box_flag/_vel_flag poisoned for the retry
        if (times is not None or steps is not None) \
                and self.format == "xyz":
            raise ValueError(
                "xyz stores no per-frame times/steps (text frames only)")
        if velocities is not None and self.format not in ("trr", "ncdf"):
            raise ValueError(
                f"{self.format} cannot store velocities (use trr/ncdf)")
        if forces is not None and self.format != "trr":
            raise ValueError(
                f"{self.format} cannot store forces (use trr)")
        if (times is not None or steps is not None) and self.format == "dcd":
            raise ValueError(
                "dcd stores no per-frame times/steps (only a fixed dt in "
                "the header — pass dt= to the writer instead)")
        if steps is not None and self.format == "ncdf":
            raise ValueError(
                "ncdf stores no integer step variable (AMBER convention "
                "keeps time only) — pass times= instead")
        if self.format in ("dcd", "ncdf"):
            # cell blocks are all-or-none: they change the fixed record
            # structure (DCD block layout; NetCDF record stride)
            if self._box_flag is not None and self._box_flag != has_box:
                raise ValueError(
                    f"{self.format} cannot mix frames with and without "
                    "unit cells")
        if self.format == "ncdf" and self._vel_flag is not None \
                and self._vel_flag != (velocities is not None):
            raise ValueError(
                "ncdf cannot mix frames with and without velocities "
                "(the record structure is fixed at the first chunk)")
        if self.format in ("dcd", "ncdf") and self._box_flag is None:
            self._box_flag = has_box
        if self.format == "ncdf" and self._vel_flag is None:
            self._vel_flag = velocities is not None
        if velocities is not None:
            velocities = np.asarray(velocities, np.float32)
            if velocities.ndim == 2:
                velocities = velocities[None]   # single-frame form,
                #                                 like the coords coerce
        lo = self.frames_written
        if times is None:
            times = np.arange(lo, lo + nf, dtype=np.float32) * self._dt
        if steps is None:
            steps = np.arange(lo, lo + nf, dtype=np.int32)

        # one-shot-write the chunk, then splice its bytes (the whole
        # sequence under one cleanup so a failed chunk write does not
        # leak the temp file)
        try:
            if self.format == "xtc":
                from mdanalysis_mpi_tpu.io.xtc import write_xtc

                write_xtc(self._chunk_path, coords, dimensions=dimensions,
                          times=np.asarray(times, np.float32),
                          steps=np.asarray(steps, np.int32),
                          precision=self._precision)
                strip = 0
            elif self.format == "trr":
                from mdanalysis_mpi_tpu.io.trr import write_trr

                write_trr(self._chunk_path, coords, dimensions=dimensions,
                          times=np.asarray(times, np.float32),
                          steps=np.asarray(steps, np.int32),
                          velocities=velocities, forces=forces)
                strip = 0
            elif self.format == "xyz":
                from mdanalysis_mpi_tpu.io.xyz import write_xyz

                write_xyz(self._chunk_path, coords,
                          start=self.frames_written)
                strip = 0
            elif self.format == "ncdf":
                from mdanalysis_mpi_tpu.io.netcdf import write_ncdf

                write_ncdf(self._chunk_path, coords,
                           dimensions=dimensions,
                           times=np.asarray(times, np.float32),
                           velocities=velocities)
                if self.frames_written == 0:
                    strip = 0
                else:
                    if self._ncdf_strip is None:   # pragma: no cover
                        raise AssertionError("ncdf strip unset")
                    strip = self._ncdf_strip
                if self._ncdf_strip is None:
                    # header length is constant across chunks (same
                    # n_atoms/box/velocity structure — enforced above),
                    # measured once from the first chunk
                    from mdanalysis_mpi_tpu.io.netcdf import _NC3Header

                    with open(self._chunk_path, "rb") as cf:
                        hdr = _NC3Header(cf.read(65536),
                                         self._chunk_path)
                    self._ncdf_strip = min(
                        v["begin"] for v in hdr.vars.values()
                        if v["record"])
            else:
                from mdanalysis_mpi_tpu.io.dcd import write_dcd

                write_dcd(self._chunk_path, coords, dimensions=dimensions,
                          dt=self._dt)
                strip = 0 if self.frames_written == 0 else _DCD_HEADER_BYTES
            with open(self._chunk_path, "rb") as f:
                if strip:
                    f.seek(strip)
                while True:
                    buf = f.read(1 << 24)
                    if not buf:
                        break
                    self._file.write(buf)
        finally:
            if os.path.exists(self._chunk_path):
                os.remove(self._chunk_path)
        self.frames_written += nf
        return self.frames_written

    def close(self) -> None:
        if self._closed:
            return
        self._file.close()
        self._closed = True
        if self.format == "ncdf" and self.frames_written:
            # patch numrecs (bytes 4:8, big-endian) — each chunk's
            # header recorded only its own frame count
            with open(self.path, "r+b") as f:
                f.seek(4)
                f.write(struct.pack(">i", self.frames_written))
        if self.format == "dcd" and self.frames_written:
            # patch the two frame-count fields the first chunk's header
            # recorded for only its own frames
            with open(self.path, "r+b") as f:
                for off in _DCD_NFRAMES_OFFSETS:
                    f.seek(off)
                    f.write(struct.pack("<I", self.frames_written))

    def __del__(self):
        # a never-closed DCD writer would otherwise leave the header's
        # frame count claiming only the first chunk's frames
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "TrajectoryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def Writer(path: str, n_atoms: int | None = None, **kwargs):
    """Upstream-style factory: ``mda.Writer(filename, n_atoms)``."""
    return TrajectoryWriter(path, n_atoms=n_atoms, **kwargs)
