// trajio: native trajectory codecs for mdanalysis_mpi_tpu.
//
// XTC: XDR-encoded frames with GROMACS "3dfcoord" fixed-point bit-packed
// compression (the reference reads XTC through the MDAnalysis
// Cython/C libxdrfile path, RMSF.py:56,92,124 — SURVEY.md §2.2).  This
// is a from-scratch implementation of the documented wire format: XDR
// big-endian primitives; per-frame header (magic 1995, natoms, step,
// time, 3x3 box); coordinates quantized to ints at `precision`,
// bounding-box offset, mixed-radix big-int bit packing (sizeofints /
// sendints / receiveints scheme), optional delta-runs against a
// "small" window with the first-two-atoms interchange and smallidx
// adaptation on decode.
//
// DCD: CHARMM/NAMD binary with Fortran record markers, optional unit
// cell record per frame, X/Y/Z float records (BASELINE config 1's
// format).
//
// C ABI only (consumed via ctypes).  All coordinate buffers are
// caller-allocated.  Functions return 0 on success, negative on error.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <cmath>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// XDR primitives (big-endian)
// ---------------------------------------------------------------------

struct Reader {
    FILE* f;
    bool ok = true;
    uint32_t u32() {
        unsigned char b[4];
        if (fread(b, 1, 4, f) != 4) { ok = false; return 0; }
        return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
               (uint32_t(b[2]) << 8) | uint32_t(b[3]);
    }
    int32_t i32() { return (int32_t)u32(); }
    float f32() {
        uint32_t v = u32();
        float out;
        std::memcpy(&out, &v, 4);
        return out;
    }
    bool bytes(unsigned char* dst, size_t n) {
        if (fread(dst, 1, n, f) != n) { ok = false; return false; }
        return true;
    }
    bool skip(long n) { return fseek(f, n, SEEK_CUR) == 0; }
};

struct Writer {
    FILE* f;
    void u32(uint32_t v) {
        unsigned char b[4] = {
            (unsigned char)(v >> 24), (unsigned char)(v >> 16),
            (unsigned char)(v >> 8), (unsigned char)v};
        fwrite(b, 1, 4, f);
    }
    void i32(int32_t v) { u32((uint32_t)v); }
    void f32(float x) {
        uint32_t v;
        std::memcpy(&v, &x, 4);
        u32(v);
    }
    void bytes(const unsigned char* src, size_t n) { fwrite(src, 1, n, f); }
};

// ---------------------------------------------------------------------
// Bit packing (the xdrfile bit-stream convention: MSB-first into a byte
// buffer through a (cnt, lastbits, lastbyte) accumulator)
// ---------------------------------------------------------------------

struct BitWriter {
    std::vector<unsigned char> buf;
    unsigned int lastbits = 0;
    unsigned int lastbyte = 0;

    void bits(int nbits, unsigned int num) {
        unsigned int mask = nbits < 32 ? (1u << nbits) - 1 : 0xffffffffu;
        num &= mask;
        while (nbits >= 8) {
            lastbyte = (lastbyte << 8) | ((num >> (nbits - 8)) & 0xff);
            buf.push_back((unsigned char)(lastbyte >> lastbits));
            nbits -= 8;
        }
        if (nbits > 0) {
            lastbyte = (lastbyte << nbits) | (num & ((1u << nbits) - 1));
            lastbits += nbits;
            if (lastbits >= 8) {
                lastbits -= 8;
                buf.push_back((unsigned char)(lastbyte >> lastbits));
            }
        }
    }
    std::vector<unsigned char> finish() {
        std::vector<unsigned char> out = buf;
        if (lastbits > 0)
            out.push_back((unsigned char)(lastbyte << (8 - lastbits)));
        return out;
    }
};

struct BitReader {
    // 64-bit accumulator bit reader (MSB-first stream).  The stream's
    // next unread bit sits at bit 63 of `acc`; refills are greedy
    // (top up to >56 valid bits whenever a read finds too few), so the
    // per-call cost is a shift/mask pair instead of the byte-at-a-time
    // fetch loop the format's reference decoders use — the decode hot
    // path (receiveints) calls this for every 8-bit digit.  Reads past
    // the end deliver zero bits and flip `ok` (checked per atom group).
    const unsigned char* data;
    size_t n;
    size_t pos = 0;
    uint64_t acc = 0;
    int navail = 0;
    size_t consumed = 0;     // bits handed out
    bool ok = true;

    inline unsigned int bits(int nbits) {
        if (nbits <= 0) return 0;
        if (navail < nbits) {
            do {
                uint64_t byte = pos < n ? data[pos] : 0;
                ++pos;
                acc |= byte << (56 - navail);
                navail += 8;
            } while (navail <= 56);
        }
        unsigned int out = (unsigned int)(acc >> (64 - nbits));
        acc <<= nbits;
        navail -= nbits;
        consumed += (size_t)nbits;
        if (consumed > 8 * n) ok = false;
        return out;
    }

    // Wide read, up to 57 bits in one shift/mask (the refill loop
    // guarantees >56 valid bits).  Lets receiveints consume a whole
    // group's full bytes in ONE accumulator operation instead of one
    // per 8-bit digit — measured 2.2x on the decode hot loop.
    inline uint64_t bits57(int nbits) {
        if (navail < nbits) {
            do {
                uint64_t byte = pos < n ? data[pos] : 0;
                ++pos;
                acc |= byte << (56 - navail);
                navail += 8;
            } while (navail <= 56);
        }
        uint64_t out = acc >> (64 - nbits);
        acc <<= nbits;
        navail -= nbits;
        consumed += (size_t)nbits;
        if (consumed > 8 * n) ok = false;
        return out;
    }
};

static const int magicints[] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 8, 10, 12, 16, 20, 25, 32, 40, 50, 64, 80,
    101, 128, 161, 203, 256, 322, 406, 512, 645, 812, 1024, 1290, 1625,
    2048, 2580, 3250, 4096, 5060, 6501, 8192, 10321, 13003, 16384, 20642,
    26007, 32768, 41285, 52015, 65536, 82570, 104031, 131072, 165140,
    208063, 262144, 330280, 416127, 524287, 660561, 832255, 1048576,
    1321122, 1664510, 2097152, 2642245, 3329021, 4194304, 5284491,
    6658042, 8388607, 10568983, 13316085, 16777216};
static const int FIRSTIDX = 9;
static const int LASTIDX = int(sizeof(magicints) / sizeof(int)) - 1;

static int sizeofint(unsigned int size) {
    int nbits = 0;
    unsigned int num = 1;
    while (size >= num && nbits < 32) {
        nbits++;
        num <<= 1;
    }
    return nbits;
}

static int sizeofints(int nints, const unsigned int sizes[]) {
    unsigned int bytes[32];
    unsigned int nbytes = 1, bytecnt, tmp;
    bytes[0] = 1;
    int nbits = 0;
    for (int i = 0; i < nints; i++) {
        tmp = 0;
        for (bytecnt = 0; bytecnt < nbytes; bytecnt++) {
            tmp = bytes[bytecnt] * sizes[i] + tmp;
            bytes[bytecnt] = tmp & 0xff;
            tmp >>= 8;
        }
        while (tmp != 0) {
            bytes[bytecnt++] = tmp & 0xff;
            tmp >>= 8;
        }
        nbytes = bytecnt;
    }
    unsigned int num = 1;
    nbytes--;
    while (bytes[nbytes] >= num) {
        nbits++;
        num *= 2;
    }
    return nbits + nbytes * 8;
}

static void sendints(BitWriter& bw, int nbits,
                     const unsigned int sizes[], const unsigned int nums[]) {
    // Mixed-radix pack of one coordinate triple: the value fits 128
    // bits (sizes < 2^25 → < 2^75), so one Horner evaluation replaces
    // the per-digit long-multiplication loop of the format's reference
    // encoders, and the base-256 digits stream out LSB-first in <=8-bit
    // fields — emitting exactly ``nbits`` total, identical layout to
    // (and round-trip-fuzzed against) the digit-buffer formulation.
    unsigned __int128 v =
        ((unsigned __int128)nums[0] * sizes[1] + nums[1]) * sizes[2]
        + nums[2];
    while (nbits > 8) {
        bw.bits(8, (unsigned int)((uint64_t)v & 0xff));
        v >>= 8;
        nbits -= 8;
    }
    if (nbits > 0)
        bw.bits(nbits, (unsigned int)((uint64_t)v & ((1u << nbits) - 1)));
}

static void receiveints(BitReader& br, int nints, int nbits,
                        const unsigned int sizes[], int nums[]) {
    // Mixed-radix big-int decode.  Base-256 digits arrive LSB-first in
    // the bit stream; every XTC group fits 128 bits (the triple-int
    // packing caps each size below 2^25, so nbits <= ~75), so the whole
    // value assembles into one __int128 and each int peels off with a
    // single div/mod — replacing the per-byte long-division loop of the
    // format's reference decoders (the decode hot path: one call per
    // absolute coordinate triple and one per small-run triple).
    unsigned __int128 v = 0;
    if (nbits <= 57 && nbits >= 8) {
        // fast lane: all full bytes in ONE wide read.  The stream is
        // MSB-first, so a wide read yields digit 0 (the value's LSB) in
        // its TOP byte; bswap64 restores little-endian digit order.
        int fb = nbits >> 3, rem = nbits & 7;
        uint64_t x = br.bits57(fb * 8);
        uint64_t lo = __builtin_bswap64(x) >> (64 - fb * 8);
        if (rem) lo |= (uint64_t)br.bits(rem) << (fb * 8);
        v = lo;
    } else {
        int shift = 0;
        while (nbits > 8) {
            v |= (unsigned __int128)br.bits(8) << shift;
            shift += 8;
            nbits -= 8;
        }
        if (nbits > 0) v |= (unsigned __int128)br.bits(nbits) << shift;
    }
    for (int i = nints - 1; i > 0; i--) {
        unsigned int s = sizes[i];
        if ((uint64_t)(v >> 64) == 0) {       // 64-bit fast lane
            uint64_t lo = (uint64_t)v;
            nums[i] = (int)(lo % s);
            v = lo / s;
        } else {
            nums[i] = (int)(uint64_t)(v % s);
            v /= s;
        }
    }
    nums[0] = (int)(uint64_t)v;
}

// ---------------------------------------------------------------------
// XTC 3dfcoord frame codec
// ---------------------------------------------------------------------

static const int XTC_MAGIC = 1995;

// Decode the compressed coordinate section (after lsize has been read).
// Returns 0 on success.  ``databuf`` is the caller's REUSABLE scratch
// for the compressed payload: one ~0.5 MB heap allocation per frame
// across a 10k-frame range is ~5 GB of page churn, and on the
// virtualized bench target fresh pages arrive 15-35x slower once the
// process is a few GB resident (measured; the device-mirror RSS of
// HBM-cached blocks puts the flagship run there) — a per-range buffer
// faults its pages once.
static int xtc_decode_coords(Reader& r, int lsize,
                             std::vector<unsigned char>& databuf,
                             float* out /*lsize*3*/) {
    if (lsize <= 9) {
        for (int i = 0; i < lsize * 3; i++) out[i] = r.f32();
        return r.ok ? 0 : -2;
    }
    float precision = r.f32();
    int minint[3], maxint[3];
    for (int k = 0; k < 3; k++) minint[k] = r.i32();
    for (int k = 0; k < 3; k++) maxint[k] = r.i32();
    int smallidx = r.i32();
    if (!r.ok || smallidx < FIRSTIDX || smallidx > LASTIDX) return -3;

    unsigned int sizeint[3], sizesmall[3];
    int bitsizeint[3] = {0, 0, 0};
    int bitsize;
    for (int k = 0; k < 3; k++)
        sizeint[k] = (unsigned int)(maxint[k] - minint[k]) + 1;
    if ((sizeint[0] | sizeint[1] | sizeint[2]) > 0xffffff) {
        for (int k = 0; k < 3; k++) bitsizeint[k] = sizeofint(sizeint[k]);
        bitsize = 0;
    } else {
        bitsize = sizeofints(3, sizeint);
    }

    int smaller = magicints[smallidx > FIRSTIDX ? smallidx - 1 : FIRSTIDX] / 2;
    int smallnum = magicints[smallidx] / 2;
    sizesmall[0] = sizesmall[1] = sizesmall[2] =
        (unsigned int)magicints[smallidx];

    int nbytes = r.i32();
    if (!r.ok || nbytes < 0 || nbytes > (1 << 30)) return -4;
    size_t padded = (size_t)((nbytes + 3) / 4) * 4;
    if (databuf.size() < padded) databuf.resize(padded);
    std::vector<unsigned char>& data = databuf;
    if (!r.bytes(data.data(), padded)) return -5;

    BitReader br{data.data(), (size_t)nbytes};
    float inv = 1.0f / precision;
    int i = 0;
    int run = 0;
    int prevcoord[3] = {0, 0, 0};
    int thiscoord[3];
    float* lfp = out;

    while (i < lsize) {
        if (bitsize == 0) {
            for (int k = 0; k < 3; k++)
                thiscoord[k] = (int)br.bits(bitsizeint[k]);
        } else {
            receiveints(br, 3, bitsize, sizeint, thiscoord);
        }
        i++;
        for (int k = 0; k < 3; k++) thiscoord[k] += minint[k];
        for (int k = 0; k < 3; k++) prevcoord[k] = thiscoord[k];

        unsigned int flag = br.bits(1);
        int is_smaller = 0;
        run = 0;
        if (flag == 1) {
            run = (int)br.bits(5);
            is_smaller = run % 3;
            run -= is_smaller;
            is_smaller--;
        }
        if (run > 0) {
            for (int k = 0; k < run; k += 3) {
                receiveints(br, 3, smallidx, sizesmall, thiscoord);
                i++;
                for (int d = 0; d < 3; d++)
                    thiscoord[d] += prevcoord[d] - smallnum;
                if (k == 0) {
                    // first two atoms interchanged for better water
                    // compression: output the small atom before the
                    // absolute one
                    for (int d = 0; d < 3; d++) {
                        int tmp = thiscoord[d];
                        thiscoord[d] = prevcoord[d];
                        prevcoord[d] = tmp;
                    }
                    for (int d = 0; d < 3; d++)
                        *lfp++ = (float)prevcoord[d] * inv;
                } else {
                    for (int d = 0; d < 3; d++) prevcoord[d] = thiscoord[d];
                }
                for (int d = 0; d < 3; d++)
                    *lfp++ = (float)thiscoord[d] * inv;
            }
        } else {
            for (int d = 0; d < 3; d++) *lfp++ = (float)thiscoord[d] * inv;
        }
        smallidx += is_smaller;
        if (is_smaller < 0) {
            smallnum = smaller;
            smaller = smallidx > FIRSTIDX ? magicints[smallidx - 1] / 2 : 0;
        } else if (is_smaller > 0) {
            smaller = smallnum;
            smallnum = magicints[smallidx] / 2;
        }
        sizesmall[0] = sizesmall[1] = sizesmall[2] =
            (unsigned int)magicints[smallidx];
        if (sizesmall[0] == 0) return -6;
        if (!br.ok) return -7;
    }
    return 0;
}

// Encode one frame's coordinates (after lsize has been written).
static int xtc_encode_coords(Writer& w, int lsize, const float* in,
                             float precision) {
    if (lsize <= 9) {
        for (int i = 0; i < lsize * 3; i++) w.f32(in[i]);
        return 0;
    }
    w.f32(precision);
    std::vector<int> lip((size_t)lsize * 3);
    int minint[3] = {INT32_MAX, INT32_MAX, INT32_MAX};
    int maxint[3] = {INT32_MIN, INT32_MIN, INT32_MIN};
    for (int i = 0; i < lsize; i++) {
        for (int k = 0; k < 3; k++) {
            float v = in[i * 3 + k] * precision;
            if (v >= 2097152.0f || v <= -2097152.0f) return -10;  // 2^21 cap
            int iv = (int)lroundf(v);
            lip[i * 3 + k] = iv;
            if (iv < minint[k]) minint[k] = iv;
            if (iv > maxint[k]) maxint[k] = iv;
        }
    }
    for (int k = 0; k < 3; k++) w.i32(minint[k]);
    for (int k = 0; k < 3; k++) w.i32(maxint[k]);

    unsigned int sizeint[3], sizesmall[3];
    int bitsizeint[3] = {0, 0, 0};
    int bitsize;
    for (int k = 0; k < 3; k++)
        sizeint[k] = (unsigned int)(maxint[k] - minint[k]) + 1;
    if ((sizeint[0] | sizeint[1] | sizeint[2]) > 0xffffff) {
        for (int k = 0; k < 3; k++) bitsizeint[k] = sizeofint(sizeint[k]);
        bitsize = 0;
    } else {
        bitsize = sizeofints(3, sizeint);
    }

    // initial small window from the typical consecutive-atom delta
    int mindiff = INT32_MAX;
    for (int i = 1; i < lsize; i++) {
        int d = 0;
        for (int k = 0; k < 3; k++) {
            int a = lip[i * 3 + k] - lip[(i - 1) * 3 + k];
            if (a < 0) a = -a;
            if (a > d) d = a;
        }
        if (d < mindiff) mindiff = d;
    }
    int smallidx = FIRSTIDX;
    while (smallidx < LASTIDX && magicints[smallidx] < 2 * mindiff + 2)
        smallidx++;
    w.i32(smallidx);
    int smallnum = magicints[smallidx] / 2;
    sizesmall[0] = sizesmall[1] = sizesmall[2] =
        (unsigned int)magicints[smallidx];

    BitWriter bw;
    int i = 0;
    while (i < lsize) {
        // probe a run: atoms i..i+m where, after the interchange, the
        // decoder's delta chain stays inside the small window.  Chain
        // (decoder order): s0 = x_i rel A=x_{i+1}; x_{i+2} rel x_i;
        // x_{j} rel x_{j-1} beyond that.
        int m = 0;  // number of small atoms in the run
        if (i + 1 < lsize) {
            auto fits = [&](const int* a, const int* b) {
                for (int k = 0; k < 3; k++) {
                    int d = a[k] - b[k] + smallnum;
                    if (d < 0 || d >= (int)sizesmall[0]) return false;
                }
                return true;
            };
            // candidate: s0=x_i vs abs x_{i+1}
            if (fits(&lip[i * 3], &lip[(i + 1) * 3])) {
                m = 1;
                const int* prev = &lip[i * 3];
                int j = i + 2;
                while (m < 8 && j < lsize && fits(&lip[j * 3], prev)) {
                    prev = &lip[j * 3];
                    m++;
                    j++;
                }
            }
        }
        if (m > 0) {
            // absolute atom = x_{i+1}
            unsigned int abs3[3];
            for (int k = 0; k < 3; k++)
                abs3[k] = (unsigned int)(lip[(i + 1) * 3 + k] - minint[k]);
            if (bitsize == 0)
                for (int k = 0; k < 3; k++) bw.bits(bitsizeint[k], abs3[k]);
            else
                sendints(bw, bitsize, sizeint, abs3);
            bw.bits(1, 1);
            bw.bits(5, (unsigned int)(m * 3 + 1));  // is_smaller enc = 0
            // small atoms in decoder chain order
            const int* prev = &lip[(i + 1) * 3];  // abs for s0
            int src = i;                          // s0 = x_i
            for (int t = 0; t < m; t++) {
                unsigned int d3[3];
                for (int k = 0; k < 3; k++)
                    d3[k] = (unsigned int)(lip[src * 3 + k] - prev[k] +
                                           smallnum);
                sendints(bw, smallidx, sizesmall, d3);
                if (t == 0) {
                    prev = &lip[i * 3];  // decoder's prevcoord = s0 after swap
                    src = i + 2;
                } else {
                    prev = &lip[src * 3];
                    src++;
                }
            }
            i += m + 1;
        } else {
            unsigned int abs3[3];
            for (int k = 0; k < 3; k++)
                abs3[k] = (unsigned int)(lip[i * 3 + k] - minint[k]);
            if (bitsize == 0)
                for (int k = 0; k < 3; k++) bw.bits(bitsizeint[k], abs3[k]);
            else
                sendints(bw, bitsize, sizeint, abs3);
            bw.bits(1, 0);
            i += 1;
        }
    }
    std::vector<unsigned char> data = bw.finish();
    w.i32((int)data.size());
    size_t padded = ((data.size() + 3) / 4) * 4;
    data.resize(padded, 0);
    w.bytes(data.data(), data.size());
    return 0;
}

// Skip the coordinate section without decoding (for offset scans).
static int xtc_skip_coords(Reader& r, int lsize) {
    if (lsize <= 9) return r.skip((long)lsize * 3 * 4) ? 0 : -2;
    // precision + minint*3 + maxint*3 + smallidx
    if (!r.skip(4 * 8)) return -2;
    int nbytes = r.i32();
    if (!r.ok || nbytes < 0) return -3;
    return r.skip(((long)nbytes + 3) / 4 * 4) ? 0 : -4;
}

struct XtcHeader {
    int natoms, step;
    float time;
    float box[9];
};

static int xtc_read_header(Reader& r, XtcHeader& h) {
    int magic = r.i32();
    if (!r.ok) return 1;  // clean EOF
    if (magic != XTC_MAGIC) return -1;
    h.natoms = r.i32();
    h.step = r.i32();
    h.time = r.f32();
    for (int k = 0; k < 9; k++) h.box[k] = r.f32();
    return r.ok ? 0 : -2;
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------

extern "C" {

// Scan an XTC file: count frames, record byte offsets + natoms.
// offsets may be null (count only).  Returns n_frames or negative error.
long xtc_scan(const char* path, int* natoms_out, long* offsets,
              long max_offsets) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    long fsize = ftell(f);
    fseek(f, 0, SEEK_SET);
    Reader r{f};
    long n = 0;
    int natoms = -1;
    while (true) {
        long off = ftell(f);
        XtcHeader h;
        int rc = xtc_read_header(r, h);
        if (rc == 1) break;  // EOF
        if (rc < 0) { fclose(f); return -2; }
        if (natoms < 0) natoms = h.natoms;
        else if (h.natoms != natoms) { fclose(f); return -3; }
        int lsize = r.i32();
        if (!r.ok || lsize != natoms) { fclose(f); return -4; }
        if (xtc_skip_coords(r, lsize) != 0) { fclose(f); return -5; }
        // drop an incomplete trailing frame (fseek past EOF succeeds, so
        // compare against the real file size; lenient like upstream)
        if (ftell(f) > fsize) break;
        if (offsets) {
            if (n >= max_offsets) { fclose(f); return -6; }
            offsets[n] = off;
        }
        n++;
    }
    fclose(f);
    if (natoms_out) *natoms_out = natoms;
    return n;
}

// Read n frames at the given byte offsets into coords (n*natoms*3).
// box (n*9, may be null), times (n, may be null), steps (n, may be null).
// One worker's slice of an xtc_read_frames call: frames are fully
// independent (every frame is self-delimiting at its known offset), so
// each worker opens its own FILE* and decodes a contiguous range.
static int xtc_read_range(const char* path, const long* offsets,
                          long lo, long hi, int natoms, float* coords,
                          float* box, float* times, int* steps) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    Reader r{f};
    std::vector<unsigned char> databuf;
    for (long i = lo; i < hi; i++) {
        if (fseek(f, offsets[i], SEEK_SET) != 0) { fclose(f); return -2; }
        XtcHeader h;
        if (xtc_read_header(r, h) != 0) { fclose(f); return -3; }
        if (h.natoms != natoms) { fclose(f); return -4; }
        int lsize = r.i32();
        if (!r.ok || lsize != natoms) { fclose(f); return -5; }
        int rc = xtc_decode_coords(r, lsize, databuf,
                                   coords + (size_t)i * natoms * 3);
        if (rc != 0) { fclose(f); return rc; }
        if (box) std::memcpy(box + i * 9, h.box, 9 * sizeof(float));
        if (times) times[i] = h.time;
        if (steps) steps[i] = h.step;
    }
    fclose(f);
    return 0;
}

// Frame-parallel decode: MDTPU_DECODE_THREADS workers (default 1; the
// decode is compute-bound bit-twiddling, so threads scale ~linearly on
// multi-core hosts — the v5e-8 target — while a single-core host keeps
// the sequential path with zero thread overhead).  Correctness is
// thread-count-independent: workers write disjoint frame ranges.
// Worker-count policy shared by the decode/stage entry points: clamp
// near real parallelism — more workers than cores cannot help (the
// decode is compute-bound) and unbounded counts would risk std::thread
// construction failure, which must not unwind across this C ABI.  The
// small floor keeps the threaded path testable on 1-core hosts.
static long xtc_nthreads(long n) {
    long nthreads = 1;
    if (const char* env = getenv("MDTPU_DECODE_THREADS")) {
        nthreads = atol(env);
        if (nthreads < 1) nthreads = 1;
        long hw = (long)std::thread::hardware_concurrency();
        long cap = hw >= 4 ? hw : 4;
        if (nthreads > cap) nthreads = cap;
    }
    if (nthreads > n) nthreads = n > 0 ? n : 1;
    return nthreads;
}

int xtc_read_frames(const char* path, const long* offsets, long n,
                    int natoms, float* coords, float* box, float* times,
                    int* steps) {
    long nthreads = xtc_nthreads(n);
    if (nthreads == 1)
        return xtc_read_range(path, offsets, 0, n, natoms, coords, box,
                              times, steps);
    std::vector<std::thread> workers;
    std::vector<int> rcs((size_t)nthreads, 0);
    long per = n / nthreads, extra = n % nthreads, lo = 0;
    for (long t = 0; t < nthreads; t++) {
        long hi = lo + per + (t < extra ? 1 : 0);
        workers.emplace_back([=, &rcs]() {
            rcs[(size_t)t] = xtc_read_range(path, offsets, lo, hi, natoms,
                                            coords, box, times, steps);
        });
        lo = hi;
    }
    for (auto& w : workers) w.join();
    for (int rc : rcs)
        if (rc != 0) return rc;
    return 0;
}

// ---------------------------------------------------------------------
// Fused cold-path staging: decode → gather → unit-convert (→ quantize)
// without ever materializing the full-system float32 block.  The plain
// cold path writes natoms*3 floats per frame into a (B, N, 3) block,
// re-reads it to gather the selection, and (int16 path) streams it once
// more to quantize — at 100k atoms / 50k selected that is ~3.6 MB of
// DRAM traffic per frame on top of the decode itself.  Here each frame
// decodes into a per-worker scratch that stays cache-hot and only the
// selection's int16/f32 bytes ever reach DRAM.  Same thread model as
// xtc_read_frames (disjoint frame ranges, MDTPU_DECODE_THREADS).
// ---------------------------------------------------------------------

// One worker's range.  sel may be null (all atoms).  out is int16
// (n_sel*3 per frame, quantized Å at `scale`); box (9 per frame, nm) may
// be null.  Tracks the worker's max |x| in Å; clamps on overflow (the
// caller rejects via the vmax*scale criterion, like
// stage_gather_quantize_i16_scaled).
static int xtc_stage_range_i16(const char* path, const long* offsets,
                               long lo, long hi, int natoms,
                               const int32_t* sel, long n_sel, float scale,
                               int16_t* out, float* box, float* vmax_out) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    Reader r{f};
    std::vector<float> scratch((size_t)natoms * 3);
    std::vector<unsigned char> databuf;
    float vmax = 0.0f;
    for (long i = lo; i < hi; i++) {
        if (fseek(f, offsets[i], SEEK_SET) != 0) { fclose(f); return -2; }
        XtcHeader h;
        if (xtc_read_header(r, h) != 0) { fclose(f); return -3; }
        if (h.natoms != natoms) { fclose(f); return -4; }
        int lsize = r.i32();
        if (!r.ok || lsize != natoms) { fclose(f); return -5; }
        int rc = xtc_decode_coords(r, lsize, databuf, scratch.data());
        if (rc != 0) { fclose(f); return rc; }
        int16_t* o = out + (size_t)i * n_sel * 3;
        for (long s = 0; s < n_sel; s++) {
            const float* p = scratch.data()
                + (size_t)(sel ? sel[s] : s) * 3;
            for (int d = 0; d < 3; d++) {
                // nm → Å as a float32 multiply, then the same f32
                // product + round-half-even as the block quantizers
                // (bit-compatible with the decode-then-quantize path)
                float x = p[d] * 10.0f;
                float a = std::fabs(x);
                if (a > vmax) vmax = a;
                float q = std::nearbyintf(x * scale);
                if (q > 32767.0f) q = 32767.0f;
                if (q < -32767.0f) q = -32767.0f;
                o[s * 3 + d] = (int16_t)q;
            }
        }
        if (box) std::memcpy(box + i * 9, h.box, 9 * sizeof(float));
    }
    fclose(f);
    *vmax_out = vmax;
    return 0;
}

// float32 variant: decode → gather → nm→Å, selection bytes only.
static int xtc_stage_range_f32(const char* path, const long* offsets,
                               long lo, long hi, int natoms,
                               const int32_t* sel, long n_sel,
                               float* out, float* box) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    Reader r{f};
    std::vector<float> scratch((size_t)natoms * 3);
    std::vector<unsigned char> databuf;
    for (long i = lo; i < hi; i++) {
        if (fseek(f, offsets[i], SEEK_SET) != 0) { fclose(f); return -2; }
        XtcHeader h;
        if (xtc_read_header(r, h) != 0) { fclose(f); return -3; }
        if (h.natoms != natoms) { fclose(f); return -4; }
        int lsize = r.i32();
        if (!r.ok || lsize != natoms) { fclose(f); return -5; }
        int rc = xtc_decode_coords(r, lsize, databuf, scratch.data());
        if (rc != 0) { fclose(f); return rc; }
        float* o = out + (size_t)i * n_sel * 3;
        for (long s = 0; s < n_sel; s++) {
            const float* p = scratch.data()
                + (size_t)(sel ? sel[s] : s) * 3;
            o[s * 3 + 0] = p[0] * 10.0f;
            o[s * 3 + 1] = p[1] * 10.0f;
            o[s * 3 + 2] = p[2] * 10.0f;
        }
        if (box) std::memcpy(box + i * 9, h.box, 9 * sizeof(float));
    }
    fclose(f);
    return 0;
}

// Fused decode+stage, int16: returns 0 ok, 1 = the provided scale would
// have clipped real data (max_abs_out holds the true max in Å; caller
// re-runs with an exact scale), negative = decode error.
int xtc_stage_i16(const char* path, const long* offsets, long n,
                  int natoms, const int32_t* sel, long n_sel, float scale,
                  int16_t* out, float* box, float* max_abs_out) {
    if (n < 0 || natoms < 0 || n_sel < 0 || !(scale > 0.0f)) return -9;
    if (sel == nullptr) n_sel = natoms;
    long nthreads = xtc_nthreads(n);
    float vmax = 0.0f;
    if (nthreads == 1) {
        int rc = xtc_stage_range_i16(path, offsets, 0, n, natoms, sel,
                                     n_sel, scale, out, box, &vmax);
        if (rc != 0) return rc;
    } else {
        std::vector<std::thread> workers;
        std::vector<int> rcs((size_t)nthreads, 0);
        std::vector<float> vmaxs((size_t)nthreads, 0.0f);
        long per = n / nthreads, extra = n % nthreads, lo = 0;
        for (long t = 0; t < nthreads; t++) {
            long hi = lo + per + (t < extra ? 1 : 0);
            workers.emplace_back([=, &rcs, &vmaxs]() {
                rcs[(size_t)t] = xtc_stage_range_i16(
                    path, offsets, lo, hi, natoms, sel, n_sel, scale,
                    out, box, &vmaxs[(size_t)t]);
            });
            lo = hi;
        }
        for (auto& w : workers) w.join();
        for (int rc : rcs)
            if (rc != 0) return rc;
        for (float v : vmaxs)
            if (v > vmax) vmax = v;
    }
    *max_abs_out = vmax;
    return ((double)vmax * (double)scale > 32767.0) ? 1 : 0;
}

// Fused decode+stage, float32 (Å out).
int xtc_stage_f32(const char* path, const long* offsets, long n,
                  int natoms, const int32_t* sel, long n_sel,
                  float* out, float* box) {
    if (n < 0 || natoms < 0 || n_sel < 0) return -9;
    if (sel == nullptr) n_sel = natoms;
    long nthreads = xtc_nthreads(n);
    if (nthreads == 1)
        return xtc_stage_range_f32(path, offsets, 0, n, natoms, sel,
                                   n_sel, out, box);
    std::vector<std::thread> workers;
    std::vector<int> rcs((size_t)nthreads, 0);
    long per = n / nthreads, extra = n % nthreads, lo = 0;
    for (long t = 0; t < nthreads; t++) {
        long hi = lo + per + (t < extra ? 1 : 0);
        workers.emplace_back([=, &rcs]() {
            rcs[(size_t)t] = xtc_stage_range_f32(
                path, offsets, lo, hi, natoms, sel, n_sel, out, box);
        });
        lo = hi;
    }
    for (auto& w : workers) w.join();
    for (int rc : rcs)
        if (rc != 0) return rc;
    return 0;
}

// Write an XTC file from coords (nframes*natoms*3, nm), box (nframes*9,
// may be null -> zero box), times/steps may be null.
int xtc_write(const char* path, int natoms, long nframes,
              const float* coords, const float* box, const float* times,
              const int* steps, float precision) {
    FILE* f = fopen(path, "wb");
    if (!f) return -1;
    Writer w{f};
    for (long i = 0; i < nframes; i++) {
        w.i32(XTC_MAGIC);
        w.i32(natoms);
        w.i32(steps ? steps[i] : (int)i);
        w.f32(times ? times[i] : (float)i);
        for (int k = 0; k < 9; k++) w.f32(box ? box[i * 9 + k] : 0.0f);
        w.i32(natoms);  // lsize
        int rc = xtc_encode_coords(w, natoms,
                                   coords + (size_t)i * natoms * 3,
                                   precision);
        if (rc != 0) { fclose(f); return rc; }
    }
    fclose(f);
    return 0;
}

// ---------------------------------------------------------------------
// DCD
// ---------------------------------------------------------------------

namespace {
struct DcdInfo {
    int natoms;
    int has_box;
    long first_frame_off;
    long frame_bytes;
};

static uint32_t rd_u32le(FILE* f, bool* ok) {
    unsigned char b[4];
    if (fread(b, 1, 4, f) != 4) { *ok = false; return 0; }
    return (uint32_t)b[0] | ((uint32_t)b[1] << 8) | ((uint32_t)b[2] << 16) |
           ((uint32_t)b[3] << 24);
}

static int dcd_parse_header(FILE* f, DcdInfo& info) {
    bool ok = true;
    uint32_t m1 = rd_u32le(f, &ok);          // record marker (84)
    if (!ok || m1 != 84) return -2;
    char cord[4];
    if (fread(cord, 1, 4, f) != 4 || std::memcmp(cord, "CORD", 4) != 0)
        return -3;
    uint32_t icntrl[20];
    for (int i = 0; i < 20; i++) icntrl[i] = rd_u32le(f, &ok);
    if (!ok) return -4;
    if (rd_u32le(f, &ok) != 84) return -5;   // closing marker
    info.has_box = icntrl[10] != 0;
    // title record
    uint32_t tlen = rd_u32le(f, &ok);
    if (!ok) return -6;
    if (fseek(f, tlen, SEEK_CUR) != 0) return -7;
    if (rd_u32le(f, &ok) != tlen) return -8;
    // natoms record
    if (rd_u32le(f, &ok) != 4) return -9;
    info.natoms = (int)rd_u32le(f, &ok);
    if (rd_u32le(f, &ok) != 4) return -10;
    if (!ok) return -11;
    info.first_frame_off = ftell(f);
    long coord_rec = 4 + (long)info.natoms * 4 + 4;
    info.frame_bytes = 3 * coord_rec + (info.has_box ? (4 + 48 + 4) : 0);
    return 0;
}
}  // namespace

// Scan a DCD: returns n_frames (computed from the file size; DCD frames
// are fixed-size so no per-frame offsets are needed).
long dcd_scan(const char* path, int* natoms_out, int* has_box_out,
              long* first_off_out, long* frame_bytes_out) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    DcdInfo info;
    int rc = dcd_parse_header(f, info);
    if (rc != 0) { fclose(f); return rc; }
    fseek(f, 0, SEEK_END);
    long end = ftell(f);
    fclose(f);
    long n = (end - info.first_frame_off) / info.frame_bytes;
    if (natoms_out) *natoms_out = info.natoms;
    if (has_box_out) *has_box_out = info.has_box;
    if (first_off_out) *first_off_out = info.first_frame_off;
    if (frame_bytes_out) *frame_bytes_out = info.frame_bytes;
    return n;
}

// Read frames [idx[0..n)] into coords (n*natoms*3) and box (n*6 doubles,
// may be null).  Box layout on disk: CHARMM XTLABC (A, gamma, B, beta,
// alpha, C) — angles as stored (deg or cosine; Python side normalizes).
int dcd_read_frames(const char* path, const long* indices, long n,
                    int natoms, int has_box, long first_off,
                    long frame_bytes, float* coords, double* box) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    std::vector<float> tmp((size_t)natoms);
    bool ok = true;
    for (long i = 0; i < n; i++) {
        long off = first_off + indices[i] * frame_bytes;
        if (fseek(f, off, SEEK_SET) != 0) { fclose(f); return -2; }
        if (has_box) {
            uint32_t m = rd_u32le(f, &ok);
            if (!ok || m != 48) { fclose(f); return -3; }
            double cell[6];
            if (fread(cell, 8, 6, f) != 6) { fclose(f); return -4; }
            if (rd_u32le(f, &ok) != 48) { fclose(f); return -5; }
            if (box) std::memcpy(box + i * 6, cell, 48);
        }
        for (int d = 0; d < 3; d++) {
            uint32_t m = rd_u32le(f, &ok);
            if (!ok || m != (uint32_t)natoms * 4) { fclose(f); return -6; }
            if (fread(tmp.data(), 4, natoms, f) != (size_t)natoms) {
                fclose(f);
                return -7;
            }
            if (rd_u32le(f, &ok) != (uint32_t)natoms * 4) {
                fclose(f);
                return -8;
            }
            float* out = coords + (size_t)i * natoms * 3;
            for (int a = 0; a < natoms; a++) out[a * 3 + d] = tmp[a];
        }
    }
    fclose(f);
    return 0;
}

// Write a DCD file.  box: nframes*6 doubles (A,gamma,B,beta,alpha,C) or
// null.  Angles written as given.
int dcd_write(const char* path, int natoms, long nframes,
              const float* coords, const double* box, double dt) {
    FILE* f = fopen(path, "wb");
    if (!f) return -1;
    auto w32 = [&](uint32_t v) {
        unsigned char b[4] = {(unsigned char)v, (unsigned char)(v >> 8),
                              (unsigned char)(v >> 16),
                              (unsigned char)(v >> 24)};
        fwrite(b, 1, 4, f);
    };
    // header record
    w32(84);
    fwrite("CORD", 1, 4, f);
    uint32_t icntrl[20] = {0};
    icntrl[0] = (uint32_t)nframes;
    icntrl[1] = 1;   // istart
    icntrl[2] = 1;   // nsavc
    icntrl[3] = (uint32_t)nframes;
    float dtf = (float)dt;
    std::memcpy(&icntrl[9], &dtf, 4);       // delta
    icntrl[10] = box != nullptr ? 1 : 0;    // unit cell flag
    icntrl[19] = 24;                        // CHARMM version
    for (int i = 0; i < 20; i++) w32(icntrl[i]);
    w32(84);
    // title
    const char title[80] = "Created by mdanalysis_mpi_tpu trajio";
    w32(4 + 80);
    w32(1);
    char buf[80] = {0};
    std::strncpy(buf, title, 79);
    fwrite(buf, 1, 80, f);
    w32(4 + 80);
    // natoms
    w32(4);
    w32((uint32_t)natoms);
    w32(4);
    // frames
    std::vector<float> tmp((size_t)natoms);
    for (long i = 0; i < nframes; i++) {
        if (box) {
            w32(48);
            fwrite(box + i * 6, 8, 6, f);
            w32(48);
        }
        for (int d = 0; d < 3; d++) {
            for (int a = 0; a < natoms; a++)
                tmp[a] = coords[(size_t)i * natoms * 3 + a * 3 + d];
            w32((uint32_t)natoms * 4);
            fwrite(tmp.data(), 4, natoms, f);
            w32((uint32_t)natoms * 4);
        }
    }
    fclose(f);
    return 0;
}

// ---------------------------------------------------------------------------
// Staging kernels: fused selection-gather + int16 quantization of a frame
// block.  The hot host-side path when feeding the accelerator (the staging
// pipeline is the framework's whole performance game on a single staging
// core — SURVEY.md §7 "Host I/O vs TPU throughput").  Semantics match the
// Python reference implementation `quantize_block` in
// parallel/executors.py exactly: one symmetric scale per block,
// scale = 32000 / max|x| (double), q = nearbyint(x * scale) (round half to
// even, like np.round), inv_scale = float(1/scale).
// ---------------------------------------------------------------------------

// src: (n_frames, n_atoms, 3) float32; idx: (n_sel,) int32 selection into
// the atom axis, or nullptr for all atoms; out: (n_frames, n_sel, 3) int16.
// Writes 1/scale to *inv_scale_out.  Two passes over the selected data
// (max, then quantize) — both touch only the selection's bytes.
int stage_gather_quantize_i16(const float* src, long n_frames, long n_atoms,
                              const int32_t* idx, long n_sel,
                              int16_t* out, float* inv_scale_out) {
    if (n_frames < 0 || n_atoms < 0 || n_sel < 0) return -1;
    if (idx == nullptr) n_sel = n_atoms;
    float vmax = 0.0f;
    for (long f = 0; f < n_frames; f++) {
        const float* fr = src + (size_t)f * n_atoms * 3;
        if (idx == nullptr) {
            const size_t n3 = (size_t)n_atoms * 3;
            for (size_t k = 0; k < n3; k++) {
                float a = std::fabs(fr[k]);
                if (a > vmax) vmax = a;
            }
        } else {
            for (long s = 0; s < n_sel; s++) {
                const float* p = fr + (size_t)idx[s] * 3;
                for (int d = 0; d < 3; d++) {
                    float a = std::fabs(p[d]);
                    if (a > vmax) vmax = a;
                }
            }
        }
    }
    const double m = (n_frames * n_sel > 0) ? (double)vmax : 1.0;
    const double scale = 32000.0 / std::max(m, 1e-30);
    // float multiply, not double: NumPy promotes f32-array * python-float
    // to float32 (NEP 50), so matching the reference path bit-for-bit
    // requires the same f32 product before round-half-to-even.
    const float scalef = (float)scale;
    for (long f = 0; f < n_frames; f++) {
        const float* fr = src + (size_t)f * n_atoms * 3;
        int16_t* o = out + (size_t)f * n_sel * 3;
        if (idx == nullptr) {
            const size_t n3 = (size_t)n_atoms * 3;
            for (size_t k = 0; k < n3; k++)
                o[k] = (int16_t)std::nearbyintf(fr[k] * scalef);
        } else {
            for (long s = 0; s < n_sel; s++) {
                const float* p = fr + (size_t)idx[s] * 3;
                for (int d = 0; d < 3; d++)
                    o[s * 3 + d] = (int16_t)std::nearbyintf(p[d] * scalef);
            }
        }
    }
    *inv_scale_out = (float)(1.0 / scale);
    return 0;
}

// One-pass variant: gather + quantize with a CALLER-PROVIDED scale,
// accumulating the true max|x| of the selection while it streams.  This
// halves the memory traffic of the two-pass kernel above (the extra
// max-read pass is what made int16 staging lose to float32 in round 1 —
// VERDICT r1 weak #2).  The caller supplies a scale from a previous
// block (plus a safety margin); if the observed max would overflow the
// int16 range under that scale, the kernel returns 1 WITHOUT writing a
// usable block and the caller re-quantizes with the fresh max — a rare
// second pass instead of an every-block one.  Values are clamped to
// ±32767 so even the reject path never writes out-of-range data.
int stage_gather_quantize_i16_scaled(const float* src, long n_frames,
                                     long n_atoms, const int32_t* idx,
                                     long n_sel, float scale, int16_t* out,
                                     float* max_abs_out) {
    if (n_frames < 0 || n_atoms < 0 || n_sel < 0 || !(scale > 0.0f))
        return -1;
    if (idx == nullptr) n_sel = n_atoms;
    float vmax = 0.0f;
    for (long f = 0; f < n_frames; f++) {
        const float* fr = src + (size_t)f * n_atoms * 3;
        int16_t* o = out + (size_t)f * n_sel * 3;
        if (idx == nullptr) {
            const size_t n3 = (size_t)n_atoms * 3;
            for (size_t k = 0; k < n3; k++) {
                float x = fr[k];
                float a = std::fabs(x);
                if (a > vmax) vmax = a;
                float q = std::nearbyintf(x * scale);
                if (q > 32767.0f) q = 32767.0f;
                if (q < -32767.0f) q = -32767.0f;
                o[k] = (int16_t)q;
            }
        } else {
            for (long s = 0; s < n_sel; s++) {
                const float* p = fr + (size_t)idx[s] * 3;
                for (int d = 0; d < 3; d++) {
                    float x = p[d];
                    float a = std::fabs(x);
                    if (a > vmax) vmax = a;
                    float q = std::nearbyintf(x * scale);
                    if (q > 32767.0f) q = 32767.0f;
                    if (q < -32767.0f) q = -32767.0f;
                    o[s * 3 + d] = (int16_t)q;
                }
            }
        }
    }
    *max_abs_out = vmax;
    // reject when the provided scale would have clipped real data
    return ((double)vmax * (double)scale > 32767.0) ? 1 : 0;
}

// Plain selection gather into float32 (the transfer_dtype="float32"
// staging path): out (n_frames, n_sel, 3) = src[:, idx].
int stage_gather_f32(const float* src, long n_frames, long n_atoms,
                     const int32_t* idx, long n_sel, float* out) {
    if (n_frames < 0 || n_atoms < 0 || n_sel < 0) return -1;
    if (idx == nullptr) {
        std::memcpy(out, src, (size_t)n_frames * n_atoms * 3 * 4);
        return 0;
    }
    for (long f = 0; f < n_frames; f++) {
        const float* fr = src + (size_t)f * n_atoms * 3;
        float* o = out + (size_t)f * n_sel * 3;
        for (long s = 0; s < n_sel; s++) {
            const float* p = fr + (size_t)idx[s] * 3;
            o[s * 3 + 0] = p[0];
            o[s * 3 + 1] = p[1];
            o[s * 3 + 2] = p[2];
        }
    }
    return 0;
}

}  // extern "C"

// ============================================================================
// QCP host kernels (SURVEY.md §2.2: the reference's per-rank hot loop runs
// C qcprot + BLAS; these give the serial/MPI host backend the same native
// weight class).  Rotation convention matches ops/host.py:qcp_rotation —
// the quaternion matrix rq rotates column vectors; callers apply row
// vectors, so aligned = (x - com) · rqᵀ.
// ============================================================================

extern "C" {

// Cyclic Jacobi eigensolver for a symmetric 4x4: a is destroyed, v gets
// the eigenvectors (columns).  ~8 sweeps reach f64 machine precision.
static void jacobi4(double a[4][4], double v[4][4]) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 4; j++) v[i][j] = (i == j) ? 1.0 : 0.0;
    for (int sweep = 0; sweep < 32; sweep++) {
        double off = 0.0;
        for (int p = 0; p < 3; p++)
            for (int q = p + 1; q < 4; q++) off += a[p][q] * a[p][q];
        if (off < 1e-30) break;
        for (int p = 0; p < 3; p++) {
            for (int q = p + 1; q < 4; q++) {
                double apq = a[p][q];
                if (apq == 0.0) continue;
                double theta = (a[q][q] - a[p][p]) / (2.0 * apq);
                double t = (theta >= 0 ? 1.0 : -1.0)
                           / (std::fabs(theta) + std::sqrt(theta * theta + 1.0));
                double c = 1.0 / std::sqrt(t * t + 1.0);
                double s = t * c;
                for (int k = 0; k < 4; k++) {
                    double akp = a[k][p], akq = a[k][q];
                    a[k][p] = c * akp - s * akq;
                    a[k][q] = s * akp + c * akq;
                }
                for (int k = 0; k < 4; k++) {
                    double apk = a[p][k], aqk = a[q][k];
                    a[p][k] = c * apk - s * aqk;
                    a[q][k] = s * apk + c * aqk;
                }
                for (int k = 0; k < 4; k++) {
                    double vkp = v[k][p], vkq = v[k][q];
                    v[k][p] = c * vkp - s * vkq;
                    v[k][q] = s * vkp + c * vkq;
                }
            }
        }
    }
}

// Quaternion key matrix -> rq (3x3) from the correlation matrix m.
static void qcp_rq_from_m(const double m[3][3], double rq[3][3]) {
    double sxx = m[0][0], sxy = m[0][1], sxz = m[0][2];
    double syx = m[1][0], syy = m[1][1], syz = m[1][2];
    double szx = m[2][0], szy = m[2][1], szz = m[2][2];
    double k4[4][4] = {
        {sxx + syy + szz, syz - szy, szx - sxz, sxy - syx},
        {syz - szy, sxx - syy - szz, sxy + syx, szx + sxz},
        {szx - sxz, sxy + syx, -sxx + syy - szz, syz + szy},
        {sxy - syx, szx + sxz, syz + szy, -sxx - syy + szz},
    };
    double vecs[4][4];
    jacobi4(k4, vecs);
    int best = 0;
    for (int i = 1; i < 4; i++) if (k4[i][i] > k4[best][best]) best = i;
    double q0 = vecs[0][best], q1 = vecs[1][best];
    double q2 = vecs[2][best], q3 = vecs[3][best];
    rq[0][0] = q0*q0 + q1*q1 - q2*q2 - q3*q3;
    rq[0][1] = 2.0 * (q1*q2 - q0*q3);
    rq[0][2] = 2.0 * (q1*q3 + q0*q2);
    rq[1][0] = 2.0 * (q1*q2 + q0*q3);
    rq[1][1] = q0*q0 - q1*q1 + q2*q2 - q3*q3;
    rq[1][2] = 2.0 * (q2*q3 - q0*q1);
    rq[2][0] = 2.0 * (q1*q3 - q0*q2);
    rq[2][1] = 2.0 * (q2*q3 + q0*q1);
    rq[2][2] = q0*q0 - q1*q1 - q2*q2 + q3*q3;
}

// Shared setup: weighted selection COM + unweighted correlation vs the
// centered reference (weights=None rotation, the reference's RMSF.py:48
// path).  Returns 0 or a negative error.
static int qcp_setup(const float* coords, long n_atoms,
                     const int64_t* sel, long n_sel, const double* sel_w,
                     const double* ref_c,
                     double com[3], double rq[3][3]) {
    if (n_sel <= 0 || n_atoms <= 0) return -1;
    double wsum = 0.0;
    com[0] = com[1] = com[2] = 0.0;
    for (long s = 0; s < n_sel; s++) {
        int64_t i = sel[s];
        if (i < 0 || i >= n_atoms) return -2;
        double w = sel_w[s];
        wsum += w;
        const float* p = coords + (size_t)i * 3;
        com[0] += w * p[0]; com[1] += w * p[1]; com[2] += w * p[2];
    }
    if (wsum == 0.0) return -3;
    com[0] /= wsum; com[1] /= wsum; com[2] /= wsum;
    double m[3][3] = {{0, 0, 0}, {0, 0, 0}, {0, 0, 0}};
    for (long s = 0; s < n_sel; s++) {
        const float* p = coords + (size_t)sel[s] * 3;
        double d0 = p[0] - com[0], d1 = p[1] - com[1], d2 = p[2] - com[2];
        const double* r = ref_c + (size_t)s * 3;
        m[0][0] += d0 * r[0]; m[0][1] += d0 * r[1]; m[0][2] += d0 * r[2];
        m[1][0] += d1 * r[0]; m[1][1] += d1 * r[1]; m[1][2] += d1 * r[2];
        m[2][0] += d2 * r[0]; m[2][1] += d2 * r[1]; m[2][2] += d2 * r[2];
    }
    qcp_rq_from_m(m, rq);
    return 0;
}

// Superpose one full frame: out (n_atoms,3) f64 = (coords - com)·rqᵀ + ref_com.
// rot_out (9, optional) receives R = rqᵀ (the matrix for row-vector apply).
int qcp_superpose_apply(const float* coords, long n_atoms,
                        const int64_t* sel, long n_sel, const double* sel_w,
                        const double* ref_c, const double* ref_com,
                        double* out, double* rot_out) {
    double com[3], rq[3][3];
    int rc = qcp_setup(coords, n_atoms, sel, n_sel, sel_w, ref_c, com, rq);
    if (rc != 0) return rc;
    for (long i = 0; i < n_atoms; i++) {
        const float* p = coords + (size_t)i * 3;
        double d0 = p[0] - com[0], d1 = p[1] - com[1], d2 = p[2] - com[2];
        double* o = out + (size_t)i * 3;
        o[0] = d0 * rq[0][0] + d1 * rq[0][1] + d2 * rq[0][2] + ref_com[0];
        o[1] = d0 * rq[1][0] + d1 * rq[1][1] + d2 * rq[1][2] + ref_com[1];
        o[2] = d0 * rq[2][0] + d1 * rq[2][1] + d2 * rq[2][2] + ref_com[2];
    }
    if (rot_out != nullptr)
        for (int j = 0; j < 3; j++)
            for (int k = 0; k < 3; k++)
                rot_out[j * 3 + k] = rq[k][j];
    return 0;
}

// Superpose the selection only and fold it into the streaming Welford
// state (the reference's pass-2 body, RMSF.py:124-138, in one native
// pass): m2 += (k/(k+1))·(x−mean)²; mean = (k·mean+x)/(k+1).
int qcp_superpose_moments(const float* coords, long n_atoms,
                          const int64_t* sel, long n_sel, const double* sel_w,
                          const double* ref_c, const double* ref_com,
                          long k, double* mean, double* m2) {
    double com[3], rq[3][3];
    int rc = qcp_setup(coords, n_atoms, sel, n_sel, sel_w, ref_c, com, rq);
    if (rc != 0) return rc;
    double kf = (double)k, kp1 = kf + 1.0, ratio = kf / kp1;
    for (long s = 0; s < n_sel; s++) {
        const float* p = coords + (size_t)sel[s] * 3;
        double d0 = p[0] - com[0], d1 = p[1] - com[1], d2 = p[2] - com[2];
        double x[3];
        x[0] = d0 * rq[0][0] + d1 * rq[0][1] + d2 * rq[0][2] + ref_com[0];
        x[1] = d0 * rq[1][0] + d1 * rq[1][1] + d2 * rq[1][2] + ref_com[1];
        x[2] = d0 * rq[2][0] + d1 * rq[2][1] + d2 * rq[2][2] + ref_com[2];
        double* mu = mean + (size_t)s * 3;
        double* mm = m2 + (size_t)s * 3;
        for (int j = 0; j < 3; j++) {
            double diff = x[j] - mu[j];
            mm[j] += ratio * diff * diff;
            mu[j] = (kf * mu[j] + x[j]) / kp1;
        }
    }
    return 0;
}

}  // extern "C"
