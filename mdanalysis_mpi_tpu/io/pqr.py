"""PQR topology/coordinate parser + writer (upstream ``PQRParser`` /
``PQRWriter``; the APBS/pdb2pqr format).

PQR is "PDB with charge and radius": whitespace-separated ATOM/HETATM
records whose last two fields are the partial charge (e) and atomic
radius (Å).  Both token layouts in the wild are accepted, matching
upstream's flexible parser:

- ``ATOM serial name resName     resSeq x y z charge radius`` (10)
- ``ATOM serial name resName chn resSeq x y z charge radius`` (11,
  pdb2pqr ``--whitespace`` with chain ids; the chain becomes segid)

Insertion codes glued to resSeq (``52A``) are handled the upstream way
(digits prefix → resid).  Parsed charges/radii land on the
:class:`~mdanalysis_mpi_tpu.core.topology.Topology` (``ag.charges``,
``ag.radii``, ``prop charge`` / ``prop radius`` selections);
coordinates form a single-frame in-memory trajectory, like the other
single-structure formats (GRO/PDB path of RMSF.py:56).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files


def _resid(tok: str) -> int:
    """resSeq token, tolerating a glued insertion code ('52A' → 52)."""
    digits = ""
    for c in tok:
        if c.isdigit() or (c == "-" and not digits):
            digits += c
        else:
            break
    if not digits:
        raise ValueError(f"unparseable PQR resSeq field {tok!r}")
    return int(digits)


def parse_pqr(path: str) -> Topology:
    names, resnames, segids, resids = [], [], [], []
    charges, radii, coords = [], [], []
    with open(path) as fh:
        for lineno, ln in enumerate(fh, 1):
            if not ln.startswith(("ATOM", "HETATM")):
                continue
            t = ln.split()
            if len(t) == 10:
                chain = ""
                (_rec, _serial, name, resname, resseq,
                 x, y, z, q, r) = t
            elif len(t) == 11:
                (_rec, _serial, name, resname, chain, resseq,
                 x, y, z, q, r) = t
            else:
                raise ValueError(
                    f"{path}:{lineno}: PQR ATOM record needs 10 or 11 "
                    f"whitespace-separated fields, got {len(t)}")
            names.append(name)
            resnames.append(resname)
            segids.append(chain or "SYSTEM")
            resids.append(_resid(resseq))
            coords.append([float(x), float(y), float(z)])
            charges.append(float(q))
            radii.append(float(r))
    if not names:
        raise ValueError(f"PQR file {path!r} contains no ATOM records")
    top = Topology(
        names=np.array(names), resnames=np.array(resnames),
        resids=np.array(resids), segids=np.array(segids),
        charges=np.array(charges), radii=np.array(radii))
    top._coordinates = np.asarray(coords, np.float32)[None]
    top._dimensions = None
    return top


def write_pqr(path: str, universe_or_group) -> None:
    """Write the current frame as PQR (chain layout iff segids are
    single characters, upstream-style; otherwise the 10-field form)."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    top = ag._universe.topology
    if top.charges is None or top.radii is None:
        raise ValueError(
            "PQR output needs charges AND radii on the topology "
            "(add_TopologyAttr('charges'/'radii'))")
    idx = ag.indices
    pos = ag.positions
    with open(path, "w") as fh:
        fh.write("REMARK   1 Written by mdanalysis_mpi_tpu\n")
        for serial, i in enumerate(idx, 1):
            seg = str(top.segids[i])
            chain = f"{seg} " if len(seg) == 1 else ""
            fh.write(
                f"ATOM {serial:6d} {top.names[i]:<4s} {top.resnames[i]:<4s} "
                f"{chain}{int(top.resids[i]):4d}   "
                f"{pos[serial - 1][0]:8.3f} {pos[serial - 1][1]:8.3f} "
                f"{pos[serial - 1][2]:8.3f} "
                f"{top.charges[i]:7.4f} {top.radii[i]:6.4f}\n")
        fh.write("END\n")


topology_files.register("pqr", parse_pqr)
