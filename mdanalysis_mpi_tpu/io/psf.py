"""PSF (CHARMM/NAMD protein structure file) topology parser + writer.

BASELINE config 1's topology format (ADK PSF/DCD).  Whitespace-delimited
sections introduced by ``<count> !<FLAG>`` headers; the ``!NATOM``
section carries ``id segid resid resname name type charge mass [imove]``
per line; ``!NBOND`` lists flat pairs of 1-based atom ids.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files


def parse_psf(path: str) -> Topology:
    with open(path) as fh:
        lines = fh.read().splitlines()
    if not lines or "PSF" not in lines[0]:
        raise ValueError(f"{path!r} is not a PSF file (missing PSF header)")
    i = 0
    natom = -1
    while i < len(lines):
        ln = lines[i]
        if "!NATOM" in ln:
            natom = int(ln.split("!")[0].strip())
            i += 1
            break
        i += 1
    if natom < 0:
        raise ValueError(f"PSF file {path!r} has no !NATOM section")
    segids = np.empty(natom, dtype="U8")
    resids = np.empty(natom, dtype=np.int64)
    resnames = np.empty(natom, dtype="U8")
    names = np.empty(natom, dtype="U8")
    charges = np.empty(natom, dtype=np.float64)
    masses = np.empty(natom, dtype=np.float64)
    for a in range(natom):
        parts = lines[i + a].split()
        if len(parts) < 8:
            raise ValueError(
                f"PSF file {path!r}: malformed atom line {i + a + 1}")
        segids[a] = parts[1]
        resids[a] = int(parts[2])
        resnames[a] = parts[3]
        names[a] = parts[4]
        charges[a] = float(parts[6])
        masses[a] = float(parts[7])
    i += natom

    def _section(flag: str, width: int, start: int):
        """Scan for ``<count> !FLAG`` from ``start``; return the
        (count, width) 0-based tuple array (or None) and the scan
        position after it."""
        j = start
        while j < len(lines):
            if flag in lines[j]:
                count = int(lines[j].split("!")[0].strip())
                flat: list[int] = []
                j += 1
                while j < len(lines) and len(flat) < width * count:
                    flat.extend(int(x) for x in lines[j].split())
                    j += 1
                arr = (np.asarray(flat[: width * count], np.int64)
                       .reshape(-1, width) - 1)
                return arr, j
            j += 1
        return None, start

    bonds, i = _section("!NBOND", 2, i)
    angles, i = _section("!NTHETA", 3, i)
    dihedrals, i = _section("!NPHI", 4, i)
    impropers, _ = _section("!NIMPHI", 4, i)
    return Topology(names=names, resnames=resnames, resids=resids,
                    segids=segids, charges=charges, masses=masses,
                    bonds=bonds, angles=angles, dihedrals=dihedrals,
                    impropers=impropers)


def write_psf(path: str, topology: Topology) -> None:
    """Minimal PSF writer (fixture generation)."""
    t = topology
    with open(path, "w") as fh:
        fh.write("PSF\n\n")
        fh.write("%8d !NTITLE\n" % 1)
        fh.write(" REMARKS written by mdanalysis_mpi_tpu\n\n")
        fh.write("%8d !NATOM\n" % t.n_atoms)
        charges = (t.charges if t.charges is not None
                   else np.zeros(t.n_atoms))
        for i in range(t.n_atoms):
            fh.write("%8d %-4s %-4d %-4s %-4s %-4s %10.6f %13.4f %11d\n" % (
                i + 1, t.segids[i][:4], t.resids[i], t.resnames[i][:4],
                t.names[i][:4], (t.elements[i] or "X")[:4],
                charges[i], t.masses[i], 0))
        def _section(flag: str, tuples, width: int):
            arr = (tuples if tuples is not None
                   else np.empty((0, width), np.int64))
            fh.write("\n%8d !%s\n" % (len(arr), flag))
            flat = (np.asarray(arr, np.int64) + 1).ravel()
            per_line = 8 if width != 3 else 9     # whole tuples per line
            for j in range(0, len(flat), per_line):
                fh.write("".join("%8d" % x for x in flat[j:j + per_line])
                         + "\n")

        _section("NBOND: bonds", t.bonds, 2)
        _section("NTHETA: angles", t.angles, 3)
        _section("NPHI: dihedrals", t.dihedrals, 4)
        _section("NIMPHI: impropers", t.impropers, 4)


topology_files.register("psf", parse_psf)
