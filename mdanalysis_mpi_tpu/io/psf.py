"""PSF (CHARMM/NAMD protein structure file) topology parser + writer.

BASELINE config 1's topology format (ADK PSF/DCD).  Whitespace-delimited
sections introduced by ``<count> !<FLAG>`` headers; the ``!NATOM``
section carries ``id segid resid resname name type charge mass [imove]``
per line; ``!NBOND`` lists flat pairs of 1-based atom ids.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files


def parse_psf(path: str) -> Topology:
    with open(path) as fh:
        lines = fh.read().splitlines()
    if not lines or "PSF" not in lines[0]:
        raise ValueError(f"{path!r} is not a PSF file (missing PSF header)")
    i = 0
    natom = -1
    while i < len(lines):
        ln = lines[i]
        if "!NATOM" in ln:
            natom = int(ln.split("!")[0].strip())
            i += 1
            break
        i += 1
    if natom < 0:
        raise ValueError(f"PSF file {path!r} has no !NATOM section")
    segids = np.empty(natom, dtype="U8")
    resids = np.empty(natom, dtype=np.int64)
    resnames = np.empty(natom, dtype="U8")
    names = np.empty(natom, dtype="U8")
    charges = np.empty(natom, dtype=np.float64)
    masses = np.empty(natom, dtype=np.float64)
    for a in range(natom):
        parts = lines[i + a].split()
        if len(parts) < 8:
            raise ValueError(
                f"PSF file {path!r}: malformed atom line {i + a + 1}")
        segids[a] = parts[1]
        resids[a] = int(parts[2])
        resnames[a] = parts[3]
        names[a] = parts[4]
        charges[a] = float(parts[6])
        masses[a] = float(parts[7])
    i += natom
    bonds = None
    while i < len(lines):
        ln = lines[i]
        if "!NBOND" in ln:
            nbond = int(ln.split("!")[0].strip())
            flat: list[int] = []
            i += 1
            while i < len(lines) and len(flat) < 2 * nbond:
                flat.extend(int(x) for x in lines[i].split())
                i += 1
            bonds = np.asarray(flat[: 2 * nbond], dtype=np.int64).reshape(-1, 2) - 1
            break
        i += 1
    return Topology(names=names, resnames=resnames, resids=resids,
                    segids=segids, charges=charges, masses=masses,
                    bonds=bonds)


def write_psf(path: str, topology: Topology) -> None:
    """Minimal PSF writer (fixture generation)."""
    t = topology
    with open(path, "w") as fh:
        fh.write("PSF\n\n")
        fh.write("%8d !NTITLE\n" % 1)
        fh.write(" REMARKS written by mdanalysis_mpi_tpu\n\n")
        fh.write("%8d !NATOM\n" % t.n_atoms)
        charges = (t.charges if t.charges is not None
                   else np.zeros(t.n_atoms))
        for i in range(t.n_atoms):
            fh.write("%8d %-4s %-4d %-4s %-4s %-4s %10.6f %13.4f %11d\n" % (
                i + 1, t.segids[i][:4], t.resids[i], t.resnames[i][:4],
                t.names[i][:4], (t.elements[i] or "X")[:4],
                charges[i], t.masses[i], 0))
        fh.write("\n")
        bonds = t.bonds if t.bonds is not None else np.empty((0, 2), np.int64)
        fh.write("%8d !NBOND: bonds\n" % len(bonds))
        flat = (bonds + 1).ravel()
        for j in range(0, len(flat), 8):
            fh.write("".join("%8d" % x for x in flat[j:j + 8]) + "\n")


topology_files.register("psf", parse_psf)
