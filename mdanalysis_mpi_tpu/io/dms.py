"""Desmond DMS files (upstream ``DMSParser``) — a single-frame
topology+coordinates container stored as an SQLite database (stdlib
``sqlite3``; no external dependency).

Tables consumed: ``particle`` (id, name, resname, resid, chain/segid,
mass, charge, anum, x, y, z — ordered by id), ``bond`` (p0, p1), and
``global_cell`` (three rows spanning the cell matrix; an orthorhombic
diagonal cell maps to box lengths + 90° angles, anything else is
converted through the shared box math).  Elements derive from the
atomic number column when present.
"""

from __future__ import annotations

import os
import sqlite3

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files
from mdanalysis_mpi_tpu.io.prmtop import _Z_TO_ELEMENT


def _columns(cur, table: str) -> set:
    return {r[1] for r in cur.execute(f"PRAGMA table_info({table})")}


def parse_dms(path: str) -> Topology:
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "rb") as fh:
        if fh.read(15) != b"SQLite format 3":
            raise ValueError(
                f"{path!r} is not an SQLite database (not a DMS file)")
    con = sqlite3.connect(f"file:{path}?mode=ro", uri=True)
    try:
        cur = con.cursor()
        tables = {r[0] for r in cur.execute(
            "SELECT name FROM sqlite_master WHERE type='table'")}
        if "particle" not in tables:
            raise ValueError(
                f"{path!r} has no 'particle' table — not a Desmond DMS")
        cols = _columns(cur, "particle")
        seg_col = ("segid" if "segid" in cols
                   else "chain" if "chain" in cols else None)
        want = ["id", "name", "resname", "resid", "mass", "charge",
                "x", "y", "z"]
        missing = [c for c in want if c not in cols]
        if missing:
            raise ValueError(
                f"{path!r}: particle table lacks columns {missing}")
        opt = ([c] if (c := seg_col) else [])
        has_anum = "anum" in cols            # optional (docstring)
        has_vel = {"vx", "vy", "vz"} <= cols
        sel = ", ".join(want[1:] + opt
                        + (["anum"] if has_anum else [])
                        + (["vx", "vy", "vz"] if has_vel else []))
        rows = cur.execute(
            f"SELECT {sel} FROM particle ORDER BY id").fetchall()
        if not rows:
            raise ValueError(f"{path!r}: particle table is empty")
        arr = list(zip(*rows))
        names = np.array([str(v) for v in arr[0]])
        resnames = np.array([str(v) for v in arr[1]])
        resids = np.array(arr[2], np.int64)
        masses = np.array(arr[3], np.float64)
        charges = np.array(arr[4], np.float64)
        coords = np.stack([np.array(arr[5], np.float32),
                           np.array(arr[6], np.float32),
                           np.array(arr[7], np.float32)], axis=1)
        k = 8
        segids = None
        if seg_col:
            segids = np.array([str(v) if v else "SYSTEM"
                               for v in arr[k]])
            k += 1
        elements = None
        if has_anum:
            anum = np.array(arr[k], np.int64)
            k += 1
            if (anum > 0).any():
                elements = np.array(
                    [_Z_TO_ELEMENT.get(int(z), "X") for z in anum])
        vels = None
        if has_vel:
            vels = np.stack([np.array(arr[k], np.float32),
                             np.array(arr[k + 1], np.float32),
                             np.array(arr[k + 2], np.float32)],
                            axis=1)
        bonds = None
        if "bond" in tables:
            b = cur.execute("SELECT p0, p1 FROM bond").fetchall()
            if b:
                bonds = np.asarray(b, np.int64)
        dims = None
        if "global_cell" in tables:
            cell = np.asarray(
                cur.execute(
                    "SELECT x, y, z FROM global_cell ORDER BY id"
                ).fetchall(), np.float64)
            if cell.shape == (3, 3) and np.abs(cell).sum() > 0:
                from mdanalysis_mpi_tpu.lib.mdamath import triclinic_box

                dims = np.asarray(
                    triclinic_box(cell[0], cell[1], cell[2]),
                    np.float32)
    finally:
        con.close()
    top = Topology(
        names=names, resnames=resnames, resids=resids, segids=segids,
        masses=masses, charges=charges, elements=elements, bonds=bonds)
    top._coordinates = coords[None]
    top._dimensions = dims
    if vels is not None:
        top._velocities = vels[None]
    return top


topology_files.register("dms", parse_dms)
