"""CHARMM CRD coordinate-card parser + writer (upstream ``CRDParser``
/ ``CRDWriter``) — the standard partner of the PSF topology
(``Universe("sys.psf", "sys.crd")`` style workflows; here a CRD also
stands alone as a single-frame topology+coordinates file like GRO).

Both card layouts are handled, keyed the upstream way on the header's
``EXT`` keyword (and on the >99999-atom rule CHARMM itself uses):

- standard: ``atomno resno resname name x y z segid resid weight``
  fixed columns (I5,I5,1X,A4,1X,A4,3F10.5,1X,A4,1X,A4,F10.5)
- extended (``EXT``): i10/a8 fields with f20.10 coordinates

Title lines start with ``*``; the atom-count line ends the header.
Parsing is FIXED-COLUMN first (the layouts above — CHARMM-written
files with touching fields parse exactly), with a whitespace-token
fallback for the liberal variants other tools emit.  The per-atom
``weight`` column is NOT preserved: there is no topology field for it;
``write_crd`` emits 0.0 (or an explicit ``weights`` array).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files


# fixed-column slices (resname, name, x, y, z, segid, resid) for the
# two card layouts: standard I5,I5,1X,A4,1X,A4,3F10.5,1X,A4,1X,A4 and
# extended I10,I10,2X,A8,2X,A8,3F20.10,2X,A8,2X,A8
_STD_COLS = ((11, 15), (16, 20), (20, 30), (30, 40), (40, 50),
             (51, 55), (56, 60))
_EXT_COLS = ((22, 30), (32, 40), (40, 60), (60, 80), (80, 100),
             (102, 110), (112, 120))


def _fields(ln: str, ext: bool):
    """One atom line → (resname, name, x, y, z, segid, resid) strings.
    Fixed columns first (CHARMM's own output parses exactly even when
    f10.5 fields touch); token split as the liberal fallback."""
    cols = _EXT_COLS if ext else _STD_COLS
    try:
        out = [ln[a:b].strip() for a, b in cols]
        float(out[2]); float(out[3]); float(out[4]); int(out[6])
        if not (out[0] and out[1]):
            raise ValueError
        return out
    except (ValueError, IndexError):
        t = ln.split()
        if len(t) < 9:
            raise ValueError(
                f"CRD atom line needs >= 9 fields (atomno resno "
                f"resname name x y z segid resid[ weight]), got "
                f"{len(t)}: {ln!r}") from None
        return [t[2], t[3], t[4], t[5], t[6], t[7], t[8]]


def parse_crd(path: str) -> Topology:
    names, resnames, segids, resids = [], [], [], []
    coords = []
    n_atoms = None
    ext = False
    with open(path) as fh:
        for lineno, raw in enumerate(fh, 1):
            ln = raw.rstrip("\n")
            if not ln.strip() or ln.lstrip().startswith("*"):
                continue
            if n_atoms is None:
                t = ln.split()
                try:
                    n_atoms = int(t[0])
                except ValueError as e:
                    raise ValueError(
                        f"{path}:{lineno}: expected the CRD atom-count "
                        f"line, got {ln!r}") from e
                ext = any(x.upper() == "EXT" for x in t[1:])
                continue
            try:
                rn, nm, x, y, z, seg, rid = _fields(ln, ext)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}") from None
            resnames.append(rn)
            names.append(nm)
            coords.append([float(x), float(y), float(z)])
            segids.append(seg)
            resids.append(int(rid))
    if n_atoms is None:
        raise ValueError(f"CRD file {path!r} has no atom-count line")
    if len(names) != n_atoms:
        raise ValueError(
            f"CRD file {path!r} declares {n_atoms} atoms but carries "
            f"{len(names)}")
    top = Topology(
        names=np.array(names), resnames=np.array(resnames),
        resids=np.array(resids), segids=np.array(segids))
    top._coordinates = np.asarray(coords, np.float32)[None]
    top._dimensions = None
    return top


def write_crd(path: str, universe_or_group, extended: bool | None = None,
              weights=None) -> None:
    """Write the current frame as a CRD card.

    ``extended`` defaults to the CHARMM rule: automatic EXT when the
    system exceeds 99,999 atoms (the standard i5 field would
    overflow).  ``weights``: optional per-atom values for the weight
    column (default 0.0 — the Topology has no field for it)."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    top = ag._universe.topology
    idx = np.asarray(ag.indices)
    pos = ag.positions
    w = (np.zeros(len(idx)) if weights is None
         else np.asarray(weights, np.float64))
    if w.shape != (len(idx),):
        raise ValueError(
            f"weights needs {len(idx)} values, got shape {w.shape}")
    if extended is None:
        extended = len(idx) > 99999
    # cumulative residue numbering across the written selection
    ri = top.resindices[idx]
    _, resno = np.unique(ri, return_inverse=True)
    with open(path, "w") as fh:
        fh.write("* Written by mdanalysis_mpi_tpu\n*\n")
        if extended:
            fh.write(f"{len(idx):10d}  EXT\n")
            for j, i in enumerate(idx):
                fh.write(
                    f"{j + 1:10d}{resno[j] + 1:10d}  "
                    f"{top.resnames[i]:<8s}  {top.names[i]:<8s}"
                    f"{pos[j][0]:20.10f}{pos[j][1]:20.10f}"
                    f"{pos[j][2]:20.10f}  {top.segids[i]:<8s}  "
                    f"{int(top.resids[i]):<8d}{w[j]:20.10f}\n")
        else:
            fh.write(f"{len(idx):5d}\n")
            for j, i in enumerate(idx):
                fh.write(
                    f"{j + 1:5d}{resno[j] + 1:5d} "
                    f"{top.resnames[i]:<4s} {top.names[i]:<4s}"
                    f"{pos[j][0]:10.5f}{pos[j][1]:10.5f}"
                    f"{pos[j][2]:10.5f} {top.segids[i]:<4s} "
                    f"{int(top.resids[i]):<4d}{w[j]:10.5f}\n")


topology_files.register("crd", parse_crd)
