"""Shared frame-offset index cache (XTC + TRR readers).

Upstream builds a frame-offset index on first open and caches it beside
the trajectory (SURVEY.md §2.2 random-access requirement); both XDR
readers here use this one mtime-validated npz scheme.
"""

from __future__ import annotations

import os

import numpy as np


def cache_path(path: str) -> str:
    return path + ".mdtpu_offsets.npz"


def load(path: str):
    """Cached (offsets, natoms) for ``path``, or None if absent/stale."""
    cache = cache_path(path)
    if not os.path.exists(cache):
        return None
    try:
        z = np.load(cache)
        if float(z["mtime"]) == os.path.getmtime(path):
            return z["offsets"].astype(np.int64), int(z["natoms"])
    except Exception:
        pass
    return None


def save(path: str, offsets: np.ndarray, natoms: int,
         mtime: float) -> None:
    """``mtime`` must be captured BEFORE the scan: a trajectory appended
    to mid-scan then fails validation next open (rescan) instead of
    serving a stale index forever."""
    try:
        np.savez(cache_path(path), offsets=offsets, natoms=natoms,
                 mtime=mtime)
    except OSError:
        pass  # read-only directory: index just isn't cached
