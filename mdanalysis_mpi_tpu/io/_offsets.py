"""Shared frame-offset index cache (XTC + TRR readers).

Upstream builds a frame-offset index on first open and caches it beside
the trajectory (SURVEY.md §2.2 random-access requirement); both XDR
readers here use this one mtime-validated npz scheme.
"""

from __future__ import annotations

import os

import numpy as np


def cache_path(path: str) -> str:
    return path + ".mdtpu_offsets.npz"


def load(path: str):
    """Cached (offsets, natoms) for ``path``, or None if absent/stale."""
    cache = cache_path(path)
    if not os.path.exists(cache):
        return None
    try:
        z = np.load(cache)
        if float(z["mtime"]) == os.path.getmtime(path):
            return z["offsets"].astype(np.int64), int(z["natoms"])
    except Exception:
        pass
    return None


def save(path: str, offsets: np.ndarray, natoms: int,
         mtime: float) -> None:
    """``mtime`` must be captured BEFORE the scan: a trajectory appended
    to mid-scan then fails validation next open (rescan) instead of
    serving a stale index forever.

    Atomic (tmp + rename): N processes opening the same trajectory at
    once — the reference's N-independent-readers pattern (RMSF.py:56)
    and every multi-controller run here — may all scan and save; a
    concurrent reader must only ever see a complete index.  (A torn
    read would merely trigger a rescan via ``load``'s guard, but
    never-torn is cheaper than sometimes-rescan.)"""
    cache = cache_path(path)
    tmp = f"{cache}.tmp.{os.getpid()}"
    try:
        np.savez(tmp, offsets=offsets, natoms=natoms, mtime=mtime)
        # np.savez appends .npz when the name lacks it
        os.replace(tmp if tmp.endswith(".npz") else tmp + ".npz", cache)
    except OSError:
        # read-only directory: index just isn't cached
        for cand in (tmp, tmp + ".npz"):
            if os.path.exists(cand):
                try:
                    os.remove(cand)
                except OSError:
                    pass
