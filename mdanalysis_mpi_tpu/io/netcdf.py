"""Amber NetCDF trajectory format (upstream ``coordinates.TRJ`` /
``NCDFReader``; the AMBER ``.nc``/``.ncdf`` convention).

A from-scratch NetCDF-3 implementation — no netCDF4/scipy dependency:
the classic (magic ``CDF\\x01``) and 64-bit-offset (``CDF\\x02``)
container is a small, fully specified binary header (dimension list,
global attributes, variable list with per-variable type/shape/offset)
followed by fixed-size data; record variables interleave per record
(frame) with a constant record stride.  The parser below reads ANY
conforming NetCDF-3 header; the reader then applies the AMBER
convention on top (dimensions ``frame``/``atom``/``spatial``;
variables ``coordinates`` (frame, atom, spatial) float32 Å, ``time``
(frame) float32 ps, optional ``cell_lengths``/``cell_angles`` (frame,
cell_spatial) double).

Writer: :func:`write_ncdf` emits the same convention (classic format,
``frame`` unlimited), so round-trips are exact at float32; the header
layout is additionally pinned against the NetCDF spec byte-for-byte in
``tests/test_netcdf.py`` (golden-offset checks), so reader and writer
cannot drift into a private dialect.

Random access: frame i's coordinates live at
``begin + i · recsize`` — O(1) seeks, and ``read_block`` slices whole
frame runs with one contiguous read per frame (the staging-primitive
contract, SURVEY.md §7 layer 2).
"""

from __future__ import annotations

import struct

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase

_NC_DIMENSION = 0x0A
_NC_VARIABLE = 0x0B
_NC_ATTRIBUTE = 0x0C

#: NetCDF external types → (numpy dtype, size)
_NC_TYPES = {
    1: (np.dtype(">i1"), 1),   # byte
    2: (np.dtype("S1"), 1),    # char
    3: (np.dtype(">i2"), 2),   # short
    4: (np.dtype(">i4"), 4),   # int
    5: (np.dtype(">f4"), 4),   # float
    6: (np.dtype(">f8"), 8),   # double
}


def _pad4(n: int) -> int:
    return (n + 3) & ~3


class _NC3Header:
    """Parsed NetCDF-3 header: ``dims`` [(name, length)], ``gatts``
    {name: value}, ``vars`` {name: dict(dims, dtype, vsize, begin,
    record)}, plus ``numrecs`` and ``recsize``."""

    def __init__(self, raw: bytes, path: str):
        self._b = raw
        self._o = 0
        self._path = path
        magic = self._take(3)
        if magic != b"CDF":
            raise ValueError(f"{path!r} is not a NetCDF file "
                             f"(magic {magic!r})")
        version = self._take(1)[0]
        if version not in (1, 2):
            raise ValueError(
                f"{path!r}: unsupported NetCDF version byte {version} "
                "(classic and 64-bit-offset only; NetCDF-4/HDF5 files "
                "need conversion, e.g. `nccopy -k classic`)")
        self._offsets64 = version == 2
        self.numrecs = self._int()           # 0xFFFFFFFF = streaming
        self.dims = self._dim_list()
        self.gatts = self._att_list()
        self.vars = self._var_list()
        rec_vars = [v for v in self.vars.values() if v["record"]]
        self.recsize = sum(_pad4(v["vsize"]) if len(rec_vars) > 1
                           else v["vsize"] for v in rec_vars)

    # -- primitive readers --
    def _take(self, n: int) -> bytes:
        b = self._b[self._o:self._o + n]
        if len(b) != n:
            raise ValueError(f"{self._path!r}: truncated NetCDF header")
        self._o += n
        return b

    def _int(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def _uint(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def _name(self) -> str:
        n = self._int()
        s = self._take(n).decode("ascii")
        self._take(_pad4(n) - n)
        return s

    def _dim_list(self):
        tag, n = self._int(), self._int()
        if tag not in (0, _NC_DIMENSION):
            raise ValueError(f"{self._path!r}: bad dim_list tag {tag}")
        return [(self._name(), self._int()) for _ in range(n)]

    def _att_list(self):
        tag, n = self._int(), self._int()
        if tag not in (0, _NC_ATTRIBUTE):
            raise ValueError(f"{self._path!r}: bad att_list tag {tag}")
        out = {}
        for _ in range(n):
            name = self._name()
            xtype = self._int()
            count = self._int()
            dt, size = _NC_TYPES[xtype]
            raw = self._take(count * size)
            self._take(_pad4(count * size) - count * size)
            if xtype == 2:
                out[name] = raw.decode("ascii", errors="replace")
            else:
                out[name] = np.frombuffer(raw, dt)
        return out

    def _var_list(self):
        tag, n = self._int(), self._int()
        if tag not in (0, _NC_VARIABLE):
            raise ValueError(f"{self._path!r}: bad var_list tag {tag}")
        out = {}
        for _ in range(n):
            name = self._name()
            ndims = self._int()
            dimids = [self._int() for _ in range(ndims)]
            atts = self._att_list()
            xtype = self._int()
            vsize = self._uint()
            begin = (struct.unpack(">Q", self._take(8))[0]
                     if self._offsets64 else self._uint())
            record = bool(dimids) and self.dims[dimids[0]][1] == 0
            shape = tuple(self.dims[d][1] for d in dimids)
            out[name] = {"dims": [self.dims[d][0] for d in dimids],
                         "shape": shape, "dtype": _NC_TYPES[xtype][0],
                         "vsize": vsize, "begin": begin,
                         "record": record, "atts": atts}
        return out


class NCDFReader(ReaderBase):
    """Random-access AMBER NetCDF trajectory reader (Å, ps)."""

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        with open(path, "rb") as f:
            head = f.read(65536)
            hdr = _NC3Header(head, path)
            coords = hdr.vars.get("coordinates")
            if coords is None or not coords["record"] \
                    or coords["dims"][1:] != ["atom", "spatial"]:
                raise ValueError(
                    f"{path!r}: no (frame, atom, spatial) coordinates "
                    "variable — not an AMBER trajectory NetCDF")
            self._hdr = hdr
            self._natoms = coords["shape"][1]
            nrec = hdr.numrecs
            if nrec < 0:                     # streaming count: derive
                # the record region starts at the FIRST record
                # variable's begin (coordinates sit after time within
                # each record)
                rec_start = min(v["begin"] for v in hdr.vars.values()
                                if v["record"])
                f.seek(0, 2)
                data = f.tell() - rec_start
                nrec = data // hdr.recsize if hdr.recsize else 0
            self._nframes = int(nrec)
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"NetCDF {path!r} has {self._natoms} atoms, expected "
                f"{n_atoms}")
        self._file = open(path, "rb")

    @property
    def n_frames(self) -> int:
        return self._nframes

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "NCDFReader":
        return NCDFReader(self._path)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _rec_field(self, var: str, i: int):
        v = self._hdr.vars[var]
        self._file.seek(v["begin"] + i * self._hdr.recsize)
        raw = self._file.read(v["vsize"])
        arr = np.frombuffer(raw, v["dtype"]).reshape(v["shape"][1:])
        # the AMBER convention allows a scale_factor attribute on ANY
        # variable (coordinates/velocities/cells/time) — apply it
        # uniformly, as upstream does
        sf = v["atts"].get("scale_factor")
        if sf is not None:
            arr = arr * np.asarray(sf).reshape(())[()]
        return arr

    def _read_frame(self, i: int) -> Timestep:
        if not 0 <= i < self._nframes:
            raise IndexError(
                f"frame {i} out of range [0, {self._nframes})")
        pos = self._rec_field("coordinates", i).astype(np.float32)
        time = 0.0
        if "time" in self._hdr.vars and self._hdr.vars["time"]["record"]:
            time = float(self._rec_field("time", i).reshape(()))
        dims = None
        if "cell_lengths" in self._hdr.vars \
                and "cell_angles" in self._hdr.vars:
            lengths = self._rec_field("cell_lengths", i).astype(
                np.float64)
            angles = self._rec_field("cell_angles", i).astype(np.float64)
            if np.all(lengths > 0):
                dims = np.concatenate([lengths, angles]).astype(
                    np.float32)
        vels = None
        vvar = self._hdr.vars.get("velocities")
        if vvar is not None and vvar["record"]:
            # scale_factor (AMBER 20.455 Å/ps convention) applied in
            # _rec_field like every variable's
            vels = self._rec_field("velocities", i).astype(np.float32)
        return Timestep(pos, frame=i, time=time, dimensions=dims,
                        velocities=vels)

    def frame_times(self, frames) -> np.ndarray | None:
        if "time" not in self._hdr.vars:
            return None
        return np.asarray([float(self._rec_field("time", int(i))
                                 .reshape(())) for i in frames])


def write_ncdf(path: str, frames: np.ndarray, dimensions=None,
               times=None, velocities=None,
               vel_scale_factor: float | None = None,
               title: str = "mdanalysis_mpi_tpu") -> None:
    """Write (F, N, 3) Å coordinates as an AMBER-convention NetCDF-3
    classic file (``frame`` unlimited; ``time`` ps; optional box as
    ``cell_lengths``/``cell_angles`` — one (6,) vector or per-frame
    (F, 6); optional ``velocities`` (F, N, 3) in Å/ps, stored divided
    by ``vel_scale_factor`` with the matching ``scale_factor``
    attribute when given — the AMBER 20.455 convention)."""
    frames = np.asarray(frames, np.float32)
    if frames.ndim != 3 or frames.shape[2] != 3:
        raise ValueError(f"frames must be (F, N, 3), got {frames.shape}")
    f_count, n_atoms, _ = frames.shape
    if times is None:
        times = np.arange(f_count, dtype=np.float32)
    times = np.asarray(times, np.float32)
    if len(times) != f_count:
        raise ValueError(
            f"{len(times)} times for {f_count} frames")
    has_box = dimensions is not None
    if has_box:
        dimensions = np.asarray(dimensions, np.float64)
        if dimensions.size == 6:
            dimensions = np.broadcast_to(dimensions.reshape(6),
                                         (f_count, 6))
        elif dimensions.shape != (f_count, 6):
            raise ValueError(
                f"dimensions must be (6,) or (F, 6), got "
                f"{dimensions.shape}")
    has_vel = velocities is not None
    if has_vel:
        velocities = np.asarray(velocities, np.float32)
        if velocities.shape != frames.shape:
            raise ValueError(
                f"velocities must match frames shape {frames.shape}, "
                f"got {velocities.shape}")
        if vel_scale_factor is not None:
            velocities = velocities / np.float32(vel_scale_factor)

    def name(s: str) -> bytes:
        b = s.encode("ascii")
        return struct.pack(">i", len(b)) + b + b"\0" * (_pad4(len(b))
                                                        - len(b))

    def att(k: str, v) -> bytes:
        if isinstance(v, str):
            b = v.encode("ascii")
            return (name(k) + struct.pack(">ii", 2, len(b)) + b
                    + b"\0" * (_pad4(len(b)) - len(b)))
        # numeric attributes are NC_FLOAT: the AMBER convention
        # specifies scale_factor as type float
        return (name(k) + struct.pack(">ii", 5, 1)
                + struct.pack(">f", float(v)))

    def att_list(pairs) -> bytes:
        if not pairs:
            return struct.pack(">ii", 0, 0)
        return (struct.pack(">ii", _NC_ATTRIBUTE, len(pairs))
                + b"".join(att(k, v) for k, v in pairs))

    dims = [("frame", 0), ("spatial", 3), ("atom", n_atoms)]
    if has_box:
        dims += [("cell_spatial", 3), ("cell_angular", 3)]
    dim_block = (struct.pack(">ii", _NC_DIMENSION, len(dims))
                 + b"".join(name(n) + struct.pack(">i", ln)
                            for n, ln in dims))
    gatts = att_list([("Conventions", "AMBER"),
                      ("ConventionVersion", "1.0"),
                      ("program", "mdanalysis_mpi_tpu"),
                      ("programVersion", "1.0"),
                      ("title", title)])

    # record variables, in record order
    specs = [("time", [0], 5, 4, [("units", "picosecond")])]
    specs.append(("coordinates", [0, 2, 1], 5, n_atoms * 12,
                  [("units", "angstrom")]))
    if has_vel:
        vel_atts = [("units", "angstrom/picosecond")]
        if vel_scale_factor is not None:
            vel_atts.append(("scale_factor", float(vel_scale_factor)))
        specs.append(("velocities", [0, 2, 1], 5, n_atoms * 12,
                      vel_atts))
    if has_box:
        specs.append(("cell_lengths", [0, 3], 6, 24,
                      [("units", "angstrom")]))
        specs.append(("cell_angles", [0, 4], 6, 24,
                      [("units", "degree")]))
    n_rec_vars = len(specs)

    def var_header(nm, dimids, xtype, vsize, atts, begin):
        return (name(nm) + struct.pack(">i", len(dimids))
                + b"".join(struct.pack(">i", d) for d in dimids)
                + att_list(atts)
                + struct.pack(">iiI", xtype, vsize, begin))

    def build(begins):
        var_block = (struct.pack(">ii", _NC_VARIABLE, len(specs))
                     + b"".join(var_header(nm, dimids, xt, vs, atts,
                                           begins[j])
                                for j, (nm, dimids, xt, vs, atts)
                                in enumerate(specs)))
        return (b"CDF\x01" + struct.pack(">i", f_count)
                + dim_block + gatts + var_block)

    header_len = len(build([0] * n_rec_vars))
    aligned = [_pad4(vs) if n_rec_vars > 1 else vs
               for (_, _, _, vs, _) in specs]
    begins, off = [], header_len
    for a in aligned:
        begins.append(off)
        off += a
    header = build(begins)
    assert len(header) == header_len

    with open(path, "wb") as out:
        out.write(header)
        for i in range(f_count):
            rec = bytearray()
            rec += struct.pack(">f", float(times[i]))
            rec += b"\0" * (aligned[0] - 4)
            coord = frames[i].astype(">f4").tobytes()
            rec += coord + b"\0" * (aligned[1] - len(coord))
            if has_vel:
                rec += velocities[i].astype(">f4").tobytes()
            if has_box:
                rec += np.asarray(dimensions[i, :3], ">f8").tobytes()
                rec += np.asarray(dimensions[i, 3:], ">f8").tobytes()
            out.write(bytes(rec))


trajectory_files.register("nc", NCDFReader)
trajectory_files.register("ncdf", NCDFReader)
