"""AMBER ASCII trajectory (``mdcrd`` / ``.crdbox``; upstream
``TRJReader``).

Layout: one title line, then every frame's 3·natom coordinates
flattened in 10F8.3 fields (10 per line), optionally followed by one
3F8.3 box-lengths line per frame (``.crdbox``, or mdcrd written with
periodic boxes).  The format is self-delimiting only GIVEN natom — the
reader takes it from the Universe's topology (the registry passes it)
— and the box-per-frame question is answered by exact arithmetic:
the LINE structure must replay exactly as AMBER writes it — each
frame is ceil(3n/10) coordinate lines (all full 10-value lines, the
last carrying the remainder) optionally followed by one 3-value box
line.  Both candidate layouts are replayed against the actual lines;
the one that consumes the file exactly wins, a file fitting neither
refuses loudly, and the one truly ambiguous shape (n = 1: every line
carries 3 values whether coordinates or box) refuses with the remedy
rather than guessing.

Whole-file parse into a
:class:`~mdanalysis_mpi_tpu.io.memory.MemoryReader` — the cost of an
ASCII trajectory format; convert to NetCDF for scale.  Box angles are
orthorhombic 90° (AMBER's mdcrd convention; truncated-octahedron
files carry angles nowhere and are not guessed).  A writer covers
fixtures and round trips.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.io import trajectory_files
from mdanalysis_mpi_tpu.io.memory import MemoryReader

_W = 8      # F8.3 field width


def _line_counts(path: str):
    """Per-line value counts + flat values (line structure is the
    disambiguator)."""
    counts: list[int] = []
    vals: list[float] = []
    with open(path) as fh:
        fh.readline()                     # title
        for ln in fh:
            ln = ln.rstrip("\n")
            c = 0
            for k in range(0, len(ln), _W):
                f = ln[k:k + _W]
                if f.strip():
                    vals.append(float(f))
                    c += 1
            if c:
                counts.append(c)
    return counts, vals


def _replays(counts, n_atoms, boxed) -> bool:
    """True iff the line-count sequence is exactly N repetitions of
    one frame's shape: full 10-value lines, a remainder line, and
    (boxed) a 3-value box line."""
    per = 3 * n_atoms
    full, rem = divmod(per, 10)
    frame = [10] * full + ([rem] if rem else [])
    if boxed:
        frame = frame + [3]
    if not frame or len(counts) % len(frame):
        return False
    k = len(frame)
    return all(counts[i] == frame[i % k] for i in range(len(counts)))


def read_mdcrd(path: str, n_atoms: int):
    """→ (coords (F, n, 3) f32, boxes (F, 6) f32 or None)."""
    if n_atoms is None or n_atoms <= 0:
        raise ValueError(
            "mdcrd needs the atom count from a topology "
            "(Universe(top, 'x.mdcrd')); the format does not carry it")
    counts, vals = _line_counts(path)
    if not counts:
        raise ValueError(
            f"{path}: no coordinate lines after the title — empty or "
            "truncated mdcrd")
    total = len(vals)
    per_plain = 3 * n_atoms
    per_boxed = per_plain + 3
    plain = _replays(counts, n_atoms, boxed=False)
    boxed = _replays(counts, n_atoms, boxed=True)
    if plain and boxed:
        # only reachable at n=1, where every line (coords or box)
        # carries exactly 3 values
        raise ValueError(
            f"{path}: ambiguous mdcrd for {n_atoms} atom(s) — every "
            "line carries 3 values whether coordinates or box; "
            "convert to NetCDF (or restart format) for 1-atom systems")
    if boxed:
        n_frames = total // per_boxed
        arr = np.asarray(vals, np.float32).reshape(n_frames, per_boxed)
        coords = arr[:, :per_plain].reshape(n_frames, n_atoms, 3)
        boxes = np.concatenate(
            [arr[:, per_plain:],
             np.full((n_frames, 3), 90.0, np.float32)], axis=1)
        return coords, boxes
    if plain:
        n_frames = total // per_plain
        return (np.asarray(vals, np.float32)
                .reshape(n_frames, n_atoms, 3)), None
    raise ValueError(
        f"{path}: line structure fits neither {n_atoms}-atom frames "
        f"({per_plain} values/frame) nor boxed frames ({per_boxed}) — "
        "wrong topology or truncated file")


def open_mdcrd(path: str, n_atoms: int | None = None) -> MemoryReader:
    coords, boxes = read_mdcrd(path, n_atoms)
    return MemoryReader(coords, dimensions=boxes)


def write_mdcrd(path: str, frames, boxes=None, title="mdanalysis_mpi_tpu"
                ) -> None:
    """Write (F, n, 3) frames as AMBER ASCII (10F8.3; one 3F8.3 box
    line per frame when ``boxes`` is given)."""
    frames = np.asarray(frames, np.float64)

    def check(arr, what):
        # F8.3 holds [-999.999, 9999.999]: 8 chars with no separators,
        # so one overflowing value shifts every later column
        if arr.size and (arr.max() > 9999.999 or arr.min() < -999.999):
            raise ValueError(
                f"{what} out of range for the F8.3 mdcrd format "
                "(must fit [-999.999, 9999.999]); use NetCDF")

    check(frames, "coordinate")
    if boxes is not None:
        check(np.asarray(boxes, np.float64), "box length")
    with open(path, "w") as fh:
        fh.write(title + "\n")
        for f, frame in enumerate(frames):
            flat = frame.reshape(-1)
            for k in range(0, len(flat), 10):
                fh.write("".join(f"{v:8.3f}" for v in flat[k:k + 10])
                         + "\n")
            if boxes is not None:
                b = np.asarray(boxes, np.float64)
                b = b[f] if b.ndim == 2 else b
                fh.write("".join(f"{v:8.3f}" for v in b[:3]) + "\n")


for _ext in ("mdcrd", "crdbox", "trj"):
    trajectory_files.register(_ext, open_mdcrd)
