"""Topology file-format registry: path → Topology.

Dispatch point for ``Universe("prot.gro", ...)`` (RMSF.py:56 analog).
Formats register themselves via :func:`register`; parsers live in
sibling modules (``gro``, ``psf``, ``pdb``).
"""

from __future__ import annotations

import os

from mdanalysis_mpi_tpu.core.topology import Topology

_PARSERS: dict[str, callable] = {}


def register(extension: str, parser) -> None:
    """Register ``parser(path) -> Topology`` for a file extension."""
    _PARSERS[extension.lower().lstrip(".")] = parser


def parse(path: str) -> Topology:
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    _autoload()
    parser = _PARSERS.get(ext)
    if parser is None:
        known = ", ".join(sorted(_PARSERS)) or "(none)"
        raise ValueError(
            f"no topology parser for {ext!r} ({path}); known formats: {known}")
    return parser(path)


def _autoload():
    """Import parser modules lazily so core has no hard format deps."""
    if _PARSERS:
        return
    try:
        from mdanalysis_mpi_tpu.io import gro, pdb, psf  # noqa: F401  (self-register)
    except ImportError:
        pass
