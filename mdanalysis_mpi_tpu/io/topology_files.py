"""Topology file-format registry: path → Topology.

Dispatch point for ``Universe("prot.gro", ...)`` (RMSF.py:56 analog).
Formats register themselves via :func:`register`; parsers live in
sibling modules (``gro``, ``psf``, ``pdb``).
"""

from __future__ import annotations

import os

from mdanalysis_mpi_tpu.core.topology import Topology

_PARSERS: dict[str, callable] = {}


def register(extension: str, parser) -> None:
    """Register ``parser(path) -> Topology`` for a file extension."""
    _PARSERS[extension.lower().lstrip(".")] = parser


def parse(path: str) -> Topology:
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    if not ext:
        # extensionless conventions (DL_POLY's CONFIG/REVCON): the
        # basename IS the format name
        ext = os.path.basename(path).lower()
    _autoload()
    parser = _PARSERS.get(ext)
    if parser is None:
        known = ", ".join(sorted(_PARSERS)) or "(none)"
        raise ValueError(
            f"no topology parser for {ext!r} ({path}); known formats: {known}")
    return parser(path)


def _tpr(path: str) -> Topology:
    """TPR (GROMACS portable run input) — documented conversion path.

    The serial oracle's docstring opens ``mda.Universe(TPR, XTC)``
    (RMSF.py:8).  TPR is a versioned binary serialization of the whole
    run input (tpx body: full mtop, force field, integrator state)
    whose layout changes between GROMACS releases; a parser that cannot
    be validated against real files from multiple GROMACS versions
    would be worse than none.  Convert once next to your trajectory —
    every GROMACS install can do it — and open the result:

        gmx editconf -f topol.tpr -o topol.gro     # coordinates+names
        # or, for a PDB with chain ids:
        gmx editconf -f topol.tpr -o topol.pdb

    then ``Universe("topol.gro", "traj.xtc")``.
    """
    raise ValueError(
        f"TPR files are not parsed directly ({path}); convert once with "
        "'gmx editconf -f topol.tpr -o topol.gro' (or -o topol.pdb) and "
        "open the GRO/PDB — see io/topology_files.py:_tpr for why")


_autoloaded = False


def _autoload():
    """Import parser modules lazily so core has no hard format deps.
    Guarded by a flag, not ``_PARSERS`` truthiness: a format module
    imported directly self-registers before the first ``parse`` call,
    which must not suppress the remaining registrations."""
    global _autoloaded
    if _autoloaded:
        return
    _autoloaded = True
    # every topology parser here is pure Python/NumPy: an ImportError
    # is a programming error and must surface — a swallowed one would
    # unregister EVERY format and misreport "no topology parser"
    from mdanalysis_mpi_tpu.io import (  # noqa: F401  (self-register)
        crd, dlpoly, dms, gro, itp, mol2, pdb, pdbqt, pqr, prmtop, psf,
        txyz)
    register("tpr", _tpr)
