"""Native chunked trajectory block store (docs/STORE.md).

The reader boundary was the last MDAnalysis-shaped layer in the repo:
every cold pass re-decoded the trajectory file sequentially, and
"Parallel Performance of MD Trajectory Analysis" (PAPERS.md,
1907.00097) shows trajectory I/O — not compute — is what caps parallel
scaling.  This package owns the I/O tier instead:

- **ingest once** (:func:`ingest`): one sequential decode pass
  re-chunks a trajectory to the staging geometry (chunk = the frame
  block ``_run_batches`` stages), quantizes coordinates with the same
  int16/int8 policy as the wire formats (f32 passthrough supported),
  and frames every chunk with CRC fingerprints
  (``utils/integrity.py``);
- **random access** (:class:`StoreReader`): a
  :class:`~mdanalysis_mpi_tpu.io.base.ReaderBase` whose
  ``stage_block`` serves chunk-aligned quantized requests as raw
  slices — no XDR decode, no re-quantize — so executors, prefetch,
  ``HostStageCache`` and the fleet's ``shard_windows`` children fetch
  exactly their slices through the boundary they already use;
- **verified reads**: every chunk's per-array fingerprints are
  re-computed at read time and compared against BOTH the chunk's own
  CRC-framed header and the manifest's stage-time copy — a flipped
  bit or a swapped chunk raises a typed
  :class:`~mdanalysis_mpi_tpu.utils.integrity.StoreCorruptError`
  (counted: ``mdtpu_store_chunk_crc_rejects_total``), never silently
  wrong numbers;
- **pluggable backend** (:class:`StoreBackend`): a local directory
  (:class:`LocalDirBackend`) or the remote chunk service
  (:class:`HttpStoreBackend` — content-addressed dedup, retry/hedge/
  breaker network boundary, cache-first degradation; docs/STORE.md
  "Remote backend") — the reader and ingester only ever see the
  byte-namespace methods.
"""

from mdanalysis_mpi_tpu.io.store.append import LiveIngest, follow
from mdanalysis_mpi_tpu.io.store.backend import (
    LocalDirBackend, StoreBackend,
)
from mdanalysis_mpi_tpu.io.store.ingest import DEFAULT_CHUNK_FRAMES, ingest
from mdanalysis_mpi_tpu.io.store.manifest import (
    MANIFEST_NAME, TAIL_MANIFEST_NAME, is_store, load_any_manifest,
    load_manifest, load_tail_manifest, store_meta,
)
from mdanalysis_mpi_tpu.io.store.reader import StoreEndOfFeed, StoreReader
from mdanalysis_mpi_tpu.io.store.remote import (
    ChunkCache, ChunkServer, HttpStoreBackend, ServerFault,
    is_store_url, open_remote_store,
)

__all__ = [
    "StoreBackend", "LocalDirBackend", "StoreReader", "ingest",
    "LiveIngest", "follow", "StoreEndOfFeed",
    "DEFAULT_CHUNK_FRAMES", "MANIFEST_NAME", "TAIL_MANIFEST_NAME",
    "is_store", "load_manifest", "load_any_manifest",
    "load_tail_manifest", "store_meta", "HttpStoreBackend",
    "ChunkCache", "ChunkServer", "ServerFault", "is_store_url",
    "open_remote_store",
]
