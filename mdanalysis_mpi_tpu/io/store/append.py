"""Append-able store ingest: the live tier (docs/STREAMING.md).

:func:`~mdanalysis_mpi_tpu.io.store.ingest.ingest` closes over a
finished trajectory; this module ingests one that is STILL BEING
WRITTEN.  :class:`LiveIngest` accepts frames as they arrive — pushed
by the producer (over any :class:`~mdanalysis_mpi_tpu.io.store.
backend.StoreBackend`, the remote chunk service included, which is
the PR-16 push story) or pulled by :func:`follow` tailing a growing
source file — seals a chunk the moment it fills, and rewrites a
CRC-sealed *tail manifest* (``manifest.tail.json``) beside the sealed
chunks after every seal.

Crash contract: the tail manifest only ever references fully-written
chunks and is replaced atomically, so killing the writer at ANY point
— mid-chunk, mid-manifest — leaves a valid shorter store described by
the last tail epoch, never a corrupt one.  :meth:`LiveIngest.seal`
flushes the final partial chunk, writes the closed ``manifest.json``
(promoting the store to a normal ingested one), then deletes the
tail; a reader racing the promotion sees the closed manifest first
(:func:`~mdanalysis_mpi_tpu.io.store.manifest.load_any_manifest`).
"""

from __future__ import annotations

import os
import time

import numpy as np

from mdanalysis_mpi_tpu.io.store import ingest as _ingest_mod
from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend
from mdanalysis_mpi_tpu.io.store.ingest import (
    DEFAULT_CHUNK_FRAMES, norm_store_quant,
)
from mdanalysis_mpi_tpu.io.store.manifest import (
    MANIFEST_NAME, TAIL_MANIFEST_NAME, dump_manifest,
)


def _count(metric: str, value: int = 1, **labels) -> None:
    from mdanalysis_mpi_tpu.obs import METRICS

    METRICS.inc(metric, value, **labels)


class LiveIngest:
    """Append frames into a growing store, chunk seal by chunk seal.

    ``append()`` buffers pushed frames and seals every full chunk
    immediately (chunk put first, then the tail manifest — epoch
    ``N+1`` — atomically replaced); ``seal()`` flushes the remainder
    and promotes the tail to the closed manifest.  Works over any
    store backend: a local directory for the tailing case, the
    remote :class:`~mdanalysis_mpi_tpu.io.store.remote.
    HttpStoreBackend` when frames are pushed over the chunk-service
    protocol.  NOT thread-safe — one producer owns a live ingest."""

    def __init__(self, out: str | None = None, n_atoms: int | None = None,
                 chunk_frames: int | None = None, quant="int16",
                 backend=None, content_addressed: bool | None = None,
                 source: str | None = None):
        if backend is None:
            if out is None:
                raise ValueError(
                    "LiveIngest needs an output path or a backend")
            backend = LocalDirBackend(out)
        if content_addressed is None:
            content_addressed = bool(
                getattr(backend, "content_addressed", False))
        self._backend = backend
        self._cas = content_addressed
        self._qmode = norm_store_quant(quant)
        self._cf = int(chunk_frames or DEFAULT_CHUNK_FRAMES)
        if self._cf < 1:
            raise ValueError(
                f"chunk_frames must be >= 1, got {self._cf}")
        self._na = None if n_atoms is None else int(n_atoms)
        self._source = source
        self._scale = None
        self._entries: list = []
        self._overflow = 0
        self._dedup_chunks = 0
        self._dedup_bytes = 0
        self._total_bytes = 0
        self._epoch = 0
        self._sealed = False
        self._t0 = time.perf_counter()
        self._buf: list = []          # (coords, boxes, times) blocks
        self._buffered = 0
        # live-ingest over a prior store: kill the old manifests FIRST
        # (the re-ingest invariant) — a crash mid-append must degrade
        # to "this tail's frames", never to a stale full manifest
        # whose fingerprints reject every replaced chunk
        backend.delete_bytes(MANIFEST_NAME)
        backend.delete_bytes(TAIL_MANIFEST_NAME)
        # epoch 1, zero chunks: publish the empty tail immediately so
        # a follow reader can open the store before the first chunk
        # seals (it simply serves n_frames == 0 until then)
        self._write_tail()

    # ---- state ----

    @property
    def epoch(self) -> int:
        """Tail-manifest epoch: bumps once per sealed chunk."""
        return self._epoch

    @property
    def frames_sealed(self) -> int:
        """Frames durably visible to followers (buffered frames of a
        not-yet-full chunk are NOT — they are the crash-loss window)."""
        return self._entries[-1]["stop"] if self._entries else 0

    @property
    def sealed(self) -> bool:
        return self._sealed

    # ---- appending ----

    def append(self, coords, boxes=None, times=None) -> int:
        """Append a block of frames (``(n, atoms, 3)``; a single
        ``(atoms, 3)`` frame is accepted too).  Seals every chunk the
        block completes; returns the number of chunks sealed (0 when
        everything is still buffered)."""
        if self._sealed:
            raise RuntimeError("LiveIngest is sealed; no more appends")
        coords = np.asarray(coords, dtype=np.float32)
        if coords.ndim == 2:
            coords = coords[None]
            boxes = None if boxes is None else np.asarray(boxes)[None]
            times = None if times is None else np.atleast_1d(times)
        if coords.ndim != 3 or coords.shape[-1] != 3:
            raise ValueError(
                f"append wants (n, atoms, 3) coords, got {coords.shape}")
        if self._na is None:
            self._na = int(coords.shape[1])
        elif coords.shape[1] != self._na:
            raise ValueError(
                f"append got {coords.shape[1]} atoms, store has "
                f"{self._na}")
        n = len(coords)
        self._buf.append((
            coords,
            None if boxes is None
            else np.asarray(boxes, dtype=np.float32).reshape(n, -1),
            None if times is None
            else np.asarray(times, dtype=np.float64).reshape(n)))
        self._buffered += n
        sealed = 0
        while self._buffered >= self._cf:
            self._seal_next_chunk(self._cf)
            sealed += 1
        return sealed

    def _take(self, n: int):
        """Pop exactly ``n`` buffered frames → (coords, boxes, times).
        boxes/times are carried only when EVERY contributing block has
        them (mixed producers degrade to none, matching the manifest's
        all-or-nothing has_boxes/has_times flags per chunk)."""
        got = 0
        parts = []
        while got < n:
            c, b, t = self._buf[0]
            take = min(n - got, len(c))
            parts.append((c[:take], None if b is None else b[:take],
                          None if t is None else t[:take]))
            if take == len(c):
                self._buf.pop(0)
            else:
                self._buf[0] = (
                    c[take:], None if b is None else b[take:],
                    None if t is None else t[take:])
            got += take
        self._buffered -= n
        coords = (parts[0][0] if len(parts) == 1
                  else np.concatenate([p[0] for p in parts]))
        boxes = (None if any(p[1] is None for p in parts)
                 else parts[0][1] if len(parts) == 1
                 else np.concatenate([p[1] for p in parts]))
        times = (None if any(p[2] is None for p in parts)
                 else parts[0][2] if len(parts) == 1
                 else np.concatenate([p[2] for p in parts]))
        return coords, boxes, times

    def _seal_next_chunk(self, n: int) -> None:
        coords, boxes, times = self._take(n)
        ci = len(self._entries)
        lo = self.frames_sealed
        entry, self._scale, overflow, dedup = _ingest_mod._seal_chunk(
            self._backend, ci, lo, coords, boxes, times, self._qmode,
            self._scale, self._cas)
        if overflow:
            self._overflow += 1
        if dedup:
            self._dedup_chunks += 1
            self._dedup_bytes += dedup
        self._entries.append(entry)
        self._total_bytes += entry["nbytes"]
        # chunk durably down → publish it: the tail manifest is the
        # ONLY thing a follower trusts, so the epoch bump happens
        # strictly after the chunk bytes it references
        self._write_tail()
        _count("mdtpu_stream_chunks_sealed_total")

    def _manifest(self) -> dict:
        return _ingest_mod.build_manifest(
            {"n_atoms": int(self._na or 0), "chunk_frames": self._cf,
             "quant": self._qmode, "source": self._source},
            self._entries, self._overflow)

    def _write_tail(self) -> None:
        self._epoch += 1
        man = self._manifest()
        man["epoch"] = self._epoch
        self._backend.put_bytes(TAIL_MANIFEST_NAME, dump_manifest(man))

    # ---- sealing ----

    def seal(self) -> dict:
        """Flush the final partial chunk, promote tail → closed
        manifest, delete the tail.  Returns an ingest-style summary
        dict (idempotent: a second seal returns the same summary)."""
        if self._sealed:
            return self._summary
        if self._buffered:
            self._seal_next_chunk(self._buffered)
        man = self._manifest()
        self._backend.put_bytes(MANIFEST_NAME, dump_manifest(man))
        self._backend.delete_bytes(TAIL_MANIFEST_NAME)
        self._sealed = True
        wall = time.perf_counter() - self._t0
        n_frames = man["n_frames"]
        summary = {
            "store": self._backend.describe(), "quant": self._qmode,
            "n_frames": n_frames, "n_chunks": len(self._entries),
            "chunk_frames": self._cf, "bytes": self._total_bytes,
            "scale_overflow_chunks": self._overflow,
            "epochs": self._epoch, "live": True,
            "wall_s": round(wall, 4),
            "store_ingest_fps": (round(n_frames / wall, 2)
                                 if wall > 0 else None),
        }
        if self._cas:
            summary["content_addressed"] = True
            summary["dedup_chunks"] = self._dedup_chunks
            summary["dedup_bytes"] = self._dedup_bytes
            summary["dedup_ratio"] = (
                round(self._dedup_bytes / self._total_bytes, 4)
                if self._total_bytes else 0.0)
        self._summary = summary
        return summary

    # context-manager sugar: ``with LiveIngest(out) as live: ...``
    # seals on clean exit; an exception propagates WITHOUT sealing —
    # the crash contract (valid shorter store) is the whole point
    def __enter__(self) -> "LiveIngest":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.seal()


def follow(trajectory, out: str | None = None,
           chunk_frames: int | None = None, quant="int16",
           backend=None, content_addressed: bool | None = None,
           poll_interval_s: float = 0.05, idle_timeout_s: float = 5.0,
           expect_frames: int | None = None, clock=time.monotonic,
           sleep=time.sleep) -> dict:
    """Tail a growing source trajectory into a live store.

    Polls the source for new frames (reopening the reader to refresh
    its frame count — file readers cache it at open), appends them
    through :class:`LiveIngest`, and seals when ``expect_frames`` is
    reached or the source stops growing for ``idle_timeout_s``.
    Returns the seal summary.  ``clock``/``sleep`` are injectable for
    deterministic tests."""
    from mdanalysis_mpi_tpu.io import trajectory_files

    reader = trajectory_files.open(os.fspath(trajectory))
    try:
        live = LiveIngest(out=out, n_atoms=reader.n_atoms,
                          chunk_frames=chunk_frames, quant=quant,
                          backend=backend,
                          content_addressed=content_addressed,
                          source=os.fspath(trajectory))
        done = 0
        last_growth = clock()
        while True:
            nf = reader.n_frames
            if expect_frames is not None:
                nf = min(nf, int(expect_frames))
            if nf > done:
                block, boxes = reader.read_block(done, nf)
                times = reader.frame_times(range(done, nf))
                live.append(block, boxes, times)
                done = nf
                last_growth = clock()
            if expect_frames is not None and done >= expect_frames:
                break
            if clock() - last_growth >= idle_timeout_s:
                break
            sleep(poll_interval_s)
            fresh = reader.reopen()
            if fresh is not reader:
                reader.close()
                reader = fresh
        return live.seal()
    finally:
        reader.close()
