"""Chunk codec: self-verifying framed array blobs.

One chunk = one staged frame block, encoded as::

    MAGIC | header_len (4 B LE) | header JSON | array bytes (C-order,
                                                concatenated)

The header carries each array's name/dtype/shape plus its
``zlib.crc32`` fingerprint — the SAME fingerprint family as the SDC
scrubber's ``utils.integrity.staged_fingerprint`` (C speed, fit for
bulk payloads) — and is itself sealed with the CRC32C record framing
the journal uses (``utils.integrity.record_crc``: pure-Python
Castagnoli is fine for a ~300-byte header, exactly the short-record
case it exists for).  Decoding verifies BOTH layers and, when the
caller passes the manifest's stage-time fingerprint list, cross-checks
it too — so a flipped payload bit, a corrupted header, a truncated
file, and a swapped-but-self-valid chunk all raise the same typed
:class:`~mdanalysis_mpi_tpu.utils.integrity.StoreCorruptError` instead
of dequantizing garbage.
"""

from __future__ import annotations

import hashlib
import json
import zlib

import numpy as np

from mdanalysis_mpi_tpu.utils import integrity as _integrity

MAGIC = b"MDTC1\n"
_LEN_BYTES = 4


def _reject(path: str, message: str):
    _integrity.note_corrupt("store", path)
    raise _integrity.integrity_error("store", message, path)


def encode_chunk(arrays: dict, meta: dict) -> tuple[bytes, list[int]]:
    """Encode named arrays + chunk metadata into one framed blob.

    Returns ``(blob, fingerprints)`` — the per-array ``zlib.crc32``
    list in array order, which the manifest records as the chunk's
    stage-time fingerprint (what read-time verification compares
    against).
    """
    descs = []
    payloads = []
    fps = []
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        crc = zlib.crc32(a)
        descs.append({"name": name, "dtype": str(a.dtype),
                      "shape": list(a.shape), "crc": crc})
        payloads.append(a.tobytes())
        fps.append(crc)
    header = {"format": "mdtpu-store-chunk", "meta": dict(meta),
              "arrays": descs}
    header["crc"] = _integrity.record_crc(header)
    hjson = json.dumps(header, sort_keys=True).encode()
    blob = b"".join(
        [MAGIC, len(hjson).to_bytes(_LEN_BYTES, "little"), hjson]
        + payloads)
    return blob, fps


def decode_chunk(blob: bytes, path: str = "<chunk>",
                 expect_fps=None) -> tuple[dict, dict]:
    """Decode + verify one framed chunk blob → ``(arrays, meta)``.

    ``expect_fps`` (the manifest's recorded fingerprint list) enables
    the read-time scrub comparison: a chunk whose bytes verify against
    its OWN header but not against the manifest (two valid chunks
    swapped on disk, or a re-written chunk under a stale manifest) is
    rejected too.  Returned arrays are read-only views over ``blob``
    (zero copy — the staging fast path slices them directly).
    """
    head_end = len(MAGIC) + _LEN_BYTES
    if blob[:len(MAGIC)] != MAGIC:
        _reject(path, f"store chunk {path!r} has no {MAGIC!r} magic — "
                      "not a chunk, or its head was destroyed")
    hlen = int.from_bytes(blob[len(MAGIC):head_end], "little")
    try:
        header = json.loads(blob[head_end:head_end + hlen])
        descs = header["arrays"]
    except Exception as exc:     # truncated / flipped header bytes
        _reject(path, f"store chunk {path!r} header is unparseable "
                      f"({type(exc).__name__}: {exc})")
    if not _integrity.verify_record(header):
        _reject(path, f"store chunk {path!r} fails its header CRC32C "
                      "— the frame metadata cannot be trusted")
    arrays: dict = {}
    crcs = []
    off = head_end + hlen
    mv = memoryview(blob)        # true zero copy: bytes slicing copies
    for d in descs:
        dt = np.dtype(d["dtype"])
        shape = tuple(d["shape"])
        size = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        seg = mv[off:off + size]
        if len(seg) != size:
            _reject(path, f"store chunk {path!r} is truncated: array "
                          f"{d['name']!r} needs {size} bytes, "
                          f"{len(seg)} remain")
        if zlib.crc32(seg) != d["crc"]:
            _reject(path, f"store chunk {path!r} array {d['name']!r} "
                          "fails its fingerprint — the bytes on disk "
                          "are not the bytes that were ingested")
        arrays[d["name"]] = np.frombuffer(seg, dtype=dt).reshape(shape)
        crcs.append(d["crc"])
        off += size
    if off != len(blob):
        _reject(path, f"store chunk {path!r} carries {len(blob) - off} "
                      "trailing bytes past its declared arrays")
    if expect_fps is not None and list(expect_fps) != crcs:
        _reject(path, f"store chunk {path!r} fingerprints do not match "
                      "the manifest's stage-time record — the chunk "
                      "was swapped or re-written under a stale "
                      "manifest")
    return arrays, header["meta"]


def chunk_name(index: int) -> str:
    return f"chunk-{index:08d}.mdtc"


# ---- content addressing (docs/STORE.md "Remote backend") ----

#: Content-addressed chunk names: ``cas-<sha256 hex>.mdtc``.  The name
#: IS the payload digest, so (a) identical chunks ingested by any
#: tenant collapse to one immutable shared object (dedup), and (b) any
#: holder — a network boundary included — can verify a CAS payload
#: from the name alone, before the reader's CRC/fingerprint pass.
CAS_PREFIX = "cas-"


def payload_digest(blob: bytes) -> str:
    """sha256 hex of a chunk payload — the content address."""
    return hashlib.sha256(blob).hexdigest()


def cas_chunk_name(digest: str) -> str:
    return f"{CAS_PREFIX}{digest}.mdtc"


def cas_digest(name: str) -> str | None:
    """The digest a CAS name claims, or None for positional
    (``chunk-``) and non-chunk names."""
    if not name.startswith(CAS_PREFIX) or not name.endswith(".mdtc"):
        return None
    return name[len(CAS_PREFIX):-len(".mdtc")]


def verify_cas(name: str, blob: bytes, source: str = "?") -> None:
    """Digest-check a CAS payload against its own name; mismatch is
    the fatal half of the taxonomy (bad bytes, never retried from the
    same source).  Positional names pass through — their verification
    lives in :func:`decode_chunk`."""
    want = cas_digest(name)
    if want is None:
        return
    got = payload_digest(blob)
    if got != want:
        _integrity.note_corrupt("store", name)
        raise _integrity.integrity_error(
            "store",
            f"store chunk {name!r} from {source} fails its content "
            f"address (sha256 {got[:16]}… != named {want[:16]}…) — "
            f"corrupt payload", name)
