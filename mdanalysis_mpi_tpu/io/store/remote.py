"""Remote chunk service: HTTP store backend + fault-hardened boundary.

The remote data tier ROADMAP item 3 calls in: chunks live behind a
minimal GET/PUT/HEAD/range HTTP protocol (stdlib ``http.client`` —
object stores are this four-verb shape) instead of a local directory,
and the network boundary is hardened the way the serving stack already
is (docs/RELIABILITY.md): deterministic fault injection, retry with
backoff under per-request deadlines, one hedged read for a slow
replica, a per-endpoint circuit breaker, and a degradation ladder that
keeps jobs completing through an outage.

Protocol (one namespace per tenant store + one shared CAS namespace)::

    GET/PUT/HEAD/DELETE  /stores/<store>/<name>     mutable objects
    GET/PUT/HEAD         /cas/<name>                immutable chunks
    GET                  /stores/<store>/           JSON name list

Chunks are **content-addressed** (``codec.cas_chunk_name``): the
object name is the payload's sha256, so identical trajectories
ingested by different tenants collapse to shared immutable objects
(dedup, surfaced in ingest summaries), and ANY holder can verify a
chunk payload from its name alone — which is what lets this boundary
reject a corrupt remote body typed (``StoreCorruptError``) and try a
different source instead of poisoning the cache, before the reader's
own CRC/fingerprint pass (which stays mandatory).

Read path = the degradation ladder (each step disclosed with a
``store_remote_degraded`` span instant):

1. per-host read-through :class:`ChunkCache` (immutable CAS names
   only — mutable names consult the remote first and fall back here);
2. remote endpoints in order, each behind a ``BreakerBoard`` breaker
   keyed ``(endpoint, "remote")``: retry with backoff on transient
   faults (timeout / 5xx / reset / truncated), at most one hedged
   read per GET when the primary is slow, NEVER a same-source retry
   after provable corruption;
3. the local mirror, if configured;
4. typed :class:`~mdanalysis_mpi_tpu.utils.integrity.
   StoreUnavailableError` (retryable) — or ``StoreCorruptError`` when
   every source that answered produced provably bad bytes.

:class:`ChunkServer` is the in-process test fixture (the ``statusd``
pattern: stdlib ``ThreadingHTTPServer`` on an ephemeral port) serving
the same protocol off a local directory, with a deterministic
server-side fault schedule (:class:`ServerFault`: 5xx, stalls,
connection resets, truncated bodies, corrupt payloads — visit
counters, no randomness) so every client failure path replays
bit-for-bit.  Client-side injection (a connection that never starts)
is the ``remote`` site in :mod:`mdanalysis_mpi_tpu.reliability.faults`.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from mdanalysis_mpi_tpu.io.store import codec
from mdanalysis_mpi_tpu.io.store.backend import (
    LocalDirBackend, StoreBackend,
)
from mdanalysis_mpi_tpu.reliability import faults as _faults
from mdanalysis_mpi_tpu.reliability.breaker import (
    HALF_OPEN, OPEN, BreakerBoard,
)
from mdanalysis_mpi_tpu.utils import integrity as _integrity

#: Default per-host read-through cache budget: enough to keep a whole
#: bench-scale wave's working set servable through an outage, small
#: next to the staged-block tiers above it.
DEFAULT_CACHE_BYTES = 256 << 20


def _obs():
    # lazy obs import, the utils/integrity.py convention
    from mdanalysis_mpi_tpu.obs import METRICS, span_event

    return METRICS, span_event


class _TransportError(Exception):
    """One failed HTTP round trip, classified for the retry policy and
    the ``mdtpu_store_remote_errors_total{kind=}`` counter: ``timeout``
    / ``reset`` / ``truncated`` / ``http_5xx``.  Internal — the
    envelope turns exhaustion into the typed public split."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


class ChunkCache:
    """Per-host read-through chunk cache: a byte-bounded, thread-safe
    LRU of VERIFIED blobs (everything inserted has passed its content
    address or come off a trusted local source).  Step 2 of the
    degradation ladder: a breaker-open remote serves warm reads from
    here at local speed.  Counts
    ``mdtpu_store_cache_{hits,misses}_total`` and gauges
    ``mdtpu_store_cache_bytes``."""

    def __init__(self, max_bytes: int = DEFAULT_CACHE_BYTES):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._blobs: dict = {}          # key -> bytes, LRU order
        self._bytes = 0

    def get(self, key) -> bytes | None:
        with self._lock:
            blob = self._blobs.pop(key, None)
            if blob is not None:
                self._blobs[key] = blob          # refresh recency
        metrics, _ = _obs()
        metrics.inc("mdtpu_store_cache_hits_total" if blob is not None
                    else "mdtpu_store_cache_misses_total")
        return blob

    def put(self, key, blob: bytes) -> None:
        if len(blob) > self.max_bytes:
            return                        # never evict the world for one
        with self._lock:
            old = self._blobs.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._blobs[key] = blob
            self._bytes += len(blob)
            while self._bytes > self.max_bytes and self._blobs:
                lru = next(iter(self._blobs))   # insertion order = LRU
                self._bytes -= len(self._blobs.pop(lru))
            total = self._bytes
        metrics, _ = _obs()
        metrics.set_gauge("mdtpu_store_cache_bytes", total)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._blobs)


#: The default per-host cache every remote reader shares (one outage,
#: one working set — a second reader of the same store must not fault
#: the same chunks twice).  Tests pass their own for isolation.
HOST_CHUNK_CACHE = ChunkCache()


class HttpStoreBackend(StoreBackend):
    """:class:`StoreBackend` over the chunk-service HTTP protocol.

    ``endpoints``
        One base URL or an ordered replica list
        (``"http://host:port"``); reads try them in order, writes land
        on the first healthy one.
    ``store``
        Tenant namespace for mutable names (``manifest.json``);
        content-addressed chunks live in the shared ``/cas/``
        namespace regardless.
    ``timeout_s`` / ``retries`` / ``backoff_s`` / ``backoff_factor``
        Per-request deadline (socket timeout) and the transient-fault
        retry envelope per endpoint.
    ``hedge_s``
        When set and a second replica exists: a GET whose primary has
        not answered within ``hedge_s`` issues ONE hedged read against
        the next replica and takes whichever source answers first
        (counted ``mdtpu_store_remote_hedges_total``; breaker
        accounting stays with the primary conversation).
    ``breakers``
        A shared :class:`~mdanalysis_mpi_tpu.reliability.breaker.
        BreakerBoard` (one per process is typical); breakers are keyed
        ``(endpoint, "remote")`` so one replica's outage never
        blacklists another.  An open breaker skips its endpoint; a
        half-open one admits traffic only after a cheap HEAD probe
        succeeds (recovery is the probe path, not a tenant read).
    ``cache`` / ``mirror``
        Ladder steps 2 and 3: the read-through :class:`ChunkCache`
        (defaults to the per-host :data:`HOST_CHUNK_CACHE`) and an
        optional local :class:`StoreBackend` (or path) holding a
        mirror of the store.
    """

    #: The ingester keys chunks by payload digest over this backend
    #: (dedup across tenants — docs/STORE.md "Remote backend").
    content_addressed = True

    def __init__(self, endpoints, store: str = "default", *,
                 timeout_s: float = 5.0, retries: int = 2,
                 backoff_s: float = 0.05, backoff_factor: float = 2.0,
                 hedge_s: float | None = None, breakers=None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 cache: ChunkCache | None = None, mirror=None,
                 sleep=time.sleep):
        if isinstance(endpoints, str):
            endpoints = [endpoints]
        self.endpoints = [e.rstrip("/") for e in endpoints]
        if not self.endpoints:
            raise ValueError("HttpStoreBackend needs >= 1 endpoint")
        self.store = store
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.hedge_s = None if hedge_s is None else float(hedge_s)
        self.breakers = breakers if breakers is not None else \
            BreakerBoard(threshold=breaker_threshold,
                         cooldown_s=breaker_cooldown_s)
        self.cache = cache if cache is not None else HOST_CHUNK_CACHE
        if isinstance(mirror, (str, os.PathLike)):
            mirror = LocalDirBackend(os.fspath(mirror))
        self.mirror = mirror
        self._sleep = sleep
        # per-thread source of the last get_bytes (usage attribution);
        # thread-local because fleet workers share one backend
        self._usage_src = threading.local()

    @property
    def usage_source(self) -> str:
        """``cache`` / ``remote`` / ``local`` (mirror) — which ladder
        rung served this thread's last :meth:`get_bytes`."""
        return getattr(self._usage_src, "value", "remote")

    # ---- protocol plumbing ----

    def _path(self, name: str) -> str:
        if codec.cas_digest(name) is not None:
            return f"/cas/{name}"
        return f"/stores/{self.store}/{name}"

    def _cache_key(self, name: str):
        # CAS names are globally immutable (digest-verified), so the
        # cache entry is shared across stores/tenants; mutable names
        # (the manifest) are namespaced to this store's first endpoint
        if codec.cas_digest(name) is not None:
            return ("cas", name)
        return (self.endpoints[0], self.store, name)

    def _request(self, endpoint: str, method: str, path: str,
                 body: bytes | None = None, headers=None,
                 timeout: float | None = None):
        """One HTTP round trip → ``(status, headers, body)``; raises
        :class:`_TransportError` for anything that is not a complete
        response (and maps 5xx there too — a retryable server-side
        failure, unlike 4xx which is a protocol answer)."""
        if _faults.plans():
            try:
                _faults.fire("remote")
            except _faults.InjectedTransientError as exc:
                # the client-half injection (a connection that never
                # starts) enters the SAME retry envelope a real
                # refused connection would; deliberately only the
                # transient class — an InjectedCrash must keep
                # unwinding past every recovery layer
                raise _TransportError("injected", str(exc)) from exc
        metrics, _ = _obs()
        metrics.inc("mdtpu_store_remote_requests_total", verb=method)
        u = urlsplit(endpoint)
        conn = http.client.HTTPConnection(
            u.hostname, u.port, timeout=timeout or self.timeout_s)
        try:
            try:
                conn.request(method, path, body=body,
                             headers=dict(headers or {}))
                resp = conn.getresponse()
                status = resp.status
                rheaders = dict(resp.getheaders())
                data = b"" if method == "HEAD" else resp.read()
            except TimeoutError as exc:
                raise _TransportError(
                    "timeout", f"{method} {endpoint}{path} exceeded "
                               f"its {timeout or self.timeout_s}s "
                               f"deadline") from exc
            except http.client.IncompleteRead as exc:
                raise _TransportError(
                    "truncated", f"{method} {endpoint}{path} body "
                                 f"truncated ({exc})") from exc
            except (ConnectionError, http.client.HTTPException,
                    OSError) as exc:
                raise _TransportError(
                    "reset", f"{method} {endpoint}{path} connection "
                             f"failed ({type(exc).__name__}: "
                             f"{exc})") from exc
        finally:
            conn.close()
        if method != "HEAD":
            clen = rheaders.get("Content-Length")
            if clen is not None and len(data) != int(clen):
                raise _TransportError(
                    "truncated", f"{method} {endpoint}{path} body "
                                 f"truncated ({len(data)}/{clen} B)")
        if status >= 500:
            raise _TransportError(
                "http_5xx", f"{method} {endpoint}{path} -> {status}")
        return status, rheaders, data

    # ---- the robustness envelope ----

    def _breaker(self, endpoint: str):
        return self.breakers.get(endpoint, "remote")

    def _admit(self, endpoint: str) -> bool:
        """Consult the endpoint's breaker: open skips it, half-open
        admits only after a cheap HEAD probe (the recovery path —
        probe success closes the breaker, failure re-opens it for
        another cooldown)."""
        br = self._breaker(endpoint)
        st = br.state
        if st == OPEN:
            return False
        if st == HALF_OPEN:
            return br.probe(lambda: self._request(
                endpoint, "HEAD",
                f"/stores/{self.store}/manifest.json"))
        return True

    def _with_retries(self, endpoint: str, fn, name: str):
        """Run ``fn()`` against one endpoint under the transient-fault
        retry envelope.  Corruption is NOT in the envelope — a caller
        seeing ``StoreCorruptError`` moves to a different source."""
        metrics, span_event = _obs()
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                return fn(attempt)
            except _TransportError as exc:
                metrics.inc("mdtpu_store_remote_errors_total",
                            kind=exc.kind)
                if attempt >= self.retries:
                    raise
                metrics.inc("mdtpu_store_remote_retries_total")
                span_event("store_remote_retry", endpoint=endpoint,
                           chunk=name, kind=exc.kind,
                           attempt=attempt + 1)
                self._sleep(delay)
                delay *= self.backoff_factor

    def _hedged_get(self, endpoint: str, path: str, name: str,
                    attempt: int):
        """One GET with at most one hedged read: if the primary has
        not answered within ``hedge_s``, race the next replica and
        take the first complete answer (hedging applies to the first
        attempt only — backoff retries are already the slow path)."""
        others = [e for e in self.endpoints if e != endpoint]
        if self.hedge_s is None or attempt > 0 or not others:
            return self._request(endpoint, "GET", path)
        outcome: dict = {}
        done = threading.Event()

        def _primary():
            try:
                outcome["r"] = self._request(endpoint, "GET", path)
            except Exception as exc:            # re-raised by winner
                outcome["e"] = exc
            finally:
                done.set()

        t = threading.Thread(target=_primary, daemon=True,
                             name="mdtpu-store-hedge-primary")
        t.start()
        if done.wait(self.hedge_s):
            if "e" in outcome:
                raise outcome["e"]
            return outcome["r"]
        metrics, span_event = _obs()
        metrics.inc("mdtpu_store_remote_hedges_total")
        span_event("store_remote_hedge", slow=endpoint,
                   hedge=others[0], chunk=name)
        try:
            return self._request(others[0], "GET", path)
        except (_TransportError, _integrity.IntegrityError):
            # hedge lost too: fall back to however the slow primary
            # ends (its own deadline bounds the wait)
            done.wait(self.timeout_s + 1.0)
            if "r" in outcome:
                return outcome["r"]
            if "e" in outcome:
                raise outcome["e"] from None
            raise

    def _remote_get(self, name: str):
        """The per-endpoint read loop → ``(blob | None,
        last_corrupt_exc | None)``.  A provably corrupt body fails the
        endpoint immediately (never a same-source retry); transient
        faults retry with backoff then fail the endpoint; a 404 is a
        HEALTHY conversation (the replica just lacks the name)."""
        metrics, span_event = _obs()
        path = self._path(name)
        last_corrupt = None
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                status, _h, data = self._with_retries(
                    endpoint,
                    lambda attempt: self._hedged_get(
                        endpoint, path, name, attempt),
                    name)
            except _TransportError:
                br.record_failure()
                continue
            if status == 404:
                br.record_success()
                continue
            if status >= 400:
                br.record_failure()
                continue
            try:
                codec.verify_cas(name, data, source=endpoint)
            except _integrity.StoreCorruptError as exc:
                metrics.inc("mdtpu_store_remote_errors_total",
                            kind="corrupt")
                last_corrupt = exc
                br.record_failure()
                continue            # a DIFFERENT source, never a retry
            br.record_success()
            return data, None
        return None, last_corrupt

    # ---- StoreBackend surface ----

    def get_bytes(self, name: str) -> bytes:
        metrics, span_event = _obs()
        key = self._cache_key(name)
        immutable = codec.cas_digest(name) is not None
        if immutable:
            hit = self.cache.get(key)
            if hit is not None:
                self._usage_src.value = "cache"
                return hit
        blob, corrupt = self._remote_get(name)
        if blob is not None:
            self.cache.put(key, blob)
            self._usage_src.value = "remote"
            return blob
        # ---- degradation ladder (remote exhausted) ----
        if not immutable:
            # mutable names consult the remote first (a re-ingest must
            # be visible), so the cache is their OUTAGE copy — serving
            # it here is the disclosed stale-read degradation
            hit = self.cache.get(key)
            if hit is not None:
                span_event("store_remote_degraded", step="cache",
                           chunk=name)
                self._usage_src.value = "cache"
                return hit
        if self.mirror is not None:
            try:
                blob = self.mirror.get_bytes(name)
                codec.verify_cas(name, blob,
                                 source=self.mirror.describe())
                metrics.inc("mdtpu_store_mirror_reads_total")
                span_event("store_remote_degraded", step="mirror",
                           chunk=name)
                self.cache.put(key, blob)
                self._usage_src.value = "local"
                return blob
            except (_integrity.StoreUnavailableError, OSError):
                pass
        if corrupt is not None:
            # every source that produced bytes produced WRONG bytes:
            # that is the fatal half of the split, not an availability
            # blip a retry could heal
            raise corrupt
        metrics.inc("mdtpu_store_unavailable_total")
        span_event("store_remote_degraded", step="unavailable",
                   chunk=name)
        raise _integrity.StoreUnavailableError(
            f"store object {name!r} unavailable: every remote "
            f"endpoint failed or is breaker-open "
            f"({', '.join(self.endpoints)}), not cached"
            + ("" if self.mirror is None else ", mirror missed"),
            name=name, source=self.describe())

    def get_range(self, name: str, start: int, stop: int) -> bytes:
        if start < 0 or stop < start:
            raise ValueError(
                f"bad byte range [{start}, {stop}) for {name!r}")
        if start == stop:
            return b""
        hit = self.cache.get(self._cache_key(name))
        if hit is not None:
            return hit[start:stop]
        path = self._path(name)
        rng = {"Range": f"bytes={start}-{stop - 1}"}
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                status, _h, data = self._with_retries(
                    endpoint,
                    lambda attempt: self._request(
                        endpoint, "GET", path, headers=rng),
                    name)
            except _TransportError:
                br.record_failure()
                continue
            if status == 416:           # start past the end: slice
                br.record_success()     # semantics say empty, like
                return b""              # get_bytes(name)[len:...]
            if status == 404:
                br.record_success()
                continue
            if status >= 400:
                br.record_failure()
                continue
            br.record_success()
            if status == 206:
                return data
            return data[start:stop]     # 200: whole body, slice local
        if self.mirror is not None:
            try:
                data = self.mirror.get_range(name, start, stop)
                metrics, span_event = _obs()
                metrics.inc("mdtpu_store_mirror_reads_total")
                span_event("store_remote_degraded", step="mirror",
                           chunk=name)
                return data
            except (_integrity.StoreUnavailableError, OSError):
                pass
        metrics, span_event = _obs()
        metrics.inc("mdtpu_store_unavailable_total")
        span_event("store_remote_degraded", step="unavailable",
                   chunk=name)
        raise _integrity.StoreUnavailableError(
            f"byte range [{start},{stop}) of {name!r} unavailable "
            f"from {', '.join(self.endpoints)}",
            name=name, source=self.describe())

    def put_bytes(self, name: str, data: bytes) -> None:
        path = self._path(name)
        last: Exception | None = None
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                status, _h, _b = self._with_retries(
                    endpoint,
                    lambda attempt: self._request(
                        endpoint, "PUT", path, body=data,
                        headers={"Content-Length": str(len(data))}),
                    name)
            except _TransportError as exc:
                br.record_failure()
                last = exc
                continue
            if status in (200, 201, 204):
                br.record_success()
                # writes refresh the read-through copy so a read-after-
                # write during an outage serves what was written
                self.cache.put(self._cache_key(name), data)
                return
            br.record_failure()
            last = _TransportError("http_5xx",
                                   f"PUT {path} -> {status}")
        raise _integrity.StoreUnavailableError(
            f"could not write store object {name!r} to any endpoint "
            f"({', '.join(self.endpoints)}): {last}",
            name=name, source=self.describe())

    def exists(self, name: str) -> bool:
        if codec.cas_digest(name) is not None \
                and self.cache.get(self._cache_key(name)) is not None:
            return True
        path = self._path(name)
        last: Exception | None = None
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                status, _h, _b = self._with_retries(
                    endpoint,
                    lambda attempt: self._request(endpoint, "HEAD",
                                                  path),
                    name)
            except _TransportError as exc:
                br.record_failure()
                last = exc
                continue
            br.record_success()
            if status == 200:
                return True
            # 404: this replica lacks it — a later replica may not
        if last is None:
            return False
        raise _integrity.StoreUnavailableError(
            f"could not HEAD store object {name!r} on any endpoint: "
            f"{last}", name=name, source=self.describe())

    def delete_bytes(self, name: str) -> None:
        if codec.cas_digest(name) is not None:
            return          # CAS objects are immutable and shared —
        #                     lifecycle (refcount/GC) is the service's
        path = self._path(name)
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                self._with_retries(
                    endpoint,
                    lambda attempt: self._request(endpoint, "DELETE",
                                                  path),
                    name)
                br.record_success()
                return
            except _TransportError:
                br.record_failure()
        raise _integrity.StoreUnavailableError(
            f"could not delete store object {name!r} on any endpoint",
            name=name, source=self.describe())

    def list_names(self) -> list[str]:
        for endpoint in self.endpoints:
            if not self._admit(endpoint):
                continue
            br = self._breaker(endpoint)
            try:
                status, _h, data = self._with_retries(
                    endpoint,
                    lambda attempt: self._request(
                        endpoint, "GET", f"/stores/{self.store}/"),
                    "<list>")
            except _TransportError:
                br.record_failure()
                continue
            br.record_success()
            if status == 200:
                return sorted(json.loads(data))
        raise _integrity.StoreUnavailableError(
            "could not list store names on any endpoint",
            source=self.describe())

    def describe(self) -> str:
        return f"{self.endpoints[0]}/stores/{self.store}"


# ---------------------------------------------------------------------------
# URL plumbing: job specs / CLI carry one string
# ---------------------------------------------------------------------------

def is_store_url(target) -> bool:
    return isinstance(target, str) and \
        target.startswith(("http://", "https://"))


def backend_from_url(url: str, cache: ChunkCache | None = None,
                     breakers=None) -> HttpStoreBackend:
    """Build a hardened backend from a store URL::

        http://host:port/stores/<name>[?mirror=/local/store&...]

    Query knobs (all optional, so a fleet job spec can carry the whole
    remote-read policy in its one trajectory string): ``mirror``
    (local mirror directory), ``retries``, ``timeout_s``,
    ``backoff_s``, ``hedge_s``, ``breaker_threshold``,
    ``breaker_cooldown_s``.
    """
    u = urlsplit(url)
    parts = [p for p in u.path.split("/") if p]
    if len(parts) != 2 or parts[0] != "stores":
        raise ValueError(
            f"store URL must look like http://host:port/stores/NAME, "
            f"got {url!r}")
    q = {k: v[-1] for k, v in parse_qs(u.query).items()}
    opts: dict = {}
    if "mirror" in q:
        opts["mirror"] = q["mirror"]
    for knob, conv in (("retries", int), ("timeout_s", float),
                       ("backoff_s", float), ("hedge_s", float),
                       ("breaker_threshold", int),
                       ("breaker_cooldown_s", float)):
        if knob in q:
            opts[knob] = conv(q[knob])
    endpoint = f"{u.scheme}://{u.netloc}"
    return HttpStoreBackend(endpoint, store=parts[1], cache=cache,
                            breakers=breakers, **opts)


def open_remote_store(url: str, n_atoms: int | None = None,
                      cache: ChunkCache | None = None, breakers=None):
    """A :class:`~mdanalysis_mpi_tpu.io.store.reader.StoreReader` over
    a store URL — what ``trajectory_files.open`` dispatches to, so a
    fleet job spec's trajectory can be a remote store the same way it
    can be an ingested directory."""
    from mdanalysis_mpi_tpu.io.store.reader import StoreReader

    backend = backend_from_url(url, cache=cache, breakers=breakers)
    return StoreReader(url, n_atoms=n_atoms, backend=backend)


def remote_store_meta(url: str) -> dict | None:
    """Verified manifest for a store URL, or None when it cannot be
    fetched right now — the fleet controller's routing accessor
    (chunk-aligned shard windows) must degrade to un-chunked sharding,
    not fail the submit, when the remote tier is briefly dark."""
    from mdanalysis_mpi_tpu.io.store.manifest import load_manifest

    try:
        return load_manifest(backend_from_url(url))
    except (_integrity.IntegrityError, OSError):
        return None


# ---------------------------------------------------------------------------
# the in-process fixture service (tests, smoke, bench)
# ---------------------------------------------------------------------------

class ServerFault:
    """One armed server-side fault, deterministic like
    :class:`~mdanalysis_mpi_tpu.reliability.faults.FaultSpec` (visit
    counters, no randomness).

    ``kind``
        ``"http_5xx"`` (answer ``status``), ``"stall"`` (sleep
        ``stall_s`` before answering — longer than the client deadline
        means a client-side timeout), ``"reset"`` (close the socket
        without a response), ``"truncate"`` (declare the full
        Content-Length, send ``truncate_at`` bytes, close), or
        ``"corrupt"`` (flip one payload byte — the content address no
        longer matches).
    ``method`` / ``match``
        Only requests with this verb whose path contains ``match``
        fire the fault (empty matches everything).
    ``after`` / ``times``
        Skip ``after`` matching requests, then fire at most ``times``
        (None = every match) — deterministic mid-wave placement.
    """

    KINDS = ("http_5xx", "stall", "reset", "truncate", "corrupt")

    def __init__(self, kind: str, *, method: str = "GET",
                 match: str = "", after: int = 0,
                 times: int | None = 1, status: int = 503,
                 stall_s: float = 0.2, truncate_at: int = 64):
        if kind not in self.KINDS:
            raise ValueError(f"unknown server fault kind {kind!r}")
        self.kind = kind
        self.method = method
        self.match = match
        self.after = int(after)
        self.times = times
        self.status = int(status)
        self.stall_s = float(stall_s)
        self.truncate_at = int(truncate_at)
        self.visits = 0
        self.fired = 0


class ChunkServer:
    """In-process chunk service over a local directory (the
    ``statusd`` pattern: stdlib ``ThreadingHTTPServer``, daemon serve
    thread, ephemeral port) — the deterministic test double for a real
    object-store tier, with :class:`ServerFault` injection riding the
    real socket so the client's timeout/5xx/reset/truncated/corrupt
    handling is exercised end to end.

    Layout under ``root``: ``stores/<store>/<name>`` for mutable
    objects, ``cas/<name>`` for content-addressed chunks.  CAS PUTs
    are digest-verified (422 on mismatch — poison never enters the
    shared namespace) and deduplicated: re-putting an existing object
    is acknowledged without a write (``dedup_puts`` counts them, and
    ``cas_bytes_written`` proves a second tenant's identical ingest
    moved zero chunk bytes)."""

    def __init__(self, root: str, bind_host: str = "127.0.0.1",
                 port: int = 0):
        self.root = os.fspath(root)
        os.makedirs(os.path.join(self.root, "cas"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "stores"), exist_ok=True)
        self._lock = threading.Lock()
        self._faults: list[ServerFault] = []
        self.requests = 0
        self.put_requests = 0
        self.dedup_puts = 0
        self.cas_bytes_written = 0
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):    # quiet: tests, not stderr
                pass

            def do_GET(self):
                outer._handle(self, "GET")

            def do_HEAD(self):
                outer._handle(self, "HEAD")

            def do_PUT(self):
                outer._handle(self, "PUT")

            def do_DELETE(self):
                outer._handle(self, "DELETE")

        self._server = ThreadingHTTPServer((bind_host, port), _Handler)
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self.url = f"http://{self.address[0]}:{self.address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mdtpu-chunkd")
        self._thread.start()

    def store_url(self, store: str, **query) -> str:
        """The one-string client target for ``store`` (optionally with
        policy query knobs — see :func:`backend_from_url`)."""
        qs = "&".join(f"{k}={v}" for k, v in query.items())
        return f"{self.url}/stores/{store}" + (f"?{qs}" if qs else "")

    # ---- fault schedule ----

    def inject(self, *faults: ServerFault) -> None:
        with self._lock:
            self._faults.extend(faults)

    def clear_faults(self) -> None:
        with self._lock:
            self._faults.clear()

    def _match_fault(self, method: str, path: str) -> ServerFault | None:
        with self._lock:
            for f in self._faults:
                if f.method != method or f.match not in path:
                    continue
                f.visits += 1
                if f.visits <= f.after:
                    continue
                if f.times is not None and f.fired >= f.times:
                    continue
                f.fired += 1
                return f
        return None

    # ---- storage ----

    def _fs_path(self, path: str) -> str | None:
        parts = [p for p in path.split("/") if p]
        if len(parts) == 2 and parts[0] == "cas":
            return os.path.join(self.root, "cas", parts[1])
        if len(parts) == 3 and parts[0] == "stores":
            return os.path.join(self.root, "stores", parts[1],
                                parts[2])
        return None

    def _handle(self, handler, method: str) -> None:
        path = handler.path.split("?", 1)[0]
        with self._lock:
            self.requests += 1
        fault = self._match_fault(method, path)
        if fault is not None and fault.kind == "http_5xx":
            self._send(handler, fault.status,
                       json.dumps({"error": "injected"}).encode())
            return
        if fault is not None and fault.kind == "reset":
            # no response at all: the client sees a dropped connection
            try:
                handler.connection.close()
            except OSError:
                pass
            return
        if fault is not None and fault.kind == "stall":
            time.sleep(fault.stall_s)
        try:
            self._dispatch(handler, method, path, fault)
        except BrokenPipeError:
            pass                     # client gave up mid-response

    def _dispatch(self, handler, method: str, path: str,
                  fault) -> None:
        parts = [p for p in path.split("/") if p]
        # listing: GET /stores/<store>/ (or no trailing slash)
        if method == "GET" and len(parts) == 2 \
                and parts[0] == "stores":
            d = os.path.join(self.root, "stores", parts[1])
            names = sorted(os.listdir(d)) if os.path.isdir(d) else []
            self._send(handler, 200, json.dumps(names).encode())
            return
        fsp = self._fs_path(path)
        if fsp is None:
            self._send(handler, 400,
                       json.dumps({"error": f"bad path {path!r}"})
                       .encode())
            return
        if method in ("GET", "HEAD"):
            if not os.path.exists(fsp):
                self._send(handler, 404, b"", head=(method == "HEAD"))
                return
            with open(fsp, "rb") as f:
                body = f.read()
            status = 200
            rng = handler.headers.get("Range")
            if rng is not None and method == "GET":
                status, body = self._slice(handler, rng, body)
                if body is None:
                    return              # 416 already sent
            if fault is not None and fault.kind == "corrupt":
                mut = bytearray(body)
                if mut:
                    mut[len(mut) // 2] ^= 0x40
                body = bytes(mut)
            if fault is not None and fault.kind == "truncate":
                self._send_truncated(handler, body,
                                     fault.truncate_at)
                return
            self._send(handler, status, body,
                       head=(method == "HEAD"))
            return
        if method == "PUT":
            clen = int(handler.headers.get("Content-Length", "0"))
            body = handler.rfile.read(clen)
            with self._lock:
                self.put_requests += 1
            digest = codec.cas_digest(os.path.basename(fsp))
            if path.startswith("/cas/"):
                if digest is None or \
                        codec.payload_digest(body) != digest:
                    self._send(handler, 422, json.dumps(
                        {"error": "payload does not match its "
                                  "content address"}).encode())
                    return
                if os.path.exists(fsp):
                    with self._lock:
                        self.dedup_puts += 1
                    self._send(handler, 200, b"")   # dedup: no write
                    return
                with self._lock:
                    self.cas_bytes_written += len(body)
            os.makedirs(os.path.dirname(fsp), exist_ok=True)
            _integrity.atomic_write_bytes(fsp, body, artifact="store")
            self._send(handler, 201, b"")
            return
        if method == "DELETE":
            if path.startswith("/cas/"):
                self._send(handler, 405, b"")       # immutable
                return
            try:
                os.remove(fsp)
            except FileNotFoundError:
                pass
            self._send(handler, 204, b"")
            return
        self._send(handler, 405, b"")

    def _slice(self, handler, rng: str, body: bytes):
        try:
            unit, _, span = rng.partition("=")
            lo_s, _, hi_s = span.partition("-")
            if unit.strip() != "bytes" or not lo_s:
                raise ValueError(rng)
            lo = int(lo_s)
            hi = int(hi_s) if hi_s else len(body) - 1
        except ValueError:
            self._send(handler, 400, b"")
            return None, None
        if lo >= len(body):
            handler.send_response(416)
            handler.send_header("Content-Range",
                                f"bytes */{len(body)}")
            handler.send_header("Content-Length", "0")
            handler.end_headers()
            return None, None
        return 206, body[lo:min(hi, len(body) - 1) + 1]

    def _send(self, handler, status: int, body: bytes,
              head: bool = False) -> None:
        try:
            handler.send_response(status)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            if not head and body:
                handler.wfile.write(body)
        except OSError:
            pass

    def _send_truncated(self, handler, body: bytes,
                        truncate_at: int) -> None:
        """Declare the full length, write a prefix, drop the socket —
        the wire shape of a mid-transfer replica death."""
        try:
            handler.send_response(200)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body[:max(0, truncate_at)])
            handler.wfile.flush()
        except OSError:
            pass
        try:
            handler.connection.close()
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
