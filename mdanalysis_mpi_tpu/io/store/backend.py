"""Pluggable store backends: a named byte namespace.

The reader and ingester address chunks by NAME only (``manifest.json``,
``chunk-00000042.mdtc``); everything about *where* those bytes live is
behind this four-method interface.  A local directory is the shipped
backend; an object store (GCS/S3-style) implements the same four
methods later — which is why the interface is bytes-in/bytes-out with
no seek/stream surface: chunk granularity IS the access granularity
(a chunk equals one staged block, so partial-chunk reads would only
re-create the random-access problem the store exists to solve).
"""

from __future__ import annotations

import os

from mdanalysis_mpi_tpu.utils import integrity as _integrity


class StoreBackend:
    """Abstract chunk-store backend (local dir now, object store
    later).  Implementations must make :meth:`put_bytes` atomic —
    a reader must never observe a torn chunk (the local backend
    rides ``utils.integrity.atomic_write_bytes``'s
    tmp → fsync → rename)."""

    def put_bytes(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, name: str) -> bytes:
        raise NotImplementedError

    def exists(self, name: str) -> bool:
        raise NotImplementedError

    def delete_bytes(self, name: str) -> None:
        """Remove ``name`` if present (idempotent).  The ingester
        deletes a pre-existing manifest BEFORE overwriting chunks, so
        a crashed re-ingest leaves a directory that is not a store
        (the fresh-ingest invariant) instead of a valid-looking
        manifest over half-replaced chunks."""
        raise NotImplementedError

    def list_names(self) -> list[str]:
        raise NotImplementedError

    def describe(self) -> str:
        """Human-readable location (error messages, manifests)."""
        return type(self).__name__


class LocalDirBackend(StoreBackend):
    """Chunks as files under one directory.

    Writes are atomic (``utils.integrity.atomic_write_bytes``:
    tmp → fsync → rename, ENOSPC-class failures mapped to typed
    :class:`~mdanalysis_mpi_tpu.utils.integrity.ArtifactWriteError`
    and counted), so a crashed ingest leaves whole chunks or no
    chunks — and since the manifest is written LAST, no manifest at
    all: the half-ingested directory is simply not a store."""

    def __init__(self, root: str):
        self.root = os.fspath(root)

    def put_bytes(self, name: str, data: bytes) -> None:
        os.makedirs(self.root, exist_ok=True)
        _integrity.atomic_write_bytes(
            os.path.join(self.root, name), data, artifact="store")

    def get_bytes(self, name: str) -> bytes:
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.root, name))

    def delete_bytes(self, name: str) -> None:
        try:
            os.remove(os.path.join(self.root, name))
        except FileNotFoundError:
            pass

    def list_names(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(os.listdir(self.root))

    def describe(self) -> str:
        return self.root
