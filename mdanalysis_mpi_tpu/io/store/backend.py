"""Pluggable store backends: a named byte namespace.

The reader and ingester address chunks by NAME only (``manifest.json``,
``chunk-00000042.mdtc``, ``cas-<digest>.mdtc``); everything about
*where* those bytes live is behind this interface.  A local directory
is the shipped default; :class:`~mdanalysis_mpi_tpu.io.store.remote.
HttpStoreBackend` speaks the same methods over a GET/PUT/HEAD/range
chunk protocol.  The interface stays bytes-in/bytes-out with chunk
granularity as the PRIMARY access granularity (a chunk equals one
staged block); :meth:`StoreBackend.get_range` exists for transports
where a byte sub-range is cheaper than the whole object (HTTP Range
requests serving exactly the spans a shard child needs) and defaults
to slicing a whole-object read, so every backend supports it.

Error taxonomy at this boundary (the reader keys off it):

- :class:`~mdanalysis_mpi_tpu.utils.integrity.StoreUnavailableError`
  (an ``OSError``, retryable): the name could not be produced at all
  — missing file/replica, unreachable endpoint.
- :class:`~mdanalysis_mpi_tpu.utils.integrity.StoreCorruptError`
  (an ``IntegrityError``, fatal): bytes were produced and are
  provably wrong (digest/CRC mismatch).  Never re-fetched from the
  same source as "transient".
"""

from __future__ import annotations

import os

from mdanalysis_mpi_tpu.utils import integrity as _integrity


class StoreBackend:
    """Abstract chunk-store backend (local dir, HTTP chunk service).
    Implementations must make :meth:`put_bytes` atomic — a reader
    must never observe a torn chunk (the local backend rides
    ``utils.integrity.atomic_write_bytes``'s tmp → fsync → rename) —
    and must raise the typed split above from :meth:`get_bytes`:
    missing name → ``StoreUnavailableError``, provably bad bytes →
    ``StoreCorruptError``."""

    #: Where the LAST ``get_bytes`` on this thread was actually served
    #: from, for usage attribution (obs/usage.py ``source=`` label):
    #: ``local`` / ``remote`` / ``cache``.  Plain files are always
    #: local; the HTTP backend overrides this per degradation rung.
    usage_source = "local"

    def put_bytes(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, name: str) -> bytes:
        raise NotImplementedError

    def get_range(self, name: str, start: int, stop: int) -> bytes:
        """Bytes ``[start, stop)`` of ``name`` — exactly
        ``get_bytes(name)[start:stop]`` (a past-the-end ``stop``
        clamps, like a slice).  Default: fetch whole, slice local;
        transports with native ranged reads (HTTP ``Range``) override
        to move only the span."""
        if start < 0 or stop < start:
            raise ValueError(
                f"bad byte range [{start}, {stop}) for {name!r}")
        return self.get_bytes(name)[start:stop]

    def exists(self, name: str) -> bool:
        raise NotImplementedError

    def delete_bytes(self, name: str) -> None:
        """Remove ``name`` if present (idempotent).  The ingester
        deletes a pre-existing manifest BEFORE overwriting chunks, so
        a crashed re-ingest leaves a directory that is not a store
        (the fresh-ingest invariant) instead of a valid-looking
        manifest over half-replaced chunks."""
        raise NotImplementedError

    def list_names(self) -> list[str]:
        raise NotImplementedError

    def describe(self) -> str:
        """Human-readable location (error messages, manifests)."""
        return type(self).__name__


class LocalDirBackend(StoreBackend):
    """Chunks as files under one directory.

    Writes are atomic (``utils.integrity.atomic_write_bytes``:
    tmp → fsync → rename, ENOSPC-class failures mapped to typed
    :class:`~mdanalysis_mpi_tpu.utils.integrity.ArtifactWriteError`
    and counted), so a crashed ingest leaves whole chunks or no
    chunks — and since the manifest is written LAST, no manifest at
    all: the half-ingested directory is simply not a store."""

    def __init__(self, root: str):
        self.root = os.fspath(root)

    def put_bytes(self, name: str, data: bytes) -> None:
        os.makedirs(self.root, exist_ok=True)
        _integrity.atomic_write_bytes(
            os.path.join(self.root, name), data, artifact="store")

    def get_bytes(self, name: str) -> bytes:
        try:
            with open(os.path.join(self.root, name), "rb") as f:
                return f.read()
        except FileNotFoundError as exc:
            # the retryable half of the split: the name is absent, not
            # torn — on a replicated tier another source may have it
            raise _integrity.StoreUnavailableError(
                f"store object {name!r} missing under {self.root!r}",
                name=name, source=self.root) from exc

    def get_range(self, name: str, start: int, stop: int) -> bytes:
        if start < 0 or stop < start:
            raise ValueError(
                f"bad byte range [{start}, {stop}) for {name!r}")
        try:
            with open(os.path.join(self.root, name), "rb") as f:
                f.seek(start)
                return f.read(stop - start)
        except FileNotFoundError as exc:
            raise _integrity.StoreUnavailableError(
                f"store object {name!r} missing under {self.root!r}",
                name=name, source=self.root) from exc

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.root, name))

    def delete_bytes(self, name: str) -> None:
        try:
            os.remove(os.path.join(self.root, name))
        except FileNotFoundError:
            pass

    def list_names(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(os.listdir(self.root))

    def describe(self) -> str:
        return self.root
