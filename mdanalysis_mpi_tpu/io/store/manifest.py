"""Store manifest: the chunk index, CRC-sealed.

One JSON document (``manifest.json``) describing the whole store:
frame/atom counts, the chunk geometry, the quantization tier, and one
entry per chunk carrying its file name, byte size and stage-time
fingerprint list.  Sealed with the same CRC32C record framing as
journal records (``utils.integrity.record_crc``) and written LAST by
the ingester, so a crashed ingest never leaves a readable-but-partial
store — no manifest, no store.
"""

from __future__ import annotations

import json
import os

from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend
from mdanalysis_mpi_tpu.utils import integrity as _integrity

FORMAT = "mdtpu-store"
VERSION = 1
MANIFEST_NAME = "manifest.json"
#: Live-ingest tail manifest (docs/STREAMING.md): same CRC-sealed
#: document shape as ``manifest.json`` plus an ``epoch`` counter,
#: rewritten atomically beside the sealed chunks after EVERY chunk
#: seal and referencing only fully-written chunks — so a crashed live
#: ingest degrades to a valid shorter store, never a corrupt one.
#: The final seal promotes tail → closed manifest and deletes it.
TAIL_MANIFEST_NAME = "manifest.tail.json"


def dump_manifest(man: dict) -> bytes:
    man = dict(man)
    man.pop("crc", None)
    man["crc"] = _integrity.record_crc(man)
    return json.dumps(man, sort_keys=True).encode()


def parse_manifest(data: bytes, path: str = MANIFEST_NAME) -> dict:
    try:
        man = json.loads(data)
    except Exception as exc:
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} is unparseable "
                     f"({type(exc).__name__}: {exc})", path) from exc
    return validate_manifest(man, path)


def validate_manifest(man, path: str = MANIFEST_NAME) -> dict:
    """Format/version/CRC checks over an already-parsed manifest dict
    (split from :func:`parse_manifest` so callers that must sniff AND
    load — :func:`store_meta` — parse the O(chunks) JSON once)."""
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"{path!r} is not a {FORMAT} manifest", path)
    if int(man.get("version", 0)) > VERSION:
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} is version "
                     f"{man.get('version')}; this reader understands "
                     f"<= {VERSION}", path)
    if not _integrity.verify_record(man):
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} fails its CRC32C — "
                     "the chunk index cannot be trusted", path)
    return man


def load_manifest(backend) -> dict:
    path = os.path.join(backend.describe(), MANIFEST_NAME)
    try:
        data = backend.get_bytes(MANIFEST_NAME)
    except OSError as exc:
        raise FileNotFoundError(
            f"no store manifest at {path!r} ({exc}); run "
            f"`python -m mdanalysis_mpi_tpu ingest` first") from exc
    return parse_manifest(data, path)


def load_tail_manifest(backend) -> dict | None:
    """Parsed + verified tail manifest, or ``None`` when the store has
    no live tail (it is either closed or not a store at all).  A tail
    that EXISTS but fails parsing/CRC raises typed — a live feed whose
    index cannot be trusted must not be silently treated as absent."""
    try:
        data = backend.get_bytes(TAIL_MANIFEST_NAME)
    except OSError:
        return None
    path = os.path.join(backend.describe(), TAIL_MANIFEST_NAME)
    return parse_manifest(data, path)


def load_any_manifest(backend) -> tuple:
    """``(manifest, sealed)`` — the closed manifest when present
    (``sealed=True``), else the live tail manifest (``sealed=False``).
    The closed manifest wins: the final seal writes it BEFORE deleting
    the tail, so a reader racing the promotion sees the sealed store,
    never a gap."""
    try:
        return load_manifest(backend), True
    except FileNotFoundError:
        tail = load_tail_manifest(backend)
        if tail is None:
            raise
        return tail, False


def is_store(path) -> bool:
    """Cheap sniff: does ``path`` look like an ingested store?  (A
    directory carrying a ``manifest.json`` that declares the store
    format — full CRC verification happens at open.)"""
    if not isinstance(path, (str, os.PathLike)) or not os.path.isdir(path):
        return False
    mpath = os.path.join(os.fspath(path), MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            return json.load(f).get("format") == FORMAT
    except Exception:
        return False


#: (path → (stamp, manifest)) — repeat lookups (every sharded submit
#: on the fleet controller, under its lock) hit this instead of
#: re-parsing + re-CRCing an O(chunks) JSON document per submit.  The
#: stamp covers BOTH manifests' (mtime_ns, size, inode): a growing
#: store's tail-manifest rewrite must invalidate the entry even when
#: it lands within the same second at an equal byte size (chunk
#: entries are fixed-width enough for that to happen in practice) —
#: the atomic replace always allocates a fresh inode, so the inode
#: component catches what the mtime+size stamp used to miss and fleet
#: shard routing never sees a stale chunk count.  Bounded; stale
#: entries evict on mismatch.
_META_CACHE: dict = {}
_META_CACHE_MAX = 8


def _stat_stamp(path):
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size, st.st_ino)


def store_meta(path) -> dict | None:
    """Verified manifest for a store directory, or None when ``path``
    is not one — the fleet controller's lightweight accessor for
    routing per-shard chunk ranges (``chunk_frames``/``n_frames``)
    without opening a reader."""
    if not isinstance(path, (str, os.PathLike)):
        return None
    path = os.fspath(path)
    if isinstance(path, str) and \
            path.startswith(("http://", "https://")):
        # remote store URL: fetch-and-verify through the hardened
        # backend (None when the remote tier is briefly dark — the
        # controller degrades to un-chunked sharding, never fails
        # the submit on a routing lookup)
        from mdanalysis_mpi_tpu.io.store import remote

        return remote.remote_store_meta(path)
    # O(1) stats first: a cache hit must not pay the is_store sniff's
    # full O(chunks) json.load (the fleet controller calls this per
    # sharded submit, under its lock).  A growing (live-ingest) store
    # has only the tail manifest; the closed manifest wins when both
    # exist (the reader's load_any_manifest precedence).
    mpath = os.path.join(path, MANIFEST_NAME)
    tpath = os.path.join(path, TAIL_MANIFEST_NAME)
    mstamp = _stat_stamp(mpath)
    tstamp = _stat_stamp(tpath)
    if mstamp is None and tstamp is None:
        return None
    stamp = (mstamp, tstamp)
    hit = _META_CACHE.get(path)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    # one parse total: sniff (unparseable / foreign manifest.json →
    # "not a store") and verification (OUR format failing its CRC →
    # typed refusal) share the same json.loads
    src = mpath if mstamp is not None else tpath
    try:
        with open(src, "rb") as f:
            man = json.loads(f.read())
    except Exception:
        return None
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        return None
    man = validate_manifest(man, src)
    while len(_META_CACHE) >= _META_CACHE_MAX:
        _META_CACHE.pop(next(iter(_META_CACHE)))
    _META_CACHE[path] = (stamp, man)
    return man
