"""Store manifest: the chunk index, CRC-sealed.

One JSON document (``manifest.json``) describing the whole store:
frame/atom counts, the chunk geometry, the quantization tier, and one
entry per chunk carrying its file name, byte size and stage-time
fingerprint list.  Sealed with the same CRC32C record framing as
journal records (``utils.integrity.record_crc``) and written LAST by
the ingester, so a crashed ingest never leaves a readable-but-partial
store — no manifest, no store.
"""

from __future__ import annotations

import json
import os

from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend
from mdanalysis_mpi_tpu.utils import integrity as _integrity

FORMAT = "mdtpu-store"
VERSION = 1
MANIFEST_NAME = "manifest.json"


def dump_manifest(man: dict) -> bytes:
    man = dict(man)
    man.pop("crc", None)
    man["crc"] = _integrity.record_crc(man)
    return json.dumps(man, sort_keys=True).encode()


def parse_manifest(data: bytes, path: str = MANIFEST_NAME) -> dict:
    try:
        man = json.loads(data)
    except Exception as exc:
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} is unparseable "
                     f"({type(exc).__name__}: {exc})", path) from exc
    return validate_manifest(man, path)


def validate_manifest(man, path: str = MANIFEST_NAME) -> dict:
    """Format/version/CRC checks over an already-parsed manifest dict
    (split from :func:`parse_manifest` so callers that must sniff AND
    load — :func:`store_meta` — parse the O(chunks) JSON once)."""
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"{path!r} is not a {FORMAT} manifest", path)
    if int(man.get("version", 0)) > VERSION:
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} is version "
                     f"{man.get('version')}; this reader understands "
                     f"<= {VERSION}", path)
    if not _integrity.verify_record(man):
        _integrity.note_corrupt("store", path)
        raise _integrity.integrity_error(
            "store", f"store manifest {path!r} fails its CRC32C — "
                     "the chunk index cannot be trusted", path)
    return man


def load_manifest(backend) -> dict:
    path = os.path.join(backend.describe(), MANIFEST_NAME)
    try:
        data = backend.get_bytes(MANIFEST_NAME)
    except OSError as exc:
        raise FileNotFoundError(
            f"no store manifest at {path!r} ({exc}); run "
            f"`python -m mdanalysis_mpi_tpu ingest` first") from exc
    return parse_manifest(data, path)


def is_store(path) -> bool:
    """Cheap sniff: does ``path`` look like an ingested store?  (A
    directory carrying a ``manifest.json`` that declares the store
    format — full CRC verification happens at open.)"""
    if not isinstance(path, (str, os.PathLike)) or not os.path.isdir(path):
        return False
    mpath = os.path.join(os.fspath(path), MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            return json.load(f).get("format") == FORMAT
    except Exception:
        return False


#: (path → (mtime_ns, size, manifest)) — ingest-once means a store's
#: manifest changes only on re-ingest (atomic replace bumps mtime), so
#: repeat lookups (every sharded submit on the fleet controller, under
#: its lock) hit this instead of re-parsing + re-CRCing an O(chunks)
#: JSON document per submit.  Bounded; stale entries evict on mismatch.
_META_CACHE: dict = {}
_META_CACHE_MAX = 8


def store_meta(path) -> dict | None:
    """Verified manifest for a store directory, or None when ``path``
    is not one — the fleet controller's lightweight accessor for
    routing per-shard chunk ranges (``chunk_frames``/``n_frames``)
    without opening a reader."""
    if not isinstance(path, (str, os.PathLike)):
        return None
    path = os.fspath(path)
    if isinstance(path, str) and \
            path.startswith(("http://", "https://")):
        # remote store URL: fetch-and-verify through the hardened
        # backend (None when the remote tier is briefly dark — the
        # controller degrades to un-chunked sharding, never fails
        # the submit on a routing lookup)
        from mdanalysis_mpi_tpu.io.store import remote

        return remote.remote_store_meta(path)
    # O(1) stat first: a cache hit must not pay the is_store sniff's
    # full O(chunks) json.load (the fleet controller calls this per
    # sharded submit, under its lock)
    try:
        st = os.stat(os.path.join(path, MANIFEST_NAME))
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        return None
    hit = _META_CACHE.get(path)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    # one parse total: sniff (unparseable / foreign manifest.json →
    # "not a store") and verification (OUR format failing its CRC →
    # typed refusal) share the same json.loads
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            man = json.loads(f.read())
    except Exception:
        return None
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        return None
    man = validate_manifest(man, mpath)
    while len(_META_CACHE) >= _META_CACHE_MAX:
        _META_CACHE.pop(next(iter(_META_CACHE)))
    _META_CACHE[path] = (stamp, man)
    return man
