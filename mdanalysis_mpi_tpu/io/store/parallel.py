"""Parallel ensemble ingest: N trajectories → N member stores, one
content-addressed chunk pool (docs/ENSEMBLE.md "Ingest pre-stage").

The single-trajectory :func:`~mdanalysis_mpi_tpu.io.store.ingest.
ingest` already dedups content-addressed chunks *within one backend*;
an ensemble needs the dedup to span MEMBERS — a replica/restart
ensemble re-ingesting the same coordinates N times must write the
chunk bytes once.  Two pieces:

- :class:`PooledCasBackend` — a :class:`~mdanalysis_mpi_tpu.io.store.
  backend.LocalDirBackend` over one member's store directory whose
  ``cas-*`` objects are also HARDLINKED into a shared pool directory
  under the ensemble root.  ``exists`` consults the pool: a chunk any
  sibling already ingested links into this member for free (same
  inode, zero new bytes) and the ingester's dedup ledger counts it.
  Each member directory stays a complete, independently-readable
  store — ``StoreReader(member_dir)`` works with no knowledge of the
  pool — while identical chunk payloads occupy the disk once.
- :func:`ingest_many` — the fan-out driver: N ingests on a thread
  pool (``--jobs``; ingest is I/O + XDR decode, it releases the GIL
  in numpy/zlib for useful overlap), per-member idempotence (an
  existing verified member store short-circuits, like the ``ingest``
  CLI), per-member summaries plus the aggregate cross-member
  ``dedup_ratio`` the ensemble bench leg discloses.

The fleet's ensemble pre-stage runs ONE member ingest per ingest
child (fanned across hosts); this module is the within-host driver
the ``mdtpu ingest --jobs N`` CLI and those children share.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor

from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend

#: Shared chunk-pool directory name under the ensemble ``out_root``.
POOL_DIR = "cas"


def member_dir(out_root: str, index: int) -> str:
    """Canonical per-member store directory under an ensemble root:
    deterministic across re-runs, so idempotence and placement both
    key off it."""
    return os.path.join(os.fspath(out_root), f"m{index:04d}")


class PooledCasBackend(LocalDirBackend):
    """Member-store backend whose content-addressed chunks ride a
    shared hardlink pool (see module docstring).  ``content_addressed``
    is True so :func:`~mdanalysis_mpi_tpu.io.store.ingest.ingest`
    keys chunks by payload digest without being told to."""

    content_addressed = True

    def __init__(self, root: str, pool: str):
        super().__init__(root)
        self.pool = os.fspath(pool)

    def _link(self, src: str, dst: str) -> bool:
        """Hardlink ``src`` → ``dst``; False when the filesystem
        refuses links (cross-device, FAT, ...) — the caller falls back
        to plain bytes, trading the dedup for correctness.  A
        concurrent sibling winning the race (EEXIST) counts as
        success: the object is there."""
        try:
            os.link(src, dst)
            return True
        except FileExistsError:
            return True
        except OSError:
            return False

    def exists(self, name: str) -> bool:
        if super().exists(name):
            return True
        if not name.startswith("cas-"):
            return False
        pooled = os.path.join(self.pool, name)
        if not os.path.exists(pooled):
            return False
        # a sibling already ingested this payload: adopt it by link so
        # THIS member directory stays a complete store on its own —
        # the ingester sees "exists", skips the put, counts the dedup
        os.makedirs(self.root, exist_ok=True)
        if self._link(pooled, os.path.join(self.root, name)):
            return True
        # link refused (e.g. pool on another device): let the
        # ingester write real bytes instead of claiming a chunk this
        # member cannot serve
        return False

    def put_bytes(self, name: str, data: bytes) -> None:
        super().put_bytes(name, data)
        if name.startswith("cas-"):
            os.makedirs(self.pool, exist_ok=True)
            # publish into the pool for the NEXT member; losing the
            # race to a concurrent sibling is fine (same payload,
            # same digest)
            self._link(os.path.join(self.root, name),
                       os.path.join(self.pool, name))


def _count(metric: str, value: int = 1, **labels) -> None:
    from mdanalysis_mpi_tpu.obs import METRICS

    METRICS.inc(metric, value, **labels)


def _ingest_member(index: int, trajectory, out_root: str,
                   chunk_frames, quant, stop, force: bool) -> dict:
    from mdanalysis_mpi_tpu.io.store import store_meta
    from mdanalysis_mpi_tpu.io.store.ingest import ingest

    dest = member_dir(out_root, index)
    summary: dict = {"member": index,
                     "trajectory": os.fspath(trajectory)
                     if not hasattr(trajectory, "read_block")
                     else getattr(trajectory, "filename", "<reader>"),
                     "store": dest}
    try:
        existing = None if force else store_meta(dest)
    except Exception:
        existing = None        # a torn half-store re-ingests cleanly
    if existing is not None:
        # idempotent per member, like the single-trajectory CLI: an
        # existing verified store IS the answer
        summary.update(already_ingested=True,
                       n_frames=existing["n_frames"],
                       n_chunks=len(existing["chunks"]),
                       quant=existing["quant"],
                       chunk_frames=existing["chunk_frames"])
        return summary
    backend = PooledCasBackend(dest,
                               os.path.join(os.fspath(out_root),
                                            POOL_DIR))
    summary.update(ingest(trajectory, backend=backend,
                          chunk_frames=chunk_frames, quant=quant,
                          stop=stop))
    summary["store"] = dest    # describe() returns the dir already,
    #                            but keep the key stable either way
    return summary


def ingest_many(trajectories, out_root: str, jobs: int | None = None,
                chunk_frames: int | None = None, quant="int16",
                stop: int | None = None, force: bool = False) -> dict:
    """Ingest N trajectories into member stores under ``out_root`` on
    a thread pool, content-addressed through the shared chunk pool.

    Returns the aggregate summary: ``members`` (per-trajectory
    summaries, member order), ``dedup_ratio`` — deduped bytes over
    total chunk bytes ACROSS members (a replica pair's second copy
    dedups to ~1.0 against the first), ``members_already`` (members
    short-circuited by idempotence — their bytes are unknown and
    excluded from the ratio, disclosed rather than guessed), and
    ``ok`` (False when any member failed; failures carry ``error`` in
    their member summary instead of killing the siblings).
    """
    trajectories = list(trajectories)
    if not trajectories:
        raise ValueError("ingest_many needs at least one trajectory")
    n_jobs = max(1, int(jobs) if jobs else
                 min(len(trajectories), os.cpu_count() or 4))
    t0 = time.perf_counter()
    members: list[dict] = [{} for _ in trajectories]

    def run(i: int) -> None:
        try:
            members[i] = _ingest_member(i, trajectories[i], out_root,
                                        chunk_frames, quant, stop,
                                        force)
            _count("mdtpu_ensemble_ingest_members_total")
        except Exception as exc:
            members[i] = {"member": i,
                          "trajectory": os.fspath(trajectories[i])
                          if not hasattr(trajectories[i], "read_block")
                          else "<reader>",
                          "error": f"{type(exc).__name__}: {exc}"}
            _count("mdtpu_ensemble_ingest_failures_total")

    with ThreadPoolExecutor(max_workers=n_jobs) as pool:
        list(pool.map(run, range(len(trajectories))))
    wall = time.perf_counter() - t0
    total_bytes = sum(m.get("bytes", 0) for m in members)
    dedup_bytes = sum(m.get("dedup_bytes", 0) for m in members)
    dedup_chunks = sum(m.get("dedup_chunks", 0) for m in members)
    already = sum(1 for m in members if m.get("already_ingested"))
    failed = [m for m in members if "error" in m]
    out = {
        "out_root": os.fspath(out_root), "jobs": n_jobs,
        "n_members": len(members), "members": members,
        "members_already": already, "members_failed": len(failed),
        "bytes": total_bytes, "dedup_chunks": dedup_chunks,
        "dedup_bytes": dedup_bytes,
        "dedup_ratio": (round(dedup_bytes / total_bytes, 4)
                        if total_bytes else 0.0),
        "wall_s": round(wall, 4),
        "ok": not failed,
    }
    from mdanalysis_mpi_tpu.obs import METRICS

    METRICS.set_gauge("mdtpu_ensemble_dedup_ratio",
                      out["dedup_ratio"])
    return out
