"""Ingest-once: re-chunk a trajectory into the block store.

One sequential decode pass — the LAST one the trajectory ever needs.
Frames are read through the source reader's bulk ``read_block`` (the
fused native decode when the format has one), re-chunked to the
staging geometry (``chunk_frames`` = the frame block ``_run_batches``
stages; default 512, the flagship batch), quantized once with the
executors' wire policy, framed with per-array fingerprints
(``codec.encode_chunk``), and written through the backend's atomic
puts.  The CRC-sealed manifest lands LAST, so a crash mid-ingest
leaves a directory that simply is not a store yet.

Quantization policy (int16/int8): ONE store-wide scale, derived from
the first chunk's max |coordinate| with the readers' drift margin
(``ReaderBase.QUANT_MARGIN``), so chunk-spanning reads can serve raw
quantized slices under a single ``inv_scale`` — the condition for the
:class:`~mdanalysis_mpi_tpu.io.store.reader.StoreReader` staging fast
path.  A chunk whose range outgrows the margin falls back to its own
exact scale (recorded per chunk; readers detect the mismatch and
requantize through f32 instead of serving mixed-scale bytes).
"""

from __future__ import annotations

import os
import time

import numpy as np

from mdanalysis_mpi_tpu.io.base import (
    QUANT_INT_MAX, QUANT_TARGETS, ReaderBase, norm_quantize,
)
from mdanalysis_mpi_tpu.io.store import codec
from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend
from mdanalysis_mpi_tpu.io.store.manifest import (
    FORMAT, MANIFEST_NAME, VERSION, dump_manifest,
)

#: Default chunk geometry: the flagship staging batch (bench.py
#: BENCH_BATCH default) — chunk = the block ``_run_batches`` stages,
#: so a store-backed run's every stage call is one chunk slice.
DEFAULT_CHUNK_FRAMES = 512

def _count(metric: str, value: int = 1) -> None:
    # lazy obs import, the utils/integrity.py convention
    from mdanalysis_mpi_tpu.obs import METRICS

    METRICS.inc(metric, value)


def norm_store_quant(quant) -> str:
    """Store quantization tier: ``"int16"`` / ``"int8"`` /
    ``"f32"`` (accepting ``None``/``False``/``"float32"`` spellings
    for the passthrough tier)."""
    if quant in (None, False, "f32", "float32"):
        return "f32"
    return norm_quantize(quant)


def ingest(trajectory, out: str | None = None,
           chunk_frames: int | None = None, quant="int16",
           backend=None, stop: int | None = None,
           content_addressed: bool | None = None) -> dict:
    """Ingest ``trajectory`` (a path or an open ReaderBase) into a
    block store at ``out`` (or through an explicit ``backend``).

    ``stop`` bounds the ingested window to frames ``[0, stop)`` —
    the bench's cold-leg protocol ingests a measurement window, not
    the whole fixture.  Returns a summary dict (frame/chunk/byte
    counts, ``store_ingest_fps``; in content-addressed mode also the
    dedup ledger: ``dedup_chunks`` / ``dedup_bytes`` /
    ``dedup_ratio``).

    ``content_addressed``: key chunks by payload digest
    (``cas-<sha256>.mdtc``) instead of position, so re-ingesting
    identical payloads — a second tenant, a re-run — writes ZERO new
    chunk bytes (the put is skipped when the object already exists;
    the manifest still maps logical chunk → digest).  ``None`` (the
    default) follows the backend's own ``content_addressed`` flag:
    on for :class:`~mdanalysis_mpi_tpu.io.store.remote.
    HttpStoreBackend`, off for local directories (where positional
    names keep stores human-diffable).
    """
    owned = None
    if hasattr(trajectory, "read_block"):
        reader = trajectory
    else:
        from mdanalysis_mpi_tpu.io import trajectory_files

        # opened here, closed here (finally below) — a long-lived
        # process re-ingesting many trajectories must not leak source
        # handles; caller-owned readers stay open
        reader = owned = trajectory_files.open(os.fspath(trajectory))
    try:
        if backend is None:
            if out is None:
                raise ValueError(
                    "ingest needs an output path or a backend")
            backend = LocalDirBackend(out)
        if content_addressed is None:
            content_addressed = bool(
                getattr(backend, "content_addressed", False))
        return _ingest(reader, backend, chunk_frames, quant, stop,
                       content_addressed)
    finally:
        if owned is not None:
            owned.close()


def _seal_chunk(backend, ci, lo, block, boxes, times, qmode, scale,
                content_addressed: bool):
    """Quantize, frame and put ONE chunk — the seal step shared by the
    closed-file ingest loop and the live appender
    (:class:`~mdanalysis_mpi_tpu.io.store.append.LiveIngest`).
    Returns ``(entry, scale, overflowed, dedup_bytes)``: the manifest
    entry, the (possibly just-seeded) store-wide scale, whether this
    chunk fell back to its own exact scale, and the bytes NOT written
    thanks to content-addressed dedup (0 otherwise)."""
    hi = lo + len(block)
    arrays: dict = {}
    meta = {"start": lo, "stop": hi, "quant": qmode}
    overflow = False
    if qmode == "f32":
        arrays["coords"] = np.asarray(block, dtype=np.float32)
    else:
        target = QUANT_TARGETS[qmode]
        m = float(np.abs(block).max()) if block.size else 1.0
        if scale is None:
            scale = target / (max(m, 1e-30) * ReaderBase.QUANT_MARGIN)
        s = scale
        if m * s > QUANT_INT_MAX[qmode]:
            # range outgrew the store-wide margin: exact per-chunk
            # scale (readers fall back to f32 requant across it)
            s = target / max(m, 1e-30)
            overflow = True
        arrays["coords"] = np.round(block * s).astype(qmode)
        meta["inv_scale"] = float(1.0 / s)
    if boxes is not None:
        arrays["boxes"] = np.ascontiguousarray(boxes, np.float32)
    if times is not None:
        arrays["times"] = np.ascontiguousarray(times, np.float32)
    blob, fps = codec.encode_chunk(arrays, meta)
    dedup = 0
    digest = None
    if content_addressed:
        # content addressing: the name IS the payload digest, so an
        # identical chunk from ANY prior ingest (another tenant's copy
        # of the same trajectory included) is already there — skip the
        # put, count the bytes not moved
        digest = codec.payload_digest(blob)
        name = codec.cas_chunk_name(digest)
        if backend.exists(name):
            dedup = len(blob)
            _count("mdtpu_store_chunks_deduped_total")
            _count("mdtpu_store_dedup_bytes_total", len(blob))
        else:
            backend.put_bytes(name, blob)
    else:
        name = codec.chunk_name(ci)
        backend.put_bytes(name, blob)
    entry = {"i": ci, "start": lo, "stop": hi, "file": name,
             "nbytes": len(blob), "arrays": list(arrays), "fps": fps}
    if digest is not None:
        entry["digest"] = digest
    if "inv_scale" in meta:
        entry["inv_scale"] = meta["inv_scale"]
    _count("mdtpu_store_chunks_ingested_total")
    return entry, scale, overflow, dedup


def build_manifest(reader_meta: dict, entries: list,
                   overflow_chunks: int) -> dict:
    """The manifest document for ``entries`` — shared by the one-shot
    ingest (written once, last) and the live appender (rewritten as
    the tail manifest after every chunk seal)."""
    n_frames = entries[-1]["stop"] if entries else 0
    return {
        "format": FORMAT, "version": VERSION,
        "n_frames": int(n_frames),
        "n_atoms": int(reader_meta["n_atoms"]),
        "chunk_frames": int(reader_meta["chunk_frames"]),
        "quant": reader_meta["quant"],
        "has_boxes": any("boxes" in e["arrays"] for e in entries),
        "has_times": any("times" in e["arrays"] for e in entries),
        "source": reader_meta.get("source"),
        # chunks that fell back to their own exact scale: every
        # stage request spanning one requantizes through f32 instead
        # of serving raw slices — disclosed, never silent (the "no
        # silent caps" rule), because it quietly costs the store its
        # headline fast path
        "scale_overflow_chunks": overflow_chunks,
        "chunks": entries,
    }


def _ingest(reader, backend, chunk_frames, quant, stop,
            content_addressed: bool = False) -> dict:
    qmode = norm_store_quant(quant)
    cf = int(chunk_frames or DEFAULT_CHUNK_FRAMES)
    if cf < 1:
        raise ValueError(f"chunk_frames must be >= 1, got {cf}")
    n_frames = reader.n_frames
    if stop is not None:
        n_frames = min(n_frames, int(stop))
    # re-ingest over an existing store: kill the old manifest FIRST,
    # so a crash mid-overwrite leaves "not a store" (the fresh-ingest
    # invariant) — never a valid-looking manifest whose fingerprints
    # reject every half-replaced chunk
    backend.delete_bytes(MANIFEST_NAME)
    t0 = time.perf_counter()
    entries = []
    total_bytes = 0
    scale = None          # store-wide scale, seeded by chunk 0
    overflow_chunks = 0
    dedup_chunks = 0
    dedup_bytes = 0
    for ci, lo in enumerate(range(0, n_frames, cf)):
        hi = min(lo + cf, n_frames)
        block, boxes = reader.read_block(lo, hi)
        times = reader.frame_times(range(lo, hi))
        entry, scale, overflow, dedup = _seal_chunk(
            backend, ci, lo, block, boxes, times, qmode, scale,
            content_addressed)
        if overflow:
            overflow_chunks += 1
        if dedup:
            dedup_chunks += 1
            dedup_bytes += dedup
        entries.append(entry)
        total_bytes += entry["nbytes"]
    man = build_manifest(
        {"n_atoms": reader.n_atoms, "chunk_frames": cf,
         "quant": qmode, "source": getattr(reader, "filename", None)},
        entries, overflow_chunks)
    man["n_frames"] = int(n_frames)
    backend.put_bytes(MANIFEST_NAME, dump_manifest(man))
    # a re-ingest with fewer/larger chunks must not strand the old
    # geometry's files as unreferenced disk forever
    kept = {e["file"] for e in entries}
    for name in backend.list_names():
        if name.startswith("chunk-") and name not in kept:
            backend.delete_bytes(name)
    wall = time.perf_counter() - t0
    summary = {
        "store": backend.describe(), "quant": qmode,
        "n_frames": int(n_frames), "n_chunks": len(entries),
        "chunk_frames": cf, "bytes": total_bytes,
        "scale_overflow_chunks": overflow_chunks,
        "wall_s": round(wall, 4),
        "store_ingest_fps": (round(n_frames / wall, 2) if wall > 0
                             else None),
    }
    if content_addressed:
        summary["content_addressed"] = True
        summary["dedup_chunks"] = dedup_chunks
        summary["dedup_bytes"] = dedup_bytes
        summary["dedup_ratio"] = (round(dedup_bytes / total_bytes, 4)
                                  if total_bytes else 0.0)
    return summary
