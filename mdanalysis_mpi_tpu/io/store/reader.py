"""StoreReader: random-access trajectory reads from the block store.

A :class:`~mdanalysis_mpi_tpu.io.base.ReaderBase` over an ingested
store, so every consumer of the reader boundary — serial iteration,
``read_block``, ``stage_block``/``stage_cached``, executors, prefetch,
``HostStageCache``, fleet shard children — works unchanged.  What
changes is the cost:

- **staging fast path**: when the requested wire format equals the
  store's quantization tier and the covered chunks share one scale
  (the ingester's store-wide-scale invariant), ``stage_block`` serves
  the raw quantized chunk slices directly — selection gather on int16,
  no XDR decode, no float32 materialization, no re-quantize.  A
  chunk-aligned request (chunk_frames == the executor batch, the
  ingest default) is a pure slice.
- **exact-slice fetches**: only the chunks covering ``[start, stop)``
  are ever read — a ``shard_windows`` child on a fleet host fetches
  its shard's chunks and nothing else.
- **verified reads**: every chunk fetch re-computes the per-array
  fingerprints and compares them against the chunk's CRC-framed
  header AND the manifest's stage-time record (``codec.decode_chunk``)
  — the SDC-scrub comparison moved to read time.  Rejects count
  ``mdtpu_store_chunk_crc_rejects_total`` labeled by taxonomy half
  (``reason="corrupt"`` → typed :class:`~mdanalysis_mpi_tpu.utils.
  integrity.StoreCorruptError`, fatal bytes; ``reason="unavailable"``
  → :class:`~mdanalysis_mpi_tpu.utils.integrity.
  StoreUnavailableError`, a retryable OSError the policy layer may
  heal from another source).
"""

from __future__ import annotations

import os
import threading

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io.base import ReaderBase, norm_quantize
from mdanalysis_mpi_tpu.io.store import codec
from mdanalysis_mpi_tpu.io.store.backend import LocalDirBackend
from mdanalysis_mpi_tpu.io.store.manifest import (
    load_any_manifest, load_manifest,
)
from mdanalysis_mpi_tpu.utils import integrity as _integrity

#: Decoded-chunk LRU depths: raw (quantized) chunks serve the staging
#: fast path, f32 chunks serve per-frame/oracle reads.  Small by
#: design — the DeviceBlockCache / HostStageCache above this layer are
#: where staged blocks actually live; these only keep sequential
#: access from re-verifying the same chunk per frame.
_RAW_CACHE_CHUNKS = 4
_F32_CACHE_CHUNKS = 2


def _count(metric: str, **labels) -> None:
    from mdanalysis_mpi_tpu.obs import METRICS

    METRICS.inc(metric, **labels)


class StoreEndOfFeed(Exception):
    """The follow-mode reader is caught up AND the feed has sealed —
    there will never be more frames.  Typed apart from
    :class:`~mdanalysis_mpi_tpu.utils.integrity.StoreCorruptError`
    (bad bytes) and a plain timeout (feed alive but stalled): end of
    feed is the streaming driver's CLEAN exit signal, not a fault."""


class StoreReader(ReaderBase):
    """Random-access reader over an ingested chunk store.

    ``follow=True`` opens a GROWING store (docs/STREAMING.md): the
    live tail manifest is accepted when no closed manifest exists yet,
    and :meth:`refresh` re-polls it, extending ``n_frames``
    monotonically as the writer seals chunks.  Everything below
    ``n_frames`` is immutable (sealed chunks never change), so the
    decoded-chunk caches, the staging fast path and every consumer of
    the ``stage_block``/``stage_cached`` boundary — executors,
    prefetch, scan-fold dispatch, the quantized/planar paths — work on
    a growing store untouched."""

    def __init__(self, path: str | None = None, n_atoms: int | None = None,
                 backend=None, follow: bool = False):
        if backend is None:
            if path is None:
                raise ValueError("StoreReader needs a path or a backend")
            backend = LocalDirBackend(os.fspath(path))
        self._backend = backend
        self._path = os.fspath(path) if path is not None \
            else backend.describe()
        self._follow = bool(follow)
        if follow:
            man, sealed = load_any_manifest(backend)
        else:
            man, sealed = load_manifest(backend), True
        self._sealed = sealed
        self._epoch = int(man.get("epoch", 0))
        self._man = man
        self._nf = int(man["n_frames"])
        self._na = int(man["n_atoms"])
        self._cf = int(man["chunk_frames"])
        self._quant = None if man["quant"] == "f32" else man["quant"]
        self._entries = man["chunks"]
        if n_atoms is not None and n_atoms != self._na:
            raise ValueError(
                f"store {self._path!r} has {self._na} atoms, "
                f"expected {n_atoms}")
        self._lock = threading.RLock()
        self._raw: dict = {}      # ci -> (arrays, meta), LRU-bounded
        self._f32: dict = {}      # ci -> f32 coords, LRU-bounded

    # ---- ReaderBase surface ----

    @property
    def n_frames(self) -> int:
        return self._nf

    @property
    def n_atoms(self) -> int:
        return self._na

    @property
    def quant(self) -> str:
        """The store's quantization tier ("int16"/"int8"/"f32")."""
        return self._man["quant"]

    @property
    def chunk_frames(self) -> int:
        return self._cf

    def reopen(self) -> "StoreReader":
        return StoreReader(self._path, backend=self._backend,
                           follow=self._follow)

    # ---- follow mode ----

    @property
    def follow(self) -> bool:
        return self._follow

    @property
    def sealed(self) -> bool:
        """True once the closed manifest exists — ``n_frames`` is
        final.  Non-follow readers are sealed by construction."""
        return self._sealed

    @property
    def epoch(self) -> int:
        """Tail-manifest epoch last observed (0 for a closed store)."""
        return self._epoch

    def refresh(self) -> bool:
        """Re-poll the manifests; returns True when new frames
        appeared.  ``n_frames`` only ever GROWS — a tail that shrank
        or rewound its epoch means the writer restarted underneath
        this reader's immutable-prefix assumption, which is corruption
        from where the reader stands, raised typed."""
        if not self._follow or self._sealed:
            return False
        man, sealed = load_any_manifest(self._backend)
        nf = int(man["n_frames"])
        epoch = int(man.get("epoch", 0))
        if nf < self._nf or (not sealed and epoch < self._epoch):
            _integrity.note_corrupt("store", self._path)
            raise _integrity.integrity_error(
                "store",
                f"store {self._path!r} shrank under a follow reader "
                f"({self._nf} → {nf} frames, epoch {self._epoch} → "
                f"{epoch}): the writer restarted; sealed chunks are "
                "no longer trustworthy", self._path)
        if self._nf == 0:
            # nothing served yet: adopt geometry — a live store's
            # empty epoch-1 tail may not know n_atoms until the
            # writer's first chunk seals
            self._na = int(man["n_atoms"])
            self._cf = int(man["chunk_frames"])
            self._quant = (None if man["quant"] == "f32"
                           else man["quant"])
        elif (int(man["n_atoms"]) != self._na
                or int(man["chunk_frames"]) != self._cf
                or man["quant"] != self._man["quant"]):
            _integrity.note_corrupt("store", self._path)
            raise _integrity.integrity_error(
                "store",
                f"store {self._path!r} changed geometry under a "
                "follow reader (atoms/chunk_frames/quant)", self._path)
        grew = nf > self._nf
        self._man = man
        self._entries = man["chunks"]
        self._nf = nf
        self._epoch = epoch if not sealed else self._epoch
        self._sealed = sealed
        return grew

    def wait_frames(self, n: int, timeout_s: float = 5.0,
                    poll_interval_s: float = 0.02,
                    clock=None, sleep=None) -> int:
        """Block until the store serves >= ``n`` frames; returns the
        new ``n_frames``.  Raises :class:`StoreEndOfFeed` when the
        feed seals with fewer (the feed is over — nothing to wait
        for), and :class:`TimeoutError` when the feed is still open
        but stopped growing for ``timeout_s`` (a stall the caller's
        park/resume policy owns)."""
        import time as _time

        clock = clock or _time.monotonic
        sleep = sleep or _time.sleep
        deadline = clock() + timeout_s
        while True:
            self.refresh()
            if self._nf >= n:
                return self._nf
            if self._sealed:
                raise StoreEndOfFeed(
                    f"store {self._path!r} sealed at {self._nf} "
                    f"frames; {n} will never arrive")
            if clock() >= deadline:
                raise TimeoutError(
                    f"store {self._path!r} stuck at {self._nf} frames "
                    f"for {timeout_s}s waiting for {n}")
            sleep(poll_interval_s)

    # ---- chunk access ----

    def _chunk_path(self, ci: int) -> str:
        return os.path.join(self._backend.describe(),
                            self._entries[ci]["file"])

    def _load_raw(self, ci: int):
        """Fetch + verify chunk ``ci`` → (arrays, meta).  The read-time
        scrub boundary: fingerprint mismatches are counted, marked on
        the span timeline, and raised typed.

        The lock guards ONLY the cache lookup/insert — the disk fetch
        and the full-payload CRC verification run outside it, so a
        prefetch thread and a worker staging through one shared reader
        keep their overlap (the file-reader stage path this replaces
        takes no lock at all).  Two threads racing the same cold chunk
        may both verify it; the first insert wins (double-checked) and
        the loser adopts it."""
        with self._lock:
            hit = self._raw.get(ci)
            if hit is not None:
                # refresh recency (true LRU): a cyclic working set of
                # cache-size+1 chunks must not evict exactly the
                # next-needed chunk every access
                self._raw.pop(ci)
                self._raw[ci] = hit
                return hit
            entry = self._entries[ci]
        try:
            blob = self._backend.get_bytes(entry["file"])
            arrays, meta = codec.decode_chunk(
                blob, path=self._chunk_path(ci),
                expect_fps=entry.get("fps"))
        except (_integrity.IntegrityError, OSError) as exc:
            from mdanalysis_mpi_tpu.obs import span_event

            # the taxonomy split drives the reject label AND the
            # retry contract: "unavailable" (missing replica, remote
            # outage) stays an OSError the policy layer retries /
            # re-sources; "corrupt" (provably bad bytes) is fatal and
            # never re-fetched from the same source as transient
            reason = ("unavailable"
                      if isinstance(exc,
                                    _integrity.StoreUnavailableError)
                      else "corrupt")
            _count("mdtpu_store_chunk_crc_rejects_total",
                   reason=reason)
            span_event("store_chunk_reject", chunk=ci,
                       path=self._chunk_path(ci), reason=reason)
            if isinstance(exc, _integrity.StoreUnavailableError):
                raise
            if isinstance(exc, _integrity.IntegrityError):
                raise
            # a chunk the manifest promises but the backend produced
            # unreadably (torn file, permission) is the truncation
            # case taken to its limit — same typed taxonomy, so upper
            # layers route it as corruption, not as a random OSError
            _integrity.note_corrupt("store", self._chunk_path(ci))
            raise _integrity.integrity_error(
                "store",
                f"store chunk {self._chunk_path(ci)!r} is in the "
                f"manifest but unreadable ({type(exc).__name__}: "
                f"{exc})", self._chunk_path(ci)) from exc
        _count("mdtpu_store_chunks_read_total")
        # usage charge site: one decoded chunk, attributed to the
        # backend rung that actually served it (local / remote / cache)
        from mdanalysis_mpi_tpu.obs import usage as _usage

        _usage.charge_current_store(
            source=getattr(self._backend, "usage_source", "local"),
            chunks=1, nbytes=len(blob))
        with self._lock:
            hit = self._raw.get(ci)
            if hit is not None:
                return hit                 # lost the race: adopt
            self._raw[ci] = (arrays, meta)
            while len(self._raw) > _RAW_CACHE_CHUNKS:
                self._raw.pop(next(iter(self._raw)))
            return arrays, meta

    def _chunk_f32(self, ci: int):
        """(arrays, dequantized f32 coords) for chunk ``ci`` — the
        per-frame / float32-read tier (one multiply per element; the
        staging fast path never comes here)."""
        arrays, meta = self._load_raw(ci)
        with self._lock:
            x = self._f32.get(ci)
            if x is not None:
                self._f32.pop(ci)          # refresh recency (LRU)
                self._f32[ci] = x
            if x is None:
                c = arrays["coords"]
                if self._quant is None:
                    x = c
                else:
                    x = c.astype(np.float32) * np.float32(
                        meta["inv_scale"])
                self._f32[ci] = x
                while len(self._f32) > _F32_CACHE_CHUNKS:
                    self._f32.pop(next(iter(self._f32)))
            return arrays, x

    # ---- reads ----

    def _read_frame(self, i: int) -> Timestep:
        ci, k = divmod(i, self._cf)
        arrays, x = self._chunk_f32(ci)
        dims = None
        if "boxes" in arrays:
            dims = np.array(arrays["boxes"][k])
            if not dims[:3].any():
                dims = None
        t = (float(arrays["times"][k]) if "times" in arrays
             else float(i))
        return Timestep(x[k].copy(), frame=i, time=t, dimensions=dims)

    def frame_times(self, frames) -> np.ndarray | None:
        if not self._man.get("has_times"):
            return None
        idx = np.asarray(list(frames), dtype=np.int64)
        out = np.empty(len(idx), dtype=np.float64)
        for ci in np.unique(idx // self._cf):
            m = (idx // self._cf) == ci
            arrays, _meta = self._load_raw(int(ci))
            out[m] = arrays["times"][idx[m] - int(ci) * self._cf]
        return out

    def read_block(self, start: int, stop: int,
                   sel: np.ndarray | None = None, step: int = 1):
        if self.transformations:
            # transformed reads go through the generic
            # read-transform-gather loop (ReaderBase), like XTCReader
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        if not 0 <= start <= stop <= self._nf:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self._nf}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        idx = np.arange(start, stop, step)
        n = self._na if sel is None else len(sel)
        out = np.empty((len(idx), n, 3), dtype=np.float32)
        boxes = None
        pos = np.arange(len(idx))
        for ci in np.unique(idx // self._cf):
            m = (idx // self._cf) == ci
            local = idx[m] - int(ci) * self._cf
            arrays, x = self._chunk_f32(int(ci))
            blk = x[local]
            out[pos[m]] = blk if sel is None else blk[:, sel]
            if "boxes" in arrays:
                if boxes is None:
                    # zeros, not empty: frames before the first boxed
                    # chunk must not leak uninitialized memory
                    boxes = np.zeros((len(idx), 6), dtype=np.float32)
                boxes[pos[m]] = arrays["boxes"][local]
        return out, boxes

    # ---- staging ----

    def stage_block(self, start: int, stop: int,
                    sel: np.ndarray | None = None, quantize=False,
                    layout: str = "interleaved"):
        """Staging primitive with the decode REMOVED: a request in the
        store's own wire format is served as raw quantized slices (see
        module docs).  A ``layout='planar'`` request is honored inside
        the fast path by transposing each raw chunk slice to component
        planes — still zero float32 materialization (``_f32`` /
        ``_chunk_f32`` untouched), the quantized bytes just land in the
        ``(3, B, S)`` shape the fused Pallas kernel reads.  Everything
        else — f32 requests, cross-tier requests, mixed-scale chunk
        spans, transformed readers — rides the generic ``ReaderBase``
        path over :meth:`read_block` (which still never touches the
        original file)."""
        qmode = norm_quantize(quantize)
        if (qmode is not None and qmode == self._quant
                and not self.transformations and start < stop):
            fast = self._stage_direct(start, stop, sel, layout=layout)
            if fast is not None:
                return fast
        return ReaderBase.stage_block(self, start, stop, sel=sel,
                                      quantize=quantize, layout=layout)

    def _stage_direct(self, start: int, stop: int, sel,
                      layout: str = "interleaved"):
        """(q, boxes, inv_scale) from raw chunk slices, or None when
        the covered chunks do not share one scale (an ingest-margin
        overflow chunk — the caller requantizes through f32)."""
        if not 0 <= start <= stop <= self._nf:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self._nf}]")
        planar = layout == "planar"
        cis = range(start // self._cf, (stop - 1) // self._cf + 1)
        loaded = [(ci, *self._load_raw(ci)) for ci in cis]
        inv_scales = {m["inv_scale"] for _, _, m in loaded}
        if len(inv_scales) != 1:
            return None
        parts = []
        box_parts = []
        have_boxes = False
        for ci, arrays, _meta in loaded:
            lo = max(start, ci * self._cf) - ci * self._cf
            hi = min(stop, (ci + 1) * self._cf) - ci * self._cf
            c = arrays["coords"][lo:hi]
            c = c if sel is None else c[:, sel]
            if planar:
                from mdanalysis_mpi_tpu.io.base import planar_repack

                c = planar_repack(c)
            parts.append(c)
            if "boxes" in arrays:
                have_boxes = True
                box_parts.append(arrays["boxes"][lo:hi])
            else:
                box_parts.append(
                    np.zeros((hi - lo, 6), dtype=np.float32))
        q = (parts[0] if len(parts) == 1
             else np.concatenate(parts, axis=1 if planar else 0))
        boxes = (None if not have_boxes
                 else box_parts[0] if len(box_parts) == 1
                 else np.concatenate(box_parts))
        return q, boxes, np.float32(inv_scales.pop())
