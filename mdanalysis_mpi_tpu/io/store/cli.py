"""``python -m mdanalysis_mpi_tpu ingest`` — the store's CLI surface.

Ingest-once: re-chunk a trajectory into the block store
(docs/STORE.md), then point every later run at the store directory
instead of the file (``batch --store=DIR``, or simply pass the
directory where a trajectory path is expected — the format registry
recognizes ingested stores).  Jax-free by construction (dispatched
before any platform re-pin in ``__main__``): ingest is a host decode
pass, and a fleet re-ingesting on a fresh host must not pay a jax
import for it.

``--smoke`` runs the self-contained ingest→read verification gate
(``scripts/verify.sh`` stage): write a tiny synthetic XTC, ingest it,
prove read parity against the file reader, and prove a corrupt chunk
is rejected typed — then repeat the gate through the HTTP fixture
backend (in-process ``ChunkServer``): content-addressed ingest, a
two-tenant dedup proof (second ingest moves zero chunk bytes), remote
read parity, and a corrupt-wire-body typed rejection.  One JSON line,
exit 0 on success.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mdanalysis_mpi_tpu ingest",
        description="re-chunk a trajectory into the native block "
                    "store: ingest-once, random-access, quantized "
                    "(docs/STORE.md)")
    p.add_argument("trajectory", nargs="*", default=[],
                   help="trajectory file(s) to ingest (any registered "
                        "format: XTC/DCD/TRR/...); several files + "
                        "--out-root run the parallel ensemble ingest "
                        "(docs/ENSEMBLE.md)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="store directory (created if missing) — "
                        "single-trajectory mode")
    p.add_argument("--out-root", default=None, metavar="DIR",
                   help="ensemble root: member stores land at "
                        "DIR/m0000, DIR/m0001, ... with cas-* chunk "
                        "bytes deduplicated across members through "
                        "the shared DIR/cas hardlink pool")
    p.add_argument("--jobs", type=int, default=None, metavar="N",
                   help="parallel member ingests (default: one per "
                        "trajectory, capped at the CPU count)")
    p.add_argument("--chunk-frames", type=int, default=None,
                   help="frames per chunk (default 512 — the flagship "
                        "staging batch; match your executor batch_size "
                        "so every stage call is one chunk slice)")
    p.add_argument("--quant", default="int16",
                   choices=("int16", "int8", "f32"),
                   help="coordinate tier: int16 (wire default, "
                        "~0.004 Å at 120 Å range), int8 (coarse), "
                        "f32 (lossless passthrough)")
    p.add_argument("--stop", type=int, default=None,
                   help="ingest only frames [0, STOP)")
    p.add_argument("--force", action="store_true",
                   help="re-ingest even if DIR already holds a store")
    p.add_argument("--smoke", action="store_true",
                   help="self-contained ingest→read→corrupt-reject "
                        "verification gate (no arguments needed)")
    return p


def ingest_main(argv=None) -> int:
    ns = _parser().parse_args(argv)
    if ns.smoke:
        return _smoke()
    if ns.out_root is not None or len(ns.trajectory) > 1:
        # parallel ensemble ingest: N members fanned on a thread
        # pool, content-addressed dedup across members, aggregate
        # dedup_ratio in the summary (docs/ENSEMBLE.md)
        if not ns.trajectory or not ns.out_root:
            print(json.dumps({
                "error": "ensemble ingest needs >= 1 trajectory and "
                         "--out-root DIR (use --out for a single "
                         "store)"}))
            return 2
        from mdanalysis_mpi_tpu.io.store.parallel import ingest_many

        summary = ingest_many(ns.trajectory, ns.out_root,
                              jobs=ns.jobs,
                              chunk_frames=ns.chunk_frames,
                              quant=ns.quant, stop=ns.stop,
                              force=ns.force)
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    if not ns.trajectory or not ns.out:
        print(json.dumps({"error": "ingest needs a trajectory and "
                                   "--out DIR (or --smoke)"}))
        return 2
    from mdanalysis_mpi_tpu.io.store import ingest, store_meta

    existing = store_meta(ns.out)
    if existing is not None and not ns.force:
        # ingest-once honored literally: an existing verified store is
        # the answer, not an error (--force re-ingests)
        print(json.dumps({
            "store": ns.out, "already_ingested": True,
            "n_frames": existing["n_frames"],
            "n_chunks": len(existing["chunks"]),
            "quant": existing["quant"],
            "chunk_frames": existing["chunk_frames"]}))
        return 0
    summary = ingest(ns.trajectory[0], ns.out,
                     chunk_frames=ns.chunk_frames, quant=ns.quant,
                     stop=ns.stop)
    print(json.dumps(summary))
    return 0


def _smoke() -> int:
    """Ingest→read smoke: parity vs the file reader + typed
    corrupt-chunk rejection, in a temp dir, ~a second, jax-free."""
    import os
    import tempfile

    import numpy as np

    from mdanalysis_mpi_tpu.io.store import StoreReader, ingest
    from mdanalysis_mpi_tpu.io.xtc import XTCReader, write_xtc
    from mdanalysis_mpi_tpu.utils.integrity import IntegrityError

    out: dict = {"smoke": "ingest-read"}
    with tempfile.TemporaryDirectory() as td:
        rng = np.random.default_rng(7)
        frames = rng.normal(scale=12.0,
                            size=(24, 300, 3)).astype(np.float32)
        xtc = os.path.join(td, "smoke.xtc")
        write_xtc(xtc, frames,
                  dimensions=np.array([40.0, 40, 40, 90, 90, 90]),
                  times=np.arange(24, dtype=np.float32))
        store = os.path.join(td, "smoke.store")
        summary = ingest(xtc, store, chunk_frames=8, quant="int16")
        out.update(n_chunks=summary["n_chunks"],
                   store_ingest_fps=summary["store_ingest_fps"])

        src = XTCReader(xtc)
        sr = StoreReader(store)
        ref, _ = src.read_block(0, 24)
        got, _ = sr.read_block(0, 24)
        # one int16 round trip at this range: ~1e-3 Å resolution
        err = float(np.abs(got - ref).max())
        out["read_parity_max_err"] = round(err, 6)
        if err > 5e-3:
            out["error"] = f"store read diverged from file read: {err}"
            print(json.dumps(out))
            return 1
        # the staging fast path serves raw int16 under one scale
        q, _boxes, inv = sr.stage_block(8, 16, quantize="int16")
        if q.dtype != np.int16:
            out["error"] = f"fast path returned {q.dtype}, not int16"
            print(json.dumps(out))
            return 1
        # corrupt one payload byte -> typed rejection, not wrong data
        chunk_path = os.path.join(store, "chunk-00000001.mdtc")
        blob = bytearray(open(chunk_path, "rb").read())
        blob[-10] ^= 0x40
        with open(chunk_path, "wb") as f:
            f.write(bytes(blob))
        fresh = StoreReader(store)
        try:
            fresh.read_block(8, 16)
        except IntegrityError as exc:
            out["corrupt_chunk_rejected"] = type(exc).__name__
        else:
            out["error"] = "corrupt chunk was served instead of rejected"
            print(json.dumps(out))
            return 1

        # ---- remote leg: the same gate through the HTTP fixture ----
        # (in-process ChunkServer, still jax-free: ingest→read-parity→
        # corrupt-reject over the hardened network boundary, plus the
        # two-tenant content-addressing dedup proof — docs/STORE.md
        # "Remote backend")
        from mdanalysis_mpi_tpu.io.store import (
            ChunkCache, ChunkServer, HttpStoreBackend, ServerFault,
        )

        with ChunkServer(os.path.join(td, "chunkd")) as srv:
            be1 = HttpStoreBackend(srv.url, store="tenant-a",
                                   cache=ChunkCache(), timeout_s=5.0)
            rsum = ingest(xtc, backend=be1, chunk_frames=8,
                          quant="int16")
            out["remote_n_chunks"] = rsum["n_chunks"]
            # tenant B ingests the SAME trajectory: every chunk must
            # dedup against tenant A's CAS objects — zero new bytes
            wrote_before = srv.cas_bytes_written
            be2 = HttpStoreBackend(srv.url, store="tenant-b",
                                   cache=ChunkCache(), timeout_s=5.0)
            rsum2 = ingest(xtc, backend=be2, chunk_frames=8,
                           quant="int16")
            out["remote_dedup_ratio"] = rsum2["dedup_ratio"]
            if rsum2["dedup_ratio"] != 1.0 \
                    or srv.cas_bytes_written != wrote_before:
                out["error"] = ("second-tenant ingest moved chunk "
                                "bytes instead of deduplicating")
                print(json.dumps(out))
                return 1
            rr = StoreReader(srv.url + "/stores/tenant-a",
                             backend=be1)
            rgot, _ = rr.read_block(0, 24)
            rerr = float(np.abs(rgot - ref).max())
            out["remote_parity_max_err"] = round(rerr, 6)
            if rerr > 5e-3:
                out["error"] = (f"remote read diverged from file "
                                f"read: {rerr}")
                print(json.dumps(out))
                return 1
            # a corrupt remote body (flipped payload byte on the
            # wire) must be rejected typed by the content address —
            # and must NOT poison the cache with bad bytes
            from mdanalysis_mpi_tpu.io.store.manifest import (
                load_manifest,
            )

            chunk0 = load_manifest(be1)["chunks"][0]["file"]
            srv.inject(ServerFault("corrupt", match=chunk0,
                                   times=None))
            be3 = HttpStoreBackend(srv.url, store="tenant-a",
                                   cache=ChunkCache(), timeout_s=5.0,
                                   retries=0)
            try:
                be3.get_bytes(chunk0)
            except IntegrityError as exc:
                out["remote_corrupt_rejected"] = type(exc).__name__
            else:
                out["error"] = ("corrupt remote body was served "
                                "instead of rejected")
                print(json.dumps(out))
                return 1
    out["ok"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(ingest_main())
