"""AutoDock PDBQT parser + writer (upstream ``PDBQTParser`` /
``PDBQTWriter``).

PDB fixed columns with two extra fields per ATOM/HETATM record:
partial charge (columns 67–76, f10.4 in practice) and the AutoDock
atom type (columns 78–79).  Charges land on ``Topology.charges``; the
AutoDock type maps to the element (``OA``→O, ``NA``→N, ``HD``→H,
``A``→C aromatic, ...), falling back to name-based guessing for
unknown types.  Docking outputs carry multiple MODELs (poses) — they
become frames of an in-memory trajectory like multi-MODEL PDB.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core import tables
from mdanalysis_mpi_tpu.core.topology import Topology
from mdanalysis_mpi_tpu.io import topology_files

#: AutoDock type → element (the table AutoDock4/Vina use)
AUTODOCK_ELEMENTS = {
    "A": "C", "C": "C", "N": "N", "NA": "N", "NS": "N", "OA": "O",
    "OS": "O", "O": "O", "H": "H", "HD": "H", "HS": "H", "S": "S",
    "SA": "S", "P": "P", "F": "F", "CL": "CL", "BR": "BR", "I": "I",
    "MG": "MG", "MN": "MN", "ZN": "ZN", "CA": "CA", "FE": "FE",
}


def parse_pdbqt(path: str) -> Topology:
    names, resnames, segids, resids = [], [], [], []
    charges, elements = [], []
    frames: list[list[list[float]]] = []
    current: list[list[float]] = []
    first_model_done = False
    with open(path) as fh:
        for ln in fh:
            rec = ln[:6]
            if rec in ("ATOM  ", "HETATM"):
                current.append([float(ln[30:38]), float(ln[38:46]),
                                float(ln[46:54])])
                if not first_model_done:
                    names.append(ln[12:16].strip())
                    resnames.append(ln[17:21].strip())
                    resids.append(int(ln[22:26]))
                    chain = ln[21].strip()
                    segids.append(chain or "SYSTEM")
                    charges.append(float(ln[66:76]))
                    ad = ln[77:79].strip().upper()
                    elements.append(AUTODOCK_ELEMENTS.get(ad, ""))
            elif rec.startswith("ENDMDL"):
                if current:
                    frames.append(current)
                    current = []
                    first_model_done = True
    if current:
        frames.append(current)
    if not frames:
        raise ValueError(f"PDBQT file {path!r} contains no ATOM records")
    n = len(frames[0])
    if any(len(f) != n for f in frames):
        raise ValueError(
            f"PDBQT file {path!r}: models differ in atom count")
    top = Topology(
        names=np.array(names), resnames=np.array(resnames),
        resids=np.array(resids), segids=np.array(segids),
        charges=np.array(charges),
        # per-atom fallback: unknown AutoDock types get a name-based
        # guess instead of discarding every authoritative assignment
        elements=(np.array([e or tables.guess_element(nm, rn)
                            for e, nm, rn in zip(elements, names,
                                                 resnames)])
                  if any(elements) else None))
    top._coordinates = np.asarray(frames, np.float32)
    top._dimensions = None
    return top


_ELEMENT_TO_AD = {"C": "C", "N": "N", "O": "OA", "H": "HD", "S": "SA",
                  "P": "P", "F": "F", "CL": "Cl", "BR": "Br", "I": "I"}


def write_pdbqt(path: str, universe_or_group) -> None:
    """Write the current frame as PDBQT (charges required)."""
    ag = getattr(universe_or_group, "atoms", universe_or_group)
    top = ag._universe.topology
    if top.charges is None:
        raise ValueError(
            "PDBQT output needs charges on the topology "
            "(add_TopologyAttr('charges'))")
    idx = ag.indices
    pos = ag.positions
    with open(path, "w") as fh:
        fh.write("REMARK  Written by mdanalysis_mpi_tpu\n")
        for serial, i in enumerate(idx, 1):
            el = str(top.elements[i]).upper()
            ad = _ELEMENT_TO_AD.get(el, el[:2] or "A")
            seg = str(top.segids[i])
            chain = seg[0] if len(seg) == 1 else " "
            # columns per the PDB/PDBQT standard: name [12:16],
            # altLoc [16], resName [17:21], chain [21], resSeq [22:26]
            fh.write(
                f"ATOM  {serial:5d} {top.names[i]:<4s} "
                f"{top.resnames[i]:<4s}{chain}{int(top.resids[i]):4d}    "
                f"{pos[serial - 1][0]:8.3f}{pos[serial - 1][1]:8.3f}"
                f"{pos[serial - 1][2]:8.3f}  1.00  0.00    "
                f"{top.charges[i]:6.3f} {ad:<2s}\n")
        fh.write("END\n")


topology_files.register("pdbqt", parse_pdbqt)
