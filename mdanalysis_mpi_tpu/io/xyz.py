"""XYZ trajectory format (upstream ``coordinates.XYZ``).

The plain-text format: per frame, an atom-count line, a comment line,
then ``element x y z`` rows (Å).  Self-delimiting frames make chunk
concatenation valid (the TrajectoryWriter append property XTC/TRR
share).  Random access uses a one-pass byte-offset index built at open
— the text format has no seek table of its own.

No box or velocities (the format carries none); times default to the
frame index.  Comment lines are preserved on write round trips only as
the frame index (upstream writes a generated comment too).
"""

from __future__ import annotations

import os

import numpy as np

from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import _offsets
from mdanalysis_mpi_tpu.io import trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase


def _scan(path: str):
    """One pass → (frame byte offsets, n_atoms), through the shared
    mtime-validated offset cache (the text format has no seek table,
    and a multi-GB XYZ must not re-scan per open/reopen — the same
    discipline as the XTC/TRR indexes)."""
    cached = _offsets.load(path)
    if cached is not None:
        return cached
    mtime = os.path.getmtime(path)     # BEFORE the scan (_offsets.save)
    offsets = []
    n_atoms = None
    with open(path, "rb") as f:
        pos = 0
        while True:
            line = f.readline()
            if not line:
                break
            stripped = line.strip()
            if not stripped:
                pos = f.tell()
                continue
            try:
                count = int(stripped)
            except ValueError:
                raise ValueError(
                    f"{path!r}: expected an atom-count line at byte "
                    f"{pos}, got {stripped[:40]!r}")
            if n_atoms is None:
                n_atoms = count
            elif count != n_atoms:
                raise ValueError(
                    f"{path!r}: frame {len(offsets)} has {count} atoms, "
                    f"previous frames {n_atoms} (variable-count XYZ is "
                    "not a trajectory)")
            offsets.append(pos)
            f.readline()                      # comment
            for _ in range(count):
                if not f.readline():
                    raise ValueError(
                        f"{path!r}: truncated frame {len(offsets) - 1}")
            pos = f.tell()
    if n_atoms is None:
        raise ValueError(f"{path!r}: empty XYZ file")
    offsets = np.asarray(offsets, np.int64)
    _offsets.save(path, offsets, n_atoms, mtime)
    return offsets, n_atoms


class XYZReader(ReaderBase):
    """Random-access XYZ reader (Å; names from the element column are
    NOT used — the topology owns identity, upstream semantics)."""

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        self._offsets, self._natoms = _scan(path)
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"XYZ {path!r} has {self._natoms} atoms, expected "
                f"{n_atoms}")
        self._file = open(path, "rb")

    @property
    def n_frames(self) -> int:
        return len(self._offsets)

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "XYZReader":
        return XYZReader(self._path)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _read_frame(self, i: int) -> Timestep:
        if not 0 <= i < len(self._offsets):
            raise IndexError(
                f"frame {i} out of range [0, {len(self._offsets)})")
        self._file.seek(self._offsets[i])
        self._file.readline()                 # count
        self._file.readline()                 # comment
        pos = np.empty((self._natoms, 3), np.float32)
        for a in range(self._natoms):
            parts = self._file.readline().split()
            if len(parts) < 4:
                raise ValueError(
                    f"{self._path!r}: malformed atom line in frame {i}")
            pos[a] = [float(parts[1]), float(parts[2]), float(parts[3])]
        return Timestep(pos, frame=i, time=float(i))


def write_xyz(path: str, frames: np.ndarray, names=None,
              mode: str = "w", start: int = 0) -> None:
    """Write (F, N, 3) Å coordinates as XYZ text; ``names`` (N,)
    element column (default 'X').  ``mode='a'`` appends frames (the
    chunk-concatenation property the streaming writer uses); ``start``
    offsets the comment-line frame numbering so appended chunks keep a
    monotone index."""
    frames = np.asarray(frames, np.float32)
    if frames.ndim != 3 or frames.shape[2] != 3:
        raise ValueError(f"frames must be (F, N, 3), got {frames.shape}")
    n = frames.shape[1]
    if names is None:
        names = ["X"] * n
    elif len(names) != n:
        raise ValueError(f"{len(names)} names for {n} atoms")
    with open(path, mode) as out:
        for f, frame in enumerate(frames, start=start):
            out.write(f"{n}\n")
            out.write(f"frame {f}\n")
            for nm, (x, y, z) in zip(names, frame):
                out.write(f"{nm} {x:.6f} {y:.6f} {z:.6f}\n")


trajectory_files.register("xyz", XYZReader)
