"""XTC trajectory reader/writer over the native codec.

The reference's trajectory layer is MDAnalysis' Cython/C XTC stack
(random access via a frame-offset index, RMSF.py:56,92,124 — SURVEY.md
§2.2); here the decode core is C++ (io/native/trajio.cpp) and this
module adds the offset index with on-disk caching, the ``ReaderBase``
interface, bulk ``read_block`` staging, and Å↔nm unit conversion
(XTC stores nm; the framework's coordinate unit is Å).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from mdanalysis_mpi_tpu.core.box import box_to_vectors, vectors_to_box
from mdanalysis_mpi_tpu.core.timestep import Timestep
from mdanalysis_mpi_tpu.io import native, trajectory_files
from mdanalysis_mpi_tpu.io.base import ReaderBase, sel_fingerprint

_NM_TO_A = 10.0


from mdanalysis_mpi_tpu.io import _offsets

_offset_cache_path = _offsets.cache_path    # shared scheme with TRR


def _scan(path: str):
    """Frame offsets + natoms, with the shared mtime-validated on-disk
    cache (upstream builds and caches the same index — SURVEY.md §2.2)."""
    cached = _offsets.load(path)
    if cached is not None:
        return cached
    mtime = os.path.getmtime(path)     # BEFORE the scan (_offsets.save)
    lib = native.load()
    natoms = ctypes.c_int(-1)
    n = lib.xtc_scan(path.encode(), ctypes.byref(natoms), None, 0)
    if n < 0:
        raise IOError(f"cannot scan XTC file {path!r} (error {n})")
    offsets = np.zeros(n, dtype=np.int64)
    n2 = lib.xtc_scan(path.encode(), ctypes.byref(natoms),
                      offsets.ctypes.data_as(ctypes.c_void_p), n)
    if n2 != n:
        raise IOError(f"inconsistent XTC scan of {path!r}")
    _offsets.save(path, offsets, natoms.value, mtime)
    return offsets, natoms.value


class XTCReader(ReaderBase):
    """Random-access XTC reader (coordinates in Å, box as dimensions)."""

    def __init__(self, path: str, n_atoms: int | None = None):
        self._path = path
        self._offsets, self._natoms = _scan(path)
        if n_atoms is not None and n_atoms != self._natoms:
            raise ValueError(
                f"XTC {path!r} has {self._natoms} atoms, expected {n_atoms}")
        self._lib = native.load()

    @property
    def n_frames(self) -> int:
        return len(self._offsets)

    @property
    def n_atoms(self) -> int:
        return self._natoms

    def reopen(self) -> "XTCReader":
        return XTCReader(self._path)

    def _read_range(self, idx: np.ndarray):
        n = len(idx)
        coords = np.empty((n, self._natoms, 3), dtype=np.float32)
        box = np.empty((n, 9), dtype=np.float32)
        times = np.empty(n, dtype=np.float32)
        steps = np.empty(n, dtype=np.int32)
        rc = self._lib.xtc_read_frames(
            self._path.encode(), self._offsets[idx], n, self._natoms,
            coords, box.ctypes.data_as(ctypes.c_void_p),
            times.ctypes.data_as(ctypes.c_void_p),
            steps.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise IOError(f"XTC decode failed for {self._path!r} (error {rc})")
        coords *= _NM_TO_A
        return coords, box, times, steps

    def _read_frame(self, i: int) -> Timestep:
        coords, box, times, steps = self._read_range(np.array([i]))
        dims = vectors_to_box(box[0].reshape(3, 3) * _NM_TO_A)
        if not dims[:3].any():
            dims = None
        return Timestep(coords[0], frame=i, time=float(times[0]),
                        dimensions=dims)

    def frame_times(self, frames) -> np.ndarray:
        # XTC frame layout: magic, natoms, step, time (XDR big-endian) —
        # time sits 12 bytes into each frame, readable without decoding
        idx = np.asarray(list(frames), dtype=np.int64)
        times = np.empty(len(idx), dtype=np.float64)
        with open(self._path, "rb") as f:
            for j, i in enumerate(idx):
                f.seek(int(self._offsets[i]) + 12)
                times[j] = np.frombuffer(f.read(4), ">f4")[0]
        return times

    def _dims_from_raw(self, box: np.ndarray):
        """(F, 9) nm box vectors → (F, 6) Å dimensions, or None when the
        whole block is boxless (all-zero), matching read_block's
        contract."""
        boxes = np.stack([
            vectors_to_box(b.reshape(3, 3) * _NM_TO_A) for b in box])
        if not boxes[:, :3].any():
            return None
        return boxes

    def read_block(self, start: int, stop: int, sel=None, step: int = 1):
        if self.transformations:
            # transformed reads must go through the generic
            # read-transform-gather loop (ReaderBase)
            return ReaderBase.read_block(self, start, stop, sel=sel,
                                         step=step)
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        if start == stop:
            n = self._natoms if sel is None else len(sel)
            return np.empty((0, n, 3), np.float32), None
        idx = np.arange(start, stop, step)
        if sel is not None:
            # fused decode→gather→Å: the full-system float32 block is
            # never materialized (cold-path staging; see trajio.cpp
            # xtc_stage_f32)
            coords, box = native.xtc_stage_f32(
                self._path, self._offsets[idx], self._natoms, sel)
            return coords, self._dims_from_raw(box)
        coords, box, _, _ = self._read_range(idx)
        return coords, self._dims_from_raw(box)

    def stage_block(self, start: int, stop: int, sel=None,
                    quantize: bool = False, layout: str = "interleaved"):
        """Staging primitive with the decode fused in (overrides the
        read-then-quantize base path): on the int16 leg each frame goes
        XDR bits → scratch → selection int16 in one native call, cutting
        the cold path's DRAM traffic by the full-system float32 block
        (~3.6 MB/frame at the flagship config).  Scale-hint mechanics
        mirror ``ReaderBase._quantize_staged`` (adaptive one-pass with
        exact re-run on overflow, hints scoped per selection content).
        Planar requests stage through this same fused decode, then one
        ``planar_repack`` on the quantized bytes.
        """
        if not 0 <= start <= stop <= self.n_frames:
            raise IndexError(
                f"block [{start},{stop}) out of range [0,{self.n_frames}]")
        from mdanalysis_mpi_tpu.io.base import norm_quantize, planar_repack

        if layout == "planar":
            if norm_quantize(quantize) is None:
                raise ValueError(
                    "layout='planar' requires quantized staging "
                    "(int16/int8); float32 blocks stay interleaved")
            q, boxes, inv_scale = self.stage_block(start, stop, sel=sel,
                                                   quantize=quantize)
            return planar_repack(q), boxes, inv_scale

        qmode = norm_quantize(quantize)
        if self.transformations or qmode == "int8":
            # int8 has no fused native kernel (opt-in coarse path);
            # the base read-then-quantize handles it
            return ReaderBase.stage_block(self, start, stop, sel=sel,
                                          quantize=quantize)
        if qmode is None:
            block, boxes = self.read_block(start, stop, sel=sel)
            return block, boxes, None
        if start == stop:
            block, boxes = self.read_block(start, stop, sel=sel)
            from mdanalysis_mpi_tpu.parallel.executors import quantize_block

            q, inv_scale = quantize_block(block)
            return q, boxes, inv_scale
        hints = self._quant_hints()
        key = sel_fingerprint(sel)
        hint = hints.get(key, 0.0)
        offs = self._offsets[np.arange(start, stop)]
        if hint > 0.0:
            # float64 scale arithmetic, shared policy constants — must
            # stay bit-identical to ReaderBase._quantize_staged
            scale = self.QUANT_TARGET / (hint * self.QUANT_MARGIN)
            q, box, vmax, overflowed = native.xtc_stage_i16(
                self._path, offs, self._natoms, sel, scale)
            if vmax > hint:
                hints[key] = vmax
            if not overflowed:
                return q, self._dims_from_raw(box), np.float32(1.0 / scale)
            scale = self.QUANT_TARGET / max(vmax, 1e-30)
            q, box, vmax, _ = native.xtc_stage_i16(
                self._path, offs, self._natoms, sel, scale)
            return q, self._dims_from_raw(box), np.float32(1.0 / scale)
        # first block for this selection: fused f32 decode, exact-scale
        # quantize (bit-identical to the NumPy reference), seed the hint
        block, boxes = self.read_block(start, stop, sel=sel)
        q, inv_scale = self._quantize_staged(block, None, sel_fp=key)
        return q, boxes, inv_scale


def write_xtc(path: str, coordinates: np.ndarray,
              dimensions: np.ndarray | None = None,
              times: np.ndarray | None = None,
              steps: np.ndarray | None = None,
              precision: float = 1000.0) -> None:
    """Write (n_frames, n_atoms, 3) Å coordinates as a compressed XTC.

    ``precision`` is in the XTC convention (positions quantized to
    1/precision nm; 1000 ≈ 0.001 nm = 0.01 Å resolution).  This is the
    fixture *writer* SURVEY.md §4 requires (no MDAnalysisTests data
    offline).
    """
    coords = np.ascontiguousarray(
        np.asarray(coordinates, dtype=np.float32) / _NM_TO_A)
    if coords.ndim != 3 or coords.shape[2] != 3:
        raise ValueError(f"coordinates must be (F, N, 3), got {coords.shape}")
    nframes, natoms = coords.shape[:2]
    boxp = None
    if dimensions is not None:
        dimensions = np.asarray(dimensions)
        if dimensions.ndim == 1:
            dimensions = np.broadcast_to(dimensions, (nframes, 6))
        box = np.stack([
            box_to_vectors(d) / _NM_TO_A for d in dimensions]
        ).astype(np.float32).reshape(nframes, 9)
        box = np.ascontiguousarray(box)
        boxp = box.ctypes.data_as(ctypes.c_void_p)
    timesp = stepsp = None
    if times is not None:
        times = np.ascontiguousarray(times, dtype=np.float32)
        timesp = times.ctypes.data_as(ctypes.c_void_p)
    if steps is not None:
        steps = np.ascontiguousarray(steps, dtype=np.int32)
        stepsp = steps.ctypes.data_as(ctypes.c_void_p)
    rc = native.load().xtc_write(path.encode(), natoms, nframes, coords,
                                 boxp, timesp, stepsp,
                                 ctypes.c_float(precision))
    if rc != 0:
        raise IOError(f"XTC write failed for {path!r} (error {rc})")


trajectory_files.register("xtc", XTCReader)
