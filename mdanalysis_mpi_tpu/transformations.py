"""On-the-fly trajectory transformations.

Upstream-API mirror (``MDAnalysis.transformations``): callables applied
to every Timestep as it is read —
``u.trajectory.add_transformations(translate([1,0,0]), wrap(ag))`` —
so every consumer (per-frame iteration, ``read_block`` staging, every
analysis backend) sees the transformed coordinates.  The reference
program transforms frames *imperatively* inside its loop (the in-place
translate/rotate/translate at RMSF.py:99-101); this module is that
pattern made composable and declarative.

Execution model: transformations are host-side, frame-wise NumPy — they
run where the decode runs, before selection gather and device staging
(a transformation may need atoms outside the staged selection, e.g.
centering on the protein while staging water).  Readers with
transformations attached automatically fall back from their fused
decode→gather fast paths to the generic read-transform-gather loop;
attach none and the fast paths are untouched.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.ops import host


class TransformationBase:
    """A callable ``ts -> ts`` that edits ``ts.positions`` in place
    (upstream convention).

    ``stateful = True`` marks transformations whose output depends on
    the SEQUENCE of frames they saw (PositionAverager): those are
    sequential-cursor-only — block staging refuses them (block/cache
    schedules would silently change their numbers), ``Universe.copy()``
    refuses to share them across cursors, and attaching one resets its
    state (``reset()``)."""

    stateful = False

    def __call__(self, ts):
        raise NotImplementedError

    def reset(self) -> None:            # stateful subclasses override
        pass


def _require_box(ts, who: str):
    """Strict per-frame box validation (core.box.valid_box_matrix —
    the one shared validator): a partially degenerate box must raise
    here, not write NaN positions downstream.  Returns ``(dims, m)``
    — the validator already built the (3, 3) cell matrix, so per-frame
    callers must not rebuild it."""
    from mdanalysis_mpi_tpu.core.box import valid_box_matrix

    m = valid_box_matrix(ts.dimensions, f"{who} (frame {ts.frame})")
    return ts.dimensions.astype(np.float64), m


def _group_center(ag, positions: np.ndarray, center: str) -> np.ndarray:
    sub = positions[ag.indices].astype(np.float64)
    if center == "mass":
        w = ag.masses
        return (w[:, None] * sub).sum(axis=0) / w.sum()
    if center == "geometry":
        return sub.mean(axis=0)
    raise ValueError(f"center must be 'geometry' or 'mass', got {center!r}")


class translate(TransformationBase):
    """Shift every atom by a constant vector."""

    def __init__(self, vector):
        self._v = np.asarray(vector, dtype=np.float32)
        if self._v.shape != (3,):
            raise ValueError(f"vector must be (3,), got {self._v.shape}")

    def __call__(self, ts):
        ts.positions += self._v
        return ts


class rotateby(TransformationBase):
    """Rotate every atom by ``angle`` degrees about the axis along
    ``direction`` through ``point`` — or through the center of ``ag``,
    recomputed per frame (upstream ``transformations.rotate.rotateby``).
    Exactly one of ``point``/``ag`` must be given."""

    def __init__(self, angle, direction, point=None, ag=None,
                 center: str = "geometry"):
        if (point is None) == (ag is None):
            raise ValueError("rotateby needs exactly one of point= or ag=")
        from mdanalysis_mpi_tpu.lib.transformations import rotation_matrix

        # one Rodrigues implementation for the whole package
        self._rot = rotation_matrix(np.radians(float(angle)),
                                    direction)[:3, :3]
        self._point = None if point is None else np.asarray(point,
                                                            np.float64)
        self._ag = ag
        self._center = center
        if center not in ("geometry", "mass"):      # fail at build time
            raise ValueError(
                f"center must be 'geometry' or 'mass', got {center!r}")
        if ag is None and center != "geometry":
            raise ValueError("center= applies only with ag=")

    def __call__(self, ts):
        p = (self._point if self._point is not None
             else _group_center(self._ag, ts.positions, self._center))
        x = ts.positions.astype(np.float64) - p
        ts.positions = (x @ self._rot.T + p).astype(np.float32)
        return ts


class center_in_box(TransformationBase):
    """Translate each frame so ``ag``'s center sits at the box center
    (or at ``point``).  ``wrap=True`` wraps the group into the primary
    cell before computing its center (upstream semantics)."""

    def __init__(self, ag, center: str = "geometry", point=None,
                 wrap: bool = False):
        if center not in ("geometry", "mass"):
            raise ValueError(
                f"center must be 'geometry' or 'mass', got {center!r}")
        self._ag = ag
        self._center = center
        self._point = (None if point is None
                       else np.asarray(point, np.float64))
        self._wrap = wrap

    def __call__(self, ts):
        from mdanalysis_mpi_tpu.core.box import wrap_positions

        dim, m = _require_box(ts, "center_in_box")
        pos = ts.positions
        if self._wrap:
            # wrap affects only the CENTER COMPUTATION (upstream
            # inplace=False semantics) — atom positions themselves keep
            # their image, so molecules never break across the boundary
            sub = wrap_positions(pos[self._ag.indices], m)
            if self._center == "mass":
                w = self._ag.masses
                center = (w[:, None] * sub).sum(axis=0) / w.sum()
            else:
                center = sub.mean(axis=0)
        else:
            center = _group_center(self._ag, pos, self._center)
        target = (self._point if self._point is not None
                  else m.sum(axis=0) / 2.0)
        shift = target - center
        ts.positions = (pos.astype(np.float64) + shift).astype(np.float32)
        return ts


class fit_translation(TransformationBase):
    """Translate each frame so ``ag``'s center matches the reference
    group's center (captured from the reference universe's CURRENT
    frame at construction).  ``plane`` ('xy' | 'yz' | 'xz') restricts
    the shift to that plane."""

    _PLANES = {"xy": (0, 1), "yz": (1, 2), "xz": (0, 2)}

    def __init__(self, ag, reference, plane: str | None = None,
                 weights: str | None = None):
        if plane is not None and plane not in self._PLANES:
            raise ValueError(
                f"plane must be one of {sorted(self._PLANES)}, got {plane!r}")
        if weights not in (None, "mass"):
            raise ValueError(f"weights must be None or 'mass', got {weights!r}")
        self._ag = ag
        self._center = "mass" if weights == "mass" else "geometry"
        ref_u = reference.universe
        self._ref_center = _group_center(
            reference, ref_u.trajectory.ts.positions, self._center)
        self._dims = (self._PLANES[plane] if plane is not None
                      else (0, 1, 2))

    def __call__(self, ts):
        shift = np.zeros(3)
        full = self._ref_center - _group_center(
            self._ag, ts.positions, self._center)
        for d in self._dims:
            shift[d] = full[d]
        ts.positions = (ts.positions.astype(np.float64) + shift
                        ).astype(np.float32)
        return ts


class fit_rot_trans(TransformationBase):
    """Least-squares-superpose each frame onto a reference: rotation fit
    on ``ag``, transform applied to ALL atoms — the reference program's
    per-frame body (RMSF.py:94-101) as a reusable transformation.
    Reference coordinates are captured from the reference universe's
    CURRENT frame at construction."""

    def __init__(self, ag, reference, weights: str | None = None):
        if weights not in (None, "mass"):
            raise ValueError(f"weights must be None or 'mass', got {weights!r}")
        if reference.n_atoms != ag.n_atoms:
            raise ValueError(
                f"fit group has {ag.n_atoms} atoms, reference "
                f"{reference.n_atoms}")
        self._ag = ag
        w = ag.masses if weights == "mass" else np.ones(ag.n_atoms)
        self._w = w
        ref_u = reference.universe
        ref = ref_u.trajectory.ts.positions[reference.indices].astype(
            np.float64)
        com = host.weighted_center(ref, w)
        self._ref_c = ref - com
        self._ref_com = com

    def __call__(self, ts):
        ts.positions = host.superpose_frame(
            ts.positions, self._ag.indices, self._w,
            self._ref_c, self._ref_com).astype(np.float32)
        return ts


class unwrap(TransformationBase):
    """Make molecules whole across periodic boundaries every frame
    (upstream ``transformations.unwrap``): walk each molecule's bond
    spanning tree and place every atom at the minimum-image position
    relative to its tree parent.  Requires bonds in the topology
    (load a PSF or call ``ag.guess_bonds()`` first).

    The walk is vectorized by BFS DEPTH: the tree is computed once at
    construction and stored as per-level (parents, children) index
    arrays, so a frame costs one minimum-image pass per tree level —
    not per atom — regardless of molecule count."""

    def __init__(self, ag):
        t = ag.universe.topology
        if t.bonds is None or len(t.bonds) == 0:
            raise ValueError(
                "unwrap needs bonds in the topology (load a PSF or call "
                "ag.guess_bonds() first)")
        members = set(int(i) for i in ag.indices)
        adj: dict[int, list[int]] = {}
        for x, y in t.bonds:
            x, y = int(x), int(y)
            if x in members and y in members:
                adj.setdefault(x, []).append(y)
                adj.setdefault(y, []).append(x)
        # BFS forest over the group, one root per connected component
        seen = set()
        levels: list[tuple[list[int], list[int]]] = []
        for root in sorted(members):
            if root in seen or root not in adj:
                seen.add(root)
                continue
            seen.add(root)
            frontier = [root]
            depth = 0
            while frontier:
                nxt: list[int] = []
                if depth >= len(levels):
                    levels.append(([], []))
                parents, children = levels[depth]
                for p in frontier:
                    for c in adj.get(p, ()):
                        if c not in seen:
                            seen.add(c)
                            parents.append(p)
                            children.append(c)
                            nxt.append(c)
                frontier = nxt
                depth += 1
        self._levels = [(np.asarray(p, np.int64), np.asarray(c, np.int64))
                        for p, c in levels if p]

    def __call__(self, ts):
        from mdanalysis_mpi_tpu.ops.host import minimum_image

        dim, _m = _require_box(ts, "unwrap")
        pos = ts.positions.astype(np.float64)
        for parents, children in self._levels:
            d = minimum_image(pos[children] - pos[parents], dim)
            pos[children] = pos[parents] + d
        ts.positions = pos.astype(np.float32)
        return ts


class wrap(TransformationBase):
    """Wrap ``ag``'s atoms into the primary unit cell every frame
    (upstream ``transformations.wrap``; per-atom, like
    ``AtomGroup.wrap``)."""

    def __init__(self, ag):
        self._ag = ag

    def __call__(self, ts):
        from mdanalysis_mpi_tpu.core.box import wrap_positions

        dim, m = _require_box(ts, "wrap")
        idx = self._ag.indices
        ts.positions[idx] = wrap_positions(
            ts.positions[idx], m).astype(np.float32)
        return ts


class PositionAverager(TransformationBase):
    """Sliding-window position averaging (upstream
    ``transformations.positionaveraging.PositionAverager``): each
    emitted frame's positions become the mean of the last
    ``avg_frames`` frames read (fewer at the start of iteration) — a
    smoothing filter for noisy trajectories.

    Stateful BY DESIGN (upstream too): it assumes SEQUENTIAL frame
    reads on ONE cursor.  With ``check_reset=True`` (default) a
    non-consecutive frame jump clears the window, so random access
    degrades to plain positions instead of averaging unrelated frames;
    ``check_reset=False`` reproduces upstream's trust-the-caller mode.
    Block staging (batch backends / transfer_to_memory) and
    ``Universe.copy()`` refuse stateful transformations loudly — their
    schedules/cursor sharing would silently change the averages.
    ``current_avg`` reports how many frames the current window held.
    """

    stateful = True

    def __init__(self, avg_frames: int, check_reset: bool = True):
        if avg_frames < 1:
            raise ValueError(
                f"avg_frames must be >= 1, got {avg_frames}")
        from collections import deque

        self._avg_frames = int(avg_frames)
        self._check_reset = bool(check_reset)
        self._buf = deque(maxlen=self._avg_frames)
        self._sum: np.ndarray | None = None    # float64 running sum
        self._last_frame: int | None = None
        self.current_avg = 0

    def reset(self) -> None:
        self._buf.clear()
        self._sum = None
        self._last_frame = None
        self.current_avg = 0

    def __call__(self, ts):
        if (self._check_reset and self._last_frame is not None
                and ts.frame != self._last_frame + 1):
            self.reset()
        x = ts.positions.astype(np.float64)
        if self._sum is None:
            self._sum = np.zeros_like(x)
        if len(self._buf) == self._buf.maxlen:
            self._sum -= self._buf[0]          # deque evicts it below
        self._buf.append(x)
        self._sum += x
        self.current_avg = len(self._buf)
        ts.positions = (self._sum / self.current_avg).astype(np.float32)
        self._last_frame = ts.frame
        return ts


class set_dimensions(TransformationBase):
    """Assign fixed box dimensions to every frame (upstream
    ``transformations.boxdimensions.set_dimensions``) — the standard
    fix for trajectories whose format carries no box (XYZ, some DCDs)
    before PBC-dependent analyses (RDF, minimum-image distances)."""

    def __init__(self, dimensions):
        dims = np.asarray(dimensions, dtype=np.float32).reshape(-1)
        if dims.shape != (6,):
            raise ValueError(
                "set_dimensions needs [lx, ly, lz, alpha, beta, gamma], "
                f"got shape {np.asarray(dimensions).shape}")
        # the ONE shared validator (core.box): an unphysical box —
        # including geometrically impossible angle combinations with no
        # volume — must fail here, at build time, not mid-analysis
        from mdanalysis_mpi_tpu.core.box import valid_box_matrix

        valid_box_matrix(dims, "set_dimensions")
        self._dims = dims

    def __call__(self, ts):
        ts.dimensions = self._dims.copy()
        return ts


class NoJump(TransformationBase):
    """Remove box jumps frame-over-frame (upstream
    ``transformations.nojump.NoJump``): each atom's displacement since
    the previous read frame is minimum-imaged, so a particle drifting
    out of the box keeps its continuous (unwrapped-in-time) trajectory
    instead of teleporting to the other side.  The first frame read is
    the anchor.

    Stateful BY DESIGN like :class:`PositionAverager` (the output
    depends on the previous frame read on this cursor): sequential
    reads on one cursor only; a non-consecutive jump with
    ``check_continuity=True`` (default) re-anchors at the new frame
    (upstream warns and does the same); block staging and
    ``Universe.copy()`` refuse stateful transformations loudly.
    Orthorhombic boxes only — the displacement wrap is per-axis
    (upstream supports triclinic via the lambda-matrix form; this port
    refuses non-90 angles rather than silently mis-unwrapping).
    """

    stateful = True

    def __init__(self, check_continuity: bool = True):
        self._check = bool(check_continuity)
        self._prev_raw: np.ndarray | None = None
        self._prev_out: np.ndarray | None = None
        self._last_frame: int | None = None

    def reset(self) -> None:
        self._prev_raw = None
        self._prev_out = None
        self._last_frame = None

    def __call__(self, ts):
        box, _ = _require_box(ts, "NoJump")
        if not np.allclose(box[3:], 90.0, atol=1e-3):
            raise ValueError(
                f"NoJump supports orthorhombic boxes only, got angles "
                f"{box[3:].tolist()}")
        if (self._check and self._last_frame is not None
                and ts.frame != self._last_frame + 1):
            import warnings

            warnings.warn(
                f"NoJump: non-sequential read (frame {self._last_frame} "
                f"-> {ts.frame}); re-anchoring — strided/random access "
                "yields RAW wrapped positions, not a continuous "
                "trajectory (iterate sequentially for unwrapping)",
                stacklevel=2)
            self.reset()
        x = ts.positions.astype(np.float64)
        if self._prev_raw is None:
            self._prev_raw = x
            self._prev_out = x.copy()
        else:
            from mdanalysis_mpi_tpu.ops.host import minimum_image

            d = minimum_image(x - self._prev_raw, box)
            self._prev_out = self._prev_out + d
            self._prev_raw = x
        ts.positions = self._prev_out.astype(np.float32)
        self._last_frame = ts.frame
        return ts
