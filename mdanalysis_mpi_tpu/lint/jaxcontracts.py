"""jit/jaxpr contract rules (MDT1xx).

Two tiers:

- **AST tier** (stdlib-only, always runs): :func:`check_module` finds
  functions routed into jax tracing — arguments of ``jit(...)`` /
  ``_cc.jit(...)`` / ``shard_map(...)`` / ``jax.lax.scan(...)`` /
  ``vmap(...)``, unwrapped through single-argument wrappers like
  ``_f32_precision(f)`` — walks the same-module call graph from those
  roots, and flags host side effects inside anything traced:

  - **MDT101 host-side-effect-in-traced** — calls into ``time.*`` /
    ``random.*`` / ``np.*`` / ``numpy.*``, ``print(...)``, or
    ``.item()``: these run at trace time (a silent constant-fold at
    best) or fail under jit (a concrete-value error at worst), and
    none of them do what the author meant on re-execution of the
    compiled program.
  - **MDT102 global-state-in-traced** — ``global``/``nonlocal``
    declarations inside a traced function: mutation only happens at
    trace time, so cached executions silently skip it (the compile
    cache makes this a one-process-in-N heisenbug).

- **Lowering tier** (``--jaxpr``, needs jax):
  :func:`check_lowered_programs` CPU-lowers the registered executor
  programs and checks the jaxpr invariants runtime tests used to pin:

  - **MDT110 one-psum-per-scan** — the mesh scan program accumulates
    LOCAL partials across a scan group and psum-merges ONCE per scan
    (PR-3's dispatch contract): collectives inside a scan body
    multiply ICI traffic by K and void the scan fold's entire point.
  - **MDT111 captured-constant-budget** — no big ndarrays baked into
    jitted closures: a captured constant is re-shipped with every
    executable and silently bloats the compile cache.
"""

from __future__ import annotations

import ast

from mdanalysis_mpi_tpu.lint.core import Finding, Rule, register

register(Rule(
    "MDT101", "host-side-effect-in-traced", "jit",
    "time/random/np/print/.item() inside a jit/shard_map/scan-traced "
    "function",
    "host ops inside traced code constant-fold at trace time or fail "
    "under jit; the compile cache (PR-6) makes the trace-once "
    "semantics extra surprising"))
register(Rule(
    "MDT102", "global-state-in-traced", "jit",
    "global/nonlocal mutation inside a traced function",
    "mutation happens at trace time only - cache-hit executions "
    "silently skip it"))
register(Rule(
    "MDT110", "one-psum-per-scan", "jaxpr",
    "collective (psum) inside a lax.scan body of a mesh program",
    "PR-3 pinned the mesh scan to ONE psum merge per scan group; a "
    "psum in the scan body costs K collectives per group", True))
register(Rule(
    "MDT111", "captured-constant-budget", "jaxpr",
    "jitted program captures ndarray constants over the byte budget",
    "closures that bake coordinate arrays into the program re-ship "
    "them with every executable and bloat the persistent compile "
    "cache (PR-6)", True))

#: Name roots whose attribute-calls are host-side inside a trace.
_HOST_ROOTS = {"time", "random", "np", "numpy"}

#: Wrapper/transform callables whose first argument is (or wraps) the
#: traced function.
_TRACE_ENTRY_ATTRS = {"jit", "shard_map", "vmap", "scan", "pmap"}

#: Per-program captured-constant budget for MDT111 (bytes).  Small
#: broadcast constants (masks, identity quaternions) are fine; a
#: coordinate block is not.
CONST_BUDGET_BYTES = 1 << 20


# ---------------------------------------------------------------- AST tier

def _unwrap_traced_arg(node: ast.AST) -> ast.AST:
    """Peel single-positional-argument wrapper calls:
    ``jit(_f32_precision(f))`` → ``f``."""
    while isinstance(node, ast.Call) and node.args:
        node = node.args[0]
    return node


class _Scope:
    """One lexical function-def scope: local defs by name + parent."""

    def __init__(self, parent=None):
        self.parent = parent
        self.defs: dict[str, ast.AST] = {}

    def resolve(self, name: str):
        scope = self
        while scope is not None:
            if name in scope.defs:
                return scope.defs[name]
            scope = scope.parent
        return None


def _index_scopes(tree: ast.Module):
    """Map every FunctionDef node to its enclosing :class:`_Scope`
    (so ``Name`` references at a call site resolve lexically)."""
    scope_of: dict[ast.AST, _Scope] = {}
    node_scope: dict[ast.AST, _Scope] = {}

    def walk(node, scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.defs[child.name] = child
                inner = _Scope(scope)
                node_scope[child] = inner
                scope_of[child] = scope
                walk(child, inner)
            else:
                walk(child, scope)

    top = _Scope()
    walk(tree, top)
    return scope_of, node_scope, top


def _qualname(node: ast.AST, parents: dict) -> str:
    parts = []
    cur = node
    while cur is not None:
        name = getattr(cur, "name", None)
        if name and isinstance(cur, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
            parts.append(name)
        cur = parents.get(cur)
    return ".".join(reversed(parts)) or "<module>"


def check_module(tree: ast.Module, rel: str) -> list[Finding]:
    findings: list[Finding] = []
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    scope_of, node_scope, top = _index_scopes(tree)

    # roots: defs handed to jit/shard_map/scan/vmap anywhere in the file
    roots: list[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name not in _TRACE_ENTRY_ATTRS or not node.args:
            continue
        target = _unwrap_traced_arg(node.args[0])
        if isinstance(target, ast.Name):
            # resolve lexically from the call site's enclosing scope
            encl = node
            while encl is not None and encl not in node_scope:
                encl = parents.get(encl)
            scope = node_scope.get(encl, top)
            resolved = scope.resolve(target.id)
            if resolved is not None:
                roots.append(resolved)
        elif isinstance(target, ast.Lambda):
            roots.append(target)

    # transitive closure over same-module calls
    traced: set = set()
    stack = list(roots)
    while stack:
        fndef = stack.pop()
        if id(fndef) in {id(t) for t in traced}:
            continue
        traced.add(fndef)
        scope = node_scope.get(fndef, top)
        for node in ast.walk(fndef):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Name):
                callee = scope.resolve(node.func.id)
                if callee is not None:
                    stack.append(callee)

    for fndef in traced:
        sym = _qualname(fndef, parents)
        for node in ast.walk(fndef):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name) and fn.id == "print":
                    findings.append(Finding(
                        "MDT101", rel, node.lineno, sym,
                        "print() inside a traced function runs at "
                        "trace time only", detail="print"))
                elif isinstance(fn, ast.Attribute):
                    root = fn.value
                    while isinstance(root, ast.Attribute):
                        root = root.value
                    if (isinstance(root, ast.Name)
                            and root.id in _HOST_ROOTS):
                        findings.append(Finding(
                            "MDT101", rel, node.lineno, sym,
                            f"host call `{root.id}.{fn.attr}` inside a "
                            f"traced function (trace-time constant "
                            f"fold / concretization error)",
                            detail=f"{root.id}.{fn.attr}"))
                    elif fn.attr == "item" and not node.args:
                        findings.append(Finding(
                            "MDT101", rel, node.lineno, sym,
                            ".item() inside a traced function forces a "
                            "host readback (concretization error "
                            "under jit)", detail=".item"))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                findings.append(Finding(
                    "MDT102", rel, node.lineno, sym,
                    f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                    f"{', '.join(node.names)}` inside a traced function "
                    f"mutates at trace time only",
                    detail=",".join(node.names)))
    return findings


# ------------------------------------------------------------ lowering tier

def _iter_subjaxprs(jaxpr):
    """Yield every (primitive_name, sub-jaxpr) pair reachable from
    ``jaxpr`` (eqn params holding Jaxpr/ClosedJaxpr, incl. in lists)."""
    from jax.extend import core as jcore

    def inner_jaxprs(value):
        if isinstance(value, jcore.ClosedJaxpr):
            yield value.jaxpr
        elif isinstance(value, jcore.Jaxpr):
            yield value
        elif isinstance(value, (list, tuple)):
            for v in value:
                yield from inner_jaxprs(v)

    for eqn in jaxpr.eqns:
        for value in eqn.params.values():
            for sub in inner_jaxprs(value):
                yield eqn.primitive.name, sub
                yield from _iter_subjaxprs(sub)


def count_psums(jaxpr) -> int:
    n = sum(1 for eqn in jaxpr.eqns if eqn.primitive.name == "psum")
    for _, sub in _iter_subjaxprs(jaxpr):
        n += sum(1 for eqn in sub.eqns if eqn.primitive.name == "psum")
    return n


def scan_psum_violations(closed_jaxpr) -> list[tuple[str, int]]:
    """``(context, n_psums_in_scan_body)`` for every ``lax.scan`` body
    that contains a collective — the MDT110 predicate.  Empty means
    the program hoists its merges out of every scan."""
    out = []

    def visit(jaxpr, trail):
        for eqn in jaxpr.eqns:
            for value in eqn.params.values():
                from jax.extend import core as jcore

                subs = []
                if isinstance(value, jcore.ClosedJaxpr):
                    subs = [value.jaxpr]
                elif isinstance(value, jcore.Jaxpr):
                    subs = [value]
                elif isinstance(value, (list, tuple)):
                    subs = [v.jaxpr if isinstance(v, jcore.ClosedJaxpr)
                            else v for v in value
                            if isinstance(v, (jcore.Jaxpr,
                                              jcore.ClosedJaxpr))]
                for sub in subs:
                    t = trail + [eqn.primitive.name]
                    if eqn.primitive.name == "scan":
                        n = count_psums(sub)
                        if n > 0:
                            out.append(("/".join(t), n))
                    visit(sub, t)

    visit(closed_jaxpr.jaxpr, [])
    return out


def captured_const_bytes(closed_jaxpr) -> int:
    """Total bytes of ndarray-like constants the program captured."""
    total = 0
    for c in closed_jaxpr.consts:
        nbytes = getattr(c, "nbytes", None)
        if nbytes:
            total += int(nbytes)
    return total


def _registered_programs():
    """Name → (closed_jaxpr, expected_total_psums | None) for the
    executor programs the contracts pin.  Built at CPU scale from the
    package's own synthetic fixtures — no hardware, no trajectory
    files."""
    import jax
    import numpy as np

    from mdanalysis_mpi_tpu.analysis.rms import RMSF
    from mdanalysis_mpi_tpu.parallel.executors import MeshExecutor
    from mdanalysis_mpi_tpu.testing import make_protein_universe

    u = make_protein_universe(n_residues=8, n_frames=64, noise=0.2)
    ag = u.select_atoms("name CA")
    a = RMSF(ag)
    a.n_frames = 64
    a._frame_indices = list(range(64))
    a._prepare()
    m = MeshExecutor(batch_size=2)
    params = a._batch_params()
    s_atoms = len(ag.indices)
    n_dev = len(jax.devices())
    gb = 2 * n_dev                      # global batch
    blk = lambda k: (np.zeros((k, gb, s_atoms, 3), np.float32),
                     np.zeros((k, gb, 6), np.float32),
                     np.ones((k, gb), np.float32))
    one = (np.zeros((gb, s_atoms, 3), np.float32),
           np.zeros((gb, 6), np.float32),
           np.ones((gb,), np.float32))
    s_init, s_fused, _ = m._build_scan(a)
    _, gfn, _, _, _ = m._build(a)
    block_jaxpr = jax.make_jaxpr(gfn)(params, *one)
    block_psums = count_psums(block_jaxpr)
    programs = {
        "mesh_block_rmsf": (block_jaxpr, None),
        "mesh_scan_rmsf_init": (
            jax.make_jaxpr(s_init)(params, *blk(4)), block_psums),
        "mesh_scan_rmsf_fused": (
            jax.make_jaxpr(s_fused)(
                jax.eval_shape(s_init, params, *blk(1)), params,
                *blk(3)),
            block_psums),
    }
    # the fused planar program (ops/pallas_fused.py — the quantized-
    # native kernel AlignedRMSF(engine='fused') registers): lowered in
    # interpret mode so the walk works on CPU.  Single-device by
    # contract (_mesh_quantized_native keeps it off the mesh), so any
    # psum is a violation: expected total 0.
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops import pallas_fused as pfu
    from mdanalysis_mpi_tpu.ops import pallas_rmsf as prm

    _, nr = prm.pad_selection(np.asarray(ag.indices))
    s_pad = prm.pad_selection(np.asarray(ag.indices))[0].shape[0]
    fparams = prm.build_params(
        jnp.zeros((nr, 3), jnp.float32), jnp.zeros((3,), jnp.float32),
        jnp.ones((nr,), jnp.float32), nr, s_pad)
    fused_k = pfu.moments_kernel_for("interpret", nr)
    programs["jax_fused_planar_moments"] = (
        jax.make_jaxpr(fused_k)(
            fparams, jnp.zeros((3, 16, s_pad), jnp.int16),
            jnp.float32(1.0), None, jnp.ones((16,), jnp.float32)),
        0)
    return programs


def check_lowered_programs(notes: list[str]) -> list[Finding]:
    """The MDT110/MDT111 pass over the registered executor programs.
    Appends a note and returns no findings when jax is unavailable."""
    try:
        import jax  # noqa: F401
    except Exception as exc:            # pragma: no cover - env-dependent
        notes.append(f"jaxpr contracts skipped: jax unavailable ({exc})")
        return []
    findings: list[Finding] = []
    programs = _registered_programs()
    rel = "mdanalysis_mpi_tpu/parallel/executors.py"
    for name, (jaxpr, expected_psums) in programs.items():
        for ctx, n in scan_psum_violations(jaxpr):
            findings.append(Finding(
                "MDT110", rel, 0, name,
                f"scan body contains {n} psum(s) (at {ctx}): the mesh "
                f"scan contract is local accumulation with ONE merge "
                f"per scan", detail=ctx))
        if expected_psums is not None:
            total = count_psums(jaxpr)
            if total != expected_psums:
                findings.append(Finding(
                    "MDT110", rel, 0, name,
                    f"program has {total} psums, expected "
                    f"{expected_psums} (the single-block program's "
                    f"count) — the merge multiplied with the scan",
                    detail="total"))
        nbytes = captured_const_bytes(jaxpr)
        if nbytes > CONST_BUDGET_BYTES:
            findings.append(Finding(
                "MDT111", rel, 0, name,
                f"program captures {nbytes} bytes of ndarray "
                f"constants (budget {CONST_BUDGET_BYTES}): data must "
                f"flow in as arguments, not baked into the closure",
                detail="consts"))
    notes.append(f"jaxpr contracts checked {len(programs)} programs")
    return findings
