"""Schema-drift rules (MDT2xx) — stdlib only.

The observability contract lives in four places that historically
drifted apart: the recording sites (``obs.METRICS.inc(...)`` and the
zero-injection tables in ``obs/metrics.py``), the pinned schema
(``PINNED_METRICS`` in ``tests/test_bench_contract.py``), the operator
catalog (``docs/OBSERVABILITY.md``), and the bench artifact keys the
driver scores.  This pass statically harvests all four and diffs them:

- **MDT201 metric-not-pinned** — a live-recorded or zero-injected
  series missing from ``PINNED_METRICS``: the schema test can't
  protect a name it doesn't know.
- **MDT202 pinned-metric-unregistered** — a pinned name no code
  records, injects or emits: the schema test is pinning vapor (a
  rename's orphaned half).
- **MDT203 metric-undocumented** — a live/injected series absent from
  the ``docs/OBSERVABILITY.md`` catalog (brace families like
  ``mdtpu_jobs_{submitted,completed}_total`` are expanded).
- **MDT204 span-undocumented** — a ``phase("...")``/``span("...")``/
  ``span_event("...")`` name the docs' span model never mentions
  (exactly how the PR-7 instants went missing).
- **MDT205 bench-key-drift** — an artifact key the bench contract
  test requires that ``bench.py`` never mentions: the pin outlived
  the field.
- **MDT206 alert-rule-drift** — the ``obs/alerts.py`` seed-rule
  catalog vs its pin (``PINNED_ALERT_RULES`` in the contract test):
  duplicate or non-snake_case rule names, rules missing from the
  pin, and pinned names no rule carries — rule drift caught exactly
  like metric drift.
"""

from __future__ import annotations

import ast
import itertools
import os
import re

from mdanalysis_mpi_tpu.lint.core import Finding, Rule, register

register(Rule(
    "MDT201", "metric-not-pinned", "schema",
    "recorded/zero-injected metric missing from PINNED_METRICS",
    "PR-5 pinned the snapshot schema precisely because renames were "
    "silently shipping; an unpinned series is outside that fence"))
register(Rule(
    "MDT202", "pinned-metric-unregistered", "schema",
    "PINNED_METRICS name that no code records, injects or emits",
    "the orphaned half of a rename: the schema test keeps passing "
    "while the series it thinks it pins no longer exists"))
register(Rule(
    "MDT203", "metric-undocumented", "schema",
    "recorded/zero-injected metric absent from docs/OBSERVABILITY.md",
    "the docs table is the operator contract; PR-6/PR-7 series "
    "drifted out of it"))
register(Rule(
    "MDT204", "span-undocumented", "schema",
    "span/phase/instant name absent from docs/OBSERVABILITY.md",
    "the PR-7 supervision instants (lease_reaped, job_quarantined...) "
    "never reached the documented span model"))
register(Rule(
    "MDT205", "bench-key-drift", "schema",
    "bench-contract-pinned artifact key that bench.py never mentions",
    "the driver scores bench.py's JSON line; a pinned-but-unemitted "
    "key means the contract test and the artifact diverged"))
register(Rule(
    "MDT206", "alert-rule-drift", "schema",
    "alert seed-rule catalog drift: duplicate/non-snake_case names, "
    "or mismatch vs PINNED_ALERT_RULES",
    "the alert rules are an operator contract like the metric "
    "schema; an unpinned rename would silently retire a rule every "
    "runbook references"))

_METRIC_RE = re.compile(r"^mdtpu_\w+$")
#: Doc tokens: a metric name possibly with ``{a,b,c}`` families.
_DOC_METRIC_RE = re.compile(r"mdtpu_[a-z0-9_]*(?:\{[a-z0-9_,]+\}[a-z0-9_]*)*")

#: Zero-injection tables in obs/metrics.py whose members are part of
#: the process-invariant snapshot schema (with their types).
_TABLE_TYPES = {
    "COMPILE_METRICS": "counter",
    "BREAKER_COUNTERS": "counter",
    "BREAKER_GAUGES": "gauge",
    "SUPERVISION_COUNTERS": "counter",
    "RELIABILITY_COUNTERS": "counter",
    "LINT_GAUGES": "gauge",
    "INTEGRITY_COUNTERS": "counter",
    "INTEGRITY_GAUGES": "gauge",
    "SCRUB_COUNTERS": "counter",
    "STORE_COUNTERS": "counter",
    "STORE_REMOTE_COUNTERS": "counter",
    "STORE_CACHE_COUNTERS": "counter",
    "STORE_CACHE_GAUGES": "gauge",
    "FLEET_COUNTERS": "counter",
    "FLEET_GAUGES": "gauge",
    "FLEET_OBS_COUNTERS": "counter",
    "FLEET_OBS_GAUGES": "gauge",
    "QOS_COUNTERS": "counter",
    "QOS_GAUGES": "gauge",
    "PROF_COUNTERS": "counter",
    "PROF_GAUGES": "gauge",
    "PROF_HISTOGRAMS": "histogram",
    "ALERT_COUNTERS": "counter",
    "ALERT_GAUGES": "gauge",
    "ENSEMBLE_COUNTERS": "counter",
    "ENSEMBLE_GAUGES": "gauge",
    "STREAM_COUNTERS": "counter",
    "STREAM_GAUGES": "gauge",
    "USAGE_COUNTERS": "counter",
    "CANARY_COUNTERS": "counter",
    "CANARY_GAUGES": "gauge",
    "CANARY_HISTOGRAMS": "histogram",
}

_RECORD_TYPES = {"inc": "counter", "observe": "histogram",
                 "set_gauge": "gauge"}


def _literal_assignments(tree: ast.Module) -> dict:
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                out[node.targets[0].id] = ast.literal_eval(node.value)
            except (ValueError, TypeError, SyntaxError):
                pass
    return out


def harvest_recorded(pkg_root: str) -> dict[str, set]:
    """``name → {types}`` for every literal-named recording call
    (``X.inc("mdtpu_..")`` etc.) in the package."""
    from mdanalysis_mpi_tpu.lint.core import iter_python_files, parse_file

    out: dict[str, set] = {}
    for path in iter_python_files(pkg_root):
        tree, _ = parse_file(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RECORD_TYPES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if _METRIC_RE.match(name):
                out.setdefault(name, set()).add(
                    _RECORD_TYPES[node.func.attr])
    return out


def harvest_metrics_tables(metrics_py: str) -> tuple[dict, set]:
    """(zero-injected ``name → type``, all code-declared names) from
    ``obs/metrics.py``: the injection tables, the telemetry-derived
    families, and the adapter-emitted literal names."""
    from mdanalysis_mpi_tpu.lint.core import parse_file

    tree, _ = parse_file(metrics_py)
    injected: dict[str, str] = {}
    declared: set[str] = set()
    if tree is None:
        return injected, declared
    consts = _literal_assignments(tree)
    for table, typ in _TABLE_TYPES.items():
        for name in consts.get(table, ()):
            injected[name] = typ
            declared.add(name)
    for key in consts.get("_TELEMETRY_COUNTERS", ()):
        declared.add(f"mdtpu_{key}_total")
    for key in consts.get("_TELEMETRY_GAUGES", ()):
        declared.add(f"mdtpu_{key}")
    # adapter-emitted literals (snap["mdtpu_phase_seconds_total"]=...)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _METRIC_RE.match(node.value)):
            declared.add(node.value)
    return injected, declared


def harvest_pinned(contract_py: str) -> dict[str, str]:
    from mdanalysis_mpi_tpu.lint.core import parse_file

    tree, _ = parse_file(contract_py)
    if tree is None:
        return {}
    pinned = _literal_assignments(tree).get("PINNED_METRICS", {})
    return pinned if isinstance(pinned, dict) else {}


def expand_doc_token(token: str) -> list[str]:
    """``mdtpu_jobs_{submitted,completed}_total`` → both names.

    A brace group WITHOUT a comma is a label annotation
    (``mdtpu_runs_total{backend}``), not a family — dropped."""
    parts = re.split(r"(\{[a-z0-9_,]+\})", token)
    options = [
        (p[1:-1].split(",") if "," in p else [""])
        if p.startswith("{") else [p]
        for p in parts]
    return ["".join(combo) for combo in itertools.product(*options)]


def harvest_doc_metrics(doc_md: str) -> set[str]:
    with open(doc_md, encoding="utf-8") as f:
        text = f.read()
    out: set[str] = set()
    for token in _DOC_METRIC_RE.findall(text):
        out.update(expand_doc_token(token))
    return out


def harvest_span_names(pkg_root: str) -> dict[str, int]:
    """Literal names handed to ``phase(...)``, ``span(...)`` and
    ``span_event(...)`` across the package → first line seen."""
    from mdanalysis_mpi_tpu.lint.core import iter_python_files, parse_file

    out: dict[str, tuple] = {}
    for path in iter_python_files(pkg_root):
        tree, _ = parse_file(path)
        if tree is None:
            continue
        rel = path
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in ("phase", "span", "span_event"):
                out.setdefault(node.args[0].value,
                               (rel, node.lineno))
    return out


def harvest_alert_rules(alerts_py: str) -> list[str]:
    """The ``SEED_RULES`` catalog's rule names, in declaration order
    (duplicates preserved — MDT206 flags them)."""
    from mdanalysis_mpi_tpu.lint.core import parse_file

    tree, _ = parse_file(alerts_py)
    if tree is None:
        return []
    rules = _literal_assignments(tree).get("SEED_RULES", [])
    if not isinstance(rules, list):
        return []
    return [r.get("name") for r in rules
            if isinstance(r, dict) and isinstance(r.get("name"), str)]


def harvest_pinned_alert_rules(contract_py: str) -> list[str]:
    from mdanalysis_mpi_tpu.lint.core import parse_file

    tree, _ = parse_file(contract_py)
    if tree is None:
        return []
    pinned = _literal_assignments(tree).get("PINNED_ALERT_RULES", ())
    if not isinstance(pinned, (list, tuple)):
        return []
    return [n for n in pinned if isinstance(n, str)]


def harvest_bench_pins(contract_py: str) -> list[str]:
    """Artifact keys the contract test iterates over (``for key in
    ("metric", ...): assert key in rec``)."""
    from mdanalysis_mpi_tpu.lint.core import parse_file

    tree, _ = parse_file(contract_py)
    if tree is None:
        return []
    keys: list[str] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and node.target.id == "key"
                and isinstance(node.iter, ast.Tuple)):
            for elt in node.iter.elts:
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    keys.append(elt.value)
    return keys


def check_repo(root: str, notes: list[str]) -> list[Finding]:
    pkg = os.path.join(root, "mdanalysis_mpi_tpu")
    metrics_py = os.path.join(pkg, "obs", "metrics.py")
    contract_py = os.path.join(root, "tests", "test_bench_contract.py")
    doc_md = os.path.join(root, "docs", "OBSERVABILITY.md")
    bench_py = os.path.join(root, "bench.py")
    missing = [p for p in (metrics_py, contract_py, doc_md)
               if not os.path.exists(p)]
    if missing:
        notes.append("schema pass skipped: missing "
                     + ", ".join(os.path.relpath(p, root)
                                 for p in missing))
        return []

    findings: list[Finding] = []
    rel_metrics = "mdanalysis_mpi_tpu/obs/metrics.py"
    rel_contract = "tests/test_bench_contract.py"

    recorded = harvest_recorded(pkg)
    injected, declared = harvest_metrics_tables(metrics_py)
    pinned = harvest_pinned(contract_py)
    documented = harvest_doc_metrics(doc_md)

    # the process-invariant schema: live-recorded + zero-injected
    invariant = dict(injected)
    for name, types in recorded.items():
        invariant.setdefault(name, sorted(types)[0])

    for name in sorted(invariant):
        if name not in pinned:
            findings.append(Finding(
                "MDT201", rel_contract, 0, "PINNED_METRICS",
                f"`{name}` is recorded/zero-injected by the package "
                f"but missing from PINNED_METRICS", detail=name))
        if name not in documented:
            findings.append(Finding(
                "MDT203", "docs/OBSERVABILITY.md", 0, "metrics-table",
                f"`{name}` is recorded/zero-injected but absent from "
                f"the docs metric catalog", detail=name))
    all_declared = set(declared) | set(recorded) | set(injected)
    for name in sorted(pinned):
        if name not in all_declared:
            findings.append(Finding(
                "MDT202", rel_metrics, 0, "PINNED_METRICS",
                f"pinned metric `{name}` is never recorded, injected "
                f"or emitted by package code", detail=name))

    with open(doc_md, encoding="utf-8") as f:
        doc_text = f.read()
    for name, (path, line) in sorted(harvest_span_names(pkg).items()):
        if not re.search(rf"\b{re.escape(name)}\b", doc_text):
            from mdanalysis_mpi_tpu.lint.core import relpath

            findings.append(Finding(
                "MDT204", relpath(path, root), line, "span-model",
                f"span/phase name `{name}` is not in the "
                f"docs/OBSERVABILITY.md span model", detail=name))

    # MDT206: the alert seed-rule catalog vs its pin — rule drift is
    # caught like metric drift (ISSUE 15 satellite)
    alerts_py = os.path.join(pkg, "obs", "alerts.py")
    rel_alerts = "mdanalysis_mpi_tpu/obs/alerts.py"
    if os.path.exists(alerts_py):
        rule_names = harvest_alert_rules(alerts_py)
        pinned_rules = harvest_pinned_alert_rules(contract_py)
        seen: set[str] = set()
        snake = re.compile(r"^[a-z][a-z0-9_]*$")
        for name in rule_names:
            if name in seen:
                findings.append(Finding(
                    "MDT206", rel_alerts, 0, "SEED_RULES",
                    f"duplicate alert rule name `{name}`",
                    detail=f"dup:{name}"))
            seen.add(name)
            if not snake.match(name):
                findings.append(Finding(
                    "MDT206", rel_alerts, 0, "SEED_RULES",
                    f"alert rule name `{name}` is not snake_case",
                    detail=f"case:{name}"))
            if name not in pinned_rules:
                findings.append(Finding(
                    "MDT206", rel_contract, 0, "PINNED_ALERT_RULES",
                    f"seed alert rule `{name}` is missing from "
                    f"PINNED_ALERT_RULES", detail=name))
        for name in pinned_rules:
            if name not in seen:
                findings.append(Finding(
                    "MDT206", rel_alerts, 0, "SEED_RULES",
                    f"pinned alert rule `{name}` is not in the "
                    f"SEED_RULES catalog", detail=name))
    else:
        notes.append("MDT206 skipped: obs/alerts.py not found")

    if os.path.exists(bench_py):
        with open(bench_py, encoding="utf-8") as f:
            bench_src = f.read()
        for key in harvest_bench_pins(contract_py):
            if key not in bench_src:
                findings.append(Finding(
                    "MDT205", rel_contract, 0,
                    "test_bench_json_contract",
                    f"pinned artifact key `{key}` never appears in "
                    f"bench.py", detail=key))
    else:
        notes.append("MDT205 skipped: bench.py not found")
    return findings
