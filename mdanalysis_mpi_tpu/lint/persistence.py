"""Persistence-discipline rules (MDT00x, persistence family) —
stdlib ``ast`` only.

- **MDT005 non-atomic-artifact-write** — the integrity layer
  (docs/RELIABILITY.md §5) made tmp→fsync→rename the repo convention
  for every persisted artifact: a crash mid-write must leave the old
  file or the new one, never a torn hybrid.  The historical bug: the
  batch CLI's per-job ``.npz`` outputs were written with a bare
  ``np.savez(output, ...)`` — a ``kill -9`` (or ENOSPC) mid-write left
  a torn file that a ``--journal`` restart then *skipped over* as
  "done".  The rule flags direct write-mode ``open()`` and
  ``np.savez``/``savez_compressed`` calls in the persistence modules
  (``service/``, ``utils/checkpoint.py``, ``utils/integrity.py``,
  ``obs/``) whose enclosing function shows no tmp+rename shape.

What counts as atomic (the negatives):

- the target expression mentions a temp name (``path + ".tmp"``, a
  variable named ``tmp*``/``*_tmp``) — the write lands on a scratch
  inode, or
- the enclosing function also calls ``os.replace``/``os.rename`` —
  the rename completes the pattern, or
- the file is opened in APPEND mode (``"a"``) — append-only logs
  (the journal) are crash-consistent by construction: a torn tail is
  detected by the CRC frame, never a torn *file*.

Reads (``"r"``/``"rb"``) and non-persistence modules are out of scope.
"""

from __future__ import annotations

import ast

from mdanalysis_mpi_tpu.lint.core import Finding, Rule, register

register(Rule(
    "MDT005", "non-atomic-artifact-write", "persistence",
    "open-for-write / np.savez without tmp+rename in a persistence "
    "module",
    "the batch CLI's per-job .npz was a bare np.savez: kill -9 or "
    "ENOSPC mid-write left a torn artifact a --journal restart then "
    "trusted as done (fixed by utils/integrity.py write_npz_atomic)"))

#: Repo-relative path prefixes/files where persisted artifacts are
#: written — the rule's scope (docs/LINT.md).
_SCOPE_PREFIXES = (
    "mdanalysis_mpi_tpu/service/",
    "mdanalysis_mpi_tpu/obs/",
)
_SCOPE_FILES = (
    "mdanalysis_mpi_tpu/utils/checkpoint.py",
    "mdanalysis_mpi_tpu/utils/integrity.py",
)

_SAVEZ_NAMES = {"savez", "savez_compressed"}
_RENAME_NAMES = {"replace", "rename"}


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return (rel in _SCOPE_FILES
            or any(rel.startswith(p) for p in _SCOPE_PREFIXES))


def _call_name(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _open_mode(node: ast.Call) -> str | None:
    """The literal mode of an ``open()`` call ("r" when omitted);
    None when the mode is not a literal (out of static reach)."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    else:
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _open_target(node: ast.Call):
    """The path expression of an ``open()`` call: first positional
    arg, or the ``file=`` keyword (PEP 8 discourages it, but the rule
    must not be dodged by spelling)."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "file":
            return kw.value
    return None


def _mentions_tmp(node: ast.AST) -> bool:
    """Does the target-path expression visibly route through a temp
    name?  (``path + ".tmp"``, ``tmp``, ``tmp_path``, ``f"{p}.tmp"``…)"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and "tmp" in sub.value.lower():
            return True
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
    return False


def _has_rename(scope: ast.AST) -> bool:
    """Does THIS scope call os.replace/os.rename — not counting
    nested function bodies: a rename inside a deferred closure does
    not make the enclosing scope's in-place write atomic (the same
    judged-alone rule _write_calls applies)."""
    found = False

    def walk(node):
        nonlocal found
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call) \
                    and _call_name(child) in _RENAME_NAMES:
                found = True
                return
            walk(child)
            if found:
                return

    walk(scope)
    return found


def _write_calls(scope: ast.AST):
    """(call, kind, target_expr) for every direct artifact write in
    ``scope``, NOT descending into nested function definitions (each
    function is judged on its own tmp+rename shape)."""
    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                name = _call_name(child)
                if name == "open":
                    mode = _open_mode(child)
                    target = _open_target(child)
                    # "x" (exclusive create) tears exactly like "w"
                    if mode is not None and target is not None \
                            and ("w" in mode or "x" in mode):
                        out.append((child, "open", target))
                elif name in _SAVEZ_NAMES and child.args:
                    out.append((child, name, child.args[0]))
            walk(child)

    walk(scope)
    return out


def _scopes(tree: ast.Module):
    """Every function/method body (plus the module top level), each
    judged independently: the tmp+rename pair must live in the SAME
    scope as the write it blesses."""
    yield "<module>", tree

    def rec(node, trail):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                name = ".".join(trail + [child.name])
                yield name, child
                yield from rec(child, trail + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, trail + [child.name])
            else:
                yield from rec(child, trail)

    yield from rec(tree, [])


def check_module(tree: ast.Module, rel: str) -> list[Finding]:
    if not _in_scope(rel):
        return []
    findings: list[Finding] = []
    for symbol, scope in _scopes(tree):
        has_rename = _has_rename(scope)
        for i, (call, kind, target) in enumerate(_write_calls(scope)):
            if _mentions_tmp(target):
                continue           # writing to a scratch inode
            if has_rename:
                continue           # tmp+rename completes in this scope
            what = ("open(..., 'w')" if kind == "open"
                    else f"np.{kind}(...)")
            findings.append(Finding(
                "MDT005", rel, call.lineno, symbol or "<module>",
                f"{what} writes a persistence artifact in place — a "
                f"crash or ENOSPC mid-write leaves a torn file; write "
                f"tmp→fsync→rename (utils/integrity.py atomic_write / "
                f"write_npz_atomic)",
                detail=f"{kind}#{i}"))
    return findings
