"""Lint infrastructure: rules, findings, pragmas, baseline, report.

Everything here is stdlib-only (``ast``/``json``/``os``/``re``) so the
fast path — ``python -m mdanalysis_mpi_tpu lint`` without ``--jaxpr``
— never imports jax (pinned by ``tests/test_lint.py``).

Suppression model (docs/LINT.md):

- **Pragma** — ``# mdtpu-lint: disable=MDT001[,MDT101]`` on the
  flagged line silences those rules for that line only; a finding is
  pointed at real code someone already reviewed.
- **Baseline** — a JSON file of accepted findings, each with a
  required ``justification`` string.  Matching is by the finding's
  stable key ``(rule, path, symbol, detail)`` — deliberately NOT line
  numbers, so unrelated edits above a baselined site don't resurrect
  it.  ``--baseline-write`` bootstraps the file; a justification of
  ``"TODO"`` still counts as unbaselined so the bootstrap cannot be
  silently shipped.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

#: Pragma grammar: ``# mdtpu-lint: disable=MDT001,MDT002`` (line) —
#: recognized anywhere in the physical line's trailing comment.
_PRAGMA_RE = re.compile(r"#\s*mdtpu-lint:\s*disable=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One named check.  ``history`` records the shipped bug the rule
    encodes — the reason it exists (docs/LINT.md catalog)."""

    id: str
    name: str
    family: str            # "concurrency" | "persistence" | "jit"
    #                        # | "jaxpr" | "schema"
    summary: str
    history: str
    needs_jax: bool = False


@dataclasses.dataclass
class Finding:
    rule: str
    path: str              # repo-relative, "/" separators
    line: int
    symbol: str            # dotted scope, e.g. "PhaseTimers.phase"
    message: str
    detail: str = ""       # stable discriminator within the symbol

    def key(self) -> tuple:
        return (self.rule, self.path, self.symbol, self.detail)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.symbol}] "
                f"{self.message}")


_RULES: dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in _RULES:
        raise ValueError(f"duplicate lint rule id {rule.id}")
    _RULES[rule.id] = rule
    return rule


def _load_passes() -> None:
    # import for side effect: each pass module registers its rules
    from mdanalysis_mpi_tpu.lint import (  # noqa: F401
        concurrency, jaxcontracts, persistence, schema,
    )


def all_rules() -> dict[str, Rule]:
    _load_passes()
    return dict(_RULES)


def rule_ids() -> tuple[str, ...]:
    return tuple(sorted(all_rules()))


def iter_python_files(root: str):
    """Yield the package's ``.py`` files under ``root`` (sorted,
    ``__pycache__`` excluded) — the AST passes' input set."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__"
                             and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def parse_file(path: str):
    """``(tree, source_lines)`` for ``path``; ``(None, [])`` on a
    syntax error (reported by the caller as unparseable, not crashed
    over — the linter must survive a broken tree)."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        return ast.parse(src), src.splitlines()
    except SyntaxError:
        return None, src.splitlines()


def pragma_suppressed(lines: list[str], finding: Finding) -> bool:
    if not 1 <= finding.line <= len(lines):
        return False
    m = _PRAGMA_RE.search(lines[finding.line - 1])
    if not m:
        return False
    ids = {tok.strip() for tok in m.group(1).split(",")}
    return finding.rule in ids or "ALL" in ids


class Baseline:
    """Accepted-findings file: ``{"version": 1, "findings": [{rule,
    path, symbol, detail, justification}, ...]}``."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("findings", []))

    def save(self, path: str) -> None:
        doc = {"version": 1, "findings": self.entries}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    def _keys(self) -> set:
        out = set()
        for e in self.entries:
            just = (e.get("justification") or "").strip()
            if not just or just.upper().startswith("TODO"):
                continue     # unjustified entries don't suppress
            out.add((e.get("rule"), e.get("path"), e.get("symbol"),
                     e.get("detail", "")))
        return out

    def match(self, finding: Finding) -> bool:
        return finding.key() in self._keys()

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      justification: str = "TODO: justify") -> "Baseline":
        return cls([
            {"rule": f.rule, "path": f.path, "symbol": f.symbol,
             "detail": f.detail, "justification": justification,
             "message": f.message}
            for f in findings])


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]            # unbaselined, unsuppressed
    baselined: list[Finding]
    suppressed: int                    # pragma-silenced count
    files: int
    rules: tuple[str, ...]
    notes: list[str]                   # skipped passes etc.

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "rules": list(self.rules),
            "n_findings": len(self.findings),
            "n_baselined": len(self.baselined),
            "n_suppressed": self.suppressed,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "notes": self.notes,
        }


def find_repo_root(start: str | None = None) -> str:
    """The directory holding the ``mdanalysis_mpi_tpu`` package — where
    ``tests/``, ``docs/`` and ``bench.py`` live for the schema pass.
    Defaults to the installed package's parent."""
    if start is not None:
        return os.path.abspath(start)
    import mdanalysis_mpi_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(mdanalysis_mpi_tpu.__file__)))


def relpath(path: str, root: str) -> str:
    return os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")


def run_lint(root: str | None = None, rules=None, jaxpr: bool = False,
             baseline: Baseline | str | None = None) -> LintReport:
    """Run every selected pass over the repo at ``root``.

    ``rules``: iterable of rule ids to keep (default: all).  ``jaxpr``:
    also run the lowering-based MDT11x contracts (imports jax).
    ``baseline``: a :class:`Baseline` or a path to one.
    """
    from mdanalysis_mpi_tpu.lint import (
        concurrency, jaxcontracts, persistence, schema,
    )

    root = find_repo_root(root)
    pkg = os.path.join(root, "mdanalysis_mpi_tpu")
    if not os.path.isdir(pkg):
        # linting some other tree (tests do this): treat root itself
        # as the package dir
        pkg = root
    selected = set(rules) if rules is not None else None
    if isinstance(baseline, str):
        baseline = Baseline.load(baseline)
    baseline = baseline or Baseline()

    notes: list[str] = []
    raw: list[Finding] = []
    suppressed = 0
    n_files = 0
    for path in iter_python_files(pkg):
        tree, lines = parse_file(path)
        n_files += 1
        rel = relpath(path, root)
        if tree is None:
            raw.append(Finding("MDT000", rel, 1, "<module>",
                               "file does not parse", "syntax"))
            continue
        file_findings = []
        file_findings += concurrency.check_module(tree, rel)
        file_findings += persistence.check_module(tree, rel)
        file_findings += jaxcontracts.check_module(tree, rel)
        kept = []
        for f in file_findings:
            if pragma_suppressed(lines, f):
                suppressed += 1
            else:
                kept.append(f)
        raw += kept

    raw += schema.check_repo(root, notes)
    if jaxpr:
        raw += jaxcontracts.check_lowered_programs(notes)
    else:
        notes.append("jaxpr contracts (MDT110/MDT111) skipped: fast "
                     "mode (pass --jaxpr)")

    if selected is not None:
        raw = [f for f in raw if f.rule in selected]
    findings, baselined = [], []
    for f in raw:
        (baselined if baseline.match(f) else findings).append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintReport(findings=findings, baselined=baselined,
                      suppressed=suppressed, files=n_files,
                      rules=rule_ids(), notes=notes)
