"""``python -m mdanalysis_mpi_tpu lint`` — the checker's command line.

Fast by default: only the stdlib-``ast`` passes run, no jax import
(the CLI discloses ``jax_imported`` in its JSON output and tests pin
it).  ``--jaxpr`` adds the lowering-based MDT11x contracts, forcing an
8-virtual-device CPU platform first so the mesh program lowers without
hardware.

Exit codes: 0 clean (no unbaselined findings), 1 findings, 2 usage.

Baseline workflow (docs/LINT.md): ``--baseline-write`` records every
current finding with ``justification: "TODO: justify"``; entries only
suppress once a real justification replaces the TODO — the bootstrap
cannot be silently shipped.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from mdanalysis_mpi_tpu.lint.core import (
    Baseline, all_rules, find_repo_root, run_lint,
)

#: Default baseline file, repo-relative.
BASELINE_NAME = ".mdtpu_lint_baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mdanalysis_mpi_tpu lint",
        description="repo-native static analysis: concurrency "
                    "discipline, persistence atomicity, jit/jaxpr "
                    "contracts, schema drift "
                    "(docs/LINT.md)")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: the installed "
                        "package's parent)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default all)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one JSON document on stdout instead of text")
    p.add_argument("--jaxpr", action="store_true",
                   help="also CPU-lower the registered executor "
                        "programs and check the MDT11x jaxpr "
                        "contracts (imports jax)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline suppression file (default "
                        f"<root>/{BASELINE_NAME})")
    p.add_argument("--baseline-write", action="store_true",
                   help="bootstrap: write every current finding to "
                        "the baseline file with a TODO justification "
                        "and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def lint_main(argv=None) -> int:
    ns = _parser().parse_args(argv)
    if ns.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{rule.id}  {rule.name:32s} [{rule.family}] "
                  f"{rule.summary}")
        return 0
    root = find_repo_root(ns.root)
    baseline_path = ns.baseline or os.path.join(root, BASELINE_NAME)
    rules = (None if ns.rules is None
             else [r.strip() for r in ns.rules.split(",") if r.strip()])
    if rules is not None:
        # a typo'd id would filter every finding away and leave a CI
        # gate permanently green — unknown ids are a usage error
        known = set(all_rules()) | {"MDT000"}   # MDT000: unparseable
        unknown = sorted(set(rules) - known)
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
    if ns.jaxpr:
        # CPU-lowering is the contract (no hardware required), and the
        # mesh program needs a multi-device axis to lower the psum
        # against: force the 8-virtual-device CPU platform BEFORE jax
        # init (same trick as tests/conftest.py — jax.config outranks
        # an axon site hook's env re-assert)
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    report = run_lint(root=root, rules=rules, jaxpr=ns.jaxpr,
                      baseline=baseline_path)

    if ns.baseline_write:
        merged = Baseline.load(baseline_path)
        # dedup by finding key: a re-run (TODO entries don't suppress,
        # so the findings come back) must not append duplicates that
        # each need hand-justifying later
        have = {(e.get("rule"), e.get("path"), e.get("symbol"),
                 e.get("detail", "")) for e in merged.entries}
        fresh = [f for f in report.findings if f.key() not in have]
        merged.entries += Baseline.from_findings(fresh).entries
        merged.save(baseline_path)
        print(f"wrote {len(fresh)} new finding(s) to {baseline_path} "
              f"({len(report.findings) - len(fresh)} already present); "
              f"add justifications to activate them", file=sys.stderr)
        return 0

    # surface the outcome in the unified metrics snapshot
    # (docs/OBSERVABILITY.md): obs imports stdlib only — still jax-free
    from mdanalysis_mpi_tpu.obs.metrics import METRICS

    METRICS.set_gauge("mdtpu_lint_rules", len(report.rules))
    METRICS.set_gauge("mdtpu_lint_findings", len(report.findings))

    if ns.as_json:
        doc = report.to_json()
        doc["jax_imported"] = "jax" in sys.modules
        doc["baseline"] = baseline_path
        print(json.dumps(doc))
    else:
        for f in report.findings:
            print(f.render())
        for note in report.notes:
            print(f"note: {note}", file=sys.stderr)
        print(f"{len(report.findings)} finding(s), "
              f"{len(report.baselined)} baselined, "
              f"{report.suppressed} pragma-suppressed, "
              f"{report.files} files, {len(report.rules)} rules",
              file=sys.stderr)
    return 0 if report.clean else 1
