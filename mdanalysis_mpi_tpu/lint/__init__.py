"""``mdtpu lint`` — repo-native static analysis (docs/LINT.md).

Three of the last four PRs shipped hand-found bugs from the same
recurring classes: unlocked shared-state read-modify-writes (the PR-5
``PhaseTimers`` race, the PR-4 ``DeviceBlockCache`` double-delete),
condition-variable misuse (the PR-7 ``submit()`` lost-wakeup via
``notify()``), swallowed ``BaseException`` control flow (the
``WorkerFenced``/``InjectedWorkerDeath`` fencing channel), and jaxpr
invariants (one psum per mesh scan) that only runtime tests pinned.
This package encodes those conventions as named rules so the classes
are caught at review time instead of in chaos suites:

- :mod:`~mdanalysis_mpi_tpu.lint.concurrency` — MDT0xx: lock
  discipline, condition-variable wakeups, fencing-exception flow,
  thread daemon/join hygiene.  Pure stdlib :mod:`ast`.
- :mod:`~mdanalysis_mpi_tpu.lint.persistence` — MDT005: non-atomic
  artifact writes in the persistence modules (the tmp→fsync→rename
  convention of docs/RELIABILITY.md §5).  Pure stdlib :mod:`ast`.
- :mod:`~mdanalysis_mpi_tpu.lint.jaxcontracts` — MDT1xx: host side
  effects inside jit/shard_map/scan-traced code (AST call-graph walk),
  plus lowering-based jaxpr contracts (one psum per mesh scan,
  captured-constant byte budget) that need jax.
- :mod:`~mdanalysis_mpi_tpu.lint.schema` — MDT2xx: schema drift
  between the metric/span names the code records, the pinned schema in
  ``tests/test_bench_contract.py``, and the catalog in
  ``docs/OBSERVABILITY.md``.

Entry points: ``python -m mdanalysis_mpi_tpu lint`` (CLI — rule ids,
JSON output, baseline suppression), :func:`run_lint` (library), and
the tree-wide self-check in ``tests/test_lint.py`` (``lint`` pytest
marker, tier-1).  The AST and schema passes import stdlib only, so the
default (fast) mode runs before any jax import.
"""

from mdanalysis_mpi_tpu.lint.core import (
    Baseline, Finding, LintReport, Rule, all_rules, iter_python_files,
    rule_ids, run_lint,
)

__all__ = [
    "Baseline", "Finding", "LintReport", "Rule", "all_rules",
    "iter_python_files", "rule_ids", "run_lint",
]
