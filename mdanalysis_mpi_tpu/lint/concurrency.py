"""Concurrency-discipline rules (MDT0xx) — stdlib ``ast`` only.

These encode the repo's own locking conventions, each distilled from a
bug that actually shipped:

- **MDT001 unlocked-shared-state** — the PR-5 ``PhaseTimers`` race and
  the PR-4 ``DeviceBlockCache`` double-delete were both unguarded
  read-modify-writes of state that *other* methods mutate under
  ``self._lock``.  The rule self-registers: any attribute a class
  mutates inside a ``with self.<lock>`` block is "shared", and any
  mutation of a shared attribute outside such a block (outside
  ``__init__``) is flagged.  Helpers whose callers hold the lock
  follow the scheduler's existing ``*_locked`` naming convention and
  are exempt.
- **MDT002 notify-with-multiple-waiters** — the PR-7 lost-wakeup:
  ``Scheduler.submit`` used ``notify()`` on a condition that the
  supervisor and prefetch threads also wait on, so the single wakeup
  could land on a non-worker and the submission sat unclaimed forever.
  The rule flags ``.notify()`` on any condition with two or more
  distinct in-class wait sites.
- **MDT003 fencing-swallow** — worker fencing (``WorkerFenced``,
  ``InjectedWorkerDeath``) is BaseException-based precisely so
  ``except Exception`` passes it through; a bare ``except:`` or
  ``except BaseException:`` that neither re-raises nor explicitly
  discriminates the fencing types would swallow the control flow.
  Scoped to ``service/`` and ``reliability/``, where fencing lives.
- **MDT004 thread-daemon-discipline** — every ``threading.Thread``
  the package creates must say ``daemon=`` explicitly: a non-daemon
  thread that is never joined hangs interpreter exit, and an
  accidental default has to be a decision, not an omission.
"""

from __future__ import annotations

import ast

from mdanalysis_mpi_tpu.lint.core import Finding, Rule, register

register(Rule(
    "MDT001", "unlocked-shared-state", "concurrency",
    "mutation of a lock-guarded attribute outside `with self.<lock>`",
    "PR-5: PhaseTimers' unguarded dict RMW lost updates under the "
    "serving worker pool; PR-4: DeviceBlockCache racing same-key puts "
    "double-deleted a device buffer"))
register(Rule(
    "MDT002", "notify-with-multiple-waiters", "concurrency",
    "notify() on a condition with >=2 distinct wait sites",
    "PR-7: Scheduler.submit's notify() could wake the supervisor "
    "instead of a worker - the submission sat unclaimed forever "
    "(intermittent drain-timeout hangs)"))
register(Rule(
    "MDT003", "fencing-swallow", "concurrency",
    "bare except/except BaseException in service|reliability without "
    "re-raise or fencing-type discrimination",
    "WorkerFenced/InjectedWorkerDeath are BaseExceptions so `except "
    "Exception` passes them through; a blanket BaseException handler "
    "silently eats the fencing channel"))
register(Rule(
    "MDT004", "thread-daemon-discipline", "concurrency",
    "threading.Thread(...) without an explicit daemon= argument",
    "supervision threads (PR-7) must not block interpreter exit; "
    "daemon-ness is a per-thread decision the code must state"))

#: Constructors whose assignment to ``self.<attr>`` makes that
#: attribute a lock for MDT001/MDT002 purposes.
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_COND_CTORS = {"Condition"}

#: Method calls that mutate their receiver (list/dict/set/deque API) —
#: counted as writes for MDT001 registration and flagging.
_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popleft", "popitem", "remove", "setdefault",
    "update",
}

#: Methods allowed to touch shared attributes unlocked: construction
#: (no concurrent observer exists yet) and the caller-holds-lock
#: naming convention the scheduler already uses (``*_locked``).
_EXEMPT_METHODS = {"__init__", "__new__"}

_FENCING_NAMES = {"WorkerFenced", "InjectedWorkerDeath"}


def _ctor_name(call: ast.AST) -> str | None:
    """'Lock' for ``threading.Lock()`` / ``Lock()`` / ``x.RLock()``."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _iter_methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _MethodScan(ast.NodeVisitor):
    """One method's attribute-write events with lock context.

    Nested function/lambda bodies are skipped on both sides of the
    rule (registration and flagging): a closure created under the lock
    may legally run much later without it, and guessing would produce
    noise in either direction.
    """

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.depth = 0
        # [(attr, locked, line, kind)]
        self.events: list[tuple] = []
        self.wait_sites: list[tuple] = []    # (cond_attr, line)
        self.notify_sites: list[tuple] = []  # (cond_attr, line, name)

    # -- lock context --

    def visit_With(self, node: ast.With) -> None:
        locked = any(_self_attr(item.context_expr) in self.lock_attrs
                     for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if locked:
            self.depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self.depth -= 1

    def _skip(self, node) -> None:      # nested defs: out of scope
        pass

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _skip

    # -- writes --

    def _note_target(self, target: ast.AST, line: int) -> None:
        attr = _self_attr(target)
        if attr is not None:
            self.events.append((attr, self.depth > 0, line, "assign"))
        elif isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None:
                self.events.append((attr, self.depth > 0, line, "item"))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._note_target(elt, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_target(t, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._note_target(t, node.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv_attr = _self_attr(fn.value)
            if recv_attr is not None and fn.attr in _MUTATORS:
                self.events.append((recv_attr, self.depth > 0,
                                    node.lineno, "mutcall"))
            if recv_attr is not None and fn.attr in ("wait", "wait_for"):
                self.wait_sites.append((recv_attr, node.lineno))
            if recv_attr is not None and fn.attr == "notify":
                self.notify_sites.append((recv_attr, node.lineno))
        self.generic_visit(node)


def _check_class(cls: ast.ClassDef, rel: str,
                 findings: list[Finding]) -> None:
    lock_attrs: set[str] = set()
    cond_attrs: set[str] = set()
    for method in _iter_methods(cls):
        for node in ast.walk(method):
            if isinstance(node, ast.Assign):
                name = _ctor_name(node.value)
                if name in _LOCK_CTORS:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            lock_attrs.add(attr)
                            if name in _COND_CTORS:
                                cond_attrs.add(attr)

    if not lock_attrs:
        return

    scans: dict[str, _MethodScan] = {}
    for method in _iter_methods(cls):
        scan = _MethodScan(lock_attrs)
        for stmt in method.body:
            scan.visit(stmt)
        scans[method.name] = scan

    # MDT001: registration, then flag unlocked writes of shared attrs
    shared = {attr for scan in scans.values()
              for (attr, locked, _, _) in scan.events if locked}
    shared -= lock_attrs
    for mname, scan in scans.items():
        if mname in _EXEMPT_METHODS or mname.endswith("_locked"):
            continue
        seen: set[tuple] = set()
        for (attr, locked, line, kind) in scan.events:
            if locked or attr not in shared:
                continue
            key = (attr, line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "MDT001", rel, line, f"{cls.name}.{mname}",
                f"`self.{attr}` is mutated under a lock elsewhere in "
                f"{cls.name} but written here without `with "
                f"self.<lock>` (helpers relying on a caller-held lock "
                f"must end in `_locked`)",
                detail=attr))

    # MDT002: notify() while >=2 distinct in-class wait sites exist
    for cond in cond_attrs:
        waits = [(m, ln) for m, s in scans.items()
                 for (a, ln) in s.wait_sites if a == cond]
        if len(waits) < 2:
            continue
        for mname, scan in scans.items():
            for (a, ln) in scan.notify_sites:
                if a != cond:
                    continue
                findings.append(Finding(
                    "MDT002", rel, ln, f"{cls.name}.{mname}",
                    f"`self.{cond}.notify()` with {len(waits)} distinct "
                    f"wait sites in {cls.name} "
                    f"({', '.join(sorted({m for m, _ in waits}))}): a "
                    f"single wakeup can land on the wrong waiter — use "
                    f"notify_all()",
                    detail=cond))


def _catches_base_exception(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (handler.type.elts
             if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    for t in types:
        name = t.id if isinstance(t, ast.Name) else (
            t.attr if isinstance(t, ast.Attribute) else None)
        if name == "BaseException":
            return True
    return False


def _handler_is_fencing_aware(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id in _FENCING_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _FENCING_NAMES:
            return True
    return False


def _enclosing_symbol(tree: ast.Module, target: ast.AST) -> str:
    """Dotted class/function scope containing ``target`` (best effort)."""
    path: list[str] = []

    def walk(node, trail):
        for child in ast.iter_child_nodes(node):
            if child is target:
                path.extend(trail)
                return True
            name = getattr(child, "name", None) if isinstance(
                child, (ast.ClassDef, ast.FunctionDef,
                        ast.AsyncFunctionDef)) else None
            if walk(child, trail + ([name] if name else [])):
                return True
        return False

    walk(tree, [])
    return ".".join(path) or "<module>"


def check_module(tree: ast.Module, rel: str) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _check_class(node, rel, findings)

    in_fencing_scope = ("/service/" in f"/{rel}"
                        or "/reliability/" in f"/{rel}")
    for node in ast.walk(tree):
        if (in_fencing_scope and isinstance(node, ast.ExceptHandler)
                and _catches_base_exception(node)
                and not _handler_is_fencing_aware(node)):
            findings.append(Finding(
                "MDT003", rel, node.lineno,
                _enclosing_symbol(tree, node),
                "handler catches BaseException (or everything) without "
                "re-raising or discriminating WorkerFenced/"
                "InjectedWorkerDeath — it would swallow worker fencing",
                detail=f"line-scope:{_enclosing_symbol(tree, node)}"))
        if isinstance(node, ast.Call):
            fn = node.func
            is_thread = ((isinstance(fn, ast.Name) and fn.id == "Thread")
                         or (isinstance(fn, ast.Attribute)
                             and fn.attr == "Thread"))
            if is_thread and not any(kw.arg == "daemon"
                                     for kw in node.keywords):
                findings.append(Finding(
                    "MDT004", rel, node.lineno,
                    _enclosing_symbol(tree, node),
                    "threading.Thread(...) without an explicit daemon= "
                    "— state the join/daemon decision",
                    detail="Thread"))
    return findings
