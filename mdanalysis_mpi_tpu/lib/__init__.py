"""Library-level helpers mirroring the upstream ``MDAnalysis.lib``
surface the reference's capability envelope touches (SURVEY.md §2.2:
``lib.distances``/``c_distances``; ``lib.qcprot`` is covered by
:mod:`mdanalysis_mpi_tpu.ops.align`/:mod:`~mdanalysis_mpi_tpu.ops.host`)."""

from mdanalysis_mpi_tpu.lib import (correlations, distances, mdamath,
                                    neighborsearch, transformations)
from mdanalysis_mpi_tpu.lib.neighborsearch import AtomNeighborSearch

__all__ = ["correlations", "distances", "mdamath", "neighborsearch",
           "transformations", "AtomNeighborSearch"]
