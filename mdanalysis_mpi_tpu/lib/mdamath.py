"""Math helpers mirroring upstream ``MDAnalysis.lib.mdamath``.

Thin, NumPy-only veneers over the framework's own primitives (the box
math lives in :mod:`mdanalysis_mpi_tpu.core.box`, the dihedral
convention in :mod:`mdanalysis_mpi_tpu.ops.dihedrals`), so migrating
code that imports ``lib.mdamath`` keeps working.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.box import box_to_vectors, vectors_to_box


def norm(v) -> float:
    """Euclidean norm of one vector."""
    v = np.asarray(v, dtype=np.float64)
    return float(np.sqrt((v * v).sum()))


def normal(vec1, vec2) -> np.ndarray:
    """Unit normal of the plane spanned by two vectors (zero vector when
    they are parallel, upstream behavior)."""
    n = np.cross(np.asarray(vec1, np.float64), np.asarray(vec2, np.float64))
    length = norm(n)
    if length == 0.0:
        return n
    return n / length


def angle(a, b) -> float:
    """Angle between two VECTORS in radians (upstream ``mdamath.angle``)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    den = norm(a) * norm(b)
    if den == 0.0:
        raise ValueError("cannot compute the angle of a zero vector")
    return float(np.arccos(np.clip((a * b).sum() / den, -1.0, 1.0)))


def dihedral(ab, bc, cd) -> float:
    """Dihedral from three consecutive BOND VECTORS, radians, IUPAC sign
    (the ops.dihedrals convention)."""
    b1 = np.asarray(ab, np.float64)
    b2 = np.asarray(bc, np.float64)
    b3 = np.asarray(cd, np.float64)
    n1 = np.cross(b1, b2)
    n2 = np.cross(b2, b3)
    b2n = b2 / max(norm(b2), 1e-300)
    x = (n1 * n2).sum()
    y = (np.cross(n1, n2) * b2n).sum()
    return float(np.arctan2(y, x))


def triclinic_vectors(dimensions) -> np.ndarray:
    """``[lx, ly, lz, alpha, beta, gamma]`` → (3, 3) box matrix (Å),
    float32 like upstream."""
    return box_to_vectors(np.asarray(dimensions)).astype(np.float32)


def triclinic_box(x, y, z) -> np.ndarray:
    """Three box vectors → ``[lx, ly, lz, alpha, beta, gamma]``."""
    return vectors_to_box(np.stack([np.asarray(x), np.asarray(y),
                                    np.asarray(z)]))


def box_volume(dimensions) -> float:
    """Cell volume (Å³) from ``[lx, ly, lz, alpha, beta, gamma]``."""
    return float(abs(np.linalg.det(
        box_to_vectors(np.asarray(dimensions, np.float64)))))


def make_whole(atomgroup, inplace: bool = True) -> np.ndarray:
    """Make a bonded group whole across periodic boundaries at the
    CURRENT frame (upstream ``lib.mdamath.make_whole``): every atom
    moves to the minimum-image position relative to its bond-tree
    parent.  Requires bonds (PSF or ``guess_bonds``) and a box on the
    frame.  Returns the whole positions; ``inplace=True`` (upstream
    default) also writes them back to the Timestep.

    One-shot form of ``transformations.unwrap`` — attach that to the
    trajectory instead when every frame needs it (the bond tree is
    then built once, not per call).
    """
    from mdanalysis_mpi_tpu.lib.distances import _valid_box_matrix
    from mdanalysis_mpi_tpu.transformations import unwrap

    u = atomgroup.universe
    ts = u.trajectory.ts
    # strict validation: a partially degenerate box ([10, 0, 0, ...])
    # would sail past an any(length > 0) check and write NaNs back
    _valid_box_matrix(ts.dimensions, "make_whole")
    t = unwrap(atomgroup)
    if inplace:
        t(ts)
        return ts.positions[atomgroup.indices]
    saved = ts.positions.copy()
    try:
        t(ts)
        return ts.positions[atomgroup.indices].copy()
    finally:
        ts.positions = saved
