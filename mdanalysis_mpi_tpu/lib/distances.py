"""Distance functions mirroring ``MDAnalysis.lib.distances`` (the
C/Cython layer in upstream — SURVEY.md §2.2; BASELINE config 5 names
``distances.self_distance_array``).

``backend="numpy"`` (default) runs the float64 host oracle;
``backend="jax"`` dispatches to the device kernels (f32) — worthwhile
for repeated large calls, not single small ones.
"""

from __future__ import annotations

import numpy as np


def _dims_of(box):
    if box is None:
        return None
    box = np.asarray(box, dtype=np.float64)
    if box.shape == (6,):
        return box
    if box.shape == (3,):
        return np.concatenate([box, [90.0, 90.0, 90.0]])
    raise ValueError(f"box must be (3,) lengths or (6,) dimensions, got {box.shape}")


def distance_array(reference, configuration, box=None,
                   backend: str = "numpy") -> np.ndarray:
    """(N, M) pair distances between two coordinate sets."""
    a = np.asarray(reference, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(configuration, dtype=np.float64).reshape(-1, 3)
    dims = _dims_of(box)
    if backend == "numpy":
        from mdanalysis_mpi_tpu.ops import host

        return host.distance_array(a, b, dims)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        return np.asarray(d.distance_array(
            jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32)),
            dtype=np.float64)
    raise ValueError(f"backend must be 'numpy' or 'jax', got {backend!r}")


def self_distance_array(reference, box=None,
                        backend: str = "numpy") -> np.ndarray:
    """Condensed upper-triangle distances, length N(N-1)/2, in upstream's
    (i<j) row-major order (BASELINE config 5)."""
    a = np.asarray(reference, dtype=np.float64).reshape(-1, 3)
    dims = _dims_of(box)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        # condensed on device — one small fetch instead of the full matrix
        return np.asarray(d.self_distance_array(
            jnp.asarray(a, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32)),
            dtype=np.float64)
    d = distance_array(a, a, box=box, backend=backend)
    iu, ju = np.triu_indices(len(a), k=1)
    return d[iu, ju]


def calc_bonds(coords1, coords2, box=None, backend: str = "numpy") -> np.ndarray:
    """Pairwise (row-wise) distances between two equal-length sets."""
    if backend not in ("numpy", "jax"):
        raise ValueError(f"backend must be 'numpy' or 'jax', got {backend!r}")
    a = np.asarray(coords1, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(coords2, dtype=np.float64).reshape(-1, 3)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    dims = _dims_of(box)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        disp = d.minimum_image(
            jnp.asarray(a - b, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32))
        return np.asarray(jnp.sqrt((disp ** 2).sum(-1)), dtype=np.float64)
    from mdanalysis_mpi_tpu.ops import host

    disp = host.minimum_image(a - b, dims)
    return np.sqrt((disp ** 2).sum(-1))


def apply_PBC(coords, box) -> np.ndarray:
    """Map coordinates into the primary unit cell (upstream
    ``lib.distances.apply_PBC``); float32 out like upstream."""
    from mdanalysis_mpi_tpu.core.box import box_to_vectors, wrap_positions

    dims = _dims_of(box)
    if dims is None:
        raise ValueError("apply_PBC needs a box")
    m = box_to_vectors(np.asarray(dims, np.float64))
    return wrap_positions(
        np.asarray(coords, np.float64).reshape(-1, 3), m).astype(np.float32)


def calc_angles(coords1, coords2, coords3, box=None) -> np.ndarray:
    """Angle at the APEX ``coords2`` of each (a, b, c) triple, in
    RADIANS (upstream ``lib.distances.calc_angles``); minimum-image
    displacements under ``box``."""
    from mdanalysis_mpi_tpu.ops import host

    a = np.asarray(coords1, np.float64).reshape(-1, 3)
    b = np.asarray(coords2, np.float64).reshape(-1, 3)
    c = np.asarray(coords3, np.float64).reshape(-1, 3)
    if not (a.shape == b.shape == c.shape):
        raise ValueError(
            f"shape mismatch {a.shape} vs {b.shape} vs {c.shape}")
    dims = _dims_of(box)
    u = host.minimum_image(a - b, dims)
    v = host.minimum_image(c - b, dims)
    num = (u * v).sum(-1)
    den = np.sqrt((u ** 2).sum(-1) * (v ** 2).sum(-1))
    return np.arccos(np.clip(num / np.maximum(den, 1e-300), -1.0, 1.0))


def calc_dihedrals(coords1, coords2, coords3, coords4, box=None) -> np.ndarray:
    """Dihedral of each (a, b, c, d) quadruple in RADIANS, IUPAC sign
    (upstream ``lib.distances.calc_dihedrals``) — the same convention as
    :mod:`mdanalysis_mpi_tpu.ops.dihedrals`; minimum-image under
    ``box``."""
    from mdanalysis_mpi_tpu.ops import host

    p = [np.asarray(x, np.float64).reshape(-1, 3)
         for x in (coords1, coords2, coords3, coords4)]
    if not all(x.shape == p[0].shape for x in p):
        raise ValueError(
            f"shape mismatch: {[x.shape for x in p]}")
    dims = _dims_of(box)
    b1 = host.minimum_image(p[1] - p[0], dims)
    b2 = host.minimum_image(p[2] - p[1], dims)
    b3 = host.minimum_image(p[3] - p[2], dims)
    n1 = np.cross(b1, b2)
    n2 = np.cross(b2, b3)
    b2n = b2 / np.maximum(
        np.linalg.norm(b2, axis=-1, keepdims=True), 1e-300)
    x = (n1 * n2).sum(-1)
    y = (np.cross(n1, n2) * b2n).sum(-1)
    return np.arctan2(y, x)


def contact_matrix(coords, cutoff: float = 15.0, box=None,
                   backend: str = "numpy") -> np.ndarray:
    """Boolean (N, N) contact map at ``cutoff`` (BASELINE config 5)."""
    a = np.asarray(coords, dtype=np.float64).reshape(-1, 3)
    return distance_array(a, a, box=box, backend=backend) < cutoff


#: ``engine="auto"`` switches from brute force to the cell list above
#: this many candidate pairs (N·M); below it the vectorized brute block
#: is faster than grid bookkeeping
AUTO_GRID_MIN_PAIRS = 16_384
#: ...and only when the grid actually prunes: with fewer total cells
#: than ~2× the 27-stencil, nearly every atom is a candidate anyway
AUTO_GRID_MIN_CELLS = 54


def capped_distance(reference, configuration, max_cutoff: float,
                    min_cutoff: float | None = None, box=None,
                    return_distances: bool = True, engine: str = "auto",
                    _self_upper=False):
    """Pairs within ``max_cutoff`` (upstream ``lib.distances
    .capped_distance``): returns ``(pairs, distances)`` — pairs is
    (K, 2) int ``[i_reference, j_configuration]`` lexsorted by (i, j)
    — or just ``pairs`` with ``return_distances=False``.

    ``engine`` selects the pair-pruning backend (the knob mirrors
    ``InterRDF``'s):

    - ``"auto"`` (default): the O(N) cell list (``lib.nsgrid``) when
      the query is large enough to profit and the grid applies;
      otherwise brute force.  Every capped-radius consumer
      (``guess_bonds``, LeafletFinder, HBond pruning,
      AtomNeighborSearch) routes through here, so auto is the
      one default that turns them ~linear at scale.
    - ``"nsgrid"``: force the cell list; raises ``ValueError`` when the
      grid cannot serve the query (degenerate box, or cutoff so large
      that fewer than 3 cells fit per axis).
    - ``"bruteforce"``: the documented fallback — blockwise over the
      reference axis so the full N×M matrix never materializes (the
      same discipline as the device pair kernels, SURVEY.md §5.7).
    - ``"jax"``: the fixed-capacity device cell list
      (``ops.neighbors``) — worthwhile for repeated large queries on an
      accelerator, not single host calls.

    The host engines (auto/nsgrid/bruteforce) emit IDENTICAL (pairs,
    distances) in identical order; the device engine computes in f32,
    so a pair whose true distance lies within f32 rounding of the
    cutoff (~1e-5 Å at solvated-box coordinates) can flip across it —
    same order and identity otherwise.  Minimum image under ``box``
    when given.  ``_self_upper`` (internal,
    :func:`self_capped_distance`) keeps only j > i pairs.
    """
    a = np.asarray(reference, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(configuration, dtype=np.float64).reshape(-1, 3)
    if max_cutoff <= 0:
        raise ValueError(f"max_cutoff must be positive, got {max_cutoff}")
    if min_cutoff is not None and min_cutoff >= max_cutoff:
        raise ValueError(
            f"min_cutoff {min_cutoff} must be below max_cutoff {max_cutoff}")
    if engine not in ("auto", "nsgrid", "bruteforce", "jax"):
        raise ValueError(
            "engine must be 'auto', 'nsgrid', 'bruteforce' or 'jax', "
            f"got {engine!r}")
    dims = _dims_of(box)

    if engine == "jax":
        from mdanalysis_mpi_tpu.ops import neighbors

        return neighbors.capped_distance(
            a, b, max_cutoff, min_cutoff=min_cutoff, dims=dims,
            return_distances=return_distances, self_upper=_self_upper)
    if engine in ("auto", "nsgrid"):
        from mdanalysis_mpi_tpu.lib import nsgrid

        try:
            plan = None
            if engine == "auto":
                if len(a) * len(b) < AUTO_GRID_MIN_PAIRS:
                    raise nsgrid.GridUnsuitable("query too small to profit")
                if len(a) and len(b):
                    plan = nsgrid.make_plan(a, b, max_cutoff, dims)
                    if int(np.prod(plan[0])) < AUTO_GRID_MIN_CELLS:
                        raise nsgrid.GridUnsuitable(
                            "grid prunes too little")
            return nsgrid.capped_pairs(
                a, b, max_cutoff, min_cutoff=min_cutoff, dims=dims,
                return_distances=return_distances,
                self_upper=_self_upper, plan=plan)
        except nsgrid.GridUnsuitable as e:
            if engine == "nsgrid":
                raise ValueError(
                    f"engine='nsgrid' cannot serve this query: {e}"
                ) from e
            # auto: documented brute-force fallback below

    from mdanalysis_mpi_tpu.ops import host

    pairs_i, pairs_j, dists = [], [], []
    # element budget sized for the REAL peak: disp (block·M·3 f64) plus
    # minimum_image's copies of the same shape and d2 — ~9 arrays of
    # block·M elements ≈ 160 MB at this setting
    block = max(1, int(2.2e6) // max(1, len(b)))
    c2 = float(max_cutoff) ** 2
    m2 = None if min_cutoff is None else float(min_cutoff) ** 2
    for lo in range(0, len(a), block):
        chunk = a[lo:lo + block]
        disp = chunk[:, None, :] - b[None, :, :]
        disp = host.minimum_image(disp, dims)
        d2 = np.einsum("abi,abi->ab", disp, disp)
        hit = d2 <= c2
        if m2 is not None:
            hit &= d2 > m2
        if _self_upper:
            hit &= (np.arange(len(b))[None, :]
                    > np.arange(lo, lo + len(chunk))[:, None])
        ii, jj = np.nonzero(hit)
        pairs_i.append(ii + lo)
        pairs_j.append(jj)
        if return_distances:
            dists.append(np.sqrt(d2[ii, jj]))
    pairs = np.stack([np.concatenate(pairs_i) if pairs_i else np.empty(0, np.int64),
                      np.concatenate(pairs_j) if pairs_j else np.empty(0, np.int64)],
                     axis=1)
    if return_distances:
        d = (np.concatenate(dists) if dists else np.empty(0))
        return pairs, d
    return pairs


def self_capped_distance(reference, max_cutoff: float,
                         min_cutoff: float | None = None, box=None,
                         return_distances: bool = True,
                         engine: str = "auto"):
    """Unique i<j pairs within ``max_cutoff`` of each other (upstream
    ``lib.distances.self_capped_distance``); ``engine`` as in
    :func:`capped_distance`."""
    return capped_distance(reference, reference, max_cutoff,
                           min_cutoff=min_cutoff, box=box,
                           return_distances=return_distances,
                           engine=engine, _self_upper=True)


def minimize_vectors(vectors, box) -> np.ndarray:
    """Apply the minimum-image convention to arbitrary displacement
    vectors (upstream ``lib.distances.minimize_vectors``): each vector
    is replaced by its TRUE shortest periodic image.

    For skewed triclinic cells the fractional round-to-nearest used by
    the hot-path kernels (ops/host.py ``minimum_image`` — the standard
    single-shift MD compromise) is not always minimal; this public
    utility finishes the job with a 27-neighbor lattice search, which
    is exact for any valid triclinic cell."""
    from mdanalysis_mpi_tpu.ops.host import minimum_image

    m = _valid_box_matrix(box, "minimize_vectors")   # refuses degenerate
    dims = np.asarray(_dims_of(box), np.float64)
    v = np.asarray(vectors, np.float64)
    base = minimum_image(v, dims)
    if np.all(np.abs(dims[3:] - 90.0) < 1e-4):
        return base.astype(np.float32)       # orthorhombic: exact already
    flat = base.reshape(-1, 3)
    shifts = np.array([(i, j, k) for i in (-1, 0, 1)
                       for j in (-1, 0, 1)
                       for k in (-1, 0, 1)], np.float64) @ m   # (27, 3)
    cand = flat[:, None, :] + shifts[None]                     # (n, 27, 3)
    best = cand[np.arange(len(flat)),
                (cand ** 2).sum(-1).argmin(axis=1)]
    return best.reshape(base.shape).astype(np.float32)


def _valid_box_matrix(box, who: str) -> np.ndarray:
    """Shared strict validation (core.box.valid_box_matrix), applied
    to this module's box argument convention (_dims_of)."""
    from mdanalysis_mpi_tpu.core.box import valid_box_matrix

    return valid_box_matrix(_dims_of(box), who)


def transform_RtoS(coords, box) -> np.ndarray:
    """Real → fractional (scaled) coordinates (upstream
    ``lib.distances.transform_RtoS``)."""
    m = _valid_box_matrix(box, "transform_RtoS")
    return (np.asarray(coords, np.float64) @ np.linalg.inv(m)).astype(
        np.float32)


def transform_StoR(coords, box) -> np.ndarray:
    """Fractional (scaled) → real coordinates (upstream
    ``lib.distances.transform_StoR``)."""
    m = _valid_box_matrix(box, "transform_StoR")
    return (np.asarray(coords, np.float64) @ m).astype(np.float32)
