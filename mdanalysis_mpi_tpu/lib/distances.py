"""Distance functions mirroring ``MDAnalysis.lib.distances`` (the
C/Cython layer in upstream — SURVEY.md §2.2; BASELINE config 5 names
``distances.self_distance_array``).

``backend="numpy"`` (default) runs the float64 host oracle;
``backend="jax"`` dispatches to the device kernels (f32) — worthwhile
for repeated large calls, not single small ones.
"""

from __future__ import annotations

import numpy as np


def _dims_of(box):
    if box is None:
        return None
    box = np.asarray(box, dtype=np.float64)
    if box.shape == (6,):
        return box
    if box.shape == (3,):
        return np.concatenate([box, [90.0, 90.0, 90.0]])
    raise ValueError(f"box must be (3,) lengths or (6,) dimensions, got {box.shape}")


def distance_array(reference, configuration, box=None,
                   backend: str = "numpy") -> np.ndarray:
    """(N, M) pair distances between two coordinate sets."""
    a = np.asarray(reference, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(configuration, dtype=np.float64).reshape(-1, 3)
    dims = _dims_of(box)
    if backend == "numpy":
        from mdanalysis_mpi_tpu.ops import host

        return host.distance_array(a, b, dims)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        return np.asarray(d.distance_array(
            jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32)),
            dtype=np.float64)
    raise ValueError(f"backend must be 'numpy' or 'jax', got {backend!r}")


def self_distance_array(reference, box=None,
                        backend: str = "numpy") -> np.ndarray:
    """Condensed upper-triangle distances, length N(N-1)/2, in upstream's
    (i<j) row-major order (BASELINE config 5)."""
    a = np.asarray(reference, dtype=np.float64).reshape(-1, 3)
    dims = _dims_of(box)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        # condensed on device — one small fetch instead of the full matrix
        return np.asarray(d.self_distance_array(
            jnp.asarray(a, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32)),
            dtype=np.float64)
    d = distance_array(a, a, box=box, backend=backend)
    iu, ju = np.triu_indices(len(a), k=1)
    return d[iu, ju]


def calc_bonds(coords1, coords2, box=None, backend: str = "numpy") -> np.ndarray:
    """Pairwise (row-wise) distances between two equal-length sets."""
    if backend not in ("numpy", "jax"):
        raise ValueError(f"backend must be 'numpy' or 'jax', got {backend!r}")
    a = np.asarray(coords1, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(coords2, dtype=np.float64).reshape(-1, 3)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    dims = _dims_of(box)
    if backend == "jax":
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops import distances as d

        disp = d.minimum_image(
            jnp.asarray(a - b, jnp.float32),
            None if dims is None else jnp.asarray(dims, jnp.float32))
        return np.asarray(jnp.sqrt((disp ** 2).sum(-1)), dtype=np.float64)
    from mdanalysis_mpi_tpu.ops import host

    disp = host.minimum_image(a - b, dims)
    return np.sqrt((disp ** 2).sum(-1))


def contact_matrix(coords, cutoff: float = 15.0, box=None,
                   backend: str = "numpy") -> np.ndarray:
    """Boolean (N, N) contact map at ``cutoff`` (BASELINE config 5)."""
    a = np.asarray(coords, dtype=np.float64).reshape(-1, 3)
    return distance_array(a, a, box=box, backend=backend) < cutoff
