"""Cell-list (grid) neighbor search — the O(N) engine behind
``lib.distances.capped_distance`` (upstream ``lib.nsgrid``, the
dependency-closure component VERDICT r5 named as the one algorithmic
regression: the brute-force path is O(N·M), ~10¹⁰ distance evaluations
for one 100k-atom capped query).

Algorithm (the standard trajectory-analysis formulation, arXiv
1907.00097; the fixed-capacity JAX twin lives in ``ops.neighbors``):

- bin atoms into cells whose edge is ≥ ``max_cutoff`` — in FRACTIONAL
  coordinates of the box matrix (``core.box.box_to_vectors``), so ortho
  and triclinic boxes share one code path.  The fractional cell count
  per axis is ``floor(d_k / cutoff)`` where ``d_k`` is the cell's
  perpendicular width along axis k (V / |cross of the other two
  vectors|) — the brick-shape bound that makes the 27-stencil
  sufficient for arbitrary triclinic skew;
- enumerate the 27-neighbor stencil per reference atom (periodic wrap
  for boxed systems; clipped at the grid edge for boxless ones) and
  gather candidates through one argsort + searchsorted per stencil
  offset — 27 fully vectorized passes, no Python loop over atoms;
- compute candidate distances with the SAME ``ops.host.minimum_image``
  metric as the brute-force path and apply the same cutoff tests, so
  the emitted (pairs, distances) sets are IDENTICAL — the grid only
  prunes, it never decides.  Output is lexsorted by (i, j), the
  brute-force path's natural order, so consumers see one ordering
  regardless of engine.

When the grid cannot apply — no/degenerate box with pathological
extents, or a cutoff so large that fewer than 3 cells fit per axis
(the 27-stencil would alias through the periodic wrap) —
:class:`GridUnsuitable` is raised and the dispatcher in
``lib.distances`` falls back to the documented brute-force path.
"""

from __future__ import annotations

import numpy as np

#: grid resolution cap per axis: finer than this adds bookkeeping, not
#: pruning (a 64³ grid already has ≤ N/262k atoms per cell at any N
#: this host path will see)
MAX_CELLS_PER_AXIS = 64

#: the 27-neighbor stencil, (27, 3) int
_STENCIL = np.array([(i, j, k) for i in (-1, 0, 1)
                     for j in (-1, 0, 1)
                     for k in (-1, 0, 1)], dtype=np.int64)


class GridUnsuitable(Exception):
    """The cell list cannot (correctly or profitably) serve this query;
    callers fall back to the brute-force path."""


def _perpendicular_widths(m: np.ndarray) -> np.ndarray:
    """Perpendicular width of the cell along each lattice axis: the
    real-space distance a fractional step of 1 covers orthogonally to
    the other two vectors — V / |cross of the other two|.  A sphere of
    radius R spans R / d_k fractional units along axis k."""
    vol = abs(np.linalg.det(m))
    crosses = np.array([np.linalg.norm(np.cross(m[1], m[2])),
                        np.linalg.norm(np.cross(m[2], m[0])),
                        np.linalg.norm(np.cross(m[0], m[1]))])
    return vol / np.maximum(crosses, 1e-300)


def _plan_periodic(max_cutoff, dims):
    """(ncell, cells_fn, periodic=True) for a full valid box; the
    per-atom binning runs only when ``cells_fn`` is called."""
    from mdanalysis_mpi_tpu.core.box import box_to_vectors

    m = box_to_vectors(np.asarray(dims, np.float64))
    if not np.isfinite(m).all() or abs(np.linalg.det(m)) < 1e-12:
        raise GridUnsuitable(
            f"box {np.asarray(dims)[:6].tolist()} has no volume")
    widths = _perpendicular_widths(m)
    ratio = widths / max_cutoff
    # +1e-8: box_to_vectors introduces ~1e-16-relative noise (cos of an
    # exact 90° angle is 6e-17, not 0), which would push an exact
    # integer ratio just below its floor
    ncell = np.floor(ratio + 1e-8).astype(np.int64)
    # An atom EXACTLY on a cell boundary snaps to either side under fp
    # noise, so a pair at distance exactly cutoff can bin 2 cells apart
    # when cell width == cutoff exactly.  A 3-cell periodic axis is
    # immune (every cell pair is adjacent mod 3); at >= 4 cells demand
    # a strict width margin and give up one cell when it's missing.
    ncell = np.where((ncell >= 4) & (ratio < ncell * (1 + 1e-9)),
                     ncell - 1, ncell)
    if (ncell < 3).any():
        raise GridUnsuitable(
            f"cutoff {max_cutoff} too large for box (cells per axis "
            f"{ncell.tolist()}, need >= 3 so the periodic 27-stencil "
            "cannot alias)")
    ncell = np.minimum(ncell, MAX_CELLS_PER_AXIS)
    inv = np.linalg.inv(m)

    def cells(x):
        frac = x @ inv
        frac -= np.floor(frac)
        # frac is [0, 1) up to float round-off; clip both ends so a
        # boundary atom (frac -> 1.0 after the subtract) stays in-grid
        return np.clip((frac * ncell).astype(np.int64), 0, ncell - 1)

    return ncell, cells, True


def _plan_free(a, b, max_cutoff):
    """(ncell, cells_fn, periodic=False) over the joint bounding box —
    the no-PBC path (plain Euclidean metric)."""
    lo = np.minimum(a.min(axis=0), b.min(axis=0))
    hi = np.maximum(a.max(axis=0), b.max(axis=0))
    extent = hi - lo
    if not np.isfinite(extent).all():
        raise GridUnsuitable("non-finite coordinates")
    ratio = extent / max_cutoff
    ncell = np.floor(ratio + 1e-8).astype(np.int64)
    # boundary-snap safety (see _plan_periodic): without wrap, any axis
    # with >= 3 cells can bin a boundary-straddling exact-cutoff pair 2
    # cells apart unless cell width strictly exceeds the cutoff
    ncell = np.where((ncell >= 3) & (ratio < ncell * (1 + 1e-9)),
                     ncell - 1, ncell)
    ncell = np.clip(ncell, 1, MAX_CELLS_PER_AXIS)
    # cell edge = extent / ncell >= max_cutoff by the floor above, so
    # the unwrapped 27-stencil is sufficient
    width = np.where(extent > 0, extent / ncell, 1.0)

    def cells(x):
        return np.clip(((x - lo) / width).astype(np.int64), 0, ncell - 1)

    return ncell, cells, False


def make_plan(a, b, max_cutoff, dims):
    """The (ncell, cells_fn, periodic) grid plan :func:`capped_pairs`
    will execute — build it ONCE and pass it back via ``plan=`` when a
    caller (the ``auto`` dispatcher) needs the geometry for a
    profitability check first.  Cheap: box math only, plus a min/max
    pass for boxless queries; the per-atom binning runs when
    ``cells_fn`` is called.  Raises :class:`GridUnsuitable` exactly
    when :func:`capped_pairs` would."""
    periodic_box = dims is not None and bool(np.all(dims[:3] > 0))
    if dims is not None and not periodic_box and bool(np.any(dims[:3] > 0)):
        raise GridUnsuitable(
            f"partially degenerate box {np.asarray(dims)[:6].tolist()}")
    if periodic_box:
        return _plan_periodic(float(max_cutoff), dims)
    return _plan_free(a, b, float(max_cutoff))


def capped_pairs(a: np.ndarray, b: np.ndarray, max_cutoff: float,
                 min_cutoff: float | None = None,
                 dims: np.ndarray | None = None,
                 return_distances: bool = True,
                 self_upper: bool = False,
                 plan=None):
    """Cell-list twin of the brute-force kernel in ``lib.distances
    .capped_distance`` — same arguments (``dims`` already normalized to
    the 6-vector by the caller), same return contract, same metric,
    identical output including order.  Raises :class:`GridUnsuitable`
    when the grid cannot serve the query.  ``plan`` accepts a
    pre-built :func:`make_plan` result so dispatchers that already
    planned for a profitability check do not plan twice."""
    from mdanalysis_mpi_tpu.ops import host

    a = np.ascontiguousarray(a, dtype=np.float64).reshape(-1, 3)
    b = np.ascontiguousarray(b, dtype=np.float64).reshape(-1, 3)
    if len(a) == 0 or len(b) == 0:
        pairs = np.empty((0, 2), dtype=np.int64)
        return (pairs, np.empty(0)) if return_distances else pairs

    ncell, cells, wrap = (make_plan(a, b, max_cutoff, dims)
                          if plan is None else plan)
    ca, cb = cells(a), cells(b)
    ny, nz = int(ncell[1]), int(ncell[2])

    def encode(c):
        return (c[:, 0] * ny + c[:, 1]) * nz + c[:, 2]

    idb = encode(cb)
    order = np.argsort(idb, kind="stable")
    sorted_ids = idb[order]

    c2 = float(max_cutoff) ** 2
    m2 = None if min_cutoff is None else float(min_cutoff) ** 2
    pairs_i, pairs_j, dists = [], [], []
    n_a = len(a)
    for off in _STENCIL:
        nc = ca + off
        if wrap:
            nc %= ncell
            ncid = encode(nc)
        else:
            oob = ((nc < 0) | (nc >= ncell)).any(axis=1)
            # -1 never matches a (non-negative) sorted cell id, so
            # out-of-grid stencil cells contribute zero candidates
            ncid = np.where(oob, -1, encode(np.clip(nc, 0, ncell - 1)))
        start = np.searchsorted(sorted_ids, ncid, side="left")
        end = np.searchsorted(sorted_ids, ncid, side="right")
        counts = end - start
        total = int(counts.sum())
        if total == 0:
            continue
        ai = np.repeat(np.arange(n_a), counts)
        run_starts = np.cumsum(counts) - counts
        within = np.arange(total) - np.repeat(run_starts, counts)
        bj = order[np.repeat(start, counts) + within]
        disp = host.minimum_image(a[ai] - b[bj], dims)
        d2 = np.einsum("ij,ij->i", disp, disp)
        hit = d2 <= c2
        if m2 is not None:
            hit &= d2 > m2
        if self_upper:
            hit &= bj > ai
        if hit.any():
            pairs_i.append(ai[hit])
            pairs_j.append(bj[hit])
            if return_distances:
                dists.append(np.sqrt(d2[hit]))

    if not pairs_i:
        pairs = np.empty((0, 2), dtype=np.int64)
        return (pairs, np.empty(0)) if return_distances else pairs
    ii = np.concatenate(pairs_i)
    jj = np.concatenate(pairs_j)
    # brute force emits (i, j) in lexicographic order; match it so the
    # engines are interchangeable row-for-row, not just as sets
    perm = np.lexsort((jj, ii))
    pairs = np.stack([ii[perm], jj[perm]], axis=1)
    if return_distances:
        return pairs, np.concatenate(dists)[perm]
    return pairs


def grid_shape(a: np.ndarray, b: np.ndarray, max_cutoff: float,
               dims: np.ndarray | None) -> tuple[int, int, int]:
    """The (nx, ny, nz) cell grid :func:`capped_pairs` would use —
    exposed for the JAX backend's static-shape planning and for the
    ``auto`` engine's profitability estimate.  Raises
    :class:`GridUnsuitable` exactly when :func:`capped_pairs` would.
    Cheap: no per-atom binning (only box math, plus a min/max pass for
    boxless queries)."""
    a = np.asarray(a, dtype=np.float64).reshape(-1, 3)
    b = np.asarray(b, dtype=np.float64).reshape(-1, 3)
    if len(a) == 0 or len(b) == 0:
        return (1, 1, 1)
    ncell, _, _ = make_plan(a, b, max_cutoff, dims)
    return (int(ncell[0]), int(ncell[1]), int(ncell[2]))
