"""Neighbor search over AtomGroups (upstream
``MDAnalysis.lib.NeighborSearch.AtomNeighborSearch``).

A thin object front over the capped-distance engines
(``lib.distances.capped_distance`` — cell list by default, brute force
as fallback; no N×M materialization either way): build once over a
(static) group, query with any coordinates or group, get the matching
atoms back at atom / residue / segment granularity.
"""

from __future__ import annotations

import numpy as np


class AtomNeighborSearch:
    """``AtomNeighborSearch(ag, box=None).search(other, radius,
    level='A'|'R'|'S')`` → AtomGroup / ResidueGroup / SegmentGroup of
    the atoms of ``ag`` within ``radius`` of ``other`` (an AtomGroup or
    (M, 3) coordinates).  ``engine`` selects the pair-pruning backend
    (``lib.distances.capped_distance``: 'auto' picks the O(N) cell
    list at scale, 'bruteforce'/'nsgrid'/'jax' force one)."""

    def __init__(self, atomgroup, box=None, engine: str = "auto"):
        from mdanalysis_mpi_tpu.analysis.base import reject_updating_groups

        reject_updating_groups(atomgroup, owner="AtomNeighborSearch")
        if atomgroup.n_atoms == 0:
            raise ValueError("cannot search an empty AtomGroup")
        self._ag = atomgroup
        self._box = box
        self._engine = engine

    def search(self, other, radius: float, level: str = "A"):
        from mdanalysis_mpi_tpu.lib.distances import capped_distance

        if radius <= 0:
            raise ValueError(f"radius must be positive, got {radius}")
        coords = (other.positions if hasattr(other, "positions")
                  else np.asarray(other, np.float64).reshape(-1, 3))
        pairs = capped_distance(self._ag.positions, coords, radius,
                                box=self._box, return_distances=False,
                                engine=self._engine)
        hits = np.unique(pairs[:, 0]) if len(pairs) else np.empty(
            0, np.int64)
        ag = self._ag[hits] if len(hits) else self._ag[[]]
        if level == "A":
            return ag
        if level == "R":
            from mdanalysis_mpi_tpu.core.groups import ResidueGroup

            return ResidueGroup(ag.universe, ag.resindices)
        if level == "S":
            from mdanalysis_mpi_tpu.core.groups import SegmentGroup

            return SegmentGroup(ag.universe, ag.segids)
        raise ValueError(
            f"level must be 'A', 'R' or 'S', got {level!r}")
