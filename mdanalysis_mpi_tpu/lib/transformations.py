"""Homogeneous (4×4) transformation matrices — the working subset of
upstream ``MDAnalysis.lib.transformations`` that MD setup scripts lean
on.  Independent implementation from the standard formulas (Rodrigues
rotation, Shoemake quaternion extraction, per-convention Euler
factorization); upstream conventions preserved exactly:

- matrices are 4×4 float64, applied to COLUMN vectors (``M @ v``),
- angles are in RADIANS,
- ``axes`` strings name the 24 Euler conventions (``"sxyz"`` static
  x-y-z default; leading ``r`` = rotating/intrinsic frame).
"""

from __future__ import annotations

import math

import numpy as np

__all__ = [
    "identity_matrix", "translation_matrix", "translation_from_matrix",
    "rotation_matrix", "rotation_from_matrix", "scale_matrix",
    "concatenate_matrices", "euler_matrix", "euler_from_matrix",
    "quaternion_matrix", "quaternion_from_matrix",
]

_NEXT_AXIS = [1, 2, 0, 1]


def _vec3(v) -> np.ndarray:
    """3-vector from a 3- or homogeneous 4-vector (upstream slices
    ``[:3]``, so ``point=M[:, 3]`` idioms must keep working)."""
    v = np.asarray(v, dtype=np.float64).reshape(-1)
    if v.shape[0] not in (3, 4):
        raise ValueError(f"expected a 3- or 4-vector, got shape {v.shape}")
    return v[:3]


def _unit(v) -> np.ndarray:
    v = _vec3(v)
    n = float(np.linalg.norm(v))
    if n == 0.0:
        raise ValueError("direction must be a nonzero vector")
    return v / n


def identity_matrix() -> np.ndarray:
    return np.eye(4)


def translation_matrix(direction) -> np.ndarray:
    m = np.eye(4)
    m[:3, 3] = _vec3(direction)
    return m


def translation_from_matrix(matrix) -> np.ndarray:
    return np.asarray(matrix, dtype=np.float64)[:3, 3].copy()


def rotation_matrix(angle: float, direction, point=None) -> np.ndarray:
    """Rotation by ``angle`` radians about the axis along ``direction``
    through ``point`` (origin if None)."""
    k = _unit(direction)
    s, c = math.sin(angle), math.cos(angle)
    kx = np.array([[0.0, -k[2], k[1]],
                   [k[2], 0.0, -k[0]],
                   [-k[1], k[0], 0.0]])
    r = np.eye(3) * c + s * kx + (1.0 - c) * np.outer(k, k)
    m = np.eye(4)
    m[:3, :3] = r
    if point is not None:
        p = _vec3(point)
        m[:3, 3] = p - r @ p
    return m


def rotation_from_matrix(matrix):
    """(angle, direction, point) recovering ``rotation_matrix`` inputs.

    The axis is the rotation part's unit-eigenvalue eigenvector; the
    point is its counterpart for the full homogeneous matrix.
    """
    m = np.asarray(matrix, dtype=np.float64)
    r = m[:3, :3]
    w, v = np.linalg.eig(r)
    i = int(np.argmin(np.abs(w - 1.0)))
    if abs(w[i] - 1.0) > 1e-8:
        raise ValueError("matrix has no rotation axis (not a rotation?)")
    direction = np.real(v[:, i])
    direction /= np.linalg.norm(direction)
    # angle from trace; sign from a component off the axis
    cosa = (np.trace(r) - 1.0) / 2.0
    if abs(direction[2]) > 1e-8:
        sina = (r[1, 0] + (cosa - 1.0) * direction[0] * direction[1]) \
            / direction[2]
    elif abs(direction[1]) > 1e-8:
        sina = (r[0, 2] + (cosa - 1.0) * direction[0] * direction[2]) \
            / direction[1]
    else:
        sina = (r[2, 1] + (cosa - 1.0) * direction[1] * direction[2]) \
            / direction[0]
    angle = math.atan2(sina, cosa)
    # fixed point: (R - I) p = -t is singular along the axis (the whole
    # axis line is fixed), so take the minimum-norm solution — the axis
    # point closest to the origin.  An eigenvector of the 4x4 would be
    # an ARBITRARY basis vector of the 2-D eigenspace and can land on
    # the direction (w = 0) instead of a point.
    point = -np.linalg.pinv(r - np.eye(3)) @ m[:3, 3]
    return angle, direction, point


def scale_matrix(factor: float, origin=None) -> np.ndarray:
    m = np.eye(4)
    m[:3, :3] *= float(factor)
    if origin is not None:
        m[:3, 3] = _vec3(origin) * (1.0 - float(factor))
    return m


def concatenate_matrices(*matrices) -> np.ndarray:
    """M₀ @ M₁ @ ... (applied right-to-left to column vectors)."""
    m = np.eye(4)
    for x in matrices:
        m = m @ np.asarray(x, dtype=np.float64)
    return m


def _axes_tuple(axes: str):
    """Euler convention string → (first axis, parity, repetition,
    frame, raw_seq).  ``axes[0]``: 's'tatic or 'r'otating; then three
    of xyz.  ``raw_seq`` is the literal axis sequence (for the forward
    composition); the i/j/k machinery fields derive from the static
    EQUIVALENT (a rotating convention equals the reversed static one
    with first/last angles swapped — the frame flag carries the swap).
    """
    try:
        frame = {"s": 0, "r": 1}[axes[0]]
        raw_seq = [{"x": 0, "y": 1, "z": 2}[a] for a in axes[1:]]
    except (KeyError, IndexError):
        raise ValueError(f"bad Euler axes string {axes!r}") from None
    if len(raw_seq) != 3 or raw_seq[0] == raw_seq[1] \
            or raw_seq[1] == raw_seq[2]:
        raise ValueError(f"bad Euler axes string {axes!r}")
    seq = raw_seq[::-1] if frame else raw_seq
    firstaxis = seq[0]
    repetition = int(seq[0] == seq[2])
    parity = int(_NEXT_AXIS[firstaxis] != seq[1])
    return firstaxis, parity, repetition, frame, raw_seq


def euler_matrix(ai: float, aj: float, ak: float,
                 axes: str = "sxyz") -> np.ndarray:
    """Rotation matrix from Euler angles (radians) in the named
    convention — the definitional composition of single-axis
    rotations: static axes apply in sequence about the FIXED frame
    (later rotations compose on the left), rotating axes about the
    body frame (right)."""
    _, _, _, frame, seq = _axes_tuple(axes)

    def axis_rot(axis, a):
        e = np.zeros(3)
        e[axis] = 1.0
        return rotation_matrix(a, e)

    m0, m1, m2 = (axis_rot(a, th)
                  for a, th in zip(seq, (ai, aj, ak)))
    return (m0 @ m1 @ m2) if frame else (m2 @ m1 @ m0)


def euler_from_matrix(matrix, axes: str = "sxyz"):
    """Euler angles (radians) of a rotation matrix in the named
    convention (inverse of :func:`euler_matrix`)."""
    firstaxis, parity, repetition, frame, _ = _axes_tuple(axes)
    i = firstaxis
    j = _NEXT_AXIS[i + parity]
    k = _NEXT_AXIS[i - parity + 1]
    m = np.asarray(matrix, dtype=np.float64)[:3, :3]
    eps = 1e-12
    if repetition:
        sy = math.sqrt(m[i, j] ** 2 + m[i, k] ** 2)
        if sy > eps:
            ax = math.atan2(m[i, j], m[i, k])
            ay = math.atan2(sy, m[i, i])
            az = math.atan2(m[j, i], -m[k, i])
        else:
            ax = math.atan2(-m[j, k], m[j, j])
            ay = math.atan2(sy, m[i, i])
            az = 0.0
    else:
        cy = math.sqrt(m[i, i] ** 2 + m[j, i] ** 2)
        if cy > eps:
            ax = math.atan2(m[k, j], m[k, k])
            ay = math.atan2(-m[k, i], cy)
            az = math.atan2(m[j, i], m[i, i])
        else:
            ax = math.atan2(-m[j, k], m[j, j])
            ay = math.atan2(-m[k, i], cy)
            az = 0.0
    if parity:
        ax, ay, az = -ax, -ay, -az
    if frame:
        ax, az = az, ax
    return ax, ay, az


def quaternion_matrix(quaternion) -> np.ndarray:
    """4×4 rotation matrix from a (w, x, y, z) quaternion (upstream
    scalar-first convention); normalizes the input."""
    q = np.asarray(quaternion, dtype=np.float64).reshape(4)
    n = float(q @ q)
    if n < 1e-30:
        return np.eye(4)
    q = q * math.sqrt(2.0 / n)
    q = np.outer(q, q)
    m = np.eye(4)
    m[:3, :3] = [
        [1.0 - q[2, 2] - q[3, 3], q[1, 2] - q[3, 0], q[1, 3] + q[2, 0]],
        [q[1, 2] + q[3, 0], 1.0 - q[1, 1] - q[3, 3], q[2, 3] - q[1, 0]],
        [q[1, 3] - q[2, 0], q[2, 3] + q[1, 0], 1.0 - q[1, 1] - q[2, 2]],
    ]
    return m


def quaternion_from_matrix(matrix) -> np.ndarray:
    """(w, x, y, z) unit quaternion of a rotation matrix (Shoemake's
    stable branch selection on the largest diagonal term)."""
    m = np.asarray(matrix, dtype=np.float64)[:3, :3]
    t = np.trace(m)
    if t > 0.0:
        s = math.sqrt(t + 1.0) * 2.0
        q = np.array([0.25 * s,
                      (m[2, 1] - m[1, 2]) / s,
                      (m[0, 2] - m[2, 0]) / s,
                      (m[1, 0] - m[0, 1]) / s])
    else:
        i = int(np.argmax(np.diagonal(m)))
        j, k = (i + 1) % 3, (i + 2) % 3
        s = math.sqrt(m[i, i] - m[j, j] - m[k, k] + 1.0) * 2.0
        q = np.empty(4)
        q[0] = (m[k, j] - m[j, k]) / s
        q[1 + i] = 0.25 * s
        q[1 + j] = (m[j, i] + m[i, j]) / s
        q[1 + k] = (m[k, i] + m[i, k]) / s
    if q[0] < 0.0:
        q = -q
    return q
