"""Discrete autocorrelation utilities (upstream
``MDAnalysis.lib.correlations``).

The shared math behind SurvivalProbability and hydrogen-bond lifetimes,
exposed in upstream's public list-of-sets API:

- :func:`autocorrelation(list_of_sets, tau_max, window_step=1)` —
  continuous-survival autocorrelation: for each lag τ, the mean over
  window starts t of ``|S_t ∩ S_{t+1} ∩ … ∩ S_{t+τ}| / |S_t|``
  (an element must be present through EVERY intermediate frame).
- :func:`correct_intermittency(list_of_sets, intermittency)` — fill
  departures of ≤ ``intermittency`` consecutive frames for elements
  present on both sides, BEFORE the survival product (upstream's
  intermittent preprocessing).

Internally both pack the sets into one (T, n_elements) boolean matrix
and reduce with vectorized running ANDs — the same representation the
analysis classes use directly (``analysis/waterdynamics.py``,
``analysis/hbonds.py:lifetime``).
"""

from __future__ import annotations

import numpy as np


def intermittency_filter(mask: np.ndarray, k: int) -> np.ndarray:
    """Fill gaps of ≤ k consecutive absent frames for columns present
    on both sides — the boolean-matrix core of
    :func:`correct_intermittency` (matrix layout: (T, n_elements))."""
    if k <= 0:
        return mask
    out = mask.copy()
    t = mask.shape[0]
    for gap in range(1, k + 1):
        # present at i and at i+gap+1 with the gap in between → filled
        for i in range(t - gap - 1):
            bridge = mask[i] & mask[i + gap + 1]
            if bridge.any():
                out[i + 1:i + gap + 1] |= bridge
    return out


def _sets_to_matrix(list_of_sets):
    elements = sorted(set().union(*list_of_sets)) if list_of_sets else []
    index = {e: i for i, e in enumerate(elements)}
    mat = np.zeros((len(list_of_sets), len(elements)), dtype=bool)
    for t, s in enumerate(list_of_sets):
        for e in s:
            mat[t, index[e]] = True
    return mat, elements


def correct_intermittency(list_of_sets, intermittency: int):
    """Upstream API: list of per-frame sets → list of per-frame sets
    with gaps of ≤ ``intermittency`` frames filled."""
    if intermittency < 0:
        raise ValueError(
            f"intermittency must be >= 0, got {intermittency}")
    if intermittency == 0 or not list_of_sets:
        return [set(s) for s in list_of_sets]
    mat, elements = _sets_to_matrix(list_of_sets)
    mat = intermittency_filter(mat, int(intermittency))
    return [{elements[i] for i in np.flatnonzero(row)} for row in mat]


def survival_windows(mask: np.ndarray, tau_max: int,
                     window_step: int = 1) -> list:
    """The ONE running-AND survival reduction (matrix level), shared by
    :func:`autocorrelation`, ``SurvivalProbability`` and
    ``HydrogenBondAnalysis.lifetime`` so the semantics cannot drift.

    mask: (T, n_elements) bool.  Returns, for each τ = 0..tau_max, the
    list of per-window survival fractions
    ``|S_t ∩ … ∩ S_{t+τ}| / |S_t|`` over window starts
    ``t = 0, window_step, …`` with ``|S_t| > 0`` (empty list when no
    window fits or every eligible start is empty)."""
    if tau_max < 0:
        raise ValueError(f"tau_max must be >= 0, got {tau_max}")
    if window_step < 1:
        raise ValueError(f"window_step must be >= 1, got {window_step}")
    t = len(mask)
    starts = np.arange(0, t, window_step)
    n0 = mask.sum(axis=1).astype(np.float64)
    surviving = mask.copy()
    data: list = []
    for tau in range(int(tau_max) + 1):
        if tau > t - 1:
            data.append([])
            continue
        if tau:
            surviving = surviving[:-1] & mask[tau:]
        ok = starts[starts < t - tau]
        ok = ok[n0[ok] > 0]
        data.append((surviving[ok].sum(axis=1) / n0[ok]).tolist())
    return data


def autocorrelation(list_of_sets, tau_max: int, window_step: int = 1):
    """Upstream API: ``(tau_timeseries, timeseries, timeseries_data)``.

    Upstream-exact shapes: ``tau_timeseries`` = [0..tau_max] and
    ``timeseries`` has tau_max+1 entries REGARDLESS of trajectory
    length (lags with no fitting window are NaN, never silently
    dropped); ``timeseries_data`` has tau_max entries indexed by
    ``τ−1`` (the τ=0 point carries no per-window list upstream).
    ``timeseries[0]`` is 1.0 whenever any window has members."""
    mat, _ = _sets_to_matrix(list_of_sets)
    if len(mat) == 0:
        raise ValueError("autocorrelation over zero frames")
    data = survival_windows(mat, tau_max, window_step)
    tau_timeseries = list(range(int(tau_max) + 1))
    timeseries = [float(np.mean(v)) if v else float("nan")
                  for v in data]
    return tau_timeseries, timeseries, data[1:]
