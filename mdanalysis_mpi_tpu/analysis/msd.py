"""Einstein mean-squared-displacement analysis.

Upstream-API mirror (``MDAnalysis.analysis.msd.EinsteinMSD``):
``EinsteinMSD(u, select=..., msd_type='xyz', fft=True).run()`` →
``results.timeseries`` (T,), ``results.msds_by_particle`` (T, S).
The reference program has no MSD, but the capability envelope
(AnalysisBase over pluggable executors) is what this plugs into —
like RMSD it is a *time-series* analysis: per-batch staged positions
are concatenated on device in frame order (no fold), and the lag
algebra runs once at the end.

TPU-first shape: the FFT route (Calandrini et al.'s decomposition
``msd(m) = S1(m) − 2·S2(m)``) turns the O(T²) lag sum into one
``rfft``/``irfft`` autocorrelation over the time axis plus cumulative
sums — all static-shape, all on device in a single jitted call.  As
with upstream, coordinates must be unwrapped for physically meaningful
MSDs (no PBC jumps); the math here is exact for whatever coordinates
are staged.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, deferred_group
from mdanalysis_mpi_tpu.core.universe import Universe

_DIM_SETS = {
    "xyz": (0, 1, 2), "xy": (0, 1), "xz": (0, 2), "yz": (1, 2),
    "x": (0,), "y": (1,), "z": (2,),
}


# ---- module-level batch kernel (stable identity → cached compiles) ----

def _collect_kernel(params, batch, boxes, mask):
    """Gather the requested dimensions; zero padded frames so the
    concatenated series carries an honest mask."""
    del boxes
    (dims_idx,) = params
    sub = batch[:, :, dims_idx]
    return (sub * mask[:, None, None], mask)


def _np_fft_msd(pos: np.ndarray) -> np.ndarray:
    """NumPy float64 reference of the FFT MSD (serial oracle).

    pos (T, S, D) → per-particle MSD (T, S).  ``msd(m) = S1(m) − 2·S2(m)``
    with S2 from the FFT autocorrelation and S1 from cumulative sums.
    """
    t = pos.shape[0]
    pos = np.asarray(pos, np.float64)
    f = np.fft.rfft(pos, n=2 * t, axis=0)
    ac = np.fft.irfft(f * np.conj(f), n=2 * t, axis=0)[:t].sum(axis=2)
    sq = (pos ** 2).sum(axis=2)                    # (T, S)
    cs = np.cumsum(sq, axis=0)
    total = cs[-1]
    a = cs[::-1]                                   # A(m) = CS[T-1-m]
    b = total[None] - np.concatenate([np.zeros((1,) + total.shape), cs[:-1]])
    norm = (t - np.arange(t))[:, None]
    return (a + b - 2.0 * ac) / norm


_FFT_MSD_JIT = None


def _jax_fft_msd(pos):
    """Device twin of :func:`_np_fft_msd` (one jitted call)."""
    global _FFT_MSD_JIT
    if _FFT_MSD_JIT is None:
        import jax
        import jax.numpy as jnp

        def f(pos):
            t = pos.shape[0]
            fr = jnp.fft.rfft(pos, n=2 * t, axis=0)
            ac = jnp.fft.irfft(
                fr * jnp.conj(fr), n=2 * t, axis=0)[:t].sum(axis=2)
            sq = (pos ** 2).sum(axis=2)
            cs = jnp.cumsum(sq, axis=0)
            total = cs[-1]
            a = cs[::-1]
            b = total[None] - jnp.concatenate(
                [jnp.zeros((1,) + total.shape, cs.dtype), cs[:-1]])
            norm = (t - jnp.arange(t, dtype=cs.dtype))[:, None]
            return (a + b - 2.0 * ac) / norm

        _FFT_MSD_JIT = jax.jit(f)
    return _FFT_MSD_JIT(pos)


def _np_windowed_msd(pos: np.ndarray) -> np.ndarray:
    """Direct O(T²) lag loop (the upstream ``fft=False`` route); exact,
    for validation and small windows."""
    t = pos.shape[0]
    pos = np.asarray(pos, np.float64)
    out = np.zeros((t, pos.shape[1]))
    for m in range(1, t):
        d = pos[m:] - pos[:-m]
        out[m] = (d ** 2).sum(axis=2).mean(axis=0)
    return out


class EinsteinMSD(AnalysisBase):
    """``EinsteinMSD(u, select='name OW', msd_type='xyz').run()``.

    Results: ``timeseries`` (T,) — MSD vs lag averaged over particles —
    and ``msds_by_particle`` (T, S).  ``fft=True`` (default) uses the
    O(T log T) FFT decomposition; ``fft=False`` the direct windowed sum
    (serial backend only).  Provide unwrapped coordinates.
    """

    def __init__(self, universe: Universe, select: str = "all",
                 msd_type: str = "xyz", fft: bool = True,
                 verbose: bool = False):
        super().__init__(universe, verbose)
        if msd_type not in _DIM_SETS:
            raise ValueError(
                f"msd_type must be one of {sorted(_DIM_SETS)}, "
                f"got {msd_type!r}")
        self._select = select
        self._dims = _DIM_SETS[msd_type]
        self._fft = fft

    def _prepare(self):
        ag = self._universe.select_atoms(self._select)
        if ag.n_atoms == 0:
            raise ValueError(f"selection {self._select!r} matched no atoms")
        self._idx = ag.indices
        self._serial_pos = []

    # -- serial path --

    def _single_frame(self, ts):
        self._serial_pos.append(
            ts.positions[self._idx][:, self._dims].astype(np.float64))

    def _serial_summary(self):
        pos = (np.stack(self._serial_pos) if self._serial_pos
               else np.empty((0, len(self._idx), len(self._dims))))
        return (pos, np.ones(len(pos)))

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _collect_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._dims, jnp.int32),)

    _device_combine = None      # series: concatenated in frame order

    def _identity_partials(self):
        return (np.empty((0, len(self._idx), len(self._dims))), np.empty(0))

    def _conclude(self, total):
        pos, mask = total
        if self.n_frames < 2:
            raise ValueError("MSD needs at least 2 frames")
        import jax

        on_device = isinstance(pos, jax.Array)
        if not self._fft and on_device:
            raise ValueError("fft=False is the serial-backend reference "
                             "route; use fft=True on accelerator backends")

        def _finalize():
            # padded-frame filtering is dynamic-shape → host side,
            # deferred (run() stays readback-free); the lag algebra then
            # runs as ONE jitted device call on the filtered series
            p = np.asarray(pos)[np.asarray(mask) > 0.5]
            if not self._fft:
                by = _np_windowed_msd(p)
            elif on_device:
                import jax.numpy as jnp

                by = np.asarray(_jax_fft_msd(jnp.asarray(p)))
            else:
                by = _np_fft_msd(p)
            return {"msds_by_particle": by,
                    "timeseries": by.mean(axis=1)}

        g = deferred_group(_finalize)
        self.results.msds_by_particle = g["msds_by_particle"]
        self.results.timeseries = g["timeseries"]
