"""Radius-of-gyration time series.

Not part of the reference program, but the canonical "write your own
analysis" example for this framework (upstream users know it as
``MDAnalysis.analysis`` recipes around ``AtomGroup.radius_of_gyration``):
an AnalysisBase subclass needs only

- a serial per-frame body (`_single_frame`) — the f64 oracle, and
- a module-level batch kernel (`_batch_fn`) over ``(B, S, 3)`` blocks —
  the accelerator path,

and every executor (serial / jax / mesh / mpi), the staging pipeline,
checkpointing, and the lazy-results machinery come for free.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, Deferred
from mdanalysis_mpi_tpu.core.groups import AtomGroup


def _rgyr_kernel(params, batch, boxes, mask):
    """sqrt(Σ mᵢ·|rᵢ−COM|²/Σ mᵢ) per frame of the staged selection."""
    del boxes
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops.moments import _HI

    (weights,) = params                       # (S,) normalized masses
    com = jnp.einsum("s,bsi->bi", weights, batch, precision=_HI)
    d2 = ((batch - com[:, None, :]) ** 2).sum(-1)      # (B, S)
    rg = jnp.sqrt(jnp.einsum("s,bs->b", weights, d2, precision=_HI))
    return (rg * mask, mask)


class RadiusOfGyration(AnalysisBase):
    """Per-frame mass-weighted radius of gyration:
    ``RadiusOfGyration(ag).run().results.rgyr`` (n_frames,)."""

    def __init__(self, atomgroup: AtomGroup, verbose: bool = False):
        super().__init__(atomgroup.universe, verbose)
        self._ag = atomgroup

    def _prepare(self):
        if self._ag.n_atoms == 0:
            raise ValueError("RadiusOfGyration needs a non-empty group")
        self._idx = self._ag.indices
        m = self._ag.masses
        self._weights = m / m.sum()
        self._serial_vals: list[float] = []

    # -- serial path (f64 oracle) --

    def _single_frame(self, ts):
        x = ts.positions[self._idx].astype(np.float64)
        com = self._weights @ x
        d2 = ((x - com) ** 2).sum(axis=1)
        self._serial_vals.append(float(np.sqrt(self._weights @ d2)))

    def _serial_summary(self):
        vals = np.asarray(self._serial_vals)
        return (vals, np.ones(len(vals)))

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _rgyr_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._weights, jnp.float32),)

    # time series: per-batch (vals, mask) concatenated on device in
    # frame order by the executor (same shape as RMSD)
    _device_combine = None

    def _identity_partials(self):
        return (np.empty(0), np.empty(0))

    def _conclude(self, total):
        vals, mask = total

        def _finalize():
            return np.asarray(vals)[np.asarray(mask) > 0.5]

        self.results.rgyr = Deferred(_finalize)
