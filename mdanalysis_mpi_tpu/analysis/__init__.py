"""Analyses: the user-facing API mirroring the serial oracle declared in
the reference's docstring (RMSF.py:1-18) — ``Analysis(...).run()`` →
``.results.<attr>`` — over the pluggable executor layer.

- :class:`~mdanalysis_mpi_tpu.analysis.rms.RMSF` — fluctuations of an
  AtomGroup (stock ``rms.RMSF`` oracle, RMSF.py:14-15).
- :class:`~mdanalysis_mpi_tpu.analysis.rms.RMSD` — time series with
  optional least-squares superposition (BASELINE config 3).
- :class:`~mdanalysis_mpi_tpu.analysis.align.AverageStructure` — the
  reference's pass 1 (RMSF.py:76-113; oracle RMSF.py:9-10).
- :class:`~mdanalysis_mpi_tpu.analysis.align.AlignTraj` — in-memory
  trajectory alignment (oracle RMSF.py:12).
- :class:`~mdanalysis_mpi_tpu.analysis.rms.AlignedRMSF` — the whole
  reference program as one call (pass 1 + pass 2, RMSF.py:53-149).

Beyond the reference's envelope, the roster mirrors upstream
MDAnalysis' analysis subpackages (each with serial f64 oracle +
batched kernels where static shapes allow — see PARITY.md for the
line-by-line map): InterRDF/InterRDF_s, ContactMap/Contacts/
PairwiseDistances/AtomicDistances, RadiusOfGyration, PCA (+
cosine_content), EinsteinMSD, Dihedral/Ramachandran/Janin,
DensityAnalysis/LinearDensity, HydrogenBondAnalysis (+ lifetime),
DistanceMatrix/DiffusionMap, VelocityAutocorr, GNMAnalysis,
SurvivalProbability/WaterOrientationalRelaxation/AngularDistribution/
MeanSquareDisplacement, DielectricConstant, PSAnalysis
(hausdorff/discrete_frechet), PersistenceLength, HELANAL, BAT, DSSP,
encore.hes/ces/dres, NucPairDist/WatsonCrickDist, nuclinfo, LeafletFinder
(+ optimize_cutoff), sequence_alignment, AnalysisFromFunction, and
AnalysisCollection (N analyses over ONE staged trajectory pass).
"""

from mdanalysis_mpi_tpu.analysis.base import (AnalysisBase,
                                               AnalysisCollection,
                                               Results,
                                               AnalysisFromFunction,
                                               UncoalescableAnalysisError,
                                               analysis_class)
from mdanalysis_mpi_tpu.analysis.rms import RMSF, RMSD, AlignedRMSF, rmsd
from mdanalysis_mpi_tpu.analysis.align import (AverageStructure, AlignTraj,
                                               alignto, rotation_matrix)
from mdanalysis_mpi_tpu.analysis.rdf import InterRDF, InterRDF_s
from mdanalysis_mpi_tpu.analysis.distances import ContactMap, PairwiseDistances
from mdanalysis_mpi_tpu.analysis.rgyr import RadiusOfGyration
from mdanalysis_mpi_tpu.analysis.pca import PCA
from mdanalysis_mpi_tpu.analysis.msd import EinsteinMSD
from mdanalysis_mpi_tpu.analysis.dihedrals import Dihedral, Ramachandran
from mdanalysis_mpi_tpu.analysis.contacts import Contacts
from mdanalysis_mpi_tpu.analysis.density import (Density,
                                                 DensityAnalysis)
from mdanalysis_mpi_tpu.analysis.hbonds import HydrogenBondAnalysis
from mdanalysis_mpi_tpu.analysis.diffusionmap import (DistanceMatrix,
                                                      DiffusionMap)
from mdanalysis_mpi_tpu.analysis.vacf import VelocityAutocorr
from mdanalysis_mpi_tpu.analysis.lineardensity import LinearDensity
from mdanalysis_mpi_tpu.analysis.gnm import GNMAnalysis
from mdanalysis_mpi_tpu.analysis.waterdynamics import (
    AngularDistribution, MeanSquareDisplacement, SurvivalProbability,
    WaterOrientationalRelaxation,
)
from mdanalysis_mpi_tpu.analysis.dielectric import DielectricConstant
from mdanalysis_mpi_tpu.analysis.psa import (PSAnalysis, discrete_frechet,
                                             hausdorff)
from mdanalysis_mpi_tpu.analysis.polymer import PersistenceLength
from mdanalysis_mpi_tpu.analysis.helix import HELANAL, helix_analysis
from mdanalysis_mpi_tpu.analysis.bat import BAT
from mdanalysis_mpi_tpu.analysis.dihedrals import Janin
from mdanalysis_mpi_tpu.analysis.dssp import DSSP
from mdanalysis_mpi_tpu.analysis.encore import ces, dres, hes
from mdanalysis_mpi_tpu.analysis.pca import cosine_content
from mdanalysis_mpi_tpu.analysis.align import sequence_alignment
from mdanalysis_mpi_tpu.analysis.atomicdistances import AtomicDistances
from mdanalysis_mpi_tpu.analysis.leaflet import (LeafletFinder,
                                                 optimize_cutoff)
from mdanalysis_mpi_tpu.analysis.nucleicacids import (
    NucPairDist, WatsonCrickDist,
)
from mdanalysis_mpi_tpu.analysis.waterbridge import WaterBridgeAnalysis

__all__ = ["AnalysisBase", "AnalysisCollection", "Results",
           "AnalysisFromFunction",
           "analysis_class", "RMSF", "RMSD", "AlignedRMSF", "rmsd",
           "AverageStructure", "AlignTraj", "alignto", "rotation_matrix",
           "InterRDF", "InterRDF_s", "ContactMap",
           "PairwiseDistances", "RadiusOfGyration", "PCA", "EinsteinMSD",
           "Dihedral", "Ramachandran", "Janin", "Contacts", "DensityAnalysis",
           "Density",
           "HydrogenBondAnalysis", "DistanceMatrix", "DiffusionMap",
           "VelocityAutocorr", "LinearDensity", "GNMAnalysis",
           "SurvivalProbability", "DielectricConstant",
           "WaterOrientationalRelaxation", "AngularDistribution",
           "PSAnalysis", "hausdorff", "discrete_frechet",
           "PersistenceLength", "HELANAL", "helix_analysis", "BAT", "DSSP", "hes", "ces", "dres", "NucPairDist", "WatsonCrickDist", "AtomicDistances",
           "LeafletFinder", "optimize_cutoff", "cosine_content",
           "MeanSquareDisplacement", "sequence_alignment",
           "WaterBridgeAnalysis"]
