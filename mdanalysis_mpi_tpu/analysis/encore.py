"""Ensemble similarity (upstream ``MDAnalysis.analysis.encore``).

:func:`hes` — the Harmonic Ensemble Similarity: model each ensemble
(trajectory) as a multivariate Gaussian over its flattened (3N,)
configuration vectors and take the symmetrized harmonic divergence

    d(A, B) = ¼ (μ_A − μ_B)ᵀ (Σ_A⁻¹ + Σ_B⁻¹) (μ_A − μ_B)
            + ½ tr(Σ_A Σ_B⁻¹ + Σ_B Σ_A⁻¹ − 2·I)

(upstream ``encore.hes``'s closed form).  Ensembles are rigid-aligned
to a common reference first (every frame Kabsch-superposed onto the
first ensemble's first frame — without this, μ differences would be
dominated by tumbling).

Covariance estimators: ``"shrinkage"`` (default, Ledoit–Wolf 2004 —
the estimator upstream defaults to, SPD even with far fewer frames
than 3N dimensions) or ``"ml"`` (sample covariance; needs T ≫ 3N and a
jitter to invert).

TPU-first shape: each ensemble's covariance is ONE (T, 3N)ᵀ(T, 3N)
matmul — the MXU-shaped reduction PCA already uses — and the
cross-ensemble terms are Cholesky solves; everything runs in float64
on host at typical Cα sizes (3N ~ 10³), with the per-frame alignment
reusing the shared QCP machinery (ops/host.py).

:func:`ces` / :func:`dres` — the clustering- and dimensionality-
reduction-based similarities (upstream ``encore.ces``/``encore.dres``),
implemented from scratch on this repo's own machinery instead of
upstream's vendored C + scikit-learn backends:

- the joint conformational distance matrix is the ONE vmapped
  superposed-RMSD kernel DiffusionMap already jits
  (``diffusionmap._pairwise_rmsd_device`` — all T² Kabsch
  superpositions in a single device call);
- :class:`AffinityPropagationNative` (upstream's default clusterer) is
  a dense vectorized message-passing loop — (T, T) responsibility/
  availability updates are row/column reductions, NumPy-whole-matrix
  per iteration, no per-point Python loops;
- :class:`KMeansNative` is seeded k-means++ Lloyd iteration on the
  aligned flattened coordinates;
- :class:`StochasticProximityEmbeddingNative` runs chunked stochastic
  proximity embedding (random pair batches, ``np.add.at`` scatter
  updates — a documented batched variant of the sequential original);
- :func:`dres` densities come from :class:`GaussianKDE` (Scott's-rule
  full-covariance kernels) with the Jensen–Shannon divergence
  estimated by seeded Monte Carlo over KDE samples, natural log,
  capped at ln 2 — upstream's estimator contract.

Determinism: every stochastic component (AP tie-breaking noise, SPE
pair draws, KDE sampling) takes an explicit integer seed and defaults
to a fixed one — repeat calls are bit-reproducible.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.psa import _as_path, align_path


def ledoit_wolf_covariance(x: np.ndarray) -> np.ndarray:
    """Ledoit–Wolf shrinkage covariance of rows of ``x`` (T, p):
    ``(1−δ)·S + δ·m·I`` with the closed-form optimal δ (LW 2004).
    SPD for any T ≥ 2."""
    x = np.asarray(x, np.float64)
    t, p = x.shape
    if t < 2:
        raise ValueError(f"need at least 2 frames, got {t}")
    xc = x - x.mean(axis=0)
    s = xc.T @ xc / t
    m = np.trace(s) / p
    d2 = ((s - m * np.eye(p)) ** 2).sum() / p
    # b̄² = (1/T²) Σ_t ‖x_t x_tᵀ − S‖_F² / p, via the expansion
    # ‖x xᵀ − S‖² = (xᵀx)² − 2 xᵀSx + ‖S‖²
    xtx = (xc * xc).sum(axis=1)
    xsx = ((xc @ s) * xc).sum(axis=1)
    s_f2 = (s * s).sum()
    b2 = (xtx ** 2 - 2.0 * xsx + s_f2).sum() / (t * t) / p
    b2 = min(b2, d2)
    delta = b2 / d2 if d2 > 0 else 1.0
    return (1.0 - delta) * s + delta * m * np.eye(p)


def _aligned_flat(paths: list) -> list:
    """Kabsch-align every frame of every path onto paths[0][0] (the
    shared :func:`~mdanalysis_mpi_tpu.analysis.psa.align_path`);
    return flattened (T_i, 3N) float64 arrays."""
    ref = paths[0][0]
    return [align_path(p, ref).reshape(len(p), -1) for p in paths]


def hes(ensembles, select: str = "name CA", align: bool = True,
        cov_estimator: str = "shrinkage"):
    """Upstream ``encore.hes``: ``(d_matrix, details)`` with
    ``d_matrix`` the symmetric (k, k) harmonic divergences and
    ``details`` carrying each ensemble's ``means``/``covariances``."""
    if cov_estimator not in ("shrinkage", "ml"):
        raise ValueError(
            f"cov_estimator must be 'shrinkage' or 'ml', got "
            f"{cov_estimator!r}")
    paths = [_as_path(e, select) for e in ensembles]
    if len(paths) < 2:
        raise ValueError("hes needs at least two ensembles")
    widths = {p.shape[1] for p in paths}
    if len(widths) != 1:
        raise ValueError(
            f"ensembles have different selection widths {sorted(widths)}")
    if min(len(p) for p in paths) < 2:
        raise ValueError("every ensemble needs at least 2 frames")
    flats = (_aligned_flat(paths) if align
             else [p.reshape(len(p), -1).astype(np.float64)
                   for p in paths])
    p_dim = flats[0].shape[1]
    means = [f.mean(axis=0) for f in flats]
    # a zero-variance ensemble (all frames identical) has no Gaussian
    # model — fail naming the input, not with a downstream LinAlgError
    for idx, (f, mu) in enumerate(zip(flats, means)):
        var_sum = float(((f - mu) ** 2).sum())
        # relative: the mean of identical frames differs from them by
        # float roundoff, so exact zero would miss real frozen inputs
        if var_sum <= 1e-18 * max(float((f ** 2).sum()), 1e-30):
            raise ValueError(
                f"ensemble {idx} has zero variance (all frames "
                "identical); hes needs fluctuating ensembles")
    if cov_estimator == "shrinkage":
        covs = [ledoit_wolf_covariance(f) for f in flats]
    else:
        # ML sample covariance + a relative jitter so the inverse
        # exists even at T ≈ p (documented estimator caveat)
        covs = []
        for f, mu in zip(flats, means):
            xc = f - mu
            s = xc.T @ xc / len(f)
            covs.append(s + 1e-9 * (np.trace(s) / p_dim)
                        * np.eye(p_dim))
    k = len(flats)
    # Cholesky is the SPD gate (a clear failure point if an estimator
    # ever regresses); the inverses themselves come from one LU each
    for idx, c in enumerate(covs):
        try:
            np.linalg.cholesky(c)
        except np.linalg.LinAlgError:
            raise ValueError(
                f"ensemble {idx}'s covariance is not positive "
                "definite; use cov_estimator='shrinkage'") from None
    invs = [np.linalg.inv(c) for c in covs]
    d = np.zeros((k, k))
    for i in range(k):
        for j in range(i + 1, k):
            dm = means[i] - means[j]
            quad = 0.25 * dm @ ((invs[i] + invs[j]) @ dm)
            # tr(A @ B) for symmetric A, B without the (p, p) matmul
            tr = 0.5 * ((covs[i] * invs[j]).sum()
                        + (covs[j] * invs[i]).sum() - 2.0 * p_dim)
            d[i, j] = d[j, i] = float(quad + tr)
    return d, {"means": means, "covariances": covs,
               "estimator": cov_estimator}


# ---------------------------------------------------------------------
# Conformational distance matrix (shared by ces and dres)
# ---------------------------------------------------------------------

LN2 = float(np.log(2.0))


def _gather_paths(ensembles, select: str):
    """Common ces/dres front end: per-ensemble (T_i, S, 3) paths with a
    shared selection width, plus the joint frame stack and the
    ensemble-id label of every joint frame.

    No alignment happens here: the superposed-RMSD distance matrix is
    rigid-motion-invariant per frame, so every distance-matrix consumer
    (all of dres, ces's default AP path) gets bit-identical results
    with or without a pre-alignment — T host-side Kabsch SVDs for
    nothing.  Coordinate-space clusterers are the one consumer that
    needs aligned frames; ces aligns lazily in that branch."""
    paths = [_as_path(e, select) for e in ensembles]
    if len(paths) < 2:
        raise ValueError("need at least two ensembles")
    widths = {p.shape[1] for p in paths}
    if len(widths) != 1:
        raise ValueError(
            f"ensembles have different selection widths {sorted(widths)}")
    if min(len(p) for p in paths) < 2:
        raise ValueError("every ensemble needs at least 2 frames")
    joint = np.concatenate(paths, axis=0)
    labels = np.concatenate(
        [np.full(len(p), i, np.int64) for i, p in enumerate(paths)])
    return paths, joint, labels


def conformational_distance_matrix(joint: np.ndarray) -> np.ndarray:
    """All-pairs superposed RMSD over a joint (T, S, 3) frame stack —
    upstream ``encore.get_distance_matrix``'s role.  One jitted vmapped
    device call (the DiffusionMap kernel); T² Kabsch problems never
    touch a Python loop."""
    from mdanalysis_mpi_tpu.analysis.diffusionmap import (
        _pairwise_rmsd_device)

    joint = np.ascontiguousarray(joint, np.float32)
    w = np.ones(joint.shape[1], np.float32)
    d = np.asarray(_pairwise_rmsd_device(joint, w), np.float64)
    # exact symmetry + zero diagonal (float noise from the two SVD
    # orders would otherwise leak into AP's similarity ordering)
    d = 0.5 * (d + d.T)
    np.fill_diagonal(d, 0.0)
    return d


def _js_from_counts(pi: np.ndarray, pj: np.ndarray) -> float:
    """Jensen–Shannon divergence (natural log) between two discrete
    distributions given as count vectors; 0 ≤ JS ≤ ln 2."""
    p = pi / pi.sum()
    q = pj / pj.sum()
    m = 0.5 * (p + q)
    with np.errstate(divide="ignore", invalid="ignore"):
        kl_p = np.where(p > 0, p * (np.log(p) - np.log(m)), 0.0).sum()
        kl_q = np.where(q > 0, q * (np.log(q) - np.log(m)), 0.0).sum()
    return float(min(max(0.5 * (kl_p + kl_q), 0.0), LN2))


# ---------------------------------------------------------------------
# Clustering backends (upstream encore.clustering.ClusteringMethod)
# ---------------------------------------------------------------------


class AffinityPropagationNative:
    """Dense affinity propagation (Frey & Dueck 2007) — upstream ces's
    default clusterer, re-implemented as whole-matrix NumPy updates.

    Call with a (T, T) *similarity* matrix (ces passes −RMSD); returns
    integer cluster labels (T,).  ``preference`` fills the similarity
    diagonal (more negative → fewer clusters); ``noise_scale`` adds the
    seeded tie-breaking jitter upstream's ``add_noise`` flag injects
    (exact ties otherwise oscillate under damping).
    """

    uses_distance_matrix = True

    def __init__(self, preference: float = -1.0, damping: float = 0.9,
                 max_iter: int = 500, convergence_iter: int = 50,
                 noise_scale: float = 1e-9, seed: int = 0):
        if not 0.5 <= damping < 1.0:
            raise ValueError(f"damping must be in [0.5, 1), got {damping}")
        self.preference = float(preference)
        self.damping = float(damping)
        self.max_iter = int(max_iter)
        self.convergence_iter = int(convergence_iter)
        self.noise_scale = float(noise_scale)
        self.seed = int(seed)

    def __call__(self, similarity: np.ndarray) -> np.ndarray:
        s = np.array(similarity, np.float64, copy=True)
        t = len(s)
        if s.shape != (t, t):
            raise ValueError(f"similarity must be square, got {s.shape}")
        np.fill_diagonal(s, self.preference)
        if self.noise_scale:
            rng = np.random.default_rng(self.seed)
            scale = self.noise_scale * (np.abs(s).max() or 1.0)
            s += rng.standard_normal(s.shape) * scale
        r = np.zeros_like(s)
        a = np.zeros_like(s)
        idx = np.arange(t)
        stable = 0
        exemplars_prev: np.ndarray | None = None
        for _ in range(self.max_iter):
            # responsibilities: r[i,k] = s[i,k] - max_{k'!=k}(a+s)[i,k']
            as_ = a + s
            first = as_.argmax(axis=1)
            row_max = as_[idx, first]
            as_[idx, first] = -np.inf
            second = as_.max(axis=1)
            r_new = s - row_max[:, None]
            r_new[idx, first] = s[idx, first] - second
            r = self.damping * r + (1.0 - self.damping) * r_new
            # availabilities: a[i,k] = min(0, r[k,k] + sum_{i'!={i,k}}
            # max(0, r[i',k])); a[k,k] = sum_{i'!=k} max(0, r[i',k])
            rp = np.maximum(r, 0.0)
            rp[idx, idx] = r[idx, idx]
            col = rp.sum(axis=0)
            a_new = np.minimum(0.0, col[None, :] - rp)
            # a[k,k] = Σ_{i'≠k} max(0, r[i',k]); col[k] already includes
            # the raw diagonal r[k,k] (rp keeps it), so remove it once
            a_new[idx, idx] = col - r[idx, idx]
            a = self.damping * a + (1.0 - self.damping) * a_new
            exemplars = np.flatnonzero(np.diag(a) + np.diag(r) > 0)
            if (exemplars_prev is not None and len(exemplars)
                    and np.array_equal(exemplars, exemplars_prev)):
                stable += 1
                if stable >= self.convergence_iter:
                    break
            else:
                stable = 0
            exemplars_prev = exemplars
        if exemplars_prev is None or len(exemplars_prev) == 0:
            # degenerate preference: everything is one cluster
            return np.zeros(t, np.int64)
        exemplars = exemplars_prev
        labels = s[:, exemplars].argmax(axis=1)
        labels[exemplars] = np.arange(len(exemplars))
        return labels.astype(np.int64)


class KMeansNative:
    """Seeded k-means++ / Lloyd clustering on aligned flattened
    coordinates (upstream's sklearn-backed ``KMeans`` option).  Call
    with the joint (T, p) coordinate matrix; returns labels (T,)."""

    uses_distance_matrix = False

    def __init__(self, n_clusters: int, max_iter: int = 300,
                 tol: float = 1e-6, seed: int = 0):
        if n_clusters < 1:
            raise ValueError(f"n_clusters must be >= 1, got {n_clusters}")
        self.n_clusters = int(n_clusters)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.seed = int(seed)

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        t = len(x)
        k = min(self.n_clusters, t)
        rng = np.random.default_rng(self.seed)
        # k-means++ seeding
        centers = np.empty((k, x.shape[1]))
        centers[0] = x[rng.integers(t)]
        d2 = ((x - centers[0]) ** 2).sum(axis=1)
        for c in range(1, k):
            probs = d2 / d2.sum() if d2.sum() > 0 else None
            centers[c] = x[rng.choice(t, p=probs)]
            d2 = np.minimum(d2, ((x - centers[c]) ** 2).sum(axis=1))
        x_sq = (x * x).sum(axis=1)
        for _ in range(self.max_iter):
            # ||x-c||² = ||x||² - 2 x·c + ||c||² as one (T, k) matmul —
            # no (T, k, p) broadcast temporary at MD feature widths
            d2_all = (x_sq[:, None] - 2.0 * (x @ centers.T)
                      + (centers * centers).sum(axis=1)[None, :])
            labels = d2_all.argmin(axis=1)
            new_centers = centers.copy()
            for c in range(k):
                members = x[labels == c]
                if len(members):
                    new_centers[c] = members.mean(axis=0)
            shift = float(((new_centers - centers) ** 2).sum())
            centers = new_centers
            if shift <= self.tol:
                break
        return labels.astype(np.int64)


def ces(ensembles, select: str = "name CA", align: bool = True,
        clustering_method=None, distance_matrix: np.ndarray | None = None):
    """Upstream ``encore.ces``: cluster the JOINT frame set of all
    ensembles, read each ensemble's cluster-population distribution,
    and return the pairwise Jensen–Shannon divergence matrix
    (natural log, bounded by ln 2) plus details.

    ``clustering_method`` defaults to
    ``AffinityPropagationNative(preference=-1.0)`` (upstream's
    default); any callable with a ``uses_distance_matrix`` attribute
    works — ``True`` receives −RMSD similarities, ``False`` the
    flattened (T, 3S) coordinates (Kabsch-aligned to the first frame
    when ``align=True``; the distance-matrix path needs no alignment —
    superposed RMSD is rigid-motion-invariant).
    """
    paths, joint, frame_ens = _gather_paths(ensembles, select)
    method = (clustering_method if clustering_method is not None
              else AffinityPropagationNative())
    if getattr(method, "uses_distance_matrix", True):
        d = (np.asarray(distance_matrix, np.float64)
             if distance_matrix is not None
             else conformational_distance_matrix(joint))
        if d.shape != (len(joint), len(joint)):
            raise ValueError(
                f"distance_matrix shape {d.shape} does not match the "
                f"{len(joint)} joint frames")
        labels = method(-d)
    else:
        if distance_matrix is not None:
            raise ValueError(
                f"{type(method).__name__} clusters coordinates, not "
                "distances; the supplied distance_matrix would be "
                "silently ignored — drop it or use a distance-matrix "
                "clusterer (e.g. AffinityPropagationNative)")
        d = None
        if align:
            joint = align_path(joint, joint[0])
        labels = method(joint.reshape(len(joint), -1))
    labels = np.asarray(labels, np.int64)
    n_clusters = int(labels.max()) + 1 if len(labels) else 0
    k = len(paths)
    counts = np.zeros((k, n_clusters), np.float64)
    for e in range(k):
        counts[e] = np.bincount(labels[frame_ens == e],
                                minlength=n_clusters)
    out = np.zeros((k, k))
    for i in range(k):
        for j in range(i + 1, k):
            out[i, j] = out[j, i] = _js_from_counts(counts[i], counts[j])
    return out, {"labels": labels, "populations": counts,
                 "n_clusters": n_clusters, "distance_matrix": d}


# ---------------------------------------------------------------------
# Dimensionality reduction + KDE (dres)
# ---------------------------------------------------------------------


class StochasticProximityEmbeddingNative:
    """Stochastic proximity embedding (Agrafiotis 2003) — upstream
    dres's default reducer.  Call with a (T, T) distance matrix;
    returns (T, dimension) embedded coordinates.

    Batched variant (documented deviation): each cycle draws
    ``nstep`` random pairs at once and applies the pairwise spring
    updates with ``np.add.at`` scatter-accumulation, instead of the
    sequential one-pair-at-a-time original — same fixed points,
    Python-loop-free, deterministic under ``seed``.  The learning rate
    anneals linearly ``max_lam → min_lam`` across ``ncycle`` cycles.
    """

    def __init__(self, dimension: int = 3, distance_cutoff: float = 1.5,
                 min_lam: float = 0.1, max_lam: float = 2.0,
                 ncycle: int = 100, nstep: int = 10000, seed: int = 0):
        if dimension < 1:
            raise ValueError(f"dimension must be >= 1, got {dimension}")
        self.dimension = int(dimension)
        self.distance_cutoff = float(distance_cutoff)
        self.min_lam = float(min_lam)
        self.max_lam = float(max_lam)
        self.ncycle = int(ncycle)
        self.nstep = int(nstep)
        self.seed = int(seed)

    def __call__(self, d: np.ndarray) -> np.ndarray:
        d = np.asarray(d, np.float64)
        t = len(d)
        if d.shape != (t, t):
            raise ValueError(f"distance matrix must be square, got "
                             f"{d.shape}")
        rng = np.random.default_rng(self.seed)
        scale = float(d.max()) or 1.0
        x = rng.uniform(-0.5, 0.5, (t, self.dimension)) * scale
        eps = 1e-10
        for cycle in range(self.ncycle):
            lam = (self.max_lam - (self.max_lam - self.min_lam)
                   * cycle / max(self.ncycle - 1, 1))
            i = rng.integers(0, t, self.nstep)
            j = rng.integers(0, t, self.nstep)
            keep = i != j
            i, j = i[keep], j[keep]
            rij = d[i, j]
            diff = x[i] - x[j]
            dij = np.sqrt((diff * diff).sum(axis=1)) + eps
            # move only pairs whose target is local (below the cutoff)
            # or whose embedding is overstretched relative to target
            act = (rij < self.distance_cutoff) | (dij < rij)
            if not act.any():
                continue
            i, j, rij = i[act], j[act], rij[act]
            diff, dij = diff[act], dij[act]
            step = (0.5 * lam * (rij - dij) / dij)[:, None] * diff
            # batched stability: a point drawn into many pairs this
            # cycle receives the AVERAGE of its spring displacements,
            # not the sum — the sequential algorithm's per-pair move
            # magnitude is preserved while scatter-accumulated updates
            # cannot compound into divergence
            acc = np.zeros_like(x)
            cnt = np.zeros(t)
            np.add.at(acc, i, step)
            np.add.at(acc, j, -step)
            np.add.at(cnt, i, 1.0)
            np.add.at(cnt, j, 1.0)
            x += acc / np.maximum(cnt, 1.0)[:, None]
        return x


class GaussianKDE:
    """Scott's-rule full-covariance Gaussian kernel density estimate
    over points (n, d) — the scipy.stats.gaussian_kde role in upstream
    dres, with seeded sampling."""

    def __init__(self, points: np.ndarray):
        pts = np.asarray(points, np.float64)
        if pts.ndim != 2 or len(pts) < 2:
            raise ValueError("KDE needs a (n>=2, d) point set")
        self.points = pts
        n, d = pts.shape
        factor = n ** (-1.0 / (d + 4))              # Scott 1992
        cov = np.cov(pts, rowvar=False).reshape(d, d)
        # degenerate spread (identical points along an axis): jitter
        # relative to the data scale so cholesky exists
        jitter = 1e-12 * max(float(np.trace(cov)) / d, 1e-30)
        self.h = factor ** 2 * cov + jitter * np.eye(d)
        self.chol = np.linalg.cholesky(self.h)
        self.log_norm = (0.5 * d * np.log(2.0 * np.pi)
                         + np.log(np.diag(self.chol)).sum())

    def logpdf(self, x: np.ndarray) -> np.ndarray:
        """ln f(x) at query points (m, d) → (m,)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        m, d = x.shape
        n = len(self.points)
        resid = x[:, None, :] - self.points[None, :, :]      # (m, n, d)
        # whiten against the bandwidth: solve L z = rᵀ in one batch
        z = np.linalg.solve(self.chol, resid.reshape(-1, d).T)
        q = (z * z).sum(axis=0).reshape(m, n)   # squared Mahalanobis
        lk = -0.5 * q - self.log_norm           # per-kernel log density
        peak = lk.max(axis=1, keepdims=True)
        return (peak[:, 0] + np.log(np.exp(lk - peak).sum(axis=1))
                - np.log(n))

    def sample(self, n: int, rng: np.random.Generator) -> np.ndarray:
        centers = self.points[rng.integers(0, len(self.points), n)]
        noise = rng.standard_normal((n, self.points.shape[1]))
        return centers + noise @ self.chol.T


def dres(ensembles, select: str = "name CA",
         dimensionality_reduction_method=None, nsamples: int = 1000,
         distance_matrix: np.ndarray | None = None, seed: int = 0):
    """Upstream ``encore.dres``: embed the joint frames into a low-
    dimensional space, model each ensemble's density there with a
    Gaussian KDE, and Monte-Carlo-estimate the pairwise Jensen–Shannon
    divergences (natural log, clamped to [0, ln 2]).

    No ``align`` knob: everything downstream derives from the
    superposed-RMSD matrix, which is rigid-motion-invariant per frame
    — a pre-alignment could not change the result."""
    paths, joint, frame_ens = _gather_paths(ensembles, select)
    method = (dimensionality_reduction_method
              if dimensionality_reduction_method is not None
              else StochasticProximityEmbeddingNative())
    d = (np.asarray(distance_matrix, np.float64)
         if distance_matrix is not None
         else conformational_distance_matrix(joint))
    if d.shape != (len(joint), len(joint)):
        raise ValueError(
            f"distance_matrix shape {d.shape} does not match the "
            f"{len(joint)} joint frames")
    embedded = np.asarray(method(d), np.float64)
    k = len(paths)
    kdes = [GaussianKDE(embedded[frame_ens == e]) for e in range(k)]
    rng = np.random.default_rng(seed)
    samples = [kde.sample(nsamples, rng) for kde in kdes]
    logp = [[kdes[e].logpdf(samples[s]) for e in range(k)]
            for s in range(k)]
    out = np.zeros((k, k))
    for i in range(k):
        for j in range(i + 1, k):
            # KL(P||M) ~ E_{x~P}[ln p - ln m], m = (p + q)/2
            lm_i = np.logaddexp(logp[i][i], logp[i][j]) - np.log(2.0)
            lm_j = np.logaddexp(logp[j][i], logp[j][j]) - np.log(2.0)
            js = 0.5 * ((logp[i][i] - lm_i).mean()
                        + (logp[j][j] - lm_j).mean())
            out[i, j] = out[j, i] = float(min(max(js, 0.0), LN2))
    return out, {"embedded": embedded, "frame_ensemble": frame_ens,
                 "distance_matrix": d}
