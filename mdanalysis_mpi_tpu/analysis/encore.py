"""Ensemble similarity (upstream ``MDAnalysis.analysis.encore``).

:func:`hes` — the Harmonic Ensemble Similarity: model each ensemble
(trajectory) as a multivariate Gaussian over its flattened (3N,)
configuration vectors and take the symmetrized harmonic divergence

    d(A, B) = ¼ (μ_A − μ_B)ᵀ (Σ_A⁻¹ + Σ_B⁻¹) (μ_A − μ_B)
            + ½ tr(Σ_A Σ_B⁻¹ + Σ_B Σ_A⁻¹ − 2·I)

(upstream ``encore.hes``'s closed form).  Ensembles are rigid-aligned
to a common reference first (every frame Kabsch-superposed onto the
first ensemble's first frame — without this, μ differences would be
dominated by tumbling).

Covariance estimators: ``"shrinkage"`` (default, Ledoit–Wolf 2004 —
the estimator upstream defaults to, SPD even with far fewer frames
than 3N dimensions) or ``"ml"`` (sample covariance; needs T ≫ 3N and a
jitter to invert).

TPU-first shape: each ensemble's covariance is ONE (T, 3N)ᵀ(T, 3N)
matmul — the MXU-shaped reduction PCA already uses — and the
cross-ensemble terms are Cholesky solves; everything runs in float64
on host at typical Cα sizes (3N ~ 10³), with the per-frame alignment
reusing the shared QCP machinery (ops/host.py).

Scope note: upstream encore also ships clustering/dimensionality-based
similarities (ces/dres); those depend on scikit-learn-style machinery
and are out of scope — hes is the closed-form, testable core.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.psa import _as_path, align_path


def ledoit_wolf_covariance(x: np.ndarray) -> np.ndarray:
    """Ledoit–Wolf shrinkage covariance of rows of ``x`` (T, p):
    ``(1−δ)·S + δ·m·I`` with the closed-form optimal δ (LW 2004).
    SPD for any T ≥ 2."""
    x = np.asarray(x, np.float64)
    t, p = x.shape
    if t < 2:
        raise ValueError(f"need at least 2 frames, got {t}")
    xc = x - x.mean(axis=0)
    s = xc.T @ xc / t
    m = np.trace(s) / p
    d2 = ((s - m * np.eye(p)) ** 2).sum() / p
    # b̄² = (1/T²) Σ_t ‖x_t x_tᵀ − S‖_F² / p, via the expansion
    # ‖x xᵀ − S‖² = (xᵀx)² − 2 xᵀSx + ‖S‖²
    xtx = (xc * xc).sum(axis=1)
    xsx = ((xc @ s) * xc).sum(axis=1)
    s_f2 = (s * s).sum()
    b2 = (xtx ** 2 - 2.0 * xsx + s_f2).sum() / (t * t) / p
    b2 = min(b2, d2)
    delta = b2 / d2 if d2 > 0 else 1.0
    return (1.0 - delta) * s + delta * m * np.eye(p)


def _aligned_flat(paths: list) -> list:
    """Kabsch-align every frame of every path onto paths[0][0] (the
    shared :func:`~mdanalysis_mpi_tpu.analysis.psa.align_path`);
    return flattened (T_i, 3N) float64 arrays."""
    ref = paths[0][0]
    return [align_path(p, ref).reshape(len(p), -1) for p in paths]


def hes(ensembles, select: str = "name CA", align: bool = True,
        cov_estimator: str = "shrinkage"):
    """Upstream ``encore.hes``: ``(d_matrix, details)`` with
    ``d_matrix`` the symmetric (k, k) harmonic divergences and
    ``details`` carrying each ensemble's ``means``/``covariances``."""
    if cov_estimator not in ("shrinkage", "ml"):
        raise ValueError(
            f"cov_estimator must be 'shrinkage' or 'ml', got "
            f"{cov_estimator!r}")
    paths = [_as_path(e, select) for e in ensembles]
    if len(paths) < 2:
        raise ValueError("hes needs at least two ensembles")
    widths = {p.shape[1] for p in paths}
    if len(widths) != 1:
        raise ValueError(
            f"ensembles have different selection widths {sorted(widths)}")
    if min(len(p) for p in paths) < 2:
        raise ValueError("every ensemble needs at least 2 frames")
    flats = (_aligned_flat(paths) if align
             else [p.reshape(len(p), -1).astype(np.float64)
                   for p in paths])
    p_dim = flats[0].shape[1]
    means = [f.mean(axis=0) for f in flats]
    # a zero-variance ensemble (all frames identical) has no Gaussian
    # model — fail naming the input, not with a downstream LinAlgError
    for idx, (f, mu) in enumerate(zip(flats, means)):
        var_sum = float(((f - mu) ** 2).sum())
        # relative: the mean of identical frames differs from them by
        # float roundoff, so exact zero would miss real frozen inputs
        if var_sum <= 1e-18 * max(float((f ** 2).sum()), 1e-30):
            raise ValueError(
                f"ensemble {idx} has zero variance (all frames "
                "identical); hes needs fluctuating ensembles")
    if cov_estimator == "shrinkage":
        covs = [ledoit_wolf_covariance(f) for f in flats]
    else:
        # ML sample covariance + a relative jitter so the inverse
        # exists even at T ≈ p (documented estimator caveat)
        covs = []
        for f, mu in zip(flats, means):
            xc = f - mu
            s = xc.T @ xc / len(f)
            covs.append(s + 1e-9 * (np.trace(s) / p_dim)
                        * np.eye(p_dim))
    k = len(flats)
    # Cholesky is the SPD gate (a clear failure point if an estimator
    # ever regresses); the inverses themselves come from one LU each
    for idx, c in enumerate(covs):
        try:
            np.linalg.cholesky(c)
        except np.linalg.LinAlgError:
            raise ValueError(
                f"ensemble {idx}'s covariance is not positive "
                "definite; use cov_estimator='shrinkage'") from None
    invs = [np.linalg.inv(c) for c in covs]
    d = np.zeros((k, k))
    for i in range(k):
        for j in range(i + 1, k):
            dm = means[i] - means[j]
            quad = 0.25 * dm @ ((invs[i] + invs[j]) @ dm)
            # tr(A @ B) for symmetric A, B without the (p, p) matmul
            tr = 0.5 * ((covs[i] * invs[j]).sum()
                        + (covs[j] * invs[i]).sum() - 2.0 * p_dim)
            d[i, j] = d[j, i] = float(quad + tr)
    return d, {"means": means, "covariances": covs,
               "estimator": cov_estimator}
