"""Static dielectric constant from dipole fluctuations.

Upstream-API mirror (``MDAnalysis.analysis.dielectric.
DielectricConstant``): accumulate the total dipole moment
``M = Σ qᵢ·rᵢ`` per frame and estimate (tin-foil boundary conditions,
upstream's formula and result layout)

    eps_c    = 1 + 4π·(⟨M_c²⟩ − ⟨M_c⟩²) / (ε₀' V k_B T)   per axis c
    eps_mean = 1 + 4π·Σ_c fluct_c / (3 ε₀' V k_B T)

Units are upstream's: charges in e, positions in Å, T in K.
``results.M`` / ``results.M2`` / ``results.fluct`` / ``results.eps``
are per-component 3-vectors; ``results.eps_mean`` is the scalar
dielectric constant.

TPU-first shape: the per-frame dipole is one weighted sum; the
fluctuation accumulates as CHAN MOMENTS of the (B, 3) dipole series
(``ops/moments.py`` — the centered M2 keeps ⟨M²⟩−⟨M⟩² well-conditioned
in float32 where the raw difference catastrophically cancels for
systems with a persistent net dipole), folded on device and psum-merged
across chips.  The group must be charge-neutral (origin independence)
— a hard error, as is a frame without a box.

``make_whole``: upstream re-joins molecules split across the periodic
boundary every frame by default.  Here the same guarantee is provided
by the (all-backend) ``transformations.unwrap`` reader transformation,
so ``make_whole=True`` (the default) REQUIRES one to be attached when
the topology carries bonds — a split molecule contributes a spurious
~box-sized dipole, which must fail loudly, not skew ε.  Pass
``make_whole=False`` for pre-whole trajectories.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, deferred_group
from mdanalysis_mpi_tpu.core.groups import AtomGroup
from mdanalysis_mpi_tpu.ops import host
from mdanalysis_mpi_tpu.ops.moments import merge_moments, psum_moments

#: e²/(4πε₀·Å·k_B) in K — Coulomb energy of unit charges at 1 Å per k_B
_COULOMB_K_A_PER_E2 = 167100.9972


def _dielectric_kernel(params, batch, boxes, mask):
    """(T, ⟨M⟩ (3,), M2 (3,), ΣV, n_boxed) over the batch — dipole
    moments as Chan moments over the frame axis."""
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops._boxmat import batch_box_volumes
    from mdanalysis_mpi_tpu.ops.moments import batch_moments

    (charges,) = params
    m_vec = jnp.einsum("s,bsi->bi", charges, batch)       # (B, 3)
    t, mean, m2 = batch_moments(m_vec[:, None, :], mask)  # (1, 3) each
    w = mask.astype(jnp.float32)
    vols = batch_box_volumes(boxes)
    return (t, mean[0], m2[0], (vols * w).sum(),
            ((vols > 0.0) * w).sum())


def _dielectric_fold(a, b):
    t1, mu1, m21, v1, nb1 = a
    t2, mu2, m22, v2, nb2 = b
    t, mu, m2 = merge_moments((t1, mu1, m21), (t2, mu2, m22))
    return (t, mu, m2, v1 + v2, nb1 + nb2)


def _dielectric_psum(partials, axis_name):
    import jax

    t, mu, m2, v, nb = partials
    t_tot, mu_tot, m2_tot = psum_moments(t, mu, m2, axis_name)
    return (t_tot, mu_tot, m2_tot, jax.lax.psum(v, axis_name),
            jax.lax.psum(nb, axis_name))


class DielectricConstant(AnalysisBase):
    """``DielectricConstant(ag, temperature=300.0).run().results.eps_mean``."""

    def __init__(self, atomgroup: AtomGroup, temperature: float = 300.0,
                 make_whole: bool = True, verbose: bool = False):
        super().__init__(atomgroup.universe, verbose)
        if temperature <= 0:
            raise ValueError(
                f"temperature must be positive, got {temperature}")
        self._ag = atomgroup
        self._temperature = float(temperature)
        self._make_whole = bool(make_whole)

    def _prepare(self):
        t = self._universe.topology
        if t.charges is None:
            raise ValueError(
                "DielectricConstant needs partial charges (topology has "
                "none; use add_TopologyAttr or a PSF)")
        self._idx = self._ag.indices
        if len(self._idx) == 0:
            raise ValueError("selection matched no atoms")
        self._charges = np.asarray(t.charges[self._idx], np.float64)
        net = float(self._charges.sum())
        if abs(net) > 1e-4:
            raise ValueError(
                f"group carries net charge {net:+.4f} e; the dipole "
                "moment is origin-dependent (charge-neutral selection "
                "required, upstream contract)")
        if self._universe.trajectory.ts.dimensions is None:
            raise ValueError(
                "DielectricConstant needs box volumes (trajectory "
                "carries no box)")
        if self._make_whole and t.bonds is not None and len(t.bonds):
            from mdanalysis_mpi_tpu.transformations import unwrap

            xforms = self._universe.trajectory.transformations
            if not any(isinstance(x, unwrap) for x in xforms):
                raise ValueError(
                    "make_whole=True: molecules split across the box "
                    "would contribute spurious box-sized dipoles.  "
                    "Attach the all-backend equivalent first —\n"
                    "    u.trajectory.add_transformations("
                    "transformations.unwrap(u.atoms))\n"
                    "— or pass make_whole=False for trajectories that "
                    "are already whole")
        self._stream = host.StreamingMoments((3,))
        self._vol_sum = 0.0
        self._n_boxed = 0

    # -- serial path --

    def _single_frame(self, ts):
        from mdanalysis_mpi_tpu.lib.mdamath import box_volume

        if ts.dimensions is None:
            raise ValueError(
                f"frame {ts.frame} has no box; volumes are part of the "
                "dielectric formula")
        x = ts.positions[self._idx].astype(np.float64)
        self._stream.update((self._charges[:, None] * x).sum(axis=0))
        self._vol_sum += float(box_volume(ts.dimensions))
        self._n_boxed += 1

    def _serial_summary(self):
        t, mean, m2 = self._stream.summary
        return (float(t), mean, m2, self._vol_sum, float(self._n_boxed))

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _dielectric_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._charges, jnp.float32),)

    _device_combine = staticmethod(_dielectric_psum)
    _device_fold_fn = staticmethod(_dielectric_fold)

    def _identity_partials(self):
        return (0.0, np.zeros(3), np.zeros(3), 0.0, 0.0)

    def _conclude(self, total):
        temperature = self._temperature

        def _finalize():
            t, m_mean, m_m2, v_sum, n_boxed = (
                np.asarray(x, np.float64) for x in total)
            t = float(t)
            if t == 0:
                raise ValueError("DielectricConstant over zero frames")
            if float(n_boxed) != t:
                raise ValueError(
                    f"DielectricConstant: {int(t - float(n_boxed))} of "
                    f"{int(t)} frames have no (or zero-volume) box; "
                    "volumes are part of the formula")
            vol_mean = float(v_sum) / t
            fluct = m_m2 / t                       # centered: no cancel
            pref = 4.0 * np.pi * _COULOMB_K_A_PER_E2 / (
                vol_mean * temperature)
            eps = 1.0 + pref * fluct               # per-axis (upstream)
            eps_mean = 1.0 + pref * float(fluct.sum()) / 3.0
            return {"M": m_mean,
                    "M2": m_m2 / t + m_mean ** 2,  # ⟨M_c²⟩, upstream
                    "fluct": fluct, "volume": vol_mean,
                    "eps": eps, "eps_mean": eps_mean}

        g = deferred_group(_finalize)
        for k in ("M", "M2", "fluct", "volume", "eps", "eps_mean"):
            self.results[k] = g[k]
