"""Lipid leaflet identification (upstream
``MDAnalysis.analysis.leaflet.LeafletFinder``).

Headgroup atoms (e.g. ``"name P*"``) are clustered by spatial
adjacency: two headgroups belong to the same leaflet when they are
within ``cutoff`` of each other (minimum-imaged when ``pbc=True``),
and leaflets are the connected components of that graph — upstream's
networkx construction, realized here as the same union-find the
topology layer uses for bonded fragments.

``LeafletFinder(u, "name P", cutoff=15.0).run()`` (construction runs
the analysis, as upstream; ``run()`` recomputes at the current frame)
→ ``.groups()`` (list of AtomGroups, largest first),
``.groups(i)``, ``.sizes()``.  A well-chosen cutoff yields exactly two
large components — the upper and lower leaflet; upstream's
``optimize_cutoff`` helper is mirrored as :func:`optimize_cutoff`.

Host-side by design: one frame, one sparse neighbor search
(``lib.distances.self_capped_distance`` — the O(N) cell list by
default, brute force as the selectable fallback; neither materializes
the N² matrix), one union-find pass.  The per-frame batch machinery
would add nothing — leaflet assignment is a topology-building step,
not a trajectory reduction.
"""

from __future__ import annotations

import numpy as np


class LeafletFinder:
    """``LeafletFinder(universe, select, cutoff=15.0, pbc=False,
    engine='auto')``; ``engine`` is the pair-pruning backend knob
    (``lib.distances.capped_distance``)."""

    def __init__(self, universe, select: str, cutoff: float = 15.0,
                 pbc: bool = False, engine: str = "auto"):
        if cutoff <= 0:
            raise ValueError(f"cutoff must be positive, got {cutoff}")
        self._u = universe
        ag = universe.select_atoms(select)
        if ag.n_atoms == 0:
            raise ValueError(f"selection {select!r} matches no atoms")
        self._ag = ag
        self._cutoff = float(cutoff)
        self._pbc = bool(pbc)
        self._engine = engine
        self.run()

    def run(self) -> "LeafletFinder":
        """(Re)cluster at the universe's CURRENT frame."""
        from mdanalysis_mpi_tpu.core.box import valid_box_matrix
        from mdanalysis_mpi_tpu.core.topology import label_components
        from mdanalysis_mpi_tpu.lib.distances import self_capped_distance

        ts = self._u.trajectory.ts
        box = None
        if self._pbc:
            if ts.dimensions is None:
                raise ValueError("pbc=True but this frame carries no box")
            # strict: a partially degenerate box would NaN the distance
            # kernel and silently report every headgroup a singleton
            valid_box_matrix(ts.dimensions, "LeafletFinder(pbc=True)")
            box = ts.dimensions
        x = ts.positions[self._ag.indices].astype(np.float64)
        pairs = self_capped_distance(x, self._cutoff, box=box,
                                     return_distances=False,
                                     engine=self._engine)
        labels = label_components(len(x), pairs)
        comps: dict[int, list[int]] = {}
        for i, lab in enumerate(labels):
            comps.setdefault(int(lab), []).append(i)
        # largest first; ties broken by lowest atom index (determinism)
        ordered = sorted(comps.values(),
                         key=lambda m: (-len(m), m[0]))
        self._components = [np.asarray(m, np.int64) for m in ordered]
        return self

    def groups(self, index: int | None = None):
        """All leaflets as AtomGroups (largest first), or one of them."""
        from mdanalysis_mpi_tpu.core.groups import AtomGroup

        ags = [AtomGroup(self._u, self._ag.indices[m])
               for m in self._components]
        if index is None:
            return ags
        return ags[index]

    def sizes(self) -> list:
        return [len(m) for m in self._components]


def optimize_cutoff(universe, select: str, dmin: float = 10.0,
                    dmax: float = 20.0, step: float = 0.5,
                    max_imbalance: float = 0.2, pbc: bool = False,
                    engine: str = "auto"):
    """Scan cutoffs and return ``(cutoff, n_components)`` minimizing
    the component count among cutoffs whose two largest leaflets are
    balanced within ``max_imbalance`` (upstream
    ``leaflet.optimize_cutoff``)."""
    if dmin <= 0 or step <= 0 or dmax < dmin:
        raise ValueError(
            f"need 0 < dmin <= dmax and step > 0, got "
            f"[{dmin}, {dmax}] step {step}")
    best = None
    for cutoff in np.arange(dmin, dmax + 1e-9, step):
        # no try/except: every ValueError reachable here (bad selection,
        # pbc without box) is cutoff-INdependent — swallowing it would
        # scan uselessly and misreport the real error as 'no cutoff'
        lf = LeafletFinder(universe, select, cutoff=float(cutoff),
                           pbc=pbc, engine=engine)
        sizes = lf.sizes()
        if len(sizes) < 2:
            continue
        imbalance = abs(sizes[0] - sizes[1]) / max(sizes[0] + sizes[1], 1)
        if imbalance > max_imbalance:
            continue
        cand = (len(sizes), float(cutoff))
        if best is None or cand < best:
            best = cand
    if best is None:
        raise ValueError(
            "no cutoff in the scanned range produced two balanced "
            "leaflets; widen [dmin, dmax] or check the selection")
    return best[1], best[0]
