"""Dihedral-angle time series: Dihedral and Ramachandran.

Upstream-API mirror (``MDAnalysis.analysis.dihedrals``):
``Dihedral([ag1, ag2, ...]).run()`` → ``results.angles`` (T, K) for K
four-atom groups, and ``Ramachandran(ag).run()`` → ``results.angles``
(T, n_res, 2) φ/ψ backbone angles.  The reference program has no
dihedral analysis; this plugs the upstream surface into the
AnalysisBase executor layer.

TPU-first shape: a *time-series* analysis like RMSD — only the union of
the quadruples' atoms is staged (K dihedrals touch ≤ 4K atoms no matter
how big the system), and all K angles of a frame batch come from one
vectorized gather + cross-product + atan2 kernel
(:mod:`mdanalysis_mpi_tpu.ops.dihedrals`), psum-free, concatenated on
device in frame order.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, Deferred
from mdanalysis_mpi_tpu.ops.dihedrals import dihedral_batch, dihedral_batch_np


# ---- module-level batch kernel (stable identity → cached compiles) ----

def _dihedral_kernel(params, batch, boxes, mask):
    del boxes
    (quads,) = params
    return (dihedral_batch(batch, quads) * mask[:, None], mask)


class Dihedral(AnalysisBase):
    """``Dihedral([ag, ...]).run().results.angles`` — each AtomGroup is
    one dihedral: exactly 4 atoms, in order.  A
    :class:`~mdanalysis_mpi_tpu.core.topologyobjects.TopologyGroup` of
    dihedrals/impropers is accepted directly
    (``Dihedral(u.dihedrals).run()`` — all members, one batched
    kernel)."""

    def __init__(self, atomgroups, verbose: bool = False):
        from mdanalysis_mpi_tpu.analysis.base import reject_updating_groups
        from mdanalysis_mpi_tpu.core.topologyobjects import TopologyGroup

        if isinstance(atomgroups, TopologyGroup):
            tg = atomgroups
            if tg.indices.shape[1] != 4:
                raise ValueError(
                    f"a {tg.kind} TopologyGroup has {tg.indices.shape[1]}"
                    "-atom members; Dihedral needs 4-atom tuples "
                    "(dihedrals or impropers)")
            if len(tg) == 0:
                raise ValueError("empty dihedral TopologyGroup")
            super().__init__(tg._universe, verbose)
            self._quads_global = tg.indices.copy()
            return
        atomgroups = list(atomgroups)
        if not atomgroups:
            raise ValueError("need at least one 4-atom AtomGroup")
        # the groups are snapshotted below and not retained — the
        # run()-time updating-group scan cannot catch them here
        reject_updating_groups(*atomgroups, owner="Dihedral")
        u = atomgroups[0].universe
        for i, ag in enumerate(atomgroups):
            if ag.n_atoms != 4:
                raise ValueError(
                    f"atomgroup {i} has {ag.n_atoms} atoms; a dihedral "
                    "needs exactly 4 (in order)")
            if ag.universe is not u:
                raise ValueError("all atomgroups must share one universe")
        super().__init__(u, verbose)
        self._quads_global = np.stack([ag.indices for ag in atomgroups])

    def _prepare(self):
        # stage only the union of involved atoms; quads become slot
        # indices into that staged selection
        uniq, inv = np.unique(self._quads_global, return_inverse=True)
        self._idx = uniq
        self._quads = inv.reshape(self._quads_global.shape).astype(np.int32)
        self._serial_rows = []

    # -- serial path --

    def _single_frame(self, ts):
        self._serial_rows.append(dihedral_batch_np(
            ts.positions[self._idx][None], self._quads)[0])

    def _serial_summary(self):
        k = len(self._quads)
        rows = (np.stack(self._serial_rows) if self._serial_rows
                else np.empty((0, k)))
        return (rows, np.ones(len(rows)))

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _dihedral_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._quads),)

    _device_combine = None      # time series, concatenated in frame order

    def _identity_partials(self):
        return (np.empty((0, len(self._quads))), np.empty(0))

    def _conclude(self, total):
        vals, mask = total

        def _finalize():
            return np.asarray(vals)[np.asarray(mask) > 0.5]

        self.results.angles = Deferred(_finalize)


def _phi_psi_quads(ag):
    """Backbone (C_prev, N, CA, C) / (N, CA, C, N_next) quadruples for
    every residue OF ``ag`` whose chain neighbors exist in the
    UNIVERSE (upstream semantics: a selection of resids 5-10 still gets
    angles for 5 and 10 by fetching resid 4's C and 11's N from the
    universe).  Neighbors must be the same segment AND resid-contiguous
    (a ±1 resid step — unresolved-loop gaps in a chain must not produce
    gap-spanning pseudo-bonds).  Returns (phi (R, 4), psi (R, 4),
    resindices (R,))."""
    u = ag.universe
    t = u.topology
    if len(ag.indices) == 0 or not t.is_protein[ag.indices].any():
        raise ValueError("Ramachandran needs protein atoms")
    wanted = set(int(r) for r in np.unique(
        t.resindices[ag.indices[t.is_protein[ag.indices]]]))
    # backbone atom map over ALL protein residues (neighbor lookups may
    # leave the selection); shared builder, core/topology.py
    from mdanalysis_mpi_tpu.core.topology import residue_atom_map

    prot_res = np.unique(t.resindices[np.flatnonzero(t.is_protein)])
    atoms = residue_atom_map(t, prot_res, names=("N", "CA", "C"))
    segs = (t.segids if t.segids is not None
            else np.zeros(t.n_atoms, dtype="U1"))

    def _meta(r):
        d = atoms.get(r, {})
        ca = d.get("CA")
        if ca is None:                       # explicit: atom index 0 is falsy
            ca = next(iter(d.values()), None)
        return (None, None) if ca is None else (segs[ca], int(t.resids[ca]))

    phi, psi, rows = [], [], []
    for r in sorted(wanted):
        cur, prev, nxt = atoms.get(r, {}), atoms.get(r - 1), atoms.get(r + 1)
        if not all(k in cur for k in ("N", "CA", "C")):
            continue
        if prev is None or nxt is None or "C" not in prev \
                or "N" not in nxt:
            continue           # chain termini have no phi/psi pair
        seg, rid = _meta(r)
        seg_p, rid_p = _meta(r - 1)
        seg_n, rid_n = _meta(r + 1)
        # same chain AND contiguous resids (gap check)
        if seg_p != seg or seg_n != seg:
            continue
        if rid_p != rid - 1 or rid_n != rid + 1:
            continue
        phi.append((prev["C"], cur["N"], cur["CA"], cur["C"]))
        psi.append((cur["N"], cur["CA"], cur["C"], nxt["N"]))
        rows.append(r)
    if not phi:
        raise ValueError(
            "no residue in the selection has complete (C_prev, N, CA, C, "
            "N_next) backbone atoms")
    return (np.asarray(phi, np.int64), np.asarray(psi, np.int64),
            np.asarray(rows))


class Ramachandran(Dihedral):
    """``Ramachandran(u.select_atoms('protein')).run()`` →
    ``results.angles`` (T, R, 2): φ/ψ per interior residue per frame."""

    def __init__(self, atomgroup, verbose: bool = False):
        from mdanalysis_mpi_tpu.analysis.base import reject_updating_groups

        # same snapshot-and-drop pattern as Dihedral/Janin
        reject_updating_groups(atomgroup, owner="Ramachandran")
        phi, psi, rows = _phi_psi_quads(atomgroup)
        self._n_res = len(rows)
        self.resindices = rows
        AnalysisBase.__init__(self, atomgroup.universe, verbose)
        # interleave (phi_0, psi_0, phi_1, ...) so the base Dihedral
        # machinery computes them all in one kernel call
        self._quads_global = np.empty((2 * self._n_res, 4), np.int64)
        self._quads_global[0::2] = phi
        self._quads_global[1::2] = psi

    def _conclude(self, total):
        vals, mask = total
        n_res = self._n_res

        def _finalize():
            flat = np.asarray(vals)[np.asarray(mask) > 0.5]
            return flat.reshape(len(flat), n_res, 2)

        self.results.angles = Deferred(_finalize)


#: side-chain atom-name variants accepted for the chi dihedral
#: positions (upstream Janin's select strings)
_CHI1_G = ("CG", "CG1", "OG", "OG1", "SG")
_CHI2_D = ("CD", "CD1", "SD", "OD1", "ND1")


def _chi_quads(ag, remove_resnames):
    """(chi1 (R, 4), chi2 (R, 4), resindices) for every protein residue
    of ``ag`` not excluded by resname.  chi1 = N-CA-CB-G,
    chi2 = CA-CB-G-D with G/D from the name-variant tables.  A
    surviving residue MISSING any of the five atoms raises (upstream
    Janin's too-few-atoms error) — silent drops would misalign the
    per-residue rows users zip against their own residue lists."""
    u = ag.universe
    t = u.topology
    if len(ag.indices) == 0 or not t.is_protein[ag.indices].any():
        raise ValueError("Janin needs protein atoms")
    sel = ag.indices[t.is_protein[ag.indices]]
    rn = np.char.upper(t.resnames[sel].astype("U"))
    # a trailing '*' matches by prefix (upstream's 'CYS*' select idiom:
    # disulfide/protonation-state variants CYX/CYS2/CYM all lack a χ₂)
    keep = np.ones(len(sel), dtype=bool)
    for pat in remove_resnames:
        p = pat.upper()
        if p.endswith("*"):
            keep &= ~np.char.startswith(rn, p[:-1])
        else:
            keep &= rn != p
    from mdanalysis_mpi_tpu.core.topology import residue_atom_map

    wanted = np.unique(t.resindices[sel[keep]])
    atoms = residue_atom_map(t, wanted)
    chi1, chi2, rows = [], [], []
    for r in wanted:
        d = atoms[int(r)]
        g_atom = next((d[n] for n in _CHI1_G if n in d), None)
        d_atom = next((d[n] for n in _CHI2_D if n in d), None)
        missing = [n for n in ("N", "CA", "CB") if n not in d]
        if missing or g_atom is None or d_atom is None:
            resname = t.resnames[next(iter(d.values()))]
            raise ValueError(
                f"residue {resname} (resindex {int(r)}) lacks chi1/chi2 "
                f"atoms (missing {missing or 'G/D side-chain atoms'}); "
                "exclude it via remove_resnames")
        chi1.append((d["N"], d["CA"], d["CB"], g_atom))
        chi2.append((d["CA"], d["CB"], g_atom, d_atom))
        rows.append(int(r))
    if not chi1:
        raise ValueError(
            "no residue in the selection carries chi1/chi2 side chains "
            "(all excluded by remove_resnames?)")
    return (np.asarray(chi1, np.int64), np.asarray(chi2, np.int64),
            np.asarray(rows))


class Janin(Dihedral):
    """``Janin(u.select_atoms('protein')).run()`` → ``results.angles``
    (T, R, 2): χ₁/χ₂ per side-chain-bearing residue per frame, wrapped
    to [0, 360) (the upstream Janin-plot convention, unlike
    Ramachandran's (−180, 180]).  ``remove_resnames`` excludes residues
    without a χ₂ (upstream's ``select_remove`` default)."""

    REMOVE_DEFAULT = ("ALA", "CYS*", "GLY", "PRO", "SER", "THR", "VAL")

    def __init__(self, atomgroup, remove_resnames=REMOVE_DEFAULT,
                 verbose: bool = False):
        from mdanalysis_mpi_tpu.analysis.base import reject_updating_groups

        # the group is snapshotted by _chi_quads and not retained —
        # the run()-time updating-group scan cannot catch it here
        reject_updating_groups(atomgroup, owner="Janin")
        chi1, chi2, rows = _chi_quads(atomgroup, remove_resnames)
        self._n_res = len(rows)
        self.resindices = rows
        AnalysisBase.__init__(self, atomgroup.universe, verbose)
        self._quads_global = np.empty((2 * self._n_res, 4), np.int64)
        self._quads_global[0::2] = chi1
        self._quads_global[1::2] = chi2

    def _conclude(self, total):
        vals, mask = total
        n_res = self._n_res

        def _finalize():
            flat = np.asarray(vals)[np.asarray(mask) > 0.5]
            return flat.reshape(len(flat), n_res, 2) % 360.0

        self.results.angles = Deferred(_finalize)
