"""Nucleic-acid geometry functions (upstream ``analysis.nuclinfo``).

Per-call utilities evaluated at the Universe's CURRENT frame, like
upstream: base-pair distances (:func:`wc_pair`, :func:`minor_pair`,
:func:`major_pair`), backbone/glycosidic torsions (:func:`tors` and
the individual ``tors_*``), the 2'-hydroxyl dihedral
(:func:`hydroxyl`) and the two sugar-pucker phase conventions
(:func:`phase_as` Altona–Sundaralingam from the five ν torsions,
:func:`phase_cp` Cremer–Pople from ring-plane displacements).  Purine
vs pyrimidine atom choices come from the same resname tables the
:mod:`~mdanalysis_mpi_tpu.analysis.nucleicacids` module uses
(core/tables.py), with unknown bases refusing loudly.

All angles are degrees; torsions are wrapped to [0, 360) as upstream
does.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.core.tables import (PURINE_RESNAMES,
                                            PYRIMIDINE_RESNAMES)
from mdanalysis_mpi_tpu.lib.distances import calc_dihedrals

__all__ = [
    "wc_pair", "minor_pair", "major_pair", "tors", "tors_alpha",
    "tors_beta", "tors_gamma", "tors_delta", "tors_eps", "tors_zeta",
    "tors_chi", "hydroxyl", "phase_as", "phase_cp",
]


def _one_atom(universe, segid, resid, name) -> np.ndarray:
    sel = universe.select_atoms(
        f"segid {segid} and resid {resid} and name {name}")
    if sel.n_atoms != 1:
        raise ValueError(
            f"selection segid {segid} resid {resid} name {name} matched "
            f"{sel.n_atoms} atoms (need exactly 1)")
    return sel.positions[0].astype(np.float64)


def _residue(universe, segid, resid):
    """(kind, AtomGroup) of one residue — selected ONCE per residue;
    callers needing both the classification and the atom names reuse
    the group."""
    sel = universe.select_atoms(f"segid {segid} and resid {resid}")
    if sel.n_atoms == 0:
        raise ValueError(f"no atoms in segid {segid} resid {resid}")
    rn = str(sel.resnames[0]).upper()
    if rn in PURINE_RESNAMES:
        return "purine", sel
    if rn in PYRIMIDINE_RESNAMES:
        return "pyrimidine", sel
    raise ValueError(
        f"resname {rn!r} (segid {segid} resid {resid}) is neither a "
        "known purine nor pyrimidine (core/tables.py)")


def _base_kind(universe, segid, resid) -> str:
    return _residue(universe, segid, resid)[0]


def _dist(a: np.ndarray, b: np.ndarray) -> float:
    return float(np.linalg.norm(a - b))


def wc_pair(universe, i, bp, seg1="SYSTEM", seg2="SYSTEM") -> float:
    """Watson–Crick N1(purine)–N3(pyrimidine) distance between residue
    ``i`` of ``seg1`` and residue ``bp`` of ``seg2``."""
    a1 = "N1" if _base_kind(universe, seg1, i) == "purine" else "N3"
    a2 = "N1" if _base_kind(universe, seg2, bp) == "purine" else "N3"
    return _dist(_one_atom(universe, seg1, i, a1),
                 _one_atom(universe, seg2, bp, a2))


def minor_pair(universe, i, bp, seg1="SYSTEM", seg2="SYSTEM") -> float:
    """Minor-groove contact: C2 on the purine, O2 on the pyrimidine."""
    a1 = "C2" if _base_kind(universe, seg1, i) == "purine" else "O2"
    a2 = "C2" if _base_kind(universe, seg2, bp) == "purine" else "O2"
    return _dist(_one_atom(universe, seg1, i, a1),
                 _one_atom(universe, seg2, bp, a2))


def major_pair(universe, i, bp, seg1="SYSTEM", seg2="SYSTEM") -> float:
    """Major-groove contact: O6/N6 on the purine (G/A), N4/O4 on the
    pyrimidine (C/T,U) — i.e. G·C → O6–N4, A·T → N6–O4."""
    def atom_for(segid, resid):
        kind, sel = _residue(universe, segid, resid)
        names = set(map(str, sel.names))
        if kind == "purine":
            # G carries O6, A carries N6
            if "O6" in names:
                return "O6"
            if "N6" in names:
                return "N6"
            raise ValueError(
                f"purine segid {segid} resid {resid} has neither O6 "
                "nor N6")
        if "N4" in names:
            return "N4"
        if "O4" in names:
            return "O4"
        raise ValueError(
            f"pyrimidine segid {segid} resid {resid} has neither N4 "
            "nor O4")

    return _dist(_one_atom(universe, seg1, i, atom_for(seg1, i)),
                 _one_atom(universe, seg2, bp, atom_for(seg2, bp)))


def _dihedral_deg(quads) -> float:
    p = [np.asarray(q) for q in quads]
    d = float(np.degrees(calc_dihedrals(
        p[0][None], p[1][None], p[2][None], p[3][None])[0]))
    return d % 360.0


def tors_alpha(universe, seg, i) -> float:
    """α: O3'(i−1)–P(i)–O5'(i)–C5'(i)."""
    return _dihedral_deg([
        _one_atom(universe, seg, i - 1, "O3'"),
        _one_atom(universe, seg, i, "P"),
        _one_atom(universe, seg, i, "O5'"),
        _one_atom(universe, seg, i, "C5'")])


def tors_beta(universe, seg, i) -> float:
    """β: P–O5'–C5'–C4'."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "P"),
        _one_atom(universe, seg, i, "O5'"),
        _one_atom(universe, seg, i, "C5'"),
        _one_atom(universe, seg, i, "C4'")])


def tors_gamma(universe, seg, i) -> float:
    """γ: O5'–C5'–C4'–C3'."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "O5'"),
        _one_atom(universe, seg, i, "C5'"),
        _one_atom(universe, seg, i, "C4'"),
        _one_atom(universe, seg, i, "C3'")])


def tors_delta(universe, seg, i) -> float:
    """δ: C5'–C4'–C3'–O3'."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "C5'"),
        _one_atom(universe, seg, i, "C4'"),
        _one_atom(universe, seg, i, "C3'"),
        _one_atom(universe, seg, i, "O3'")])


def tors_eps(universe, seg, i) -> float:
    """ε: C4'–C3'–O3'–P(i+1)."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "C4'"),
        _one_atom(universe, seg, i, "C3'"),
        _one_atom(universe, seg, i, "O3'"),
        _one_atom(universe, seg, i + 1, "P")])


def tors_zeta(universe, seg, i) -> float:
    """ζ: C3'–O3'–P(i+1)–O5'(i+1)."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "C3'"),
        _one_atom(universe, seg, i, "O3'"),
        _one_atom(universe, seg, i + 1, "P"),
        _one_atom(universe, seg, i + 1, "O5'")])


def tors_chi(universe, seg, i) -> float:
    """χ glycosidic: O4'–C1'–N9–C4 (purine) / O4'–C1'–N1–C2
    (pyrimidine)."""
    if _base_kind(universe, seg, i) == "purine":
        n, c = "N9", "C4"
    else:
        n, c = "N1", "C2"
    return _dihedral_deg([
        _one_atom(universe, seg, i, "O4'"),
        _one_atom(universe, seg, i, "C1'"),
        _one_atom(universe, seg, i, n),
        _one_atom(universe, seg, i, c)])


def tors(universe, seg, i):
    """(α, β, γ, δ, ε, ζ, χ) of residue ``i`` — upstream's 7-tuple."""
    return (tors_alpha(universe, seg, i), tors_beta(universe, seg, i),
            tors_gamma(universe, seg, i), tors_delta(universe, seg, i),
            tors_eps(universe, seg, i), tors_zeta(universe, seg, i),
            tors_chi(universe, seg, i))


def hydroxyl(universe, seg, i) -> float:
    """2'-hydroxyl dihedral C1'–C2'–O2'–HO2' (RNA; DNA residues have
    no O2' and refuse via the 1-atom selection check)."""
    return _dihedral_deg([
        _one_atom(universe, seg, i, "C1'"),
        _one_atom(universe, seg, i, "C2'"),
        _one_atom(universe, seg, i, "O2'"),
        _one_atom(universe, seg, i, "HO2'")])


_RING = ("C1'", "C2'", "C3'", "C4'", "O4'")


def _nu(universe, seg, i):
    """Sugar torsions ν0..ν4 in degrees, SIGNED (−180, 180]."""
    pos = {n: _one_atom(universe, seg, i, n) for n in _RING}
    quads = [
        ("C4'", "O4'", "C1'", "C2'"),   # nu0
        ("O4'", "C1'", "C2'", "C3'"),   # nu1
        ("C1'", "C2'", "C3'", "C4'"),   # nu2
        ("C2'", "C3'", "C4'", "O4'"),   # nu3
        ("C3'", "C4'", "O4'", "C1'"),   # nu4
    ]
    out = []
    for a, b, c, d in quads:
        ang = float(np.degrees(calc_dihedrals(
            pos[a][None], pos[b][None], pos[c][None], pos[d][None])[0]))
        out.append(ang)
    return out


def phase_as(universe, seg, i) -> float:
    """Altona–Sundaralingam pseudorotation phase (degrees in [0, 360))
    from the five sugar torsions."""
    nu0, nu1, nu2, nu3, nu4 = _nu(universe, seg, i)
    num = (nu4 + nu1) - (nu3 + nu0)
    den = 2.0 * nu2 * (np.sin(np.radians(36.0))
                       + np.sin(np.radians(72.0)))
    p = np.degrees(np.arctan2(num, den))
    return float(p % 360.0)


def phase_cp(universe, seg, i) -> float:
    """Cremer–Pople phase of the 5-membered sugar ring (degrees in
    [0, 360)), from out-of-plane displacements about the mean plane.

    Ring order O4'→C1'→C2'→C3'→C4' (the convention that makes the CP
    and AS phases agree in offset for nucleic sugars)."""
    order = ("O4'", "C1'", "C2'", "C3'", "C4'")
    r = np.array([_one_atom(universe, seg, i, n) for n in order])
    r = r - r.mean(axis=0)
    n = 5
    j = np.arange(n)
    r1 = (r * np.sin(2 * np.pi * j / n)[:, None]).sum(axis=0)
    r2 = (r * np.cos(2 * np.pi * j / n)[:, None]).sum(axis=0)
    normal = np.cross(r1, r2)
    normal /= np.linalg.norm(normal)
    z = r @ normal
    a = np.sqrt(2.0 / n) * (z * np.cos(4 * np.pi * j / n)).sum()
    b = -np.sqrt(2.0 / n) * (z * np.sin(4 * np.pi * j / n)).sum()
    p = np.degrees(np.arctan2(b, a))
    return float(p % 360.0)
