"""Nucleic-acid pair distances (upstream
``MDAnalysis.analysis.nucleicacids``).

:class:`NucPairDist` — per-frame distances between explicit atom
pairs; :class:`WatsonCrickDist` — the standard base-pairing distance
per residue pair: the purine's N1 to the pyrimidine's N3 (the central
Watson–Crick hydrogen bond), with the correct atom picked per residue
from its resname.

``WatsonCrickDist(strand1, strand2).run()`` → ``results.pair_distances``
(T, n_pairs) with strand residues paired in order (upstream also
exposes the older ``results.distances`` name; both are provided).

TPU-first shape: a time-series analysis like RMSD — only the union of
paired atoms is staged, and every frame batch's distances come from
one vectorized gather + norm kernel, concatenated in frame order on
any backend.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, Deferred

#: purines contribute N1, pyrimidines N3 — the split lives beside
#: NUCLEIC_RESNAMES in core/tables.py; resnames in NEITHER set raise
#: rather than silently falling through to the wrong atom
from mdanalysis_mpi_tpu.core.tables import (  # noqa: E402
    PURINE_RESNAMES, PYRIMIDINE_RESNAMES,
)


def _pair_dist_kernel(params, batch, boxes, mask):
    import jax.numpy as jnp

    del boxes
    i_slots, j_slots = params
    d = batch[:, i_slots] - batch[:, j_slots]
    return (jnp.sqrt((d ** 2).sum(-1)) * mask[:, None], mask)


class NucPairDist(AnalysisBase):
    """Distances between explicit atom index pairs:
    ``NucPairDist(universe, pairs)`` with ``pairs`` an (n, 2) array of
    global atom indices (the generic base WatsonCrickDist builds on).
    """

    def __init__(self, universe, pairs, verbose: bool = False):
        super().__init__(universe, verbose)
        pairs = np.asarray(pairs, np.int64).reshape(-1, 2)
        if len(pairs) == 0:
            raise ValueError("need at least one atom pair")
        n = universe.topology.n_atoms
        if pairs.min() < 0 or pairs.max() >= n:
            raise ValueError(
                f"pair indices out of range for {n} atoms")
        self._pairs_global = pairs

    def _prepare(self):
        uniq, inv = np.unique(self._pairs_global, return_inverse=True)
        self._idx = uniq
        slots = inv.reshape(self._pairs_global.shape).astype(np.int32)
        self._i_slots = slots[:, 0]
        self._j_slots = slots[:, 1]
        self._serial_rows: list = []

    def _single_frame(self, ts):
        x = ts.positions[self._idx].astype(np.float64)
        d = x[self._i_slots] - x[self._j_slots]
        self._serial_rows.append(np.sqrt((d ** 2).sum(-1)))

    def _serial_summary(self):
        k = len(self._i_slots)
        rows = (np.stack(self._serial_rows) if self._serial_rows
                else np.empty((0, k)))
        return (rows, np.ones(len(rows)))

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _pair_dist_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._i_slots), jnp.asarray(self._j_slots))

    _device_combine = None      # time series, concatenated in frame order

    def _identity_partials(self):
        return (np.empty((0, len(self._pairs_global))), np.empty(0))

    def _conclude(self, total):
        vals, mask = total

        def _finalize():
            return np.asarray(vals, np.float64)[np.asarray(mask) > 0.5]

        d = Deferred(_finalize)
        self.results.pair_distances = d
        self.results.distances = d          # upstream's older name


class WatsonCrickDist(NucPairDist):
    """``WatsonCrickDist(strand1, strand2).run()`` — strands are
    ResidueGroups or AtomGroups whose residues pair IN ORDER; each
    pair's distance is purine-N1 ↔ pyrimidine-N3."""

    def __init__(self, strand1, strand2, n1_name: str = "N1",
                 n3_name: str = "N3", verbose: bool = False):
        from mdanalysis_mpi_tpu.core.topology import residue_atom_map

        res1 = self._strand_residues(strand1)
        res2 = self._strand_residues(strand2)
        if len(res1) != len(res2):
            raise ValueError(
                f"strands pair residue-by-residue: got {len(res1)} vs "
                f"{len(res2)} residues")
        u = strand1.universe
        if strand2.universe is not u:
            raise ValueError("strands must share one universe")
        t = u.topology
        cols = residue_atom_map(t, np.concatenate([res1, res2]))
        pairs = []
        for r1, r2 in zip(res1, res2):
            pairs.append((self._wc_atom(t, cols, int(r1), n1_name,
                                        n3_name),
                          self._wc_atom(t, cols, int(r2), n1_name,
                                        n3_name)))
        super().__init__(u, np.asarray(pairs), verbose=verbose)
        self.resindices = (res1, res2)

    @staticmethod
    def _strand_residues(strand) -> np.ndarray:
        if hasattr(strand, "resindices") and not hasattr(strand,
                                                         "positions"):
            return np.asarray(strand.resindices, np.int64)   # ResidueGroup
        if hasattr(strand, "indices"):                       # AtomGroup
            u = strand.universe
            # preserve strand order (first appearance), not sorted
            ri = u.topology.resindices[strand.indices]
            _, first = np.unique(ri, return_index=True)
            return ri[np.sort(first)].astype(np.int64)
        raise TypeError(
            f"strand must be an AtomGroup or ResidueGroup, got "
            f"{type(strand).__name__}")

    @staticmethod
    def _wc_atom(t, cols, r: int, n1_name: str, n3_name: str) -> int:
        d = cols.get(r, {})
        resname = str(t.resnames[next(iter(d.values()))]).upper() \
            if d else "?"
        if resname in PURINE_RESNAMES:
            want = n1_name
        elif resname in PYRIMIDINE_RESNAMES:
            want = n3_name
        else:
            # purines carry an N3 too, so a silent fallback would
            # return a wrong-but-plausible distance — refuse instead
            # (upstream raises on unrecognized nucleic resnames)
            raise ValueError(
                f"residue {resname} (resindex {r}) is not a known "
                "purine or pyrimidine (core/tables.py); cannot choose "
                "the Watson-Crick atom")
        if want not in d:
            raise ValueError(
                f"residue {resname} (resindex {r}) lacks atom "
                f"{want!r} for the Watson-Crick distance")
        return d[want]
