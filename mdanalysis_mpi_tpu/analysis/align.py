"""Structure-alignment analyses: AverageStructure and AlignTraj.

Mirrors the serial-oracle API of the reference docstring:
``align.AverageStructure(u, u, select=..., ref_frame=0).run()`` →
``.results.universe`` (RMSF.py:9-10) and ``align.AlignTraj(u, ref,
select=..., in_memory=True).run()`` (RMSF.py:12), re-implemented as
batched executor-dispatched analyses (the reference's pass 1,
RMSF.py:76-113).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase
from mdanalysis_mpi_tpu.core.universe import Universe
from mdanalysis_mpi_tpu.ops import host
from mdanalysis_mpi_tpu.parallel.executors import _f32_precision
from mdanalysis_mpi_tpu.parallel.partition import iter_batches, pad_batch


# ---- module-level batch kernels (stable identity → cached compiles) ----

def _avg_all_kernel(params, batch, boxes, mask):
    """Aligned all-atom masked sum: partials (T, Σ aligned) — pass 1 wide
    path (RMSF.py:89-103)."""
    del boxes
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops.align import _HI, superpose_batch

    sel_idx, w, ref_c, ref_com = params
    aligned = superpose_batch(batch, sel_idx, w, ref_c, ref_com)
    return (mask.sum(), jnp.einsum("b,bni->ni", mask, aligned, precision=_HI))


def _avg_sel_kernel(params, batch, boxes, mask):
    """Aligned selection-only masked sum (lean pass-1 path)."""
    del boxes
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops.align import _HI, superpose_selection_batch

    w, ref_c, ref_com = params
    aligned = superpose_selection_batch(batch, w, ref_c, ref_com)
    return (mask.sum(), jnp.einsum("b,bni->ni", mask, aligned, precision=_HI))


from mdanalysis_mpi_tpu.analysis.base import tree_add, tree_psum

_DIV_JIT = None


def _div_jit(s, t):
    global _DIV_JIT
    if _DIV_JIT is None:
        import jax

        _DIV_JIT = jax.jit(lambda s, t: s / t)
    return _DIV_JIT(s, t)


def _reference_sel_coords(reference: Universe, sel_idx, weights, ref_frame: int):
    """Centered float64 selection coords + COM of ``ref_frame``, with the
    cursor save/restore the reference wraps in try/finally
    (RMSF.py:80-87)."""
    traj = reference.trajectory
    current = traj.ts.frame
    try:
        ts = traj[ref_frame]
        sel = ts.positions[sel_idx].astype(np.float64)
        com = host.weighted_center(sel, weights)
        return sel - com, com
    finally:
        traj[current]


class AverageStructure(AnalysisBase):
    """Time-averaged structure after per-frame superposition.

    The reference's pass 1 (RMSF.py:76-113): superpose every frame onto
    ``ref_frame`` of ``reference`` using ``select`` (rotation fit on the
    selection, applied to all atoms — quirk Q5), average, and expose the
    result as ``.results.positions`` plus an in-memory
    ``.results.universe`` (the RMSF.py:113 rebuild).

    ``select_only=True`` averages just the selection (lean path: enough
    for a downstream RMSF of the same selection, and avoids staging
    100k-atom frames when only Cα are needed).
    """

    def __init__(self, mobile: Universe, reference: Universe | None = None,
                 select: str = "all", ref_frame: int = 0,
                 select_only: bool = False, verbose: bool = False,
                 engine: str | None = None):
        super().__init__(mobile, verbose)
        self._reference = reference if reference is not None else mobile
        self._select = select
        self._ref_frame = ref_frame
        self._select_only = select_only
        # engine='fused': int16-staged accelerator runs consume the
        # quantized block directly through the fused Pallas sweeps
        # (ops/pallas_rmsf.py) instead of dequant→superpose→sum
        from mdanalysis_mpi_tpu.ops.pallas_rmsf import validate_engine

        validate_engine(engine)
        if engine == "fused" and not select_only:
            # the wide path rotates ALL atoms (quirk Q5) — a different
            # traffic shape with no fused kernel; silently running
            # unfused would be the perf surprise validate_engine exists
            # to prevent
            raise ValueError(
                "engine='fused' requires select_only=True (the wide "
                "all-atom path has no fused kernel)")
        self._engine = engine

    def _prepare(self):
        u = self._universe
        ag = u.select_atoms(self._select)
        if ag.n_atoms == 0:
            raise ValueError(f"selection {self._select!r} matched no atoms")
        self._sel_idx = ag.indices
        self._weights = ag.masses
        self._ref_sel_c, self._ref_com = _reference_sel_coords(
            self._reference, self._sel_idx, self._weights, self._ref_frame)
        n_out = len(self._sel_idx) if self._select_only else u.topology.n_atoms
        self._acc = np.zeros((n_out, 3), dtype=np.float64)
        self._count = 0

    # -- serial path --

    def _single_frame(self, ts):
        aligned = host.superpose_frame(
            ts.positions, self._sel_idx, self._weights,
            self._ref_sel_c, self._ref_com)
        self._acc += aligned[self._sel_idx] if self._select_only else aligned
        self._count += 1

    def _serial_summary(self):
        return (float(self._count), self._acc)

    # -- batch path --

    def _batch_select(self):
        return self._sel_idx if self._select_only else None

    def _batch_fn(self):
        return _avg_sel_kernel if self._select_only else _avg_all_kernel

    def _quantized_batch(self, transfer_dtype: str):
        """Fused quantized-native path (executors._quantized_native):
        lean pass-1 average straight off the staged int16 block
        (ops/pallas_rmsf.py).  Only the select_only form qualifies —
        the wide path rotates ALL atoms (quirk Q5), a different
        traffic shape."""
        if not self._select_only:
            return None
        from mdanalysis_mpi_tpu.ops import pallas_rmsf as pr

        return pr.quantized_batch(
            "avg", self._engine, transfer_dtype, self._sel_idx,
            self._ref_sel_c, self._ref_com, self._weights)

    def _batch_params(self):
        import jax.numpy as jnp

        w = jnp.asarray(self._weights, jnp.float32)
        ref_c = jnp.asarray(self._ref_sel_c, jnp.float32)
        ref_com = jnp.asarray(self._ref_com, jnp.float32)
        if self._select_only:
            return (w, ref_c, ref_com)
        return (jnp.asarray(self._sel_idx), w, ref_c, ref_com)

    _device_combine = staticmethod(tree_psum)
    _device_fold_fn = staticmethod(tree_add)

    def _identity_partials(self):
        return (0.0, np.zeros_like(self._acc))

    def _conclude(self, total):
        t, s = total
        # zero-frame guard via the host-known frame count — float(t) on a
        # device scalar would synchronize the whole async pipeline here
        if self.n_frames == 0:
            raise ValueError("AverageStructure over zero frames")
        # s may live on device; the division stays there — only the wide
        # path (universe rebuild) forces a host fetch.  Jitted: one eager
        # op costs ~150 ms of dispatch latency on tunneled TPU targets.
        import jax

        if isinstance(s, jax.Array):
            avg = _div_jit(s, t)
        else:
            avg = s / t
        self.results.positions = avg
        if self._select_only:
            self.results.universe = None
        else:
            # RMSF.py:113: rebuild a single-frame in-memory universe —
            # deferred: the rebuild needs a device fetch, which must not
            # happen inside run() (base.Deferred rationale)
            from mdanalysis_mpi_tpu.analysis.base import Deferred

            topology = self._universe.topology

            def _build_universe():
                avg_np = np.asarray(avg, np.float64)
                return Universe(topology, avg_np[None].astype(np.float32))

            self.results.universe = Deferred(_build_universe)


class AlignTraj(AnalysisBase):
    """Align a whole trajectory to a reference frame.

    Serial-oracle API: ``AlignTraj(u, ref, select=..., in_memory=True)
    .run()`` (RMSF.py:12).  With ``in_memory=True`` the mobile
    Universe's trajectory is replaced by an aligned in-memory copy; with
    ``in_memory=False`` (the upstream default workflow) the aligned
    frames are **streamed to ``filename``** in batches through
    :class:`~mdanalysis_mpi_tpu.io.writer.TrajectoryWriter` — never more
    than one batch on the host — and ``.results.universe`` opens the
    written file (``filename`` defaults to ``prefix`` + the mobile
    trajectory's basename, upstream convention).  Per-frame old RMSD
    values are not tracked (use
    :class:`~mdanalysis_mpi_tpu.analysis.rms.RMSD`).

    This is a *map* (frame→frame), not a reduction, so it drives the
    batch kernel directly rather than through the map-reduce executors;
    ``backend="jax"`` batches through the device, ``"serial"`` uses the
    host QCP path.
    """

    def __init__(self, mobile: Universe, reference: Universe | None = None,
                 select: str = "all", ref_frame: int = 0,
                 in_memory: bool = True, filename: str | None = None,
                 prefix: str = "rmsfit_", verbose: bool = False):
        super().__init__(mobile, verbose)
        self._reference = reference if reference is not None else mobile
        self._select = select
        self._ref_frame = ref_frame
        self._in_memory = in_memory
        if not in_memory and filename is None:
            src = getattr(mobile.trajectory, "filename", None)
            if src is None:
                raise ValueError(
                    "in_memory=False needs filename= (the mobile "
                    "trajectory is not file-backed, so there is no name "
                    f"to derive from {prefix!r})")
            import os

            head, tail = os.path.split(src)
            filename = os.path.join(head, prefix + tail)
        self.filename = filename

    def run(self, start=None, stop=None, step=None, frames=None,
            backend: str = "jax", batch_size: int | None = 64, **kwargs):
        if kwargs.pop("resilient", False):
            # this run() drives its own superpose-and-write loop, not
            # the executor hooks the reliability layer wraps; accepting
            # the kwarg would promise fault tolerance it cannot deliver
            raise ValueError(
                "AlignTraj does not support resilient= (it drives its "
                "own write loop); wrap the call in your own retry, or "
                "use AlignedRMSF/AverageStructure for resilient "
                "reductions")
        from mdanalysis_mpi_tpu.io.memory import MemoryReader

        u = self._universe
        frames = list(self._frames(start, stop, step, frames))
        self.n_frames = len(frames)
        ag = u.select_atoms(self._select)
        if ag.n_atoms == 0:
            raise ValueError(f"selection {self._select!r} matched no atoms")
        sel_idx = ag.indices
        weights = ag.masses
        ref_sel_c, ref_com = _reference_sel_coords(
            self._reference, sel_idx, weights, self._ref_frame)
        n = u.topology.n_atoms
        writer = None
        if self._in_memory:
            out = np.empty((len(frames), n, 3), dtype=np.float32)
        else:
            if not frames:
                raise ValueError(
                    "AlignTraj(in_memory=False) selected zero frames — "
                    "nothing to write")
            import os

            sources = [getattr(u.trajectory, "filename", None)]
            # chained trajectories expose their segments as .filenames
            sources += list(getattr(u.trajectory, "filenames", ()) or ())
            target = os.path.abspath(self.filename)
            if any(s is not None and os.path.abspath(s) == target
                   for s in sources):
                raise ValueError(
                    f"output filename {self.filename!r} is (part of) the "
                    "source trajectory itself — opening it for writing "
                    "would destroy the input")
            from mdanalysis_mpi_tpu.io.writer import TrajectoryWriter

            writer = TrajectoryWriter(self.filename, n_atoms=n)
        dims = np.zeros((len(frames), 6), dtype=np.float32)
        have_dims = False

        def emit(a, b, aligned, boxes):
            # writer times/steps keep the writer's running 0..n-1 default
            # so file-backed output matches the in-memory MemoryReader's
            # frame numbering (the two modes must be interchangeable)
            nonlocal have_dims
            if writer is None:
                if boxes is not None:
                    dims[a:b] = boxes
                    have_dims = True
                out[a:b] = aligned
            else:
                writer.write(aligned, dimensions=boxes)

        try:
            if backend == "serial":
                if writer is None:
                    for j, i in enumerate(frames):
                        ts = u.trajectory[i]
                        if ts.dimensions is not None:
                            dims[j] = ts.dimensions
                            have_dims = True
                        out[j] = host.superpose_frame(
                            ts.positions, sel_idx, weights, ref_sel_c,
                            ref_com)
                else:
                    # buffer per-frame output so the file path writes
                    # chunk-at-a-time (one temp-file splice per flush,
                    # not per frame)
                    flush = min(256, len(frames))
                    buf = np.empty((flush, n, 3), np.float32)
                    bboxes = np.zeros((flush, 6), np.float32)
                    any_box = False
                    lo = 0
                    for j, i in enumerate(frames):
                        ts = u.trajectory[i]
                        if ts.dimensions is not None:
                            bboxes[j - lo] = ts.dimensions
                            any_box = True
                        buf[j - lo] = host.superpose_frame(
                            ts.positions, sel_idx, weights, ref_sel_c,
                            ref_com)
                        if j - lo + 1 == flush or j == len(frames) - 1:
                            emit(lo, j + 1, buf[: j - lo + 1],
                                 bboxes[: j - lo + 1] if any_box else None)
                            lo = j + 1
                            any_box = False
                            bboxes[:] = 0   # no stale boxes next window
            else:
                import jax
                import jax.numpy as jnp

                from mdanalysis_mpi_tpu.ops.align import superpose_batch

                bs = batch_size or 64
                idx_d = jnp.asarray(sel_idx)
                w_d = jnp.asarray(weights, jnp.float32)
                refc_d = jnp.asarray(ref_sel_c, jnp.float32)
                com_d = jnp.asarray(ref_com, jnp.float32)
                fn = jax.jit(_f32_precision(
                    lambda b: superpose_batch(b, idx_d, w_d, refc_d, com_d)))
                for a, b in iter_batches(0, len(frames), bs):
                    chunk = frames[a:b]
                    if chunk[-1] - chunk[0] + 1 == len(chunk):
                        block, boxes = u.trajectory.read_block(
                            chunk[0], chunk[-1] + 1)
                    else:
                        tss = [u.trajectory[i] for i in chunk]
                        block = np.stack([ts.positions for ts in tss])
                        if any(ts.dimensions is not None for ts in tss):
                            # frames without a box contribute zeros (the
                            # same value the readers map to dims=None)
                            boxes = np.stack([
                                ts.dimensions if ts.dimensions is not None
                                else np.zeros(6, np.float32) for ts in tss])
                        else:
                            boxes = None
                    padded, mask = pad_batch(block, bs)
                    aligned = np.asarray(fn(jnp.asarray(padded)))
                    emit(a, b, aligned[: b - a], boxes)
        except BaseException:
            if writer is not None:
                # never leave a truncated-but-self-consistent file behind:
                # close() patches the DCD frame count, which would make a
                # partial alignment indistinguishable from a complete one
                writer.close()
                import os

                if os.path.exists(self.filename):
                    os.remove(self.filename)
            raise
        else:
            if writer is not None:
                writer.close()

        if self._in_memory:
            u.trajectory = MemoryReader(
                out, dimensions=dims if have_dims else None)
            self.results.universe = u
        else:
            from mdanalysis_mpi_tpu.io import trajectory_files

            self.results.filename = self.filename
            self.results.universe = Universe(
                u.topology, trajectory_files.open(self.filename))
        return self


def rotation_matrix(mobile: np.ndarray, reference: np.ndarray,
                    weights: np.ndarray | None = None):
    """Optimal least-squares rotation of ``mobile`` onto ``reference``.

    The public form of the reference's ``get_rotation_matrix`` wrapper
    (RMSF.py:43-51, upstream ``align.rotation_matrix``): both inputs are
    (N, 3) coordinates, ALREADY CENTERED on their (weighted) origins as
    upstream requires.  Returns ``(R, rmsd)`` in the upstream
    convention — ``R`` acts on column vectors (``x' = R·x``), so the
    row-vector application is ``mobile @ R.T`` — and ``rmsd`` is the
    minimal (weighted) RMSD after rotation.  Drop-in for the canonical
    upstream recipe ``R, rmsd = rotation_matrix(mob0, ref0);
    positions = positions @ R.T + ref_com``.
    """
    mobile = np.asarray(mobile, np.float64)
    reference = np.asarray(reference, np.float64)
    if mobile.shape != reference.shape or mobile.ndim != 2 \
            or mobile.shape[1] != 3:
        raise ValueError(
            f"mobile/reference must both be (N, 3), got {mobile.shape} "
            f"vs {reference.shape}")
    r = host.qcp_rotation(mobile, reference, weights)
    diff = mobile @ r - reference
    if weights is None:
        rmsd = float(np.sqrt((diff ** 2).sum() / len(mobile)))
    else:
        w = np.asarray(weights, np.float64)
        rmsd = float(np.sqrt((w @ (diff ** 2).sum(axis=1)) / w.sum()))
    return r.T, rmsd


def _fit_group(obj, select: str):
    """Universe-or-AtomGroup → the selection to fit on, respecting group
    membership (``select`` refines WITHIN a passed group, upstream
    semantics)."""
    from mdanalysis_mpi_tpu.core.groups import AtomGroup

    if isinstance(obj, AtomGroup):
        return obj if select == "all" else obj.select_atoms(select)
    return obj.select_atoms(select)


def alignto(mobile, reference, select: str = "all",
            weights: str | None = None):
    """Superpose the mobile Universe/AtomGroup's CURRENT frame onto the
    reference (upstream ``align.alignto``): fit on ``select`` (refined
    within passed AtomGroups), apply the transform to ALL of the mobile
    universe's atoms in place (the reference's per-frame body,
    RMSF.py:99-101, as a one-shot).  Returns ``(old_rmsd, new_rmsd)``
    over the selection.  ``reference`` is required — aligning a frame
    onto itself is always a silent no-op.  ``weights=None`` (upstream
    default): unweighted centering, fit, and RMSD; ``weights="mass"``
    mass-weights all three.  (The trajectory-level classes
    AlignTraj/AverageStructure keep the reference script's own
    convention instead: mass COM + unweighted rotation, RMSF.py:84,48.)
    """
    from mdanalysis_mpi_tpu.core.groups import AtomGroup

    mob_u = mobile.universe if isinstance(mobile, AtomGroup) else mobile
    ag = _fit_group(mobile, select)
    ref_ag = _fit_group(reference, select)
    if ag.n_atoms == 0:
        raise ValueError(f"selection {select!r} matched no atoms")
    if ref_ag.n_atoms != ag.n_atoms:
        raise ValueError(
            f"selection {select!r} sizes differ: mobile {ag.n_atoms} vs "
            f"reference {ref_ag.n_atoms}")
    if weights not in (None, "mass"):
        raise ValueError(f"weights must be None or 'mass', got {weights!r}")
    w = ag.masses if weights == "mass" else None
    wv = w if w is not None else np.ones(ag.n_atoms)
    ref_sel = ref_ag.positions.astype(np.float64)
    ref_com = host.weighted_center(ref_sel, wv)
    ref_c = ref_sel - ref_com
    ts = mob_u.trajectory.ts
    mob_sel = ts.positions[ag.indices].astype(np.float64)
    mob_c = mob_sel - host.weighted_center(mob_sel, wv)

    def _rmsd(x):
        d2 = ((x - ref_c) ** 2).sum(axis=1)
        return float(np.sqrt((wv @ d2) / wv.sum()))

    old = _rmsd(mob_c)
    _, new = rotation_matrix(mob_c, ref_c, w)
    # the one superposition pipeline (COM, QCP rotation, apply-to-all —
    # ops/host.superpose_frame, with its native fast path)
    ts.positions = host.superpose_frame(
        ts.positions, ag.indices, wv, ref_c, ref_com,
        rot_weights=w).astype(np.float32)
    return old, new


#: three-letter → one-letter residue codes (sequence_alignment)
_AA_CODES = {
    "ALA": "A", "ARG": "R", "ASN": "N", "ASP": "D", "CYS": "C",
    "GLN": "Q", "GLU": "E", "GLY": "G", "HIS": "H", "ILE": "I",
    "LEU": "L", "LYS": "K", "MET": "M", "PHE": "F", "PRO": "P",
    "SER": "S", "THR": "T", "TRP": "W", "TYR": "Y", "VAL": "V",
    "HSD": "H", "HSE": "H", "HSP": "H", "HID": "H", "HIE": "H",
    "HIP": "H", "CYX": "C", "CYM": "C", "MSE": "M",
}


def _residue_letters(ag) -> tuple:
    """(one-letter string, per-residue resindices) for a protein group."""
    t = ag.universe.topology
    res = t.resindices[ag.indices]
    _, first = np.unique(res, return_index=True)
    order = np.sort(first)
    rows = res[order]
    letters = []
    for a in ag.indices[order]:
        rn = str(t.resnames[a]).upper()
        letters.append(_AA_CODES.get(rn, "X"))
    return "".join(letters), rows


def sequence_alignment(mobile, reference, match: float = 2.0,
                       mismatch: float = -1.0, gap_open: float = -2.0,
                       gap_extend: float = -0.1):
    """Global (Needleman–Wunsch, AFFINE gaps — full Gotoh, including
    the cross-gap X↔Y transitions so adjacent insertion+deletion paths
    are representable under any scoring) alignment of two groups'
    residue sequences (upstream ``align.sequence_alignment``,
    reimplemented without Biopython; upstream's default scoring:
    match 2, mismatch −1, gap open −2, gap extend −0.1).  Returns
    ``(seq_mobile, seq_reference, pairs)`` — the two gapped sequences
    and the (K, 2) array of ALIGNED residue index pairs
    ``[mobile_resindex, reference_resindex]`` (matched columns only),
    the input ``align.fasta2select``-style workflows need to fit
    structures with differing sequences.

    The DP runs one vectorized row at a time (the in-row Y recurrence
    is a prefix max), and the traceback re-derives each step with
    EXACT float comparisons against the forward pass's own arithmetic
    — no relative tolerances that could misread open-vs-extend on long
    high-scoring chains.
    """
    s1, r1 = _residue_letters(mobile)
    s2, r2 = _residue_letters(reference)
    if not s1 or not s2:
        raise ValueError("both groups need at least one residue")
    n, m = len(s1), len(s2)
    neg = -1e18
    M = np.full((n + 1, m + 1), neg)
    X = np.full((n + 1, m + 1), neg)   # gap in reference (consumes s1)
    Y = np.full((n + 1, m + 1), neg)   # gap in mobile (consumes s2)
    M[0, 0] = 0.0
    X[1:, 0] = gap_open + np.arange(n) * gap_extend
    Y[0, 1:] = gap_open + np.arange(m) * gap_extend
    s2b = np.frombuffer(s2.encode(), np.uint8)
    for i in range(1, n + 1):
        sub = np.where(s2b == ord(s1[i - 1]), match, mismatch)
        prev_best = np.maximum(np.maximum(M[i - 1], X[i - 1]), Y[i - 1])
        M[i, 1:] = prev_best[:-1] + sub
        X[i] = np.maximum(np.maximum(M[i - 1], Y[i - 1]) + gap_open,
                          X[i - 1] + gap_extend)
        X[i, 0] = gap_open + (i - 1) * gap_extend
        # Y's in-row recurrence Y[j] = max(base[j-1]+open, Y[j-1]+ext)
        # as a prefix max of (candidate - j*ext)
        base = np.maximum(M[i], X[i]) + gap_open
        cand = np.full(m + 1, neg)
        cand[1:] = base[:-1] - np.arange(1, m + 1) * gap_extend
        cand[0] = Y[i, 0]
        Y[i] = np.maximum.accumulate(cand) \
            + np.arange(m + 1) * gap_extend
    # traceback — every comparison recomputes the forward pass's exact
    # float expression, so abs-1e-9 equality is bit-safe
    def _eq(a, b):
        return abs(a - b) <= 1e-9

    a1, a2, pairs = [], [], []
    i, j = n, m
    state = int(np.argmax([M[n, m], X[n, m], Y[n, m]]))
    while i > 0 or j > 0:
        if state == 0 and i > 0 and j > 0:
            a1.append(s1[i - 1])
            a2.append(s2[j - 1])
            pairs.append((int(r1[i - 1]), int(r2[j - 1])))
            i -= 1
            j -= 1
            state = int(np.argmax([M[i, j], X[i, j], Y[i, j]]))
        elif state == 1 and i > 0:
            a1.append(s1[i - 1])
            a2.append("-")
            if _eq(X[i, j], M[i - 1, j] + gap_open):
                state = 0
            elif _eq(X[i, j], Y[i - 1, j] + gap_open):
                state = 2
            else:
                state = 1                       # extension
            i -= 1
        elif state == 2 and j > 0:
            a1.append("-")
            a2.append(s2[j - 1])
            if _eq(Y[i, j], M[i, j - 1] + gap_open):
                state = 0
            elif _eq(Y[i, j], X[i, j - 1] + gap_open):
                state = 1
            else:
                state = 2
            j -= 1
        else:                     # boundary: only one direction remains
            state = 1 if i > 0 else 2
    return ("".join(reversed(a1)), "".join(reversed(a2)),
            np.asarray(list(reversed(pairs)), np.int64).reshape(-1, 2))
