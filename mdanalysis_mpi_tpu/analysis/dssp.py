"""Secondary-structure assignment (upstream ``MDAnalysis.analysis.dssp``).

Three-state DSSP ('H' helix / 'E' strand / '-' loop), the same
simplified algorithm upstream wraps (pydssp):

1. amide hydrogens are ESTIMATED from backbone geometry (upstream's
   ``guess_hydrogens``): ``H_i = N_i + 1.01 Å · unit(unit(N_i−C_{i−1})
   + unit(N_i−CA_i))`` — the bisector of the two N-neighbor directions;
2. the Kabsch–Sander electrostatic H-bond energy for donor NH(i) →
   acceptor CO(j),

       E = 0.084 · 332 · (1/d_ON + 1/d_CH − 1/d_OH − 1/d_CN) kcal/mol,

   with a bond when E < −0.5 (|i−j| ≤ 1 excluded);
3. patterns on the (n, n) H-bond map: n-turns (NH(i+k)→CO(i),
   k = 3, 4, 5) in consecutive pairs mark helices; parallel /
   antiparallel bridge patterns mark strands; everything else is loop.

``DSSP(u).run()`` → ``results.dssp`` (T, n_res) of 'H'/'E'/'-' and
``results.resindices``.  TPU-first shape: step 2 — the O(n²) part — is
one batched kernel (gathers + three pairwise 1/r matrices) producing
per-frame boolean H-bond maps, concatenated in frame order; the
pattern logic (step 3) is tiny boolean shifting done on host in
``_conclude``.  The serial oracle computes the identical map in
float64; parity and the pattern rules are pinned by analytic fixtures
(ideal turn ladders, bridge patterns, a hand-built N-H···O=C geometry).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, deferred_group

#: Kabsch–Sander constant: 0.084 e² charge product × 332 kcal·Å/mol·e²
_KS = 0.084 * 332.0
_E_CUT = -0.5


def _unit_np(v):
    return v / (np.linalg.norm(v, axis=-1, keepdims=True) + 1e-30)


def _estimate_h_np(n, ca, c):
    """Amide H for residues 1..n-1 (residue 0 has no preceding C)."""
    u = _unit_np(n[1:] - c[:-1]) + _unit_np(n[1:] - ca[1:])
    return n[1:] + 1.01 * _unit_np(u)


def _hbond_map_np(n, ca, c, o) -> np.ndarray:
    """(n_res, n_res) bool: NH(i) donates to CO(j) (float64 oracle)."""
    nres = len(n)
    h = _estimate_h_np(n, ca, c)

    def inv_d(a, b):
        return 1.0 / (np.linalg.norm(a[:, None] - b[None], axis=-1)
                      + 1e-30)

    # donors are residues 1..nres-1 (rows 1..); acceptors all residues
    e = np.full((nres, nres), np.inf)
    e[1:] = _KS * (inv_d(n[1:], o) + inv_d(h, c)
                   - inv_d(h, o) - inv_d(n[1:], c))
    hb = e < _E_CUT
    i = np.arange(nres)
    hb[np.abs(i[:, None] - i[None]) <= 1] = False
    return hb


def _dssp_kernel(params, batch, boxes, mask):
    """Batched twin: (B, S, 3) staged backbone union → per-frame
    H-bond maps (B, n_res, n_res) bool (as float for masking), a
    time-series family output."""
    import jax.numpy as jnp

    del boxes
    n_s, ca_s, c_s, o_s = params
    n = batch[:, n_s]
    ca = batch[:, ca_s]
    c = batch[:, c_s]
    o = batch[:, o_s]

    def unit(v):
        return v / (jnp.linalg.norm(v, axis=-1, keepdims=True) + 1e-30)

    u = unit(n[:, 1:] - c[:, :-1]) + unit(n[:, 1:] - ca[:, 1:])
    h = n[:, 1:] + 1.01 * unit(u)

    def inv_d(a, b):
        return 1.0 / (jnp.linalg.norm(a[:, :, None] - b[:, None],
                                      axis=-1) + 1e-30)

    nres = n.shape[1]
    e = _KS * (inv_d(n[:, 1:], o) + inv_d(h, c)
               - inv_d(h, o) - inv_d(n[:, 1:], c))
    e = jnp.concatenate(
        [jnp.full((e.shape[0], 1, nres), jnp.inf, e.dtype), e], axis=1)
    hb = e < _E_CUT
    i = jnp.arange(nres)
    hb = hb & (jnp.abs(i[:, None] - i[None]) > 1)
    return (hb.astype(jnp.float32) * mask[:, None, None], mask)


def assign_from_hbond_map(hb: np.ndarray) -> np.ndarray:
    """(n, n) bool H-bond map (NH(i)→CO(j)) → (n,) array of
    'H'/'E'/'-' (the pydssp 3-state pattern rules)."""
    n = len(hb)
    out = np.full(n, "-", dtype="U1")

    def turn(k):
        # turn_k[i]: NH(i+k) donates to CO(i)
        t = np.zeros(n, dtype=bool)
        if n > k:
            t[:n - k] = hb[np.arange(k, n), np.arange(n - k)]
        return t

    helix = np.zeros(n, dtype=bool)
    for k in (3, 4, 5):
        t = turn(k)
        # two consecutive k-turns starting at i-1 and i → residues
        # i .. i+k-1 are helical (the pydssp consecutive-turn rule)
        start = np.zeros(n, dtype=bool)
        start[1:] = t[:-1] & t[1:]
        for i in np.flatnonzero(start):
            helix[i:i + k] = True

    # bridges (Kabsch-Sander): Hbond(a, b) here = hb[a, b] (NH(a)→CO(b))
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    ok = np.abs(ii - jj) >= 3          # no bridge with near neighbors

    def hb_at(a, b):
        valid = (a >= 0) & (a < n) & (b >= 0) & (b < n)
        res = np.zeros_like(a, dtype=bool)
        res[valid] = hb[a[valid], b[valid]]
        return res

    para = ((hb_at(ii - 1, jj) & hb_at(jj, ii + 1))
            | (hb_at(jj - 1, ii) & hb_at(ii, jj + 1)))
    anti = ((hb_at(ii, jj) & hb_at(jj, ii))
            | (hb_at(ii - 1, jj + 1) & hb_at(jj - 1, ii + 1)))
    pair = (para | anti) & ok
    bridge = pair.any(axis=1)

    out[bridge] = "E"
    out[helix & ~bridge] = "H"         # strand wins ties (pydssp order)
    return out


class DSSP(AnalysisBase):
    """``DSSP(u).run()`` → ``results.dssp`` (T, n_res) of 'H'/'E'/'-'.

    Needs the protein backbone atoms N, CA, C, O per residue (amide
    hydrogens are estimated — upstream's ``guess_hydrogens=True``
    path, which is also its only batch-friendly one)."""

    def __init__(self, universe, select: str = "protein",
                 verbose: bool = False):
        super().__init__(universe, verbose)
        self._select = select

    def _prepare(self):
        from mdanalysis_mpi_tpu.core.topology import residue_atom_map

        u = self._universe
        t = u.topology
        ag = u.select_atoms(self._select)
        sel = ag.indices[t.is_protein[ag.indices]]
        if len(sel) == 0:
            raise ValueError(f"DSSP: {self._select!r} has no protein")
        res = np.unique(t.resindices[sel])
        cols = residue_atom_map(t, res)
        # the pattern algebra (H estimation from the PREVIOUS residue's
        # C, the |i−j|≤1 exclusion, the turn diagonals) treats row
        # order as SEQUENCE order — a chain break would silently wire
        # chain B's first H to chain A's last C and let turns span the
        # gap, so one contiguous single-segment chain is required
        # (upstream DSSP raises for multi-chain input too)
        first = np.asarray([min(cols[int(r)].values()) for r in res])
        segs = t.segids[first] if t.segids is not None else None
        if segs is not None and len(set(segs)) > 1:
            raise ValueError(
                f"DSSP needs a single chain; selection spans segments "
                f"{sorted(set(segs))} — run per segment")
        rids = t.resids[first]
        if len(rids) > 1 and not (np.diff(rids) == 1).all():
            gap = int(np.flatnonzero(np.diff(rids) != 1)[0])
            raise ValueError(
                f"DSSP needs contiguous resids; gap after resid "
                f"{int(rids[gap])} — run per contiguous stretch")
        quad = []
        for r in res:
            d = cols[int(r)]
            missing = [nm for nm in ("N", "CA", "C", "O") if nm not in d]
            if missing:
                raise ValueError(
                    f"DSSP: residue index {int(r)} lacks backbone "
                    f"atoms {missing} (need N, CA, C, O)")
            quad.append([d["N"], d["CA"], d["C"], d["O"]])
        if len(quad) < 5:
            raise ValueError(
                f"DSSP needs at least 5 residues, got {len(quad)}")
        quad = np.asarray(quad, np.int64)          # (n_res, 4)
        self.resindices = res
        uniq, inv = np.unique(quad, return_inverse=True)
        self._idx = uniq
        slots = inv.reshape(quad.shape).astype(np.int32)
        self._n_slot = slots[:, 0]
        self._ca_slot = slots[:, 1]
        self._c_slot = slots[:, 2]
        self._o_slot = slots[:, 3]
        self._serial_rows: list = []

    def _single_frame(self, ts):
        x = ts.positions[self._idx].astype(np.float64)
        self._serial_rows.append(_hbond_map_np(
            x[self._n_slot], x[self._ca_slot], x[self._c_slot],
            x[self._o_slot]))

    def _serial_summary(self):
        nres = len(self._n_slot)
        rows = (np.stack(self._serial_rows).astype(np.float32)
                if self._serial_rows else np.empty((0, nres, nres),
                                                   np.float32))
        return (rows, np.ones(len(rows)))

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _dssp_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._n_slot), jnp.asarray(self._ca_slot),
                jnp.asarray(self._c_slot), jnp.asarray(self._o_slot))

    _device_combine = None      # time series, concatenated in frame order

    def _identity_partials(self):
        nres = len(self._n_slot)
        return (np.empty((0, nres, nres), np.float32), np.empty(0))

    def _conclude(self, total):
        maps, mask = total
        nres = len(self._n_slot)

        def _finalize():
            m = np.asarray(mask) > 0.5
            hbs = np.asarray(maps)[m] > 0.5
            letters = (np.stack([assign_from_hbond_map(hb)
                                 for hb in hbs]) if len(hbs)
                       else np.empty((0, nres), "U1"))
            return {"dssp": letters, "hbond_maps": hbs}

        g = deferred_group(_finalize)
        self.results.dssp = g["dssp"]
        self.results.hbond_maps = g["hbond_maps"]
        self.results.resindices = self.resindices
