"""Hydrogen-bond analysis.

Upstream-API mirror (``MDAnalysis.analysis.hydrogenbonds.
HydrogenBondAnalysis``): geometric hydrogen-bond detection —
donor–acceptor distance < ``d_a_cutoff`` AND donor–hydrogen–acceptor
angle > ``d_h_a_angle_cutoff`` — over fixed donor/hydrogen/acceptor
sets.  ``HydrogenBondAnalysis(u).run()`` → ``results.count`` (T,)
hydrogen bonds per frame; the serial backend additionally produces
``results.hbonds`` (one record per bond per frame, upstream's flat
table).

TPU-first shape: the (hydrogen × acceptor) candidate matrix has STATIC
shape (each hydrogen is covalently paired to its one donor up front),
so a frame batch evaluates all B×nH×nA geometric predicates in one
fused kernel — distance + angle via gathers and an einsum-free dot —
and only the per-frame count (a masked sum) leaves the kernel; the
dynamic-shape bond LIST is inherently host-side and stays a
serial-oracle feature (Deferred, like every dynamic result here).

Donor→hydrogen pairing: topology bonds when present (PSF), else a
first-frame distance heuristic (H to nearest heavy atom within
``1.2 Å`` — documented fallback for bondless formats like GRO).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, Deferred
from mdanalysis_mpi_tpu.ops.host import minimum_image


# ---- module-level batch kernel (stable identity → cached compiles) ----

def _hbond_count_kernel(params, batch, boxes, mask):
    """Per-frame hydrogen-bond counts (B,) over the static
    (hydrogen, acceptor) candidate matrix."""
    import jax
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops.distances import minimum_image as mi

    d_slots, h_slots, a_slots, self_pair, cutoff, cos_max = params

    def per_frame(args):
        x, box6 = args
        d = x[d_slots]                       # (nH, 3)
        h = x[h_slots]
        a = x[a_slots]                       # (nA, 3)
        da = mi(d[:, None] - a[None], box6)          # (nH, nA, 3)
        hd = mi(d[:, None] - h[:, None], box6)       # (nH, 1, 3)
        ha = mi(a[None] - h[:, None], box6)          # (nH, nA, 3)
        dist_ok = (da ** 2).sum(-1) < cutoff * cutoff
        # angle D-H-A at the hydrogen: cos between H→D and H→A;
        # angle > cutoff  <=>  cos < cos(cutoff)
        num = (hd * ha).sum(-1)
        den = jnp.sqrt((hd ** 2).sum(-1) * (ha ** 2).sum(-1)) + 1e-12
        ang_ok = num / den < cos_max
        ok = dist_ok & ang_ok & ~self_pair
        return ok.sum().astype(jnp.float32)

    counts = jax.lax.map(per_frame, (batch, boxes))
    return (counts * mask, mask)


class HydrogenBondAnalysis(AnalysisBase):
    """``HydrogenBondAnalysis(u, hydrogens_sel=..., acceptors_sel=...,
    d_a_cutoff=3.0, d_h_a_angle_cutoff=150.0).run()``.

    Defaults: hydrogens = the ``hydrogen`` selection keyword, acceptors
    = N/O/F heavy atoms, donors = each hydrogen's covalent partner
    (bonds, else the 1.2 Å first-frame heuristic).  Minimum-image PBC
    applies when frames carry a box.  ``results.count`` everywhere;
    ``results.hbonds`` (frame, donor, hydrogen, acceptor, distance,
    angle) on the serial backend.

    ``engine`` selects the serial path's candidate-pruning backend
    (``lib.distances.capped_distance``): the default 'auto' prunes
    donor-acceptor pairs through the O(N) cell list at scale and
    evaluates the angle criterion only on the survivors —
    'bruteforce' is the selectable fallback.
    """

    POLAR_DONOR_ELEMENTS = ("N", "O", "F", "S")

    def __init__(self, universe, hydrogens_sel: str | None = None,
                 acceptors_sel: str | None = None,
                 d_a_cutoff: float = 3.0,
                 d_h_a_angle_cutoff: float = 150.0,
                 engine: str = "auto",
                 verbose: bool = False):
        super().__init__(universe, verbose)
        # serial-path candidate pruning backend
        # (lib.distances.capped_distance): 'auto' = cell list at scale,
        # brute force selectable; the batch kernel keeps its dense
        # static-shape candidate matrix (module docstring)
        self._engine = engine
        # None → guess: all hydrogens, then keep only those whose
        # covalent partner is a polar donor element (upstream guesses
        # polar hydrogens too — counting C-H...O contacts as hydrogen
        # bonds would systematically inflate counts).  An EXPLICIT
        # hydrogens_sel is taken literally, no donor-element filter.
        self._hydrogens_sel = hydrogens_sel
        self._acceptors_sel = acceptors_sel
        self._cutoff = float(d_a_cutoff)
        self._angle_cutoff = float(d_h_a_angle_cutoff)

    def _guess_donors(self, h_idx: np.ndarray) -> np.ndarray:
        """One donor (covalent heavy partner) per hydrogen."""
        u = self._universe
        t = u.topology
        heavy = ~t.is_hydrogen
        if t.bonds is not None and len(t.bonds):
            partner = np.full(t.n_atoms, -1, dtype=np.int64)
            for x, y in t.bonds:
                if heavy[y]:
                    partner[x] = y
                if heavy[x]:
                    partner[y] = x
            donors = partner[h_idx]
            if (donors >= 0).all():
                return donors
            missing = h_idx[donors < 0]
            raise ValueError(
                f"{len(missing)} hydrogens have no bonded heavy atom "
                f"(first: atom {int(missing[0])})")
        # bondless topology: nearest heavy atom within 1.2 Å, frame 0
        # (vectorized in hydrogen chunks: one broadcast minimum-image
        # per chunk, not one Python-level pass per hydrogen)
        ts = u.trajectory[self._frame_indices[0]
                          if self._frame_indices else 0]
        pos = ts.positions.astype(np.float64)
        heavy_idx = np.flatnonzero(heavy)
        donors = np.empty(len(h_idx), dtype=np.int64)
        chunk = max(1, 2_000_000 // max(len(heavy_idx), 1))
        for lo in range(0, len(h_idx), chunk):
            hs = h_idx[lo:lo + chunk]
            disp = minimum_image(
                pos[heavy_idx][None] - pos[hs][:, None], ts.dimensions)
            d2 = (disp ** 2).sum(-1)              # (chunk, n_heavy)
            k = d2.argmin(axis=1)
            best = d2[np.arange(len(hs)), k]
            if (best > 1.2 ** 2).any():
                bad = hs[best > 1.2 ** 2][0]
                raise ValueError(
                    f"hydrogen atom {int(bad)} has no heavy atom within "
                    "1.2 Å in the first frame (no bonds in topology — "
                    "provide a PSF or fix coordinates)")
            donors[lo:lo + chunk] = heavy_idx[k]
        return donors

    def _prepare(self):
        # a re-run must not leave an earlier run's bond table behind:
        # lifetime() reads results["hbonds"] against THIS run's frame
        # window, and a stale table would mix frame grids silently
        self.results.pop("hbonds", None)
        u = self._universe
        t = u.topology
        guess = self._hydrogens_sel is None
        h_sel = "hydrogen" if guess else self._hydrogens_sel
        h_idx = u.select_atoms(h_sel).indices
        if len(h_idx) == 0:
            raise ValueError(
                f"hydrogens selection {h_sel!r} matched no atoms")
        if not t.is_hydrogen[h_idx].all():
            raise ValueError("hydrogens selection contains heavy atoms")
        if self._acceptors_sel is not None:
            a_idx = u.select_atoms(self._acceptors_sel).indices
        else:
            elements = np.char.upper(t.elements.astype("U2"))
            a_idx = np.flatnonzero(
                np.isin(elements, ("N", "O", "F")) & ~t.is_hydrogen)
        if len(a_idx) == 0:
            raise ValueError("no acceptor atoms found")
        d_idx = self._guess_donors(h_idx)
        if guess:
            # polar hydrogens only (see __init__ note)
            elements = np.char.upper(t.elements.astype("U2"))
            polar = np.isin(elements[d_idx], self.POLAR_DONOR_ELEMENTS)
            if not polar.any():
                raise ValueError(
                    "no polar (N/O/F/S-bonded) hydrogens found; pass an "
                    "explicit hydrogens_sel to override the donor filter")
            h_idx, d_idx = h_idx[polar], d_idx[polar]
        self._h_idx, self._a_idx, self._d_idx = h_idx, a_idx, d_idx
        # staged-selection slots
        uniq, inv = np.unique(np.concatenate([d_idx, h_idx, a_idx]),
                              return_inverse=True)
        self._idx = uniq
        nh = len(h_idx)
        self._d_slots = inv[:nh].astype(np.int32)
        self._h_slots = inv[nh:2 * nh].astype(np.int32)
        self._a_slots = inv[2 * nh:].astype(np.int32)
        # a donor that is itself an acceptor must not H-bond to itself
        self._self_pair = (d_idx[:, None] == self._a_idx[None, :])
        self._serial_counts = []
        self._serial_records = []

    # -- serial path --

    def _single_frame(self, ts):
        # candidate pruning (VERDICT r5: "work, don't scale"): the
        # donor-acceptor distance cutoff prunes first through the
        # capped-distance engine — O(nH + nA) with the cell list — and
        # the angle criterion is evaluated only on the K survivors,
        # never on the dense nH x nA matrix the batch kernel uses
        from mdanalysis_mpi_tpu.lib.distances import capped_distance

        pos = ts.positions.astype(np.float64)
        d = pos[self._d_idx]
        h = pos[self._h_idx]
        a = pos[self._a_idx]
        pairs, dist = capped_distance(d, a, self._cutoff,
                                      box=ts.dimensions,
                                      engine=self._engine)
        # upstream's criterion is strict <; capped_distance caps with <=
        keep = ((dist < self._cutoff)
                & (self._d_idx[pairs[:, 0]] != self._a_idx[pairs[:, 1]]))
        pi, pj, dist = pairs[keep, 0], pairs[keep, 1], dist[keep]
        hd = minimum_image(d[pi] - h[pi], ts.dimensions)
        ha = minimum_image(a[pj] - h[pi], ts.dimensions)
        num = (hd * ha).sum(-1)
        den = (np.sqrt((hd ** 2).sum(-1))
               * np.sqrt((ha ** 2).sum(-1))) + 1e-12
        ang = np.degrees(np.arccos(np.clip(num / den, -1.0, 1.0)))
        ok = ang > self._angle_cutoff
        self._serial_counts.append(float(ok.sum()))
        # pairs are lexsorted by (hydrogen, acceptor) — the same record
        # order np.nonzero emitted from the dense matrix
        for j, k, dd, aa in zip(pi[ok], pj[ok], dist[ok], ang[ok]):
            self._serial_records.append(
                (ts.frame, int(self._d_idx[j]), int(self._h_idx[j]),
                 int(self._a_idx[k]), float(dd), float(aa)))

    def _serial_summary(self):
        c = np.asarray(self._serial_counts)
        return (c, np.ones(len(c)))

    # -- batch path --

    # the batch kernel materializes dense (nH, nA, 3) candidate tensors
    # on device (three live inside the per-frame map); past this many
    # pairs that is multi-GB per frame and will OOM an HBM chip —
    # upstream sidesteps it with a neighbor search, which is inherently
    # dynamic-shape and therefore serial-oracle territory here
    MAX_BATCH_PAIRS = 25_000_000

    def _batch_select(self):
        n_pairs = len(self._h_idx) * len(self._a_idx)
        if n_pairs > self.MAX_BATCH_PAIRS:
            raise ValueError(
                f"{len(self._h_idx)} hydrogens x {len(self._a_idx)} "
                f"acceptors = {n_pairs} candidate pairs exceeds the dense "
                f"batch kernel's limit ({self.MAX_BATCH_PAIRS}); narrow "
                "hydrogens_sel/acceptors_sel or run with "
                "backend='serial'")
        return self._idx

    def _batch_fn(self):
        return _hbond_count_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._d_slots), jnp.asarray(self._h_slots),
                jnp.asarray(self._a_slots), jnp.asarray(self._self_pair),
                jnp.float32(self._cutoff),
                jnp.float32(np.cos(np.radians(self._angle_cutoff))))

    _device_combine = None      # time series, concatenated in frame order

    def _identity_partials(self):
        return (np.empty(0), np.empty(0))

    def _conclude(self, total):
        counts, mask = total

        def _finalize():
            return np.asarray(counts)[np.asarray(mask) > 0.5]

        self.results.count = Deferred(_finalize)
        if self._serial_records or self._serial_counts:
            self.results.hbonds = np.array(
                self._serial_records, dtype=np.float64).reshape(-1, 6)

    def lifetime(self, tau_max: int = 20, intermittency: int = 0):
        """Hydrogen-bond lifetime autocorrelation (upstream
        ``HydrogenBondAnalysis.lifetime``): for each lag τ, the MEAN
        over time origins t of the per-origin CONTINUOUS-survival ratio

            C(τ) = ⟨ |{p : b_p(t′) ∀ t′ ∈ [t, t+τ]}|  /  Σ_p b_p(t) ⟩_t

        over (hydrogen, acceptor) pairs ever bonded (origins with zero
        bonds are skipped) — the same semantics as upstream's
        ``lib.correlations.autocorrelation`` and this package's
        SurvivalProbability: a bond must hold through EVERY intermediate
        frame (a break-and-reform does not survive), and per-origin
        ratios are averaged (ratio-of-sums would overweight bond-rich
        origins).  Departures of ≤ ``intermittency`` consecutive frames
        are filled BEFORE the survival product (upstream's
        intermittent-lifetime preprocessing).  Returns
        ``(taus, timeseries)`` with τ in analyzed-frame steps.

        Needs the per-bond table — i.e. a completed ``run()`` on the
        SERIAL backend (the batch kernel reduces to counts on device;
        the bond LIST is inherently dynamic-shape, module docstring).
        """
        if tau_max < 0:
            raise ValueError(f"tau_max must be >= 0, got {tau_max}")
        if intermittency < 0:
            raise ValueError(
                f"intermittency must be >= 0, got {intermittency}")
        if "hbonds" not in self.results:
            raise ValueError(
                "lifetime() needs the per-bond table: run "
                ".run(backend='serial') first (batch backends produce "
                "counts only)")
        from mdanalysis_mpi_tpu.analysis.waterdynamics import (
            _apply_intermittency)

        table = self.results["hbonds"]
        frames = np.asarray(self._frame_indices, dtype=np.int64)
        t = len(frames)
        if len(table):
            # vectorized (frame, pair) scatter: the serial table can be
            # millions of rows at benchmark scale, a per-row Python
            # loop is minutes of host time
            order = np.argsort(frames, kind="stable")
            rows = order[np.searchsorted(frames[order],
                                         table[:, 0].astype(np.int64))]
            _, cols = np.unique(table[:, 2:4].astype(np.int64), axis=0,
                                return_inverse=True)
            present = np.zeros((t, int(cols.max()) + 1), dtype=bool)
            present[rows, cols] = True
        else:
            present = np.zeros((t, 0), dtype=bool)
        present = _apply_intermittency(present, int(intermittency))
        tau_max = min(int(tau_max), t - 1 if t else 0)
        from mdanalysis_mpi_tpu.lib.correlations import survival_windows

        # the shared running-AND survival reduction (one home for the
        # semantics: lib.correlations)
        data = survival_windows(present, tau_max)
        c = np.array([float(np.mean(v)) if v else 0.0 for v in data])
        return np.arange(tau_max + 1), c
