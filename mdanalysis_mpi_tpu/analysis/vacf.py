"""Velocity autocorrelation function.

Upstream-API mirror (``MDAnalysis.analysis.velocityautocorr``-style):
``VelocityAutocorr(ag).run()`` → ``results.timeseries`` (T,) —
``C(τ) = <v(t)·v(t+τ)>`` averaged over particles and time origins —
plus ``results.vacf_by_particle`` (T, S).  Needs a trajectory that
carries velocities (TRR, or a MemoryReader constructed with
``velocities=``).

Shape: velocities are host-decoded per frame (the staging pipeline
moves positions; velocity series are short-window analyses), then the
whole lag algebra runs as ONE jitted device call — the same
``rfft``/``irfft`` autocorrelation the MSD uses, O(T log T), static
shapes.  ``fft=False`` is the direct windowed reference."""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import Results, deferred_group


def _np_fft_vacf(v: np.ndarray) -> np.ndarray:
    """v (T, S, 3) → per-particle VACF (T, S), float64 reference."""
    t = v.shape[0]
    v = np.asarray(v, np.float64)
    f = np.fft.rfft(v, n=2 * t, axis=0)
    ac = np.fft.irfft(f * np.conj(f), n=2 * t, axis=0)[:t].sum(axis=2)
    norm = (t - np.arange(t))[:, None]
    return ac / norm


def _np_windowed_vacf(v: np.ndarray) -> np.ndarray:
    t = v.shape[0]
    v = np.asarray(v, np.float64)
    out = np.empty((t, v.shape[1]))
    for m in range(t):
        out[m] = (v[: t - m] * v[m:]).sum(axis=2).mean(axis=0)
    return out


_FFT_JIT = None


def _jax_fft_vacf(v):
    global _FFT_JIT
    if _FFT_JIT is None:
        import jax
        import jax.numpy as jnp

        def f(v):
            t = v.shape[0]
            fr = jnp.fft.rfft(v, n=2 * t, axis=0)
            ac = jnp.fft.irfft(fr * jnp.conj(fr), n=2 * t,
                               axis=0)[:t].sum(axis=2)
            norm = (t - jnp.arange(t, dtype=ac.dtype))[:, None]
            return ac / norm

        _FFT_JIT = jax.jit(f)
    return _FFT_JIT(v)


class VelocityAutocorr:
    """``VelocityAutocorr(ag, fft=True).run(start=, stop=, step=)``."""

    def __init__(self, atomgroup, fft: bool = True, verbose: bool = False):
        self._ag = atomgroup
        self._fft = fft
        self._verbose = verbose
        self.results = Results()

    def run(self, start=None, stop=None, step=None,
            backend: str = "jax"):
        u = self._ag.universe
        traj = u.trajectory
        frames = range(*slice(start, stop, step).indices(traj.n_frames))
        idx = self._ag.indices
        vels = []
        for i in frames:
            ts = traj[i]
            if ts.velocities is None:
                raise ValueError(
                    f"frame {i} carries no velocities (use a TRR "
                    "trajectory or MemoryReader(velocities=...))")
            vels.append(ts.velocities[idx].astype(np.float64))
        if len(vels) < 2:
            raise ValueError("VACF needs at least 2 frames")
        v = np.stack(vels)
        self.n_frames = len(v)
        fft = self._fft

        def _finalize():
            if not fft:
                by = _np_windowed_vacf(v)
            elif backend in ("jax", "mesh"):
                import jax.numpy as jnp

                by = np.asarray(_jax_fft_vacf(jnp.asarray(v, jnp.float32)),
                                np.float64)
            else:
                by = _np_fft_vacf(v)
            return {"vacf_by_particle": by,
                    "timeseries": by.mean(axis=1)}

        g = deferred_group(_finalize)
        self.results.vacf_by_particle = g["vacf_by_particle"]
        self.results.timeseries = g["timeseries"]
        return self
