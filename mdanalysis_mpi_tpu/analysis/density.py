"""Atom-density grid analysis.

Upstream-API mirror (``MDAnalysis.analysis.density.DensityAnalysis``):
accumulate a selection's positions onto a fixed 3-D grid over the
trajectory — ``DensityAnalysis(ag, delta=1.0).run()`` →
``results.grid`` (nx, ny, nz) mean occupancy per frame,
``results.density`` (number density, Å⁻³), ``results.edges`` /
``results.origin`` describing the grid.

TPU-first shape: per staged batch the kernel computes voxel indices and
scatter-adds all B×S samples in one ``.at[].add`` (XLA scatter — the
same primitive the RDF histogram uses), partials fold on device and
psum-merge across chips; out-of-grid samples fall into a trapdoor bin
that is dropped at the end (static shapes, no boolean indexing).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, tree_add, tree_psum
from mdanalysis_mpi_tpu.core.groups import AtomGroup


# ---- batch kernel (one cached function per grid shape: the shape is
# compile-time structure, so it cannot travel in the traced params
# pytree; the lru_cache keeps kernel identity stable per shape so the
# executor's jit cache survives across run() calls) ----

import functools


@functools.lru_cache(maxsize=None)
def _density_kernel_for(shape: tuple):
    nx, ny, nz = shape
    nbins = nx * ny * nz

    def kernel(params, batch, boxes, mask):
        """Partials (T int32, counts (nbins + 1,) int32): last bin is
        the out-of-grid trapdoor.  INTEGER accumulation: float32 counts
        silently saturate at 2^24 samples/bin (easily exceeded by the
        trapdoor on long runs), and device float64 is unavailable with
        x64 disabled; int32 is exact to 2^31 (guarded in _prepare)."""
        del boxes
        import jax.numpy as jnp

        origin, inv_delta = params
        ijk = jnp.floor((batch - origin) * inv_delta).astype(jnp.int32)
        inside = ((ijk >= 0).all(-1) & (ijk[..., 0] < nx)
                  & (ijk[..., 1] < ny) & (ijk[..., 2] < nz))
        flat = ijk[..., 0] * (ny * nz) + ijk[..., 1] * nz + ijk[..., 2]
        flat = jnp.where(inside, flat, nbins)      # trapdoor
        w = jnp.broadcast_to(mask[:, None] > 0, flat.shape)
        counts = jnp.zeros(nbins + 1, jnp.int32).at[flat.reshape(-1)].add(
            w.reshape(-1).astype(jnp.int32))
        return ((mask > 0).sum().astype(jnp.int32), counts)

    return kernel


class DensityAnalysis(AnalysisBase):
    """``DensityAnalysis(ag, delta=1.0).run().results.density``.

    The grid is fixed before the run: either give ``gridcenter`` +
    ``xdim``/``ydim``/``zdim`` (Å), or it is derived from the
    selection's first ``run()`` frame with ``padding`` Å of margin
    (upstream behavior).  Samples outside the grid are counted in
    ``results.n_outside`` rather than silently dropped.
    """

    def __init__(self, atomgroup: AtomGroup, delta: float = 1.0,
                 gridcenter=None, xdim: float | None = None,
                 ydim: float | None = None, zdim: float | None = None,
                 padding: float = 2.0, verbose: bool = False):
        super().__init__(atomgroup.universe, verbose)
        if delta <= 0:
            raise ValueError(f"delta must be positive, got {delta}")
        dims = (xdim, ydim, zdim)
        if gridcenter is not None and any(d is None for d in dims):
            raise ValueError(
                "gridcenter needs explicit xdim, ydim and zdim")
        if gridcenter is None and any(d is not None for d in dims):
            raise ValueError(
                "xdim/ydim/zdim need an explicit gridcenter (without "
                "one the grid is derived from the first frame)")
        self._ag = atomgroup
        self._delta = float(delta)
        self._gridcenter = (None if gridcenter is None
                            else np.asarray(gridcenter, np.float64))
        self._userdims = dims
        self._padding = float(padding)

    def _prepare(self):
        self._idx = self._ag.indices
        if len(self._idx) == 0:
            raise ValueError("selection matched no atoms")
        d = self._delta
        if self._gridcenter is not None:
            dims = np.array([float(x) for x in self._userdims])
            shape = np.maximum(np.ceil(dims / d), 1).astype(int)
            # origin from the ROUNDED extent, so the grid stays centered
            # on gridcenter even when a dimension is not a multiple of
            # delta (rounding must grow both sides, not just the high one)
            origin = self._gridcenter - shape * d / 2.0
        else:
            # derive from the run's first frame + padding (upstream)
            first = self._frame_indices[0] if self._frame_indices else 0
            pos = self._universe.trajectory[first].positions[self._idx]
            lo = pos.min(axis=0) - self._padding
            hi = pos.max(axis=0) + self._padding
            origin = lo.astype(np.float64)
            shape = np.maximum(np.ceil((hi - lo) / d), 1).astype(int)
        if int(np.prod(shape)) > 64_000_000:
            raise ValueError(
                f"grid shape {tuple(shape)} exceeds 64M voxels; coarsen "
                "delta or bound the grid")
        # int32 device counts: exact to 2^31 samples per bin — the
        # worst case for one bin (the trapdoor) is every sample of the
        # whole run
        if self.n_frames * len(self._idx) >= 2 ** 31:
            raise ValueError(
                f"{self.n_frames} frames x {len(self._idx)} atoms "
                "exceeds the int32 device-count capacity (2^31 samples); "
                "split the run into windows and merge the grids")
        self._origin = origin
        self._shape = tuple(int(s) for s in shape)
        self._counts = np.zeros(self._shape, dtype=np.float64)
        self._t = 0.0
        self._outside = 0.0

    # -- serial path --

    def _single_frame(self, ts):
        pos = ts.positions[self._idx].astype(np.float64)
        ijk = np.floor((pos - self._origin) / self._delta).astype(np.int64)
        nx, ny, nz = self._shape
        inside = ((ijk >= 0).all(1) & (ijk[:, 0] < nx)
                  & (ijk[:, 1] < ny) & (ijk[:, 2] < nz))
        self._outside += float((~inside).sum())
        k = ijk[inside]
        np.add.at(self._counts, (k[:, 0], k[:, 1], k[:, 2]), 1.0)
        self._t += 1.0

    def _serial_summary(self):
        flat = np.concatenate([self._counts.reshape(-1), [self._outside]])
        return (self._t, flat)

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _density_kernel_for(self._shape)

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.asarray(self._origin, jnp.float32),
                jnp.float32(1.0 / self._delta))

    _device_combine = staticmethod(tree_psum)
    _device_fold_fn = staticmethod(tree_add)

    def _identity_partials(self):
        return (0.0, np.zeros(int(np.prod(self._shape)) + 1))

    def _conclude(self, total):
        t, flat = total
        if self.n_frames == 0:
            raise ValueError("DensityAnalysis over zero frames")
        from mdanalysis_mpi_tpu.analysis.base import deferred_group

        shape = self._shape
        origin = self._origin
        delta = self._delta

        def _finalize():
            f = np.asarray(flat, np.float64)
            tt = float(np.asarray(t))
            grid = f[:-1].reshape(shape) / tt
            ex, ey, ez = (origin[i] + delta * np.arange(shape[i] + 1)
                          for i in range(3))
            return {
                "grid": grid,
                "density": grid / delta ** 3,
                "n_outside": f[-1] / tt,
                "origin": origin,
                "edges": [ex, ey, ez],
                "edges_x": ex, "edges_y": ey, "edges_z": ez,
                # upstream's unit-aware container (convert_density /
                # DX export); `density` stays the plain ndarray —
                # documented deviation, PARITY.md
                "density_object": Density(grid / delta ** 3,
                                          [ex, ey, ez]),
            }

        g = deferred_group(_finalize)
        for k in ("grid", "density", "n_outside", "origin", "edges",
                  "edges_x", "edges_y", "edges_z", "density_object"):
            self.results[k] = g[k]


class Density:
    """Grid + metadata container (upstream ``analysis.density.Density``):
    unit-aware number-density grid with in-place
    :meth:`convert_density` (via :mod:`mdanalysis_mpi_tpu.units`) and
    OpenDX :meth:`export` for VMD/PyMOL interop.

    ``grid`` is (nx, ny, nz); ``edges`` the three bin-edge arrays (Å).
    The density unit starts as ``A^{-3}`` (the framework's base).
    """

    def __init__(self, grid: np.ndarray, edges, units: str = "A^{-3}"):
        from mdanalysis_mpi_tpu.units import densityUnit_factor

        self.grid = np.asarray(grid, np.float64)
        if self.grid.ndim != 3:
            raise ValueError(f"grid must be 3-D, got {self.grid.shape}")
        self.edges = [np.asarray(e, np.float64) for e in edges]
        if len(self.edges) != 3 or any(
                len(e) != n + 1 for e, n in zip(self.edges,
                                                self.grid.shape)):
            raise ValueError(
                "edges must be three arrays of length grid.shape[i]+1")
        if units not in densityUnit_factor:
            raise ValueError(
                f"unknown density unit {units!r}; known: "
                f"{sorted(densityUnit_factor)}")
        self.units = {"length": "A", "density": units}

    @property
    def origin(self) -> np.ndarray:
        return np.array([e[0] for e in self.edges])

    @property
    def delta(self) -> np.ndarray:
        return np.array([e[1] - e[0] for e in self.edges])

    def convert_density(self, unit: str = "water") -> "Density":
        """In-place unit conversion of the grid values (upstream
        semantics); returns self for chaining."""
        from mdanalysis_mpi_tpu import units as u

        if unit not in u.densityUnit_factor:
            raise ValueError(
                f"unknown density unit {unit!r}; known: "
                f"{sorted(u.densityUnit_factor)}")
        factor = u.get_conversion_factor(
            "density", self.units["density"], unit)
        self.grid *= factor
        self.units["density"] = unit
        return self

    def export(self, path: str, type: str = "DX") -> None:
        """Write the grid as OpenDX (the VMD/PyMOL volumetric format;
        upstream ``Density.export``)."""
        if type.upper() != "DX":
            raise ValueError(f"only DX export is supported, got {type!r}")
        nx, ny, nz = self.grid.shape
        d = self.delta
        # DX grid positions are the SAMPLE POINTS — upstream
        # (gridData/APBS/VMD) puts the origin at the first voxel
        # CENTER, i.e. half a voxel inside the first bin edge.  The
        # in-repo round trip cannot distinguish the two conventions
        # (a symmetric shift cancels), so this is pinned explicitly
        # in tests against the documented convention.
        o = self.origin + 0.5 * d
        with open(path, "w") as fh:
            fh.write("# OpenDX density written by mdanalysis_mpi_tpu\n")
            fh.write(f"object 1 class gridpositions counts "
                     f"{nx} {ny} {nz}\n")
            fh.write(f"origin {o[0]:.10g} {o[1]:.10g} {o[2]:.10g}\n")
            fh.write(f"delta {d[0]:.10g} 0 0\n")
            fh.write(f"delta 0 {d[1]:.10g} 0\n")
            fh.write(f"delta 0 0 {d[2]:.10g}\n")
            fh.write(f"object 2 class gridconnections counts "
                     f"{nx} {ny} {nz}\n")
            fh.write(f"object 3 class array type double rank 0 items "
                     f"{self.grid.size} data follows\n")
            flat = self.grid.ravel()        # C order: z fastest (DX)
            full = len(flat) // 3 * 3       # 64M-voxel grids are legal:
            if full:                        # C-speed formatting, not a
                np.savetxt(fh, flat[:full].reshape(-1, 3),  # py loop
                           fmt="%.10g")
            if full < len(flat):
                fh.write(" ".join(f"{v:.10g}" for v in flat[full:])
                         + "\n")
            fh.write('attribute "dep" string "positions"\n')
            fh.write('object "density" class field\n')
            fh.write('component "positions" value 1\n')
            fh.write('component "connections" value 2\n')
            fh.write('component "data" value 3\n')

    @classmethod
    def from_dx(cls, path: str, units: str = "A^{-3}") -> "Density":
        """Read an OpenDX grid (regular deltas) back into a Density."""
        counts = origin = None
        deltas = []
        values: list = []
        n_items = None
        with open(path) as fh:
            for ln in fh:
                s = ln.strip()
                if not s or s.startswith("#"):
                    continue
                t = s.split()
                if s.startswith("object") and "gridpositions" in s:
                    counts = [int(x) for x in t[-3:]]
                elif t[0] == "origin":
                    origin = [float(x) for x in t[1:4]]
                elif t[0] == "delta":
                    deltas.append([float(x) for x in t[1:4]])
                elif "data follows" in s:
                    n_items = int(t[t.index("items") + 1])
                elif n_items is not None and len(values) < n_items:
                    try:
                        values.extend(float(x) for x in t)
                    except ValueError:
                        break          # trailing attribute block
        if counts is None or origin is None or len(deltas) != 3:
            raise ValueError(f"{path!r} is not a regular-grid DX file")
        for i, dv in enumerate(deltas):
            off = [abs(dv[j]) for j in range(3) if j != i]
            if max(off) > 1e-9 * max(abs(dv[i]), 1e-30):
                raise ValueError(
                    f"{path!r}: delta {i} has off-axis components "
                    f"{dv} — sheared/rotated DX grids are not "
                    "supported (regular axis-aligned grids only)")
        d = [deltas[i][i] for i in range(3)]
        if n_items is None or len(values) < n_items:
            raise ValueError(f"{path!r}: truncated data section")
        grid = np.asarray(values[:n_items], np.float64).reshape(counts)
        # DX origin is the first voxel CENTER (see export); the first
        # bin EDGE sits half a voxel below it
        edges = [origin[i] - 0.5 * d[i] + d[i] * np.arange(counts[i] + 1)
                 for i in range(3)]
        return cls(grid, edges, units=units)
