"""Gaussian Network Model (GNM) trajectory analysis.

Upstream-API mirror (``MDAnalysis.analysis.gnm.GNMAnalysis``): per
frame, build the Kirchhoff (graph-Laplacian) matrix of the selection's
contact graph — nodes within ``cutoff`` Å are springs — and report the
first non-trivial eigenvalue (the slowest internal GNM mode, upstream's
per-frame mobility proxy) and its eigenvector.
``GNMAnalysis(u, select="protein and name CA").run()`` →
``results.times``, ``results.eigenvalues`` (T,), ``results.eigenvectors``
(T, S).

TPU-first shape: the contact matrix has STATIC (S, S) shape, so a frame
batch builds all B Kirchhoff matrices with one broadcast distance
computation and eigendecomposes them with one vmapped ``eigh`` — dense
(S, S) symmetric eigensolves are exactly the MXU-friendly regime (the
same on-device ``eigh`` PCA uses; guarded to selection scales where
S² matrices are sane).  Eigenvector sign is normalized (largest-|value|
component positive) so serial/batch and chip/host agree; eigenvalues of
a Laplacian are sorted ascending with λ₀ ≈ 0 for a connected graph, and
index 1 is reported (upstream convention — for a DISCONNECTED contact
graph additional near-zero eigenvalues appear there; upstream warns,
here the value itself discloses it).
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import AnalysisBase, deferred_group


def _gnm_kernel(params, batch, boxes, mask):
    """Per-frame (first-mode eigenvalue, eigenvector) over the batch."""
    del boxes
    import jax
    import jax.numpy as jnp

    (cutoff2,) = params

    def per_frame(x):
        d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        adj = (d2 < cutoff2).astype(jnp.float32)
        adj = adj - jnp.eye(x.shape[0], dtype=jnp.float32)
        lap = jnp.diag(adj.sum(axis=1)) - adj
        vals, vecs = jnp.linalg.eigh(lap)
        v = vecs[:, 1]
        # deterministic sign: largest-|component| entry positive
        s = jnp.sign(v[jnp.argmax(jnp.abs(v))])
        return vals[1], v * jnp.where(s == 0, 1.0, s)

    # time-series family: no _device_combine — per-shard outputs
    # concatenate in frame order.  vmap (not lax.map): all B
    # eigensolves batch into one call — memory is B·S²·f32 per live
    # buffer, which the node-count guard in _prepare sizes for
    vals, vecs = jax.vmap(per_frame)(batch)
    m = mask.astype(jnp.float32)
    return (vals * m, vecs * m[:, None], m)


class GNMAnalysis(AnalysisBase):
    """``GNMAnalysis(u, select="protein and name CA", cutoff=7.0)``.

    ``results.eigenvalues`` / ``results.eigenvectors`` / ``results.times``
    — one slowest-internal-mode record per frame, upstream layout.

    Precision envelope: the batch backends eigensolve the Kirchhoff
    matrix in float32 (vmapped ``eigh``) while the serial oracle runs
    float64.  λ1 agrees to ~1e-5 relative in practice, but for
    NEAR-DEGENERATE low modes (weakly connected contact graphs, λ1≈λ2)
    the float32 eigenVECTOR can rotate inside the near-degenerate
    subspace and diverge from the oracle beyond ordinary tolerances.
    Before trusting per-frame eigenvectors from a batch run, check the
    spectral gap: ``results.eigenvalues`` close to the next mode's
    value flags frames where only the eigenvalue is comparable across
    backends.  (Advisor r4; the suite's config-7 check compares
    eigenvalues, not vectors, for exactly this reason.)
    """

    def __init__(self, universe, select: str = "protein and name CA",
                 cutoff: float = 7.0, verbose: bool = False):
        super().__init__(universe, verbose)
        if cutoff <= 0:
            raise ValueError(f"cutoff must be positive, got {cutoff}")
        self._select = select
        self._cutoff = float(cutoff)

    def _prepare(self):
        ag = self._universe.select_atoms(self._select)
        if ag.n_atoms < 3:
            raise ValueError(
                f"GNM needs at least 3 nodes; selection "
                f"{self._select!r} matched {ag.n_atoms}")
        if ag.n_atoms > 2_048:
            # the batch kernel vmaps the eigensolve: live buffers are
            # B·S²·4 bytes each (~1 GB at S=2048, batch 64) — and GNM
            # is a residue-level model anyway
            raise ValueError(
                f"selection spans {ag.n_atoms} nodes -> batched "
                f"{ag.n_atoms}x{ag.n_atoms} Kirchhoff eigensolves; GNM "
                "is meant for Cα/residue-level networks (coarsen the "
                "selection)")
        self._idx = ag.indices
        self._vals: list[float] = []
        self._vecs: list[np.ndarray] = []

    # -- serial path --

    def _single_frame(self, ts):
        x = ts.positions[self._idx].astype(np.float64)
        d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        adj = (d2 < self._cutoff ** 2).astype(np.float64)
        np.fill_diagonal(adj, 0.0)
        lap = np.diag(adj.sum(axis=1)) - adj
        vals, vecs = np.linalg.eigh(lap)
        v = vecs[:, 1]
        s = np.sign(v[np.argmax(np.abs(v))]) or 1.0
        self._vals.append(float(vals[1]))
        self._vecs.append(v * s)

    def _serial_summary(self):
        n = len(self._vals)
        return (np.asarray(self._vals), np.asarray(self._vecs).reshape(
            n, len(self._idx)), np.ones(n))

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _gnm_kernel

    def _batch_params(self):
        import jax.numpy as jnp

        return (jnp.float32(self._cutoff ** 2),)

    _device_combine = None          # concatenated time series

    def _identity_partials(self):
        return (np.empty(0), np.empty((0, len(self._idx))), np.empty(0))

    def _conclude(self, total):
        vals, vecs, mask = total
        frames = list(self._frame_indices)
        times = self._universe.trajectory.frame_times(frames)
        self.results.times = (np.asarray(times, np.float64)
                              if times is not None
                              else np.asarray(frames, np.float64))

        def _finalize():
            m = np.asarray(mask) > 0.5
            return {"eigenvalues": np.asarray(vals, np.float64)[m],
                    "eigenvectors": np.asarray(vecs, np.float64)[m]}

        g = deferred_group(_finalize)
        self.results.eigenvalues = g["eigenvalues"]
        self.results.eigenvectors = g["eigenvectors"]
