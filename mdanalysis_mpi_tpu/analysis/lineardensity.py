"""Linear (per-axis slab) mass/charge density profiles.

Upstream-API mirror (``MDAnalysis.analysis.lineardensity.
LinearDensity``): histogram a selection's mass and charge along each
box axis in fixed slabs — ``LinearDensity(ag, binsize=0.25).run()`` →
``results.x`` / ``results.y`` / ``results.z``, each carrying
``mass_density`` (g/cm³), ``charge_density`` (e/Å³), their per-frame
standard deviations, and ``hist_bin_edges``.  ``grouping`` bins atoms
directly or the centers of mass of residues/segments.

Bin layout follows upstream exactly, quirks included: per-axis bin
counts ``bins_i = dims_i // binsize`` set ``nbins = max(bins_i)``, and
EVERY axis histograms over ``[0, max(dims))`` with those ``nbins``
bins (shorter axes simply leave their tail bins empty), while the
normalizing ``slab_volume_i = volume / bins_i`` stays per-axis.  The
layout is fixed by the run's FIRST frame box; samples strictly outside
the range are dropped and the last bin is right-closed, mirroring
upstream's ``np.histogram(..., range=)`` exactly.

TPU-first shape: one batch kernel scatter-adds all three axis
histograms (mass- and charge-weighted) per frame with static shapes,
then reduces them to Chan moments over the frame axis
(``ops/moments.py`` — the centered M2 keeps the per-frame stddev
well-conditioned in float32, where a raw Σh² accumulation would
catastrophically cancel; same reasoning as the RMSF pipeline).
Residue/segment centers of mass reduce on device via the same scatter
primitive with host-precomputed per-group weights.  Partials fold on
device and psum-merge across chips via the law of total variance.
"""

from __future__ import annotations

import functools

import numpy as np

from mdanalysis_mpi_tpu.analysis.base import (AnalysisBase, Deferred,
                                              Results, deferred_group)
from mdanalysis_mpi_tpu.core.groups import AtomGroup
from mdanalysis_mpi_tpu.ops import host
from mdanalysis_mpi_tpu.ops.moments import merge_moments, psum_moments

#: amu/Å³ → g/cm³ (upstream reports mass densities in g/cm³)
_AMU_PER_A3_TO_G_PER_CM3 = 1.66053906660


@functools.lru_cache(maxsize=None)
def _lindens_kernel_for(nbins: int, n_groups: int | None):
    """One cached kernel per (nbins, grouping) static structure."""

    def kernel(params, batch, boxes, mask):
        del boxes
        import jax.numpy as jnp

        from mdanalysis_mpi_tpu.ops.moments import batch_moments

        if n_groups is not None:
            # residue/segment centers of mass: mass-weighted scatter
            # mean over the static group map (one gather-free reduce);
            # the per-group total mass/charge weights are precomputed
            rng_max, atom_m, _, gids, gmass_sum, w_m, w_q = params
            wsum = jnp.zeros((batch.shape[0], n_groups, 3), jnp.float32)
            wsum = wsum.at[:, gids, :].add(batch * atom_m[None, :, None])
            x = wsum / gmass_sum[None, :, None]         # (B, G, 3)
        else:
            rng_max, w_m, w_q, _ = params
            x = batch                                   # (B, S, 3)
        b, p = x.shape[0], x.shape[1]
        idx = jnp.floor(x * (nbins / rng_max)).astype(jnp.int32)
        # np.histogram semantics: the LAST bin is right-closed, so a
        # sample exactly at rng_max belongs to bin nbins-1
        idx = jnp.minimum(idx, nbins - 1)
        inside = (x >= 0.0) & (x <= rng_max) & (idx >= 0)
        idx = jnp.where(inside, idx, nbins)             # trapdoor
        fbase = (jnp.arange(b, dtype=jnp.int32)
                 * 3 * (nbins + 1))[:, None, None]
        abase = (jnp.arange(3, dtype=jnp.int32) * (nbins + 1))[None, :,
                                                               None]
        # (B, 3, P) flat bin ids → two scatter-adds build every
        # per-frame per-axis histogram at once
        flat = (fbase + abase
                + jnp.transpose(idx, (0, 2, 1))).reshape(-1)
        wm3 = jnp.broadcast_to(w_m[None, None, :], (b, 3, p)).reshape(-1)
        wq3 = jnp.broadcast_to(w_q[None, None, :], (b, 3, p)).reshape(-1)
        size = b * 3 * (nbins + 1)
        mh = jnp.zeros(size, jnp.float32).at[flat].add(wm3)
        qh = jnp.zeros(size, jnp.float32).at[flat].add(wq3)
        mh = mh.reshape(b, 3, nbins + 1)
        qh = qh.reshape(b, 3, nbins + 1)
        # Chan moments over the frame axis (mask keeps padding honest)
        t, m_mean, m_m2 = batch_moments(mh, mask)
        _, q_mean, q_m2 = batch_moments(qh, mask)
        return (t, m_mean, m_m2, q_mean, q_m2)

    return kernel


def _lindens_fold(a, b):
    """Device-side cross-batch merge: Chan merge for both moment sets
    (the frame counts are shared)."""
    t1, mm1, mv1, qm1, qv1 = a
    t2, mm2, mv2, qm2, qv2 = b
    t, mm, mv = merge_moments((t1, mm1, mv1), (t2, mm2, mv2))
    _, qm, qv = merge_moments((t1, qm1, qv1), (t2, qm2, qv2))
    return (t, mm, mv, qm, qv)


def _lindens_psum(partials, axis_name):
    """Cross-chip merge: law-of-total-variance psum for both sets."""
    t, mm, mv, qm, qv = partials
    t_tot, mm_tot, mv_tot = psum_moments(t, mm, mv, axis_name)
    _, qm_tot, qv_tot = psum_moments(t, qm, qv, axis_name)
    return (t_tot, mm_tot, mv_tot, qm_tot, qv_tot)


class LinearDensity(AnalysisBase):
    """``LinearDensity(ag, grouping="atoms", binsize=0.25).run()``.

    ``results.x|y|z``: ``mass_density`` / ``mass_density_stddev``
    (g/cm³), ``charge_density`` / ``charge_density_stddev`` (e/Å³),
    ``hist_bin_edges`` (Å), ``dim``, ``slab_volume`` (Å³).  Requires a
    box and partial charges (upstream raises on chargeless topologies;
    so does this).  Wrap the trajectory first if coordinates roam
    outside the primary cell.
    """

    _GROUPINGS = ("atoms", "residues", "segments")

    def __init__(self, select: AtomGroup, grouping: str = "atoms",
                 binsize: float = 0.25, verbose: bool = False):
        super().__init__(select.universe, verbose)
        if grouping not in self._GROUPINGS:
            raise ValueError(
                f"grouping must be one of {self._GROUPINGS}, "
                f"got {grouping!r}")
        if binsize <= 0:
            raise ValueError(f"binsize must be positive, got {binsize}")
        self._ag = select
        self._grouping = grouping
        self._binsize = float(binsize)

    def _prepare(self):
        self._idx = self._ag.indices
        if len(self._idx) == 0:
            raise ValueError("selection matched no atoms")
        t = self._universe.topology
        if t.charges is None:
            raise ValueError(
                "LinearDensity needs partial charges and this topology "
                "carries none (upstream raises NoDataError too); load a "
                "format with charges (PSF) or set topology.charges")
        first = self._frame_indices[0] if self._frame_indices else 0
        dims = self._universe.trajectory[first].dimensions
        if dims is None:
            raise ValueError(
                "LinearDensity needs box dimensions (the slabs span the "
                "box); this trajectory carries none")
        extent = np.asarray(dims[:3], np.float64)
        # upstream layout: per-axis bin counts from floor division, one
        # shared nbins = max, every histogram over [0, max extent)
        self._bins = np.maximum(
            (extent // self._binsize).astype(int), 1)
        self._nbins = int(self._bins.max())
        self._rng_max = float(extent.max())
        self._volume = float(np.prod(extent))
        masses = np.asarray(t.masses[self._idx], np.float64)
        charges = np.asarray(t.charges[self._idx], np.float64)
        if self._grouping == "atoms":
            self._gids = None
            self._w_mass, self._w_charge = masses, charges
        else:
            # segments have no dense index attribute; np.unique over the
            # segid strings builds the same 0-based group map
            raw = (t.resindices if self._grouping == "residues"
                   else t.segids)[self._idx]
            uniq, gids = np.unique(raw, return_inverse=True)
            self._gids = gids.astype(np.int32)
            self._n_groups = len(uniq)
            self._gmass_sum = np.zeros(self._n_groups)
            np.add.at(self._gmass_sum, gids, masses)
            if (self._gmass_sum <= 0).any():
                raise ValueError(
                    f"a {self._grouping[:-1]} in the selection has zero "
                    "total mass; centers of mass are undefined")
            self._w_mass = self._gmass_sum
            self._w_charge = np.zeros(self._n_groups)
            np.add.at(self._w_charge, gids, charges)
            self._atom_masses = masses
        shape = (3, self._nbins + 1)
        self._m_stream = host.StreamingMoments(shape)
        self._q_stream = host.StreamingMoments(shape)

    def _group_positions(self, pos: np.ndarray) -> np.ndarray:
        """(S, 3) atom positions → binned positions per grouping."""
        if self._gids is None:
            return pos
        w = np.zeros((self._n_groups, 3))
        np.add.at(w, self._gids, pos * self._atom_masses[:, None])
        return w / self._gmass_sum[:, None]

    # -- serial path --

    def _single_frame(self, ts):
        pos = self._group_positions(
            ts.positions[self._idx].astype(np.float64))
        nb = self._nbins
        idx = np.floor(pos * (nb / self._rng_max)).astype(np.int64)
        # np.histogram semantics: right-closed last bin
        idx = np.minimum(idx, nb - 1)
        inside = (pos >= 0) & (pos <= self._rng_max) & (idx >= 0)
        idx = np.where(inside, idx, nb)
        mh = np.zeros((3, nb + 1))
        qh = np.zeros((3, nb + 1))
        for a in range(3):
            np.add.at(mh[a], idx[:, a], self._w_mass)
            np.add.at(qh[a], idx[:, a], self._w_charge)
        self._m_stream.update(mh)
        self._q_stream.update(qh)

    def _serial_summary(self):
        t, m_mean, m_m2 = self._m_stream.summary
        _, q_mean, q_m2 = self._q_stream.summary
        return (float(t), m_mean, m_m2, q_mean, q_m2)

    # -- batch path --

    def _batch_select(self):
        return self._idx

    def _batch_fn(self):
        return _lindens_kernel_for(
            self._nbins, None if self._gids is None else self._n_groups)

    def _batch_params(self):
        import jax.numpy as jnp

        rng_max = jnp.float32(self._rng_max)
        if self._gids is None:
            return (rng_max, jnp.asarray(self._w_mass, jnp.float32),
                    jnp.asarray(self._w_charge, jnp.float32), None)
        return (rng_max, jnp.asarray(self._atom_masses, jnp.float32),
                None, jnp.asarray(self._gids),
                jnp.asarray(self._gmass_sum, jnp.float32),
                jnp.asarray(self._w_mass, jnp.float32),
                jnp.asarray(self._w_charge, jnp.float32))

    _device_combine = staticmethod(_lindens_psum)
    _device_fold_fn = staticmethod(_lindens_fold)

    def _identity_partials(self):
        z = np.zeros((3, self._nbins + 1))
        return (0.0, z, z, z, z)

    def _conclude(self, total):
        if self.n_frames == 0:
            raise ValueError("LinearDensity over zero frames")
        nbins, rng_max = self._nbins, self._rng_max
        slab_vols = self._volume / self._bins        # per-axis (upstream)

        def _finalize():
            t, m_mean, m_m2, q_mean, q_m2 = (
                np.asarray(x, np.float64) for x in total)
            t = float(t)
            out = {}
            for a, axis in enumerate(("x", "y", "z")):
                sv = float(slab_vols[a])
                mm = m_mean[a, :nbins]
                ms = np.sqrt(np.maximum(m_m2[a, :nbins] / t, 0.0))
                qm = q_mean[a, :nbins]
                qs = np.sqrt(np.maximum(q_m2[a, :nbins] / t, 0.0))
                out[axis] = {
                    "mass_density": mm / sv * _AMU_PER_A3_TO_G_PER_CM3,
                    "mass_density_stddev":
                        ms / sv * _AMU_PER_A3_TO_G_PER_CM3,
                    "charge_density": qm / sv,
                    "charge_density_stddev": qs / sv,
                    "hist_bin_edges":
                        np.linspace(0.0, rng_max, nbins + 1),
                    "dim": a,
                    "slab_volume": sv,
                }
            return out

        groups = deferred_group(_finalize)
        keys = ("mass_density", "mass_density_stddev", "charge_density",
                "charge_density_stddev", "hist_bin_edges", "dim",
                "slab_volume")
        for axis in ("x", "y", "z"):
            outer = groups[axis]
            sub = Results()
            for key in keys:
                # one shared finalize pass; each Deferred picks its key
                sub[key] = Deferred(
                    lambda o=outer, k=key: o.thunk()[k])
            self.results[axis] = sub
        self.results.nbins = nbins
