"""Per-atom-pair distance time series (upstream
``MDAnalysis.analysis.atomicdistances.AtomicDistances``).

Two equal-length AtomGroups pair element-by-element; every frame
yields the N distances, minimum-imaged under the frame's box when
``pbc=True`` (the upstream default).  ``run()`` →
``results.distances`` (T, N).

Built ON :class:`~mdanalysis_mpi_tpu.analysis.nucleicacids.NucPairDist`
(the shared paired-distance machinery — staging, kernels, conclude):
this class only adds the group-pairing validation and the
minimum-image variant of the serial/batch kernels.
"""

from __future__ import annotations

import numpy as np

from mdanalysis_mpi_tpu.analysis.nucleicacids import (
    NucPairDist, _pair_dist_kernel,
)


def _pair_dist_kernel_pbc(params, batch, boxes, mask):
    """The shared pair-distance kernel + per-frame minimum image
    (a distinct function identity: the pbc choice must be static
    under jit)."""
    import jax
    import jax.numpy as jnp

    from mdanalysis_mpi_tpu.ops.distances import minimum_image as mi

    i_slots, j_slots = params
    d = batch[:, i_slots] - batch[:, j_slots]
    d = jax.vmap(mi)(d, boxes)
    return (jnp.sqrt((d ** 2).sum(-1)) * mask[:, None], mask)


class AtomicDistances(NucPairDist):
    """``AtomicDistances(ag1, ag2, pbc=True).run().results.distances``.
    """

    def __init__(self, ag1, ag2, pbc: bool = True,
                 verbose: bool = False):
        from mdanalysis_mpi_tpu.analysis.base import reject_updating_groups

        reject_updating_groups(ag1, ag2, owner="AtomicDistances")
        if ag1.universe is not ag2.universe:
            raise ValueError("both groups must share one universe")
        if ag1.n_atoms != ag2.n_atoms:
            raise ValueError(
                f"groups pair atom-by-atom: {ag1.n_atoms} vs "
                f"{ag2.n_atoms} atoms")
        if ag1.n_atoms == 0:
            raise ValueError("empty groups")
        super().__init__(ag1.universe,
                         np.stack([ag1.indices, ag2.indices], axis=1),
                         verbose=verbose)
        self._pbc = bool(pbc)

    def _single_frame(self, ts):
        if not self._pbc:
            return super()._single_frame(ts)
        from mdanalysis_mpi_tpu.ops.host import minimum_image

        x = ts.positions[self._idx].astype(np.float64)
        d = minimum_image(x[self._i_slots] - x[self._j_slots],
                          ts.dimensions)
        self._serial_rows.append(np.sqrt((d ** 2).sum(-1)))

    def _batch_fn(self):
        return _pair_dist_kernel_pbc if self._pbc else _pair_dist_kernel
